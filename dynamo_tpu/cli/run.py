"""serve CLI: ``python -m dynamo_tpu.cli.run in=<src> out=<engine> [flags]``.

The dynamo-run analog (reference: launch/dynamo-run/src/{main,lib}.rs —
in={http,text,stdin,batch:,dyn://} × out={echo_full,echo_core,engines...}).
Wires the local pipeline frontend → preprocessor → backend → engine and
serves it over the chosen input.

Examples:
  python -m dynamo_tpu.cli.run in=http out=echo_full --http-port 8080
  python -m dynamo_tpu.cli.run in=http out=echo_core --model-path /path/to/model
  python -m dynamo_tpu.cli.run in=http out=jax --model-path /path/to/model
  python -m dynamo_tpu.cli.run in=dyn://ns.comp.ep out=jax --model-path ... \
      --store-port 4871 --model-name my-model
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import sys
from typing import List, Optional

logger = logging.getLogger(__name__)


def parse_io(args: List[str]):
    src, engine = "http", "echo_full"
    rest = []
    for a in args:
        if a.startswith("in="):
            src = a[3:]
        elif a.startswith("out="):
            engine = a[4:]
        else:
            rest.append(a)
    return src, engine, rest


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dynamo-tpu run", add_help=True)
    p.add_argument("--model-path", default=None, help="HF snapshot dir")
    p.add_argument("--model-name", default=None, help="served model name")
    p.add_argument("--http-host", default="0.0.0.0")
    p.add_argument("--http-port", type=int, default=8080)
    p.add_argument("--store-host", default="127.0.0.1", help="dynstore host")
    p.add_argument("--store-port", type=int, default=None, help="dynstore port (distributed mode)")
    p.add_argument("--namespace", default="public")
    p.add_argument("--router-mode", default="round_robin",
                   choices=["random", "round_robin", "kv"])
    p.add_argument("--tensor-parallel-size", type=int, default=1)
    p.add_argument("--expert-parallel-size", type=int, default=1,
                   help="experts shard over the ep mesh axis (MoE)")
    p.add_argument("--data-parallel-size", type=int, default=1,
                   help="batch shards over the dp mesh axis")
    p.add_argument("--sequence-parallel-size", type=int, default=1,
                   help="sequence-parallel axis for long-context prefill: "
                        "one oversized prompt's tokens shard across this "
                        "many devices (ring attention + chunk-streamed KV "
                        "commit; docs/long_context.md). Decode is "
                        "unaffected. Llama-family GQA dense models only.")
    p.add_argument("--long-prefill-threshold-tokens", type=int, default=0,
                   help="admission class: prompts whose uncached suffix is "
                        "at least this long take the sequence-parallel "
                        "prefill program (or, in disagg mode, prefer the "
                        "prefill-worker pool). 0 = default to the per-step "
                        "prefill budget when --sequence-parallel-size > 1, "
                        "else disabled.")
    p.add_argument("--pipeline-parallel-size", type=int, default=1,
                   help="dense trunk stages over the pp mesh axis "
                        "(collective GPipe; reference analog: "
                        "pipeline_parallel_size=num_nodes)")
    p.add_argument("--token-level", action="store_true",
                   help="serve PreprocessedRequests (engine worker behind a processor)")
    p.add_argument("--worker-endpoint", default=None,
                   help="dyn://ns.comp.ep of token-level workers (processor role)")
    p.add_argument("--kv-block-size", type=int, default=16)
    p.add_argument("--max-batch-size", type=int, default=8)
    p.add_argument("--max-model-len", type=int, default=None)
    p.add_argument("--extra-engine-args", default=None, help="JSON file of engine kwargs")
    p.add_argument("--isolate-engine", action="store_true",
                   help="host the engine (out=jax, pystr:, pytok:) in a "
                        "supervised subprocess (heartbeat + respawn; an "
                        "engine crash or hung Mosaic/XLA compile cannot "
                        "take the worker down)")
    p.add_argument("--engine-heartbeat-s", type=float, default=5.0,
                   help="isolated-engine heartbeat interval; the child's "
                        "event loop must pong within interval x misses "
                        "(sync work belongs in run_in_executor)")
    p.add_argument("--engine-heartbeat-misses", type=int, default=6,
                   help="consecutive missed pongs before the isolated "
                        "engine is declared wedged and killed")
    p.add_argument("--engine-init-timeout-s", type=float, default=120.0,
                   help="isolated-engine spawn+initialize() deadline")
    p.add_argument("--host-kv-blocks", type=int, default=0,
                   help="host-RAM KV offload tier capacity in blocks (0 = off)")
    p.add_argument("--multi-step-decode", type=int, default=1,
                   help="decode steps fused per device dispatch (tokens "
                        "stream in bursts of K; 1 = per-token)")
    p.add_argument("--decode-pipeline-depth", type=int, default=1,
                   help="dispatch-ahead decode: 2 double-buffers bursts "
                        "(burst k+1 dispatches while the host streams "
                        "burst k's tokens); 0/1 = strictly synchronous")
    p.add_argument("--device-finish", choices=["auto", "on", "off"],
                   default="auto",
                   help="device-resident finish detection: the decode "
                        "burst carries a per-row done mask (eos/stop/"
                        "max-token checks inside the scan; finished rows "
                        "freeze), so bursts chain back-to-back and "
                        "completed rows drain asynchronously. auto = "
                        "follow --decode-pipeline-depth >= 2")
    p.add_argument("--fused-epilogue", choices=["auto", "on", "off"],
                   default="auto",
                   help="fused sampling epilogue: the per-burst sampling "
                        "tail (penalties, top-k/p/min-p, count commit, "
                        "finish mask, stop-suffix hash) runs as ONE "
                        "Pallas dispatch; bit-identical stream. auto = "
                        "ride the Pallas attention route")
    p.add_argument("--guided-table-max-states", type=int, default=256,
                   help="unrestricted chain: state bound for compiling "
                        "guided grammars to device transition tables "
                        "(in-bound grammars chain; larger ones keep the "
                        "host sync path, counted in "
                        "dynamo_engine_sync_fallback_total)")
    p.add_argument("--no-guided-device-table", action="store_true",
                   help="disable guided device tables: guided rows keep "
                        "the per-token host mask path")
    p.add_argument("--no-device-stop-strings", action="store_true",
                   help="disable the chain's device-approximate stop-"
                        "string detection (suffix-hash over the carry's "
                        "token ring): stop-string rows keep the per-"
                        "burst sync pipeline")
    p.add_argument("--disagg-stream-depth", type=int, default=2,
                   help="streamed remote prefill: KV transfer frames in "
                        "flight on the prefill worker (2 double-buffers "
                        "— next frame gathers while the previous one is "
                        "on the wire; 1 = strictly serial frames)")
    p.add_argument("--quantization", choices=["int8"], default=None,
                   help="serving-time weight-only quantization (halves "
                        "the decode weight stream; llama-family)")
    p.add_argument("--kv-cache-dtype", choices=["auto", "fp8"],
                   default="auto",
                   help="paged KV cache storage dtype: fp8 halves the "
                        "decode KV stream and doubles cache capacity "
                        "(~6%% elementwise KV error; GQA families)")
    p.add_argument("--spec-ngram-tokens", type=int, default=0,
                   help="ngram speculative decoding: propose up to K "
                        "tokens per step from the context's own history "
                        "(greedy requests; 0 = off)")
    p.add_argument("--spec-draft-model", default=None,
                   help="draft-model speculative decoding: HF dir of a "
                        "small same-tokenizer model that proposes "
                        "--spec-draft-tokens per round (one fused burst) "
                        "for the target to verify in one forward")
    p.add_argument("--spec-draft-tokens", type=int, default=0,
                   help="proposals per draft round (2..16)")
    p.add_argument("--spec-ngram-match", type=int, default=3,
                   help="trailing n-gram length the proposer looks up")
    p.add_argument("--num-kv-blocks", type=int, default=2048,
                   help="HBM paged-cache capacity in blocks")
    p.add_argument("--allow-random-weights", action="store_true",
                   help="serve random-init weights when the model dir has no "
                        "checkpoint (topology dry runs only)")
    # disaggregated prefill/decode (xPyD)
    p.add_argument("--remote-prefill", action="store_true",
                   help="decode worker: offload long prefills to the prefill queue")
    p.add_argument("--max-local-prefill-length", type=int, default=1000,
                   help="un-cached prompt tokens above this go remote")
    p.add_argument("--max-prefill-queue-size", type=int, default=2,
                   help="skip remote prefill when the queue is this deep")
    p.add_argument("--advertise-host", default="127.0.0.1",
                   help="host other workers use to reach this worker's KV transfer server")
    p.add_argument("--kv-transfer", choices=("tcp", "ici"), default="tcp",
                   help="KV block payload path: tcp (host bounce, works "
                        "anywhere) or ici (HBM-to-HBM XLA collective; "
                        "requires prefill+decode in one jax.distributed "
                        "world via --num-nodes/--leader-addr)")
    p.add_argument("--ici-sender-rank", type=int, default=1,
                   help="jax process index of the prefill (sender) worker")
    p.add_argument("--ici-receiver-rank", type=int, default=0,
                   help="jax process index of the decode (receiver) worker")
    # multi-host bring-up (reference MultiNodeConfig {num_nodes, node_rank,
    # leader_addr}, lib/llm/src/engines.rs:39-57; Ray leader/follower,
    # lib/engines/vllm0_7/src/ray.rs:66-230 — here JAX's coordinator is the
    # leader and the mesh spans slices, ICI within / DCN across)
    p.add_argument("--num-nodes", type=int, default=1,
                   help="hosts in this worker's mesh (multi-host serving)")
    p.add_argument("--node-rank", type=int, default=0,
                   help="this host's rank (0 = leader/coordinator)")
    p.add_argument("--leader-addr", default="",
                   help="host:port of node 0's JAX coordinator")
    # profiling (utils/profiling.py — XLA profiler, the TPU-first answer
    # to the reference's external genai-perf measurement)
    p.add_argument("--profile-dir", default="",
                   help="enable GET /debug/profile trace capture into this "
                        "directory (in=http only)")
    p.add_argument("--profiler-port", type=int, default=0,
                   help="start the jax profiler gRPC server on this port "
                        "(TensorBoard remote capture; any role)")
    # flight recorder + stall watchdog (telemetry/flight.py, watchdog.py)
    p.add_argument("--flight-dir", default="",
                   help="directory for flight artifacts (watchdog trips, "
                        "SIGUSR2, /debug/flight?save=1); also settable "
                        "via DYN_FLIGHT_DIR")
    p.add_argument("--watchdog-stall-s", type=float, default=None,
                   help="stall-watchdog deadline: trip (and dump a "
                        "flight artifact) when the engine has pending "
                        "work but its loop heartbeat or dispatch counter "
                        "has been stale this long (default 30; 0 = off)")
    # self-healing serving (recovery/): trip → drain → migrate → respawn
    p.add_argument("--self-heal", action="store_true",
                   help="automated recovery: watchdog trips (and "
                        "supervised-child deaths) drive drain → live "
                        "request migration to a healthy peer → respawn; "
                        "also enables POST /admin/drain for zero-"
                        "downtime rolling updates")
    p.add_argument("--drain-grace-s", type=float, default=5.0,
                   help="soft-drain grace: how long committed work may "
                        "finish on its own before migration starts")
    p.add_argument("--respawn-max", type=int, default=3,
                   help="consecutive failed respawns before the "
                        "recovery controller gives up")
    p.add_argument("--respawn-backoff-s", type=float, default=1.0,
                   help="respawn backoff base (doubles per consecutive "
                        "failure)")
    p.add_argument("--migrate-peers", default="",
                   help="comma-separated host:port list of peer "
                        "migration receivers (in=dyn:// workers discover "
                        "peers through the discovery plane instead)")
    p.add_argument("--migrate-port", type=int, default=0,
                   help="port for this worker's inbound-migration "
                        "receiver (0 = ephemeral; started only with "
                        "--self-heal on a native engine)")
    # cluster KV fabric (kv/fabric.py, docs/kv_fabric.md): cross-worker
    # prefix pull + content-addressed cold tier
    p.add_argument("--prefix-pull", action="store_true",
                   help="cluster KV fabric: on a router-detected remote "
                        "prefix hit, PULL the owning worker's committed "
                        "KV blocks over the transfer plane instead of "
                        "recomputing them (peers + ownership discovered "
                        "through the component's KV event stream; pull "
                        "failure falls back to local recompute "
                        "byte-identically)")
    p.add_argument("--prefix-pull-min-blocks", type=int, default=2,
                   help="minimum remote/cold extension (blocks past the "
                        "local hit) worth a pull")
    p.add_argument("--prefix-pull-timeout-s", type=float, default=30.0,
                   help="per-pull deadline before the local-recompute "
                        "fallback takes over")
    p.add_argument("--cold-tier-dir", default="",
                   help="content-addressed cold KV tier: spill host-"
                        "tier-evicted blocks to checksummed files in "
                        "this directory (shared mount → any worker, "
                        "including a respawned one, rehydrates them); "
                        "requires --host-kv-blocks > 0")
    p.add_argument("--cold-tier-blocks", type=int, default=0,
                   help="cold-tier capacity in blocks (0 = off; set "
                        "together with --cold-tier-dir)")
    # closed-loop SLA planner + HTTP-edge admission control (planner/)
    p.add_argument("--admission-limit", type=int, default=0,
                   help="HTTP-edge admission control: max concurrently "
                        "admitted requests; overflow queues per priority "
                        "class (X-Priority: high|normal|low), dequeued "
                        "highest-first, shed with 429 + Retry-After on "
                        "saturation or deadline (0 = admission off)")
    p.add_argument("--admission-queue-depth", type=int, default=64,
                   help="per-priority-class admission queue bound")
    p.add_argument("--admission-queue-timeout-s", type=float, default=10.0,
                   help="queue-wait deadline before a queued request is "
                        "shed with 429")
    p.add_argument("--planner", action="store_true",
                   help="in=http: run an in-process planner loop that "
                        "tightens/relaxes admission (and the disagg "
                        "split) from the engine's own load signals")
    p.add_argument("--planner-interval-s", type=float, default=2.0,
                   help="planner observe→decide→actuate cadence")
    p.add_argument("--planner-min-replicas", type=int, default=1)
    p.add_argument("--planner-max-replicas", type=int, default=8)
    p.add_argument("--planner-cooldown-s", type=float, default=30.0,
                   help="scale-up cooldown per role (scale-down waits "
                        "4x this)")
    p.add_argument("--planner-deployment", default=None,
                   help="in=planner: api-store deployment record whose "
                        "per-role replica counts the planner patches "
                        "(the operator applies them via --api-store-url)")
    p.add_argument("--api-store-url", default=None,
                   help="in=planner: api-store base URL for replica "
                        "actuation")
    # SLO targets + goodput accounting at the HTTP edge (telemetry/slo.py)
    p.add_argument("--slo-ttft-ms", type=float, default=0.0,
                   help="time-to-first-token SLO in ms: per-request "
                        "attainment + goodput (SLO-met tokens/s) export "
                        "on /metrics and feed the planner's slo.* "
                        "signals (0 = unjudged)")
    p.add_argument("--slo-itl-ms", type=float, default=0.0,
                   help="inter-token-latency SLO in ms, judged on each "
                        "request's WORST token gap at the edge (0 = "
                        "unjudged)")
    # fleet telemetry hub (telemetry/hub.py): cluster-wide /metrics
    # scrape → history rings → /fleet/metrics + /fleet/workers rollups
    p.add_argument("--hub", action="store_true",
                   help="run a fleet telemetry hub in this process "
                        "(in=http or in=planner): scrape every "
                        "--hub-target and discovery-registered metrics "
                        "sidecar into history rings and serve "
                        "/fleet/metrics + /fleet/workers (dynamotop's "
                        "data source); hub rollups also feed the "
                        "planner's fleet-level saturation signals")
    p.add_argument("--hub-interval-s", type=float, default=2.0,
                   help="hub scrape cadence")
    p.add_argument("--hub-target", action="append", default=None,
                   metavar="ROLE=URL",
                   help="static scrape target (repeatable): "
                        "decode=http://host:9090 — /metrics is appended "
                        "when missing; discovery-registered sidecars "
                        "are scraped in addition")
    # incident recorder (telemetry/incidents.py): trigger-driven capture
    # bundles (flight artifact + metric history + affected traces +
    # optional profiler window) at trip time
    p.add_argument("--incident-dir", default="",
                   help="capture incident bundles into this directory "
                        "on watchdog trips, recovery-ladder engagement, "
                        "SLO-floor breaches, and late-compile bursts; "
                        "also settable via DYN_INCIDENT_DIR; bundles "
                        "are listed at GET /debug/incidents and "
                        "rendered by scripts/flightdump.py --incident")
    p.add_argument("--incident-cooldown-s", type=float, default=60.0,
                   help="per-reason incident capture cooldown (one "
                        "wedge produces one bundle, not one per trip "
                        "edge)")
    p.add_argument("--incident-profile-s", type=float, default=0.0,
                   help="opt-in: include a jax.profiler capture window "
                        "of this many seconds in each incident bundle "
                        "(0 = off; skipped cleanly when a manual "
                        "/debug/profile capture is in flight)")
    # per-request trace store bounds (telemetry/tracing.py)
    p.add_argument("--trace-ttl-s", type=float, default=None,
                   help="evict completed /debug/requests traces older "
                        "than this (default 600; 0 keeps until the "
                        "capacity bound evicts them)")
    p.add_argument("--trace-capacity", type=int, default=None,
                   help="max completed traces held for /debug/requests "
                        "and /debug/trace (LRU beyond it; default 512)")
    # multi-model multi-tenant fleet (registry/, docs/multi_model.md)
    p.add_argument("--served-alias", action="append", default=None,
                   metavar="ALIAS",
                   help="extra name this model answers to (repeatable); "
                        "rides the model card workers publish at "
                        "startup, resolved by registry-aware frontends")
    p.add_argument("--model-tenants", default=None,
                   help="comma-separated tenant allow list for this "
                        "model's card (unset = public; tenant-scoped "
                        "models are invisible — 404 — to other tenants)")
    p.add_argument("--tenant-rps", type=float, default=0.0,
                   help="per-tenant requests/s token bucket (X-Tenant "
                        "header; unknown/garbage degrades to the "
                        "'default' tenant; 0 = unlimited). Exceeding "
                        "tenants are shed with 429 + Retry-After while "
                        "other tenants are untouched")
    p.add_argument("--tenant-tps", type=float, default=0.0,
                   help="per-tenant streamed-tokens/s token bucket "
                        "(charged by actual streamed tokens; overdraft "
                        "delays the tenant's next admission; 0 = "
                        "unlimited)")
    p.add_argument("--tenant-burst-s", type=float, default=2.0,
                   help="token-bucket capacity in seconds of rate")
    p.add_argument("--tenant-quotas", default=None, metavar="FILE.json",
                   help="per-tenant overrides: {tenant: {requests_per_s,"
                        " tokens_per_s, burst_s}}")
    p.add_argument("--pool-scale-to-zero-idle-s", type=float, default=0.0,
                   help="drain a model's pool to zero replicas after "
                        "this long without a request (0 = off); the "
                        "next request for the cold model triggers a "
                        "cold-start respawn with that model's card")
    p.add_argument("--pool-cold-start-deadline-s", type=float,
                   default=30.0,
                   help="how long a request for a cold model waits for "
                        "a worker to join the pool before shedding "
                        "with 503 + Retry-After")
    p.add_argument("--pool-cooldown-s", type=float, default=30.0,
                   help="per-model pool action pacing (scale-to-zero / "
                        "cold-start decisions)")
    p.add_argument("--router-staleness-bound-s", type=float, default=0.0,
                   help="KV router: skip workers whose scraped load "
                        "snapshot is older than this many seconds "
                        "(0 = trust snapshots forever)")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="dyn:// roles: serve this process's Prometheus "
                        "registry on a sidecar GET /metrics port (the "
                        "router's per-worker load view, a token-level "
                        "worker's scheduler/KV instruments; 0 = off — "
                        "in=http exposes /metrics on the service port)")
    p.add_argument("-v", "--verbose", action="store_true")
    return p


def _make_ici(flags, runner):
    """--kv-transfer ici → the collective HBM-to-HBM plane, else None."""
    if getattr(flags, "kv_transfer", "tcp") != "ici":
        return None
    from ..disagg.ici_transfer import IciKvTransfer, kv_block_shapes

    import jax as _jax

    # the cache side may be a {"pre","stg"} pytree (mixed MLA under pp);
    # every leaf shares one storage dtype
    kv_dtype = _jax.tree.leaves(runner.kv_cache[0])[0].dtype
    return IciKvTransfer(
        kv_block_shapes(runner.config),
        kv_dtype,
        sender_rank=flags.ici_sender_rank,
        receiver_rank=flags.ici_receiver_rank,
    )


def load_mdc(flags):
    from ..llm.model_card import ModelDeploymentCard
    from ..models.hub import resolve_model_path

    if not flags.model_path:
        raise SystemExit("this mode requires --model-path")
    # accept a HF repo id anywhere a path is accepted (reference:
    # launch/dynamo-run/src/hub.rs) — local dirs pass through untouched
    flags.model_path = resolve_model_path(flags.model_path)
    if flags.model_path.endswith(".gguf"):
        from ..llm.gguf import mdc_from_gguf

        return mdc_from_gguf(
            flags.model_path, flags.model_name,
            kv_block_size=flags.kv_block_size,
        )
    return ModelDeploymentCard.from_local_path(
        flags.model_path, flags.model_name, kv_block_size=flags.kv_block_size
    )


def _engine_args(flags) -> dict:
    """--extra-engine-args <file.json> → kwargs for the engine."""
    from ..engine.serving import load_extra_engine_args

    return load_extra_engine_args(flags)


async def _load_python_engine(path: str, flags):
    """BYO python-file engine, in-process or (``--isolate-engine``)
    hosted in a supervised subprocess with heartbeat + respawn."""
    if getattr(flags, "isolate_engine", False):
        from ..llm.engines.subprocess_host import SubprocessEngine

        return await SubprocessEngine.load(
            path, _engine_args(flags),
            heartbeat_interval_s=getattr(flags, "engine_heartbeat_s", 5.0),
            heartbeat_misses=getattr(flags, "engine_heartbeat_misses", 6),
            init_timeout_s=getattr(flags, "engine_init_timeout_s", 120.0),
        )
    from ..llm.engines.python_file import PythonFileEngine

    return await PythonFileEngine.load(path, _engine_args(flags))


async def build_core_engine(engine_spec: str, flags, mdc, events=None, drt=None):
    """Token-level engine (PreprocessedRequest → EngineOutput stream)."""
    from ..llm.engines.echo import EchoEngineCore

    if engine_spec == "echo_core":
        return EchoEngineCore()
    if engine_spec.startswith("pytok:"):
        return await _load_python_engine(
            engine_spec[len("pytok:"):], flags
        )
    if engine_spec == "jax":
        if getattr(flags, "isolate_engine", False):
            # the native JAX engine is the actual compile-hang hazard
            # (a wedged Mosaic compile freezes the whole host process);
            # host it as a supervised child: heartbeats catch the wedge,
            # the worker keeps its lease, in-flight requests fail through
            # the error prologue, and the next request respawns the
            # child (warm-started via the persistent compilation cache).
            if getattr(flags, "remote_prefill", False):
                raise SystemExit(
                    "--isolate-engine is incompatible with "
                    "--remote-prefill: the disagg coordinator needs "
                    "in-process access to the runner's KV cache"
                )
            from ..llm.engines.subprocess_host import SubprocessEngine

            wire_flags = {
                k: v for k, v in vars(flags).items()
                if isinstance(v, (str, int, float, bool, list, dict))
                or v is None
            }
            wire_flags["isolate_engine"] = False  # no recursion
            return await SubprocessEngine.load(
                "@jax", {"flags": wire_flags},
                heartbeat_interval_s=getattr(flags, "engine_heartbeat_s", 5.0),
                heartbeat_misses=getattr(flags, "engine_heartbeat_misses", 6),
                init_timeout_s=getattr(flags, "engine_init_timeout_s", 120.0),
                events=events,
            )
        from ..engine.serving import JaxServingEngine

        disagg_factory = None
        if getattr(flags, "remote_prefill", False):
            if drt is None:
                raise SystemExit("--remote-prefill requires distributed mode (in=dyn://)")

            async def disagg_factory(runner):
                from ..disagg import DisaggRouter, RemotePrefillCoordinator

                router = DisaggRouter(
                    max_local_prefill_length=flags.max_local_prefill_length,
                    max_prefill_queue_size=flags.max_prefill_queue_size,
                    model_name=flags.model_name,
                    namespace=flags.namespace,
                )
                return await RemotePrefillCoordinator(
                    drt, runner, namespace=flags.namespace,
                    router=router, advertise_host=flags.advertise_host,
                    ici=_make_ici(flags, runner),
                ).start()

        return await JaxServingEngine.create(
            mdc, flags, events=events, disagg_factory=disagg_factory
        )
    raise SystemExit(f"unknown core engine {engine_spec!r}")


async def build_engine(engine_spec: str, flags, drt=None, events=None):
    """Returns (openai_engine, mdc_or_None). The engine accepts
    ChatCompletionRequest/CompletionRequest contexts and yields chunks."""
    from ..llm.engines.echo import EchoEngineFull

    if engine_spec == "none":
        # pure frontend: models come exclusively from the discovery watcher
        return None, None
    if engine_spec == "echo_full":
        from ..llm.embeddings import EchoEmbedder

        engine = EchoEngineFull()
        # the echo stack serves /v1/embeddings too (deterministic
        # hash-seeded vectors) so the endpoint is drivable creds-free
        engine.embedder = EchoEmbedder()
        return engine, None
    if engine_spec.startswith("pystr:"):
        # bring-your-own OpenAI-level engine (reference: out=pystr:<file>)
        engine = await _load_python_engine(
            engine_spec[len("pystr:"):], flags
        )
        return engine, None

    if engine_spec in ("echo_core", "jax") or engine_spec.startswith("pytok:"):
        from ..llm.backend import Backend
        from ..llm.preprocessor import OpenAIPreprocessor
        from ..llm.tokenizer import HFTokenizer
        from ..runtime.pipeline import build_pipeline

        mdc = load_mdc(flags)
        tokenizer = HFTokenizer.from_model_path(flags.model_path)
        core = await build_core_engine(engine_spec, flags, mdc, events, drt=drt)
        pipe = build_pipeline(
            [OpenAIPreprocessor(mdc, tokenizer), Backend(tokenizer)], core
        )
        # the recovery wiring (and /admin/drain) needs the token-level
        # engine behind the preprocessing stages
        pipe.core_engine = core
        if getattr(core, "host_registry", None) is not None:
            # subprocess-hosted engines: the supervision registry
            # (restart counter) rides separately from the dict-gauge
            # metrics the child pongs back
            pipe.host_registry = core.host_registry
        if hasattr(core, "metrics"):
            # surfaced on the frontend's /metrics as engine gauges
            # (run_http) — slot/KV occupancy, prefix hits, speculation
            # acceptance; the reference publishes the same counters via
            # its ForwardPassMetrics plane
            pipe.engine_metrics = core.metrics
        if getattr(core, "registry", None) is not None:
            # in-process jax engine: its full instrument set (scheduler
            # step/phase histograms, KV counters, disagg RTT) merges into
            # the frontend's exposition instead of the dict-gauge fallback
            pipe.telemetry_registry = core.registry
        if getattr(core, "embed_ready", False) and hasattr(core, "embed"):
            # /v1/embeddings rides the batched-prefill path of THIS
            # engine (llm/embeddings.py; prefill-only, no decode slot)
            from ..llm.embeddings import Embedder

            vocab = None
            cfg_e = getattr(core, "config", None)
            if cfg_e is not None:
                vocab = cfg_e.model.vocab_size
            pipe.embedder = Embedder(
                tokenizer, core,
                max_model_len=(
                    cfg_e.max_model_len if cfg_e is not None
                    else mdc.context_length
                ),
                vocab_size=vocab,
            )
        return pipe, mdc

    raise SystemExit(f"unknown engine {engine_spec!r}")


async def _setup_self_healing(flags, core, admission=None, drt=None,
                              component: str = "backend",
                              peer_ranker=None, instance_id: str = "",
                              ici=None):
    """--self-heal wiring: a RecoveryController per engine plus (native
    engines) a migration receiver for peers draining TOWARD this worker.

    Returns (controller, migration_server) — either may be None. Native
    in-process engines get the full ladder (trip → drain → migrate);
    subprocess-hosted engines get the respawn ladder driven by child
    deaths (their drain/migrate happens inside the child's own stack).
    """
    import uuid as _uuid

    import msgpack as _msgpack

    from ..recovery import (
        MigrationServer,
        MigrationSink,
        RecoveryConfig,
        RecoveryController,
        migration_key,
    )

    config = RecoveryConfig(
        drain_grace_s=flags.drain_grace_s,
        respawn_backoff_s=flags.respawn_backoff_s,
        max_respawns=flags.respawn_max,
    )
    # supervised-child engines: respawn ladder only — the wedge/death
    # detection and stream failure live in the subprocess host itself.
    # respawn() (not _ensure_running) so POST /admin/drain?respawn=1
    # actually restarts a LIVE child (rolling engine restart), while a
    # dead child just respawns; the controller suppresses the down
    # listener during its own drain so the kill doesn't re-trigger it.
    if hasattr(core, "add_down_listener"):
        controller = RecoveryController(
            engine_id=f"eng-{_uuid.uuid4().hex[:12]}",
            respawner=core.respawn,
            admission=admission,
            config=config,
        )
        core.add_down_listener(controller.on_child_down)
        return controller, None

    scheduler = getattr(core, "scheduler", None)
    if scheduler is None:
        return None, None  # echo/BYO engines have nothing to recover
    engine_id = f"eng-{_uuid.uuid4().hex[:12]}"
    sink = MigrationSink(scheduler, core.runner)
    server = await MigrationServer(
        sink, host=flags.advertise_host, port=flags.migrate_port,
        ici=ici,
        ici_rank=None if ici is None else getattr(ici, "receiver_rank",
                                                  None),
    ).start()

    static_peers = [
        {"host": hp.rsplit(":", 1)[0], "port": int(hp.rsplit(":", 1)[1]),
         "engine_id": f"static-{hp}"}
        for hp in flags.migrate_peers.split(",") if hp.strip()
    ]
    peers = (lambda: static_peers)
    deregister = register = None
    if drt is not None:
        key = migration_key(flags.namespace, component, engine_id)
        # worker_id: the KV-event id this worker publishes under — the
        # join key peer fabrics use to rank migration targets by prefix
        # overlap (their ownership view is keyed by KV-event ids, not
        # migration engine ids)
        desc = _msgpack.packb(
            dict(server.descriptor, engine_id=engine_id,
                 **({"worker_id": instance_id} if instance_id else {})),
            use_bin_type=True,
        )
        lease = await drt.discovery.primary_lease()
        await drt.discovery.kv_put(key, desc, lease_id=lease.id)
        # snapshot of live peer receivers, primed now and refreshed per
        # drain; excludes self by engine_id inside the controller
        peer_cache: list = list(static_peers)

        async def refresh_peers():
            prefix = migration_key(flags.namespace, component, "")
            kvs = await drt.discovery.kv_get_prefix(prefix)
            peer_cache[:] = static_peers + [
                _msgpack.unpackb(v, raw=False) for v in kvs.values()
            ]

        async def deregister():
            # routers already skip us via the draining snapshot; this
            # removes the migration descriptor so no peer drains INTO a
            # draining worker. Delete FIRST and unconditionally — a
            # flaky peer refresh must neither leave the dead worker's
            # descriptor registered nor abort the drain (the cache keeps
            # its last known pool on refresh failure).
            await drt.discovery.kv_delete(key)
            try:
                await refresh_peers()  # post-delete: self is gone too
            except Exception:
                logger.warning("peer refresh failed during drain; using "
                               "last known peers", exc_info=True)

        async def register():
            await drt.discovery.kv_put(key, desc, lease_id=lease.id)

        try:
            await refresh_peers()
        except Exception:
            logger.warning("initial migration-peer discovery failed; "
                           "starting with static peers only", exc_info=True)
        peers = (lambda: peer_cache)

    controller = RecoveryController(
        engine_id=engine_id,
        scheduler=scheduler,
        runner=core.runner,
        watchdog=getattr(core, "watchdog", None),
        peers=peers,
        deregister=deregister,
        register=register,
        admission=admission,
        config=config,
        peer_ranker=peer_ranker,
        ici=ici,
    )
    return controller, server


def _pool_scope_peers(peers: dict, endpoint_records: dict,
                      model: str = "") -> tuple:
    """Filter a fabric peer-descriptor map to this worker's model pool.

    Several model pools can share one component (per-model clients and
    the KV router partition a shared component's instances by the
    ``model`` metadata on their lease-scoped endpoint records), but the
    fabric descriptor prefix is component-wide — so without this filter
    a pull could splice another model's KV blocks into this pool's
    cache. Peers with no endpoint record yet (descriptor published
    before the registration landed) or no model metadata (single-pool
    deployments) are kept: missing metadata is a wildcard, same as the
    client-side partition rule. Returns ``(scoped, live)`` where
    ``live`` is every instance id holding an endpoint record — the
    indexer-prune set, which stays pool-agnostic because liveness is a
    property of the lease, not the pool.
    """
    import msgpack as _msgpack

    pool_of: dict = {}
    for key, raw in endpoint_records.items():
        wid = key.rsplit(":", 1)[-1]
        try:
            pool_of[wid] = _msgpack.unpackb(raw, raw=False).get("model")
        except Exception:
            logger.debug("unreadable endpoint record for %s; treating "
                         "its pool as wildcard", wid, exc_info=True)
            pool_of[wid] = None
    scoped = {
        wid: desc for wid, desc in peers.items()
        if not model or pool_of.get(wid) in (None, model)
    }
    return scoped, set(pool_of)


async def _setup_kv_fabric(flags, core, drt=None, component: str = "backend",
                           endpoint=None, instance_id: str = "",
                           model: str = "", ici=None):
    """Cluster-KV-fabric wiring for a token-level worker.

    The engine already built its fabric half (Scheduler.fabric — cold
    tier + pull machinery) from the EngineConfig knobs; this attaches
    the cluster half: the pull SERVER (advertised in discovery under
    ``fabric_key`` so peers can pull from this worker), the peer
    descriptor cache (refreshed on a cadence), and the ownership view
    (the component's KV event stream — the same events the router
    indexes). Returns the fabric or None.
    """
    import msgpack as _msgpack

    from ..kv.fabric import fabric_key
    from ..kv_router.protocols import KV_EVENT_SUBJECT, RouterEvent

    scheduler = getattr(core, "scheduler", None)
    fabric = getattr(scheduler, "fabric", None) if scheduler else None
    if fabric is None:
        return None
    if instance_id:
        # the ownership view keys workers by the SAME id the KV event
        # publisher stamps, so self-events are skippable and peer scores
        # map onto descriptors
        fabric.engine_id = instance_id
    if fabric.cold is not None:
        # respawn-warm: prime the cold index off-loop so the first
        # request after a recovery respawn sees the spilled prefixes
        n = await asyncio.get_running_loop().run_in_executor(
            None, fabric.cold.refresh
        )
        if n:
            logger.info("cold tier primed: %d resident blocks", n)
    if not fabric.peer_pull:
        # cold-tier-only configuration: local disk spill was the opt-in,
        # not cross-worker networking — no pull server, no peer view
        return fabric
    if ici is not None:
        # intra-pod peers negotiate device-to-device pulls off this
        # plane; the descriptor below advertises it
        fabric.set_ici(ici)
    server = await fabric.serve(host=flags.advertise_host)
    if drt is None or endpoint is None:
        return fabric
    key = fabric_key(flags.namespace, component, fabric.engine_id)
    # the pull server's descriptor carries modes (+ ici_rank) so peers
    # can negotiate the transfer backend per pair — TCP stays the
    # universal fallback
    desc = _msgpack.packb(
        dict(getattr(server, "descriptor", None)
             or {"host": flags.advertise_host, "port": server.port},
             engine_id=fabric.engine_id),
        use_bin_type=True,
    )
    lease = await drt.discovery.primary_lease()
    await drt.discovery.kv_put(key, desc, lease_id=lease.id)

    peer_cache: dict = {}

    async def refresh_peers():
        prefix = fabric_key(flags.namespace, component, "")
        kvs = await drt.discovery.kv_get_prefix(prefix)
        peers = {}
        for v in kvs.values():
            d = _msgpack.unpackb(v, raw=False)
            wid = d.get("engine_id")
            if wid and wid != fabric.engine_id:
                peers[wid] = d
        # prune dead workers from the ownership view: respawn churn
        # mints a fresh id per incarnation, so without this the indexer
        # accumulates dead workers' hash runs forever (and keeps the
        # admission gate open with nothing pullable). Liveness comes
        # from the lease-scoped ENDPOINT registry (keyed by the same
        # instance id KV events carry), not the pull-server descriptors
        # — workers without a pull server (cold-tier-only, plain
        # KV-routed) still publish events and still die. The same
        # records carry pool membership, scoping pulls to this model.
        eps = await drt.discovery.kv_get_prefix(
            endpoint.component.etcd_prefix())
        peers, live = _pool_scope_peers(peers, eps, model)
        peer_cache.clear()
        peer_cache.update(peers)
        for wid in list(fabric.indexer.worker_ids):
            if wid != fabric.engine_id and wid not in live:
                fabric.remove_worker(wid)

    async def refresh_loop():
        while True:
            try:
                await refresh_peers()
            except Exception:
                # discovery hiccup: keep the last known pool — a pull
                # to a dead descriptor just falls back to recompute
                logger.debug("fabric peer refresh failed", exc_info=True)
            await asyncio.sleep(5.0)

    try:
        await refresh_peers()
    except Exception:
        logger.warning("initial fabric peer discovery failed; starting "
                       "with no peers", exc_info=True)
    fabric.peers = (lambda: peer_cache)
    fabric.hold_task(drt.runtime.spawn(refresh_loop()))

    # the ownership view rides the SAME event subject the KV router
    # consumes; apply_event skips this engine's own events
    sub = await endpoint.component.subscribe_event(KV_EVENT_SUBJECT)

    async def consume_events():
        async for msg in sub:
            try:
                fabric.apply_event(RouterEvent.from_wire(
                    _msgpack.unpackb(msg.payload, raw=False)
                ))
            except Exception:
                logger.exception("bad kv event on the fabric feed")

    fabric.hold_task(drt.runtime.spawn(consume_events()))
    return fabric


def _model_card(flags, mdc, endpoint_path: str, model_type: str = "both"):
    """The fleet card a worker publishes at startup (registry/cards.py):
    name + pool endpoint + family/context from the deployment card,
    aliases and tenant visibility from the flags."""
    from ..registry.cards import card_from_mdc

    tenants = None
    if flags.model_tenants is not None:
        tenants = [t.strip() for t in flags.model_tenants.split(",")
                   if t.strip()]
    return card_from_mdc(
        mdc, endpoint_path,
        name=flags.model_name or mdc.display_name,
        model_type=model_type,
        aliases=flags.served_alias or [],
        tenants=tenants,
    )


def _advertise_model(registry, name: Optional[str]) -> None:
    """Stamp the model this process serves on its metrics registry —
    the fleet hub reads the label into /fleet/workers' MODEL column."""
    if registry is None or not name:
        return
    registry.gauge(
        "dynamo_registry_model_info",
        "1 for the model= this worker currently serves",
    ).set(1.0, model=name)


def _build_quotas(flags, admissions_registry=None):
    """--tenant-* → a TenantQuotas gate for the HTTP edge, or None.
    ``admissions_registry`` shares the admission controller's counter
    family so outcome="quota" rides the same instrument."""
    if (flags.tenant_rps <= 0 and flags.tenant_tps <= 0
            and not flags.tenant_quotas):
        return None
    from ..registry.tenants import TenantQuotas

    quotas = TenantQuotas.from_flags(
        flags.tenant_rps, flags.tenant_tps,
        overrides_path=flags.tenant_quotas,
        burst_s=flags.tenant_burst_s,
    )
    if admissions_registry is not None:
        quotas.bind_admissions(admissions_registry)
    return quotas


def _build_pools(flags, manager, watcher):
    """Pool manager for the multi-model frontend: scale-to-zero for
    idle model pools and cold-start gating for requests that find
    their pool empty. Replica actuation rides the api-store record
    when --api-store-url/--planner-deployment are set (the operator
    reconciles the patch, like the standalone planner); without a
    backend, cold requests just wait out the deadline for an
    externally-started worker."""
    from ..registry import (
        PoolConfig,
        PoolManager,
        PoolPolicy,
        PoolPolicyConfig,
        StorePoolBackend,
    )

    backend = None
    if flags.api_store_url and flags.planner_deployment:
        from ..deploy.store_source import ApiStoreClient

        backend = StorePoolBackend(
            ApiStoreClient(flags.api_store_url), flags.planner_deployment)
    if backend is None and flags.pool_scale_to_zero_idle_s <= 0:
        return None
    return PoolManager(
        manager.registry, watcher.pool_size,
        spawner=backend.spawn if backend is not None else None,
        drainer=backend.drain if backend is not None else None,
        config=PoolConfig(
            cold_start_deadline_s=flags.pool_cold_start_deadline_s),
        policy=PoolPolicy(PoolPolicyConfig(
            idle_to_zero_s=flags.pool_scale_to_zero_idle_s,
            cooldown_s=flags.pool_cooldown_s,
        )),
    )


def _build_hub(flags):
    """--hub → a FleetHub over the static --hub-target list (discovery
    targets attach later, once a DistributedRuntime exists)."""
    if not getattr(flags, "hub", False):
        return None
    from ..telemetry.hub import FleetHub, parse_target_flag

    return FleetHub(
        targets=[parse_target_flag(s) for s in (flags.hub_target or [])],
        interval_s=flags.hub_interval_s,
    )


async def _setup_incidents(flags, registry=None, watchdog=None,
                           recovery=None, slo=None, compiles=None):
    """DYN_INCIDENT_DIR / --incident-dir → an IncidentRecorder wired to
    every degradation edge this process emits, plus a local history
    sampler so bundles carry the metric curve INTO the incident.

    Returns (recorder, sampler) — both None when no dir is configured.
    """
    from ..telemetry.incidents import (
        IncidentConfig,
        IncidentRecorder,
        incident_dir,
        late_compile_probe,
        slo_probe,
    )

    if not incident_dir():
        return None, None
    from ..telemetry.history import LocalHistorySampler, MetricHistory

    recorder = IncidentRecorder(
        IncidentConfig(
            cooldown_s=flags.incident_cooldown_s,
            profile_s=flags.incident_profile_s,
        ),
        history=MetricHistory(window_s=600.0),
    )
    if watchdog is not None:
        recorder.watch_watchdog(watchdog)
    if recovery is not None:
        recorder.watch_recovery(recovery)
    if slo is not None:
        recorder.add_probe(slo_probe(slo))
    if compiles is not None:
        recorder.add_probe(late_compile_probe(compiles))
    sampler = None
    if registry is not None:
        sampler = LocalHistorySampler(
            registry, history=recorder.history, interval_s=5.0
        ).start()
    recorder.start()
    return recorder, sampler


async def run_http(flags, engine, mdc) -> None:
    from ..http.service import HttpService, ModelManager, ModelWatcher

    manager = ModelManager()
    if engine is not None:
        name = flags.model_name or (mdc.display_name if mdc else "echo")
        manager.add_chat_model(name, engine)
        if mdc is not None:  # pipeline engines dispatch chat AND completions
            manager.add_completion_model(name, engine)
        manager.set_metadata(
            name,
            model_type="both" if mdc is not None else "chat",
            max_model_len=mdc.context_length if mdc is not None else None,
        )
    admission = None
    if flags.admission_limit > 0:
        from ..planner import AdmissionConfig, AdmissionController

        admission = AdmissionController(AdmissionConfig(
            limit=flags.admission_limit,
            queue_depth=flags.admission_queue_depth,
            queue_timeout_s=flags.admission_queue_timeout_s,
        ))
    slo = None
    if flags.slo_ttft_ms > 0 or flags.slo_itl_ms > 0:
        from ..telemetry.slo import SloTracker

        slo = SloTracker(
            ttft_s=flags.slo_ttft_ms / 1e3 if flags.slo_ttft_ms > 0 else None,
            itl_s=flags.slo_itl_ms / 1e3 if flags.slo_itl_ms > 0 else None,
        )
    hub = _build_hub(flags)
    quotas = _build_quotas(
        flags, admission.registry if admission is not None else None)
    service = HttpService(
        manager, flags.http_host, flags.http_port,
        profile_dir=flags.profile_dir or None,
        admission=admission,
        slo=slo,
        trace_ttl_s=flags.trace_ttl_s,
        trace_capacity=flags.trace_capacity,
        hub=hub,
        quotas=quotas,
    )
    if engine is not None:
        # the model this frontend serves locally, for the fleet hub's
        # MODEL column (the distributed shape advertises per worker)
        _advertise_model(
            service.metrics.registry,
            flags.model_name or (mdc.display_name if mdc else "echo"))
    if hub is not None:
        # the frontend scrapes ITSELF (engine registries attach into the
        # service registry below, so one local scrape covers every layer
        # of this process) alongside the remote targets
        hub.add_local("frontend", "frontend", service.metrics.registry)
    if getattr(engine, "telemetry_registry", None) is not None:
        # in-process engine: one registry, one exposition — HTTP,
        # scheduler, KV allocator, and disagg instruments in one scrape
        service.metrics.attach_registry(engine.telemetry_registry)
    elif engine is not None and hasattr(engine, "engine_metrics"):
        # subprocess-hosted / BYO engine: metrics cross the process
        # boundary as a dict — expose them as callback gauges
        service.metrics.register_callback_gauges(
            "dynamo_engine", engine.engine_metrics
        )
    if getattr(engine, "host_registry", None) is not None:
        # supervision instruments (engine-child restart counter)
        service.metrics.attach_registry(engine.host_registry)

    recovery = migserver = None
    if flags.self_heal and engine is not None:
        core = getattr(engine, "core_engine", engine)
        recovery, migserver = await _setup_self_healing(
            flags, core, admission=admission
        )
        if recovery is not None:
            recovery.attach()
            service.drainer = recovery.admin_drain
            service.metrics.attach_registry(recovery.registry)

    planner = None
    if flags.planner:
        # in-process planner: the frontend's own saturation signals drive
        # admission tightening (and, with an engine attached, the
        # engine's slot/KV/queue state feeds the policy too)
        from ..planner import (
            LocalActuator,
            Planner,
            PlannerConfig,
            PolicyConfig,
            SlaPolicy,
            engine_metrics_source,
        )

        policy = SlaPolicy(PolicyConfig(
            min_replicas=flags.planner_min_replicas,
            max_replicas=flags.planner_max_replicas,
            scale_up_cooldown_s=flags.planner_cooldown_s,
            scale_down_cooldown_s=flags.planner_cooldown_s * 4,
        ))
        planner = Planner(
            policy, config=PlannerConfig(interval_s=flags.planner_interval_s)
        )
        if admission is not None:
            planner.add_source(admission.snapshot)
            planner.add_actuator(LocalActuator(admission=admission))
        if slo is not None:
            # user-visible latency as a first-class planner signal: the
            # policy sheds on SLO attainment, not just queue proxies
            from ..planner import slo_source

            planner.add_source(slo_source(slo))
        if engine is not None and hasattr(engine, "engine_metrics"):
            planner.add_source(engine_metrics_source(engine.engine_metrics))
        if hub is not None:
            # fleet-level saturation: the policy consults the scraped
            # POOL's busy/KV/SLO rollups, not just this process's view
            planner.add_source(hub.signal_source())
        service.metrics.attach_registry(planner.registry)
        planner.start()

    # incident recorder: wired to every degradation edge this process
    # emits (engine watchdog, recovery ladder, SLO floor, late compiles)
    core = getattr(engine, "core_engine", engine) if engine is not None else None
    incidents, inc_sampler = await _setup_incidents(
        flags, registry=service.metrics.registry,
        watchdog=getattr(core, "watchdog", None),
        recovery=recovery, slo=slo,
        compiles=getattr(getattr(core, "runner", None), "compiles", None),
    )
    if incidents is not None:
        service.incidents = incidents
        service.metrics.attach_registry(incidents.registry)

    watcher = None
    pools = None
    if flags.store_port is not None:
        from ..registry.registry import RegistryAdmin
        from ..runtime.component import DistributedRuntime
        from ..runtime.client import RouterMode

        drt = await DistributedRuntime.connect(flags.store_host, flags.store_port)
        if hub is not None:
            # distributed frontend: scrape every sidecar workers
            # registered in the discovery plane, on top of the statics
            from ..telemetry.hub import discovery_targets

            hub.discover = discovery_targets(drt, flags.namespace)
        watcher = ModelWatcher(
            drt, manager, flags.namespace, RouterMode(flags.router_mode)
        )
        await watcher.start()
        # dynamic model management (POST/DELETE /admin/models,
        # dynamoctl): writes the same discovery records workers publish
        service.registry_admin = RegistryAdmin(drt, flags.namespace)
        # per-model pool elasticity: scale-to-zero + cold-start gating
        pools = _build_pools(flags, manager, watcher)
        if pools is not None:
            service.attach_pools(pools)
            pools.start(spawn=drt.runtime.spawn)
    if hub is not None:
        hub.start()

    await service.start()
    print(f"listening on http://{flags.http_host}:{service.port}", flush=True)
    # SIGTERM drains in-flight requests for up to the configured grace
    # period (reference WorkerConfig.graceful_shutdown_timeout, DYN_WORKER_
    # env) instead of dropping streams mid-token
    import signal

    from ..utils.config import RuntimeSettings

    settings = RuntimeSettings.from_settings()
    stop_event = asyncio.Event()
    force_event = asyncio.Event()
    loop = asyncio.get_running_loop()

    def _on_signal():
        # first signal: drain; second: skip the drain and exit now
        if stop_event.is_set():
            force_event.set()
        stop_event.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, _on_signal)
        except (NotImplementedError, RuntimeError):
            pass
    try:
        await stop_event.wait()
        # stop accepting first — otherwise new requests keep arriving and
        # the drain below can never converge under steady traffic
        await service.stop_accepting()
        deadline = loop.time() + settings.graceful_shutdown_timeout
        while (service.metrics.inflight_total() > 0
               and loop.time() < deadline and not force_event.is_set()):
            await asyncio.sleep(0.1)
    finally:
        if planner is not None:
            planner.stop()
        if pools is not None:
            await pools.stop()
        if hub is not None:
            await hub.stop()
        if inc_sampler is not None:
            await inc_sampler.stop()
        if incidents is not None:
            await incidents.stop()
        if recovery is not None:
            await recovery.close()
        if migserver is not None:
            await migserver.close()
        if watcher:
            await watcher.stop()
        await service.stop()


async def run_text(flags, engine, mdc, interactive: bool = True) -> None:
    from ..protocols.annotated import Annotated
    from ..protocols.openai import ChatCompletionRequest
    from ..runtime.engine import Context

    name = flags.model_name or (mdc.display_name if mdc else "echo")
    loop = asyncio.get_running_loop()
    while True:
        try:
            line = await loop.run_in_executor(None, lambda: input("> "))
        except (EOFError, KeyboardInterrupt):
            return
        if not line.strip():
            continue
        req = ChatCompletionRequest(
            model=name, messages=[{"role": "user", "content": line}], stream=True
        )
        async for chunk in engine.generate(Context(req)):
            if Annotated.maybe_from_wire(chunk) is not None:
                continue  # annotation envelopes carry no printable text
            d = chunk if isinstance(chunk, dict) else chunk.model_dump(exclude_none=True)
            for choice in d.get("choices", []):
                content = (choice.get("delta") or {}).get("content")
                if content:
                    print(content, end="", flush=True)
        print()


async def advertise_sidecar(drt, flags, mserver, role: str,
                            instance: str) -> None:
    """Register a process's /metrics sidecar in discovery so a fleet hub
    (in=hub / --hub) finds it without static config; the lease-scoped
    key vanishes with the worker. Shared by every sidecar-running role
    (run_worker's three shapes, run_prefill)."""
    if mserver is None:
        return
    from ..telemetry.hub import register_metrics_endpoint

    try:
        await register_metrics_endpoint(
            drt, flags.namespace, role, instance,
            f"http://{flags.advertise_host}:{mserver.port}/metrics",
        )
    except Exception:
        logger.warning("metrics-sidecar discovery registration "
                       "failed; hub scrapes need --hub-target",
                       exc_info=True)


async def run_worker(flags, engine_spec: str, path: str) -> None:
    """Distributed worker roles (in=dyn://ns.comp.ep):

    - default: full OpenAI-level worker (preprocess+engine+detokenize here)
    - --token-level: engine worker serving PreprocessedRequests, publishing
      KV events + ForwardPassMetrics for KV-aware routers
    - out=processor: preprocess + KV-route to --worker-endpoint workers
    """
    import uuid

    from ..http.service import parse_endpoint_path, register_model
    from ..runtime.component import DistributedRuntime
    from ..runtime.engine import Context
    from ..telemetry.server import maybe_start_metrics_server

    if flags.store_port is None:
        raise SystemExit("in=dyn:// requires --store-port")
    if engine_spec == "none":
        raise SystemExit("out=none is only valid with in=http (pure frontend)")
    ns_name, comp, ep_name = parse_endpoint_path(path)
    drt = await DistributedRuntime.connect(flags.store_host, flags.store_port)
    endpoint = drt.namespace(ns_name).component(comp).endpoint(ep_name)
    mserver = None  # sidecar /metrics exposition (--metrics-port)
    incidents = inc_sampler = None

    def make_openai_handler(engine):
        async def handler(payload, ctx):
            from ..protocols.annotated import Annotated
            from ..protocols.openai import ChatCompletionRequest, CompletionRequest

            cls = ChatCompletionRequest if "messages" in payload else CompletionRequest
            async for chunk in engine.generate(Context(cls.model_validate(payload), ctx)):
                if isinstance(chunk, Annotated):
                    yield chunk.to_wire()
                else:
                    yield chunk if isinstance(chunk, dict) else chunk.model_dump(exclude_none=True)

        return handler

    if engine_spec == "processor":
        from ..kv_router.router import KvRouter
        from ..llm.processor import build_processor_pipeline
        from ..runtime.client import Client, RouterMode

        if not flags.worker_endpoint:
            raise SystemExit("out=processor requires --worker-endpoint")
        mdc = load_mdc(flags)
        wns, wcomp, wep = parse_endpoint_path(flags.worker_endpoint)
        w_endpoint = drt.namespace(wns).component(wcomp).endpoint(wep)
        client = Client(
            w_endpoint,
            RouterMode.ROUND_ROBIN if flags.router_mode == "kv"
            else RouterMode(flags.router_mode),
        )
        router = None
        if flags.router_mode == "kv":
            router = await KvRouter(
                w_endpoint.component, client, block_size=flags.kv_block_size,
                staleness_bound_s=flags.router_staleness_bound_s,
            ).start()
        else:
            await client.start()
        engine = build_processor_pipeline(mdc, client, router)
        name = flags.model_name or mdc.display_name
        serving = await endpoint.serve(make_openai_handler(engine),
                                       span_source="processor",
                                       metadata={"model": name})
        await register_model(drt, flags.namespace, name, path, model_type="both",
                             mdc={"context_length": mdc.context_length},
                             card=_model_card(flags, mdc, path))
        if router is not None:
            # the router's own observability surface: per-worker scraped
            # load + routing decisions, previously internal-only
            _advertise_model(router.registry, name)
            mserver = await maybe_start_metrics_server(
                router.registry, flags.metrics_port
            )
            await advertise_sidecar(
                drt, flags, mserver, "processor",
                f"processor-{uuid.uuid4().hex[:12]}")
        print(f"processor serving {path} (model={name} → {flags.worker_endpoint})", flush=True)

    elif flags.token_level:
        from ..kv_router.publisher import KvEventPublisher, KvMetricsPublisher

        mdc = load_mdc(flags)
        instance_id = f"w-{uuid.uuid4().hex[:12]}"
        publisher = KvEventPublisher(endpoint.component, instance_id)
        publisher.start()
        core = await build_core_engine(
            engine_spec, flags, mdc, events=publisher.as_sink(), drt=drt
        )

        async def handler(payload, ctx):
            async for out in core.generate(Context(payload, ctx)):
                yield out

        metrics_fn = core.metrics if hasattr(core, "metrics") else dict
        model_name = flags.model_name or mdc.display_name
        serving = await endpoint.serve(
            handler,
            instance_id=instance_id,
            stats_handler=KvMetricsPublisher(metrics_fn).stats_handler,
            span_source="decode_engine",
            # pool membership rides the lease-scoped endpoint record:
            # per-model clients and the KV router partition instances
            # of a shared component by this metadata
            metadata={"model": model_name},
        )
        _advertise_model(getattr(core, "registry", None), model_name)
        # one ICI plane per worker, shared by the fabric pull path and
        # hot migration — a single collective-ordering lock means the
        # two planes can never interleave (mis-pair) their collectives
        ici = None
        if getattr(core, "runner", None) is not None:
            raw_ici = _make_ici(flags, core.runner)
            if raw_ici is not None:
                from ..transfer.ici import IciBackend

                ici = IciBackend(raw_ici)
        # cluster KV fabric: pull server + peer/ownership view, keyed by
        # the same instance id the KV event publisher stamps
        fabric = await _setup_kv_fabric(
            flags, core, drt=drt, component=comp, endpoint=endpoint,
            instance_id=instance_id, model=model_name, ici=ici,
        )
        recovery = None
        if flags.self_heal:
            # watchdog trips drain this worker, migrate its in-flight
            # requests to peer workers discovered under the component's
            # migration prefix, and respawn (docs/self_healing.md);
            # migration targets rank by the fabric's ownership view
            # (prefix overlap) when one exists
            recovery, _migserver = await _setup_self_healing(
                flags, core, drt=drt, component=comp,
                peer_ranker=fabric.rank_peers if fabric is not None
                else None,
                instance_id=instance_id, ici=ici,
            )
            if recovery is not None:
                recovery.attach()
                reg = getattr(core, "registry", None)
                if reg is not None:
                    reg.attach(recovery.registry)
        # incident bundles at trip time: the engine worker is where the
        # wedges actually happen — a decode_stall here must leave its
        # evidence on disk even after recovery respawns the engine
        incidents, inc_sampler = await _setup_incidents(
            flags, registry=getattr(core, "registry", None),
            watchdog=getattr(core, "watchdog", None),
            recovery=recovery,
            compiles=getattr(getattr(core, "runner", None), "compiles", None),
        )
        if incidents is not None:
            reg = getattr(core, "registry", None)
            if reg is not None:
                reg.attach(incidents.registry)
        # in-process jax engines carry the full scheduler/KV registry;
        # workers with no registry (echo, BYO) just skip the sidecar
        mserver = await maybe_start_metrics_server(
            getattr(core, "registry", None), flags.metrics_port
        )
        await advertise_sidecar(drt, flags, mserver, "decode_engine",
                                instance_id)
        print(f"token-level worker {instance_id} serving {path}", flush=True)

    else:
        engine, mdc = await build_engine(engine_spec, flags, drt=drt)
        name = flags.model_name or (mdc.display_name if mdc else "echo")
        serving = await endpoint.serve(make_openai_handler(engine),
                                       metadata={"model": name})
        model_type = "both" if mdc is not None else "chat"
        await register_model(
            drt, flags.namespace, name, path, model_type=model_type,
            mdc={"context_length": mdc.context_length} if mdc else None,
            card=_model_card(flags, mdc, path, model_type)
            if mdc is not None else None,
        )
        _advertise_model(
            getattr(engine, "telemetry_registry", None), name)
        mserver = await maybe_start_metrics_server(
            getattr(engine, "telemetry_registry", None), flags.metrics_port
        )
        await advertise_sidecar(
            drt, flags, mserver, "worker", f"worker-{uuid.uuid4().hex[:12]}")
        print(f"worker serving {path} (model={name})", flush=True)

    try:
        await asyncio.Event().wait()
    finally:
        if inc_sampler is not None:
            await inc_sampler.stop()
        if incidents is not None:
            await incidents.stop()
        if mserver is not None:
            await mserver.stop()
        await serving.stop()


async def run_prefill(flags) -> None:
    """Dedicated prefill worker: consumes the namespace prefill queue.

    The prefill_worker role of the disagg graph (reference:
    examples/llm/components/prefill_worker.py poll loop)."""
    from ..disagg import PrefillWorker
    from ..engine.model_runner import ModelRunner
    from ..engine.serving import engine_config_from_mdc
    from ..runtime.component import DistributedRuntime
    from ..telemetry.server import maybe_start_metrics_server

    if flags.store_port is None:
        raise SystemExit("in=prefill requires --store-port")
    mdc = load_mdc(flags)
    engine_config = engine_config_from_mdc(mdc, flags)
    drt = await DistributedRuntime.connect(flags.store_host, flags.store_port)
    loop = asyncio.get_running_loop()
    runner = await loop.run_in_executor(
        None, lambda: ModelRunner(engine_config, model_dir=mdc.model_path)
    )
    worker = PrefillWorker(
        drt, runner, engine_config, namespace=flags.namespace,
        ici=_make_ici(flags, runner),
    )
    # same sidecar the decode workers run: prefill throughput, transfer
    # bytes, queue wait, and the transfer-overlap histograms land in a
    # scrapeable /metrics instead of only the ad-hoc metrics() dict
    _advertise_model(worker.registry,
                     flags.model_name or mdc.display_name)
    mserver = await maybe_start_metrics_server(
        worker.registry, flags.metrics_port
    )
    import uuid

    await advertise_sidecar(
        drt, flags, mserver, "prefill_worker",
        f"prefill-{uuid.uuid4().hex[:12]}")
    print(f"prefill worker consuming {worker.queue.name}", flush=True)
    try:
        await worker.run()
    finally:
        if mserver is not None:
            await mserver.stop()
        await worker.close()
        await drt.close()


async def run_hub(flags) -> None:
    """Standalone fleet-telemetry-hub role (in=hub): scrape every
    --hub-target and discovery-registered metrics sidecar into history
    rings and serve /metrics (the hub's own instruments + rollup
    gauges), /fleet/metrics, /fleet/workers, and /debug/incidents on
    ``--http-port`` — the process ``scripts/dynamotop.py`` points at."""
    from ..runtime.component import DistributedRuntime
    from ..telemetry.hub import FleetHub, discovery_targets, parse_target_flag
    from ..telemetry.incidents import IncidentRecorder, incident_dir
    from ..telemetry.server import MetricsServer

    targets = [parse_target_flag(s) for s in (flags.hub_target or [])]
    discover = None
    drt = None
    if flags.store_port is not None:
        drt = await DistributedRuntime.connect(
            flags.store_host, flags.store_port)
        discover = discovery_targets(drt, flags.namespace)
    if not targets and discover is None:
        raise SystemExit(
            "in=hub needs scrape targets: --hub-target role=url and/or "
            "--store-port for discovery-registered sidecars"
        )
    hub = FleetHub(targets=targets, discover=discover,
                   interval_s=flags.hub_interval_s)
    routes = [
        ("GET", "/fleet/metrics", hub.handle_fleet_metrics),
        ("GET", "/fleet/workers", hub.handle_fleet_workers),
    ]
    incidents = None
    if incident_dir():
        # listing/fetch surface only — triggers live in the engine
        # processes that own the evidence
        incidents = IncidentRecorder()
        routes.append(("GET", "/debug/incidents",
                       incidents.handle_debug_incidents))
    else:
        # same 501-with-hint contract as the frontend: an operator must
        # learn the flag, not guess at a bare 404
        async def _incidents_off(request):
            from aiohttp import web

            return web.json_response(
                {"error": "no incident recorder attached (set "
                          "DYN_INCIDENT_DIR or --incident-dir)"},
                status=501,
            )

        routes.append(("GET", "/debug/incidents", _incidents_off))
    server = await MetricsServer(
        hub.registry, flags.http_host, flags.http_port, routes=routes
    ).start()
    hub.start()
    print(f"fleet hub on http://{flags.http_host}:{server.port} "
          f"({len(targets)} static target(s)"
          f"{', discovery-driven' if discover else ''})", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await hub.stop()
        await server.stop()
        if drt is not None:
            await drt.close()


async def run_planner(flags) -> None:
    """Standalone SLA-planner role (in=planner): scrape the worker pool's
    load snapshots + the prefill work-queue depth, run the policy, and
    actuate — disagg-router thresholds through the discovery plane, and
    per-role replica counts through the api-store record the operator
    reconciles (``--api-store-url`` + ``--planner-deployment``)."""
    from ..disagg.protocols import PrefillQueue
    from ..http.service import parse_endpoint_path
    from ..kv_router.metrics_aggregator import KvMetricsAggregator
    from ..planner import (
        LocalActuator,
        Planner,
        PlannerConfig,
        PolicyConfig,
        SlaPolicy,
        StoreScaleActuator,
        aggregator_source,
    )
    from ..runtime.client import Client, RouterMode
    from ..runtime.component import DistributedRuntime
    from ..telemetry.server import maybe_start_metrics_server

    if flags.store_port is None:
        raise SystemExit("in=planner requires --store-port")
    if not flags.worker_endpoint:
        raise SystemExit(
            "in=planner requires --worker-endpoint "
            "(the decode workers to observe)"
        )
    drt = await DistributedRuntime.connect(flags.store_host, flags.store_port)
    wns, wcomp, wep = parse_endpoint_path(flags.worker_endpoint)
    client = Client(
        drt.namespace(wns).component(wcomp).endpoint(wep),
        RouterMode.ROUND_ROBIN,
    )
    await client.start()
    aggregator = KvMetricsAggregator(client)
    aggregator.start()

    policy = SlaPolicy(
        PolicyConfig(
            min_replicas=flags.planner_min_replicas,
            max_replicas=flags.planner_max_replicas,
            scale_up_cooldown_s=flags.planner_cooldown_s,
            scale_down_cooldown_s=flags.planner_cooldown_s * 4,
        ),
        initial_local_prefill_length=flags.max_local_prefill_length,
        initial_prefill_queue_size=flags.max_prefill_queue_size,
    )
    planner = Planner(
        policy, config=PlannerConfig(interval_s=flags.planner_interval_s)
    )
    planner.add_source(aggregator_source(aggregator))

    # prefill work-queue depth: same cached-poll pattern the decode-side
    # coordinator uses (disagg/coordinator.py _depth_loop). The dict
    # starts EMPTY and empties again on failure — fabricating a 0 here
    # would read as "queue drained" and steer the rebalance policy the
    # wrong way exactly when the messaging plane is down.
    queue = PrefillQueue(drt.messaging, flags.namespace)
    depth: dict = {}

    async def _depth_loop() -> None:
        while True:
            try:
                depth["prefill.queue_depth"] = float(await queue.depth())
            except Exception:
                depth.clear()
                logger.debug("prefill queue depth refresh failed",
                             exc_info=True)
            await asyncio.sleep(1.0)

    depth_task = drt.runtime.spawn(_depth_loop())
    planner.add_source(lambda: depth)

    planner.add_actuator(LocalActuator(
        discovery=drt.discovery, namespace=flags.namespace,
        model_name=flags.model_name,
    ))
    if flags.api_store_url and flags.planner_deployment:
        from ..deploy.store_source import ApiStoreClient

        planner.add_actuator(StoreScaleActuator(
            ApiStoreClient(flags.api_store_url), flags.planner_deployment,
        ))
    else:
        logger.warning(
            "in=planner without --api-store-url/--planner-deployment: "
            "scale actions will be decided and logged but not actuated"
        )

    hub = _build_hub(flags)
    routes = None
    if hub is not None:
        # fleet hub riding the planner: scrape the discovery-registered
        # sidecars, feed fleet-level saturation into the policy, and
        # serve /fleet/* next to the planner's own exposition
        from ..telemetry.hub import discovery_targets

        hub.discover = discovery_targets(drt, flags.namespace)
        planner.add_source(hub.signal_source())
        planner.registry.attach(hub.registry)
        routes = [
            ("GET", "/fleet/metrics", hub.handle_fleet_metrics),
            ("GET", "/fleet/workers", hub.handle_fleet_workers),
        ]
        hub.start(spawn=drt.runtime.spawn)

    mserver = await maybe_start_metrics_server(
        planner.registry, flags.metrics_port, routes=routes
    )
    planner.start(spawn=drt.runtime.spawn)
    print(f"planner observing {flags.worker_endpoint} "
          f"every {flags.planner_interval_s:.1f}s"
          f"{' + fleet hub' if hub else ''}", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        planner.stop()
        depth_task.cancel()
        if hub is not None:
            await hub.stop()
        if mserver is not None:
            await mserver.stop()
        aggregator.stop()
        await client.close()
        await drt.close()


async def amain(argv: List[str]) -> None:
    src, engine_spec, rest = parse_io(argv)
    flags = build_parser().parse_args(rest)
    from ..utils.logging import setup_logging
    setup_logging(logging.DEBUG if flags.verbose else logging.INFO)

    if flags.flight_dir:
        # one env var is the single source of truth for every dump site
        # (watchdog trips, SIGUSR2, /debug/flight?save=1)
        import os

        os.environ["DYN_FLIGHT_DIR"] = flags.flight_dir
    if flags.incident_dir:
        # same single-source-of-truth pattern for incident bundles
        import os

        os.environ["DYN_INCIDENT_DIR"] = flags.incident_dir
    # SIGUSR2 → flight artifact, on EVERY role (frontend, worker,
    # prefill): the zero-downtime way to ask "what is this process
    # doing" — works even when the event loop is wedged
    from ..telemetry.watchdog import install_signal_dump

    install_signal_dump()

    if flags.num_nodes > 1:
        # must run before the first jax backend touch in this process so
        # jax.devices() is already global when the engine builds its mesh
        from ..parallel.mesh import MultiHostConfig, initialize_multihost

        initialize_multihost(MultiHostConfig(
            leader_addr=flags.leader_addr,
            num_nodes=flags.num_nodes,
            node_rank=flags.node_rank,
        ))

    if flags.profiler_port:
        # AFTER multihost init: start_server touches the backend, which
        # would pin a local-only world before jax.distributed runs
        from ..utils.profiling import enable_profiler_server

        enable_profiler_server(flags.profiler_port)

    if src == "prefill":
        await run_prefill(flags)
        return
    if src == "planner":
        await run_planner(flags)
        return
    if src == "hub":
        await run_hub(flags)
        return
    if src.startswith("dyn://"):
        await run_worker(flags, engine_spec, src)
        return

    engine, mdc = await build_engine(engine_spec, flags)
    if src == "http":
        await run_http(flags, engine, mdc)
    elif src in ("text", "stdin"):
        await run_text(flags, engine, mdc)
    elif src.startswith("batch:"):
        from .batch import run_batch

        await run_batch(flags, engine, mdc, src[len("batch:"):])
    else:
        raise SystemExit(f"unknown input {src!r}")


def main() -> None:
    from ..utils.platform import apply_jax_platform_override

    apply_jax_platform_override()
    try:
        asyncio.run(amain(sys.argv[1:]))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
