"""Namespace metrics aggregator: scrape worker stats → Prometheus.

Reference analog: components/metrics (reference: components/metrics/src/
{main,lib}.rs — standalone binary that scrapes a target endpoint's
service stats, subscribes to namespace kv-hit-rate events, and exposes
namespace-level Prometheus). Here the scrape rides the ``_stats.*``
RPC every serving endpoint answers (runtime/component.py), whose ``data``
field carries the worker's ForwardPassMetrics.

    python -m dynamo_tpu.cli.metrics --store-port 4871 \
        --endpoint dyn://public.backend.generate --metrics-port 9091
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import sys
from typing import List

from aiohttp import web

from ..http.metrics import Counter, Gauge
from ..http.service import parse_endpoint_path
from ..kv_router.protocols import KV_HIT_RATE_EVENT, ForwardPassMetrics
from ..runtime.client import Client
from ..runtime.component import DistributedRuntime

logger = logging.getLogger(__name__)

# which ForwardPassMetrics fields become gauges (labelled by instance)
_FPM_FIELDS = (
    "request_active_slots", "request_total_slots", "kv_active_blocks",
    "kv_total_blocks", "num_requests_waiting", "gpu_cache_usage_perc",
    "gpu_prefix_cache_hit_rate",
)


class MetricsAggregator:
    def __init__(self, drt: DistributedRuntime, endpoint_path: str,
                 prefix: str = "dynamo", poll_interval: float = 1.0):
        ns, comp, ep = parse_endpoint_path(endpoint_path)
        self.namespace = ns
        self.client = Client(drt.namespace(ns).component(comp).endpoint(ep))
        self.drt = drt
        self.poll_interval = poll_interval
        self.gauges = {
            f: Gauge(f"{prefix}_worker_{f}", f"worker {f} (scraped)")
            for f in _FPM_FIELDS
        }
        self.inflight = Gauge(
            f"{prefix}_worker_inflight_requests", "in-flight requests"
        )
        # a scraped snapshot of the worker's monotonic request counter —
        # exposed as TYPE counter (values are set, not incremented, each
        # scrape; the federation pattern)
        self.requests_total = Counter(
            f"{prefix}_worker_requests_total", "requests handled (scraped)"
        )
        self.kv_hit_events = Counter(
            f"{prefix}_kv_hit_rate_events_total", "KVHitRateEvents by worker"
        )
        self.kv_hit_blocks = Counter(
            f"{prefix}_kv_hit_overlap_blocks_total", "overlap blocks in hit events"
        )
        self._tasks: List[asyncio.Task] = []

    async def start(self) -> None:
        await self.client.start()
        self._tasks.append(self.drt.runtime.spawn(self._poll_loop()))
        sub = await self.drt.namespace(self.namespace).subscribe_event(
            KV_HIT_RATE_EVENT
        )
        self._tasks.append(self.drt.runtime.spawn(self._consume_hit_events(sub)))

    async def collect_once(self) -> int:
        """One scrape pass; returns the number of instances that answered.

        Series for instances that stopped answering are dropped so dead or
        restarted workers don't export phantom capacity forever."""
        stats = await self.client.scrape_stats()
        live = set(stats)
        for g in (self.inflight, self.requests_total, *self.gauges.values()):
            g.values = {
                k: v for k, v in g.values.items()
                if dict(k).get("instance") in live
            }
        for iid, s in stats.items():
            self.inflight.set(float(s.get("inflight", 0)), instance=iid)
            self.requests_total.set_sample(
                float(s.get("requests_total", 0)), instance=iid
            )
            data = s.get("data")
            if data:
                fpm = ForwardPassMetrics.from_wire(data)
                for f in _FPM_FIELDS:
                    self.gauges[f].set(float(getattr(fpm, f)), instance=iid)
        return len(stats)

    async def _poll_loop(self) -> None:
        while True:
            try:
                await self.collect_once()
            except Exception:
                logger.exception("scrape failed")
            await asyncio.sleep(self.poll_interval)

    async def _consume_hit_events(self, sub) -> None:
        import msgpack

        async for msg in sub:
            try:
                ev = msgpack.unpackb(msg.payload, raw=False)
                wid = str(ev.get("worker_id"))
                self.kv_hit_events.inc(worker=wid)
                self.kv_hit_blocks.inc(ev.get("overlap_blocks", 0), worker=wid)
            except Exception:
                logger.exception("bad kv-hit-rate event")

    def render(self) -> str:
        metrics = [
            self.inflight, self.requests_total, self.kv_hit_events,
            self.kv_hit_blocks, *self.gauges.values(),
        ]
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"

    def stop(self) -> None:
        for t in self._tasks:
            t.cancel()


async def amain(argv: List[str]) -> None:
    p = argparse.ArgumentParser(prog="dynamo-tpu metrics")
    p.add_argument("--store-host", default="127.0.0.1")
    p.add_argument("--store-port", type=int, required=True)
    p.add_argument("--endpoint", required=True, help="dyn://ns.comp.ep to scrape")
    p.add_argument("--metrics-host", default="0.0.0.0")
    p.add_argument("--metrics-port", type=int, default=9091)
    p.add_argument("--poll-interval", type=float, default=1.0)
    args = p.parse_args(argv)

    drt = await DistributedRuntime.connect(args.store_host, args.store_port)
    agg = MetricsAggregator(drt, args.endpoint, poll_interval=args.poll_interval)
    await agg.start()

    async def metrics_handler(_request):
        return web.Response(text=agg.render(), content_type="text/plain")

    app = web.Application()
    app.router.add_get("/metrics", metrics_handler)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, args.metrics_host, args.metrics_port)
    await site.start()
    logger.info("metrics on http://%s:%d/metrics", args.metrics_host, args.metrics_port)
    try:
        await drt.runtime.wait_shutdown()
    finally:
        agg.stop()
        await runner.cleanup()
        await drt.close()


def main() -> None:
    from ..utils.logging import setup_logging
    setup_logging(logging.INFO)
    asyncio.run(amain(sys.argv[1:]))


if __name__ == "__main__":
    main()
