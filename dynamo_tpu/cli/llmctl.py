"""llmctl: manage model registrations and graph deployments.

Reference analogs: launch/llmctl (reference: launch/llmctl/src/main.rs:105-452
— ``llmctl http add chat-models <name> dyn://ns.comp.ep`` writing
ModelEntry records the HTTP frontend's model watcher picks up) and the
SDK's deploy client (reference: deploy/dynamo/sdk/src/dynamo/sdk/cli/
deploy.py — POSTing a packaged graph to the api-store, which creates the
cluster deployment).

    python -m dynamo_tpu.cli.llmctl --store-port 4871 http add chat-models m8b dyn://public.backend.generate
    python -m dynamo_tpu.cli.llmctl --store-port 4871 http list
    python -m dynamo_tpu.cli.llmctl --store-port 4871 http remove chat-models m8b
    python -m dynamo_tpu.cli.llmctl deploy create mygraph -f graph.json --api-store http://store:8790
    python -m dynamo_tpu.cli.llmctl deploy list --api-store http://store:8790
    python -m dynamo_tpu.cli.llmctl deploy delete mygraph --api-store http://store:8790
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import sys
from typing import List

from ..http.service import (
    list_models,
    parse_endpoint_path,
    register_model,
    unregister_model,
)
from ..runtime.component import DistributedRuntime

logger = logging.getLogger(__name__)

# CLI model-kind words → registry model_type
KINDS = {
    "chat-models": "chat",
    "completion-models": "completions",
    "models": "both",
}


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="llmctl")
    p.add_argument("--store-host", default="127.0.0.1")
    p.add_argument("--store-port", type=int, default=None,
                   help="dynstore port (required for the http plane)")
    p.add_argument("--namespace", default="public")
    sub = p.add_subparsers(dest="plane", required=True)
    http = sub.add_parser("http", help="manage the HTTP frontend's models")
    hsub = http.add_subparsers(dest="action", required=True)

    add = hsub.add_parser("add")
    add.add_argument("kind", choices=sorted(KINDS))
    add.add_argument("name")
    add.add_argument("endpoint", help="dyn://ns.comp.ep")

    rm = hsub.add_parser("remove")
    rm.add_argument("kind", choices=sorted(KINDS))
    rm.add_argument("name")

    hsub.add_parser("list")

    dep = sub.add_parser(
        "deploy", help="manage graph deployments via the api-store"
    )
    # shared by every deploy leaf so the flag works in any position
    store_opt = argparse.ArgumentParser(add_help=False)
    store_opt.add_argument("--api-store", default="http://127.0.0.1:8790",
                           help="api-store base URL")
    dsub = dep.add_subparsers(dest="action", required=True)
    dc = dsub.add_parser("create", parents=[store_opt],
                         help="register a graph deployment spec")
    dc.add_argument("name")
    dc.add_argument("-f", "--file",
                    help="JSON (or YAML) deployment spec — the CR spec: "
                         "{services: {...}, modelName: ...}")
    dc.add_argument("--from-artifact", metavar="TARBALL",
                    help="versioned graph artifact (sdk.build output); "
                         "the spec is rendered from its manifest, with "
                         "-f (if given) overlaid on top")
    du = dsub.add_parser("update", parents=[store_opt])
    du.add_argument("name")
    du.add_argument("-f", "--file")
    du.add_argument("--from-artifact", metavar="TARBALL")
    dg = dsub.add_parser("get", parents=[store_opt])
    dg.add_argument("name")
    dsub.add_parser("list", parents=[store_opt])
    dd = dsub.add_parser("delete", parents=[store_opt])
    dd.add_argument("name")
    return p


def _load_spec(path: str) -> dict:
    import json

    with open(path) as f:
        text = f.read()
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        import yaml

        return yaml.safe_load(text)


def run_deploy(args) -> int:
    """Sync deploy-plane commands (no distributed runtime needed)."""
    import json

    from ..deploy.store_source import ApiStoreClient

    client = ApiStoreClient(args.api_store)

    def _resolve_spec() -> dict:
        """--from-artifact renders the spec from the manifest; -f (alone
        or on top) supplies/overlays raw spec fields."""
        artifact = getattr(args, "from_artifact", None)
        if not artifact and not args.file:
            raise SystemExit("one of -f/--file or --from-artifact is required")
        spec: dict = {}
        if artifact:
            from ..sdk.build import deployment_spec, inspect_artifact

            spec = deployment_spec(inspect_artifact(artifact))
        if args.file:
            overlay = _load_spec(args.file)
            services = {**spec.get("services", {}),
                        **overlay.get("services", {})}
            spec = {**spec, **overlay}
            if services:
                spec["services"] = services
        return spec

    if args.action == "create":
        spec = _resolve_spec()
        rec = client.create(args.name, spec)
        ver = (spec.get("artifact") or {}).get("version")
        print(f"created deployment {rec['name']}"
              + (f" (artifact {ver})" if ver else ""))
        return 0
    if args.action == "update":
        rec = client.update(args.name, _resolve_spec())
        print(f"updated deployment {rec['name']}")
        return 0
    if args.action == "get":
        rec = client.get(args.name)
        if rec is None:
            print(f"deployment {args.name!r} not found")
            return 1
        print(json.dumps(rec, indent=2))
        return 0
    if args.action == "list":
        records = client.list()
        if not records:
            print("(no deployments)")
        for rec in records:
            conds = (rec.get("status") or {}).get("conditions") or []
            health = conds[0]["status"] if conds else "-"
            print(f"{rec['name']:30s} reconciled={health:6s} "
                  f"services={len(rec['spec'].get('services') or {})}")
        return 0
    if args.action == "delete":
        client.delete(args.name)
        print(f"deleted deployment {args.name}")
        return 0
    return 2


async def run(args, drt: DistributedRuntime) -> int:
    if args.action == "add":
        try:
            # strict parse — the frontend's model watcher parses the same
            # way, so a malformed address must fail HERE, not there
            parse_endpoint_path(args.endpoint)
        except ValueError as e:
            print(f"bad endpoint {args.endpoint!r}: {e}")
            return 2
        await register_model(
            drt, args.namespace, args.name, args.endpoint,
            model_type=KINDS[args.kind],
            # registrations from a short-lived CLI must outlive it
            lease_scoped=False,
        )
        print(f"added {KINDS[args.kind]} model {args.name} -> {args.endpoint}")
        return 0
    if args.action == "remove":
        await unregister_model(drt, args.namespace, args.name, KINDS[args.kind])
        print(f"removed {KINDS[args.kind]} model {args.name}")
        return 0
    if args.action == "list":
        models = await list_models(drt, args.namespace)
        if not models:
            print("(no models registered)")
        for m in models:
            print(f"{m.get('model_type', '?'):12s} {m['name']:30s} {m['endpoint']}")
        return 0
    return 2


async def amain(argv: List[str]) -> int:
    args = build_parser().parse_args(argv)
    if args.plane == "deploy":
        return run_deploy(args)
    if args.store_port is None:
        print("--store-port is required for the http plane")
        return 2
    drt = await DistributedRuntime.connect(args.store_host, args.store_port)
    try:
        return await run(args, drt)
    finally:
        await drt.close()


def main() -> None:
    from ..utils.logging import setup_logging
    setup_logging(logging.WARNING)
    raise SystemExit(asyncio.run(amain(sys.argv[1:])))


if __name__ == "__main__":
    main()
