"""llmctl: manage model registrations in the discovery plane.

Reference analog: launch/llmctl (reference: launch/llmctl/src/main.rs:105-452
— ``llmctl http add chat-models <name> dyn://ns.comp.ep`` writing
ModelEntry records the HTTP frontend's model watcher picks up).

    python -m dynamo_tpu.cli.llmctl --store-port 4871 http add chat-models m8b dyn://public.backend.generate
    python -m dynamo_tpu.cli.llmctl --store-port 4871 http list
    python -m dynamo_tpu.cli.llmctl --store-port 4871 http remove chat-models m8b
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import sys
from typing import List

from ..http.service import (
    list_models,
    parse_endpoint_path,
    register_model,
    unregister_model,
)
from ..runtime.component import DistributedRuntime

logger = logging.getLogger(__name__)

# CLI model-kind words → registry model_type
KINDS = {
    "chat-models": "chat",
    "completion-models": "completions",
    "models": "both",
}


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="llmctl")
    p.add_argument("--store-host", default="127.0.0.1")
    p.add_argument("--store-port", type=int, required=True)
    p.add_argument("--namespace", default="public")
    sub = p.add_subparsers(dest="plane", required=True)
    http = sub.add_parser("http", help="manage the HTTP frontend's models")
    hsub = http.add_subparsers(dest="action", required=True)

    add = hsub.add_parser("add")
    add.add_argument("kind", choices=sorted(KINDS))
    add.add_argument("name")
    add.add_argument("endpoint", help="dyn://ns.comp.ep")

    rm = hsub.add_parser("remove")
    rm.add_argument("kind", choices=sorted(KINDS))
    rm.add_argument("name")

    hsub.add_parser("list")
    return p


async def run(args, drt: DistributedRuntime) -> int:
    if args.action == "add":
        try:
            # strict parse — the frontend's model watcher parses the same
            # way, so a malformed address must fail HERE, not there
            parse_endpoint_path(args.endpoint)
        except ValueError as e:
            print(f"bad endpoint {args.endpoint!r}: {e}")
            return 2
        await register_model(
            drt, args.namespace, args.name, args.endpoint,
            model_type=KINDS[args.kind],
            # registrations from a short-lived CLI must outlive it
            lease_scoped=False,
        )
        print(f"added {KINDS[args.kind]} model {args.name} -> {args.endpoint}")
        return 0
    if args.action == "remove":
        await unregister_model(drt, args.namespace, args.name, KINDS[args.kind])
        print(f"removed {KINDS[args.kind]} model {args.name}")
        return 0
    if args.action == "list":
        models = await list_models(drt, args.namespace)
        if not models:
            print("(no models registered)")
        for m in models:
            print(f"{m.get('model_type', '?'):12s} {m['name']:30s} {m['endpoint']}")
        return 0
    return 2


async def amain(argv: List[str]) -> int:
    args = build_parser().parse_args(argv)
    drt = await DistributedRuntime.connect(args.store_host, args.store_port)
    try:
        return await run(args, drt)
    finally:
        await drt.close()


def main() -> None:
    from ..utils.logging import setup_logging
    setup_logging(logging.WARNING)
    raise SystemExit(asyncio.run(amain(sys.argv[1:])))


if __name__ == "__main__":
    main()
