"""Batch input driver: run a JSONL file of prompts through the engine.

Reference analog: launch/dynamo-run/src/input/batch.rs. Each line is
{"text": ...} or a full chat request; writes JSONL results with latency and
token counts to stdout (or --output).
"""

from __future__ import annotations

import json
import time

from ..protocols.annotated import Annotated
from ..protocols.openai import ChatCompletionRequest
from ..runtime.engine import Context


async def run_batch(flags, engine, mdc, path: str) -> None:
    name = flags.model_name or (mdc.display_name if mdc else "echo")
    with open(path) as f:
        lines = [json.loads(line) for line in f if line.strip()]
    for i, entry in enumerate(lines):
        if "messages" in entry:
            req = ChatCompletionRequest.model_validate({"model": name, **entry})
        else:
            req = ChatCompletionRequest(
                model=name,
                messages=[{"role": "user", "content": entry.get("text", "")}],
                max_tokens=entry.get("max_tokens"),
            )
        start = time.monotonic()
        first = None
        parts = []
        async for chunk in engine.generate(Context(req)):
            if Annotated.maybe_from_wire(chunk) is not None:
                continue  # annotation envelopes carry no completion text
            d = chunk if isinstance(chunk, dict) else chunk.model_dump(exclude_none=True)
            for choice in d.get("choices", []):
                content = (choice.get("delta") or {}).get("content")
                if content:
                    if first is None:
                        first = time.monotonic() - start
                    parts.append(content)
        print(
            json.dumps(
                {
                    "index": i,
                    "output": "".join(parts),
                    "ttft_s": round(first or 0.0, 4),
                    "total_s": round(time.monotonic() - start, 4),
                }
            ),
            flush=True,
        )
