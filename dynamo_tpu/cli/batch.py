"""Batch input driver: run a JSONL file of prompts through the engine.

Reference analog: launch/dynamo-run/src/input/batch.rs. Each line is
{"text": ...} or a full chat request; writes JSONL results with latency and
token counts to stdout (or --output). Per-request records carry TTFT,
inter-token latency (mean/p99), and total duration; a final aggregate
summary goes to stderr so result streams stay machine-parseable.
"""

from __future__ import annotations

import asyncio
import json
import sys
import time
from typing import List

from ..protocols.annotated import Annotated
from ..protocols.openai import ChatCompletionRequest
from ..runtime.engine import Context


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def itl_stats(gaps: List[float]) -> dict:
    """Gaps between consecutive emissions → {mean, p99} (0.0 when a
    request produced fewer than two chunks)."""
    if not gaps:
        return {"itl_mean_s": 0.0, "itl_p99_s": 0.0}
    return {
        "itl_mean_s": round(sum(gaps) / len(gaps), 4),
        "itl_p99_s": round(_percentile(sorted(gaps), 0.99), 4),
    }


def _load_jsonl(path: str) -> List[dict]:
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


async def run_batch(flags, engine, mdc, path: str) -> None:
    name = flags.model_name or (mdc.display_name if mdc else "echo")
    # off-loop read: the engine (and its KV publishers) may already be
    # serving on this loop while a big batch file loads
    lines = await asyncio.get_running_loop().run_in_executor(
        None, _load_jsonl, path)
    ttfts: List[float] = []
    all_gaps: List[float] = []
    for i, entry in enumerate(lines):
        if "messages" in entry:
            req = ChatCompletionRequest.model_validate({"model": name, **entry})
        else:
            req = ChatCompletionRequest(
                model=name,
                messages=[{"role": "user", "content": entry.get("text", "")}],
                max_tokens=entry.get("max_tokens"),
            )
        start = time.monotonic()
        first = None
        last_emit = None
        gaps: List[float] = []
        parts = []
        async for chunk in engine.generate(Context(req)):
            if Annotated.maybe_from_wire(chunk) is not None:
                continue  # annotation envelopes carry no completion text
            d = chunk if isinstance(chunk, dict) else chunk.model_dump(exclude_none=True)
            for choice in d.get("choices", []):
                content = (choice.get("delta") or {}).get("content")
                if content:
                    now = time.monotonic()
                    if first is None:
                        first = now - start
                    else:
                        gaps.append(now - last_emit)
                    last_emit = now
                    parts.append(content)
        if first is not None:
            ttfts.append(first)
        all_gaps.extend(gaps)
        print(
            json.dumps(
                {
                    "index": i,
                    "output": "".join(parts),
                    "ttft_s": round(first or 0.0, 4),
                    **itl_stats(gaps),
                    "total_s": round(time.monotonic() - start, 4),
                }
            ),
            flush=True,
        )
    if lines:
        summary = {
            "requests": len(lines),
            "ttft_mean_s": round(sum(ttfts) / len(ttfts), 4) if ttfts else 0.0,
            "ttft_p99_s": round(_percentile(sorted(ttfts), 0.99), 4),
            **itl_stats(all_gaps),
        }
        print(f"batch summary: {json.dumps(summary)}", file=sys.stderr, flush=True)
