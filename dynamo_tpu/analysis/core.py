"""dynlint framework: source model, rule protocol, suppressions, runner.

Design constraints (why this is not just flake8 config):

- Rules need *semantic* context a line-regex can't see — "is this call
  inside an ``async def``", "is this function traced by ``jax.jit``",
  "is this lock held across an ``await``". Everything here is AST.
- The analyzer must never import the code under analysis (importing
  dynamo_tpu modules pulls in jax; lint must run on a bare CPU box in
  CI before any heavy dep is touched). Parsing only.
- Findings are keyed *without* line numbers (``file:rule: message``)
  so the checked-in baseline survives unrelated edits shifting lines.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, Iterator, List, NamedTuple, Optional, Sequence

__all__ = [
    "Finding",
    "ProjectRule",
    "Rule",
    "SourceModule",
    "dotted_name",
    "iter_python_files",
    "lint_paths",
    "lint_source",
]


class Finding(NamedTuple):
    """One rule violation at one source location."""

    rule: str
    file: str  # repo-relative, forward slashes
    line: int
    message: str

    def key(self) -> str:
        """Line-number-free identity used for baseline matching."""
        return f"{self.file}:{self.rule}: {self.message}"

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}"

    def render_github(self) -> str:
        return (
            f"::error file={self.file},line={self.line},"
            f"title=dynlint/{self.rule}::{self.message}"
        )


# ``# dynlint: allow(rule-a, rule-b) - why this is fine``
_ALLOW_RE = re.compile(r"#\s*dynlint:\s*allow\(([a-zA-Z0-9_,\- ]+)\)")


class SourceModule:
    """One parsed file plus the derived context rules share.

    ``rel`` is the path findings are reported under; for real files it
    is relative to the lint root's parent (``dynamo_tpu/http/service.py``),
    for in-memory snippets (tests) it is whatever the caller passed.
    """

    def __init__(self, rel: str, source: str, tree: Optional[ast.AST] = None):
        self.rel = rel.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree if tree is not None else ast.parse(source, filename=rel)
        self._aliases: Optional[Dict[str, str]] = None

    # --- import alias map -------------------------------------------------

    @property
    def aliases(self) -> Dict[str, str]:
        """Local name -> canonical dotted path, from this module's imports.

        ``import threading``            -> {"threading": "threading"}
        ``import subprocess as sp``     -> {"sp": "subprocess"}
        ``from time import sleep``      -> {"sleep": "time.sleep"}
        ``from jax import jit as j``    -> {"j": "jax.jit"}
        """
        if self._aliases is None:
            amap: Dict[str, str] = {}
            for node in ast.walk(self.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        amap[a.asname or a.name.split(".")[0]] = (
                            a.name if a.asname else a.name.split(".")[0]
                        )
                elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
                    for a in node.names:
                        if a.name == "*":
                            continue
                        amap[a.asname or a.name] = f"{node.module}.{a.name}"
            self._aliases = amap
        return self._aliases

    def resolve_call(self, func: ast.AST) -> Optional[str]:
        """Canonical dotted name of a call target, or None."""
        return dotted_name(func, self.aliases)

    # --- suppressions -----------------------------------------------------

    def allowed_rules_at(self, line: int) -> frozenset:
        """Rules suppressed for a finding on 1-indexed ``line``.

        A suppression counts on the flagged line itself (trailing
        comment), or on the immediately preceding line ONLY when that
        line is a standalone comment — a trailing allow on the previous
        line of code suppresses that line alone, never its neighbors.
        """
        allowed: set = set()

        def collect(idx: int) -> None:
            m = _ALLOW_RE.search(self.lines[idx])
            if m:
                allowed.update(
                    part.strip() for part in m.group(1).split(",") if part.strip()
                )

        if 0 <= line - 1 < len(self.lines):
            collect(line - 1)
        if 0 <= line - 2 < len(self.lines) and \
                self.lines[line - 2].lstrip().startswith("#"):
            collect(line - 2)
        return frozenset(allowed)

    # --- traversal helpers ------------------------------------------------

    def async_functions(self) -> Iterator[ast.AsyncFunctionDef]:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                yield node

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(rule, self.rel, getattr(node, "lineno", 0), message)


def dotted_name(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """``ast`` expression -> canonical dotted path through import aliases.

    ``sp.run`` with ``import subprocess as sp`` -> ``subprocess.run``;
    ``sleep`` with ``from time import sleep`` -> ``time.sleep``; a bare
    un-imported name resolves to itself (covers builtins like ``open``).
    An attribute chain only resolves when its root Name is a known
    import — a local variable that happens to be called ``requests`` or
    ``socket`` must NOT make ``requests.get(rid)`` look like the
    requests library. Chains rooted in non-Name expressions
    (``self.x.y()``) resolve to None likewise.
    """
    if isinstance(node, ast.Name):
        return aliases.get(node.id, node.id)
    if isinstance(node, ast.Attribute):
        parts = [node.attr]
        cur = node.value
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if isinstance(cur, ast.Name) and cur.id in aliases:
            return ".".join([aliases[cur.id]] + list(reversed(parts)))
    return None


def body_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function's subtree WITHOUT descending into nested function
    definitions or lambdas: a nested ``def`` runs in its own (possibly
    sync, possibly deferred) context, so e.g. a blocking call inside it
    is not a blocking call in *this* function's async context. Nested
    ``async def`` bodies are still analyzed — the module walk visits
    every AsyncFunctionDef independently.
    """
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class Rule:
    """A named check over one SourceModule."""

    name: str = ""
    description: str = ""

    def check(self, mod: SourceModule) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError

    def __repr__(self) -> str:
        return f"<rule {self.name}>"


class ProjectRule(Rule):
    """A check over the WHOLE parsed module set at once.

    Per-module rules see one file; an interprocedural pass (thread-domain
    inference, call-graph reachability) needs every module of the scan to
    resolve cross-module calls. ``lint_paths`` collects all modules first
    and hands them here in one call; ``lint_source`` (the fixture entry
    point) falls back to a single-module project, so fixtures exercise a
    ProjectRule exactly like any other rule.
    """

    def check_project(
        self, mods: Sequence[SourceModule]
    ) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError

    def check(self, mod: SourceModule) -> Iterator[Finding]:
        return self.check_project([mod])


# --------------------------------------------------------------------------
# runner
# --------------------------------------------------------------------------


def iter_python_files(root: str) -> Iterator[str]:
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames if d != "__pycache__" and not d.startswith(".")
        )
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def _suppressed(mod: SourceModule, rule_name: str, finding: Finding) -> bool:
    allowed = mod.allowed_rules_at(finding.line)
    return rule_name in allowed or "all" in allowed


def _run_rules(
    mod: SourceModule, rules: Sequence[Rule]
) -> List[Finding]:
    out: List[Finding] = []
    for rule in rules:
        for finding in rule.check(mod):
            if _suppressed(mod, rule.name, finding):
                continue
            out.append(finding)
    return out


def lint_source(
    source: str, rules: Sequence[Rule], rel: str = "<snippet>.py"
) -> List[Finding]:
    """Lint an in-memory snippet — the test-fixture entry point."""
    return _run_rules(SourceModule(rel, source), rules)


def report_rel(path: str) -> str:
    """The scope-independent key path for one source file.

    Ascend from the file's own directory through enclosing packages
    (directories holding ``__init__.py``) and report relative to the
    outermost package's parent — a file inside ``dynamo_tpu`` keys as
    ``dynamo_tpu/engine/guided.py`` whether the lint was pointed at the
    repo, the package, a subpackage, or the file itself, so baseline
    entries always match. A file with no enclosing package keys as its
    bare name.
    """
    path = os.path.abspath(path)
    top = None
    cur = os.path.dirname(path)
    while os.path.exists(os.path.join(cur, "__init__.py")):
        top = cur
        parent = os.path.dirname(cur)
        if parent == cur:
            break
        cur = parent
    base = os.path.dirname(top) if top is not None else os.path.dirname(path)
    return os.path.relpath(path, base).replace(os.sep, "/")


def lint_paths(
    paths: Iterable[str], rules: Sequence[Rule],
    only_files: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Lint every ``.py`` under each path. Each file is keyed by its
    package-relative path regardless of how the scan was scoped (see
    ``report_rel``); overlapping path arguments are deduplicated so a
    file is never counted twice against the baseline ratchet. A path
    that does not exist raises — an empty scan must never read as a
    clean one.

    ``only_files`` (report-relative paths, e.g. from ``--changed``)
    restricts which files produce findings WITHOUT shrinking the scan:
    per-module rules skip the others, but every module under ``paths``
    is still parsed and handed to ProjectRules as call-graph context —
    an interprocedural verdict about a changed file must not flip just
    because its callers didn't change.
    """
    only = None if only_files is None else {
        f.replace(os.sep, "/") for f in only_files
    }
    findings: List[Finding] = []
    module_rules = [r for r in rules if not isinstance(r, ProjectRule)]
    project_rules = [r for r in rules if isinstance(r, ProjectRule)]
    mods: List[SourceModule] = []
    seen: set = set()
    for root in paths:
        if not os.path.exists(root):
            raise FileNotFoundError(f"lint path does not exist: {root}")
        for path in iter_python_files(os.path.abspath(root)):
            if path in seen:
                continue
            seen.add(path)
            rel = report_rel(path)
            with open(path, encoding="utf-8") as f:
                source = f.read()
            try:
                mod = SourceModule(rel, source)
            except SyntaxError as e:
                if only is None or rel in only:
                    findings.append(
                        Finding("parse-error", rel, e.lineno or 0,
                                f"could not parse: {e.msg}")
                    )
                continue
            mods.append(mod)
            if only is None or rel in only:
                findings.extend(_run_rules(mod, module_rules))
    if project_rules and mods:
        by_rel = {m.rel: m for m in mods}
        for rule in project_rules:
            for finding in rule.check_project(mods):
                if only is not None and finding.file not in only:
                    continue
                mod = by_rel.get(finding.file)
                if mod is not None and _suppressed(mod, rule.name, finding):
                    continue
                findings.append(finding)
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings
