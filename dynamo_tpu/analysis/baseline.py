"""Baseline ("ratchet") file: recorded debt vs. new violations.

The baseline maps line-number-free finding keys (``file:rule: message``)
to occurrence counts. Existing debt stays recorded and visible; a NEW
violation — any key whose live count exceeds its baselined count —
fails the lint. Keys are line-free so unrelated edits that shift code
up or down don't invalidate the file; moving or duplicating a violation
*within* the same file is still absorbed, which is the deliberate
trade-off every ratchet linter makes (the debt is per-site-identity,
not per-coordinate).

Stale entries (baselined debt that no longer exists) are reported as
notes and dropped on ``--update-baseline`` so the ratchet only ever
tightens.
"""

from __future__ import annotations

import json
import os
from collections import Counter
from typing import Dict, List, NamedTuple, Sequence

from .core import Finding

BASELINE_VERSION = 1


class BaselineDiff(NamedTuple):
    new: List[Finding]  # violations not covered by the baseline -> fail
    known: List[Finding]  # covered by the baseline -> recorded debt
    stale: List[str]  # keys with FEWER live findings than baselined -> prune


def load_baseline(path: str) -> Dict[str, int]:
    """Key -> allowed count. A missing file is an empty baseline."""
    if not path or not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    entries = data.get("entries", {})
    return {str(k): int(v) for k, v in entries.items()}


def write_baseline(path: str, findings: Sequence[Finding]) -> Dict[str, int]:
    counts = Counter(f.key() for f in findings)
    entries = {k: counts[k] for k in sorted(counts)}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(
            {
                "_comment": (
                    "dynlint recorded debt. Do not add entries by hand: fix "
                    "the finding or suppress it in place with a justified "
                    "'# dynlint: allow(<rule>)'. Regenerate with "
                    "'python scripts/dynlint.py --update-baseline' only when "
                    "deliberately accepting new debt."
                ),
                "version": BASELINE_VERSION,
                "entries": entries,
            },
            f,
            indent=2,
            sort_keys=False,
        )
        f.write("\n")
    return dict(entries)


def diff_against_baseline(
    findings: Sequence[Finding], baseline: Dict[str, int]
) -> BaselineDiff:
    """Split live findings into new vs. known and spot stale debt.

    When a key's live count exceeds its baseline count, the *excess*
    findings (highest line numbers, i.e. most recently added in the
    common append case) are reported as new.
    """
    by_key: Dict[str, List[Finding]] = {}
    for f in findings:
        by_key.setdefault(f.key(), []).append(f)
    new: List[Finding] = []
    known: List[Finding] = []
    for key, group in by_key.items():
        allowed = baseline.get(key, 0)
        group = sorted(group, key=lambda f: f.line)
        known.extend(group[:allowed])
        new.extend(group[allowed:])
    # stale = OVER-allowance, not just zero live findings: fixing one of
    # N identical debt items must shrink the recorded count, or the freed
    # slot would silently absorb a future new identical violation
    stale = sorted(
        k for k, allowed in baseline.items()
        if len(by_key.get(k, ())) < allowed
    )
    new.sort(key=lambda f: (f.file, f.line, f.rule))
    known.sort(key=lambda f: (f.file, f.line, f.rule))
    return BaselineDiff(new=new, known=known, stale=stale)
