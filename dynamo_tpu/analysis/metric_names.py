"""Prometheus metric-name convention checks (shared core).

Every instrument registered anywhere in ``dynamo_tpu/`` must be named
``dynamo_<component>_<name>_<unit>`` (telemetry/registry.py module
docstring): lowercase snake_case, a component segment after the prefix,
and a recognized unit suffix. Counters additionally end in ``_total``;
histograms measure something, so they end in a base unit (seconds,
bytes, tokens), never ``_total``/``_ratio``.

The check is static (AST walk over instrument-registration call sites)
so drift is caught without importing — or starting — any component.
Dynamic-name escape hatches (``register_callback_gauges`` dict
prefixes) are exempt by design.

This module is both the engine behind the dynlint ``metric-name`` rule
(rules/metric_name.py) and the implementation ``scripts/
check_metric_names.py`` shims over; the directory-walk helpers keep
that script's historical CLI/exit-code contract.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Iterator, List, NamedTuple, Optional

PREFIX = "dynamo_"

# the unit vocabulary: extend deliberately, not ad hoc
# ("depth" added for structural stage-count gauges — the decode
# pipeline's dispatch depth; same count family as slots/blocks.
# "replicas" added with the SLA planner's replica-target gauge — worker
# pool size is a first-class count unit in the deployment plane.
# "length" added with the persistent decode loop's burst-chain gauge —
# dispatches between host barriers; a structural count like depth, and
# the Grafana panel derives p50/p99 via quantile_over_time.
# "fraction" added with the live roofline gauge: unlike "ratio" (a
# part-of-whole share of counted things), a fraction names achieved-
# over-bound against a PHYSICAL limit — dynamo_engine_roofline_fraction
# is achieved HBM bytes/s over the chip's peak, the serving-time mirror
# of bench.py's vs_baseline)
UNIT_SUFFIXES = (
    "total", "seconds", "bytes", "tokens", "blocks",
    "requests", "slots", "ratio", "info", "depth", "replicas", "length",
    "fraction",
    # "channels" admitted deliberately with the unified transfer plane's
    # live-channel gauge (dynamo_transfer_channels): a count of open
    # plane connections per {plane,backend} pair — "requests" would
    # misread channels as workload volume
    "channels",
)
# what a histogram may measure. "length" admitted deliberately with the
# speculative acceptance-length histogram (dynamo_engine_spec_accept_
# length): a per-round accepted-token count is a measured quantity like
# tokens, but "tokens" would misread as throughput volume — the length
# distribution (p50/p99 via quantile_over_time) is the signal.
BASE_UNITS = ("seconds", "bytes", "tokens", "length")

# registration call sites: registry/metrics-module methods and the raw
# instrument constructors
METHOD_KINDS = {
    "counter": "counter",
    "gauge": "gauge",
    "histogram": "histogram",
    "callback_gauge": "gauge",
}
CONSTRUCTOR_KINDS = {
    "Counter": "counter",
    "Gauge": "gauge",
    "Histogram": "histogram",
    "CallbackGauge": "gauge",
}


class RegisteredMetric(NamedTuple):
    name: str
    kind: str  # counter | gauge | histogram
    file: str
    line: int


def _literal_name(node: ast.AST) -> Optional[str]:
    """First-argument expression → metric name, or None if unknowable.

    Plain string literals pass through; f-strings substitute ``dynamo``
    for interpolated prefixes (the ``f"{prefix}_..."`` idiom) so the
    constant tail is still checked.
    """
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts: List[str] = []
        for piece in node.values:
            if isinstance(piece, ast.Constant) and isinstance(piece.value, str):
                parts.append(piece.value)
            elif isinstance(piece, ast.FormattedValue) and not parts:
                parts.append("dynamo")  # leading {prefix}
            else:
                return None  # interpolation mid-name: not statically checkable
        return "".join(parts)
    return None


def iter_tree_metrics(tree: ast.AST, rel: str) -> Iterator[RegisteredMetric]:
    """Registration call sites in one parsed module."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        func = node.func
        kind = None
        if isinstance(func, ast.Attribute):
            kind = METHOD_KINDS.get(func.attr)
        elif isinstance(func, ast.Name):
            kind = CONSTRUCTOR_KINDS.get(func.id)
        if kind is None:
            continue
        name = _literal_name(node.args[0])
        if name is None or not name.startswith(PREFIX):
            # dynamic names and non-metric first args (e.g. an
            # unrelated .histogram() API) are out of scope
            continue
        yield RegisteredMetric(name, kind, rel, node.lineno)


def iter_registered_metrics(root: str) -> Iterator[RegisteredMetric]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path) as f:
                try:
                    tree = ast.parse(f.read(), filename=path)
                except SyntaxError:
                    continue  # other lint's problem
            rel = os.path.relpath(path, os.path.dirname(root))
            yield from iter_tree_metrics(tree, rel)


def check_name(metric: RegisteredMetric) -> List[str]:
    """One metric → list of human-readable violations (empty = clean)."""
    problems = []
    name, kind = metric.name, metric.kind
    if name != name.lower() or not all(
        c.isascii() and (c.isalnum() or c == "_") for c in name
    ):
        problems.append("must be lowercase snake_case ([a-z0-9_])")
    parts = name.split("_")
    if len(parts) < 3:
        problems.append(
            "needs at least dynamo_<component>_<name>_<unit> segments")
    # the unit is the LAST underscore-delimited segment — a plain
    # endswith would wave through "subtotal"/"kilobytes" tails
    unit = parts[-1]
    if unit not in UNIT_SUFFIXES:
        problems.append(
            f"must end in a unit suffix {UNIT_SUFFIXES}")
    if kind == "counter" and not name.endswith("_total"):
        problems.append("counters must end in _total")
    if kind != "counter" and name.endswith("_total"):
        problems.append("_total names a counter; this is a " + kind)
    if kind == "histogram" and unit not in BASE_UNITS:
        problems.append(
            f"histograms must measure a base unit {BASE_UNITS}")
    return problems


def run_check(root: str) -> List[str]:
    """Lint every registration under ``root`` → list of violation lines."""
    violations = []
    for metric in iter_registered_metrics(root):
        for problem in check_name(metric):
            violations.append(
                f"{metric.file}:{metric.line}: {metric.name}: {problem}")
    return violations


def main(argv: List[str]) -> int:
    root = argv[1] if len(argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        "dynamo_tpu",
    )
    violations = run_check(root)
    for line in violations:
        print(line)
    if violations:
        print(f"{len(violations)} metric-name violation(s)")
        return 1
    count = sum(1 for _ in iter_registered_metrics(root))
    print(f"{count} registered metric names conform to "
          f"{PREFIX}<component>_<name>_<unit>")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
