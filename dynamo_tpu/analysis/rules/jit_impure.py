"""jit-impure: side effects and host syncs inside traced JAX code.

A function under ``jax.jit``/``pjit``/``shard_map`` runs ONCE as a
Python trace, then replays as compiled XLA. Anything impure is wrong
twice over:

- **Mutation** (``self.x = ...``, ``global``) happens at trace time
  only — silently absent from every subsequent call, a classic
  "worked in the repl" bug.
- **Host syncs** (``.item()``, ``np.asarray``, ``jax.device_get``,
  ``block_until_ready``) either fail under tracing or, worse, force a
  device→host round-trip per dispatch — the exact stall PR 1's
  ``host_sync`` phase histogram exists to measure at runtime. This
  rule is its static twin: catch the stall before it ships.
- ``print`` fires once at trace time (misleading) — ``jax.debug.print``
  is the traced form and is not flagged.

Traced functions are found two ways: jit-ish decorators (including
``functools.partial(jax.jit, ...)``) and the call form
``jax.jit(fn)``/``jax.jit(lambda ...)`` resolved against same-module
definitions — which is how engine/model_runner.py builds all its
compiled steps.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Set, Tuple, Union

import ast

from ..core import Finding, Rule, SourceModule

FuncNode = Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]

JIT_WRAPPERS = {
    "jax.jit",
    "jit",
    "jax.pjit",
    "pjit",
    "jax.experimental.pjit.pjit",
    "jax.shard_map",
    "shard_map",
    "jax.experimental.shard_map.shard_map",
}

# dotted call names that force a device->host sync (or fail) under trace.
# CANONICAL module names only: alias resolution maps "import numpy as np;
# np.asarray" to "numpy.asarray", so "np.*" keys would never match
HOST_SYNC_CALLS = {
    "numpy.asarray",
    "numpy.array",
    "jax.device_get",
    "jax.block_until_ready",
}
# method names that host-sync regardless of receiver
HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}


def _is_jit_wrapper(mod: SourceModule, node: ast.AST) -> bool:
    """True for ``jax.jit`` / ``partial(jax.jit, ...)`` expressions."""
    name = mod.resolve_call(node) if not isinstance(node, ast.Call) else None
    if name in JIT_WRAPPERS:
        return True
    if isinstance(node, ast.Call):
        called = mod.resolve_call(node.func)
        if called in JIT_WRAPPERS:
            return True
        if called in ("functools.partial", "partial") and node.args:
            return _is_jit_wrapper(mod, node.args[0])
    return False


def _collect_traced(mod: SourceModule) -> List[Tuple[str, FuncNode]]:
    """(display name, function node) for every traced function."""
    defs: Dict[str, FuncNode] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node

    traced: List[Tuple[str, FuncNode]] = []
    seen: Set[int] = set()

    def add(name: str, fn: FuncNode) -> None:
        if id(fn) not in seen:
            seen.add(id(fn))
            traced.append((name, fn))

    # decorator form
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_wrapper(mod, dec):
                    add(node.name, node)
    # call form: jax.jit(fn) / jax.jit(lambda: ...)
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        if not _is_jit_wrapper(mod, node.func):
            continue
        target = node.args[0]
        if isinstance(target, ast.Lambda):
            add("<lambda>", target)
        elif isinstance(target, ast.Name) and target.id in defs:
            add(target.id, defs[target.id])
    return traced


class JitImpureRule(Rule):
    name = "jit-impure"
    description = (
        "side effect or host sync inside a jitted/traced function: "
        "mutation vanishes after trace, host syncs stall every dispatch"
    )

    def check(self, mod: SourceModule) -> Iterator[Finding]:
        for name, fn in _collect_traced(mod):
            where = f"traced function '{name}'"
            global_names: Set[str] = set()
            # the whole subtree is traced — including nested defs, which
            # jit inlines when called — so walk it all
            for node in ast.walk(fn):
                if isinstance(node, ast.Global):
                    global_names.update(node.names)
                    continue
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for t in targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            yield mod.finding(
                                self.name,
                                node,
                                f"mutates self.{t.attr} in {where} — the "
                                "write happens at trace time only",
                            )
                        elif isinstance(t, ast.Name) and t.id in global_names:
                            yield mod.finding(
                                self.name,
                                node,
                                f"mutates global '{t.id}' in {where} — the "
                                "write happens at trace time only",
                            )
                    continue
                if not isinstance(node, ast.Call):
                    continue
                called = mod.resolve_call(node.func)
                if called == "print":
                    yield mod.finding(
                        self.name,
                        node,
                        f"print() in {where} fires at trace time only — "
                        "use jax.debug.print",
                    )
                elif called in HOST_SYNC_CALLS:
                    yield mod.finding(
                        self.name,
                        node,
                        f"host-sync call {called}() in {where} — forces a "
                        "device->host transfer per dispatch",
                    )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in HOST_SYNC_METHODS
                ):
                    yield mod.finding(
                        self.name,
                        node,
                        f".{node.func.attr}() in {where} — host-syncs the "
                        "traced value",
                    )
