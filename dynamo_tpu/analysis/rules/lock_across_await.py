"""lock-across-await: thread locks in async code, locks held over await.

Two hazards, both deadlock-shaped:

1. A ``threading.Lock`` acquired on the event loop blocks the whole
   loop while contended — and if the holder needs the loop to make
   progress (the common case here: a callback completes a future), the
   process deadlocks. Async code wants ``asyncio.Lock``.
2. ANY lock — even an ``asyncio.Lock`` via sync ``with`` — held across
   an ``await`` extends the critical section over an arbitrary number
   of scheduler round-trips; every other acquirer stalls behind a
   suspension point they can't see. (``async with lock:`` is the
   reviewed, intentional form and is not flagged.)

Lock-ish context managers are recognized structurally
(``threading.Lock()`` etc. inline) or by name (a last path segment
containing ``lock``/``mutex``) — heuristic on purpose; name your locks
like locks.
"""

from __future__ import annotations

from typing import Iterator, Optional

import ast

from ..core import Finding, Rule, SourceModule, body_nodes, dotted_name

THREADING_PRIMITIVES = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "threading.Semaphore",
    "threading.BoundedSemaphore",
    "multiprocessing.Lock",
    "multiprocessing.RLock",
}


def _lockish_name(mod: SourceModule, expr: ast.AST) -> Optional[str]:
    """Human-readable name if ``expr`` looks like a lock, else None."""
    if isinstance(expr, ast.Call):
        called = mod.resolve_call(expr.func)
        if called in THREADING_PRIMITIVES:
            return called + "()"
        return None
    name = dotted_name(expr, mod.aliases)
    if name is None and isinstance(expr, ast.Attribute):
        name = expr.attr  # self._lock and friends
    if name is None:
        return None
    last = name.rsplit(".", 1)[-1].lower()
    if "lock" in last or "mutex" in last:
        return name
    return None


def _contains_await(node: ast.AST) -> bool:
    return any(
        isinstance(sub, (ast.Await, ast.AsyncFor, ast.AsyncWith))
        for sub in ast.walk(node)
    )


class LockAcrossAwaitRule(Rule):
    name = "lock-across-await"
    description = (
        "threading lock used in async code, or any lock held across an "
        "await — both stall or deadlock the event loop"
    )

    def check(self, mod: SourceModule) -> Iterator[Finding]:
        for fn in mod.async_functions():
            for node in body_nodes(fn):
                # (1) thread-lock constructed in async context
                if isinstance(node, ast.Call):
                    called = mod.resolve_call(node.func)
                    if called in THREADING_PRIMITIVES:
                        yield mod.finding(
                            self.name,
                            node,
                            f"{called}() created in 'async def {fn.name}' — "
                            "use asyncio synchronization primitives",
                        )
                    continue
                # (2) sync `with <lock>:` whose body awaits
                if isinstance(node, ast.With):
                    for item in node.items:
                        lock = _lockish_name(mod, item.context_expr)
                        if lock is None:
                            continue
                        if any(_contains_await(stmt) for stmt in node.body):
                            yield mod.finding(
                                self.name,
                                node,
                                f"lock '{lock}' held across an await in "
                                f"'async def {fn.name}' — the critical "
                                "section spans scheduler round-trips",
                            )
                        break
