"""wallclock-in-sim: the fleet simulator must never read the wall clock.

The simulator's whole contract (PR 16) is byte-identical reports for a
given (scenario, seed): every timestamp comes from the virtual
``SimClock``, and the 1000x speedup exists precisely because nothing
sleeps. One ``time.time()`` in a sim model silently breaks both — the
report diverges between runs and the regression gate starts flaking.
This started life as a regex scan inside ``tests/test_fleetsim.py``;
promoted to a dynlint rule so it gets suppressions, the baseline
ratchet, ``--format=github`` CI annotations, and per-line precision
instead of a per-file assert.

Scoped to ``dynamo_tpu/sim/`` only — the rest of the codebase reads the
wall clock legitimately.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import Finding, Rule, SourceModule

__all__ = ["WallclockInSimRule"]

_BANNED_CALLS = {
    "time.time": "use the scenario's SimClock, not the wall clock",
    "time.time_ns": "use the scenario's SimClock, not the wall clock",
    "time.monotonic": "use the scenario's SimClock, not the wall clock",
    "time.monotonic_ns": "use the scenario's SimClock, not the wall clock",
    "time.perf_counter": "use the scenario's SimClock, not the wall clock",
    "time.perf_counter_ns": "use the scenario's SimClock, not the wall clock",
    "time.sleep": "advance virtual time via the event heap, never sleep",
    "datetime.datetime.now": "derive timestamps from virtual time",
    "datetime.datetime.utcnow": "derive timestamps from virtual time",
    "datetime.date.today": "derive dates from virtual time",
}


class WallclockInSimRule(Rule):
    name = "wallclock-in-sim"
    description = (
        "wall-clock read (time.time/monotonic/perf_counter/sleep, "
        "datetime.now, loop.time) inside dynamo_tpu/sim/ — the simulator "
        "must run on virtual time only"
    )

    def check(self, mod: SourceModule) -> Iterator[Finding]:
        if not mod.rel.startswith("dynamo_tpu/sim/"):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = mod.resolve_call(node.func)
            if dotted in _BANNED_CALLS:
                yield mod.finding(
                    self.name, node,
                    f"{dotted}() in the simulator — {_BANNED_CALLS[dotted]}",
                )
                continue
            # loop.time(): the running asyncio loop's clock is wall-time
            # derived too; match <name containing "loop">.time()
            func = node.func
            if (
                dotted is None
                and isinstance(func, ast.Attribute)
                and func.attr == "time"
                and isinstance(func.value, ast.Name)
                and "loop" in func.value.id
            ):
                yield mod.finding(
                    self.name, node,
                    f"{func.value.id}.time() in the simulator — the event "
                    "loop clock is wall-clock derived; use virtual time",
                )
