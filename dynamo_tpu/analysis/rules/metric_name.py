"""metric-name: registered instrument names follow the house convention.

Thin rule wrapper over analysis/metric_names.py (the engine
scripts/check_metric_names.py also shims); one implementation, two
front doors — the historical standalone CLI keeps its exit-code
contract, and dynlint folds the same check into the baseline/
suppression machinery every other rule gets.
"""

from __future__ import annotations

from typing import Iterator

from ..core import Finding, Rule, SourceModule
from ..metric_names import check_name, iter_tree_metrics


class MetricNameRule(Rule):
    name = "metric-name"
    description = (
        "registered Prometheus instrument name violates "
        "dynamo_<component>_<name>_<unit>"
    )

    def check(self, mod: SourceModule) -> Iterator[Finding]:
        for metric in iter_tree_metrics(mod.tree, mod.rel):
            for problem in check_name(metric):
                yield Finding(
                    self.name,
                    mod.rel,
                    metric.line,
                    f"{metric.name}: {problem}",
                )
