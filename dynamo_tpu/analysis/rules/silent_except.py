"""silent-except: broad handlers that neither log nor re-raise.

``except Exception: pass`` turns every future bug in the guarded block
into a silent no-op — the failure class that motivated this analyzer:
nothing crashes, a counter just stops moving. Narrow handlers
(``except ConnectionResetError``) are presumed deliberate and are not
flagged; only ``except Exception``, ``except BaseException``, and bare
``except`` qualify, and only when the body contains no raise and no
call that surfaces the error (logger/logging/warnings/traceback/print).

The runtime has legitimate best-effort sites (closing a dead writer,
probing a tokenizer vocab); those carry an inline
``# dynlint: allow(silent-except)`` with the justification right where
a reviewer will read it.
"""

from __future__ import annotations

from typing import Iterator

import ast

from ..core import Finding, Rule, SourceModule

BROAD = {"Exception", "BaseException"}
LOG_ROOTS = {"logger", "logging", "log", "warnings", "traceback"}
LOG_METHODS = {
    "debug", "info", "warning", "warn", "error", "exception", "critical",
    "log", "print_exc", "print_exception", "print_stack", "format_exc",
    # propagating into a Future/callback IS observing the error
    "set_exception",
}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        if isinstance(n, ast.Name) and n.id in BROAD:
            return True
        if isinstance(n, ast.Attribute) and n.attr in BROAD:
            return True
    return False


def _surfaces_error(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Name) and func.id == "print":
                return True
            if isinstance(func, ast.Attribute):
                if func.attr in LOG_METHODS:
                    return True
                root = func.value
                while isinstance(root, ast.Attribute):
                    root = root.value
                if isinstance(root, ast.Name) and root.id in LOG_ROOTS:
                    return True
    return False


class SilentExceptRule(Rule):
    name = "silent-except"
    description = (
        "broad except that neither logs nor re-raises: future failures "
        "in the guarded block become silent no-ops"
    )

    def check(self, mod: SourceModule) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node):
                continue
            if _surfaces_error(node):
                continue
            caught = "bare except" if node.type is None else (
                f"except {ast.unparse(node.type)}"
            )
            yield mod.finding(
                self.name,
                node,
                f"{caught} swallows the error — log it, re-raise, or "
                "narrow the exception type",
            )
