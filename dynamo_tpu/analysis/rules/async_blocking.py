"""async-blocking: blocking calls inside ``async def``.

One blocking call on the event loop stalls EVERY in-flight request on
that loop — the scheduler stops stepping, heartbeats stop ponging (the
subprocess host then SIGKILLs a healthy child as "wedged"), and ITL
p99 explodes. The runtime's own rule of thumb (utils/profiling.py,
subprocess_host.py docstrings) is "run sync work through
run_in_executor"; this check makes that rule enforceable.

Matched by canonical dotted name through import aliases, so
``from time import sleep; sleep(1)`` is caught, and nested sync ``def``
bodies are skipped (they run wherever they're called, typically an
executor).
"""

from __future__ import annotations

from typing import Iterator

import ast

from ..core import Finding, Rule, SourceModule, body_nodes

# canonical dotted names that block the calling thread
BLOCKING_CALLS = {
    "time.sleep": "use 'await asyncio.sleep(...)'",
    "os.system": "use 'await asyncio.create_subprocess_shell(...)'",
    "os.wait": "use 'await proc.wait()' on an asyncio subprocess",
    "os.waitpid": "use 'await proc.wait()' on an asyncio subprocess",
    "subprocess.run": "use 'await asyncio.create_subprocess_exec(...)'",
    "subprocess.call": "use 'await asyncio.create_subprocess_exec(...)'",
    "subprocess.check_call": "use 'await asyncio.create_subprocess_exec(...)'",
    "subprocess.check_output": "use 'await asyncio.create_subprocess_exec(...)'",
    "subprocess.getoutput": "use 'await asyncio.create_subprocess_shell(...)'",
    "subprocess.getstatusoutput": "use 'await asyncio.create_subprocess_shell(...)'",
    "subprocess.Popen": "use 'await asyncio.create_subprocess_exec(...)'",
    "socket.create_connection": "use 'await asyncio.open_connection(...)'",
    "socket.getaddrinfo": "use 'await loop.getaddrinfo(...)'",
    "socket.gethostbyname": "use 'await loop.getaddrinfo(...)'",
    "urllib.request.urlopen": "use an executor or an async http client",
    "requests.get": "use an executor or an async http client",
    "requests.post": "use an executor or an async http client",
    "requests.put": "use an executor or an async http client",
    "requests.patch": "use an executor or an async http client",
    "requests.delete": "use an executor or an async http client",
    "requests.head": "use an executor or an async http client",
    "requests.request": "use an executor or an async http client",
    "open": "open via 'run_in_executor' (file IO blocks the loop)",
}


class AsyncBlockingRule(Rule):
    name = "async-blocking"
    description = (
        "blocking call (sleep/subprocess/socket/file IO/requests) inside "
        "an async function stalls the whole event loop"
    )

    def check(self, mod: SourceModule) -> Iterator[Finding]:
        for fn in mod.async_functions():
            for node in body_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = mod.resolve_call(node.func)
                hint = BLOCKING_CALLS.get(name or "")
                if hint is None:
                    continue
                yield mod.finding(
                    self.name,
                    node,
                    f"blocking call {name}() in 'async def {fn.name}' — {hint}",
                )
