"""task-leak: fire-and-forget ``asyncio.create_task``/``ensure_future``.

Two distinct failure modes hide behind a discarded task handle:

1. **Garbage collection** — the event loop holds only a weak reference
   to tasks; with no strong reference the task can be collected
   mid-flight and silently stop (runtime/network.py learned this the
   hard way — see ResponseReceiver._pump_task).
2. **Swallowed exceptions** — an unobserved task's exception surfaces
   only as a destructor log line at GC time, long after the causal
   context is gone.

A handle is "kept" if the call result is assigned, stored, awaited,
passed on, or returned. Only a bare expression statement — the value
thrown away — is flagged. TaskGroup-style receivers (``tg``,
``task_group``) are exempt: the group owns its tasks by construction.
"""

from __future__ import annotations

from typing import Iterator

import ast

from ..core import Finding, Rule, SourceModule

SPAWN_ATTRS = {"create_task", "ensure_future"}
GROUP_RECEIVERS = {"tg", "task_group", "taskgroup", "group", "nursery"}


def _is_spawn(mod: SourceModule, call: ast.Call) -> bool:
    func = call.func
    name = mod.resolve_call(func)
    if name in ("asyncio.create_task", "asyncio.ensure_future"):
        return True
    if isinstance(func, ast.Attribute) and func.attr in SPAWN_ATTRS:
        # loop.create_task / runtime-ish spawners; skip TaskGroups, which
        # keep strong references to their children themselves
        recv = func.value
        if isinstance(recv, ast.Name) and recv.id.lower() in GROUP_RECEIVERS:
            return False
        return True
    return False


class TaskLeakRule(Rule):
    name = "task-leak"
    description = (
        "create_task/ensure_future result discarded: the task can be "
        "garbage-collected mid-flight and its exception is never observed"
    )

    def check(self, mod: SourceModule) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Expr):
                continue
            value = node.value
            if isinstance(value, ast.Await):
                continue  # awaited inline: observed
            if isinstance(value, ast.Call) and _is_spawn(mod, value):
                target = mod.resolve_call(value.func) or ast.unparse(value.func)
                yield mod.finding(
                    self.name,
                    node,
                    f"{target}() result discarded — keep a strong reference "
                    "and observe its exception (add_done_callback or await)",
                )
