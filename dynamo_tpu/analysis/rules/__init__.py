"""dynlint rule registry. Rules self-describe; the CLI and tests pull
the catalog from here so adding a rule is one import line."""

from __future__ import annotations

from typing import Dict, List, Sequence

from ..core import Rule
from ..domains import CrossDomainRaceRule
from .async_blocking import AsyncBlockingRule
from .jit_impure import JitImpureRule
from .lock_across_await import LockAcrossAwaitRule
from .metric_name import MetricNameRule
from .silent_except import SilentExceptRule
from .task_leak import TaskLeakRule
from .wallclock_sim import WallclockInSimRule

_RULE_CLASSES = (
    AsyncBlockingRule,
    TaskLeakRule,
    LockAcrossAwaitRule,
    JitImpureRule,
    SilentExceptRule,
    MetricNameRule,
    WallclockInSimRule,
    CrossDomainRaceRule,
)


def all_rules() -> List[Rule]:
    return [cls() for cls in _RULE_CLASSES]


def get_rules(names: Sequence[str]) -> List[Rule]:
    by_name: Dict[str, Rule] = {r.name: r for r in all_rules()}
    unknown = [n for n in names if n not in by_name]
    if unknown:
        raise KeyError(
            f"unknown rule(s) {unknown}; available: {sorted(by_name)}"
        )
    return [by_name[n] for n in names]
