"""dynrace: interprocedural thread-domain inference + cross-domain races.

The serving plane's load-bearing concurrency discipline is a convention,
not a type: loop-owned state is mutated only on the event loop, while
executor/thread code reads GIL-atomic snapshots or marshals back via
``call_soon_threadsafe``. The reference Dynamo gets this from Rust's
ownership model; Python gets nothing, and review kept finding the same
violation classes after the fact (the off-loop ``/fleet`` reads, the
trace-writer close-under-write race). This pass makes the convention
checkable.

Three stages over the whole parsed module set (a :class:`ProjectRule` —
per-module rules can't see who calls whom):

1. **Call graph.** Every ``def``/``async def``/``lambda`` becomes a
   node. Edges come from direct sync calls resolved through the same
   import-alias machinery the per-module rules use (``core.dotted_name``),
   extended with relative imports, plus ``self.method`` and nested-
   function references.

2. **Thread domains.** Each function is inferred to run in one or more
   *domains*:

   - ``loop``  — ``async def``s, and callables handed to ``call_soon``/
     ``call_later``/``call_at``/``call_soon_threadsafe``/
     ``add_done_callback`` (asyncio futures invoke these on the loop);
   - ``executor`` — callables handed to ``run_in_executor`` /
     ``asyncio.to_thread``;
   - ``thread``  — ``threading.Thread(target=...)`` targets (the FIFO
     writer threads).

   Seeds propagate caller→callee to fixpoint: a sync helper called from
   an ``async def`` runs on the loop; called *also* from a thread
   target, it runs in both (which is exactly what makes its writes
   dangerous). Dynamic dispatch the graph can't resolve (registry
   callbacks, stored function pointers) is covered by an annotation
   vocabulary — ``# dynrace: domain(loop|executor|thread|any)`` on the
   ``def`` line or the line above pins the function (``any`` excludes
   it). Unannotated functions the graph never reaches stay
   domain-unknown and produce no findings: the pass is deliberately
   no-false-positive-biased.

3. **Per-class attribute audit.** For every ``self.<attr>`` of every
   class, each touch is recorded with its function's domains, the
   ``with self.<lock>:`` locks held around it, and its *kind*:

   - ``rebind``  — ``self.x = fresh`` (an atomic pointer publish);
   - ``rmw``     — ``self.x += 1`` (read-modify-write);
   - ``inplace`` — mutation of the object behind the attribute:
     subscript stores/deletes and mutator method calls (``append``,
     ``update``, ``pop``, ``move_to_end``, ``write``, ``close``, …);
   - reads, split into GIL-atomic forms (subscript/``get``/membership/
     truthiness/reference grabs, and materialized snapshots —
     ``list(self.x)``, ``len(self.x)``, ``sorted``, …) versus unsafe
     forms (direct iteration of the live container, unknown method
     calls on it).

   A finding fires when (a) the attribute is **written from two
   different domains** with no common lock (write/write race — lost
   updates, close-under-write), or (b) it is **mutated in place in one
   domain and unsafely read in another** with no common lock (the
   iterate-while-the-loop-mutates ``RuntimeError`` class).

   Everything the repo sanctions comes out clean by construction:
   init-only assignment (``__init__`` runs before concurrency),
   snapshot publishes (rebind + any read), ``list()`` snapshot reads,
   a ``threading.Lock`` held on both sides, ``queue.Queue``/
   ``asyncio.Queue``/``deque``/``Event`` attributes (their methods ARE
   the handoff idiom), and ``call_soon_threadsafe`` marshals (the
   callback is inferred ``loop``, so the touch lands in the right
   domain).

Entry points: :class:`CrossDomainRaceRule` (the ``cross-domain-race``
rule in the catalog) and :func:`infer_domains` (fixture introspection).
See docs/static_analysis.md "Thread domains".
"""

from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, Iterator, List, NamedTuple, Optional, Sequence, Set, Tuple

from .core import Finding, ProjectRule, SourceModule, dotted_name

__all__ = [
    "CrossDomainRaceRule",
    "DomainAnalysis",
    "infer_domains",
]

LOOP = "loop"
EXECUTOR = "executor"
THREAD = "thread"
ANY = "any"

_DOMAIN_RE = re.compile(r"#\s*dynrace:\s*domain\((loop|executor|thread|any)\)")

# attribute method names that mutate the receiver in place (builtin
# containers, files, OrderedDict). ``get`` is deliberately absent: on the
# non-queue attributes this audit covers it is the dict read.
_MUTATOR_METHODS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "remove",
    "pop", "popleft", "popitem", "clear", "update", "setdefault", "add",
    "discard", "sort", "reverse", "move_to_end", "write", "writelines",
    "truncate", "close", "flush",
})

# attr.<accessor>() views that still expose the LIVE container — reading
# through them inherits the consumer's safety (list(x.values()) is a
# snapshot; for ... in x.values() is not)
_VIEW_METHODS = frozenset({"values", "items", "keys", "copy"})

# builtins that consume an iterable whole without running bytecode
# mid-iteration: the C call holds the GIL, so a concurrent loop-side
# mutation cannot interleave — the sanctioned snapshot-read spelling
_MATERIALIZERS = frozenset({
    "list", "tuple", "set", "frozenset", "dict", "sorted", "len", "sum",
    "min", "max", "any", "all", "bool", "str", "repr",
})

# self.<attr> = <ctor>() types that ARE a marshalling idiom: their whole
# contract is cross-thread use, so touches are exempt from the audit.
# collections.deque is deliberately NOT here: append/pop/[-1]/len are
# the sanctioned GIL-atomic ops (classified individually), but iterating
# a live deque while another domain appends raises RuntimeError — the
# audit must see that.
_EXEMPT_TYPES = frozenset({
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue", "asyncio.Queue",
    "asyncio.LifoQueue", "asyncio.PriorityQueue", "threading.Event",
    "asyncio.Event", "threading.Lock", "threading.RLock",
    "threading.Condition", "threading.Semaphore",
    "threading.BoundedSemaphore", "asyncio.Lock", "asyncio.Condition",
    "asyncio.Semaphore", "concurrent.futures.ThreadPoolExecutor",
})

# the subset that counts as a lock for `with self.<attr>:` coverage
_LOCK_TYPES = frozenset({
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
})

_INIT_METHODS = frozenset({"__init__", "__post_init__", "__new__"})

_WRITE_KINDS = frozenset({"rebind", "rmw", "inplace"})
_UNSAFE_READ_KINDS = frozenset({"read_iter", "read_call"})


def _fmt(domains: FrozenSet[str]) -> str:
    return "+".join(sorted(domains))


class _Fn:
    """One function/lambda node with its inference state."""

    __slots__ = ("node", "mod", "qual", "name", "cls", "parent", "is_async",
                 "domains", "pinned", "seeded")

    def __init__(self, node, mod: SourceModule, qual: str, name: str,
                 cls: Optional[str], parent: Optional["_Fn"], is_async: bool):
        self.node = node
        self.mod = mod
        self.qual = qual          # "ClassName.method" / "fn.<locals>.inner"
        self.name = name          # display name ("method", "<lambda>")
        self.cls = cls            # innermost enclosing class, if a method
        self.parent = parent      # lexically enclosing function
        self.is_async = is_async
        self.domains: Set[str] = {LOOP} if is_async else set()
        self.pinned = is_async    # async defs always run on a loop
        self.seeded = is_async    # got a domain from structure, not a caller


class _Touch(NamedTuple):
    kind: str                  # rebind|rmw|inplace|read_atomic|read_iter|read_call
    domains: FrozenSet[str]
    locks: FrozenSet[str]
    line: int
    fn: str                    # display qual for messages
    in_init: bool


def _module_dotted(rel: str) -> str:
    """``dynamo_tpu/telemetry/hub.py`` → ``dynamo_tpu.telemetry.hub``."""
    p = rel[:-3] if rel.endswith(".py") else rel
    parts = p.split("/")
    if parts and parts[-1] == "__init__":
        parts.pop()
    return ".".join(parts)


def _rich_aliases(mod: SourceModule) -> Dict[str, str]:
    """The module's alias map PLUS relative imports resolved against its
    package path (core's map skips ``from .x import y`` — fine for
    stdlib-name rules, fatal for an intra-package call graph)."""
    amap = dict(mod.aliases)
    dotted = _module_dotted(mod.rel)
    pkg_parts = dotted.split(".")
    if not mod.rel.endswith("/__init__.py") and "/" in mod.rel:
        pkg_parts = pkg_parts[:-1]
    elif not mod.rel.endswith("/__init__.py"):
        pkg_parts = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ImportFrom) and node.level:
            base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
            if not base:
                continue
            prefix = ".".join(base + ([node.module] if node.module else []))
            for a in node.names:
                if a.name == "*":
                    continue
                amap.setdefault(a.asname or a.name, f"{prefix}.{a.name}")
    return amap


class DomainAnalysis:
    """The whole-package pass: build once, query findings/domains."""

    def __init__(self, mods: Sequence[SourceModule]):
        self.mods = list(mods)
        self.fns: Dict[int, _Fn] = {}            # id(node) → _Fn
        self.module_fns: Dict[Tuple[str, str], _Fn] = {}
        self.method_fns: Dict[Tuple[str, str, str], _Fn] = {}
        self.dotted_fns: Dict[str, _Fn] = {}
        self.aliases: Dict[str, Dict[str, str]] = {}
        # (mod.rel, cls) → attr → ctor dotted names seen for it
        self.attr_types: Dict[Tuple[str, str], Dict[str, Set[str]]] = {}
        self.edges: List[Tuple[_Fn, _Fn]] = []
        self.touches: Dict[Tuple[str, str, str], List[_Touch]] = {}
        for mod in self.mods:
            self.aliases[mod.rel] = _rich_aliases(mod)
        for mod in self.mods:
            self._collect_functions(mod)
        for mod in self.mods:
            self._collect_usage(mod)
        self._fixpoint()
        self._collect_touches()

    # ------------------------------------------------------------------
    # pass 1: function inventory (+ annotations, + attribute ctor types)
    # ------------------------------------------------------------------

    def _annotation(self, mod: SourceModule, node) -> Optional[str]:
        line = getattr(node, "lineno", 0)
        for idx in (line - 1, line - 2):
            if 0 <= idx < len(mod.lines):
                m = _DOMAIN_RE.search(mod.lines[idx])
                if m:
                    return m.group(1)
        return None

    def _collect_functions(self, mod: SourceModule) -> None:
        dotted_mod = _module_dotted(mod.rel)

        def visit(node, cls: Optional[str], cls_qual: Optional[str],
                  fn: Optional[_Fn], qual: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    cq = f"{cls_qual}.{child.name}" if cls_qual else child.name
                    visit(child, child.name, cq, fn, cq)
                elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                        ast.Lambda)):
                    name = getattr(child, "name", "<lambda>")
                    q = f"{qual}.{name}" if qual else name
                    info = _Fn(child, mod, q, name,
                               cls if fn is None or fn.cls == cls else fn.cls,
                               fn, isinstance(child, ast.AsyncFunctionDef))
                    # nested functions keep the enclosing method's class
                    # (they close over the same ``self``)
                    if fn is not None:
                        info.cls = fn.cls
                    ann = self._annotation(mod, child)
                    if ann is not None:
                        info.pinned = True
                        info.seeded = True
                        info.domains = set() if ann == ANY else {ann}
                    self.fns[id(child)] = info
                    if fn is None and cls is None:
                        self.module_fns[(mod.rel, name)] = info
                        self.dotted_fns[f"{dotted_mod}.{name}"] = info
                    elif fn is None and cls is not None:
                        self.method_fns[(mod.rel, cls_qual, name)] = info
                    visit(child, cls, cls_qual, info, q)
                else:
                    visit(child, cls, cls_qual, fn, qual)

        visit(mod.tree, None, None, None, "")

        # attribute ctor types: self.X = <call>() anywhere in a class
        amap = self.aliases[mod.rel]

        def scan_types(node, cls_qual: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                cq = cls_qual
                if isinstance(child, ast.ClassDef):
                    cq = f"{cls_qual}.{child.name}" if cls_qual else child.name
                if cls_qual is not None and isinstance(child, ast.Assign) \
                        and isinstance(child.value, ast.Call):
                    ctor = dotted_name(child.value.func, amap)
                    if ctor:
                        for tgt in child.targets:
                            if isinstance(tgt, ast.Attribute) and \
                                    isinstance(tgt.value, ast.Name) and \
                                    tgt.value.id == "self":
                                self.attr_types.setdefault(
                                    (mod.rel, cls_qual), {}
                                ).setdefault(tgt.attr, set()).add(ctor)
                scan_types(child, cq)

        scan_types(mod.tree, None)

    # ------------------------------------------------------------------
    # pass 2: seeds + call edges
    # ------------------------------------------------------------------

    def _own_nodes(self, root) -> Iterator[ast.AST]:
        """The function's body without nested function bodies (those are
        their own nodes in the graph). The defs/lambdas themselves are
        yielded so dispatch sites can seed them."""
        body = root.body if not isinstance(root, ast.Lambda) else [root.body]
        stack = list(body)
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    def _resolve_callable(self, expr, mod: SourceModule,
                          fn: Optional[_Fn]) -> Optional[_Fn]:
        """A callable-valued expression → its _Fn, through locals,
        methods, module functions, and import aliases."""
        if expr is None:
            return None
        if isinstance(expr, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return self.fns.get(id(expr))
        # functools.partial(f, ...) → f
        if isinstance(expr, ast.Call):
            target = dotted_name(expr.func, self.aliases[mod.rel])
            if target == "functools.partial" and expr.args:
                return self._resolve_callable(expr.args[0], mod, fn)
            return None
        if isinstance(expr, ast.Name):
            cur = fn
            while cur is not None:
                for cand_id, cand in self.fns.items():
                    if cand.parent is cur and cand.name == expr.id:
                        return cand
                cur = cur.parent
            hit = self.module_fns.get((mod.rel, expr.id))
            if hit is not None:
                return hit
            dotted = self.aliases[mod.rel].get(expr.id)
            return self.dotted_fns.get(dotted) if dotted else None
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                    and fn is not None and fn.cls is not None:
                return self.method_fns.get((mod.rel, fn.cls, expr.attr))
            dotted = dotted_name(expr, self.aliases[mod.rel])
            return self.dotted_fns.get(dotted) if dotted else None
        return None

    def _seed(self, target, mod: SourceModule, fn: Optional[_Fn],
              domain: str) -> None:
        info = self._resolve_callable(target, mod, fn)
        if info is None or info.pinned:
            return
        info.domains.add(domain)
        info.seeded = True

    def _collect_usage(self, mod: SourceModule) -> None:
        roots: List[Optional[_Fn]] = [None]
        roots.extend(f for f in self.fns.values() if f.mod is mod)
        for fn in roots:
            nodes = (self._own_nodes(fn.node) if fn is not None
                     else self._module_level_nodes(mod))
            for node in nodes:
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                # dispatch seeds --------------------------------------
                if isinstance(func, ast.Attribute):
                    attr = func.attr
                    if attr == "run_in_executor" and len(node.args) >= 2:
                        self._seed(node.args[1], mod, fn, EXECUTOR)
                    elif attr in ("call_soon", "call_soon_threadsafe",
                                  "add_done_callback") and node.args:
                        self._seed(node.args[0], mod, fn, LOOP)
                    elif attr in ("call_later", "call_at") and \
                            len(node.args) >= 2:
                        self._seed(node.args[1], mod, fn, LOOP)
                dotted = dotted_name(func, self.aliases[mod.rel])
                if dotted == "threading.Thread":
                    target = None
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target = kw.value
                    if target is None and len(node.args) >= 2:
                        target = node.args[1]
                    self._seed(target, mod, fn, THREAD)
                elif dotted == "asyncio.to_thread" and node.args:
                    self._seed(node.args[0], mod, fn, EXECUTOR)
                # call edges ------------------------------------------
                if fn is not None:
                    callee = self._resolve_callable(func, mod, fn)
                    if callee is not None and not callee.is_async:
                        self.edges.append((fn, callee))
        # nested functions with no structural seed run where their
        # enclosing function runs (defined and called inline)
        for info in self.fns.values():
            if info.mod is mod and info.parent is not None \
                    and not info.seeded:
                self.edges.append((info.parent, info))

    def _module_level_nodes(self, mod: SourceModule) -> Iterator[ast.AST]:
        stack = list(ast.iter_child_nodes(mod.tree))
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    # ------------------------------------------------------------------
    # pass 3: fixpoint
    # ------------------------------------------------------------------

    def _fixpoint(self) -> None:
        changed = True
        while changed:
            changed = False
            for caller, callee in self.edges:
                if callee.pinned:
                    continue
                add = caller.domains - callee.domains
                if add:
                    callee.domains |= add
                    changed = True

    # ------------------------------------------------------------------
    # pass 4: attribute touches
    # ------------------------------------------------------------------

    def _lock_attrs(self, mod: SourceModule, cls: str) -> Set[str]:
        types = self.attr_types.get((mod.rel, cls), {})
        return {a for a, ctors in types.items() if ctors & _LOCK_TYPES}

    def _exempt_attrs(self, mod: SourceModule, cls: str) -> Set[str]:
        types = self.attr_types.get((mod.rel, cls), {})
        return {a for a, ctors in types.items() if ctors & _EXEMPT_TYPES}

    def _collect_touches(self) -> None:
        for info in self.fns.values():
            if info.cls is None:
                continue
            locks = self._lock_attrs(info.mod, info.cls)
            self._walk_touches(info, locks)

    def _walk_touches(self, info: _Fn, lock_attrs: Set[str]) -> None:
        mod, cls = info.mod, info.cls
        in_init = info.name in _INIT_METHODS and info.parent is None
        domains = frozenset(info.domains)
        key_base = (mod.rel, cls)

        # parent map over the function's own subtree
        parents: Dict[int, ast.AST] = {}
        body = info.node.body if not isinstance(info.node, ast.Lambda) \
            else [info.node.body]
        stack: List[ast.AST] = list(body)
        own: List[ast.AST] = []
        while stack:
            node = stack.pop()
            own.append(node)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node
                stack.append(child)

        def held_locks(node) -> FrozenSet[str]:
            held: Set[str] = set()
            cur = parents.get(id(node))
            while cur is not None:
                if isinstance(cur, (ast.With, ast.AsyncWith)):
                    for item in cur.items:
                        ce = item.context_expr
                        if isinstance(ce, ast.Attribute) and \
                                isinstance(ce.value, ast.Name) and \
                                ce.value.id == "self" and \
                                ce.attr in lock_attrs:
                            held.add(ce.attr)
                cur = parents.get(id(cur))
            return frozenset(held)

        def classify_load(node) -> str:
            """A Load of self.<attr> → read kind, by its consumer."""
            p = parents.get(id(node))
            consumer = node
            # look through live views: self.x.values() etc.
            if isinstance(p, ast.Attribute) and p.value is node:
                gp = parents.get(id(p))
                if isinstance(gp, ast.Call) and gp.func is p:
                    if p.attr in _MUTATOR_METHODS:
                        return "inplace"
                    if p.attr in _VIEW_METHODS:
                        consumer, p = gp, parents.get(id(gp))
                    elif p.attr == "get":
                        return "read_atomic"
                    else:
                        # unknown method on the live object: the audit
                        # can't see inside it — assume it iterates
                        return "read_call"
                else:
                    # plain sub-attribute read (self.x.y): atomic
                    return "read_atomic"
            if isinstance(p, ast.Subscript) and p.value is consumer:
                if isinstance(p.ctx, (ast.Store, ast.Del)):
                    return "inplace"
                return "read_atomic"
            if isinstance(p, ast.Call):
                if consumer in p.args or any(
                        kw.value is consumer for kw in p.keywords):
                    fname = p.func.id if isinstance(p.func, ast.Name) else None
                    if fname in _MATERIALIZERS:
                        return "read_atomic"
                    if fname in ("iter", "enumerate", "map", "filter",
                                 "zip", "reversed"):
                        return "read_iter"
                    # passed by reference — the grab itself is atomic
                    return "read_atomic"
                if p.func is consumer:
                    return "read_atomic"  # calling a stored callable
            if isinstance(p, (ast.For, ast.AsyncFor)) and p.iter is consumer:
                return "read_iter"
            if isinstance(p, ast.comprehension) and p.iter is consumer:
                return "read_iter"
            return "read_atomic"

        for node in own:
            if not (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                continue
            if isinstance(node.ctx, ast.Store):
                p = parents.get(id(node))
                kind = "rmw" if isinstance(p, ast.AugAssign) else "rebind"
            elif isinstance(node.ctx, ast.Del):
                kind = "inplace"
            else:
                kind = classify_load(node)
            self.touches.setdefault(
                key_base + (node.attr,), []
            ).append(_Touch(kind, domains, held_locks(node),
                            getattr(node, "lineno", 0), info.qual, in_init))

    # ------------------------------------------------------------------
    # findings
    # ------------------------------------------------------------------

    def findings(self) -> List[Finding]:
        out: List[Finding] = []
        rel_to_mod = {m.rel: m for m in self.mods}
        for (rel, cls, attr), touches in sorted(self.touches.items()):
            if attr in self._exempt_attrs(rel_to_mod[rel], cls):
                continue
            active = [t for t in touches if not t.in_init and t.domains]
            writes = [t for t in active if t.kind in _WRITE_KINDS]
            if not writes:
                continue
            emitted: Set[Tuple[int, str]] = set()

            def emit(line: int, msg: str) -> None:
                key = (line, msg)
                if key not in emitted:
                    emitted.add(key)
                    out.append(Finding("cross-domain-race", rel, line, msg))

            def crosses(a: FrozenSet[str], b: FrozenSet[str]) -> bool:
                return any(d1 != d2 for d1 in a for d2 in b)

            # (a) write/write across domains without a common lock
            for i, w1 in enumerate(writes):
                peers = [
                    w2 for j, w2 in enumerate(writes)
                    if i != j and crosses(w1.domains, w2.domains)
                    and not (w1.locks & w2.locks)
                ]
                if peers:
                    peer_doms = frozenset().union(
                        *(p.domains for p in peers)) - w1.domains or \
                        frozenset().union(*(p.domains for p in peers))
                    peer_fns = sorted({p.fn for p in peers if p.fn != w1.fn}) \
                        or [w1.fn]
                    emit(
                        w1.line,
                        f"self.{attr} of {cls} written on the "
                        f"{_fmt(w1.domains)} domain ({w1.fn}) and "
                        f"concurrently on {_fmt(peer_doms)} "
                        f"({', '.join(peer_fns)}) — hold one lock on every "
                        "side or marshal all writes onto a single domain",
                    )
                elif len(w1.domains) >= 2 and not w1.locks:
                    # one function, reachable from two domains: it races
                    # with concurrent invocations of itself
                    emit(
                        w1.line,
                        f"self.{attr} of {cls} written by {w1.fn}, which "
                        f"is reachable from multiple domains "
                        f"({_fmt(w1.domains)}) — concurrent invocations "
                        "race; pin it with # dynrace: domain(...) or lock "
                        "the write",
                    )

            # (b) in-place mutation vs unsafe cross-domain read
            inplace = [w for w in writes if w.kind == "inplace"]
            if not inplace:
                continue
            wdoms = frozenset().union(*(w.domains for w in inplace))
            wfns = sorted({w.fn for w in inplace})
            for t in active:
                if t.kind not in _UNSAFE_READ_KINDS:
                    continue
                racing = [w for w in inplace
                          if crosses(w.domains, t.domains)
                          and not (w.locks & t.locks)]
                if not racing:
                    continue
                emit(
                    t.line,
                    f"self.{attr} of {cls} read on the {_fmt(t.domains)} "
                    f"domain ({t.fn}) while mutated in place on "
                    f"{_fmt(wdoms)} ({', '.join(wfns)}) — iterate a "
                    "list()/dict() snapshot, hold the writer's lock, or "
                    "marshal via call_soon_threadsafe",
                )
        out.sort(key=lambda f: (f.file, f.line))
        return out

    def domains_of(self) -> Dict[str, Set[str]]:
        """``"<rel>:<qual>" → domains`` — fixture introspection."""
        return {f"{f.mod.rel}:{f.qual}": set(f.domains)
                for f in self.fns.values()}


def infer_domains(mods: Sequence[SourceModule]) -> Dict[str, Set[str]]:
    return DomainAnalysis(mods).domains_of()


class CrossDomainRaceRule(ProjectRule):
    name = "cross-domain-race"
    description = (
        "self.<attr> state written in one thread domain "
        "(loop/executor/thread) and touched in another without a "
        "recognized marshalling idiom (lock both sides, queue handoff, "
        "snapshot publish/read, call_soon_threadsafe)"
    )

    def check_project(
        self, mods: Sequence[SourceModule]
    ) -> Iterator[Finding]:
        return iter(DomainAnalysis(mods).findings())
