"""dynlint: project-specific static analysis for dynamo-tpu.

The reference Dynamo leans on rustc + clippy for its concurrency and
purity guarantees; this package is the Python port's equivalent. It is
a small AST-walking lint framework (no third-party deps, no imports of
the code under analysis) with rules tuned to the invariants this
codebase actually depends on:

- the asyncio runtime/data plane must never block the event loop or
  drop task exceptions (``async-blocking``, ``task-leak``,
  ``lock-across-await``, ``silent-except``);
- jitted/traced JAX code must stay pure and free of hidden host syncs
  (``jit-impure`` — the static twin of the runtime ``host_sync``
  phase histogram);
- registered metric names must follow the house convention
  (``metric-name`` — shared with scripts/check_metric_names.py);
- the fleet simulator must never read the wall clock
  (``wallclock-in-sim`` — byte-identical reports per (scenario, seed));
- loop-owned serving-plane state must not cross thread domains without
  a marshalling idiom (``cross-domain-race`` — interprocedural
  thread-domain inference over the whole package; see ``domains.py``
  and the ``# dynrace: domain(...)`` annotation vocabulary).

Entry points: ``scripts/dynlint.py`` (CLI, baseline-aware) and
``tests/test_dynlint.py`` (tier-1 enforcement). Suppress a finding
in place with ``# dynlint: allow(<rule>) - justification`` on the
flagged line or the line above; record pre-existing debt in
``scripts/dynlint_baseline.json`` (regenerate with
``--update-baseline``). See docs/static_analysis.md.
"""

from .baseline import diff_against_baseline, load_baseline, write_baseline
from .core import (
    Finding,
    ProjectRule,
    Rule,
    SourceModule,
    lint_paths,
    lint_source,
)
from .domains import DomainAnalysis, infer_domains
from .rules import all_rules, get_rules

__all__ = [
    "DomainAnalysis",
    "Finding",
    "ProjectRule",
    "Rule",
    "SourceModule",
    "infer_domains",
    "all_rules",
    "get_rules",
    "lint_paths",
    "lint_source",
    "load_baseline",
    "write_baseline",
    "diff_against_baseline",
]
