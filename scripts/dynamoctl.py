#!/usr/bin/env python
"""dynamoctl: manage the multi-model fleet through a frontend's admin API.

The ``llmctl`` analogue for the registry plane (docs/multi_model.md):
where ``cli/llmctl.py`` writes discovery records directly (and so needs
a dynstore address), dynamoctl speaks HTTP to any running frontend —
``POST/DELETE /admin/models`` + the read surfaces — so an operator can
drive the fleet from anywhere the frontend is reachable.

    dynamoctl --frontend http://host:8080 models list
    dynamoctl models add m8b dyn://public.backend.generate \
        --family llama --context-length 8192 --alias m8b-fast \
        --tenants acme,globex
    dynamoctl models remove m8b
    dynamoctl models catalog --tenant acme      # the tenant's /v1/models
    dynamoctl pools                             # pool sizes + cold state

Exit codes: 0 ok, 1 server-side refusal (4xx/5xx), 2 usage/unreachable.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.request
from typing import Optional, Tuple


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dynamoctl")
    p.add_argument("--frontend", default="http://127.0.0.1:8080",
                   help="frontend base URL (the HTTP service with the "
                        "admin API)")
    sub = p.add_subparsers(dest="plane", required=True)

    models = sub.add_parser("models", help="manage registered model cards")
    msub = models.add_subparsers(dest="action", required=True)

    add = msub.add_parser("add", help="register a model card dynamically")
    add.add_argument("name")
    add.add_argument("endpoint", help="dyn://ns.comp.ep of the pool")
    add.add_argument("--model-type", default="both",
                     choices=["chat", "completions", "both"])
    add.add_argument("--family", default=None)
    add.add_argument("--context-length", type=int, default=None)
    add.add_argument("--alias", action="append", default=None,
                     help="served alias (repeatable)")
    add.add_argument("--tenants", default=None,
                     help="comma-separated tenant allow list "
                          "(unset = public)")
    add.add_argument("--owned-by", default="dynamo")
    add.add_argument("--model-path", default=None,
                     help="checkpoint dir for cold-start respawns")

    rm = msub.add_parser("remove", help="unregister a model")
    rm.add_argument("name")

    msub.add_parser("list", help="registered cards (admin view)")
    cat = msub.add_parser("catalog",
                          help="the OpenAI /v1/models view, optionally "
                               "as one tenant")
    cat.add_argument("--tenant", default=None)

    sub.add_parser("pools", help="per-model pool state "
                                 "(workers, idle age, cold starts)")
    return p


def _call(method: str, url: str, body: Optional[dict] = None,
          headers: Optional[dict] = None) -> Tuple[int, dict]:
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("Content-Type", "application/json")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read().decode() or "{}")
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read().decode() or "{}")
        except json.JSONDecodeError:
            payload = {"error": str(e)}
        return e.code, payload
    except (urllib.error.URLError, OSError) as e:
        print(f"frontend unreachable at {url}: {e}", file=sys.stderr)
        raise SystemExit(2)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    base = args.frontend.rstrip("/")

    if args.plane == "pools":
        status, body = _call("GET", f"{base}/admin/pools")
        if status != 200:
            print(body.get("error", body), file=sys.stderr)
            return 1
        pools = body.get("pools", [])
        if not pools:
            print("(no pools)")
        for row in pools:
            cold = " COLD-STARTING" if row.get("cold_starting") else ""
            print(f"{row['model']:30s} workers={row['workers']:<3d} "
                  f"idle={row['idle_s']:>8.1f}s "
                  f"requests={row['requests_total']}{cold}")
        return 0

    if args.action == "add":
        card = {
            "name": args.name,
            "endpoint": args.endpoint,
            "model_type": args.model_type,
            "family": args.family,
            "context_length": args.context_length,
            "aliases": args.alias or [],
            "owned_by": args.owned_by,
            "model_path": args.model_path,
        }
        if args.tenants is not None:
            card["tenants"] = [t.strip() for t in args.tenants.split(",")
                               if t.strip()]
        status, body = _call("POST", f"{base}/admin/models", body=card)
        if status != 200:
            print(body.get("error", body), file=sys.stderr)
            return 1
        print(f"registered {body.get('registered', args.name)} -> "
              f"{args.endpoint}")
        return 0

    if args.action == "remove":
        status, body = _call("DELETE", f"{base}/admin/models/{args.name}")
        if status != 200:
            print(body.get("error", body), file=sys.stderr)
            return 1
        print(f"removed {body.get('removed', args.name)}")
        return 0

    if args.action == "list":
        status, body = _call("GET", f"{base}/admin/models")
        if status != 200:
            print(body.get("error", body), file=sys.stderr)
            return 1
        cards = body.get("models", [])
        if not cards:
            print("(no models registered)")
        for c in cards:
            vis = ("public" if c.get("tenants") is None
                   else ",".join(c["tenants"]) or "admin-only")
            aliases = f" aliases={','.join(c['aliases'])}" \
                if c.get("aliases") else ""
            print(f"{c.get('model_type', '?'):12s} {c['name']:26s} "
                  f"{c.get('endpoint', '?'):40s} "
                  f"family={c.get('family') or '-':10s} "
                  f"tenants={vis}{aliases}")
        return 0

    if args.action == "catalog":
        headers = {"X-Tenant": args.tenant} if args.tenant else None
        status, body = _call("GET", f"{base}/v1/models", headers=headers)
        if status != 200:
            print(body.get("error", body), file=sys.stderr)
            return 1
        for m in body.get("data", []):
            extras = []
            if m.get("family"):
                extras.append(f"family={m['family']}")
            if m.get("max_model_len"):
                extras.append(f"ctx={m['max_model_len']}")
            if m.get("aliases"):
                extras.append(f"aliases={','.join(m['aliases'])}")
            print(f"{m['id']:30s} owned_by={m.get('owned_by', '?'):12s} "
                  + " ".join(extras))
        return 0

    return 2


if __name__ == "__main__":
    raise SystemExit(main())
