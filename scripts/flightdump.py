#!/usr/bin/env python3
"""Offline pretty-printer for flight artifacts (telemetry/watchdog.py).

A flight artifact is the JSON dump a stall-watchdog trip, SIGUSR2, or
``GET /debug/flight?save=1`` writes to ``DYN_FLIGHT_DIR``: the engine's
flight-ring events, all-thread stacks, per-engine liveness probes,
request tables, and a metrics snapshot. Raw, it takes jq gymnastics to
read; this renders it as a chronological event table plus the
supporting sections.

Usage:
    python scripts/flightdump.py <artifact.json> [--request <id>]
        [--last N] [--no-stacks] [--no-requests] [--metrics]
    python scripts/flightdump.py <artifact.json | traces.jsonl> --trace <id>
    python scripts/flightdump.py --incident <bundle-dir>

``--request <id>`` filters the event table (and request tables) to one
request/trace id — the "what happened to MY request" view. ``--last N``
keeps only the most recent N events. ``--metrics`` additionally prints
the (long) metrics snapshot of each source.

``--trace <id>`` renders the request X-RAY instead: the cluster-
stitched span timeline the live server serves at
``GET /debug/trace/{id}``, reconstructed offline from either a flight
artifact's ``traces`` section or a ``DYN_TRACE_JSONL`` sink (one trace
object per line) — the post-mortem view when the server is gone. Shows
each hop's clock offset/rtt, every span on the trace-origin axis, and
the unattributed gaps. Exits 2 when the id is not in the file.

``--incident <dir>`` renders a capture bundle end to end (telemetry/
incidents.py — written to DYN_INCIDENT_DIR at trip time): the trigger
header (reason, request, trip info), the bundled flight artifact's
event table, metric-history sparklines over the bundle window, and the
stitched trace timeline of every affected request. Exits 2 when the
directory is not a readable bundle.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional


def _fmt_wall(wall: Optional[float]) -> str:
    if not wall:
        return "-" * 12
    return time.strftime("%H:%M:%S", time.localtime(wall)) + (
        ".%03d" % int((wall % 1) * 1000)
    )


def _fmt_data(evt: dict) -> str:
    data = evt.get("data") or {}
    return " ".join(f"{k}={v}" for k, v in data.items())


def render_events(events: List[dict], t_ref: Optional[float]) -> List[str]:
    """Chronological table: wall clock, seconds-before-dump, kind,
    request id, and the event's structured payload."""
    lines = [
        f"{'WALL':<12} {'T-DUMP':>9} {'KIND':<26} {'REQUEST':<34} DATA",
    ]
    for evt in events:
        rel = ""
        if t_ref is not None and evt.get("t") is not None:
            rel = f"{evt['t'] - t_ref:+.3f}s"
        rid = evt.get("request_id") or ""
        if evt.get("trace_id"):
            rid = f"{rid} ({evt['trace_id']})" if rid else evt["trace_id"]
        lines.append(
            f"{_fmt_wall(evt.get('wall')):<12} {rel:>9} "
            f"{evt.get('kind', '?'):<26} {rid:<34} {_fmt_data(evt)}"
        )
    return lines


def render_requests(sources: List[dict],
                    request: Optional[str]) -> List[str]:
    lines: List[str] = []
    for src in sources:
        table = src.get("requests") or []
        if request:
            table = [r for r in table
                     if request in (r.get("request_id"), r.get("trace_id"))]
        if not table:
            continue
        lines.append(f"--- active requests [{src.get('name', '?')}] ---")
        for row in table:
            lines.append("  " + " ".join(
                f"{k}={v}" for k, v in row.items()
            ))
    return lines


def render_probes(sources: List[dict]) -> List[str]:
    lines: List[str] = []
    for src in sources:
        probe = src.get("probe")
        header = f"--- engine [{src.get('name', '?')}] ---"
        if src.get("error"):
            lines += [header, f"  dump error: {src['error']}"]
            continue
        if probe:
            lines.append(header)
            lines.append("  " + " ".join(f"{k}={v}" for k, v in probe.items()))
            if src.get("last_trip"):
                lt = src["last_trip"]
                lines.append(
                    f"  last trip: {lt.get('reason')} after "
                    f"{lt.get('stalled_for_s', 0):.1f}s stalled"
                )
    return lines


def render_stacks(threads: List[dict]) -> List[str]:
    lines: List[str] = []
    for th in threads:
        lines.append(
            f"--- thread {th.get('name', '?')} (id {th.get('thread_id')}) ---"
        )
        lines.extend("  " + ln for ln in th.get("stack", []))
    return lines


def _iter_traces(path: str):
    """Traces from either input shape: a flight artifact (its "traces"
    section) or a DYN_TRACE_JSONL sink (one trace object per line)."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if isinstance(doc, dict) and "spans" in doc:
        return [doc]  # a single-trace JSONL file parses as one object
    if isinstance(doc, dict):
        return list(doc.get("traces") or [])
    traces = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict) and "spans" in obj:
            traces.append(obj)
    return traces


def render_trace(trace: dict) -> str:
    """One stitched timeline, mirroring GET /debug/trace/{id}: per-hop
    offset table, span rows on the trace-origin axis, gap attribution."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from dynamo_tpu.telemetry.stitch import stitched_timeline, timeline_gaps

    stitched = stitched_timeline(trace)
    out = [
        f"trace {trace.get('request_id')}: model={trace.get('model')} "
        f"status={trace.get('status')} total={trace.get('total_s', 0):.4f}s",
        "",
        f"{'SOURCE':<18} {'CLOCK OFFSET':>13} {'RTT':>9}",
    ]
    for src in stitched["sources"]:
        out.append(
            f"{src['source']:<18} {src['offset_s']:>+12.6f}s "
            f"{src['rtt_s']:>8.4f}s"
        )
    out += ["", f"{'START':>10} {'DUR':>9} {'SOURCE':<18} SPAN"]
    for row in stitched["timeline"]:
        out.append(
            f"{row['start_s']:>+9.4f}s {row['duration_s']:>8.4f}s "
            f"{row['source']:<18} {row['name']}"
        )
    gaps = timeline_gaps(stitched["timeline"], min_gap_s=0.0005)
    if gaps:
        out += ["", "unattributed gaps (no span of any source):"]
        for g in gaps:
            out.append(
                f"{g['start_s']:>+9.4f}s {g['duration_s']:>8.4f}s "
                f"  between {g['after']} and {g['before']}"
            )
    return "\n".join(out)


SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def sparkline(values: List[float], width: int = 40) -> str:
    """Min-max-normalized unicode sparkline, resampled to ``width``."""
    if not values:
        return ""
    if len(values) > width:
        # bucket-mean resample so a long window still fits one line
        step = len(values) / width
        buckets = []
        for i in range(width):
            lo = int(i * step)
            hi = max(lo + 1, int((i + 1) * step))
            chunk = values[lo:hi]
            buckets.append(sum(chunk) / len(chunk))
        values = buckets
    lo, hi = min(values), max(values)
    span = hi - lo
    if span <= 0:
        return SPARK_BLOCKS[0] * len(values)
    return "".join(
        SPARK_BLOCKS[min(len(SPARK_BLOCKS) - 1,
                         int((v - lo) / span * (len(SPARK_BLOCKS) - 1)))]
        for v in values
    )


def render_history(history: Optional[dict], max_series: int = 24) -> List[str]:
    """Metric-history sparklines: counters as per-sample deltas (the
    rate shape), gauges raw; busiest series first, capped."""
    series = (history or {}).get("series") or []
    if not series:
        return []
    rows = []
    for s in series:
        pts = [p[2] for p in s.get("points") or []]
        if s.get("kind") == "counter":
            pts = [b - a for a, b in zip(pts, pts[1:])]
        if not pts:
            continue
        lo, hi = min(pts), max(pts)
        label = s["name"]
        labels = {k: v for k, v in (s.get("labels") or {}).items()}
        if labels:
            label += "{" + ",".join(f"{k}={v}"
                                    for k, v in sorted(labels.items())) + "}"
        rows.append((hi - lo, label, lo, hi, sparkline(pts)))
    # variance first: flat series are rarely what an incident is about
    rows.sort(key=lambda r: (-r[0], r[1]))
    shown = rows[:max_series]
    lines = [f"--- metric history ({len(series)} series, window "
             f"{history.get('window_s', '?')}s"
             + (f", showing {len(shown)} of {len(rows)}"
                if len(rows) > len(shown) else "") + ") ---"]
    for _, label, lo, hi, spark in shown:
        lines.append(f"  {label:<58.58} [{lo:>10.4g} .. {hi:>10.4g}] {spark}")
    return lines


def render_incident(bundle: dict) -> str:
    """One capture bundle end to end: trigger header, flight event
    table, history sparklines, stitched trace timelines."""
    manifest = bundle.get("manifest") or {}
    out = [
        f"incident bundle: reason={manifest.get('reason')} "
        f"time={_fmt_wall(manifest.get('time'))} "
        f"pid={manifest.get('pid')} "
        f"request={manifest.get('request_id') or '-'}",
    ]
    info = manifest.get("info") or {}
    if info:
        out.append("  trigger: " + " ".join(f"{k}={v}"
                                            for k, v in sorted(info.items())))
    profile = manifest.get("profile")
    if profile:
        out.append("  profile: " + " ".join(f"{k}={v}"
                                            for k, v in sorted(profile.items())))
    flight = bundle.get("flight")
    if flight:
        out.append("")
        out.append(
            f"--- flight artifact ({len(flight.get('events') or [])} "
            f"events, +{flight.get('dropped_events', 0)} dropped) ---"
        )
        out += render_events(flight.get("events") or [],
                             flight.get("monotonic"))
        probes = render_probes(flight.get("sources") or [])
        if probes:
            out.append("")
            out += probes
        table = render_requests(flight.get("sources") or [], None)
        if table:
            out.append("")
            out += table
    hist = render_history(bundle.get("history"))
    if hist:
        out.append("")
        out += hist
    for trace in bundle.get("traces") or []:
        out.append("")
        out.append(f"--- stitched trace {trace.get('request_id')} ---")
        try:
            out.append(render_trace(trace))
        except Exception as e:  # dynlint: allow(silent-except) - error is surfaced in the rendered output; one malformed trace must not make the whole bundle unreadable
            out.append(f"  (trace render failed: {e})")
    return "\n".join(out)


def render(artifact: dict, request: Optional[str] = None,
           last: Optional[int] = None, stacks: bool = True,
           requests: bool = True, metrics: bool = False) -> str:
    out: List[str] = []
    out.append(
        f"flight artifact: reason={artifact.get('reason')} "
        f"pid={artifact.get('pid')} "
        f"time={_fmt_wall(artifact.get('time'))} "
        f"events={len(artifact.get('events') or [])} "
        f"(+{artifact.get('dropped_events', 0)} dropped)"
    )
    events = artifact.get("events") or []
    if request:
        events = [e for e in events
                  if request in (e.get("request_id"), e.get("trace_id"))]
        out.append(f"filtered to request {request}: {len(events)} events")
    if last:
        events = events[-last:]
    out.append("")
    out += render_events(events, artifact.get("monotonic"))
    probes = render_probes(artifact.get("sources") or [])
    if probes:
        out.append("")
        out += probes
    if requests:
        table = render_requests(artifact.get("sources") or [], request)
        if table:
            out.append("")
            out += table
    if stacks:
        out.append("")
        out += render_stacks(artifact.get("threads") or [])
    if metrics:
        for src in artifact.get("sources") or []:
            if src.get("metrics"):
                out.append("")
                out.append(f"--- metrics [{src.get('name', '?')}] ---")
                out.append(src["metrics"].rstrip("\n"))
    return "\n".join(out)


def main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="flightdump", description=__doc__.splitlines()[0]
    )
    ap.add_argument("artifact", nargs="?", default=None,
                    help="flight artifact JSON path (omit with --incident)")
    ap.add_argument("--incident", default=None, metavar="DIR",
                    help="render an incident capture bundle directory "
                         "(manifest + flight + history + traces) instead "
                         "of a single artifact; exit 2 on unreadable "
                         "bundle")
    ap.add_argument("--request", default=None,
                    help="filter events/request tables to one request or "
                         "trace id")
    ap.add_argument("--trace", default=None,
                    help="render the stitched span timeline of one "
                         "request id (from the artifact's traces section "
                         "or a DYN_TRACE_JSONL file) instead of the "
                         "event table; exit 2 on unknown id")
    ap.add_argument("--last", type=int, default=None,
                    help="only the most recent N events")
    ap.add_argument("--no-stacks", action="store_true",
                    help="omit the thread-stack section")
    ap.add_argument("--no-requests", action="store_true",
                    help="omit the active-request tables")
    ap.add_argument("--metrics", action="store_true",
                    help="also print each source's metrics snapshot")
    args = ap.parse_args(argv[1:])
    if args.incident:
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from dynamo_tpu.telemetry.incidents import load_bundle_dir

        bundle = load_bundle_dir(args.incident)
        if bundle is None:
            print(f"flightdump: {args.incident} is not a readable "
                  f"incident bundle (missing/corrupt manifest.json)",
                  file=sys.stderr)
            return 2
        print(render_incident(bundle))
        return 0
    if args.artifact is None:
        ap.error("an artifact path is required (or use --incident <dir>)")
    if args.trace:
        try:
            traces = _iter_traces(args.artifact)
        except OSError as e:
            print(f"flightdump: cannot read {args.artifact}: {e}",
                  file=sys.stderr)
            return 2
        match = [t for t in traces if t.get("request_id") == args.trace]
        if not match:
            print(f"flightdump: no trace {args.trace!r} in "
                  f"{args.artifact} ({len(traces)} trace(s) present)",
                  file=sys.stderr)
            return 2
        print(render_trace(match[-1]))  # newest wins for a reused id
        return 0
    try:
        with open(args.artifact) as f:
            artifact = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"flightdump: cannot read {args.artifact}: {e}",
              file=sys.stderr)
        return 2
    print(render(
        artifact, request=args.request, last=args.last,
        stacks=not args.no_stacks, requests=not args.no_requests,
        metrics=args.metrics,
    ))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
