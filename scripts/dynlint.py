#!/usr/bin/env python
"""dynlint CLI: run the project's static analyzer against the baseline.

Usage:
    python scripts/dynlint.py [paths ...]
        Lint (default: dynamo_tpu/). Exit 1 if any violation is NOT
        covered by the baseline, else 0.
    python scripts/dynlint.py --update-baseline
        Rewrite the baseline to the current findings (accepting debt —
        prefer fixing or an inline '# dynlint: allow(<rule>)').
    python scripts/dynlint.py --format=github
        Emit ::error workflow commands for CI annotations.
    python scripts/dynlint.py --list-rules
        Print the rule catalog.

Options:
    --baseline PATH   baseline file (default scripts/dynlint_baseline.json)
    --no-baseline     report every finding, recorded debt included
    --rules a,b       run only the named rules

Exit codes: 0 clean (modulo baseline), 1 new violations, 2 usage error.
The enforcement twin is tests/test_dynlint.py (marker: dynlint), which
runs the same check in tier-1 with no network, TPU, or heavy imports.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from dynamo_tpu.analysis import (  # noqa: E402
    all_rules,
    diff_against_baseline,
    get_rules,
    lint_paths,
    load_baseline,
    write_baseline,
)

DEFAULT_BASELINE = os.path.join(REPO_ROOT, "scripts", "dynlint_baseline.json")


def main(argv: List[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="dynlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("paths", nargs="*",
                        default=[os.path.join(REPO_ROOT, "dynamo_tpu")])
    parser.add_argument("--baseline", default=DEFAULT_BASELINE)
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline; report all findings")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline to current findings")
    parser.add_argument("--format", choices=("text", "github"),
                        default="text")
    parser.add_argument("--rules", default=None,
                        help="comma-separated subset of rules to run")
    parser.add_argument("--list-rules", action="store_true")
    try:
        args = parser.parse_args(argv[1:])
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    rules = all_rules()
    if args.list_rules:
        width = max(len(r.name) for r in rules)
        for r in rules:
            print(f"{r.name:<{width}}  {r.description}")
        return 0
    if args.rules:
        try:
            rules = get_rules([s.strip() for s in args.rules.split(",") if s.strip()])
        except KeyError as e:
            print(f"dynlint: {e.args[0]}", file=sys.stderr)
            return 2

    try:
        findings = lint_paths(args.paths, rules)
    except FileNotFoundError as e:
        print(f"dynlint: {e}", file=sys.stderr)
        return 2

    if args.update_baseline:
        # the baseline is rewritten WHOLE from this run's findings: a
        # narrowed scope would silently delete every entry outside it
        default_scope = [os.path.join(REPO_ROOT, "dynamo_tpu")]
        narrowed = args.rules or (
            [os.path.abspath(p) for p in args.paths]
            != [os.path.abspath(p) for p in default_scope]
        )
        if narrowed and args.baseline == DEFAULT_BASELINE:
            print("dynlint: refusing --update-baseline with --rules or a "
                  "narrowed path scope — it would drop every out-of-scope "
                  "entry from the shared baseline. Run it bare, or point "
                  "--baseline at a different file.", file=sys.stderr)
            return 2
        entries = write_baseline(args.baseline, findings)
        print(f"baseline written: {len(entries)} unique finding(s) "
              f"({len(findings)} total) -> {args.baseline}")
        return 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    diff = diff_against_baseline(findings, baseline)

    render = (lambda f: f.render_github()) if args.format == "github" \
        else (lambda f: f.render())
    for f in diff.new:
        print(render(f))
    if args.format == "text":
        for key in diff.stale:
            print(f"note: stale baseline entry (fixed? run "
                  f"--update-baseline to prune): {key}")
    if diff.new:
        print(f"{len(diff.new)} new violation(s) "
              f"({len(diff.known)} known in baseline)")
        return 1
    print(f"dynlint clean: 0 new violations "
          f"({len(diff.known)} recorded in baseline, "
          f"{len(diff.stale)} stale entr{'y' if len(diff.stale) == 1 else 'ies'})")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
