#!/usr/bin/env python
"""dynlint CLI: run the project's static analyzer against the baseline.

Usage:
    python scripts/dynlint.py [paths ...]
        Lint (default: dynamo_tpu/). Exit 1 if any violation is NOT
        covered by the baseline, else 0.
    python scripts/dynlint.py --update-baseline
        Rewrite the baseline to the current findings (accepting debt —
        prefer fixing or an inline '# dynlint: allow(<rule>)').
    python scripts/dynlint.py --format=github
        Emit ::error workflow commands for CI annotations.
    python scripts/dynlint.py --list-rules
        Print the rule catalog.
    python scripts/dynlint.py --changed[=<git-ref>]
        Report findings only for files differing from <git-ref>
        (default HEAD) plus untracked files — the pre-commit fast
        path. The whole package is still PARSED (interprocedural
        rules need the full call graph for context); only the
        reporting is scoped, so a verdict about a changed file never
        flips because its callers didn't change.

Options:
    --baseline PATH   baseline file (default scripts/dynlint_baseline.json)
    --no-baseline     report every finding, recorded debt included
    --rules a,b       run only the named rules

Exit codes: 0 clean (modulo baseline), 1 new violations, 2 usage error.
The enforcement twin is tests/test_dynlint.py (marker: dynlint), which
runs the same check in tier-1 with no network, TPU, or heavy imports.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import List, Optional, Set

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from dynamo_tpu.analysis import (  # noqa: E402
    all_rules,
    diff_against_baseline,
    get_rules,
    lint_paths,
    load_baseline,
    write_baseline,
)

DEFAULT_BASELINE = os.path.join(REPO_ROOT, "scripts", "dynlint_baseline.json")


def changed_files(ref: str) -> Set[str]:
    """Report-relative keys of .py files differing from ``ref`` (plus
    untracked ones), for ``--changed`` scoping. Raises CalledProcessError
    on a bad ref — a typo'd ref must not read as "nothing changed"."""
    from dynamo_tpu.analysis.core import report_rel

    diffed = subprocess.run(
        ["git", "-C", REPO_ROOT, "diff", "--name-only", ref, "--"],
        check=True, capture_output=True, text=True,
    ).stdout.splitlines()
    untracked = subprocess.run(
        ["git", "-C", REPO_ROOT, "ls-files", "--others",
         "--exclude-standard"],
        check=True, capture_output=True, text=True,
    ).stdout.splitlines()
    out: Set[str] = set()
    for rel in diffed + untracked:
        if not rel.endswith(".py"):
            continue
        path = os.path.join(REPO_ROOT, rel)
        if os.path.exists(path):  # deleted files have no findings
            out.add(report_rel(path))
    return out


def main(argv: List[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="dynlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("paths", nargs="*",
                        default=[os.path.join(REPO_ROOT, "dynamo_tpu")])
    parser.add_argument("--baseline", default=DEFAULT_BASELINE)
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline; report all findings")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline to current findings")
    parser.add_argument("--format", choices=("text", "github"),
                        default="text")
    parser.add_argument("--rules", default=None,
                        help="comma-separated subset of rules to run")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--changed", nargs="?", const="HEAD", default=None,
                        metavar="GIT_REF",
                        help="report findings only for files differing "
                             "from GIT_REF (default HEAD) or untracked")
    try:
        args = parser.parse_args(argv[1:])
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    rules = all_rules()
    if args.list_rules:
        width = max(len(r.name) for r in rules)
        for r in rules:
            print(f"{r.name:<{width}}  {r.description}")
        return 0
    if args.rules:
        try:
            rules = get_rules([s.strip() for s in args.rules.split(",") if s.strip()])
        except KeyError as e:
            print(f"dynlint: {e.args[0]}", file=sys.stderr)
            return 2

    only: Optional[Set[str]] = None
    if args.changed is not None:
        try:
            only = changed_files(args.changed)
        except (subprocess.CalledProcessError, OSError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            print(f"dynlint: --changed failed: {detail.strip()}",
                  file=sys.stderr)
            return 2
        if not only:
            print(f"dynlint clean: no .py files changed vs {args.changed}")
            return 0

    try:
        findings = lint_paths(args.paths, rules, only_files=only)
    except FileNotFoundError as e:
        print(f"dynlint: {e}", file=sys.stderr)
        return 2

    if args.update_baseline:
        # the baseline is rewritten WHOLE from this run's findings: a
        # narrowed scope would silently delete every entry outside it
        default_scope = [os.path.join(REPO_ROOT, "dynamo_tpu")]
        narrowed = args.rules or args.changed is not None or (
            [os.path.abspath(p) for p in args.paths]
            != [os.path.abspath(p) for p in default_scope]
        )
        if narrowed and args.baseline == DEFAULT_BASELINE:
            print("dynlint: refusing --update-baseline with --rules, "
                  "--changed, or a narrowed path scope — it would drop "
                  "every out-of-scope entry from the shared baseline. Run "
                  "it bare, or point --baseline at a different file.",
                  file=sys.stderr)
            return 2
        entries = write_baseline(args.baseline, findings)
        print(f"baseline written: {len(entries)} unique finding(s) "
              f"({len(findings)} total) -> {args.baseline}")
        return 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    if only is not None:
        # keep only the changed files' debt: an unchanged file's baseline
        # entry must not read as stale just because it wasn't scanned
        baseline = {k: v for k, v in baseline.items()
                    if k.split(":", 1)[0] in only}
    diff = diff_against_baseline(findings, baseline)

    render = (lambda f: f.render_github()) if args.format == "github" \
        else (lambda f: f.render())
    for f in diff.new:
        print(render(f))
    if args.format == "text":
        for key in diff.stale:
            print(f"note: stale baseline entry (fixed? run "
                  f"--update-baseline to prune): {key}")
    if diff.new:
        print(f"{len(diff.new)} new violation(s) "
              f"({len(diff.known)} known in baseline)")
        return 1
    print(f"dynlint clean: 0 new violations "
          f"({len(diff.known)} recorded in baseline, "
          f"{len(diff.stale)} stale entr{'y' if len(diff.stale) == 1 else 'ies'})")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
