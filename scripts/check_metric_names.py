#!/usr/bin/env python
"""Lint registered Prometheus metric names against the house convention.

Thin shim: the implementation moved to dynamo_tpu/analysis/metric_names.py
when it became dynlint's ``metric-name`` rule (scripts/dynlint.py). This
entry point keeps the historical CLI and exit-code contract — and the
import surface tests/test_metric_lint.py depends on — unchanged.

Usage: python scripts/check_metric_names.py [root]   # exit 1 on violation
"""

from __future__ import annotations

import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from dynamo_tpu.analysis.metric_names import (  # noqa: E402,F401
    BASE_UNITS,
    CONSTRUCTOR_KINDS,
    METHOD_KINDS,
    PREFIX,
    UNIT_SUFFIXES,
    RegisteredMetric,
    check_name,
    iter_registered_metrics,
    iter_tree_metrics,
    run_check,
    main,
)

if __name__ == "__main__":
    sys.exit(main(sys.argv))
