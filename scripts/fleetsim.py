#!/usr/bin/env python3
"""Trace-driven fleet simulator CLI: replay traffic against the real
control plane at 1000x and publish capacity curves.

Runs one named scenario (see ``--list``) on a virtual clock, driving
the package's real SlaPolicy / AdmissionController / PoolManager /
RecoveryController / KvScheduler against simulated workers timed by
the measured device-time byte model. Reports a QPS-vs-SLO-attainment
capacity curve, shed rates by tenant and priority, scale / chaos /
recovery timelines, and KV pressure.

Usage:
    python scripts/fleetsim.py --scenario diurnal --speedup 1000
    python scripts/fleetsim.py --scenario chaos --seed 7 --json
    python scripts/fleetsim.py --scenario replay --trace dyn_traces.jsonl
    python scripts/fleetsim.py --scenario replay --bundle incident-123/
    python scripts/fleetsim.py --list

Exit status: 0 on success, 2 when the scenario's SLO-attainment floor
is violated (CI capacity gate), 3 when ``--speedup`` was requested but
not achieved. The report JSON is deterministic for a (scenario, seed)
pair — wall-clock facts (achieved speedup) go to stderr only.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from dynamo_tpu.sim.report import render_table                   # noqa: E402
from dynamo_tpu.sim.scenarios import SCENARIOS, run_scenario     # noqa: E402
from dynamo_tpu.sim.workload import (                            # noqa: E402
    load_incident_bundle, load_trace_jsonl,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="fleet simulator: scenarios vs the real control plane")
    ap.add_argument("--scenario", help="scenario name (see --list)")
    ap.add_argument("--list", action="store_true",
                    help="list scenarios and exit")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--duration", type=float, default=None,
                    metavar="SECONDS",
                    help="override the scenario's virtual duration")
    ap.add_argument("--speedup", type=float, default=None,
                    help="required virtual/wall speedup; exit 3 if the "
                         "run comes in slower")
    ap.add_argument("--slo-floor", type=float, default=None,
                    help="override the scenario's SLO-attainment floor")
    ap.add_argument("--trace", help="DYN_TRACE_JSONL sink to replay")
    ap.add_argument("--bundle",
                    help="incident bundle directory to replay "
                         "(reads traces.json)")
    ap.add_argument("--json", action="store_true",
                    help="print the report JSON instead of the table")
    ap.add_argument("--json-out", metavar="PATH",
                    help="also write the report JSON to PATH")
    ap.add_argument("--metrics-out", metavar="PATH",
                    help="write the run's /metrics exposition "
                         "(dynamo_sim_* + control-plane families)")
    args = ap.parse_args(argv)

    if args.list:
        for name in sorted(SCENARIOS):
            scn = SCENARIOS[name]
            print(f"{name:<14} floor={scn.slo_floor:.2f} "
                  f"duration={scn.duration_s:.0f}s  {scn.description}")
        return 0
    if not args.scenario:
        ap.error("--scenario is required (or --list)")
    if args.scenario not in SCENARIOS:
        ap.error(f"unknown scenario {args.scenario!r}; "
                 f"have {sorted(SCENARIOS)}")

    requests = None
    if args.trace:
        requests = load_trace_jsonl(args.trace)
    elif args.bundle:
        requests = load_incident_bundle(args.bundle)
    if args.scenario == "replay" and requests is None:
        ap.error("--scenario replay needs --trace or --bundle")

    exposition = {}
    if args.metrics_out:
        def grab(fleet):
            exposition["text"] = fleet.registry.render()
    else:
        grab = None

    t0 = time.monotonic()
    report = run_scenario(
        args.scenario,
        seed=args.seed,
        duration_s=args.duration,
        requests=requests,
        slo_floor=args.slo_floor,
        on_fleet=grab,
    )
    wall_s = max(1e-9, time.monotonic() - t0)
    achieved = report["duration_s"] / wall_s

    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(report, f, sort_keys=True, indent=1)
            f.write("\n")
    if args.metrics_out:
        with open(args.metrics_out, "w", encoding="utf-8") as f:
            f.write(exposition.get("text", ""))
    if args.json:
        print(json.dumps(report, sort_keys=True, indent=1))
    else:
        print(render_table(report))
    print(f"[fleetsim] {report['duration_s']:.0f} virtual s in "
          f"{wall_s:.2f} wall s — {achieved:.0f}x realtime",
          file=sys.stderr)

    floor = report["slo_floor"]
    if not report["capacity"]["meets_floor"]:
        print(f"[fleetsim] SLO floor violated: attainment "
              f"{report['totals']['slo_attainment']:.3f} < {floor:.2f}",
              file=sys.stderr)
        return 2
    if args.speedup is not None and achieved < args.speedup:
        print(f"[fleetsim] speedup target missed: {achieved:.0f}x < "
              f"{args.speedup:.0f}x", file=sys.stderr)
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
