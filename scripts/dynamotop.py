#!/usr/bin/env python3
"""Terminal top-view of a dynamo-tpu fleet, live from the telemetry hub.

Points at a process serving the fleet endpoints (``in=hub``, or an
``in=http``/``in=planner`` role started with ``--hub``), polls
``GET /fleet/workers`` + ``GET /fleet/metrics``, and renders a
per-worker table — role, liveness, busy/KV/roofline, SLO attainment,
drain state, watchdog trips — plus a fleet summary line. The terminal
sibling of grafana panels 24-25, for when the incident is NOW and the
browser is far away.

Usage:
    python scripts/dynamotop.py [--hub http://host:port]
        [--interval 2] [--once] [--no-clear] [--json]

``--once`` prints a single frame and exits (scripts/CI); the default
loops until interrupted, redrawing in place. ``--json`` implies
``--once`` and emits a machine-readable fleet snapshot (per-worker
rows + the summary rollup) instead of the table — for runbooks and
cron probes that today scrape the human frame.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request
from typing import List, Optional

CLEAR = "\x1b[2J\x1b[H"


def fetch_json(url: str, timeout: float = 3.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _pct(v: Optional[float]) -> str:
    if v is None:
        return "    -"
    return f"{100 * v:4.0f}%"


def _num(v: Optional[float]) -> str:
    if v is None:
        return "   -"
    if float(v).is_integer():
        return f"{int(v):4d}"
    return f"{v:4.1f}"


def _state(w: dict) -> str:
    if not w.get("up"):
        return "DOWN"
    if w.get("draining"):
        return "DRAIN"
    return "up"


def render_workers(workers: List[dict]) -> List[str]:
    lines = [
        f"{'WORKER':<26} {'ROLE':<14} {'MODEL':<14} {'STATE':<6} "
        f"{'BUSY':>5} "
        f"{'KV':>5} {'WAIT':>4} {'ROOF':>5} {'HIT':>5} {'PULL':>5} "
        f"{'SLO':>5} {'TRIP':>4} {'REQ/S':>6} {'AGE':>5}"
    ]
    for w in workers:
        age = w.get("scrape_age_s")
        # fabric-aware prefix columns: HIT is the local two-tier hit
        # ratio; PULL is committed prefix pulls per second (peer + cold
        # sources — cold per-block hit rates stay in the hub JSON, they
        # are a different unit)
        pulls = w.get("prefix_pulls_per_s")
        pull_s = f"{pulls:.1f}" if pulls is not None else "-"
        lines.append(
            f"{str(w.get('name', '?')):<26.26} "
            f"{str(w.get('role', '?')):<14.14} "
            f"{str(w.get('model') or '-'):<14.14} "
            f"{_state(w):<6} "
            f"{_pct(w.get('busy_ratio')):>5} "
            f"{_pct(w.get('kv_usage_ratio')):>5} "
            f"{_num(w.get('waiting')):>4} "
            f"{_pct(w.get('roofline_fraction')):>5} "
            f"{_pct(w.get('prefix_hit_ratio')):>5} "
            f"{pull_s:>5} "
            f"{_pct(w.get('slo_attainment')):>5} "
            f"{_num(w.get('watchdog_trips')):>4} "
            f"{w.get('requests_per_s') if w.get('requests_per_s') is not None else '     -':>6} "
            f"{f'{age:.1f}s' if age is not None else '    -':>5}"
        )
    return lines


def render_summary(workers: List[dict], metrics: Optional[dict]) -> List[str]:
    up = [w for w in workers if w.get("up")]
    draining = sum(1 for w in workers if w.get("draining"))
    busy = [w["busy_ratio"] for w in up if w.get("busy_ratio") is not None]
    kv = [w["kv_usage_ratio"] for w in up
          if w.get("kv_usage_ratio") is not None]
    parts = [
        f"workers {len(up)}/{len(workers)} up",
        f"{draining} draining",
    ]
    if busy:
        parts.append(f"busy avg {100 * sum(busy) / len(busy):.0f}%")
    if kv:
        parts.append(f"kv avg {100 * sum(kv) / len(kv):.0f}%")
    fams = (metrics or {}).get("families") or {}
    inc = fams.get("dynamo_incidents_total")
    if inc:
        total = sum(e["sum"] for e in inc["roles"].values())
        parts.append(f"incidents {total:.0f}")
    trips = fams.get("dynamo_watchdog_trips_total")
    if trips:
        total = sum(e["sum"] for e in trips["roles"].values())
        parts.append(f"trips {total:.0f}")
    return [" | ".join(parts)]


def snapshot(fleet_workers: dict, fleet_metrics: Optional[dict] = None,
             hub_url: str = "") -> dict:
    """One-shot machine-readable fleet snapshot (the ``--json`` body):
    the raw per-worker rows as served by ``/fleet/workers`` plus the
    same rollup the human summary line renders, as numbers."""
    workers = fleet_workers.get("workers") or []
    up = [w for w in workers if w.get("up")]
    busy = [w["busy_ratio"] for w in up if w.get("busy_ratio") is not None]
    kv = [w["kv_usage_ratio"] for w in up
          if w.get("kv_usage_ratio") is not None]
    fams = (fleet_metrics or {}).get("families") or {}

    def _family_sum(name):
        fam = fams.get(name)
        if not fam:
            return None
        return sum(e["sum"] for e in fam["roles"].values())

    return {
        "hub": hub_url,
        "summary": {
            "workers_total": len(workers),
            "workers_up": len(up),
            "draining": sum(1 for w in workers if w.get("draining")),
            "busy_avg": sum(busy) / len(busy) if busy else None,
            "kv_usage_avg": sum(kv) / len(kv) if kv else None,
            "incidents_total": _family_sum("dynamo_incidents_total"),
            "watchdog_trips_total":
                _family_sum("dynamo_watchdog_trips_total"),
        },
        "workers": workers,
    }


def render(fleet_workers: dict, fleet_metrics: Optional[dict] = None,
           hub_url: str = "") -> str:
    workers = fleet_workers.get("workers") or []
    out = [
        f"dynamotop — {hub_url}  "
        f"{time.strftime('%H:%M:%S')}  ({len(workers)} worker(s))",
        "",
    ]
    out += render_summary(workers, fleet_metrics)
    out.append("")
    out += render_workers(workers)
    return "\n".join(out)


def main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="dynamotop", description=__doc__.splitlines()[0]
    )
    ap.add_argument("--hub", default="http://127.0.0.1:8080",
                    help="base URL of the process serving /fleet/*")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--once", action="store_true",
                    help="print one frame and exit")
    ap.add_argument("--no-clear", action="store_true",
                    help="append frames instead of redrawing in place")
    ap.add_argument("--json", action="store_true",
                    help="print one machine-readable snapshot and exit")
    args = ap.parse_args(argv[1:])
    if args.json:
        args.once = True
    base = args.hub.rstrip("/")
    while True:
        try:
            workers = fetch_json(f"{base}/fleet/workers")
            try:
                metrics = fetch_json(f"{base}/fleet/metrics")
            except (urllib.error.URLError, OSError, ValueError):
                metrics = None
            if args.json:
                print(json.dumps(snapshot(workers, metrics, hub_url=base),
                                 sort_keys=True, indent=1))
                return 0
            frame = render(workers, metrics, hub_url=base)
        except (urllib.error.URLError, OSError, ValueError) as e:
            frame = f"dynamotop: cannot reach {base}/fleet/workers: {e}"
            if args.once:
                print(frame, file=sys.stderr)
                return 2
        if not args.once and not args.no_clear:
            sys.stdout.write(CLEAR)
        print(frame, flush=True)
        if args.once:
            return 0
        try:
            time.sleep(max(0.2, args.interval))
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
