"""Benchmark: decode throughput of the native JAX engine on one TPU chip.

Runs the flagship Llama-3.2-1B-class config (bf16, paged KV cache) and
measures steady-state batched decode throughput. Prints ONE JSON line.

``vs_baseline`` is measured tokens/sec divided by the single-chip
HBM-roofline estimate for the same model/batch (decode is bandwidth-bound:
every step must stream all weights + the batch's KV context from HBM).
v5e: ~819 GB/s HBM. A value near 1.0 means the engine is at roofline;
the reference's engines (vLLM-class) typically sit at 0.5-0.7 of roofline
on their hardware (no absolute numbers are published in the reference —
BASELINE.md).

Attempt order: the known-safe per-token XLA path first (bank a number),
then the engine's fused multi-step decode on the same XLA path
(multi_step_decode: 8 steps per dispatch via lax.scan — amortizes the
fixed dispatch overhead that dominates small-model decode), then a
tiny-shape subprocess probe of the Pallas decode kernel, then — only if
the probe passed — the Pallas burst attempt with the remaining budget.
The best valid number wins. A hung Mosaic compile can wedge a host's
shared compile service (round-2 lesson), so nothing Pallas compiles
before the XLA number is recorded, and every attempt runs in a child
with a hard timeout. Budget knobs: BENCH_TOTAL_BUDGET_S (default 1380),
BENCH_TIMEOUT_S (per-XLA-attempt, default 600), BENCH_XLA_ONLY=1,
BENCH_SINGLE_STEP_ONLY=1.
"""

from __future__ import annotations

import json
import os as _os
import time

import numpy as np

import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
# honor JAX_PLATFORMS despite the site hook's early jax import, so CPU
# smoke runs (BENCH_SMOKE=1 JAX_PLATFORMS=cpu) never touch the relay
from dynamo_tpu.utils.platform import apply_jax_platform_override  # noqa: E402

apply_jax_platform_override()

V5E_HBM_GBPS = 819e9
METRIC = "decode_tokens_per_sec_per_chip_1b_bf16_b8_ctx512"


def run_once(attention_impl: str, burst: int = 1,
             pipeline: bool = False, persistent: bool = False,
             spec: bool = False, guided: bool = False) -> dict:
    import os

    import jax
    import jax.numpy as jnp

    from __graft_entry__ import FLAGSHIP
    from dynamo_tpu.engine.config import EngineConfig, ModelConfig
    from dynamo_tpu.models import llama

    smoke = bool(os.environ.get("BENCH_SMOKE"))  # tiny shapes: logic check only
    mcfg = ModelConfig(**(dict(
        vocab_size=512, hidden_size=64, intermediate_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2,
    ) if smoke else FLAGSHIP), attention_impl=attention_impl)
    cfg = EngineConfig(
        model=mcfg, max_batch_size=8, max_model_len=2048, kv_block_size=16,
        num_kv_blocks=1024, dtype="float32" if smoke else "bfloat16",
    )
    b, bs = cfg.max_batch_size, cfg.kv_block_size
    ctx = 512  # steady-state context per sequence
    # the engine sizes decode block tables to the live context
    # (EngineConfig.kv_width_bucket); the bench mirrors that
    w = cfg.kv_width_bucket(ctx // bs + 1)

    dtype = jnp.float32 if smoke else jnp.bfloat16
    params = llama.init_params(mcfg, jax.random.PRNGKey(0), dtype)
    if os.environ.get("BENCH_QUANT") == "int8":
        # weight-only int8 serving (models/quant.py): halves the weight
        # stream; the roofline below re-computes from the actual leaf
        # bytes, so vs_baseline stays honest for the quantized program
        from dynamo_tpu.models.quant import quantize_params

        params = quantize_params(params)
    kv_dtype = (
        jnp.float8_e4m3fn if os.environ.get("BENCH_KV") == "fp8" else dtype
    )
    k_cache, v_cache = llama.init_kv_cache(
        mcfg, cfg.num_kv_blocks, cfg.kv_block_size, kv_dtype
    )

    block_tables = jnp.asarray(
        np.arange(b * w, dtype=np.int32).reshape(b, w) % cfg.num_kv_blocks
    )

    def decode_step(params, k_cache, v_cache, tokens, positions,
                    slot_mapping, context_lens):
        logits, (k_cache, v_cache) = llama.forward(
            params, mcfg, tokens, positions, (k_cache, v_cache),
            block_tables, slot_mapping, context_lens,
        )
        return jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32), k_cache, v_cache

    step = jax.jit(decode_step, donate_argnums=(1, 2))

    tokens = jnp.zeros((b, 1), jnp.int32)
    positions = jnp.full((b, 1), ctx, jnp.int32)
    slot_mapping = (block_tables[:, ctx // bs] * bs + ctx % bs)[:, None]
    context_lens = jnp.full((b,), ctx + 1, jnp.int32)

    if burst > 1 and persistent and spec:
        # the engine's chained propose-verify round (decode_burst_spec):
        # each dispatch runs ONE S = burst-position forward (pending
        # token + proposals), takes the per-position argmax as the
        # verify, and folds acceptance + the done-mask freeze into the
        # device carry — the serving scheduler's shape for speculative
        # traffic under --device-finish. The measured number is verified
        # positions/s (the full-acceptance ceiling; real acceptance
        # scales it by (a+1)/S — the live
        # dynamo_engine_spec_accept_length histogram is the serving-time
        # scaler).
        stop_ids = jnp.full((b, 8), mcfg.vocab_size + 1, jnp.int32)
        S = burst
        spec_positions = positions + jnp.arange(S)[None, :]
        spec_slots = jnp.tile(slot_mapping, (1, S))

        def spec_round(params, k_cache, v_cache, tok0, done0):
            row_toks = jnp.tile(tok0[:, None], (1, S))
            logits, (k_cache, v_cache) = llama.forward(
                params, mcfg, row_toks, spec_positions, (k_cache, v_cache),
                block_tables, spec_slots, context_lens + S,
            )
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            match = greedy[:, :-1] == row_toks[:, 1:]
            acc = jnp.cumprod(match.astype(jnp.int32), axis=1).sum(axis=1)
            nt = jnp.take_along_axis(greedy, acc[:, None], axis=1)[:, 0]
            nt = jnp.where(done0, tok0, nt)
            done = done0 | (nt[:, None] == stop_ids).any(axis=1)
            return nt, done, k_cache, v_cache

        step = jax.jit(spec_round, donate_argnums=(1, 2))
        done0 = jnp.zeros((b,), jnp.bool_)

        def dispatch(out, k, v):
            nt, _done, k, v = step(params, k, v, out, done0)
            return nt, k, v
    elif burst > 1 and persistent:
        # the engine's persistent decode loop (device_finish): the fused
        # K-step burst additionally carries a per-row done mask and runs
        # the stop-token membership check each step — the on-device
        # finish detection the serving scheduler uses to chain bursts
        # without a per-burst host barrier. The stop set here is chosen
        # never to hit (token ids are < vocab), so the chain runs full
        # length while paying the real per-step check cost. With
        # ``guided`` the carry additionally holds a per-row grammar
        # state advanced through a device transition table whose row
        # masks the logits each step (the serving scheduler's shape for
        # in-bound guided traffic under --device-finish) — transitions
        # never reject, so the chain runs full length while paying the
        # real mask-compute + table-lookup cost.
        stop_ids = jnp.full((b, 8), mcfg.vocab_size + 1, jnp.int32)
        n_states = 64
        gtable = (
            jnp.asarray(
                np.random.default_rng(0).integers(
                    1, n_states, size=(n_states, mcfg.vocab_size)
                ), jnp.int32,
            ) if guided else None
        )

        def decode_burst_df(params, k_cache, v_cache, tok0, done0, gst0):
            def one(carry, _):
                k_cache, v_cache, toks, done, gst = carry
                logits, (k_cache, v_cache) = llama.forward(
                    params, mcfg, toks[:, None], positions,
                    (k_cache, v_cache), block_tables, slot_mapping,
                    context_lens,
                )
                last = logits[:, -1]
                if gtable is not None:
                    last = last + jnp.where(gtable[gst] < 0, -1e9, 0.0)
                nt = jnp.argmax(last, axis=-1).astype(jnp.int32)
                nt = jnp.where(done, toks, nt)  # frozen rows hold
                done = done | (nt[:, None] == stop_ids).any(axis=1)
                if gtable is not None:
                    gst = gtable[gst, nt]
                return (k_cache, v_cache, nt, done, gst), None
            (k_cache, v_cache, nt, done, gst), _ = jax.lax.scan(
                one, (k_cache, v_cache, tok0, done0, gst0), None,
                length=burst
            )
            return nt, done, gst, k_cache, v_cache

        step = jax.jit(decode_burst_df, donate_argnums=(1, 2))
        done0 = jnp.zeros((b,), jnp.bool_)
        gst0 = jnp.zeros((b,), jnp.int32)

        def dispatch(out, k, v):
            nt, _done, _gst, k, v = step(params, k, v, out, done0, gst0)
            return nt, k, v
    elif burst > 1:
        # the engine's multi_step_decode path: K steps fused into one
        # dispatch via lax.scan (steady-state position, same per-token
        # work) — measures how much of the per-dispatch overhead the
        # fused program removes
        def decode_burst(params, k_cache, v_cache, tok0):
            def one(carry, _):
                k_cache, v_cache, toks = carry
                nt, k_cache, v_cache = decode_step(
                    params, k_cache, v_cache, toks[:, None], positions,
                    slot_mapping, context_lens,
                )
                return (k_cache, v_cache, nt), None
            (k_cache, v_cache, nt), _ = jax.lax.scan(
                one, (k_cache, v_cache, tok0), None, length=burst
            )
            return nt, k_cache, v_cache
        step = jax.jit(decode_burst, donate_argnums=(1, 2))
        dispatch = lambda out, k, v: step(params, k, v, out)  # noqa: E731
    else:
        dispatch = lambda out, k, v: step(  # noqa: E731
            params, k, v, out[:, None], positions, slot_mapping, context_lens
        )

    # warmup / compile
    out = jnp.zeros((b,), jnp.int32) if burst > 1 else tokens[:, 0]
    out, k_cache, v_cache = dispatch(out, k_cache, v_cache)
    out.block_until_ready()

    n_steps = (4 * burst) if smoke else 64
    t0 = time.perf_counter()
    if persistent:
        # the engine's persistent decode loop: bursts dispatch
        # back-to-back off the device-resident carry (finish detection
        # rides inside the program — no per-burst verdict needed on the
        # host), while a drain thread syncs every burst's tokens to the
        # host — as serving must stream them — WITHOUT ever gating the
        # next dispatch. Compare against xla:k8:pipelined (per-burst
        # sync overlapped but still completing before dispatch k+2) and
        # xla:k8 (never syncs, the unreachable upper bound).
        import concurrent.futures as _cf

        with _cf.ThreadPoolExecutor(max_workers=1) as drain:
            drains = []
            for _ in range(n_steps // burst):
                out, k_cache, v_cache = dispatch(out, k_cache, v_cache)
                drains.append(drain.submit(np.asarray, out))
            for f in drains:
                f.result()
    elif pipeline:
        # the engine's dispatch-ahead decode loop
        # (EngineConfig.decode_pipeline_depth=2): every burst's sampled
        # tokens ARE synced to the host (the serving engine must stream
        # them), but the sync happens AFTER the next burst is dispatched,
        # so the host conversion overlaps device compute instead of
        # serializing with it. Compare against the plain burst attempt
        # (no per-burst sync at all — an upper bound the engine can't
        # reach) to see what the overlap recovers.
        prev = None
        for _ in range(n_steps // burst):
            out, k_cache, v_cache = dispatch(out, k_cache, v_cache)
            if prev is not None:
                np.asarray(prev)  # reconcile burst k while k+1 executes
            prev = out
        np.asarray(prev)
    else:
        for _ in range(n_steps // burst):
            out, k_cache, v_cache = dispatch(out, k_cache, v_cache)
        out.block_until_ready()
    dt = time.perf_counter() - t0

    toks_per_sec = b * (n_steps // burst) * burst / dt

    # HBM roofline: per decode step, stream weights once + per-seq KV(ctx)
    param_bytes = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))
    kv_bytes_per_seq = (
        2 * mcfg.num_layers * ctx * mcfg.num_kv_heads * mcfg.head_dim
        * jnp.dtype(kv_dtype).itemsize
    )
    step_bytes = param_bytes + b * kv_bytes_per_seq
    roofline_steps = V5E_HBM_GBPS / step_bytes
    roofline_toks = roofline_steps * b

    metric = METRIC
    if os.environ.get("BENCH_QUANT") == "int8":
        # a different workload must not masquerade as the bf16 series
        metric = metric.replace("_bf16_", "_int8_")
    if os.environ.get("BENCH_KV") == "fp8":
        metric += "_kvfp8"
    return {
        "metric": metric,
        "value": round(toks_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(toks_per_sec / roofline_toks, 3),
    }


def run_sp_prefill(ctx: int) -> dict:
    """The long-context prefill lever (xla:k8:sp-prefill): prefill
    tokens/s of the sequence-parallel chunk ladder across the mesh vs
    the single-chip dense chunk ladder, at one context length.

    Runs the REAL serving programs (ModelRunner.sp_prefill_chunk and
    ModelRunner.step over the scheduler's shared bucket ladder), so the
    number includes every cost the engine pays: chunk padding, paged
    prefix gathers, the ring rotation, and the final sampling tail.
    CPU smoke (BENCH_SMOKE=1) forces an 8-device virtual host platform
    so the mesh logic is exercised creds-free.
    """
    import os

    smoke = bool(os.environ.get("BENCH_SMOKE"))
    if smoke and "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax
    import numpy as _np

    from __graft_entry__ import FLAGSHIP
    from dynamo_tpu.engine.config import EngineConfig, ModelConfig
    from dynamo_tpu.engine.model_runner import ModelRunner
    from dynamo_tpu.engine.scheduler import (
        build_prefill_arrays,
        prefill_bucket_cap,
    )

    n_dev = len(jax.devices())
    sp = 8 if n_dev >= 8 else max(1, n_dev)
    mdims = dict(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, num_kv_heads=2,
    ) if smoke else dict(FLAGSHIP)
    mdims["max_position_embeddings"] = max(
        mdims.get("max_position_embeddings", 4096), ctx + 64)
    mcfg = ModelConfig(**mdims, attention_impl="xla")
    bs = 16
    blocks = ctx // bs + 8

    def build(sp_size):
        cfg = EngineConfig(
            model=mcfg, max_batch_size=1, max_model_len=ctx + 64,
            kv_block_size=bs, num_kv_blocks=blocks,
            dtype="float32" if smoke else "bfloat16",
            sp_size=sp_size,
            max_prefill_tokens_per_step=64 if smoke else 8192,
        )
        return cfg, ModelRunner(cfg, model_dir=None)

    prompt = [int(t) for t in _np.random.default_rng(0).integers(
        1, mcfg.vocab_size, ctx)]
    block_ids = list(range(ctx // bs + 1))
    zeros1 = _np.zeros(1, _np.float32)

    def dense_ladder(cfg, runner):
        cap = prefill_bucket_cap(cfg) or cfg.prefill_buckets[0]
        pos, outs, chunks = 0, None, 0
        t0 = time.perf_counter()
        while pos < ctx:
            end = min(pos + cap, ctx)
            arrays = build_prefill_arrays(cfg, prompt[:end], pos, block_ids)
            outs = runner.step(
                *arrays, zeros1, _np.zeros(1, _np.int32),
                _np.ones(1, _np.float32),
                seed_keys=_np.zeros((1, 2), _np.uint32),
                counters=_np.zeros(1, _np.int32),
                sample_slots=_np.zeros(1, _np.int32),
                commit=_np.asarray([end >= ctx]), want_top=False,
            )
            pos, chunks = end, chunks + 1
        _np.asarray(outs[0])  # drain
        return time.perf_counter() - t0, chunks

    def sp_ladder(cfg, runner):
        cap = runner.sp_chunk_tokens
        pos, outs, chunks = 0, None, 0
        t0 = time.perf_counter()
        while pos < ctx:
            end = min(pos + cap, ctx)
            outs = runner.sp_prefill_chunk(
                prompt[:end], pos, block_ids, commit=end >= ctx,
            )
            pos, chunks = end, chunks + 1
        _np.asarray(outs[0])  # drain
        return time.perf_counter() - t0, chunks

    # dense single-chip ladder first (compile + measure), then free it
    # before the SP runner claims HBM
    cfg_d, runner_d = build(1)
    dense_ladder(cfg_d, runner_d)  # compile pass
    dense_s, dense_chunks = dense_ladder(cfg_d, runner_d)
    del runner_d

    cfg_sp, runner_sp = build(sp)
    sp_ladder(cfg_sp, runner_sp)  # compile pass
    sp_s, sp_chunks = sp_ladder(cfg_sp, runner_sp)

    return {
        "metric": f"prefill_tokens_per_sec_1b_ctx{ctx}",
        "value": round(ctx / sp_s, 1),
        "unit": "tokens/s",
        "dense_tokens_per_s": round(ctx / dense_s, 1),
        "speedup_vs_single_chip": round(dense_s / sp_s, 3),
        "sp_axis": sp,
        "sp_chunks": sp_chunks,
        "dense_chunks": dense_chunks,
        "ctx": ctx,
        "smoke": smoke,
    }


def run_sp_kernel(ctx: int) -> dict:
    """The paged SP ring-prefill kernel lever (xla:k8:sp-kernel):
    prefill tokens/s of the sequence-parallel ladder with the Pallas
    page-walk prefix kernel (ops/pallas_sp.py — the committed prefix is
    read page-by-page from the cache via double-buffered DMA) vs the
    XLA gather path (which materializes the whole [1, W*bs] prefix per
    layer). Both runs go through the REAL SP serving program; only the
    attention route differs. CPU smoke (BENCH_SMOKE=1) runs the kernel
    in interpret mode over an 8-device virtual host platform, proving
    the route end-to-end creds-free (the number is then a smoke
    artifact, not a perf claim).
    """
    import os

    smoke = bool(os.environ.get("BENCH_SMOKE"))
    if smoke:
        if "xla_force_host_platform_device_count" not in \
                os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8"
            ).strip()
        os.environ["DYN_PALLAS_INTERPRET"] = "1"
    import jax
    import numpy as _np

    from __graft_entry__ import FLAGSHIP
    from dynamo_tpu.engine.config import EngineConfig, ModelConfig
    from dynamo_tpu.engine.model_runner import ModelRunner

    n_dev = len(jax.devices())
    sp = 8 if n_dev >= 8 else max(1, n_dev)
    mdims = dict(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, num_kv_heads=2,
    ) if smoke else dict(FLAGSHIP)
    mdims["max_position_embeddings"] = max(
        mdims.get("max_position_embeddings", 4096), ctx + 64)
    bs = 16
    blocks = ctx // bs + 8

    def build(impl):
        mcfg = ModelConfig(**mdims, attention_impl=impl)
        cfg = EngineConfig(
            model=mcfg, max_batch_size=1, max_model_len=ctx + 64,
            kv_block_size=bs, num_kv_blocks=blocks,
            dtype="float32" if smoke else "bfloat16",
            sp_size=sp,
            max_prefill_tokens_per_step=64 if smoke else 8192,
        )
        return cfg, ModelRunner(cfg, model_dir=None)

    prompt = [int(t) for t in _np.random.default_rng(0).integers(
        1, mdims["vocab_size"], ctx)]
    block_ids = list(range(ctx // bs + 1))

    def sp_ladder(runner):
        cap = runner.sp_chunk_tokens
        pos, outs, chunks = 0, None, 0
        t0 = time.perf_counter()
        while pos < ctx:
            end = min(pos + cap, ctx)
            outs = runner.sp_prefill_chunk(
                prompt[:end], pos, block_ids, commit=end >= ctx,
            )
            pos, chunks = end, chunks + 1
        _np.asarray(outs[0])  # drain
        return time.perf_counter() - t0, chunks

    cfg_x, runner_x = build("xla")
    sp_ladder(runner_x)  # compile pass
    gather_s, chunks = sp_ladder(runner_x)
    del runner_x

    cfg_k, runner_k = build("pallas")
    sp_ladder(runner_k)  # compile pass
    kernel_s, _ = sp_ladder(runner_k)

    return {
        "metric": f"sp_kernel_prefill_tokens_per_sec_ctx{ctx}",
        "value": round(ctx / kernel_s, 1),
        "unit": "tokens/s",
        "gather_tokens_per_s": round(ctx / gather_s, 1),
        "speedup_vs_gather": round(gather_s / kernel_s, 3),
        "sp_axis": sp,
        "chunks": chunks,
        "ctx": ctx,
        "smoke": smoke,
    }


def run_fused_epilogue(iters: int = 200) -> dict:
    """The fused sampling-epilogue lever (xla:k8:fused-epilogue):
    per-step latency of the decode tail — penalties, top-k/top-p/min-p
    sampling, count commit, finish verdict + stop-suffix hash — as the
    ONE-dispatch Pallas kernel (ops/pallas_epilogue.py) vs the unfused
    [B, V] XLA op ladder. Drives the REAL shared tail
    (model_runner._sample_and_logprobs with fused on/off), so the two
    timings cover exactly what the chained burst pays per token. CPU
    smoke runs the kernel in interpret mode (route proof, not perf).
    """
    import functools
    import os
    import types

    smoke = bool(os.environ.get("BENCH_SMOKE"))
    if smoke:
        os.environ["DYN_PALLAS_INTERPRET"] = "1"
    import jax
    import jax.numpy as jnp
    import numpy as _np

    from dynamo_tpu.engine.model_runner import _sample_and_logprobs
    from dynamo_tpu.engine.sampling import SamplingParams

    b, v, ns = (8, 2048, 8) if smoke else (64, 32768, 64)
    iters = 20 if smoke else iters
    rng = _np.random.default_rng(0)
    cfg = types.SimpleNamespace(vocab_size=v)
    logits = jnp.asarray(rng.normal(size=(b, v)), jnp.float32)
    counts = jnp.zeros((ns, v), jnp.int32)
    seen = jnp.zeros((ns, v), jnp.bool_)
    bias = jnp.zeros((ns, v), jnp.float32)
    slots = jnp.arange(b, dtype=jnp.int32)
    commit = jnp.ones((b,), jnp.bool_)
    samp = SamplingParams.zeros(b)
    samp = _dataclasses_replace_samp(samp, b)
    want_top = jnp.asarray(False)

    def tail(fused):
        @jax.jit
        def run(logits, samp, counts, seen, bias):
            return _sample_and_logprobs(
                cfg, logits, samp, counts, seen, bias, slots, commit,
                want_top, fused=fused,
            )[:3]
        return run

    results = {}
    for name, fused in (("xla", False), ("fused", True)):
        fn = tail(fused)
        jax.block_until_ready(fn(logits, samp, counts, seen, bias))
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(logits, samp, counts, seen, bias)
        jax.block_until_ready(out)
        results[name] = (time.perf_counter() - t0) / iters

    return {
        "metric": "fused_epilogue_tail_us_per_step",
        "value": round(results["fused"] * 1e6, 2),
        "unit": "us",
        "xla_tail_us": round(results["xla"] * 1e6, 2),
        "speedup_vs_xla": round(results["xla"] / results["fused"], 3),
        "batch": b,
        "vocab": v,
        "iters": iters,
        "smoke": smoke,
    }


def _dataclasses_replace_samp(samp, b):
    """Non-trivial sampling params so the lever times the whole ladder
    (temperature + top-k + top-p + penalties), not constant-folded
    no-ops."""
    import dataclasses

    import jax.numpy as jnp

    return dataclasses.replace(
        samp,
        temperature=jnp.full((b,), 0.8, jnp.float32),
        top_k=jnp.full((b,), 40, jnp.int32),
        top_p=jnp.full((b,), 0.95, jnp.float32),
        repetition_penalty=jnp.full((b,), 1.1, jnp.float32),
    )


def run_ici_pull(nblocks: int = 0, chunk: int = 16) -> dict:
    """The unified-transfer-plane payload lever (xla:k8:ici-pull): KV
    block throughput of the ici (device-to-device collective) payload
    path vs the tcp fallback, through the REAL plane seams — the tcp
    side pays the full framing bill (executor byte-pack, socket frames,
    decode, host→device install), the ici side enters the collective
    plane with device arrays and the host touches only headers.

    On hardware the collective rides the actual interconnect; CPU smoke
    (BENCH_SMOKE=1) runs the loopback plane (transfer/ici.py), so the
    framing, one-in-flight pairing, and seq cross-check are exercised
    creds-free — there the RATIO is the logic check, not a perf claim.
    """
    import asyncio
    import os

    import jax.numpy as jnp
    import numpy as _np

    from dynamo_tpu.transfer import (
        IciBackend,
        LoopbackIciTransfer,
        TcpBackend,
        pack_frame,
        read_header,
    )

    smoke = bool(os.environ.get("BENCH_SMOKE"))
    if not nblocks:
        nblocks = 128 if smoke else 2048
    bs, heads, hd = (16, 2, 32) if smoke else (16, 8, 128)
    frames = [
        (jnp.asarray(_np.random.default_rng(i).standard_normal(
            (1, chunk, bs, heads, hd), dtype=_np.float32)),) * 2
        for i in range(nblocks // chunk)
    ]
    frame_bytes = 2 * int(frames[0][0].nbytes)

    async def tcp_pass() -> float:
        done = asyncio.Event()

        async def handle(reader, writer):
            while True:
                header = await read_header(reader, "bench")
                if header is None or header.get("type") == "end":
                    break
                k, v = await TcpBackend.recv_blocks(reader, header)
                # the install cost a real pull pays before scatter
                jnp.asarray(k).block_until_ready()
                jnp.asarray(v).block_until_ready()
            done.set()
            writer.close()

        server = await asyncio.start_server(handle, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        t0 = time.perf_counter()
        for i, (k, v) in enumerate(frames):
            await TcpBackend.send_blocks(
                writer, {"type": "blocks", "offset": i * chunk}, k, v)
        pack_frame(writer, {"type": "end"})
        await writer.drain()
        await done.wait()
        wall = time.perf_counter() - t0
        writer.close()
        server.close()
        await server.wait_closed()
        return wall

    async def ici_pass() -> float:
        lb = LoopbackIciTransfer(buckets=(chunk,))
        tx, rx = IciBackend(lb), IciBackend(lb)

        async def pull():
            for _ in frames:
                k, v, _seq = await rx.recv(chunk)
                k.block_until_ready()
                v.block_until_ready()

        t0 = time.perf_counter()
        task = asyncio.ensure_future(pull())
        for k, v in frames:
            await tx.send(k, v, tx.next_seq(), chunk)
        await task
        return time.perf_counter() - t0

    loop = asyncio.new_event_loop()
    try:
        tcp_s = loop.run_until_complete(tcp_pass())  # warm executor/socket
        tcp_s = min(tcp_s, loop.run_until_complete(tcp_pass()))
        ici_s = loop.run_until_complete(ici_pass())
        ici_s = min(ici_s, loop.run_until_complete(ici_pass()))
    finally:
        loop.close()
    return {
        "metric": "kv_pull_blocks_per_sec_ici",
        "value": round(nblocks / ici_s, 1),
        "unit": "blocks/s",
        "tcp_blocks_per_s": round(nblocks / tcp_s, 1),
        "speedup_vs_tcp": round(tcp_s / ici_s, 3),
        "nblocks": nblocks,
        "chunk_blocks": chunk,
        "frame_bytes": frame_bytes,
        "smoke": smoke,
    }


# one JSON line per attempt/probe outcome, appended as they happen: the
# driver's BENCH_r*.json keeps only the winning line, so when a round
# goes sideways (wedged relay, timeouts) this sidecar is the record of
# what was actually tried and how long each try burned
_ATTEMPTS_PATH = None


def _attempts_sidecar_init() -> str:
    global _ATTEMPTS_PATH
    _ATTEMPTS_PATH = _os.path.join(
        _os.path.dirname(_os.path.abspath(__file__)),
        time.strftime("BENCH_attempts_%Y%m%dT%H%M%SZ.jsonl", time.gmtime()),
    )
    return _ATTEMPTS_PATH


def _log_attempt(record: dict) -> None:
    if _ATTEMPTS_PATH is None:
        return
    record = dict(record, t_utc=time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
    try:
        with open(_ATTEMPTS_PATH, "a") as f:
            f.write(json.dumps(record) + "\n")
    except OSError:
        pass  # the sidecar is best-effort; never fail the bench over it


def _relay_probe(timeout_s: float = 45.0) -> str:
    """Cheap aliveness check: can a child compile a 128x128 matmul?

    The host's compile service is shared and serializes; a wedged Mosaic
    compile (observed rounds 2 and 4) blocks EVERY process's compiles,
    including trivial XLA ones. Returns ``"alive"``, ``"wedged"`` (child
    hung — drain-waiting may heal it), ``"crashed"`` (child failed
    fast — deterministic breakage a wait cannot fix), or ``"cpu-only"``
    (the child came up on the CPU backend: the relay "healed" into a
    fallback that would measure CPU numbers and report them as the chip
    metric — observed round 7; banking the recorded number is the only
    honest output there).
    """
    import subprocess
    import sys

    code = ("import os, jax; "
            "p = os.environ.get('JAX_PLATFORMS'); "
            "p and jax.config.update('jax_platforms', p); "
            "import jax.numpy as jnp; x = jnp.ones((128, 128)); "
            "print('RELAY_ALIVE', jax.default_backend(), "
            "float((x @ x).sum()))")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return "wedged"
    for line in proc.stdout.splitlines():
        if line.startswith("RELAY_ALIVE"):
            backend = (line.split() + ["?", "?"])[1]
            return "cpu-only" if backend == "cpu" else "alive"
    return "crashed"


def _run_impl_subprocess(impl: str, timeout_s: float, burst: int = 1,
                         pipeline: bool = False, persistent: bool = False,
                         spec: bool = False, guided: bool = False,
                         label: str = ""):
    """Run one bench attempt in a child process with a hard timeout.

    A Mosaic compile can (rarely) hang rather than fail; an in-process
    attempt would then wedge the whole bench. The child prints its result
    JSON on the last line; timeout/crash → None and the caller falls back.
    Every outcome (result, rc, wall time, error) is appended to the
    BENCH_attempts_*.jsonl sidecar.
    """
    import subprocess
    import sys

    label = label or impl
    code = (
        "import json; from bench import run_once; "
        "print('BENCH_RESULT ' + json.dumps("
        f"run_once({impl!r}, {burst}, pipeline={pipeline}, "
        f"persistent={persistent}, spec={spec}, guided={guided})))"
    )
    t0 = time.monotonic()
    rec = {"label": label, "impl": impl, "burst": burst,
           "pipeline": pipeline, "persistent": persistent,
           "spec": spec, "guided": guided,
           "timeout_s": round(timeout_s, 1)}
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s, cwd=_os.path.dirname(
                _os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        print(f"bench[{label}] timed out after {timeout_s:.0f}s", flush=True)
        _log_attempt(dict(rec, rc=124, wall_s=round(
            time.monotonic() - t0, 1), error="timeout"))
        return None
    wall = round(time.monotonic() - t0, 1)
    for line in reversed(proc.stdout.splitlines()):
        if line.startswith("BENCH_RESULT "):
            result = json.loads(line[len("BENCH_RESULT "):])
            _log_attempt(dict(rec, rc=proc.returncode, wall_s=wall,
                              result=result))
            return result
    sys.stderr.write(proc.stderr[-4000:])
    print(f"bench[{label}] failed (rc={proc.returncode})", flush=True)
    _log_attempt(dict(rec, rc=proc.returncode, wall_s=wall,
                      error=(proc.stderr[-500:] or "no result line")))
    return None


def _run_sp_subprocess(ctx: int, timeout_s: float):
    """One sp-prefill lever attempt in a child with a hard timeout —
    the same discipline as every other attempt; per-ctx rows land in
    the attempts sidecar."""
    import subprocess
    import sys

    label = f"xla:k8:sp-prefill:ctx{ctx}"
    code = (
        "import json; from bench import run_sp_prefill; "
        f"print('BENCH_RESULT ' + json.dumps(run_sp_prefill({ctx})))"
    )
    t0 = time.monotonic()
    rec = {"label": label, "ctx": ctx, "timeout_s": round(timeout_s, 1)}
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s,
            cwd=_os.path.dirname(_os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        print(f"bench[{label}] timed out after {timeout_s:.0f}s", flush=True)
        _log_attempt(dict(rec, rc=124, wall_s=round(
            time.monotonic() - t0, 1), error="timeout"))
        return None
    wall = round(time.monotonic() - t0, 1)
    for line in reversed(proc.stdout.splitlines()):
        if line.startswith("BENCH_RESULT "):
            result = json.loads(line[len("BENCH_RESULT "):])
            _log_attempt(dict(rec, rc=proc.returncode, wall_s=wall,
                              result=result))
            return result
    print(f"bench[{label}] failed (rc={proc.returncode})", flush=True)
    _log_attempt(dict(rec, rc=proc.returncode, wall_s=wall,
                      error=(proc.stderr[-500:] or "no result line")))
    return None


def _run_kernel_lever_subprocess(label: str, fn_name: str, call: str,
                                 timeout_s: float, **rec_extra):
    """One kernel-campaign lever attempt (sp-kernel / fused-epilogue)
    in a child with a hard timeout — the same discipline as every
    other attempt; rows land in the attempts sidecar."""
    import subprocess
    import sys

    code = (
        f"import json; from bench import {fn_name}; "
        f"print('BENCH_RESULT ' + json.dumps({call}))"
    )
    t0 = time.monotonic()
    rec = {"label": label, "timeout_s": round(timeout_s, 1), **rec_extra}
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s,
            cwd=_os.path.dirname(_os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        print(f"bench[{label}] timed out after {timeout_s:.0f}s", flush=True)
        _log_attempt(dict(rec, rc=124, wall_s=round(
            time.monotonic() - t0, 1), error="timeout"))
        return None
    wall = round(time.monotonic() - t0, 1)
    for line in reversed(proc.stdout.splitlines()):
        if line.startswith("BENCH_RESULT "):
            result = json.loads(line[len("BENCH_RESULT "):])
            _log_attempt(dict(rec, rc=proc.returncode, wall_s=wall,
                              result=result))
            return result
    print(f"bench[{label}] failed (rc={proc.returncode})", flush=True)
    _log_attempt(dict(rec, rc=proc.returncode, wall_s=wall,
                      error=(proc.stderr[-500:] or "no result line")))
    return None


def _run_ici_pull_subprocess(timeout_s: float):
    """One ici-pull lever attempt in a child with a hard timeout."""
    import subprocess
    import sys

    label = "xla:k8:ici-pull"
    code = (
        "import json; from bench import run_ici_pull; "
        "print('BENCH_RESULT ' + json.dumps(run_ici_pull()))"
    )
    t0 = time.monotonic()
    rec = {"label": label, "timeout_s": round(timeout_s, 1)}
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s,
            cwd=_os.path.dirname(_os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        print(f"bench[{label}] timed out after {timeout_s:.0f}s", flush=True)
        _log_attempt(dict(rec, rc=124, wall_s=round(
            time.monotonic() - t0, 1), error="timeout"))
        return None
    wall = round(time.monotonic() - t0, 1)
    for line in reversed(proc.stdout.splitlines()):
        if line.startswith("BENCH_RESULT "):
            result = json.loads(line[len("BENCH_RESULT "):])
            _log_attempt(dict(rec, rc=proc.returncode, wall_s=wall,
                              result=result))
            return result
    print(f"bench[{label}] failed (rc={proc.returncode})", flush=True)
    _log_attempt(dict(rec, rc=proc.returncode, wall_s=wall,
                      error=(proc.stderr[-500:] or "no result line")))
    return None


def main() -> None:
    # Bank a number FIRST, improve on it second. Ordering is deliberate:
    # the XLA path's compile is known-safe, while a Pallas kernel's first
    # Mosaic compile on a new host can hang the machine's shared compile
    # service for every later process (observed: round 2 recorded rc 124
    # and no number because the preferred path ran first and wedged the
    # relay). So: (1) measure the XLA path in a child with a bounded
    # timeout; (2) probe the decode kernel standalone on tiny shapes in
    # a child; (3) only if the probe passes, run the Pallas attempt with
    # the remaining budget. Whatever happens in (2)/(3), the XLA number
    # from (1) is already in hand and gets printed.
    import os
    import time as _time

    total_budget = float(os.environ.get("BENCH_TOTAL_BUDGET_S", "1380"))
    xla_timeout = min(float(os.environ.get("BENCH_TIMEOUT_S", "600")), total_budget)
    t0 = _time.monotonic()
    sidecar = _attempts_sidecar_init()
    print(f"attempt log: {os.path.basename(sidecar)}", flush=True)

    # preflight: a TINY op under a SHORT timeout. A wedged compile
    # service used to burn two full attempt timeouts before the banked
    # fallback engaged (the r05 failure mode); the cheap probe detects it
    # in under a minute and the wedged branch below banks immediately.
    preflight_s = float(os.environ.get("BENCH_PREFLIGHT_TIMEOUT_S", "45"))
    t_probe = _time.monotonic()
    health = _relay_probe(preflight_s)
    _log_attempt({"label": "preflight", "outcome": health,
                  "timeout_s": preflight_s,
                  "wall_s": round(_time.monotonic() - t_probe, 1)})
    if health == "wedged":
        # wedged relay: wait for the remote compile queue to drain before
        # spending real budget, but cap the wait so a dead-all-day relay
        # still leaves time for one full XLA attempt (it may heal between
        # probes — observed recovery is abrupt, not gradual). A "crashed"
        # probe is deterministic breakage: waiting cannot heal it, so
        # skip the drain and let the (fast-failing) attempts report it.
        print("relay preflight hung (compile service wedged); waiting "
              "for it to drain", flush=True)
        drain_deadline = t0 + min(0.4 * total_budget, 600.0)
        while _time.monotonic() < drain_deadline:
            _time.sleep(45.0)
            t_probe = _time.monotonic()
            health = _relay_probe(preflight_s)
            _log_attempt({"label": "preflight-drain", "outcome": health,
                          "wall_s": round(_time.monotonic() - t_probe, 1)})
            if health == "alive":
                print("relay recovered; proceeding", flush=True)
                break
            if health in ("crashed", "cpu-only"):
                # wedge became deterministic breakage (crashed) or healed
                # into the CPU fallback (cpu-only, banked below either
                # way); more drain-waiting can't change the verdict
                break
    if health == "crashed":
        print("relay preflight failed fast (device init error, not a "
              "wedge); attempting anyway", flush=True)
    if health == "cpu-only" and not os.environ.get("BENCH_SMOKE"):
        # no accelerator visible: every attempt would "succeed" on CPU
        # and report garbage as the chip metric, silently replacing the
        # real banked measurement — bank instead. (BENCH_SMOKE runs are
        # logic checks on tiny shapes and keep going on CPU on purpose.)
        print("relay preflight came up on the CPU backend (no chip "
              "visible); banking the recorded number instead of "
              "measuring CPU garbage", flush=True)
        best = banked_fallback()
        best["error"] = ("no accelerator visible (cpu-only backend); "
                         "the chip metric cannot be measured here")
        _log_attempt({"label": "banked-cpu-only", "result": best})
        _log_attempt({"label": "winner", "result": best})
        print(json.dumps(best))
        return
    if health == "wedged":
        # still wedged after the drain window: every live attempt would
        # time out — bank the last real-hardware number IMMEDIATELY
        # instead of burning full attempt timeouts on a dead relay
        print("relay still wedged after drain wait; banking the recorded "
              "number without live attempts", flush=True)
        best = banked_fallback()
        _log_attempt({"label": "banked-early", "result": best})
        _log_attempt({"label": "winner", "result": best})
        print(json.dumps(best))
        return

    # persistent compilation cache: repeated bench runs (and the driver's
    # end-of-round run) reuse executables instead of re-compiling through
    # the shared relay; harmless no-op where serialization is unsupported.
    # Set AFTER the health probes — a cache hit on the probe matmul would
    # report "alive" without ever touching the relay.
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
    )

    def note(label: str, result) -> None:
        # one line per attempt: the driver log keeps the whole lever
        # table even though only the best goes on the final line
        if result is not None:
            print(f"attempt[{label}]: {json.dumps(result)}", flush=True)

    result = _run_impl_subprocess("xla", timeout_s=xla_timeout,
                                  label="xla:k1")
    note("xla:k1", result)
    if result is None:
        # one retry: a draining relay often comes back abruptly, and the
        # XLA number is the one that must not be lost
        remaining = total_budget - (_time.monotonic() - t0)
        if remaining > 180:
            result = _run_impl_subprocess(
                "xla", timeout_s=min(300.0, remaining - 60),
                label="xla:k1-retry",
            )
            note("xla:k1-retry", result)
    best = result

    # the engine's fused multi-step decode (multi_step_decode=K): same
    # XLA-safe program shape, K dispatches' overhead amortized into one.
    # K=8 should recover most of the ~10ms/step dispatch gap
    # (docs/perf_tuning.md); K=16 checks for a remaining tail.
    remaining = total_budget - (_time.monotonic() - t0)
    if remaining > 360 and not os.environ.get("BENCH_SINGLE_STEP_ONLY"):
        burst = _run_impl_subprocess(
            "xla", timeout_s=min(300.0, remaining - 240), burst=8,
            label="xla:k8",
        )
        note("xla:k8", burst)
        if burst is not None and (best is None
                                  or burst["value"] > best["value"]):
            best = burst
        remaining = total_budget - (_time.monotonic() - t0)
        if burst is not None and remaining > 460:
            burst16 = _run_impl_subprocess(
                "xla", timeout_s=min(300.0, remaining - 300), burst=16,
                label="xla:k16",
            )
            note("xla:k16", burst16)
            if burst16 is not None and burst16["value"] > best["value"]:
                best = burst16

    # the engine's dispatch-ahead decode pipeline
    # (decode_pipeline_depth=2): the same fused K=8 burst, but every
    # burst's tokens are synced to the host — as serving must — with the
    # sync overlapped behind the next burst's device time. This is the
    # engine-shaped number (plain k8 never syncs, an upper bound the
    # scheduler cannot reach). Same known-safe XLA program, same child-
    # process + hard-timeout discipline as every other attempt.
    remaining = total_budget - (_time.monotonic() - t0)
    if remaining > 360 and not os.environ.get("BENCH_SINGLE_STEP_ONLY"):
        piped = _run_impl_subprocess(
            "xla", timeout_s=min(300.0, remaining - 240), burst=8,
            pipeline=True, label="xla:k8:pipelined",
        )
        note("xla:k8:pipelined", piped)
        if piped is not None and (best is None
                                  or piped["value"] > best["value"]):
            best = piped

    # the persistent decode loop (device-resident finish + chained
    # dispatch + async row drain): the serving scheduler's new shape
    # under --device-finish. Strictly more overlap than :pipelined —
    # dispatch never waits for ANY burst's host sync to complete.
    remaining = total_budget - (_time.monotonic() - t0)
    if remaining > 360 and not os.environ.get("BENCH_SINGLE_STEP_ONLY"):
        persist = _run_impl_subprocess(
            "xla", timeout_s=min(300.0, remaining - 240), burst=8,
            persistent=True, label="xla:k8:persistent",
        )
        note("xla:k8:persistent", persist)
        if persist is not None and (best is None
                                    or persist["value"] > best["value"]):
            best = persist

    # the unrestricted-chain levers (ISSUE 13): the chained propose-
    # verify round (spec) and the device-guided-table chain (guided) —
    # the serving scheduler's shapes for the traffic classes that used
    # to force the per-burst host-sync path. Neither replaces the
    # headline (spec measures verified positions/s — a full-acceptance
    # ceiling; guided adds mask work the plain chain doesn't pay), so
    # they are logged per attempt, compared on the lever table, and only
    # the guided number may win the headline (it IS a decode
    # tokens/s measurement).
    remaining = total_budget - (_time.monotonic() - t0)
    if remaining > 360 and not os.environ.get("BENCH_SINGLE_STEP_ONLY"):
        persist_spec = _run_impl_subprocess(
            "xla", timeout_s=min(300.0, remaining - 240), burst=8,
            persistent=True, spec=True, label="xla:k8:persistent-spec",
        )
        note("xla:k8:persistent-spec", persist_spec)
    remaining = total_budget - (_time.monotonic() - t0)
    if remaining > 360 and not os.environ.get("BENCH_SINGLE_STEP_ONLY"):
        persist_guided = _run_impl_subprocess(
            "xla", timeout_s=min(300.0, remaining - 240), burst=8,
            persistent=True, guided=True,
            label="xla:k8:persistent-guided",
        )
        note("xla:k8:persistent-guided", persist_guided)
        if persist_guided is not None and (
                best is None or persist_guided["value"] > best["value"]):
            best = persist_guided

    # the long-context sequence-parallel prefill lever (xla:k8:sp-prefill;
    # docs/long_context.md): prefill tokens/s across the mesh vs the
    # single-chip ladder, one child per context length so a wedge at
    # 128k cannot eat the 32k number. A different metric family — the
    # per-ctx rows ride the attempt sidecar and the lever table, never
    # the decode headline.
    sp_ctxs = ((512, 1024) if os.environ.get("BENCH_SMOKE")
               else (32768, 131072))
    for sp_ctx in sp_ctxs:
        remaining = total_budget - (_time.monotonic() - t0)
        if remaining <= 300 or os.environ.get("BENCH_SINGLE_STEP_ONLY"):
            break
        sp_res = _run_sp_subprocess(
            sp_ctx, timeout_s=min(420.0, remaining - 180))
        note(f"xla:k8:sp-prefill:ctx{sp_ctx}", sp_res)

    # the paged SP ring-prefill KERNEL lever (xla:k8:sp-kernel;
    # docs/performance.md "Kernel campaign"): SP prefill tokens/s with
    # the Pallas page-walk prefix kernel vs the XLA gather route, one
    # child at one context. Rides the attempt sidecar and the lever
    # table, never the decode headline.
    remaining = total_budget - (_time.monotonic() - t0)
    if remaining > 300 and not os.environ.get("BENCH_SINGLE_STEP_ONLY"):
        sk_ctx = 512 if os.environ.get("BENCH_SMOKE") else 32768
        sk_res = _run_kernel_lever_subprocess(
            "xla:k8:sp-kernel", "run_sp_kernel",
            f"run_sp_kernel({sk_ctx})",
            timeout_s=min(420.0, remaining - 180), ctx=sk_ctx,
        )
        note("xla:k8:sp-kernel", sk_res)

    # the fused sampling-epilogue lever (xla:k8:fused-epilogue): the
    # decode tail as one Pallas dispatch vs the unfused XLA op ladder.
    remaining = total_budget - (_time.monotonic() - t0)
    if remaining > 150 and not os.environ.get("BENCH_SINGLE_STEP_ONLY"):
        fe_res = _run_kernel_lever_subprocess(
            "xla:k8:fused-epilogue", "run_fused_epilogue",
            "run_fused_epilogue()",
            timeout_s=min(240.0, remaining - 90),
        )
        note("xla:k8:fused-epilogue", fe_res)

    # the unified-transfer-plane payload lever (xla:k8:ici-pull;
    # docs/transfer_plane.md): KV block throughput of the ici
    # device-to-device path vs the tcp framing fallback. A different
    # metric family — it rides the attempt sidecar and the lever table,
    # never the decode headline.
    remaining = total_budget - (_time.monotonic() - t0)
    if remaining > 150 and not os.environ.get("BENCH_SINGLE_STEP_ONLY"):
        pull_res = _run_ici_pull_subprocess(
            timeout_s=min(240.0, remaining - 90))
        note("xla:k8:ici-pull", pull_res)

    remaining = total_budget - (_time.monotonic() - t0)
    if remaining > 240 and not os.environ.get("BENCH_XLA_ONLY"):
        import sys

        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from dynamo_tpu.ops.probe import probe_kernel

        # the bench workload is decode-only (run_once builds a single
        # S=1 step; ops/attention dispatches S==1 to the decode kernel,
        # never the flash-prefill one), so only the decode kernel needs
        # probing — in the dtype specialization this run will compile
        # (BENCH_KV=fp8 builds a distinct Mosaic program). Serving
        # engines probe their full kernel set in ModelRunner.warmup.
        decode_kind = (
            "decode_fp8" if os.environ.get("BENCH_KV") == "fp8"
            else "decode"
        )
        if probe_kernel(decode_kind, timeout_s=min(180.0, remaining - 120)):
            remaining = total_budget - (_time.monotonic() - t0)
            pallas = _run_impl_subprocess(
                "pallas", timeout_s=max(min(remaining - 120, 480), 60),
                burst=8, label="pallas:k8",
            )
            note("pallas:k8", pallas)
            if pallas is None:
                # the probe validates the bare kernel, not the scanned
                # program — if the burst wrapper is what failed, the
                # single-step Pallas attempt is still worth banking
                remaining = total_budget - (_time.monotonic() - t0)
                pallas = _run_impl_subprocess(
                    "pallas", timeout_s=max(remaining, 60),
                    label="pallas:k1",
                )
                note("pallas:k1", pallas)
            if pallas is not None and (
                best is None or pallas["value"] > best["value"]
            ):
                best = pallas
        else:
            print("pallas decode kernel probe failed; keeping the XLA "
                  "number", flush=True)

    if best is None:
        best = banked_fallback()
        _log_attempt({"label": "banked", "result": best})
    _log_attempt({"label": "winner", "result": best})
    print(json.dumps(best))


def banked_fallback(repo_root: str | None = None) -> dict:
    """Result to print when every live attempt failed.

    The driver-captured BENCH_r*.json is the record of truth; printing
    0.0 when the relay is wedged at capture time erases measurements the
    round actually made (this under-reported rounds 2 and 4). So the
    fallback's ``value`` IS the most recent number this same workload
    produced on live hardware — clearly annotated ``banked: true`` with
    its source file and measurement timestamp so nobody mistakes it for
    a fresh run. Only if no banked number exists does 0.0 appear.
    """
    import glob as _glob
    import os
    import re as _re

    best = {
        "metric": METRIC,
        "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
        "error": "all attempts failed or timed out (device/compile "
                 "service unreachable?)",
    }
    here = repo_root or os.path.dirname(os.path.abspath(__file__))

    def round_num(path: str) -> int:
        m = _re.search(r"_r(\d+)", os.path.basename(path))
        return int(m.group(1)) if m else -1

    candidates = sorted(
        _glob.glob(os.path.join(
            here, "examples", "llm", "benchmarks", "results",
            "bench_levers_r*.json")),
        key=round_num,
    )
    for path in reversed(candidates):
        try:
            with open(path) as f:
                recorded = json.load(f)
        except (OSError, ValueError):
            continue
        headline = recorded.get("headline")
        if recorded.get("metric") not in (None, METRIC):
            continue  # a different workload's bank is not this headline
        if headline and headline.get("tokens_per_s"):
            best["value"] = headline["tokens_per_s"]
            best["vs_baseline"] = headline.get("vs_baseline", 0.0)
            best["banked"] = True
            best["banked_from"] = {
                "file": os.path.relpath(path, here),
                "measured": recorded.get("measured_utc")
                or recorded.get("note", "")[:160],
                **headline,
            }
            break
    return best


if __name__ == "__main__":
    main()
