"""fp8 (float8_e4m3fn) paged-KV-cache serving.

The cache stores e4m3 and every consumer upcasts at the read: the XLA
gather path, both Pallas kernels (interpret mode here), and the engine
end-to-end. Reference analog: the GPU engines' kv_cache_dtype=fp8
serving lever (vLLM-class; SURVEY §2.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.models import llama
from dynamo_tpu.ops.attention import attention, scatter_kv_stacked

CFG = ModelConfig(
    vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=2,
    num_heads=4, num_kv_heads=2, head_dim=64, attention_impl="xla",
)


def _filled_caches(rng, layers, n, bs, kvh, d, dtype):
    vals = rng.standard_normal((layers, n, bs, kvh, d)).astype(np.float32)
    return jnp.asarray(vals, dtype), vals


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_fp8_cache_attention_close_to_fp32(impl):
    """Attention over an fp8 cache tracks the fp32-cache result within
    e4m3's ~6% elementwise error, on both dispatch paths (decode S=1
    and prefill S>1)."""
    rng = np.random.default_rng(0)
    layers, b, h, kvh, d, bs, w = 2, 4, 4, 2, 64, 16, 8
    n = b * w + 1
    kf8, kvals = _filled_caches(rng, layers, n, bs, kvh, d, jnp.float8_e4m3fn)
    vf8, vvals = _filled_caches(rng, layers, n, bs, kvh, d, jnp.float8_e4m3fn)
    k32 = jnp.asarray(kvals, jnp.float32)
    v32 = jnp.asarray(vvals, jnp.float32)
    bt = jnp.asarray(rng.permutation(n)[: b * w].reshape(b, w), jnp.int32)
    ctx = jnp.asarray([1, 17, 60, 128], jnp.int32)

    # decode (S = 1)
    q = jnp.asarray(rng.standard_normal((b, 1, h, d)), jnp.float32)
    pos = (ctx - 1)[:, None]
    ref = attention(q, k32, v32, bt, pos, ctx, impl="xla",
                    layer_idx=jnp.int32(1))
    got = attention(q, kf8, vf8, bt, pos, ctx, impl=impl, interpret=True,
                    layer_idx=jnp.int32(1))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=0.15, atol=0.15)

    # prefill (S > 1, affine positions)
    s = 16
    qp = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    base = jnp.zeros((b,), jnp.int32)
    posp = base[:, None] + jnp.arange(s)[None, :]
    ctxp = jnp.full((b,), s, jnp.int32)
    ref = attention(qp, k32, v32, bt, posp, ctxp, impl="xla",
                    layer_idx=jnp.int32(0))
    got = attention(qp, kf8, vf8, bt, posp, ctxp, impl=impl, interpret=True,
                    layer_idx=jnp.int32(0))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=0.15, atol=0.15)


def test_scatter_casts_to_cache_dtype():
    """Writes into an fp8 cache quantize at the scatter; the stored
    values roundtrip within e4m3 error."""
    rng = np.random.default_rng(1)
    layers, n, bs, kvh, d = 2, 8, 8, 2, 64
    k_all = jnp.zeros((layers, n, bs, kvh, d), jnp.float8_e4m3fn)
    v_all = jnp.zeros((layers, n, bs, kvh, d), jnp.float8_e4m3fn)
    new_k = jnp.asarray(rng.standard_normal((2, 4, kvh, d)), jnp.float32)
    new_v = jnp.asarray(rng.standard_normal((2, 4, kvh, d)), jnp.float32)
    slots = jnp.asarray([[0, 1, 2, 3], [8, 9, 10, -1]], jnp.int32)
    k_all, v_all = scatter_kv_stacked(k_all, v_all, new_k, new_v, slots,
                                      jnp.int32(1))
    assert k_all.dtype == jnp.float8_e4m3fn
    stored = np.asarray(k_all[1].reshape(n * bs, kvh, d)[0], np.float32)
    np.testing.assert_allclose(stored, np.asarray(new_k[0, 0]),
                               rtol=0.07, atol=0.02)
    # dropped sentinel row untouched
    assert float(jnp.sum(jnp.abs(
        k_all[1].reshape(n * bs, kvh, d)[11].astype(jnp.float32)))) == 0.0


def test_engine_serves_with_fp8_cache(tmp_path):
    """End-to-end: the engine decodes greedily with kv_cache_dtype=fp8;
    the capacity bookkeeping is unchanged and the stream finishes."""
    import asyncio

    from dynamo_tpu.engine.serving import JaxServingEngine
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    params = llama.init_params(CFG, jax.random.PRNGKey(0), jnp.float32)

    async def serve(kv_dtype):
        econfig = EngineConfig(
            model=CFG, max_batch_size=2, max_model_len=64, kv_block_size=8,
            num_kv_blocks=32, dtype="float32", kv_cache_dtype=kv_dtype,
            prefill_buckets=[16], allow_random_weights=True,
        )
        mdc = ModelDeploymentCard(display_name="t", slug="t")
        engine = await JaxServingEngine.create(
            mdc, engine_config=econfig, params=params, warmup=False)
        req = PreprocessedRequest(
            token_ids=[1, 5, 9, 13, 2],
            stop_conditions=StopConditions(max_tokens=8, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
        )
        toks = []
        async for out in engine.generate(Context(req)):
            toks.extend(out["token_ids"])
        await engine.close()
        return toks

    ref = asyncio.run(serve("auto"))
    got = asyncio.run(serve("fp8"))
    assert len(got) == len(ref) == 8
    assert all(0 <= t < CFG.vocab_size for t in got)
    # tiny random model: fp8 KV error may flip argmaxes even on the
    # first step when the CPU backend's e4m3 rounding lands a near-tie
    # differently, so the strict first-token pin only holds on a real
    # accelerator (same caveat as the MLA serving test below — the
    # chip path keeps the strict check)
    import jax as _jax

    if _jax.default_backend() != "cpu":
        assert got[0] == ref[0]


def test_fp8_mla_serves_and_tracks_fp32():
    """fp8 latent cache for MLA (the round-4 guard did not survive
    measurement: teacher-forced e4m3 noise matches the GQA fp8 path —
    examples/llm/benchmarks/results/fp8_mla_accuracy.json). The engine
    serves an MLA model with kv_cache_dtype=fp8 and the first greedy
    step matches the fp32-cache engine; the MLA decode kernel's fp8
    specialization agrees in interpret mode."""
    from dynamo_tpu.engine.model_runner import ModelRunner
    from dynamo_tpu.models import deepseek

    mla = ModelConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, num_kv_heads=4, head_dim=16, kv_lora_rank=16,
        qk_rope_head_dim=8, qk_nope_head_dim=12, v_head_dim=12,
        attention_impl="xla",
    )
    import jax

    params = deepseek.init_params(mla, jax.random.PRNGKey(3), jnp.float32)

    def first_step(kv_dtype):
        runner = ModelRunner(EngineConfig(
            model=mla, max_batch_size=2, max_model_len=32, kv_block_size=8,
            num_kv_blocks=16, dtype="float32", kv_cache_dtype=kv_dtype,
            prefill_buckets=[16], allow_random_weights=True,
        ), params=params)
        b, s, bs = 2, 8, 8
        rng = np.random.default_rng(4)
        tokens = rng.integers(0, 128, (b, s)).astype(np.int32)
        positions = np.tile(np.arange(s, dtype=np.int32), (b, 1))
        btab = np.zeros((b, runner.config.blocks_per_seq), np.int32)
        for i in range(b):
            btab[i, 0] = i
        slots = btab[:, :1] * bs + positions
        out, *_ = runner.step(
            tokens, positions, btab, slots, np.full(b, s, np.int32),
            np.full(b, s - 1, np.int32), np.zeros(b, np.float32),
            np.zeros(b, np.int32), np.ones(b, np.float32),
            jax.random.PRNGKey(5),
        )
        return np.asarray(out)

    got8, got32 = first_step("fp8"), first_step("auto")
    # tiny random model: e4m3 noise may legitimately flip an argmax with
    # near-tied logits (same caveat as the GQA serving test above), so
    # the engine check is serve-and-valid; the kernel check below pins
    # the numerics against the fp32 dense formulation
    assert got8.shape == got32.shape and (got8 >= 0).all() and (got8 < 128).all()

    # the fp8 MLA decode kernel (interpret mode) tracks the fp32 dense
    # formulation within e4m3 error
    rng = np.random.default_rng(6)
    l, n, bs_, r, rd, b, w, h = 2, 9, 8, 128, 64, 2, 4, 4
    cvals = rng.standard_normal((l, n, bs_, 1, r)).astype(np.float32)
    krvals = rng.standard_normal((l, n, bs_, 1, rd)).astype(np.float32)
    ql = jnp.asarray(rng.standard_normal((b, 1, h, r)), jnp.float32)
    qr = jnp.asarray(rng.standard_normal((b, 1, h, rd)), jnp.float32)
    bt = jnp.asarray(rng.permutation(n)[: b * w].reshape(b, w), jnp.int32)
    ctx = jnp.asarray([9, 25], jnp.int32)
    pos = (ctx - 1)[:, None]
    scale = float(r) ** -0.5

    ref = deepseek.mla_attention(
        ql, qr, jnp.asarray(cvals), jnp.asarray(krvals), jnp.int32(1),
        bt, pos, ctx, scale, impl="xla")
    from dynamo_tpu.ops.pallas_decode import mla_paged_decode_attention

    got = mla_paged_decode_attention(
        ql, qr, jnp.asarray(cvals, jnp.float8_e4m3fn),
        jnp.asarray(krvals, jnp.float8_e4m3fn), bt, ctx, jnp.int32(1),
        scale=scale, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=0.2, atol=0.2)


def test_fp8_cache_composes_with_host_offload():
    """The host KV tier stores whatever the device blocks hold — fp8
    blocks offload/restore unchanged (half the host RAM per block)."""
    import asyncio

    from dynamo_tpu.engine.serving import JaxServingEngine
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    params = llama.init_params(CFG, jax.random.PRNGKey(0), jnp.float32)

    async def serve():
        econfig = EngineConfig(
            model=CFG, max_batch_size=2, max_model_len=64, kv_block_size=8,
            num_kv_blocks=8, host_kv_blocks=16, dtype="float32",
            kv_cache_dtype="fp8", prefill_buckets=[16],
            allow_random_weights=True,
        )
        mdc = ModelDeploymentCard(display_name="t", slug="t")
        engine = await JaxServingEngine.create(
            mdc, engine_config=econfig, params=params, warmup=False)
        outs = []
        # several sequential requests on a tiny block pool force
        # eviction -> offload -> prefix-hit restore
        for i in range(3):
            req = PreprocessedRequest(
                token_ids=[1, 5, 9, 13, 2 + i],
                stop_conditions=StopConditions(max_tokens=4, ignore_eos=True),
                sampling_options=SamplingOptions(temperature=0.0),
            )
            toks = []
            async for out in engine.generate(Context(req)):
                toks.extend(out["token_ids"])
            outs.append(toks)
        await engine.close()
        return outs

    outs = asyncio.run(serve())
    assert all(len(t) == 4 for t in outs)
