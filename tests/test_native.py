"""Native C++ core: hash parity, differential radix-tree testing, C ABI.

The pure-Python implementations (tokens.py, kv_router/indexer.py) are the
executable spec; the C++ hot paths must match them bit-for-bit.
"""

import random

import pytest
import xxhash

from dynamo_tpu import native
from dynamo_tpu.kv_router.indexer import KvIndexer, OverlapScores, RadixTree
from dynamo_tpu.kv_router.protocols import KvCacheRemoved, KvCacheStored, RouterEvent
from dynamo_tpu.tokens import chain_hash, compute_block_hash, compute_block_hashes

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native C++ core not built (no toolchain?)"
)


def test_xxh64_matches_python_xxhash():
    rng = random.Random(42)
    for _ in range(200):
        data = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 300)))
        seed = rng.randrange(2**64)
        assert native.xxh64(data, seed) == xxhash.xxh64_intdigest(data, seed=seed)


def test_native_block_hashes_match_python():
    rng = random.Random(7)
    for _ in range(50):
        n = rng.randrange(0, 100)
        tokens = [rng.randrange(2**31) for _ in range(n)]
        bs = rng.choice([1, 4, 16, 32])
        seed = rng.randrange(2**63)
        got = native.compute_block_hashes(tokens, bs, seed)
        # hand-rolled python chain (avoid the dispatching wrapper)
        expect = []
        parent = None
        for i in range(n // bs):
            bh = compute_block_hash(tokens[i * bs : (i + 1) * bs], seed)
            parent = chain_hash(parent, bh)
            expect.append(parent)
        assert got == expect


def test_tokens_module_dispatches_to_native():
    # the public API must give the same answer regardless of dispatch
    tokens = list(range(64))
    from dynamo_tpu import tokens as tokmod

    via_module = compute_block_hashes(tokens, 16)
    saved = tokmod._native_hashes
    try:
        tokmod._native_hashes = None
        via_python = compute_block_hashes(tokens, 16)
    finally:
        tokmod._native_hashes = saved
    assert via_module == via_python


def _random_events(rng, n_workers=4, n_events=300, block_size=4):
    """Random stored/removed event stream + chains for querying."""
    chains = []  # list of hash-chains built from random token seqs
    for _ in range(12):
        toks = [rng.randrange(1000) for _ in range(block_size * rng.randrange(1, 9))]
        chains.append(compute_block_hashes(toks, block_size))
    events = []
    for eid in range(n_events):
        worker = f"w{rng.randrange(n_workers)}"
        chain = rng.choice(chains)
        if rng.random() < 0.7:
            # store a prefix or suffix segment of a chain
            start = rng.randrange(len(chain))
            end = rng.randrange(start, len(chain)) + 1
            parent = chain[start - 1] if start > 0 else None
            events.append(RouterEvent(
                worker_id=worker, event_id=eid,
                stored=KvCacheStored(block_hashes=chain[start:end], parent_hash=parent),
            ))
        else:
            k = rng.randrange(1, len(chain) + 1)
            events.append(RouterEvent(
                worker_id=worker, event_id=eid,
                removed=KvCacheRemoved(block_hashes=rng.sample(chain, k)),
            ))
    return chains, events


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_native_tree_differential(seed):
    rng = random.Random(seed)
    chains, events = _random_events(rng)
    py = RadixTree()
    cpp = native.NativeRadixTree()
    for ev in events:
        py.apply_event(ev)
        if ev.stored is not None:
            cpp.apply_stored(ev.worker_id, ev.stored.parent_hash, ev.stored.block_hashes)
        if ev.removed is not None:
            cpp.apply_removed(ev.worker_id, ev.removed.block_hashes)
    assert len(py) == len(cpp)
    for chain in chains:
        for qlen in (1, len(chain) // 2 + 1, len(chain)):
            for early in (False, True):
                expect = py.find_matches(chain[:qlen], early_exit=early)
                scores, freqs = cpp.find_matches(chain[:qlen], early_exit=early)
                assert scores == expect.scores, (qlen, early)
                assert freqs == expect.frequencies, (qlen, early)
    # worker removal must also agree
    py.remove_worker("w0")
    cpp.remove_worker("w0")
    assert len(py) == len(cpp)
    for chain in chains:
        expect = py.find_matches(chain)
        scores, _ = cpp.find_matches(chain)
        assert scores == expect.scores


def test_native_tree_expiration_parity():
    import time

    chain = compute_block_hashes(list(range(16)), 4)
    py = RadixTree(expiration_s=0.05)
    cpp = native.NativeRadixTree(expiration_s=0.05)
    py.apply_event(RouterEvent(worker_id="w", stored=KvCacheStored(chain)))
    cpp.apply_stored("w", None, chain)
    assert py.find_matches(chain).scores == {"w": 4}
    assert cpp.find_matches(chain)[0] == {"w": 4}
    time.sleep(0.1)
    assert py.find_matches(chain).scores == {}
    assert cpp.find_matches(chain)[0] == {}
    # clear_expired prunes leaf-first the same way
    assert py.clear_expired() == cpp.clear_expired()
    assert len(py) == len(cpp)


def test_native_tree_early_exit_extends_single_holder():
    chain = compute_block_hashes(list(range(32)), 4)
    cpp = native.NativeRadixTree()
    cpp.apply_stored("solo", None, chain)
    scores, freqs = cpp.find_matches(chain, early_exit=True)
    assert scores == {"solo": len(chain)}
    assert len(freqs) == len(chain)


def test_kv_indexer_uses_native_by_default():
    idx = KvIndexer(block_size=4)
    from dynamo_tpu.kv_router.indexer import _NativeTreeAdapter

    assert isinstance(idx.tree, _NativeTreeAdapter)
    chain = compute_block_hashes(list(range(16)), 4)
    idx.apply_event(RouterEvent(worker_id="a", stored=KvCacheStored(chain)))
    out = idx.find_matches_for_request(list(range(16)))
    assert isinstance(out, OverlapScores)
    assert out.scores == {"a": 4}
    # forced python still works
    py_idx = KvIndexer(block_size=4, use_native=False)
    py_idx.apply_event(RouterEvent(worker_id="a", stored=KvCacheStored(chain)))
    assert py_idx.find_matches_for_request(list(range(16))).scores == {"a": 4}


class TestCApi:
    def test_publish_roundtrip(self):
        capi = native.CApi()
        assert capi.init("ns", "comp", "worker-7", kv_block_size=4) == 0
        try:
            got = []
            capi.set_sink(got.append)
            tokens = list(range(12))
            assert capi.publish_stored(1, tokens) == 0
            assert len(got) == 1
            ev = RouterEvent.from_wire(got[0])
            assert ev.worker_id == "worker-7"
            assert ev.event_id == 1
            # hashes computed inside the C ABI must match the Python scheme
            assert ev.stored.block_hashes == compute_block_hashes(tokens, 4)
            assert ev.stored.parent_hash is None

            # chained publish from an explicit parent
            parent = ev.stored.block_hashes[-1]
            assert capi.publish_stored(2, list(range(12, 16)), parent_hash=parent) == 0
            ev2 = RouterEvent.from_wire(got[1])
            full = compute_block_hashes(list(range(16)), 4)
            assert ev2.stored.block_hashes == [full[-1]]
            assert ev2.stored.parent_hash == parent

            assert capi.publish_removed(3, [1, 2, 3]) == 0
            ev3 = RouterEvent.from_wire(got[2])
            assert ev3.removed.block_hashes == [1, 2, 3]
        finally:
            capi.shutdown()

    def test_worker_id_json_escaped(self):
        capi = native.CApi()
        assert capi.init("ns", "comp", 'w"\\evil\n', kv_block_size=4) == 0
        try:
            got = []
            capi.set_sink(got.append)
            assert capi.publish_removed(1, [7]) == 0
            assert got[0]["worker_id"] == 'w"\\evil\n'
        finally:
            capi.shutdown()

    def test_drain_grows_buffer_for_oversized_events(self):
        capi = native.CApi()
        assert capi.init("ns", "comp", "w0", kv_block_size=4) == 0
        try:
            big = list(range(5000))
            assert capi.publish_removed(1, big) == 0
            ev = capi.drain(cap=64)  # far smaller than the event
            assert ev is not None and ev["removed"]["block_hashes"] == big
            assert capi.drain(cap=64) is None
        finally:
            capi.shutdown()

    def test_drain_mode_and_errors(self):
        capi = native.CApi()
        # not initialized → status 1
        assert capi.publish_removed(1, [5]) == 1
        assert capi.init("ns", "comp", "w0", kv_block_size=4) == 0
        try:
            assert capi.init("ns", "comp", "w0", kv_block_size=4) == 1  # double init
            assert capi.publish_stored(9, list(range(8))) == 0
            ev = capi.drain()
            assert ev is not None and ev["event_id"] == 9
            assert capi.drain() is None
            # partial blocks only → bad args
            assert capi.publish_stored(10, [1, 2]) == 2
        finally:
            capi.shutdown()
