"""Incident recorder (telemetry/incidents.py): trigger-driven capture
bundles — rate-limit units, bundle anatomy, listener fan-out hardening,
the profiler-lock satellite, flightdump --incident, and the chaos e2e.

The acceptance bar (ISSUE 10): an injected ``DYN_FAULT=decode_burst_
hang`` wedge auto-produces EXACTLY ONE bundle (cooldown pinned) whose
flight artifact, metric-history window, and stitched trace all reference
the wedged request — while PR 8's recovery still drains, migrates, and
respawns the engine underneath it.
"""

import asyncio
import json
import os
import sys

import aiohttp
import pytest

from dynamo_tpu.engine.scheduler import Scheduler
from dynamo_tpu.http.service import HttpService, ModelManager
from dynamo_tpu.recovery import (
    MigrationServer,
    MigrationSink,
    RecoveryConfig,
    RecoveryController,
)
from dynamo_tpu.telemetry.flight import FlightRecorder
from dynamo_tpu.telemetry.history import LocalHistorySampler, MetricHistory
from dynamo_tpu.telemetry.incidents import (
    IncidentConfig,
    IncidentRecorder,
    late_compile_probe,
    load_bundle_dir,
    slo_probe,
)
from dynamo_tpu.telemetry.tracing import TraceRecorder
from dynamo_tpu.telemetry.watchdog import StallWatchdog
from dynamo_tpu.utils import faults

from test_recovery import MigRunner, _baseline, _collect, _config, _request

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _recorder(tmp_path=None, clk=None, history=None, **cfg):
    """An IncidentRecorder with a PRIVATE flight ring (the global one is
    shared across the whole test process) and settle_s=0 by default."""
    cfg.setdefault("settle_s", 0.0)
    if tmp_path is not None:
        cfg.setdefault("out_dir", str(tmp_path))
    return IncidentRecorder(
        IncidentConfig(**cfg),
        history=history,
        flight=FlightRecorder(capacity=64),
        clock=clk or Clock(),
    )


# --------------------------------------------------------------------------
# trigger rate limiting: cooldown, global min interval, (reason, request)
# dedup — one wedge, one bundle
# --------------------------------------------------------------------------


async def test_trigger_cooldown_min_interval_and_dedup():
    clk = Clock()
    rec = _recorder(clk=clk, cooldown_s=10.0, min_interval_s=5.0,
                    dedup_s=100.0)
    try:
        assert rec.trigger("decode_stall") is True
        # same reason inside the cooldown: suppressed
        assert rec.trigger("decode_stall") is False
        # DIFFERENT reason inside the global min interval: the same
        # wedge trips the watchdog AND engages recovery within seconds —
        # that must fold into ONE bundle
        clk.t += 2.0
        assert rec.trigger("recovery_drain") is False
        # past the global floor, a different reason fires
        clk.t += 4.0
        assert rec.trigger("recovery_drain") is True
        # per-reason cooldown outlives the global floor
        clk.t += 3.0  # 9s after the first decode_stall: still cooling
        assert rec.trigger("decode_stall") is False
        clk.t += 6.0
        assert rec.trigger("decode_stall") is True
        # (reason, request) dedup outlives the per-reason cooldown
        clk.t += 20.0
        assert rec.trigger("slo_floor", request_id="req-1") is True
        clk.t += 15.0  # > cooldown_s, < dedup_s
        assert rec.trigger("slo_floor", request_id="req-1") is False
        assert rec.trigger("slo_floor", request_id="req-2") is True
    finally:
        await rec.stop()
    assert rec.captures == 5
    assert rec.suppressed == 4
    text = rec.registry.render()
    assert 'dynamo_incidents_total{reason="decode_stall"} 2' in text
    assert 'dynamo_incidents_suppressed_total{reason="decode_stall"} 2' in text
    # every suppression is visible in the flight ring too
    kinds = [e["kind"] for e in rec.flight.snapshot()]
    assert kinds.count("incident.suppressed") == 4
    assert kinds.count("incident.captured") == 5


# --------------------------------------------------------------------------
# bundle anatomy: manifest + flight + history + traces on disk
# --------------------------------------------------------------------------


async def _write_one_bundle_async(tmp_path, request_id="req-x"):
    """Capture one bundle with every payload populated; returns the
    recorder and the bundle's manifest (with the on-disk path)."""
    hist = MetricHistory(window_s=600.0)
    for i in range(5):
        hist.observe("dynamo_kv_block_usage_ratio", {}, i / 10)
        hist.observe("dynamo_watchdog_trips_total", {"reason": "x"},
                     float(i), kind="counter")
    rec = _recorder(tmp_path, history=hist)
    rec.flight.record("scheduler.admission", request_id=request_id, slot=0)
    rec.flight.record("scheduler.burst_dispatch", rows=1,
                      requests=[request_id])
    tr = TraceRecorder(capacity=8)
    tr.record(request_id, "m", "completed",
              [("ingress", 100.0), ("first_token", 100.1)], end=100.3)
    assert rec.trigger("manual_test", request_id=request_id,
                       stalled_for_s=1.25) is True
    await rec.stop()
    del tr  # recorder registry holds weak refs; keep it alive until here
    assert rec.captures == 1
    return rec, rec.bundles[0]


def _write_one_bundle(tmp_path, request_id="req-x"):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(
            _write_one_bundle_async(tmp_path, request_id))
    finally:
        loop.close()


def test_bundle_anatomy_on_disk(tmp_path):
    rec, manifest = _write_one_bundle(tmp_path)
    path = manifest["path"]
    assert path and os.path.isdir(path)
    assert sorted(os.listdir(path)) == [
        "flight.json", "history.json", "manifest.json", "traces.json"]
    assert manifest["reason"] == "manual_test"
    assert manifest["request_id"] == "req-x"
    assert manifest["info"] == {"stalled_for_s": 1.25}
    assert manifest["pid"] == os.getpid()
    bundle = load_bundle_dir(path)
    # flight: the private ring's events, request-correlated
    rids = {e.get("request_id") for e in bundle["flight"]["events"]}
    assert "req-x" in rids
    # history: the curve INTO the incident, counters marked as such
    series = {s["name"]: s for s in bundle["history"]["series"]}
    assert len(series["dynamo_kv_block_usage_ratio"]["points"]) == 5
    assert series["dynamo_watchdog_trips_total"]["kind"] == "counter"
    # traces: the affected request's stitched trace rode along
    assert [t["request_id"] for t in bundle["traces"]] == ["req-x"]
    # listing surfaces the complete bundle
    listed = rec.list_bundles()
    assert [b["bundle"] for b in listed] == [manifest["bundle"]]
    assert rec.load_bundle(manifest["bundle"])["manifest"]["reason"] == \
        "manual_test"


async def test_bundle_prune_keeps_newest_max_bundles(tmp_path):
    clk = Clock()
    rec = _recorder(tmp_path, clk=clk, cooldown_s=0.0, min_interval_s=0.0,
                    max_bundles=2)
    try:
        for i in range(4):
            clk.t += 1.0
            assert rec.trigger(f"reason_{i}") is True
            # captures write on the executor: let each land so the
            # prune sees a stable, ordered bundle set
            await asyncio.gather(*list(rec._tasks))
    finally:
        await rec.stop()
    assert rec.captures == 4
    dirs = sorted(d for d in os.listdir(tmp_path)
                  if d.startswith("incident-"))
    assert len(dirs) == 2
    # completion time orders bundles: the NEWEST two survive
    reasons = {load_bundle_dir(os.path.join(tmp_path, d))["manifest"]["reason"]
               for d in dirs}
    assert reasons == {"reason_2", "reason_3"}


def test_bundle_prune_orders_by_time_not_name(tmp_path):
    """Review pin: bundle names embed a pid, so a lexicographic sort
    compares pid digits first — with processes sharing DYN_INCIDENT_DIR
    it would prune a worker's JUST-captured wedge evidence while keeping
    a frontend's stale bundles. Prune and listing must order by
    completion time (manifest mtime), never by name."""
    stale_name = "incident-3041-999999-frontend_stale"
    fresh_name = "incident-29876-111-worker_fresh"  # sorts FIRST by name
    for name, age_s in ((stale_name, 3600), (fresh_name, 0)):
        d = tmp_path / name
        d.mkdir()
        (d / "manifest.json").write_text(json.dumps(
            {"reason": name.rsplit("-", 1)[-1], "bundle": name}))
        mtime = os.path.getmtime(d / "manifest.json") - age_s
        os.utime(d / "manifest.json", (mtime, mtime))
        os.utime(d, (mtime, mtime))
    rec = _recorder(tmp_path, max_bundles=1)
    rec._prune_bundles(str(tmp_path))
    survivors = [d for d in os.listdir(tmp_path) if d.startswith("incident-")]
    assert survivors == [fresh_name]
    # listing shares the chronological ordering (oldest first)
    (tmp_path / stale_name).mkdir()
    (tmp_path / stale_name / "manifest.json").write_text(json.dumps(
        {"reason": "frontend_stale", "bundle": stale_name}))
    old = os.path.getmtime(tmp_path / stale_name / "manifest.json") - 3600
    os.utime(tmp_path / stale_name / "manifest.json", (old, old))
    assert [b["bundle"] for b in rec.list_bundles()] == \
        [stale_name, fresh_name]


# --------------------------------------------------------------------------
# listener fan-out hardening: one throwing subscriber must not starve
# the rest — in EITHER direction (satellite)
# --------------------------------------------------------------------------


def _watchdog():
    return StallWatchdog(probe=lambda: {"queue_depth": 0, "active": 0},
                         flight=FlightRecorder(), interval_s=0.02,
                         stall_s=0.15)


async def test_watchdog_trip_fanout_survives_throwing_listener():
    """Incident capture must still fire when an earlier trip listener
    (e.g. the RecoveryController's handler) throws — and a later one
    must survive the incident listener throwing. Pin both orders."""
    for bad_first in (True, False):
        wd = _watchdog()
        seen = []

        def bad(info):
            raise RuntimeError("recovery handler exploded")

        def good(info):
            seen.append(info["reason"])

        if bad_first:
            wd.add_trip_listener(bad)
            wd.add_trip_listener(good)
        else:
            wd.add_trip_listener(good)
            wd.add_trip_listener(bad)
        await wd.trip("decode_stall", {"queue_depth": 1}, 1.0)
        assert seen == ["decode_stall"], f"bad_first={bad_first}"


async def test_recovery_drain_fanout_survives_throwing_listener():
    """A throwing drain listener must not prevent the remaining
    listeners NOR the drain itself (recovery > evidence)."""
    config = _config()
    sched = Scheduler(MigRunner(config), config, flight=FlightRecorder())
    sched.start()
    seen = []
    controller = RecoveryController(
        engine_id="e", scheduler=sched, runner=None, watchdog=None,
        peers=lambda: [], config=RecoveryConfig(drain_grace_s=0.01),
        flight=sched.flight,
    )
    controller.add_drain_listener(
        lambda info: (_ for _ in ()).throw(RuntimeError("boom")))
    controller.add_drain_listener(lambda info: seen.append(info))
    try:
        summary = await controller.drain(hard=True, reason="unit_fault")
        assert seen and seen[0]["reason"] == "unit_fault"
        assert seen[0]["hard"] is True
        assert summary["migrated"] == 0 and summary["failed"] == 0
    finally:
        await controller.close()
        await sched.stop()


async def test_watch_recovery_ignores_admin_drains():
    """Rolling updates are operator-intended: the admin drain edge must
    not produce an incident bundle."""
    rec = _recorder()

    class FakeController:
        def add_drain_listener(self, fn):
            self.fn = fn

    ctl = FakeController()
    rec.watch_recovery(ctl)
    try:
        ctl.fn({"engine": "e", "reason": "admin", "hard": False})
        assert rec.captures == 0 and not rec._tasks
        ctl.fn({"engine": "e", "reason": "decode_stall", "hard": True})
        await rec.stop()
        assert rec.captures == 1
        assert rec.bundles[0]["reason"] == "recovery_drain"
        assert rec.bundles[0]["info"]["reason_detail"] == "decode_stall"
    finally:
        await rec.stop()


# --------------------------------------------------------------------------
# edge probes: SLO floor + late-compile burst
# --------------------------------------------------------------------------


class FakeSlo:
    def __init__(self, attainment, n):
        self.attainment, self.n = attainment, n

    def snapshot(self):
        return ({"slo.attainment": self.attainment}
                if self.attainment is not None else {})

    def window_count(self):
        return self.n


def test_slo_probe_gates_on_floor_and_window_size():
    tracker = FakeSlo(0.5, 10)
    probe = slo_probe(tracker, floor=0.9, min_requests=5)
    fired = probe()
    assert fired["reason"] == "slo_floor"
    assert fired["attainment"] == 0.5
    assert fired["window_requests"] == 10
    # a 1-request blip breaching the floor is noise, not an incident
    tracker.n = 2
    assert probe() is None
    tracker.n, tracker.attainment = 10, 0.95
    assert probe() is None
    tracker.attainment = None  # blind window (no judged requests)
    assert probe() is None


def test_late_compile_probe_needs_burst_within_window():
    clk = Clock()

    class FakeCompiles:
        late_compiles = 0

    compiles = FakeCompiles()
    probe = late_compile_probe(compiles, burst=3, window_s=60.0, clock=clk)
    assert probe() is None
    compiles.late_compiles = 2  # two late compiles: below the burst bar
    assert probe() is None
    clk.t += 10
    compiles.late_compiles = 3
    fired = probe()
    assert fired["reason"] == "late_compile_burst"
    assert fired["late_compiles_in_window"] == 3
    # the window slides: old marks expire and the probe re-arms
    clk.t += 120
    assert probe() is None


async def test_probe_loop_is_edge_triggered_and_rearms():
    clk = Clock()
    rec = _recorder(clk=clk, cooldown_s=1.0, min_interval_s=0.0)
    state = {"degraded": False}
    rec.add_probe(lambda: ({"reason": "slo_floor", "attainment": 0.4}
                           if state["degraded"] else None))
    rec.start(probe_interval_s=0.02)
    try:
        state["degraded"] = True
        for _ in range(100):
            if rec.captures >= 1:
                break
            await asyncio.sleep(0.02)
        assert rec.captures == 1
        # STILL degraded: level-hold must not re-fire (edge, not level)
        await asyncio.sleep(0.1)
        assert rec.captures == 1
        # clear → re-arm → next breach fires again (past the cooldown)
        state["degraded"] = False
        await asyncio.sleep(0.1)
        clk.t += 5.0
        state["degraded"] = True
        for _ in range(100):
            if rec.captures >= 2:
                break
            await asyncio.sleep(0.02)
        assert rec.captures == 2
    finally:
        await rec.stop()


# --------------------------------------------------------------------------
# satellite: jax.profiler.trace is not reentrant — the process-wide
# capture lock turns a concurrent capture into a clean refusal
# --------------------------------------------------------------------------


def test_capture_trace_refuses_while_lock_held(tmp_path):
    from dynamo_tpu.utils import profiling

    assert profiling._capture_lock.acquire(blocking=False)
    try:
        with pytest.raises(profiling.CaptureBusyError):
            profiling.capture_trace(str(tmp_path), 0.0)
    finally:
        profiling._capture_lock.release()
    # the loser must not have leaked the lock state: a fresh capture
    # works immediately after the holder releases
    made = profiling.capture_trace(str(tmp_path), 0.0)
    assert os.path.isdir(made)


async def test_debug_profile_409_when_incident_capture_holds_lock(tmp_path):
    """The HTTP endpoint's asyncio lock only serializes ITS callers; a
    capture from another path (an incident bundle's profile window) holds
    the process-wide lock — the endpoint must 409, not crash."""
    from dynamo_tpu.utils import profiling

    service = HttpService(ModelManager(), host="127.0.0.1", port=0,
                          profile_dir=str(tmp_path))
    await service.start()
    assert profiling._capture_lock.acquire(blocking=False)
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(
                    f"http://127.0.0.1:{service.port}/debug/profile"
                    f"?seconds=0.01") as r:
                assert r.status == 409
                body = await r.json()
        assert "capture" in body["error"]
    finally:
        profiling._capture_lock.release()
        await service.stop()


async def test_incident_profile_lands_inside_bundle(tmp_path):
    """Review pin: the profiler window must capture INTO the bundle's
    profile/ dir (docs: bundle anatomy) — not as an unpruned sibling in
    the incident dir that outlives every bundle and eats the volume."""
    rec = _recorder(tmp_path, profile_s=0.01)
    assert rec.trigger("manual_test") is True
    await rec.stop()
    assert rec.captures == 1
    manifest = rec.bundles[0]
    bundle = manifest["path"]
    trace_dir = manifest["profile"]["trace_dir"]
    assert os.path.isdir(trace_dir)
    assert os.path.dirname(trace_dir) == os.path.join(bundle, "profile")
    assert "profile/" in manifest["files"]
    # nothing leaked beside the bundle in the incident dir, and pruning
    # the bundle takes the capture with it
    assert [d for d in os.listdir(tmp_path)
            if not d.startswith("incident-")] == []
    rec.config.max_bundles = 0
    rec._prune_bundles(str(tmp_path))
    assert os.listdir(tmp_path) == []


async def test_incident_profile_skips_cleanly_when_capture_in_flight(
        tmp_path):
    from dynamo_tpu.utils import profiling

    rec = _recorder(tmp_path, profile_s=0.1)
    assert profiling._capture_lock.acquire(blocking=False)
    try:
        assert rec.trigger("manual_test") is True
        await rec.stop()
    finally:
        profiling._capture_lock.release()
    assert rec.captures == 1  # the bundle still landed, minus the profile
    assert rec.bundles[0]["profile"] == {
        "skipped": "another profiler capture is in flight"}


# --------------------------------------------------------------------------
# GET /debug/incidents: list + fetch
# --------------------------------------------------------------------------


async def test_debug_incidents_endpoint_lists_and_fetches(tmp_path):
    rec, manifest = await _write_one_bundle_async(tmp_path)
    service = HttpService(ModelManager(), host="127.0.0.1", port=0,
                          incidents=rec)
    await service.start()
    try:
        async with aiohttp.ClientSession() as s:
            base = f"http://127.0.0.1:{service.port}"
            async with s.get(f"{base}/debug/incidents") as r:
                assert r.status == 200
                body = await r.json()
            assert body["dir"] == str(tmp_path)
            assert [b["bundle"] for b in body["bundles"]] == \
                [manifest["bundle"]]
            async with s.get(f"{base}/debug/incidents"
                             f"?id={manifest['bundle']}") as r:
                assert r.status == 200
                bundle = await r.json()
            assert bundle["manifest"]["reason"] == "manual_test"
            assert bundle["traces"][0]["request_id"] == "req-x"
            async with s.get(f"{base}/debug/incidents?id=nope") as r:
                assert r.status == 404
    finally:
        await service.stop()


# --------------------------------------------------------------------------
# satellite: scripts/flightdump.py --incident renders a bundle end to end
# --------------------------------------------------------------------------


def test_flightdump_incident_renders_bundle(tmp_path, capsys):
    _, manifest = _write_one_bundle(tmp_path)
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    import flightdump

    rc = flightdump.main(["flightdump", "--incident", manifest["path"]])
    out = capsys.readouterr().out
    assert rc == 0
    # trigger header + flight event table + history sparklines + trace
    assert "reason=manual_test" in out
    assert "request=req-x" in out
    assert "stalled_for_s=1.25" in out
    assert "scheduler.burst_dispatch" in out
    assert "--- metric history" in out
    assert "dynamo_kv_block_usage_ratio" in out
    assert any(c in out for c in flightdump.SPARK_BLOCKS)
    assert "--- stitched trace req-x ---" in out


def test_flightdump_incident_exit_2_on_unreadable(tmp_path, capsys):
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    import flightdump

    assert flightdump.main(
        ["flightdump", "--incident", str(tmp_path / "nope")]) == 2
    # a dir with a corrupt manifest is unreadable too
    bad = tmp_path / "incident-1-2-bad"
    bad.mkdir()
    (bad / "manifest.json").write_text("{not json")
    assert flightdump.main(["flightdump", "--incident", str(bad)]) == 2
    assert "not a readable" in capsys.readouterr().err


# --------------------------------------------------------------------------
# the chaos e2e: DYN_FAULT wedge → ONE bundle, evidence intact, recovery
# still drains/migrates/respawns underneath
# --------------------------------------------------------------------------


def test_wedge_autoproduces_one_bundle_with_evidence(tmp_path):
    config = _config()
    prompt = [1, 17, 43]
    max_tokens = 48
    out = {}

    async def go():
        src_runner = MigRunner(config, sync_delay=0.02)
        dst_runner = MigRunner(config)
        src = Scheduler(src_runner, config, flight=FlightRecorder())
        dst = Scheduler(dst_runner, config, flight=FlightRecorder())
        src.start()
        dst.start()
        server = await MigrationServer(
            MigrationSink(dst, dst_runner)).start()
        wd = StallWatchdog(
            probe=src.watchdog_probe, requests=src.request_table,
            registry=src.registry,  # trips land in the sampled registry
            flight=src.flight, interval_s=0.02, stall_s=0.15,
        ).start()
        controller = RecoveryController(
            engine_id="src", scheduler=src, runner=src_runner,
            watchdog=wd,
            peers=lambda: [{"host": server.host, "port": server.port,
                            "engine_id": "dst"}],
            config=RecoveryConfig(drain_grace_s=0.05,
                                  respawn_backoff_s=0.01),
            flight=src.flight,
        ).attach()
        # the incident autopilot, wired exactly as cli/run.py does it:
        # watchdog trips + recovery drains + a local history sampler
        # feeding the bundle's metric window (settle_s holds the capture
        # open long enough for the drain outcome and the migrated
        # request's just-completed trace to land in the bundle). No
        # flight= override: the capture merges the global ring (where
        # fault.injected lands) with the engine's private ring via the
        # registered watchdog — exactly the production artifact
        recorder = IncidentRecorder(
            IncidentConfig(out_dir=str(tmp_path), settle_s=1.5),
            history=MetricHistory(window_s=600.0),
        )
        recorder.watch_watchdog(wd)
        recorder.watch_recovery(controller)
        sampler = LocalHistorySampler(
            src.registry, history=recorder.history, interval_s=0.03,
        ).start()
        tracer = TraceRecorder(capacity=32)

        er = _request(prompt, max_tokens)
        src.add_request(er)
        toks, finish = await _collect(er, limit=6)
        assert finish is None, "finished before the wedge"
        faults.arm("decode_burst_hang", "once")
        rest, finish = await _collect(er)
        out["stream"] = (toks + rest, finish)
        # the stream completed on the peer: record its trace the way the
        # edge does, so the settling capture bundles it
        tracer.record(er.request_id, "m", "completed",
                      list(er.ctx.stages), ctx=er.ctx)
        for _ in range(200):  # capture lands after settle_s
            if recorder.captures:
                break
            await asyncio.sleep(0.05)
        out["captures"] = recorder.captures
        out["suppressed"] = recorder.suppressed
        out["bundles"] = list(recorder.bundles)
        out["trips"] = [t["reason"] for t in wd.trips]
        for _ in range(100):
            if controller.recoveries:
                break
            await asyncio.sleep(0.02)
        out["recovery"] = controller.recoveries[0]
        out["request_id"] = er.request_id
        faults.release()
        await sampler.stop()
        await recorder.stop()
        await wd.stop()
        await controller.close()
        await server.close()
        await dst.stop()
        await src.stop()
        out["src_used"] = src.allocator.used
        out["dst_used"] = dst.allocator.used

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(go())
    finally:
        loop.close()

    # recovery is untouched by the autopilot riding along: automated
    # drain + cold migration + byte-identical continuation + respawn
    assert out["trips"] == ["decode_stall"]
    assert out["recovery"]["reason"] == "decode_stall"
    assert out["recovery"]["migrated"] == 1
    assert out["stream"] == _baseline(prompt, max_tokens)
    assert out["src_used"] == 0 and out["dst_used"] == 0

    # EXACTLY one bundle: the watchdog trip captured; the recovery-drain
    # edge (same wedge, moments later) folded into it by the global floor
    assert out["captures"] == 1
    assert out["suppressed"] >= 1
    dirs = [d for d in os.listdir(tmp_path) if d.startswith("incident-")]
    assert len(dirs) == 1
    bundle = load_bundle_dir(os.path.join(tmp_path, dirs[0]))
    manifest = bundle["manifest"]
    assert manifest["reason"] == "decode_stall"
    assert manifest["info"]["stalled_for_s"] >= 0.15

    rid = out["request_id"]
    # flight artifact: the wedged request's lifecycle is in the ring,
    # from admission through the wedge to the recovery ladder
    events = bundle["flight"]["events"]
    kinds_for_req = {e["kind"] for e in events
                     if e.get("request_id") == rid
                     or rid in ((e.get("data") or {}).get("requests") or [])}
    assert "scheduler.admission" in kinds_for_req
    assert "scheduler.burst_dispatch" in kinds_for_req
    kinds = {e["kind"] for e in events}
    assert "watchdog.trip" in kinds
    assert "recovery.drain" in kinds
    assert "fault.injected" in kinds

    # metric history: rings cover the window INTO the trip — scheduler
    # gauges sampled from before the wedge through the drain
    series = {s["name"] for s in bundle["history"]["series"]}
    assert "dynamo_scheduler_active_slots" in series
    assert "dynamo_watchdog_trips_total" in series
    slots = next(s for s in bundle["history"]["series"]
                 if s["name"] == "dynamo_scheduler_active_slots")
    assert len(slots["points"]) >= 2, "history ring holds a curve, not a point"
    # at least one sample predates the trip (t_rel is negative seconds
    # relative to capture; the trip happened >= settle_s before it)
    assert slots["points"][0][0] < -1.0

    # stitched trace: the wedged request's end-to-end timeline — with
    # the migration relay stamped — rode into the bundle
    traces = {t["request_id"]: t for t in bundle["traces"]}
    assert rid in traces
    span_names = [s["name"] for s in traces[rid]["spans"]]
    assert "migration.relay" in span_names

    # and flightdump renders the whole thing offline
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    import flightdump

    text = flightdump.render_incident(bundle)
    assert "reason=decode_stall" in text
    assert "watchdog.trip" in text
    assert "dynamo_scheduler_active_slots" in text
    assert f"stitched trace {rid}" in text
