"""Test harness: force an 8-device virtual CPU platform before JAX import.

Mirrors the reference's GPU-free CI strategy (SURVEY.md §4): all tests run
without TPU hardware; sharding/mesh logic is exercised on a virtual 8-device
CPU mesh. Real-TPU tests are opt-in via the ``tpu`` marker.
"""

import os
import sys

# DYN_TPU_TESTS=1 opts into real-TPU tests; otherwise everything is pinned
# to the virtual 8-device CPU platform.
if not os.environ.get("DYN_TPU_TESTS"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    # The environment's site hook (PYTHONPATH sitecustomize) imports jax at
    # interpreter startup with JAX_PLATFORMS=axon (the real TPU), so env vars
    # set here are too late — jax's config already snapshotted them. Update
    # the live config instead, before any backend is initialized.
    import jax

    jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "tpu: requires real TPU hardware (opt-in)")
    config.addinivalue_line("markers", "slow: long-running test")
    config.addinivalue_line("markers", "asyncio: run test in a fresh event loop")
    config.addinivalue_line(
        "markers",
        "dynlint: static-analysis enforcement gate (pure AST walk — "
        "no network, no TPU, no heavy imports; always on in tier-1)",
    )


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests in a fresh event loop (no pytest-asyncio in env)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(asyncio.wait_for(fn(**kwargs), timeout=120))
        return True
    return None


def pytest_collection_modifyitems(config, items):
    if os.environ.get("DYN_TPU_TESTS"):
        return
    skip_tpu = pytest.mark.skip(reason="TPU tests disabled (set DYN_TPU_TESTS=1)")
    for item in items:
        if "tpu" in item.keywords:
            item.add_marker(skip_tpu)
