"""Bring-your-own Python engines (out=pystr:/pytok:, reference
lib/engines/python)."""

import pytest

from dynamo_tpu.llm.engines.python_file import PythonFileEngine
from dynamo_tpu.runtime.engine import Context

PYSTR_ENGINE = '''
INIT_ARGS = {}

async def initialize(engine_args):
    INIT_ARGS.update(engine_args)

async def generate(request):
    text = request["messages"][-1]["content"]
    for word in text.split():
        yield {"choices": [{"delta": {"content": word.upper()},
                            "index": 0}], "init": INIT_ARGS}
'''

PYTOK_ENGINE = '''
async def generate(request):
    for tid in request["token_ids"]:
        yield {"token_ids": [tid * 2]}
'''

NOT_A_GENERATOR = '''
async def generate(request):
    return [1, 2, 3]
'''


async def test_pystr_engine_streams(tmp_path):
    path = tmp_path / "engine.py"
    path.write_text(PYSTR_ENGINE)
    engine = await PythonFileEngine.load(str(path), {"temperature": 0.5})
    req = {"messages": [{"role": "user", "content": "hello tpu"}]}
    out = [c async for c in engine.generate(Context(req))]
    assert [c["choices"][0]["delta"]["content"] for c in out] == ["HELLO", "TPU"]
    assert out[0]["init"] == {"temperature": 0.5}  # initialize() ran


async def test_pytok_engine_token_level(tmp_path):
    path = tmp_path / "tok.py"
    path.write_text(PYTOK_ENGINE)
    engine = await PythonFileEngine.load(str(path))
    out = [c async for c in engine.generate(Context({"token_ids": [1, 2, 3]}))]
    assert [c["token_ids"] for c in out] == [[2], [4], [6]]


async def test_cooperative_stop(tmp_path):
    path = tmp_path / "tok.py"
    path.write_text(PYTOK_ENGINE)
    engine = await PythonFileEngine.load(str(path))
    ctx = Context({"token_ids": list(range(100))})
    seen = []
    async for c in engine.generate(ctx):
        seen.append(c)
        if len(seen) == 2:
            ctx.context.stop_generating()
    assert len(seen) == 2


async def test_rejects_non_generator(tmp_path):
    path = tmp_path / "bad.py"
    path.write_text(NOT_A_GENERATOR)
    with pytest.raises(TypeError, match="async generator"):
        await PythonFileEngine.load(str(path))


async def test_missing_file():
    with pytest.raises(FileNotFoundError):
        await PythonFileEngine.load("/nonexistent/engine.py")
