"""Sequence parallelism (ring + all-to-all attention) and mesh utilities,
exercised on the virtual 8-device CPU mesh (conftest)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.parallel import (
    MultiHostConfig,
    dense_reference,
    initialize_multihost,
    make_mesh,
    ring_attention,
    sp_prefill_attention,
    ulysses_attention,
)


def _qkv(key, b, s, h, kvh, d, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, h, d), dtype)
    k = jax.random.normal(kk, (b, s, kvh, d), dtype)
    v = jax.random.normal(kv, (b, s, kvh, d), dtype)
    return q, k, v


def _positions(b, s, valid_lens):
    pos = np.tile(np.arange(s, dtype=np.int32), (b, 1))
    for i, n in enumerate(valid_lens):
        pos[i, n:] = -1
    return jnp.asarray(pos)


class TestMesh:
    def test_axis_order_and_sizes(self):
        mesh = make_mesh({"dp": 2, "tp": 4})
        assert mesh.shape == {"dp": 2, "tp": 4}
        mesh = make_mesh({"tp": 2, "sp": 2, "dp": 2})
        assert tuple(mesh.shape.keys()) == ("dp", "sp", "tp")

    def test_too_many_devices_raises(self):
        with pytest.raises(ValueError, match="needs 16"):
            make_mesh({"dp": 16})

    def test_unknown_axis_raises(self):
        with pytest.raises(ValueError, match="unknown mesh axes"):
            make_mesh({"banana": 2})

    def test_multihost_single_node_is_noop(self):
        initialize_multihost(MultiHostConfig(num_nodes=1))

    def test_multihost_requires_leader(self):
        with pytest.raises(ValueError, match="leader_addr"):
            initialize_multihost(MultiHostConfig(num_nodes=2))


@pytest.mark.parametrize("strategy,h,kvh", [
    ("ring", 8, 8), ("ring", 8, 2),       # ring works for any head count
    ("ulysses", 8, 8), ("ulysses", 8, 4),  # ulysses needs KVH % sp == 0
])
def test_sp_attention_matches_dense(strategy, h, kvh):
    """Both sequence-parallel strategies must equal unsharded causal GQA."""
    b, s, d = 2, 32, 16
    mesh = make_mesh({"sp": 4})
    q, k, v = _qkv(jax.random.PRNGKey(0), b, s, h, kvh, d)
    valid = [s, s - 5]  # one full row, one padded row
    pos = _positions(b, s, valid)

    want = dense_reference(q, k, v, pos, pos)
    fn = ring_attention if strategy == "ring" else ulysses_attention
    got = fn(q, k, v, pos, pos, mesh)
    # padded rows are garbage-in/zero-out; compare valid region only
    for i, n in enumerate(valid):
        np.testing.assert_allclose(
            np.asarray(got[i, :n]), np.asarray(want[i, :n]), rtol=2e-4, atol=2e-4
        )


def test_ring_attention_jits_under_mesh():
    b, s, h, kvh, d = 1, 16, 4, 2, 8
    mesh = make_mesh({"sp": 8})
    q, k, v = _qkv(jax.random.PRNGKey(1), b, s, h, kvh, d)
    pos = _positions(b, s, [s])
    jitted = jax.jit(lambda *a: ring_attention(*a, mesh=mesh))
    got = jitted(q, k, v, pos, pos)
    want = dense_reference(q, k, v, pos, pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("strategy", ["ring", "ulysses"])
def test_fully_masked_pad_rows_are_zero(strategy):
    """Padded query positions (pos == -1) must yield exactly 0, not mean(V)."""
    b, s, h, kvh, d = 2, 16, 4, 4, 8
    mesh = make_mesh({"sp": 4})
    q, k, v = _qkv(jax.random.PRNGKey(5), b, s, h, kvh, d)
    valid = [16, 9]
    pos = _positions(b, s, valid)
    fn = ring_attention if strategy == "ring" else ulysses_attention
    got = np.asarray(fn(q, k, v, pos, pos, mesh))
    assert np.all(got[1, 9:] == 0.0), got[1, 9:]
    want = np.asarray(dense_reference(q, k, v, pos, pos))
    assert np.all(want[1, 9:] == 0.0)


def test_ulysses_rejects_indivisible_heads():
    b, s, h, kvh, d = 1, 16, 4, 2, 8
    mesh = make_mesh({"sp": 4})
    q, k, v = _qkv(jax.random.PRNGKey(2), b, s, h, kvh, d)
    pos = _positions(b, s, [s])
    with pytest.raises(ValueError, match="num_kv_heads"):
        ulysses_attention(q, k, v, pos, pos, mesh)


@pytest.mark.parametrize("strategy", ["ring", "ulysses", "auto"])
def test_sp_prefill_attention_pads_and_unpads(strategy):
    """S not divisible by sp: the wrapper pads, computes, strips."""
    b, s, h, kvh, d = 2, 30, 4, 4, 8
    mesh = make_mesh({"sp": 4})
    q, k, v = _qkv(jax.random.PRNGKey(3), b, s, h, kvh, d)
    valid = jnp.asarray([30, 21], jnp.int32)
    got = sp_prefill_attention(q, k, v, valid, mesh, strategy=strategy)
    assert got.shape == (b, s, h, d)

    pos = _positions(b, s, [30, 21])
    want = dense_reference(q, k, v, pos, pos)
    for i, n in enumerate([30, 21]):
        np.testing.assert_allclose(
            np.asarray(got[i, :n]), np.asarray(want[i, :n]), rtol=2e-4, atol=2e-4
        )


def test_sp_prefill_matches_engine_prefill_attention():
    """Cross-check vs the engine's dense prefill path (ops/attention.py)."""
    from dynamo_tpu.ops.attention import prefill_attention

    b, s, h, kvh, d = 2, 32, 8, 2, 16
    mesh = make_mesh({"sp": 4})
    q, k, v = _qkv(jax.random.PRNGKey(4), b, s, h, kvh, d)
    valid = jnp.asarray([32, 17], jnp.int32)
    got = sp_prefill_attention(q, k, v, valid, mesh, strategy="ring")
    want = prefill_attention(q, k, v, valid)
    for i, n in enumerate([32, 17]):
        np.testing.assert_allclose(
            np.asarray(got[i, :n]), np.asarray(want[i, :n]), rtol=2e-4, atol=2e-4
        )
