"""Qwen3-MoE: HF logit parity + engine greedy equality.

Qwen3-MoE = the GShard MoE trunk (models/mixtral.py) with Qwen3's
per-head q/k RMSNorms and norm_topk_prob routing; the checkpoint
loader speaks its mlp.gate / mlp.experts.N.{gate,up,down}_proj naming.
Reference analog: the Qwen MoE models of the engines the reference
delegates to (vLLM model zoo, SURVEY §2.4)."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.models import mixtral, resolve
from dynamo_tpu.models.loader import load_checkpoint_params

from fixtures import make_model_dir

TINY = dict(
    vocab_size=256,
    hidden_size=32,
    intermediate_size=64,
    moe_intermediate_size=48,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    head_dim=8,
    num_experts=4,
    num_experts_per_tok=2,
    norm_topk_prob=True,
    max_position_embeddings=128,
    rms_norm_eps=1e-6,
    rope_theta=10000.0,
    tie_word_embeddings=False,
)

PROMPT = [2, 17, 43, 99, 7, 3, 250, 12]


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    import torch
    from transformers import Qwen3MoeConfig, Qwen3MoeForCausalLM

    d = make_model_dir(tmp_path_factory.mktemp("q3moe"), name="tiny-q3moe")
    cfg = Qwen3MoeConfig(**TINY)
    torch.manual_seed(0)
    Qwen3MoeForCausalLM(cfg).save_pretrained(d, safe_serialization=True)
    with open(os.path.join(d, "config.json")) as f:
        c = json.load(f)
    c["eos_token_id"] = 1
    c["bos_token_id"] = 2
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump(c, f)
    return d


@pytest.fixture(scope="module")
def hf_out(model_dir):
    import torch
    from transformers import Qwen3MoeForCausalLM

    model = Qwen3MoeForCausalLM.from_pretrained(
        model_dir, torch_dtype=torch.float32, attn_implementation="eager"
    )
    model.eval()
    with torch.no_grad():
        logits = model(torch.tensor([PROMPT])).logits[0].numpy()
        gen = model.generate(
            torch.tensor([PROMPT]), max_new_tokens=8, do_sample=False,
        )[0][len(PROMPT):].tolist()
    return logits, gen


def test_resolve_and_config(model_dir):
    cfg = ModelConfig.from_model_dir(model_dir)
    assert cfg.num_experts == 4 and cfg.num_experts_per_tok == 2
    assert cfg.moe_intermediate_size == 48
    assert cfg.norm_topk_prob is True
    assert not cfg.attention_bias  # qwen3: no qkv biases
    assert resolve(cfg) is mixtral


def test_qwen3_moe_prefill_logits_match_hf(model_dir, hf_out):
    hf_logits, _ = hf_out
    cfg = ModelConfig.from_model_dir(model_dir)
    cfg.attention_impl = "xla"
    # ample capacity: the tiny prompt must not drop tokens or HF parity
    # becomes capacity-policy parity
    cfg.moe_capacity_factor = 8.0
    params = load_checkpoint_params(model_dir, cfg, mixtral, jnp.float32)
    assert "q_norm" in params["layers"] and "k_norm" in params["layers"]
    s = len(PROMPT)
    k, v = mixtral.init_kv_cache(cfg, 16, 8, jnp.float32)
    tokens = jnp.asarray([PROMPT], jnp.int32)
    positions = jnp.arange(s, dtype=jnp.int32)[None]
    bt = jnp.arange(4, dtype=jnp.int32)[None]
    logits, _ = mixtral.forward(
        params, cfg, tokens, positions, (k, v), bt, positions,
        jnp.asarray([s], jnp.int32),
    )
    np.testing.assert_allclose(
        np.asarray(logits[0]), hf_logits, rtol=2e-4, atol=2e-4
    )


@pytest.mark.asyncio
async def test_qwen3_moe_engine_greedy_matches_hf_generate(model_dir, hf_out):
    from dynamo_tpu.engine.serving import JaxServingEngine
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    _, hf_gen = hf_out
    mdc = ModelDeploymentCard.from_local_path(model_dir)
    mcfg = ModelConfig.from_model_dir(model_dir)
    mcfg.attention_impl = "xla"
    mcfg.moe_capacity_factor = 8.0
    econfig = EngineConfig(
        model=mcfg, max_batch_size=2, max_model_len=64, kv_block_size=8,
        num_kv_blocks=32, dtype="float32",
    )
    engine = await JaxServingEngine.create(
        mdc, engine_config=econfig, warmup=False)
    req = PreprocessedRequest(
        token_ids=PROMPT,
        stop_conditions=StopConditions(max_tokens=8, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0),
    )
    toks = []
    async for out in engine.generate(Context(req)):
        toks.extend(out["token_ids"])
    await engine.close()
    assert toks == hf_gen


def test_mixed_dense_sparse_rejected():
    with pytest.raises(NotImplementedError, match="mlp_only_layers"):
        ModelConfig.from_hf_config(
            {**TINY, "architectures": ["Qwen3MoeForCausalLM"],
             "mlp_only_layers": [0]}
        )


def test_qwen2_moe_rejected_at_config_parse():
    """The gated-shared-expert family fails BEFORE any checkpoint
    streaming (config carries shared_expert_intermediate_size)."""
    with pytest.raises(NotImplementedError, match="shared expert"):
        ModelConfig.from_hf_config(
            {**TINY, "architectures": ["Qwen2MoeForCausalLM"],
             "shared_expert_intermediate_size": 64}
        )


def test_qwen3_moe_pp_ep_matches_single_stage(model_dir):
    """Loaded Qwen3-MoE weights (incl. per-head q/k norms) through the
    pipelined pp x ep engine: same greedy step outputs as the unstaged
    runner — the norms ride the shared attention factory under staging."""
    from dynamo_tpu.engine.model_runner import ModelRunner

    mcfg = ModelConfig.from_model_dir(model_dir)
    mcfg.attention_impl = "xla"
    params = load_checkpoint_params(model_dir, mcfg, mixtral, jnp.float32)

    def run_step(pp, ep):
        runner = ModelRunner(EngineConfig(
            model=mcfg, max_batch_size=4, max_model_len=64, kv_block_size=8,
            num_kv_blocks=64, dtype="float32", pp_size=pp, ep_size=ep,
            prefill_buckets=[16],
        ), params=params)
        b, s, bs = 4, 8, 8
        rng = np.random.default_rng(11)
        tokens = rng.integers(0, mcfg.vocab_size, (b, s)).astype(np.int32)
        positions = np.tile(np.arange(s, dtype=np.int32), (b, 1))
        w = runner.config.blocks_per_seq
        btab = np.zeros((b, w), np.int32)
        for i in range(b):
            btab[i, 0] = i
        slots = btab[:, :1] * bs + positions
        out, *_ = runner.step(
            tokens, positions, btab, slots, np.full(b, s, np.int32),
            np.full(b, s - 1, np.int32), np.zeros(b, np.float32),
            np.zeros(b, np.int32), np.ones(b, np.float32),
            jax.random.PRNGKey(12),
        )
        return np.asarray(out)

    ref = run_step(1, 1)
    got = run_step(2, 2)
    np.testing.assert_array_equal(got, ref)
