"""Artifact packaging (`dynamo build` analog): build → store → operator
reconcile → version in CR status.

VERDICT r3 item 8 — parity with the reference's versioned graph bundles
(deploy/dynamo/sdk/src/dynamo/sdk/cli/{build,bentos}.py): a deploy pins
exactly what it runs via a content-addressed version.
"""

import asyncio
import json
import os
import tarfile

import pytest

from dynamo_tpu.deploy.api_store import ApiStoreService, DeploymentStore
from dynamo_tpu.deploy.operator import InMemoryKube, Reconciler
from dynamo_tpu.sdk.build import (
    build_artifact,
    deployment_spec,
    inspect_artifact,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TARGET = "examples.llm.graphs.agg:Frontend"
CONFIG = os.path.join(REPO, "examples/llm/configs/agg.yaml")


def build(tmp_path, **kw):
    return build_artifact(
        TARGET, config_path=CONFIG, output_dir=str(tmp_path), **kw
    )


def test_artifact_is_versioned_and_deterministic(tmp_path):
    a1 = build(tmp_path)
    a2 = build(tmp_path)
    assert a1.version == a2.version == a1.manifest["version"]
    assert len(a1.version) == 12
    assert a1.path.endswith(f"agg-{a1.version}.dyn.tar.gz")
    # the graph topology is captured
    svcs = a1.manifest["services"]
    assert set(svcs) == {"Frontend", "Processor", "Worker"}
    assert svcs["Frontend"]["links"] == ["Processor"]
    assert svcs["Processor"]["links"] == ["Worker"]
    # source + config are embedded; code digests pin the content
    with tarfile.open(a1.path) as tar:
        names = tar.getnames()
    assert "manifest.json" in names
    assert any(n.startswith("src/") for n in names)
    assert any(n.startswith("config") for n in names)
    assert a1.manifest["code"]["digests"]


def test_version_changes_with_config(tmp_path):
    a1 = build(tmp_path)
    alt = tmp_path / "alt.yaml"
    alt.write_text(open(CONFIG).read() + "\n# drift\nExtra:\n  x: 1\n")
    a2 = build_artifact(TARGET, config_path=str(alt),
                        output_dir=str(tmp_path))
    assert a1.version != a2.version


def test_artifact_archives_are_byte_identical(tmp_path):
    a1 = build(tmp_path / "a")
    a2 = build(tmp_path / "b")
    assert a1.version == a2.version
    assert open(a1.path, "rb").read() == open(a2.path, "rb").read()


def test_file_target_digests_code_and_names_artifact(tmp_path):
    """File-path graph targets: the artifact is named after the file, its
    source is digested, and editing the code mints a NEW version."""
    graph = tmp_path / "mygraph.py"
    src = (
        "from dynamo_tpu.sdk import service, dynamo_endpoint\n\n"
        "@service\n"
        "class Frontend:\n"
        "    @dynamo_endpoint()\n"
        "    async def chat(self, req):\n"
        "        yield req\n"
    )
    graph.write_text(src)
    a1 = build_artifact(f"{graph}:Frontend", output_dir=str(tmp_path))
    assert a1.name == "mygraph"
    assert a1.manifest["code"]["digests"], "file-target code not digested"
    graph.write_text(src + "\n# drift\n")
    a2 = build_artifact(f"{graph}:Frontend", output_dir=str(tmp_path))
    assert a1.version != a2.version


def test_deployment_spec_applies_common_config_inheritance(tmp_path):
    """A model-path the Worker opts into from Common (the sdk YAML
    convention) must reach the rendered deploy spec."""
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(
        "Common:\n  model-path: /models/m8b\n  model-name: m8b\n"
        "Worker:\n  common-configs: [model-path, model-name]\n"
        "Frontend:\n  http-port: 8080\n"
    )
    art = build_artifact(TARGET, config_path=str(cfg),
                         output_dir=str(tmp_path))
    spec = deployment_spec(art.manifest)
    assert spec["services"]["worker"]["modelPath"] == "/models/m8b"
    assert spec["services"]["worker"]["modelName"] == "m8b"
    # Frontend did not opt in: no model fields leak
    assert "modelPath" not in spec["services"]["frontend"]


def test_inspect_roundtrip_and_bad_archive(tmp_path):
    art = build(tmp_path)
    m = inspect_artifact(art.path)
    assert m == art.manifest
    bogus = tmp_path / "x.tar.gz"
    with tarfile.open(bogus, "w:gz") as tar:
        pass
    with pytest.raises(ValueError):
        inspect_artifact(str(bogus))


def test_deployment_spec_renders_operator_ready(tmp_path):
    from dynamo_tpu.deploy.operator import render_manifests

    art = build(tmp_path)
    spec = deployment_spec(art.manifest)
    assert spec["artifact"]["version"] == art.version
    assert spec["services"]["worker"]["role"] == "worker"
    # the spec renders directly into cluster manifests
    cr = {"apiVersion": "dynamo.tpu/v1alpha1", "kind": "DynamoDeployment",
          "metadata": {"name": "agg1", "namespace": "default"},
          "spec": spec}
    manifests = render_manifests(cr)
    assert any(m["kind"] == "Deployment" for m in manifests)


async def test_build_store_reconcile_version_in_status(tmp_path):
    """The full path: sdk.build → llmctl --from-artifact spec → api-store
    → operator reconcile sourced from the store → artifactVersion lands
    in the record's CR status."""
    from dynamo_tpu.deploy.store_source import ApiStoreClient

    art = build(tmp_path)
    spec = deployment_spec(art.manifest)

    service = ApiStoreService(DeploymentStore(":memory:"), "127.0.0.1", 0)
    await service.start()
    try:
        client = ApiStoreClient(f"http://127.0.0.1:{service.port}")
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(
            None, lambda: client.create("agg-pinned", spec))

        kube = InMemoryKube()
        rec = Reconciler(kube, status_writer=client.write_status)
        crs = await loop.run_in_executor(None, client.get_crs)
        assert len(crs) == 1
        await loop.run_in_executor(None, rec.reconcile, crs[0])

        record = await loop.run_in_executor(None, client.get, "agg-pinned")
        status = record["status"]
        assert status["artifactVersion"] == art.version
        assert status["artifactName"] == "agg"
        assert status["conditions"][0]["status"] == "True"
        # the cluster runs the artifact's services
        kinds = [k.split("/")[0] for k in kube.objects]
        assert kinds.count("Deployment") >= 4  # 3 graph svcs + dynstore
    finally:
        await service.stop()


def test_llmctl_create_from_artifact(tmp_path, capsys):
    """llmctl deploy create --from-artifact registers the rendered spec."""
    from dynamo_tpu.cli.llmctl import amain

    async def run():
        service = ApiStoreService(DeploymentStore(":memory:"), "127.0.0.1", 0)
        await service.start()
        try:
            art = build(tmp_path)
            loop = asyncio.get_running_loop()

            def llmctl(argv):
                # the CLI's deploy plane is a sync urllib client; in
                # production it is a separate process, so run it off this
                # loop (which is serving the store)
                return asyncio.run(amain(argv))

            rc = await loop.run_in_executor(None, llmctl, [
                "deploy", "create", "agg-a",
                "--from-artifact", art.path,
                "--api-store", f"http://127.0.0.1:{service.port}",
            ])
            assert rc == 0
            from dynamo_tpu.deploy.store_source import ApiStoreClient

            client = ApiStoreClient(f"http://127.0.0.1:{service.port}")
            record = await loop.run_in_executor(None, client.get, "agg-a")
            assert record["spec"]["artifact"]["version"] == art.version
            assert "worker" in record["spec"]["services"]
            # overlay: -f on top of the artifact spec wins per-field
            overlay = tmp_path / "patch.json"
            overlay.write_text(json.dumps(
                {"modelName": "m8b",
                 "services": {"worker": {"role": "worker", "tpus": 4}}}
            ))
            rc = await loop.run_in_executor(None, llmctl, [
                "deploy", "update", "agg-a",
                "--from-artifact", art.path, "-f", str(overlay),
                "--api-store", f"http://127.0.0.1:{service.port}",
            ])
            assert rc == 0
            record = await loop.run_in_executor(None, client.get, "agg-a")
            assert record["spec"]["modelName"] == "m8b"
            assert record["spec"]["services"]["worker"]["tpus"] == 4
            assert record["spec"]["artifact"]["version"] == art.version
        finally:
            await service.stop()

    asyncio.run(run())
    out = capsys.readouterr().out
    assert "created deployment agg-a" in out
