"""SDK: decorators, graph composition, config inheritance, allocator, e2e.

Mirrors the reference SDK's test strategy (reference: deploy/dynamo/sdk/
src/dynamo/sdk/tests/{test_config,test_link,test_e2e}.py)."""

import asyncio
import os

import pytest

from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.transports.memory import MemoryHub
from dynamo_tpu.sdk import (
    AllocationError,
    ServiceConfig,
    TpuAllocator,
    async_on_start,
    depends,
    dynamo_endpoint,
    graph_services,
    serve_graph_inprocess,
    service,
    stop_graph,
)


# ---- a tiny two-service graph used across tests ----

@service(dynamo={"namespace": "testns"}, resources={"tpu": 1}, workers=2)
class Backend:
    @dynamo_endpoint
    async def generate(self, request):
        for tok in request["prompt"].split():
            yield {"token": tok.upper()}

    @dynamo_endpoint(name="ping")
    async def ping_handler(self, request):
        yield {"pong": True}


@service(dynamo={"namespace": "testns"})
class Middle:
    backend = depends(Backend)

    @async_on_start
    async def setup(self):
        self.started = True

    @dynamo_endpoint
    async def chat(self, request):
        assert self.started
        async for item in self.backend.generate(request):
            yield {"echo": item["token"]}


class TestDecorators:
    def test_service_metadata(self):
        assert Backend.name == "Backend"
        assert Backend.spec.namespace == "testns"
        assert Backend.spec.resources == {"tpu": 1}
        assert Backend.spec.workers == 2
        assert set(Backend.endpoints) == {"generate", "ping"}
        assert Backend.endpoints["ping"] == "ping_handler"
        assert Backend.endpoint_path("generate") == "dyn://testns.Backend.generate"

    def test_dependencies_and_hooks(self):
        assert "backend" in Middle.dependencies
        assert Middle.dependencies["backend"].target is Backend
        assert Middle.on_start == ["setup"]

    def test_depends_rejects_plain_class(self):
        with pytest.raises(TypeError):
            depends(object)

    def test_link_chain_and_graph(self):
        @service
        class A:
            pass

        @service
        class B:
            pass

        @service
        class C:
            pass

        # reference-style chain: A -> B -> C
        A.link(B).link(C)
        names = [s.name for s in graph_services(A)]
        assert names == ["A", "B", "C"]
        # Middle's graph pulls Backend through depends()
        assert [s.name for s in graph_services(Middle)] == ["Middle", "Backend"]


class TestServiceConfig:
    def test_common_opt_in(self):
        cfg = ServiceConfig({
            "Common": {"model": "m8b", "block-size": 64, "max-model-len": 16384},
            "Worker": {"enforce-eager": True,
                       "common-configs": ["model", "block-size"]},
        })
        merged = cfg.get("Worker")
        assert merged == {"enforce-eager": True, "model": "m8b", "block-size": 64}
        args = cfg.as_args("Worker")
        assert "--enforce-eager" in args
        assert args[args.index("--model") + 1] == "m8b"
        assert "--max-model-len" not in args

    def test_no_opt_in_no_common(self):
        cfg = ServiceConfig({
            "Common": {"model": "m8b"},
            "Worker": {"enforce-eager": True},
        })
        assert "model" not in cfg.get("Worker")

    def test_service_values_beat_common(self):
        cfg = ServiceConfig({
            "Common": {"model": "common-model"},
            "Worker": {"model": "mine", "common-configs": ["model"]},
        })
        assert cfg.get("Worker")["model"] == "mine"

    def test_false_bool_and_list_args(self):
        cfg = ServiceConfig({"W": {"flag-off": False, "multi": [1, 2]}})
        args = cfg.as_args("W")
        assert "--flag-off" not in args
        assert args.count("--multi") == 2


class TestAllocator:
    def test_assign_and_exhaust(self):
        alloc = TpuAllocator(total_chips=4)
        env, chips = alloc.env_for({"tpu": 2})
        assert env == {"TPU_VISIBLE_CHIPS": "0,1"} and chips == [0, 1]
        env, chips2 = alloc.env_for({"tpu": 2})
        assert env == {"TPU_VISIBLE_CHIPS": "2,3"}
        with pytest.raises(AllocationError):
            alloc.env_for({"tpu": 1})

    def test_release_makes_chips_reusable(self):
        alloc = TpuAllocator(total_chips=2)
        _env, chips = alloc.env_for({"tpu": 2})
        assert alloc.available == 0
        alloc.release(chips)
        assert alloc.available == 2
        _env, again = alloc.env_for({"tpu": 2})
        assert again == [0, 1]

    def test_cpu_only_service(self):
        alloc = TpuAllocator(total_chips=1)
        env, chips = alloc.env_for({})
        assert env == {"JAX_PLATFORMS": "cpu"} and chips == []
        assert alloc.available == 1


async def test_e2e_graph_inprocess():
    """Full depends() round-trip: Middle.chat -> network -> Backend.generate."""
    drt = DistributedRuntime.in_process(MemoryHub())
    drt2, handles, _objs = await serve_graph_inprocess(Middle, drt)
    try:
        from dynamo_tpu.sdk import DynamoClient

        client = DynamoClient(Middle, drt)
        await client.start()
        await client.wait_ready(timeout=5.0)
        out = [item async for item in client.chat({"prompt": "hello tpu world"})]
        assert out == [{"echo": "HELLO"}, {"echo": "TPU"}, {"echo": "WORLD"}]
    finally:
        await stop_graph(drt2, handles)


async def test_optional_second_param_is_not_ctx():
    """generate(self, request, temperature=0.7) must NOT receive the ctx."""

    @service(dynamo={"namespace": "optns"})
    class Sampler:
        @dynamo_endpoint
        async def generate(self, request, temperature=0.7):
            yield {"temperature": temperature}

    drt = DistributedRuntime.in_process(MemoryHub())
    drt2, handles, _objs = await serve_graph_inprocess(Sampler, drt)
    try:
        from dynamo_tpu.sdk import DynamoClient

        client = DynamoClient(Sampler, drt)
        await client.start()
        await client.wait_ready(timeout=5.0)
        out = [i async for i in client.generate({})]
        assert out == [{"temperature": 0.7}]
    finally:
        await stop_graph(drt2, handles)


def test_inherited_endpoints_are_discovered():
    class BaseWorker:
        @dynamo_endpoint
        async def generate(self, request):
            yield {"base": True}

    @service
    class Derived(BaseWorker):
        @dynamo_endpoint
        async def extra(self, request):
            yield {}

    assert set(Derived.endpoints) == {"generate", "extra"}


async def test_endpoint_receives_ctx_and_stops():
    """(request, ctx) endpoints get the engine context; stop is cooperative."""

    @service(dynamo={"namespace": "ctxns"})
    class Stoppable:
        @dynamo_endpoint
        async def stream(self, request, ctx):
            for i in range(1000):
                if ctx.is_stopped:
                    return
                yield {"i": i}
                await asyncio.sleep(0)

    drt = DistributedRuntime.in_process(MemoryHub())
    drt2, handles, _objs = await serve_graph_inprocess(Stoppable, drt)
    try:
        from dynamo_tpu.runtime.client import Client
        from dynamo_tpu.runtime.engine import Context

        client = Client(
            drt.namespace("ctxns").component("Stoppable").endpoint("stream")
        )
        await client.start()
        await client.wait_for_instances(timeout=5.0)
        request = Context({"x": 1})
        seen = 0
        async for _item in client.generate(request):
            seen += 1
            if seen == 3:
                request.context.stop_generating()
        assert seen < 1000  # stopped early, not fully drained
    finally:
        await stop_graph(drt2, handles)


async def test_e2e_unknown_endpoint_raises():
    drt = DistributedRuntime.in_process(MemoryHub())
    drt2, handles, _objs = await serve_graph_inprocess(Backend, drt)
    try:
        from dynamo_tpu.sdk import DynamoClient

        client = DynamoClient(Backend, drt)
        with pytest.raises(AttributeError, match="no endpoint"):
            client.nope
    finally:
        await stop_graph(drt2, handles)


class TestLadderConfigs:
    """The BASELINE.json config ladder ships as loadable example YAMLs."""

    CONFIGS = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "examples", "llm", "configs",
    )

    def _load(self, name):
        return ServiceConfig.from_file(os.path.join(self.CONFIGS, name))

    def test_all_ladder_configs_parse(self):
        for name in ("agg.yaml", "agg_router.yaml", "disagg_router.yaml",
                     "tp70b_router.yaml", "mixtral_ep.yaml",
                     "disagg_ici.yaml", "deepseek_mla_disagg.yaml"):
            cfg = self._load(name)
            worker = cfg.get("Worker")
            assert worker.get("model-path"), name
            assert cfg.get("Frontend").get("http-port"), name

    def test_tp70b_shards_and_routes(self):
        cfg = self._load("tp70b_router.yaml")
        assert cfg.get("Worker")["tensor-parallel-size"] == 8
        assert cfg.get("Processor")["router-mode"] == "kv"

    def test_ici_configs_join_one_world(self):
        import sys

        sys.path.insert(0, os.path.dirname(os.path.dirname(self.CONFIGS)))
        from examples.llm.components import _WorkerFlags

        for name in ("disagg_ici.yaml", "deepseek_mla_disagg.yaml"):
            cfg = self._load(name)
            w, p = cfg.get("Worker"), cfg.get("PrefillWorker")
            assert w["kv-transfer"] == p["kv-transfer"] == "ici", name
            assert w["num-nodes"] == p["num-nodes"] == 2, name
            assert w["node-rank"] != p["node-rank"], name
            assert w["leader-addr"] == p["leader-addr"], name
            # the REAL wiring: the SDK worker services build their flags
            # through _WorkerFlags — the keys must survive the mapping
            wf, pf = _WorkerFlags(w), _WorkerFlags(p)
            assert wf.kv_transfer == pf.kv_transfer == "ici", name
            assert wf.num_nodes == pf.num_nodes == 2, name
            assert wf.node_rank != pf.node_rank, name

    def test_worker_flags_map_parallelism(self):
        import sys

        sys.path.insert(0, os.path.dirname(os.path.dirname(self.CONFIGS)))
        from examples.llm.components import _WorkerFlags

        cfg = self._load("mixtral_ep.yaml")
        flags = _WorkerFlags(cfg.get("Worker"))
        assert flags.expert_parallel_size == 8
        cfg = self._load("tp70b_router.yaml")
        assert _WorkerFlags(cfg.get("Worker")).tensor_parallel_size == 8
