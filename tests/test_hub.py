"""Fleet telemetry hub (telemetry/hub.py) + metric history rings
(telemetry/history.py).

The acceptance bar (ISSUE 10): a hub scraping a live multi-worker stack
over real HTTP shows every worker on ``GET /fleet/workers`` with
correct busy/KV/drain rollups, ``GET /fleet/metrics`` aggregates
sum/max/avg by role, history rings survive counter resets with sane
rates, and ``scripts/dynamotop.py`` renders the fleet from those
endpoints.
"""

import asyncio
import json
import os
import sys

import aiohttp
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.scheduler import Scheduler
from dynamo_tpu.http.service import HttpService, ModelManager
from dynamo_tpu.telemetry.exposition import parse_exposition
from dynamo_tpu.telemetry.flight import FlightRecorder
from dynamo_tpu.telemetry.history import (
    LocalHistorySampler,
    MetricHistory,
)
from dynamo_tpu.telemetry.hub import FleetHub, parse_target_flag
from dynamo_tpu.telemetry.registry import MetricsRegistry
from dynamo_tpu.telemetry.server import MetricsServer

from test_decode_pipeline import FakeRunner


# --------------------------------------------------------------------------
# history rings
# --------------------------------------------------------------------------


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def test_history_gauge_latest_and_window():
    clk = Clock()
    h = MetricHistory(window_s=10.0, clock=clk)
    for i in range(5):
        h.observe("g", {}, float(i), t=clk.t + i)
    clk.t += 4
    assert h.latest("g") == 4.0
    pts = h.window("g")
    assert [v for _, v in pts] == [0.0, 1.0, 2.0, 3.0, 4.0]
    # age the window out: a dead series goes blind, not stale
    clk.t += 100
    assert h.latest("g") is None
    assert h.window("g") == []


def test_history_counter_reset_detection():
    """A scraped counter going backward = remote restart: rate/delta
    must stay non-negative and the reset must be counted."""
    clk = Clock()
    h = MetricHistory(window_s=100.0, clock=clk)
    feed = [(0, 10.0), (1, 20.0), (2, 30.0), (3, 2.0), (4, 6.0)]
    for dt, v in feed:
        h.observe("c", {}, v, t=clk.t + dt, kind="counter")
    clk.t += 4
    assert h.resets("c") == 1
    # adjusted: 10,20,30,32,36 → delta 26 over 4s
    assert h.delta("c") == pytest.approx(26.0)
    assert h.rate("c") == pytest.approx(26.0 / 4.0)
    # latest() reports the adjusted (monotonic) total, not the raw 6
    assert h.latest("c") == pytest.approx(36.0)


def test_history_bounds_max_series_and_samples():
    clk = Clock()
    h = MetricHistory(window_s=1e9, max_samples=4, max_series=2, clock=clk)
    h.observe("a", {}, 1.0)
    h.observe("b", {}, 1.0)
    h.observe("c", {}, 1.0)  # over the series bound: dropped, counted
    assert h.series_count() == 2
    assert h.dropped_series == 1
    for i in range(10):
        h.observe("a", {}, float(i), t=clk.t + i)
    assert len(h.window("a", window_s=1e9)) == 4  # ring bound


def test_history_label_matching_sums_families():
    h = MetricHistory(clock=Clock())
    h.observe("t", {"reason": "a"}, 3.0, kind="counter")
    h.observe("t", {"reason": "b"}, 4.0, kind="counter")
    assert h.latest("t") == 7.0  # family total
    assert h.latest("t", {"reason": "a"}) == 3.0
    assert h.latest("t", {"reason": "missing"}) is None


def test_history_ingests_exposition_skipping_buckets():
    reg = MetricsRegistry()
    reg.gauge("dynamo_test_gauge_ratio", "g").set(0.5)
    reg.counter("dynamo_test_events_total", "c").inc(7, kind="x")
    hist_metric = reg.histogram("dynamo_test_latency_seconds", "h")
    hist_metric.observe(0.2)
    hist_metric.observe(0.4)
    clk = Clock()
    h = MetricHistory(clock=clk)
    h.ingest(parse_exposition(reg.render()))
    assert h.latest("dynamo_test_gauge_ratio") == 0.5
    assert h.latest("dynamo_test_events_total") == 7.0
    assert h.latest("dynamo_test_latency_seconds_count") == 2.0
    assert h.latest("dynamo_test_latency_seconds_sum") == pytest.approx(0.6)
    # per-le bucket series are the cardinality explosion the bounds
    # exist to prevent — never ingested
    assert not any(n.endswith("_bucket") for n in h.names())


def test_history_snapshot_shape():
    clk = Clock()
    h = MetricHistory(window_s=100.0, clock=clk)
    h.observe("s", {"l": "v"}, 1.0, t=clk.t - 1)
    snap = h.snapshot(window_s=50.0)
    assert snap["window_s"] == 50.0
    assert len(snap["series"]) == 1
    s = snap["series"][0]
    assert s["name"] == "s" and s["labels"] == {"l": "v"}
    # points carry [t_rel, wall_estimate, value]
    assert s["points"][0][0] == pytest.approx(-1.0)
    assert s["points"][0][2] == 1.0


async def test_local_history_sampler_fills_rings():
    reg = MetricsRegistry()
    g = reg.gauge("dynamo_test_sampled_ratio", "g")
    sampler = LocalHistorySampler(reg, interval_s=0.02)
    sampler.start()
    try:
        for i in range(3):
            g.set(i / 10)
            await asyncio.sleep(0.05)
        pts = sampler.history.window("dynamo_test_sampled_ratio")
        assert len(pts) >= 2
        assert pts[-1][1] == pytest.approx(0.2)
    finally:
        await sampler.stop()


# --------------------------------------------------------------------------
# hub units
# --------------------------------------------------------------------------


def test_parse_target_flag():
    t = parse_target_flag("decode=http://h:9090")
    assert t == {"url": "http://h:9090/metrics", "role": "decode",
                 "name": "h:9090"}
    assert parse_target_flag("h:1/metrics")["role"] == "worker"
    assert parse_target_flag("prefill=h:2")["url"] == "http://h:2/metrics"


def _worker_registry(busy=0.5, kv=0.25, waiting=2.0, draining=0.0,
                     trips=0):
    reg = MetricsRegistry()
    reg.gauge("dynamo_scheduler_slot_occupancy_ratio", "b").set(busy)
    reg.gauge("dynamo_kv_block_usage_ratio", "k").set(kv)
    reg.gauge("dynamo_scheduler_waiting_requests", "w").set(waiting)
    reg.gauge("dynamo_scheduler_draining_info", "d").set(draining)
    c = reg.counter("dynamo_watchdog_trips_total", "t")
    if trips:
        c.inc(trips, reason="decode_stall")
    return reg


async def test_hub_local_scrape_rollups_and_signals():
    hub = FleetHub(interval_s=0.05)
    hub.add_local("w1", "decode", _worker_registry(busy=0.8, waiting=3))
    hub.add_local("w2", "decode", _worker_registry(busy=0.2, kv=0.75,
                                                   trips=2))
    hub.add_local("fe", "frontend", MetricsRegistry())
    try:
        await hub.scrape_once()
        workers = hub.fleet_workers()["workers"]
        assert {w["name"] for w in workers} == {"w1", "w2", "fe"}
        w1 = next(w for w in workers if w["name"] == "w1")
        assert w1["up"] and w1["busy_ratio"] == 0.8
        assert w1["draining"] is False
        # rollups: sum/max/avg by role
        fams = hub.fleet_metrics()["families"]
        busy = fams["dynamo_scheduler_slot_occupancy_ratio"]["roles"]["decode"]
        assert busy["workers"] == 2
        assert busy["sum"] == pytest.approx(1.0)
        assert busy["max"] == pytest.approx(0.8)
        assert busy["avg"] == pytest.approx(0.5)
        # planner signals ride the existing policy vocabulary
        sig = hub.signal_source()()
        assert sig["decode.slot_busy_ratio"] == pytest.approx(0.5)
        assert sig["decode.waiting"] == pytest.approx(5.0)
        assert sig["kv.usage_ratio"] == pytest.approx(0.5)
        assert sig["watchdog.trips"] == pytest.approx(2.0)
        # the hub's own rollup gauges render (grafana panel 25 sanity)
        text = hub.registry.render()
        assert 'dynamo_hub_fleet_busy_ratio{role="decode"} 0.5' in text
        assert "dynamo_hub_history_series_depth" in text
    finally:
        await hub.stop()


async def test_fleet_rates_gate_on_counter_kind_and_report_flatlines():
    """Review pins: (1) fleet_metrics reports rate_per_s only for
    cumulative series — a gauge's slope under the same key would read
    as an event rate; (2) a flatlined counter is 0.0, not None — a
    wedged frontend at 0 req/s must not render like a worker that never
    exported HTTP metrics at all."""
    clk = Clock()
    hub = FleetHub(interval_s=0.05, clock=clk)
    reg = MetricsRegistry()
    reg.gauge("dynamo_kv_block_usage_ratio", "k").set(0.5)
    reg.counter("dynamo_http_service_requests_total", "r").inc(5)
    hub.add_local("fe", "frontend", reg)
    hub.add_local("bare", "prefill", MetricsRegistry())
    try:
        await hub.scrape_once()
        clk.t += 30.0
        await hub.scrape_once()
        fams = hub.fleet_metrics()["families"]
        gauge_entry = fams["dynamo_kv_block_usage_ratio"]["roles"]["frontend"]
        assert "rate_per_s" not in gauge_entry
        counter_entry = \
            fams["dynamo_http_service_requests_total"]["roles"]["frontend"]
        assert counter_entry["rate_per_s"] == 0.0
        workers = {w["name"]: w for w in hub.fleet_workers()["workers"]}
        assert workers["fe"]["requests_per_s"] == 0.0  # flatline, visible
        assert workers["bare"]["requests_per_s"] is None  # no HTTP metrics
    finally:
        await hub.stop()


async def test_fleet_rollups_exclude_down_workers():
    """Review pin: a wedged worker's LAST scrape stays visible in its
    /fleet/workers row (marked down) but must not keep steering the
    fleet averages and /fleet/metrics for up to history_window_s —
    rollups follow the same _up staleness rule signal_source uses."""
    clk = Clock()
    hub = FleetHub(interval_s=0.05, clock=clk)
    hub.add_local("w1", "decode", _worker_registry(busy=0.8))
    hub.add_local("w2", "decode", _worker_registry(busy=0.2))
    try:
        await hub.scrape_once()
        # w2 stops answering; the clock sails past the up-threshold
        del hub._locals["w2"]
        clk.t += 10.0
        await hub.scrape_once()
        workers = {w["name"]: w for w in hub.fleet_workers()["workers"]}
        assert workers["w2"]["up"] is False
        assert workers["w2"]["busy_ratio"] == 0.2  # last-known, marked down
        fams = hub.fleet_metrics()["families"]
        busy = fams["dynamo_scheduler_slot_occupancy_ratio"]["roles"]["decode"]
        assert busy["workers"] == 1
        assert busy["sum"] == pytest.approx(0.8)
        assert 'dynamo_hub_fleet_busy_ratio{role="decode"} 0.8' in \
            hub.registry.render()
        assert hub.signal_source()()["decode.slot_busy_ratio"] == \
            pytest.approx(0.8)
    finally:
        await hub.stop()


async def test_fleet_slo_attainment_is_per_request_not_blended():
    """Review pin: the hub consumes the slo="request" conjunction
    series. Blending the ttft/itl dimension counts overstates
    attainment exactly when requests miss one dimension — here the
    blend reads 0.9 (at the SlaPolicy floor) while per-request truth
    is 0.8 (the planner must shed)."""
    clk = Clock()
    hub = FleetHub(interval_s=0.05, clock=clk)
    reg = MetricsRegistry()
    c = reg.counter("dynamo_slo_attainment_total", "v")

    def ten_requests_two_missing_one_dimension():
        c.inc(10, slo="ttft", met="true")
        c.inc(8, slo="itl", met="true")
        c.inc(2, slo="itl", met="false")
        c.inc(8, slo="request", met="true")
        c.inc(2, slo="request", met="false")

    ten_requests_two_missing_one_dimension()
    hub.add_local("fe", "frontend", reg)
    try:
        await hub.scrape_once()
        clk.t += 30.0
        ten_requests_two_missing_one_dimension()
        await hub.scrape_once()
        workers = {w["name"]: w for w in hub.fleet_workers()["workers"]}
        assert workers["fe"]["slo_attainment"] == pytest.approx(0.8)
        assert hub.signal_source()()["slo.attainment"] == pytest.approx(0.8)
    finally:
        await hub.stop()


def test_fleet_reads_survive_concurrent_scrape_writes():
    """Review pin: /fleet handlers ride the executor and registry.render
    (invoking the hub's callback gauges) runs executor-side in the
    sidecar server AND the hub's local scrape — all while the scrape
    loop inserts/expires workers and appends series on the event loop.
    Readers must snapshot, never raise 'dict/deque changed size during
    iteration', and never mutate the rings."""
    import threading

    hub = FleetHub(interval_s=0.05)
    errors = []
    stop = threading.Event()

    def reader():
        try:
            while not stop.is_set():
                hub.registry.render()   # callback gauges over _workers
                hub.fleet_workers()
                hub.fleet_metrics()
                hub.signal_source()()
        except Exception as e:  # pragma: no cover - the failure mode
            errors.append(e)

    t = threading.Thread(target=reader, name="fleet-reader")
    t.start()
    try:
        # the writer side of a scrape cycle, churned hard: new workers,
        # expired workers, fresh series, appended points
        for i in range(4000):
            w = hub._worker_for(f"w{i % 7}", "decode", None)
            w.history.observe("dynamo_scheduler_slot_occupancy_ratio",
                              {"shard": str(i % 97)}, (i % 10) / 10)
            w.history.observe(f"dynamo_test_churn_{i % 211}_total", {},
                              float(i), kind="counter")
            if i % 11 == 0:
                hub._workers.pop(f"w{(i + 3) % 7}", None)
    finally:
        stop.set()
        t.join()
    assert errors == []


async def test_hub_scrape_failure_is_counted_not_fatal():
    hub = FleetHub(
        targets=[{"url": "http://127.0.0.1:1/metrics", "role": "decode",
                  "name": "dead"}],
        interval_s=0.05, timeout_s=0.2,
    )
    hub.add_local("fe", "frontend", _worker_registry())
    try:
        await hub.scrape_once()
        workers = {w["name"]: w for w in hub.fleet_workers()["workers"]}
        assert workers["dead"]["up"] is False
        assert workers["dead"]["error"]
        assert workers["fe"]["up"] is True
        text = hub.registry.render()
        assert 'outcome="error"' in text and 'outcome="ok"' in text
    finally:
        await hub.stop()


# --------------------------------------------------------------------------
# multi-process e2e: two workers behind real HTTP sidecars + a frontend
# --------------------------------------------------------------------------


def _engine_config(**kw):
    kw.setdefault("num_kv_blocks", 64)
    kw.setdefault("max_model_len", 256)
    kw.setdefault("multi_step_decode", 4)
    return EngineConfig(
        model=ModelConfig(vocab_size=512, hidden_size=32,
                          intermediate_size=64, num_layers=1, num_heads=2,
                          num_kv_heads=1),
        max_batch_size=4, kv_block_size=8, dtype="float32",
        enable_prefix_caching=False, **kw,
    )


@pytest.mark.asyncio
async def test_fleet_e2e_two_workers_and_frontend(tmp_path):
    """The satellite e2e: a hub inside a frontend scrapes two REAL
    scheduler registries over real HTTP sidecars; /fleet/workers shows
    both with correct drain state, and dynamotop renders the table."""
    config = _engine_config()
    s1 = Scheduler(FakeRunner(config), config, flight=FlightRecorder())
    s2 = Scheduler(FakeRunner(config), config, flight=FlightRecorder())
    s2.set_draining(True)  # worker 2 mid-recovery: the pane must show it
    side1 = await MetricsServer(s1.registry, "127.0.0.1", 0).start()
    side2 = await MetricsServer(s2.registry, "127.0.0.1", 0).start()
    hub = FleetHub(
        targets=[
            {"url": f"http://127.0.0.1:{side1.port}/metrics",
             "role": "decode_engine", "name": "w1"},
            {"url": f"http://127.0.0.1:{side2.port}/metrics",
             "role": "decode_engine", "name": "w2"},
        ],
        interval_s=0.05,
    )
    service = HttpService(ModelManager(), host="127.0.0.1", port=0, hub=hub)
    hub.add_local("frontend", "frontend", service.metrics.registry)
    await service.start()
    try:
        await hub.scrape_once()
        await hub.scrape_once()  # two samples → rates are derivable
        async with aiohttp.ClientSession() as s:
            base = f"http://127.0.0.1:{service.port}"
            async with s.get(f"{base}/fleet/workers") as r:
                assert r.status == 200
                body = await r.json()
            workers = {w["name"]: w for w in body["workers"]}
            assert set(workers) == {"w1", "w2", "frontend"}
            assert workers["w1"]["up"] and workers["w2"]["up"]
            assert workers["w1"]["draining"] is False
            assert workers["w2"]["draining"] is True
            assert workers["w1"]["busy_ratio"] == 0.0
            assert workers["w1"]["kv_usage_ratio"] is not None
            async with s.get(f"{base}/fleet/metrics") as r:
                assert r.status == 200
                fams = (await r.json())["families"]
            drain = fams["dynamo_scheduler_draining_info"]["roles"]
            assert drain["decode_engine"]["sum"] == 1.0
            assert drain["decode_engine"]["workers"] == 2
            # the hub's scrape instruments render in the frontend scrape
            async with s.get(f"{base}/metrics") as r:
                text = await r.text()
            assert "dynamo_hub_scrapes_total" in text
            # dynamotop renders the live fleet body
            sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "scripts"))
            import dynamotop

            frame = dynamotop.render(body, {"families": fams},
                                     hub_url=base)
            assert "w1" in frame and "w2" in frame
            assert "DRAIN" in frame  # w2's drain state in the table
            # --json: the same fleet as a machine-readable snapshot,
            # fetched by the real CLI path (urllib off-loop)
            rc = await asyncio.get_running_loop().run_in_executor(
                None,
                lambda: dynamotop.main(
                    ["dynamotop", "--hub", base, "--json"]))
            assert rc == 0
            snap = dynamotop.snapshot(body, {"families": fams},
                                      hub_url=base)
            snap2 = json.loads(json.dumps(snap))  # JSON-serializable
            assert snap2["summary"]["workers_total"] == 3
            assert snap2["summary"]["workers_up"] == 3
            assert snap2["summary"]["draining"] == 1
            rows = {w["name"]: w for w in snap2["workers"]}
            assert rows["w2"]["draining"] is True
            assert rows["w1"]["kv_usage_ratio"] is not None
    finally:
        await service.stop()
        await hub.stop()
        await side1.stop()
        await side2.stop()


def test_dynamotop_json_unreachable_hub_exits_nonzero(capsys):
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts"))
    import dynamotop

    rc = dynamotop.main(
        ["dynamotop", "--hub", "http://127.0.0.1:1", "--json"])
    assert rc == 2
    assert capsys.readouterr().out == ""  # nothing parseable on stdout


@pytest.mark.asyncio
async def test_fleet_endpoints_501_without_hub():
    service = HttpService(ModelManager(), host="127.0.0.1", port=0)
    await service.start()
    try:
        async with aiohttp.ClientSession() as s:
            for path in ("/fleet/workers", "/fleet/metrics",
                         "/debug/incidents"):
                async with s.get(
                        f"http://127.0.0.1:{service.port}{path}") as r:
                    assert r.status == 501, path
    finally:
        await service.stop()


async def test_hub_feeds_planner_policy_fleet_saturation():
    """SlaPolicy consults FLEET-level saturation through the hub source:
    one idle worker next to a saturated one must not mask the pool."""
    from dynamo_tpu.planner.policy import PolicyConfig, SlaPolicy
    from dynamo_tpu.planner.signals import SignalStore

    hub = FleetHub(interval_s=0.05)
    hub.add_local("w1", "decode", _worker_registry(
        busy=1.0, kv=0.99, waiting=20.0))
    hub.add_local("w2", "decode", _worker_registry(
        busy=0.95, kv=0.97, waiting=10.0))
    try:
        await hub.scrape_once()
        clk = Clock()
        store = SignalStore(clock=clk)
        store.observe_many(hub.signal_source()(), t=clk.t)
        policy = SlaPolicy(PolicyConfig(), clock=clk)
        actions = policy.decide(store, {"decode": 1})
        kinds = {type(a).__name__ for a in actions}
        # fleet KV ≥ bound → admission shed; fleet busy → decode scale-up
        assert "AdmissionAction" in kinds
        assert "ScaleAction" in kinds
    finally:
        await hub.stop()
