"""Phi-3 (fused qkv/gate_up checkpoints) and Qwen3 (per-head q/k norms)
— both served by the llama trunk, validated logit-exact vs HF."""

import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from dynamo_tpu.engine.config import ModelConfig
from dynamo_tpu.models import llama, resolve
from dynamo_tpu.models.loader import load_checkpoint_params

from fixtures import make_model_dir

PROMPT = [1, 17, 43, 99, 7, 3, 250, 12, 5, 77]


def _save(tmp, name, hf_cls, hf_cfg):
    import torch

    d = make_model_dir(tmp, name=name)
    torch.manual_seed(0)
    hf_cls(hf_cfg).save_pretrained(d, safe_serialization=True)
    with open(os.path.join(d, "config.json")) as f:
        c = json.load(f)
    c["eos_token_id"] = 2
    c["bos_token_id"] = 1
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump(c, f)
    return d


def _hf_reference(model_dir, hf_cls):
    import torch

    model = hf_cls.from_pretrained(
        model_dir, torch_dtype=torch.float32, attn_implementation="eager"
    )
    model.eval()
    with torch.no_grad():
        logits = model(torch.tensor([PROMPT])).logits[0].numpy()
        gen = model.generate(
            torch.tensor([PROMPT]), max_new_tokens=8, do_sample=False,
        )[0][len(PROMPT):].tolist()
    return logits, gen


def _our_logits(model_dir):
    cfg = ModelConfig.from_model_dir(model_dir)
    cfg.attention_impl = "xla"
    arch = resolve(cfg)
    assert arch is llama
    params = load_checkpoint_params(model_dir, cfg, arch, jnp.float32)
    s = len(PROMPT)
    k, v = llama.init_kv_cache(cfg, 16, 8, jnp.float32)
    logits, _ = llama.forward(
        params, cfg, jnp.asarray([PROMPT], jnp.int32),
        jnp.arange(s, dtype=jnp.int32)[None], (k, v),
        jnp.arange(4, dtype=jnp.int32)[None],
        jnp.arange(s, dtype=jnp.int32)[None],
        jnp.asarray([s], jnp.int32),
    )
    return np.asarray(logits[0])


async def _engine_greedy(model_dir, n):
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.serving import JaxServingEngine
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    mdc = ModelDeploymentCard.from_local_path(model_dir)
    mcfg = ModelConfig.from_model_dir(model_dir)
    mcfg.attention_impl = "xla"
    engine = await JaxServingEngine.create(
        mdc, engine_config=EngineConfig(
            model=mcfg, max_batch_size=2, max_model_len=64, kv_block_size=8,
            num_kv_blocks=32, dtype="float32",
        ), warmup=False)
    req = PreprocessedRequest(
        token_ids=PROMPT,
        stop_conditions=StopConditions(max_tokens=n, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0),
    )
    toks = []
    async for out in engine.generate(Context(req)):
        toks.extend(out["token_ids"])
    await engine.close()
    return toks


@pytest.fixture(scope="module")
def phi3_dir(tmp_path_factory):
    from transformers import Phi3Config, Phi3ForCausalLM

    cfg = Phi3Config(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False, pad_token_id=0,
    )
    return _save(tmp_path_factory.mktemp("phi3"), "tiny-phi3",
                 Phi3ForCausalLM, cfg)


@pytest.fixture(scope="module")
def qwen3_dir(tmp_path_factory):
    from transformers import Qwen3Config, Qwen3ForCausalLM

    cfg = Qwen3Config(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=256, rms_norm_eps=1e-5,
        rope_theta=10000.0, tie_word_embeddings=False,
    )
    return _save(tmp_path_factory.mktemp("qwen3"), "tiny-qwen3",
                 Qwen3ForCausalLM, cfg)


def test_phi3_sliding_window_logits_match_hf(tmp_path):
    # whole-model sliding window (mistral/phi3 semantics): window smaller
    # than the prompt so the mask bites, compared against HF eager
    from transformers import Phi3Config, Phi3ForCausalLM

    cfg = Phi3Config(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False, pad_token_id=0, sliding_window=4,
    )
    d = _save(tmp_path, "tiny-phi3-sw", Phi3ForCausalLM, cfg)
    mc = ModelConfig.from_model_dir(d)
    assert mc.sliding_window == 4
    hf_logits, _ = _hf_reference(d, Phi3ForCausalLM)
    np.testing.assert_allclose(
        _our_logits(d), hf_logits, rtol=2e-4, atol=2e-4
    )


def test_phi3_longrope_both_profiles_match_hf(tmp_path):
    """Phi-3 128k-style longrope: the short profile (prompt inside the
    pretraining window) and the long profile (prompt beyond it) must
    both match HF, including the always-on attention factor."""
    import torch
    from transformers import Phi3Config, Phi3ForCausalLM

    cfg = Phi3Config(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, original_max_position_embeddings=16,
        rms_norm_eps=1e-5, rope_theta=10000.0, tie_word_embeddings=False,
        pad_token_id=0,
        rope_scaling={
            "type": "longrope",
            "short_factor": [1.0 + 0.1 * i for i in range(8)],
            "long_factor": [2.0 + 0.5 * i for i in range(8)],
        },
    )
    d = _save(tmp_path, "tiny-phi3-lr", Phi3ForCausalLM, cfg)

    model = Phi3ForCausalLM.from_pretrained(
        d, torch_dtype=torch.float32, attn_implementation="eager")
    model.eval()
    mc = ModelConfig.from_model_dir(d)
    mc.attention_impl = "xla"
    params = load_checkpoint_params(d, mc, llama, jnp.float32)

    def ours(prompt):
        s = len(prompt)
        k, v = llama.init_kv_cache(mc, 16, 8, jnp.float32)
        logits, _ = llama.forward(
            params, mc, jnp.asarray([prompt], jnp.int32),
            jnp.arange(s, dtype=jnp.int32)[None], (k, v),
            jnp.arange(8, dtype=jnp.int32)[None],
            jnp.arange(s, dtype=jnp.int32)[None],
            jnp.asarray([s], jnp.int32),
        )
        return np.asarray(logits[0])

    short_prompt = PROMPT               # 10 tokens <= 16: short profile
    long_prompt = (PROMPT * 3)[:24]     # 24 tokens  > 16: long profile
    for prompt in (short_prompt, long_prompt):
        with torch.no_grad():
            hf = model(torch.tensor([prompt])).logits[0].numpy()
        np.testing.assert_allclose(ours(prompt), hf, rtol=2e-4, atol=2e-4)


def test_longrope_profile_is_per_row():
    # a long-context request co-batched with a short one must not flip
    # the short row onto the long profile
    from dynamo_tpu.models.llama import apply_rope

    scaling = {
        "type": "longrope",
        "short_factor": [1.0 + 0.1 * i for i in range(8)],
        "long_factor": [2.0 + 0.5 * i for i in range(8)],
        "original_max_position_embeddings": 16,
        "max_position_embeddings": 64,
    }
    x = jnp.ones((2, 4, 2, 16), jnp.float32)
    positions = jnp.tile(jnp.arange(4, dtype=jnp.int32)[None], (2, 1))
    mixed = apply_rope(x, positions, 10000.0, scaling,
                       seq_basis=jnp.asarray([10, 40], jnp.int32))
    alone = apply_rope(x[:1], positions[:1], 10000.0, scaling,
                       seq_basis=jnp.asarray([10], jnp.int32))
    np.testing.assert_allclose(np.asarray(mixed[0]), np.asarray(alone[0]),
                               rtol=1e-6)
    # and the long row really uses a different profile
    assert not np.allclose(np.asarray(mixed[1]), np.asarray(mixed[0]))


def test_phi3_logits_match_hf(phi3_dir):
    from transformers import Phi3ForCausalLM

    hf_logits, _ = _hf_reference(phi3_dir, Phi3ForCausalLM)
    np.testing.assert_allclose(
        _our_logits(phi3_dir), hf_logits, rtol=2e-4, atol=2e-4
    )


def test_qwen3_logits_match_hf(qwen3_dir):
    from transformers import Qwen3ForCausalLM

    hf_logits, _ = _hf_reference(qwen3_dir, Qwen3ForCausalLM)
    np.testing.assert_allclose(
        _our_logits(qwen3_dir), hf_logits, rtol=2e-4, atol=2e-4
    )


@pytest.mark.asyncio
async def test_phi3_engine_greedy_matches_hf(phi3_dir):
    from transformers import Phi3ForCausalLM

    _, hf_gen = _hf_reference(phi3_dir, Phi3ForCausalLM)
    assert await _engine_greedy(phi3_dir, 8) == hf_gen


@pytest.mark.asyncio
async def test_qwen3_engine_greedy_matches_hf(qwen3_dir):
    from transformers import Qwen3ForCausalLM

    _, hf_gen = _hf_reference(qwen3_dir, Qwen3ForCausalLM)
    assert await _engine_greedy(qwen3_dir, 8) == hf_gen
