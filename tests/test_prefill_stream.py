"""Streamed remote prefill: the worker-side compute/transfer pipeline,
isolated from model numerics by a deterministic fake runner + fake client.

Pins the three structural claims of the streamed transfer pipeline
(disagg/prefill_worker.py):

- chunk i+1's COMPUTE dispatches before chunk i's frame finishes sending
  (compute and transfer actually overlap — remote TTFT approaches
  max(compute, transfer), not their sum);
- at most 2 chunk-sized host buffers exist at any point (depth 2), and
  exactly 1 at depth 1 — host memory no longer scales with prompt length;
- the commit is sent only after every frame drained.
"""

import asyncio

import numpy as np
import pytest

from dynamo_tpu.disagg.prefill_worker import PrefillWorker
from dynamo_tpu.disagg.protocols import RemotePrefillRequest
from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.engine import AsyncEngineContext
from dynamo_tpu.runtime.transports.memory import MemoryHub


def _dequeue_ctx(rpr):
    # serve_one's contract: ctx arrives with prefill.dequeue stamped
    ctx = AsyncEngineContext(trace_id=rpr.trace_id or rpr.request_id)
    ctx.add_stage("prefill.dequeue")
    return ctx


def _config(**kw):
    kw.setdefault("max_prefill_tokens_per_step", 8)
    kw.setdefault("prefill_buckets", [8, 16, 32, 64, 128])
    return EngineConfig(
        model=ModelConfig(vocab_size=512, hidden_size=32,
                          intermediate_size=64, num_layers=1, num_heads=2,
                          num_kv_heads=1),
        max_batch_size=2, max_model_len=128, kv_block_size=8,
        num_kv_blocks=64, dtype="float32", enable_prefix_caching=False,
        **kw,
    )


class _FakeRunner:
    """Dispatch-recording stand-in: step() logs the chunk's start
    position, gathers log the frame's block ids."""

    def __init__(self, config, events):
        self.config = config
        self.events = events

    def set_sample_row(self, *a, **kw):
        pass

    def step(self, tokens, positions, btab, slot_map, ctx_lens, last_idx,
             *args, **kw):
        self.events.append(("step", int(np.asarray(positions)[0, 0])))
        shape = np.asarray(tokens).shape
        return (np.full(1, 7, np.int32), np.zeros(1, np.float32),
                np.zeros((1, 8), np.float32), np.zeros((1, 8), np.int32),
                np.zeros(shape, np.float32), np.zeros(shape, np.int32))

    def gather_blocks_device(self, block_ids):
        self.events.append(("gather", tuple(block_ids)))
        shape = (1, len(block_ids), self.config.kv_block_size, 1, 4)
        return (np.zeros(shape, np.float32), np.zeros(shape, np.float32))

    @staticmethod
    def blocks_to_host(k, v):
        return np.asarray(k), np.asarray(v)


class _SlowClient:
    """Fake decode-side transfer client whose wire is slower than the
    fake compute, forcing the overlap question."""

    modes = ("tcp",)
    ici_rank = None

    def __init__(self, events, wire_delay=0.05):
        self.events = events
        self.wire_delay = wire_delay

    async def send_blocks(self, request_id, block_ids, k, v, chunk_blocks=16,
                          trace_id=None):
        self.events.append(("send_start", tuple(block_ids)))
        await asyncio.sleep(self.wire_delay)
        self.events.append(("send_done", tuple(block_ids)))

    async def send_commit(self, request_id, token, logprob, top=None,
                          spans=None):
        self.events.append(("commit",))
        return True

    async def close(self):
        pass


async def _run_worker(depth, n_tokens=24):
    events = []
    config = _config(disagg_stream_depth=depth)
    drt = DistributedRuntime.in_process(MemoryHub())
    worker = PrefillWorker(drt, _FakeRunner(config, events), config)
    worker._clients["e1"] = _SlowClient(events)
    blocks = -(-n_tokens // config.kv_block_size)
    rpr = RemotePrefillRequest(
        request_id="r1", engine_id="e1",
        token_ids=[1 + i % 200 for i in range(n_tokens)],
        block_ids=list(range(40, 40 + blocks)), num_cached=0, seed=0,
    )
    try:
        await asyncio.wait_for(worker._handle(rpr, _dequeue_ctx(rpr)), timeout=30)
    finally:
        await drt.close()
    return events, worker


@pytest.mark.asyncio
async def test_compute_dispatches_ahead_of_frame_acks():
    """24 tokens at an 8-token chunk cap = 3 chunks / 3 one-block frames:
    every later chunk's compute must dispatch before the FIRST frame's
    send completes (the wire is 50 ms; fake compute is instant)."""
    events, worker = await _run_worker(depth=2)
    steps = [i for i, e in enumerate(events) if e[0] == "step"]
    assert len(steps) == 3
    first_send_done = next(
        i for i, e in enumerate(events) if e[0] == "send_done"
    )
    assert steps[1] < first_send_done and steps[2] < first_send_done, events
    # the commit strictly follows every frame's completion
    commit_i = events.index(("commit",))
    send_dones = [i for i, e in enumerate(events) if e[0] == "send_done"]
    send_starts = [i for i, e in enumerate(events) if e[0] == "send_start"]
    assert len(send_dones) == len(send_starts) == 3
    assert all(i < commit_i for i in send_dones)
    assert worker.transfer_frames == 3
    assert worker.prefills == 1


@pytest.mark.asyncio
async def test_host_buffers_bounded_at_depth():
    """Depth 2 = at most two chunk-sized host frames live (one packing,
    one on the wire); depth 1 = strictly serial, exactly one."""
    _, w2 = await _run_worker(depth=2, n_tokens=48)  # 6 chunks
    assert 1 <= w2.max_live_host_frames <= 2
    _, w1 = await _run_worker(depth=1, n_tokens=48)
    assert w1.max_live_host_frames == 1


@pytest.mark.asyncio
async def test_frame_failure_leaves_item_for_redelivery():
    """A frame send that dies mid-stream fails the whole attempt (no ack,
    no commit) and never deadlocks the bounded pipe."""
    events = []
    config = _config()

    class _DyingClient(_SlowClient):
        async def send_blocks(self, request_id, block_ids, k, v,
                              chunk_blocks=16, trace_id=None):
            self.events.append(("send_start", tuple(block_ids)))
            raise ConnectionResetError("wire died")

    drt = DistributedRuntime.in_process(MemoryHub())
    worker = PrefillWorker(drt, _FakeRunner(config, events), config)
    worker._clients["e1"] = _DyingClient(events)
    rpr = RemotePrefillRequest(
        request_id="r1", engine_id="e1",
        token_ids=list(range(1, 25)), block_ids=list(range(10, 13)),
        num_cached=0, seed=0,
    )
    try:
        with pytest.raises(ConnectionResetError):
            await asyncio.wait_for(worker._handle(rpr, _dequeue_ctx(rpr)), timeout=30)
    finally:
        await drt.close()
    assert ("commit",) not in events
    assert worker.prefills == 0
    assert worker.allocator.used == 0  # blocks released on the error path


@pytest.mark.asyncio
async def test_compute_failure_with_healthy_pump_does_not_wedge():
    """Producer-side failure while the pump is healthy and blocked on the
    queue: shutdown() cancels the pump and _handle must re-raise promptly
    — the pump's error-consume loop must never swallow its own
    cancellation and wait on a queue nothing will ever feed."""
    events = []
    config = _config()

    class _ExplodingRunner(_FakeRunner):
        def step(self, *a, **kw):
            if any(e[0] == "step" for e in self.events):
                raise RuntimeError("device fault mid-chunk")
            return super().step(*a, **kw)

    drt = DistributedRuntime.in_process(MemoryHub())
    worker = PrefillWorker(drt, _ExplodingRunner(config, events), config)
    worker._clients["e1"] = _SlowClient(events, wire_delay=0.2)
    rpr = RemotePrefillRequest(
        request_id="r1", engine_id="e1",
        token_ids=list(range(1, 25)), block_ids=list(range(10, 13)),
        num_cached=0, seed=0,
    )
    try:
        with pytest.raises(RuntimeError, match="device fault"):
            # wait_for is the regression oracle: the pre-fix behavior
            # deadlocked in pipe.shutdown() and timed out here
            await asyncio.wait_for(worker._handle(rpr, _dequeue_ctx(rpr)), timeout=10)
    finally:
        await drt.close()
    assert ("commit",) not in events
    assert worker.allocator.used == 0


def test_disagg_stream_depth_clamped():
    assert _config(disagg_stream_depth=0).disagg_stream_depth == 1
    assert _config(disagg_stream_depth=7).disagg_stream_depth == 2


# --------------------------------------------------------------------------
# sequence-parallel chunk ladder on the worker (docs/long_context.md)
# --------------------------------------------------------------------------


class _FakeSpRunner(_FakeRunner):
    """SP-capable fake: advertises the SP program and records which
    ladder each chunk ran through."""

    sp_ready = True
    sp_chunk_tokens = 16  # mesh-wide chunk = 2x the dense 8-token cap

    def sp_prefill_chunk(self, prompt, start, block_ids, *, commit=False,
                         want_top=False, **kw):
        self.events.append(("sp_chunk", start, len(prompt)))
        return (np.full(1, 7, np.int32), np.zeros(1, np.float32),
                np.zeros((1, 8), np.float32), np.zeros((1, 8), np.int32))


async def _run_sp_worker(threshold, n_tokens=32):
    events = []
    config = _config(disagg_stream_depth=2,
                     long_prefill_threshold_tokens=threshold)
    drt = DistributedRuntime.in_process(MemoryHub())
    worker = PrefillWorker(drt, _FakeSpRunner(config, events), config)
    worker._clients["e1"] = _SlowClient(events)
    blocks = -(-n_tokens // config.kv_block_size)
    rpr = RemotePrefillRequest(
        request_id="r1", engine_id="e1",
        token_ids=[1 + i % 200 for i in range(n_tokens)],
        block_ids=list(range(40, 40 + blocks)), num_cached=0, seed=0,
    )
    try:
        await asyncio.wait_for(worker._handle(rpr, _dequeue_ctx(rpr)),
                               timeout=30)
    finally:
        await drt.close()
    return events


@pytest.mark.asyncio
async def test_worker_long_prompt_takes_the_sp_ladder():
    """Past the threshold, chunks run through the SP program at its
    mesh-wide cap; frames still stream between chunks, the commit still
    comes last."""
    events = await _run_sp_worker(threshold=24, n_tokens=32)
    sp = [e for e in events if e[0] == "sp_chunk"]
    assert [e[1] for e in sp] == [0, 16]        # two 16-token chunks
    assert not [e for e in events if e[0] == "step"]
    assert events[-1] == ("commit",)
    assert [e for e in events if e[0] == "send_start"]


@pytest.mark.asyncio
async def test_worker_short_prompt_keeps_the_dense_ladder():
    """Below the threshold the dense 8-token ladder runs even though
    the SP program exists."""
    events = await _run_sp_worker(threshold=64, n_tokens=32)
    assert not [e for e in events if e[0] == "sp_chunk"]
    assert [e[1] for e in events if e[0] == "step"] == [0, 8, 16, 24]
    assert events[-1] == ("commit",)
