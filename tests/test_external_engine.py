"""External C++ engine example: builds engine.cc against the C ABI,
drives it through the pytok BYO-engine loader, and drains the KV events
it publishes (reference parity: lib/bindings/c consumed by a non-Python
engine)."""

import shutil

import pytest

from dynamo_tpu.llm.engines.python_file import PythonFileEngine
from dynamo_tpu.runtime.engine import Context

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None, reason="no C++ toolchain"
)

ENGINE = "examples/external_engine/engine.py"


async def test_external_engine_generates_and_publishes_kv():
    import importlib.util
    import os

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo_root, ENGINE)
    engine = await PythonFileEngine.load(path)

    prompt = list(range(40))  # 2 full blocks of 16 + remainder
    req = {"token_ids": prompt, "stop_conditions": {"max_tokens": 5}}
    chunks = []
    async for chunk in engine.generate(Context(req)):
        chunks.append(chunk)
    toks = [t for c in chunks for t in c.get("token_ids", [])]
    assert toks == prompt[:5]             # the toy engine echoes the prompt
    assert chunks[-1].get("finish_reason") == "stop"

    # the C++ side published one stored event covering the full blocks
    spec = importlib.util.spec_from_file_location("ext_engine_shim", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    events = mod.drain_kv_events()
    assert events, "no KV events drained from the C ABI queue"
    ev = events[-1]
    assert ev["worker_id"] == "ext-worker-0"
    assert len(ev["stored"]["block_hashes"]) == 2
