"""Draft-model speculative decoding.

A small same-tokenizer model proposes K tokens per round with its fused
burst; the target verifies K+1 positions in one forward. The stream is
provably identical to plain greedy decoding for ANY draft — the draft
only changes how much work each round amortizes — and the draft's paged
cache mirrors the target's block ids, so prefix-cache hits and resume
carry valid draft context. Reference analog: the draft/verify
speculation of the engines the reference delegates to (SURVEY §2.4).
"""

import asyncio
import json
import os

import numpy as np
import pytest

import jax

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.serving import JaxServingEngine, build_draft_config
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.protocols.common import (
    OutputOptions, PreprocessedRequest, SamplingOptions, StopConditions,
)
from dynamo_tpu.runtime.engine import Context

from fixtures import make_model_dir

TINY = dict(
    vocab_size=512,
    hidden_size=64,
    intermediate_size=128,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    max_position_embeddings=256,
    rms_norm_eps=1e-5,
    rope_theta=10000.0,
)


def _save_llama(d, seed, layers=2):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(**{**TINY, "num_hidden_layers": layers},
                      tie_word_embeddings=False)
    torch.manual_seed(seed)
    LlamaForCausalLM(cfg).save_pretrained(d, safe_serialization=True)
    with open(os.path.join(d, "config.json")) as f:
        c = json.load(f)
    c["eos_token_id"] = 2
    c["bos_token_id"] = 1
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump(c, f)
    return d


@pytest.fixture(scope="module")
def target_dir(tmp_path_factory):
    return _save_llama(
        make_model_dir(tmp_path_factory.mktemp("target"), name="tiny-hf"), 0
    )


@pytest.fixture(scope="module")
def draft_dir(tmp_path_factory):
    # different weights, 1 layer: a genuinely different (worse) model
    return _save_llama(
        make_model_dir(tmp_path_factory.mktemp("draft"), name="tiny-draft"),
        7, layers=1,
    )


async def _serve(model_dir, prompts, draft=None, k=4, max_tokens=12,
                 chain_len_out=None, **econfig_kw):
    econfig = EngineConfig(
        model=ModelConfig.from_model_dir(model_dir),
        max_batch_size=2, max_model_len=128, kv_block_size=8,
        num_kv_blocks=64, dtype="float32", prefill_buckets=[32],
        spec_draft_model=draft, spec_draft_tokens=k if draft else 0,
        **econfig_kw,
    )
    mdc = ModelDeploymentCard.from_local_path(model_dir)
    engine = await JaxServingEngine.create(
        mdc, engine_config=econfig, warmup=False)
    outs = []
    for prompt in prompts:
        req = PreprocessedRequest(
            token_ids=prompt,
            stop_conditions=StopConditions(
                max_tokens=max_tokens, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
        )
        toks = []
        async for out in engine.generate(Context(req)):
            toks.extend(out["token_ids"])
        outs.append(toks)
    stats = engine.scheduler.metrics() if hasattr(engine, "scheduler") else {}
    proposed = engine.scheduler.spec_proposed
    accepted = engine.scheduler.spec_accepted
    if chain_len_out is not None:
        chain_len_out["chain_len"] = engine.scheduler._last_chain_len
        chain_len_out["spec_rounds"] = sum(
            engine.scheduler._spec_accept_hist.totals.values()
        )
    await engine.close()
    del stats
    return outs, proposed, accepted


PROMPTS = [[1, 17, 43, 99, 7, 3], [1, 250, 12, 5, 77, 140, 9, 33]]


def test_draft_stream_identical_to_plain_greedy(target_dir, draft_dir):
    """THE speculation invariant: any draft, same stream."""
    ref, _, _ = asyncio.run(_serve(target_dir, PROMPTS))
    got, proposed, accepted = asyncio.run(
        _serve(target_dir, PROMPTS, draft=draft_dir)
    )
    assert got == ref
    assert proposed > 0  # speculation actually engaged
    assert 0 <= accepted <= proposed


def test_draft_chained_rounds_stream_identical(target_dir, draft_dir):
    """ISSUE 13: with device finish + dispatch-ahead, draft/target
    rounds interleave off the SAME device carry (no host barrier
    between rounds) — the stream must still equal plain greedy, the
    chain must actually run (>1 round between host barriers), and
    proposals must flow through the chained verify program."""
    ref, _, _ = asyncio.run(_serve(target_dir, PROMPTS, max_tokens=16))
    box = {}
    got, proposed, accepted = asyncio.run(_serve(
        target_dir, PROMPTS, draft=draft_dir, max_tokens=16,
        decode_pipeline_depth=2, chain_len_out=box,
    ))
    assert got == ref
    assert proposed > 0
    assert 0 <= accepted <= proposed
    assert box["spec_rounds"] > 0, "chained verify never ran"
    assert box["chain_len"] > 1, "host barrier still per round"


def test_self_draft_chained_accepts_everything(target_dir):
    """Draft == target under the chained rounds: every proposal
    verifies, so acceptance stays 100% through the carry-folded
    accept path too."""
    ref, _, _ = asyncio.run(_serve(target_dir, PROMPTS[:1]))
    got, proposed, accepted = asyncio.run(_serve(
        target_dir, PROMPTS[:1], draft=target_dir,
        decode_pipeline_depth=2,
    ))
    assert got == ref
    assert proposed > 0 and accepted == proposed


def test_self_draft_accepts_everything(target_dir):
    """Draft == target: every proposal verifies, so each round emits
    K+1 tokens and acceptance is 100%."""
    ref, _, _ = asyncio.run(_serve(target_dir, PROMPTS[:1]))
    got, proposed, accepted = asyncio.run(
        _serve(target_dir, PROMPTS[:1], draft=target_dir)
    )
    assert got == ref
    assert proposed > 0 and accepted == proposed


def test_draft_with_prefix_cache_hit(target_dir, draft_dir):
    """A second identical prompt prefix-hits the target's cache; the
    draft mirror shares block ids, so its context is valid too and the
    stream stays exact."""
    prompts = [PROMPTS[0], PROMPTS[0]]
    ref, _, _ = asyncio.run(_serve(target_dir, prompts))
    got, _, _ = asyncio.run(_serve(target_dir, prompts, draft=draft_dir))
    assert got == ref
    assert got[0] == got[1]


def test_draft_config_validation(target_dir, draft_dir):
    mcfg = ModelConfig.from_model_dir(target_dir)
    with pytest.raises(ValueError, match="2..16"):
        EngineConfig(model=mcfg, spec_draft_model=draft_dir,
                     spec_draft_tokens=1)
    with pytest.raises(ValueError, match="mutually exclusive"):
        EngineConfig(model=mcfg, spec_draft_model=draft_dir,
                     spec_draft_tokens=4, spec_ngram_tokens=4)
    with pytest.raises(ValueError, match="host KV tier"):
        EngineConfig(model=mcfg, spec_draft_model=draft_dir,
                     spec_draft_tokens=4, host_kv_blocks=8)

    with pytest.raises(ValueError, match="without spec_draft_model"):
        EngineConfig(model=mcfg, spec_draft_tokens=4)

    # the draft must cover the target's serving horizon
    too_long = EngineConfig(model=mcfg, max_model_len=4096,
                            spec_draft_model=draft_dir, spec_draft_tokens=4)
    with pytest.raises(ValueError, match="max_position_embeddings"):
        build_draft_config(too_long)

    cfg = EngineConfig(model=mcfg, max_model_len=128,
                       spec_draft_model=draft_dir, spec_draft_tokens=4)
    dcfg = build_draft_config(cfg)
    assert dcfg.model.vocab_size >= mcfg.vocab_size
    assert dcfg.multi_step_decode == 5  # K+1 burst
    assert dcfg.spec_draft_model is None


def test_draft_composes_with_fp8_cache_and_tp(target_dir, draft_dir):
    """Draft speculation atop an fp8 KV cache and a tp-sharded target
    (the draft inherits the cache dtype; it always runs unsharded):
    stream equals the plain engine with the SAME cache dtype."""

    async def serve(draft, kv_dtype, tp):
        econfig = EngineConfig(
            model=ModelConfig.from_model_dir(target_dir),
            max_batch_size=2, max_model_len=128, kv_block_size=8,
            num_kv_blocks=64, dtype="float32", prefill_buckets=[32],
            kv_cache_dtype=kv_dtype, tp_size=tp,
            spec_draft_model=draft, spec_draft_tokens=4 if draft else 0,
        )
        mdc = ModelDeploymentCard.from_local_path(target_dir)
        engine = await JaxServingEngine.create(
            mdc, engine_config=econfig, warmup=False)
        req = PreprocessedRequest(
            token_ids=PROMPTS[0],
            stop_conditions=StopConditions(max_tokens=10, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
        )
        toks = []
        async for out in engine.generate(Context(req)):
            toks.extend(out["token_ids"])
        await engine.close()
        return toks

    ref = asyncio.run(serve(None, "fp8", 1))
    got = asyncio.run(serve(draft_dir, "fp8", 1))
    assert got == ref

    ref_tp = asyncio.run(serve(None, "auto", 2))
    got_tp = asyncio.run(serve(draft_dir, "auto", 2))
    assert got_tp == ref_tp


def test_draft_engine_mixed_traffic_soak(target_dir, draft_dir):
    """Concurrent greedy (spec-eligible), sampled, guided, and logprobs
    requests on a draft-enabled engine: the batch oscillates between the
    speculative and plain paths (which mirror on the draft), and every
    stream must finish with the greedy ones matching a plain engine."""

    async def run(draft):
        econfig = EngineConfig(
            model=ModelConfig.from_model_dir(target_dir),
            max_batch_size=4, max_model_len=128, kv_block_size=8,
            num_kv_blocks=96, dtype="float32", prefill_buckets=[32],
            spec_draft_model=draft, spec_draft_tokens=4 if draft else 0,
        )
        mdc = ModelDeploymentCard.from_local_path(target_dir)
        engine = await JaxServingEngine.create(
            mdc, engine_config=econfig, warmup=False)

        def req(prompt, guided=None, logprobs=None, **kw):
            return PreprocessedRequest(
                token_ids=prompt,
                stop_conditions=StopConditions(max_tokens=8, ignore_eos=True),
                sampling_options=SamplingOptions(
                    guided_choice_token_ids=guided, **kw),
                output_options=OutputOptions(logprobs=logprobs),
            )

        async def collect(r):
            toks = []
            async for out in engine.generate(Context(r)):
                toks.extend(out["token_ids"])
            return toks

        reqs = [
            req(PROMPTS[0], temperature=0.0),                      # greedy
            req(PROMPTS[1], temperature=1.0, seed=3),              # sampled
            req([1, 9, 9, 2], temperature=0.0,
                guided=[[5, 9, 7], [40, 41]]),                     # guided
            req([1, 40, 41, 7], temperature=0.0, logprobs=2),      # greedy+lps
        ]
        outs = await asyncio.gather(*(collect(r) for r in reqs))
        await engine.close()
        return outs

    plain = asyncio.run(run(None))
    drafted = asyncio.run(run(draft_dir))
    # every row is deterministic given its per-request PRNG key and
    # counters (sampling state is per-slot, independent of engine path),
    # so ALL four streams must match the draft-less engine exactly
    assert drafted == plain
    assert drafted[2] in ([5, 9, 7], [40, 41])
    assert all(len(t) > 0 for t in drafted)
