"""Pallas paged flash attention vs. the XLA reference path.

Runs the kernel in interpret mode on CPU (same numerics path as the TPU
Mosaic compile). Reference analog: the reference trusted vLLM's kernels;
here correctness is checked against ops/attention.py's gather/softmax.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.ops.attention import paged_attention
from dynamo_tpu.ops.pallas_attention import paged_flash_attention


def make_case(rng, b, s, h, kvh, d, bs, w, dtype=jnp.float32):
    n_blocks = b * w + 3
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
    k_cache = jnp.asarray(rng.standard_normal((n_blocks, bs, kvh, d)), dtype)
    v_cache = jnp.asarray(rng.standard_normal((n_blocks, bs, kvh, d)), dtype)
    # distinct random pages per sequence
    perm = rng.permutation(n_blocks)[: b * w]
    block_tables = jnp.asarray(perm.reshape(b, w), jnp.int32)
    return q, k_cache, v_cache, block_tables


def affine_positions(base, s):
    return jnp.asarray(base)[:, None] + jnp.arange(s)[None, :]


@pytest.mark.parametrize("s,base,ctx_extra", [
    (1, [37, 5, 0, 16], 1),     # decode: ctx = base + 1
    (16, [0, 0, 3, 9], 16),     # small prefill
    (64, [0, 32, 7, 0], 64),    # bucket prefill with cached prefix
])
def test_matches_xla_reference(s, base, ctx_extra):
    rng = np.random.default_rng(0)
    b, h, kvh, d, bs, w = 4, 8, 4, 64, 16, 8
    q, k_cache, v_cache, bt = make_case(rng, b, s, h, kvh, d, bs, w)
    base = np.asarray(base, np.int32)
    ctx = jnp.asarray(base + ctx_extra, jnp.int32)
    positions = affine_positions(base, s).astype(jnp.int32)

    ref = paged_attention(q, k_cache, v_cache, bt, positions, ctx)
    out = paged_flash_attention(
        q, k_cache, v_cache, bt, jnp.asarray(base, jnp.int32), ctx,
        interpret=True,
    )
    # pad rows (position >= ctx) are garbage by contract — compare valid rows
    valid = np.asarray(positions) < np.asarray(ctx)[:, None]
    np.testing.assert_allclose(
        np.asarray(out)[valid], np.asarray(ref)[valid], rtol=2e-5, atol=2e-5
    )


def test_chunked_long_prefill():
    """S > q_chunk exercises the chunk grid dimension."""
    rng = np.random.default_rng(1)
    b, s, h, kvh, d, bs = 2, 256, 4, 2, 64, 16
    w = s // bs
    q, k_cache, v_cache, bt = make_case(rng, b, s, h, kvh, d, bs, w)
    base = np.zeros(b, np.int32)
    ctx = jnp.full((b,), s, jnp.int32)
    positions = affine_positions(base, s).astype(jnp.int32)

    ref = paged_attention(q, k_cache, v_cache, bt, positions, ctx)
    out = paged_flash_attention(
        q, k_cache, v_cache, bt, jnp.asarray(base), ctx,
        q_chunk=128, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_gqa_groups_and_bf16():
    rng = np.random.default_rng(2)
    b, s, h, kvh, d, bs, w = 2, 32, 8, 2, 32, 8, 8
    q, k_cache, v_cache, bt = make_case(rng, b, s, h, kvh, d, bs, w, jnp.bfloat16)
    base = np.asarray([0, 4], np.int32)
    ctx = jnp.asarray(base + s, jnp.int32)
    positions = affine_positions(base, s).astype(jnp.int32)

    ref = paged_attention(q, k_cache, v_cache, bt, positions, ctx)
    out = paged_flash_attention(
        q, k_cache, v_cache, bt, jnp.asarray(base), ctx, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=5e-2, atol=5e-2,
    )


def test_odd_length_picks_divisor_chunk():
    """S not divisible by 128 (e.g. odd max_model_len buckets) still works."""
    rng = np.random.default_rng(4)
    b, s, h, kvh, d, bs = 1, 96, 4, 2, 64, 16
    w = s // bs
    q, k_cache, v_cache, bt = make_case(rng, b, s, h, kvh, d, bs, w)
    base = np.zeros(b, np.int32)
    ctx = jnp.full((b,), s, jnp.int32)
    positions = affine_positions(base, s).astype(jnp.int32)

    ref = paged_attention(q, k_cache, v_cache, bt, positions, ctx)
    out = paged_flash_attention(
        q, k_cache, v_cache, bt, jnp.asarray(base), ctx,
        q_chunk=64, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_prefill_batch1_on_dp_mesh():
    """B=1 prefill (scheduler's shape) must not break under a dp>1 mesh."""
    from dynamo_tpu.engine.model_runner import build_mesh
    from dynamo_tpu.ops.attention import attention

    rng = np.random.default_rng(5)
    b, s, h, kvh, d, bs, w = 1, 32, 8, 4, 64, 16, 4
    q, k_cache, v_cache, bt = make_case(rng, b, s, h, kvh, d, bs, w)
    base = np.zeros(b, np.int32)
    ctx = jnp.full((b,), s, jnp.int32)
    positions = affine_positions(base, s).astype(jnp.int32)

    mesh = build_mesh(2, 4)
    ref = paged_attention(q, k_cache, v_cache, bt, positions, ctx)
    out = attention(
        q, k_cache, v_cache, bt, positions, ctx,
        impl="pallas", mesh=mesh, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_shard_map_wrapper_on_cpu_mesh():
    """attention(impl='pallas') under a 2x4 dp x tp mesh of CPU devices."""
    from dynamo_tpu.engine.model_runner import build_mesh
    from dynamo_tpu.ops.attention import attention

    rng = np.random.default_rng(3)
    b, s, h, kvh, d, bs, w = 4, 16, 8, 4, 64, 16, 4
    q, k_cache, v_cache, bt = make_case(rng, b, s, h, kvh, d, bs, w)
    base = np.zeros(b, np.int32)
    ctx = jnp.full((b,), s, jnp.int32)
    positions = affine_positions(base, s).astype(jnp.int32)

    mesh = build_mesh(2, 4)
    ref = paged_attention(q, k_cache, v_cache, bt, positions, ctx)
    out = attention(
        q, k_cache, v_cache, bt, positions, ctx,
        impl="pallas", mesh=mesh, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)
