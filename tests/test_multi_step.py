"""Fused multi-step decode (EngineConfig.multi_step_decode).

The K-step burst must be invisible in outputs: the same prompts, seeds,
and sampling knobs produce bit-identical token streams whether the
engine dispatches per token (K=1) or per burst (K>1) — the burst fuses
dispatch, not semantics. Reference analog: the multi-step scheduling of
the engines behind examples/llm/components/worker.py, which likewise
trades ITL granularity for dispatch amortization.
"""

import asyncio
import json
import os

import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.serving import JaxServingEngine
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import Context

from fixtures import make_model_dir

TINY = dict(
    vocab_size=512,
    hidden_size=64,
    intermediate_size=128,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    max_position_embeddings=256,
    rms_norm_eps=1e-5,
    rope_theta=10000.0,
)


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    d = make_model_dir(tmp_path_factory.mktemp("msmodel"), name="tiny-ms")
    cfg = LlamaConfig(**TINY, tie_word_embeddings=False)
    torch.manual_seed(0)
    LlamaForCausalLM(cfg).save_pretrained(d, safe_serialization=True)
    with open(os.path.join(d, "config.json")) as f:
        c = json.load(f)
    c["eos_token_id"] = 2
    c["bos_token_id"] = 1
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump(c, f)
    return d


def _config(model_dir, multi_step, **kw):
    cfg = ModelConfig.from_model_dir(model_dir)
    return EngineConfig(
        model=cfg, max_batch_size=4, max_model_len=128, kv_block_size=8,
        num_kv_blocks=96, dtype="float32", multi_step_decode=multi_step,
        **kw,
    )


async def _collect(engine, token_ids, sampling, max_tokens=24,
                   ignore_eos=True, stop_hidden=None):
    req = PreprocessedRequest(
        token_ids=list(token_ids),
        stop_conditions=StopConditions(
            max_tokens=max_tokens, ignore_eos=ignore_eos,
            stop_token_ids_hidden=stop_hidden,
        ),
        sampling_options=sampling,
    )
    toks, finish = [], None
    async for out in engine.generate(Context(req)):
        toks.extend(out["token_ids"])
        if out.get("finish_reason"):
            finish = out["finish_reason"]
    return toks, finish


def _runs(model_dir, multi_step):
    async def go():
        mdc = ModelDeploymentCard.from_local_path(model_dir)
        engine = await JaxServingEngine.create(
            mdc, engine_config=_config(model_dir, multi_step), warmup=False
        )
        results = []
        # greedy; seeded sampling; penalties + repetition; concurrent pair
        results.append(await _collect(
            engine, [1, 17, 43, 99, 7], SamplingOptions(temperature=0.0)))
        results.append(await _collect(
            engine, [1, 5, 9, 13], SamplingOptions(temperature=0.8, seed=7)))
        results.append(await _collect(
            engine, [1, 100, 200, 300],
            SamplingOptions(temperature=0.7, seed=3, top_k=40,
                            frequency_penalty=0.5, repetition_penalty=1.2)))
        pair = await asyncio.gather(
            _collect(engine, [1, 42, 42], SamplingOptions(temperature=0.0)),
            _collect(engine, [1, 7, 7, 7, 7],
                     SamplingOptions(temperature=0.9, seed=11)),
        )
        results.extend(pair)
        await engine.close()
        return results

    return asyncio.get_event_loop_policy().new_event_loop().run_until_complete(go())


def test_burst_streams_bit_equal_to_single_step(model_dir):
    assert _runs(model_dir, 1) == _runs(model_dir, 4)


@pytest.mark.asyncio
async def test_burst_actually_engages(model_dir):
    # guard against the equivalence tests passing vacuously: K=4 must
    # produce ~4x fewer device dispatches for the same token count
    mdc = ModelDeploymentCard.from_local_path(model_dir)
    engine = await JaxServingEngine.create(
        mdc, engine_config=_config(model_dir, 4), warmup=False)
    toks, _ = await _collect(engine, [1, 5, 9],
                             SamplingOptions(temperature=0.0), max_tokens=16)
    steps = engine.scheduler.steps
    await engine.close()
    assert len(toks) == 16
    # 1 prefill dispatch + ceil(16/4) bursts, plus slack for scheduling
    assert steps <= 8, f"burst never engaged ({steps} dispatches)"


@pytest.mark.asyncio
async def test_burst_stop_mid_burst_trims_and_finishes(model_dir):
    mdc = ModelDeploymentCard.from_local_path(model_dir)
    single = await JaxServingEngine.create(
        mdc, engine_config=_config(model_dir, 1), warmup=False)
    # find greedy continuation, then declare its 2nd token a hidden stop:
    # under K=4 the stop lands mid-burst and the tail must be trimmed
    toks, _ = await _collect(single, [1, 5, 9],
                             SamplingOptions(temperature=0.0), max_tokens=6)
    stop_tok = toks[1]
    want, want_finish = await _collect(
        single, [1, 5, 9], SamplingOptions(temperature=0.0), max_tokens=6,
        stop_hidden=[stop_tok])
    await single.close()
    assert want_finish == "stop" and len(want) < len(toks)

    burst = await JaxServingEngine.create(
        mdc, engine_config=_config(model_dir, 4), warmup=False)
    got, finish = await _collect(
        burst, [1, 5, 9], SamplingOptions(temperature=0.0), max_tokens=6,
        stop_hidden=[stop_tok])
    await burst.close()
    assert (got, finish) == (want, want_finish)


@pytest.mark.asyncio
async def test_burst_near_model_len_falls_back_and_finishes(model_dir):
    # a context within K of max_model_len forces per-token stepping; the
    # request still ends with reason length at the same point
    mdc = ModelDeploymentCard.from_local_path(model_dir)
    cfg = _config(model_dir, 8)
    cfg.max_model_len = 32
    engine = await JaxServingEngine.create(
        mdc, engine_config=cfg, warmup=False)
    toks, finish = await _collect(
        engine, list(range(1, 21)), SamplingOptions(temperature=0.0),
        max_tokens=64)
    await engine.close()
    assert finish == "length"
    assert len(toks) == 32 - 20  # runs right up to max_model_len


@pytest.mark.asyncio
async def test_burst_with_prefix_cache_reuse(model_dir):
    # burst-written blocks enter the prefix cache; a rerun must hit the
    # cache and still produce the identical stream
    mdc = ModelDeploymentCard.from_local_path(model_dir)
    engine = await JaxServingEngine.create(
        mdc, engine_config=_config(model_dir, 4, enable_prefix_caching=True),
        warmup=False)
    prompt = [1] + list(range(50, 50 + 23))
    first, _ = await _collect(engine, prompt, SamplingOptions(temperature=0.0))
    second, _ = await _collect(engine, prompt, SamplingOptions(temperature=0.0))
    m = engine.metrics()
    await engine.close()
    assert first == second
    assert m["gpu_prefix_cache_hit_rate"] > 0.0
