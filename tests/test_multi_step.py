"""Fused multi-step decode (EngineConfig.multi_step_decode).

The K-step burst must be invisible in outputs: the same prompts, seeds,
and sampling knobs produce bit-identical token streams whether the
engine dispatches per token (K=1) or per burst (K>1) — the burst fuses
dispatch, not semantics. Reference analog: the multi-step scheduling of
the engines behind examples/llm/components/worker.py, which likewise
trades ITL granularity for dispatch amortization.
"""

import asyncio
import json
import os

import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.serving import JaxServingEngine
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import Context

from fixtures import make_model_dir

TINY = dict(
    vocab_size=512,
    hidden_size=64,
    intermediate_size=128,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    max_position_embeddings=256,
    rms_norm_eps=1e-5,
    rope_theta=10000.0,
)


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    d = make_model_dir(tmp_path_factory.mktemp("msmodel"), name="tiny-ms")
    cfg = LlamaConfig(**TINY, tie_word_embeddings=False)
    torch.manual_seed(0)
    LlamaForCausalLM(cfg).save_pretrained(d, safe_serialization=True)
    with open(os.path.join(d, "config.json")) as f:
        c = json.load(f)
    c["eos_token_id"] = 2
    c["bos_token_id"] = 1
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump(c, f)
    return d


def _config(model_dir, multi_step, pipeline=1, **kw):
    cfg = ModelConfig.from_model_dir(model_dir)
    return EngineConfig(
        model=cfg, max_batch_size=4, max_model_len=128, kv_block_size=8,
        num_kv_blocks=96, dtype="float32", multi_step_decode=multi_step,
        decode_pipeline_depth=pipeline, **kw,
    )


async def _collect(engine, token_ids, sampling, max_tokens=24,
                   ignore_eos=True, stop_hidden=None):
    req = PreprocessedRequest(
        token_ids=list(token_ids),
        stop_conditions=StopConditions(
            max_tokens=max_tokens, ignore_eos=ignore_eos,
            stop_token_ids_hidden=stop_hidden,
        ),
        sampling_options=sampling,
    )
    toks, finish = [], None
    async for out in engine.generate(Context(req)):
        toks.extend(out["token_ids"])
        if out.get("finish_reason"):
            finish = out["finish_reason"]
    return toks, finish


def _runs(model_dir, multi_step, pipeline=1):
    async def go():
        mdc = ModelDeploymentCard.from_local_path(model_dir)
        engine = await JaxServingEngine.create(
            mdc, engine_config=_config(model_dir, multi_step, pipeline),
            warmup=False,
        )
        results = []
        # greedy; seeded sampling; penalties + repetition; concurrent pair
        results.append(await _collect(
            engine, [1, 17, 43, 99, 7], SamplingOptions(temperature=0.0)))
        results.append(await _collect(
            engine, [1, 5, 9, 13], SamplingOptions(temperature=0.8, seed=7)))
        results.append(await _collect(
            engine, [1, 100, 200, 300],
            SamplingOptions(temperature=0.7, seed=3, top_k=40,
                            frequency_penalty=0.5, repetition_penalty=1.2)))
        pair = await asyncio.gather(
            _collect(engine, [1, 42, 42], SamplingOptions(temperature=0.0)),
            _collect(engine, [1, 7, 7, 7, 7],
                     SamplingOptions(temperature=0.9, seed=11)),
        )
        results.extend(pair)
        await engine.close()
        return results

    return asyncio.get_event_loop_policy().new_event_loop().run_until_complete(go())


def test_burst_streams_bit_equal_to_single_step(model_dir):
    assert _runs(model_dir, 1) == _runs(model_dir, 4)


@pytest.mark.asyncio
async def test_burst_actually_engages(model_dir):
    # guard against the equivalence tests passing vacuously: K=4 must
    # produce ~4x fewer device dispatches for the same token count
    mdc = ModelDeploymentCard.from_local_path(model_dir)
    engine = await JaxServingEngine.create(
        mdc, engine_config=_config(model_dir, 4), warmup=False)
    toks, _ = await _collect(engine, [1, 5, 9],
                             SamplingOptions(temperature=0.0), max_tokens=16)
    steps = engine.scheduler.steps
    await engine.close()
    assert len(toks) == 16
    # 1 prefill dispatch + ceil(16/4) bursts, plus slack for scheduling
    assert steps <= 8, f"burst never engaged ({steps} dispatches)"


@pytest.mark.asyncio
async def test_burst_stop_mid_burst_trims_and_finishes(model_dir):
    mdc = ModelDeploymentCard.from_local_path(model_dir)
    single = await JaxServingEngine.create(
        mdc, engine_config=_config(model_dir, 1), warmup=False)
    # find greedy continuation, then declare its 2nd token a hidden stop:
    # under K=4 the stop lands mid-burst and the tail must be trimmed
    toks, _ = await _collect(single, [1, 5, 9],
                             SamplingOptions(temperature=0.0), max_tokens=6)
    stop_tok = toks[1]
    want, want_finish = await _collect(
        single, [1, 5, 9], SamplingOptions(temperature=0.0), max_tokens=6,
        stop_hidden=[stop_tok])
    await single.close()
    assert want_finish == "stop" and len(want) < len(toks)

    burst = await JaxServingEngine.create(
        mdc, engine_config=_config(model_dir, 4), warmup=False)
    got, finish = await _collect(
        burst, [1, 5, 9], SamplingOptions(temperature=0.0), max_tokens=6,
        stop_hidden=[stop_tok])
    await burst.close()
    assert (got, finish) == (want, want_finish)


@pytest.mark.asyncio
async def test_burst_near_model_len_falls_back_and_finishes(model_dir):
    # a context within K of max_model_len forces per-token stepping; the
    # request still ends with reason length at the same point
    mdc = ModelDeploymentCard.from_local_path(model_dir)
    cfg = _config(model_dir, 8)
    cfg.max_model_len = 32
    engine = await JaxServingEngine.create(
        mdc, engine_config=cfg, warmup=False)
    toks, finish = await _collect(
        engine, list(range(1, 21)), SamplingOptions(temperature=0.0),
        max_tokens=64)
    await engine.close()
    assert finish == "length"
    assert len(toks) == 32 - 20  # runs right up to max_model_len


def test_pipelined_streams_bit_equal_to_sync(model_dir):
    """Dispatch-ahead (decode_pipeline_depth=2) must be invisible in
    outputs: greedy, seeded sampling, penalties, and concurrent pairs
    all produce byte-identical streams vs the synchronous path."""
    assert _runs(model_dir, 4, pipeline=1) == _runs(model_dir, 4, pipeline=2)


def test_pipelined_single_step_bursts_bit_equal(model_dir):
    # pipelining with multi_step_decode=1 runs a K=1 burst program —
    # still identical to the plain per-token path
    assert _runs(model_dir, 1, pipeline=1) == _runs(model_dir, 1, pipeline=2)


@pytest.mark.asyncio
async def test_pipelined_eos_one_burst_late_trims_and_finishes(model_dir):
    """A stop token landing mid-burst under depth 2 is detected one burst
    late: the over-decoded burst must be retro-invalidated (tokens
    truncated, blocks rolled back, slot freed) and the emitted stream
    must equal the synchronous path's, byte for byte."""
    mdc = ModelDeploymentCard.from_local_path(model_dir)
    single = await JaxServingEngine.create(
        mdc, engine_config=_config(model_dir, 4), warmup=False)
    toks, _ = await _collect(single, [1, 5, 9],
                             SamplingOptions(temperature=0.0), max_tokens=12)
    stop_tok = toks[5]  # lands mid-burst AND one burst late under K=4
    want, want_finish = await _collect(
        single, [1, 5, 9], SamplingOptions(temperature=0.0), max_tokens=12,
        stop_hidden=[stop_tok])
    await single.close()
    assert want_finish == "stop" and len(want) < len(toks)

    piped = await JaxServingEngine.create(
        mdc, engine_config=_config(model_dir, 4, pipeline=2), warmup=False)
    got, finish = await _collect(
        piped, [1, 5, 9], SamplingOptions(temperature=0.0), max_tokens=12,
        stop_hidden=[stop_tok])
    sched = piped.scheduler
    assert sched.pipeline_bursts > 0, "pipeline never engaged"
    assert sched._inflight is None  # nothing left unreconciled
    # retro-invalidation returned every block (no leak from headroom)
    assert sched.allocator.used == 0
    await piped.close()
    assert (got, finish) == (want, want_finish)


@pytest.mark.asyncio
async def test_pipelined_bubble_metric_and_depth_gauge(model_dir):
    """The pipelined run must dispatch ahead (depth gauge reads 2 while a
    burst is in flight) and record bubble observations; the sync run
    records strictly positive gaps."""
    mdc = ModelDeploymentCard.from_local_path(model_dir)

    async def run(depth):
        engine = await JaxServingEngine.create(
            mdc, engine_config=_config(model_dir, 4, pipeline=depth),
            warmup=False)
        await _collect(engine, [1, 5, 9], SamplingOptions(temperature=0.0),
                       max_tokens=16)
        hist = engine.scheduler._bubble_hist
        key = ()
        totals = hist.totals.get(key, 0)
        sums = hist.sums.get(key, 0.0)
        bursts = engine.scheduler.pipeline_bursts
        exposition = engine.scheduler.registry.render()
        await engine.close()
        return totals, sums, bursts, exposition

    n_sync, sum_sync, bursts_sync, _ = await run(1)
    n_pipe, sum_pipe, bursts_pipe, expo = await run(2)
    assert bursts_sync == 0 and bursts_pipe > 0
    assert n_sync > 0 and sum_sync > 0.0  # sync path: real host bubbles
    assert n_pipe > 0  # pipelined path still observes (mostly zeros)
    assert "dynamo_engine_decode_pipeline_bubble_seconds_bucket" in expo
    assert "dynamo_engine_decode_pipeline_depth" in expo


@pytest.mark.asyncio
async def test_burst_with_prefix_cache_reuse(model_dir):
    # burst-written blocks enter the prefix cache; a rerun must hit the
    # cache and still produce the identical stream
    mdc = ModelDeploymentCard.from_local_path(model_dir)
    engine = await JaxServingEngine.create(
        mdc, engine_config=_config(model_dir, 4, enable_prefix_caching=True),
        warmup=False)
    prompt = [1] + list(range(50, 50 + 23))
    first, _ = await _collect(engine, prompt, SamplingOptions(temperature=0.0))
    second, _ = await _collect(engine, prompt, SamplingOptions(temperature=0.0))
    m = engine.metrics()
    await engine.close()
    assert first == second
    assert m["gpu_prefix_cache_hit_rate"] > 0.0
