"""Metric-name convention lint (scripts/check_metric_names.py) as a fast
tier-1 test, so a PR registering an off-convention instrument fails CI."""

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))

from check_metric_names import (  # noqa: E402
    RegisteredMetric,
    check_name,
    iter_registered_metrics,
    run_check,
)

PACKAGE_ROOT = os.path.join(REPO_ROOT, "dynamo_tpu")


def test_all_registered_metric_names_conform():
    violations = run_check(PACKAGE_ROOT)
    assert not violations, "\n".join(violations)


def test_lint_sees_the_real_instrument_catalog():
    """The AST walk must actually find the known call sites — an empty
    scan would make the conformance test pass vacuously."""
    names = {m.name for m in iter_registered_metrics(PACKAGE_ROOT)}
    expected = {
        "dynamo_http_service_requests_total",
        "dynamo_http_service_time_to_first_token_seconds",
        "dynamo_scheduler_step_duration_seconds",
        "dynamo_scheduler_inter_token_latency_seconds",
        "dynamo_kv_evictions_total",
        "dynamo_kv_block_usage_ratio",
        "dynamo_kv_router_decisions_total",
        "dynamo_kv_router_worker_staleness_seconds",
        "dynamo_disagg_remote_prefill_duration_seconds",
        "dynamo_disagg_remote_prefill_failures_total",
        # streamed remote prefill (disagg/prefill_worker.py)
        "dynamo_prefill_worker_prefills_total",
        "dynamo_prefill_worker_prefill_tokens_total",
        "dynamo_prefill_worker_queue_wait_seconds",
        "dynamo_prefill_worker_prefix_hit_ratio",
        # unified transfer plane (transfer/plane.py): one
        # {plane,backend}-labelled family replaces the per-plane
        # transfer instruments the disagg/fabric planes used to register
        "dynamo_transfer_bytes_total",
        "dynamo_transfer_duration_seconds",
        "dynamo_transfer_exposed_seconds",
        "dynamo_transfer_channels",
        # flight recorder / watchdog / XLA compile observability
        # (telemetry/flight.py, telemetry/watchdog.py)
        "dynamo_engine_xla_compiles_total",
        "dynamo_engine_xla_compile_duration_seconds",
        "dynamo_watchdog_trips_total",
        "dynamo_runtime_event_loop_lag_seconds",
        # closed-loop SLA planner (planner/admission.py, planner/planner.py)
        "dynamo_planner_admissions_total",
        "dynamo_planner_queue_wait_seconds",
        "dynamo_planner_admission_queue_depth_requests",
        "dynamo_planner_inflight_requests",
        "dynamo_planner_admission_limit_requests",
        "dynamo_planner_shedding_info",
        "dynamo_planner_actions_total",
        "dynamo_planner_cycles_total",
        "dynamo_planner_replica_target_replicas",
        "dynamo_planner_shed_level_depth",
        "dynamo_planner_local_prefill_threshold_tokens",
        # staleness-aware KV routing (kv_router/router.py)
        "dynamo_kv_router_stale_worker_skips_total",
        # persistent decode loop: device-resident finish detection
        # (engine/scheduler.py)
        "dynamo_engine_device_finished_rows_total",
        "dynamo_engine_decode_drain_lag_seconds",
        "dynamo_engine_decode_burst_chain_length",
        # self-healing serving (recovery/controller.py,
        # llm/engines/subprocess_host.py, kv_router/router.py)
        "dynamo_recovery_actions_total",
        "dynamo_recovery_migrations_total",
        "dynamo_recovery_drain_duration_seconds",
        "dynamo_engine_restarts_total",
        "dynamo_kv_router_draining_worker_skips_total",
        # request X-ray: device-time/roofline attribution
        # (telemetry/device_time.py), SLO goodput (telemetry/slo.py),
        # bounded trace store (telemetry/tracing.py)
        "dynamo_engine_device_time_seconds",
        "dynamo_engine_device_busy_ratio",
        "dynamo_engine_roofline_fraction",
        "dynamo_slo_attainment_total",
        "dynamo_slo_goodput_tokens_total",
        "dynamo_slo_target_seconds",
        "dynamo_trace_evicted_total",
        "dynamo_trace_store_requests",
        # fleet telemetry hub + incident recorder (telemetry/hub.py,
        # telemetry/incidents.py, engine/scheduler.py drain gauge)
        "dynamo_hub_scrapes_total",
        "dynamo_hub_scrape_duration_seconds",
        "dynamo_hub_fleet_workers_replicas",
        "dynamo_hub_fleet_busy_ratio",
        "dynamo_hub_fleet_kv_usage_ratio",
        "dynamo_hub_history_series_depth",
        "dynamo_incidents_total",
        "dynamo_incidents_suppressed_total",
        "dynamo_scheduler_draining_info",
        # cluster KV fabric: cross-worker prefix pull (kv/fabric.py)
        # + content-addressed cold tier (kv/cold_tier.py)
        "dynamo_kv_fabric_prefix_pull_total",
        "dynamo_kv_fabric_cold_tier_hits_total",
        "dynamo_kv_fabric_cold_tier_misses_total",
        "dynamo_kv_fabric_cold_tier_evictions_total",
        "dynamo_kv_fabric_cold_tier_bytes",
        # multi-model multi-tenant fleet (registry/: registry.py cards
        # view, pools.py scale-to-zero + cold start, tenants.py token
        # buckets; cli/run.py worker model advertisement)
        "dynamo_registry_models_info",
        "dynamo_registry_model_info",
        "dynamo_registry_pool_workers_replicas",
        "dynamo_registry_cold_starts_total",
        "dynamo_registry_scale_to_zero_total",
        "dynamo_registry_cold_start_wait_seconds",
        "dynamo_registry_tenant_sheds_total",
        "dynamo_registry_tenant_fallbacks_total",
        "dynamo_registry_tenant_tokens_total",
        # unrestricted persistent decode (engine/scheduler.py): the
        # sync-path fallback ladder attribution + the in-carry
        # propose-verify acceptance-length histogram
        "dynamo_engine_sync_fallback_total",
        "dynamo_engine_spec_accept_length",
        # attention route attribution (ops/attention.py): which kernel
        # each compiled program's attention resolved to, counted once
        # per trace via the CompileTracker dispatch hook
        "dynamo_engine_attention_route_total",
        # sequence-parallel long-context prefill (engine/scheduler.py;
        # docs/long_context.md)
        "dynamo_engine_prefill_sp_chunks_total",
        "dynamo_engine_prefill_sp_tokens_total",
        "dynamo_engine_prefill_sp_axis_depth",
        "dynamo_engine_prefill_sp_exposed_seconds",
        # trace-driven fleet simulator (sim/metrics.py): run counters
        # and gauges published through the standard /metrics plumbing
        "dynamo_sim_requests_total",
        "dynamo_sim_tokens_total",
        "dynamo_sim_scale_actions_total",
        "dynamo_sim_chaos_injections_total",
        "dynamo_sim_recoveries_total",
        "dynamo_sim_watchdog_trips_total",
        "dynamo_sim_resubmits_total",
        "dynamo_sim_slo_attainment_ratio",
        "dynamo_sim_kv_usage_ratio",
        "dynamo_sim_virtual_time_seconds",
        "dynamo_sim_workers_replicas",
    }
    missing = expected - names
    assert not missing, f"lint no longer sees: {sorted(missing)}"
    assert len(names) >= 115


def _metric(name, kind):
    return RegisteredMetric(name, kind, "x.py", 1)


def test_rules_reject_bad_names():
    assert check_name(_metric("dynamo_scheduler_preemptions", "counter"))
    assert check_name(_metric("dynamo_BadCase_seconds", "gauge"))
    # NOTE "depth" joined the unit vocabulary with the decode-pipeline
    # depth gauge (structural stage counts); "size" remains a non-unit
    assert check_name(_metric("dynamo_queue_size", "gauge"))
    assert check_name(_metric("dynamo_kv_usage_ratio", "histogram"))
    assert check_name(_metric("dynamo_kv_blocks_total", "gauge"))
    # too few segments: no component between prefix and unit
    assert check_name(_metric("dynamo_total", "counter"))


def test_rules_accept_good_names():
    assert not check_name(_metric("dynamo_scheduler_preemptions_total", "counter"))
    assert not check_name(_metric("dynamo_scheduler_step_duration_seconds", "histogram"))
    assert not check_name(_metric("dynamo_kv_block_usage_ratio", "gauge"))
    assert not check_name(_metric("dynamo_scheduler_active_slots", "gauge"))
    # "fraction" joined the unit vocabulary with the live roofline gauge
    # (achieved-over-physical-bound, vs "ratio"'s part-of-whole share)
    assert not check_name(_metric("dynamo_engine_roofline_fraction", "gauge"))
    # it names a bound comparison, not a base unit a histogram measures
    assert check_name(_metric("dynamo_engine_roofline_fraction", "histogram"))
