"""Pipeline parallelism vs. the plain forward (virtual CPU pp mesh).

The collective GPipe schedule (parallel/pipeline.py) must be numerically
identical to llama.forward — same logits, same KV cache contents — for
prefill and decode, with M == P and M > P microbatches."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.config import ModelConfig
from dynamo_tpu.models import llama
from dynamo_tpu.parallel.mesh import make_mesh
from dynamo_tpu.parallel.pipeline import (
    pipeline_forward,
    stage_cache,
    stage_params,
    unstage_cache,
)

CFG = ModelConfig(
    vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=4,
    num_heads=4, num_kv_heads=2, head_dim=8, attention_impl="xla",
)


def _setup(b, s, bs=8, blocks=32):
    params = llama.init_params(CFG, jax.random.PRNGKey(0), jnp.float32)
    kv = llama.init_kv_cache(CFG, blocks, bs, jnp.float32)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, CFG.vocab_size, (b, s)), jnp.int32)
    positions = jnp.tile(jnp.arange(s, dtype=jnp.int32), (b, 1))
    w = 4
    btab = jnp.asarray(
        (np.arange(b * w).reshape(b, w)) % blocks, jnp.int32
    )
    slots = (
        jnp.take_along_axis(btab, positions // bs, axis=1) * bs + positions % bs
    ).astype(jnp.int32)
    ctx = jnp.full((b,), s, jnp.int32)
    return params, kv, tokens, positions, btab, slots, ctx


@pytest.mark.parametrize("microbatches", [None, 8])
def test_pp_prefill_matches_plain_forward(microbatches):
    pp = 4
    mesh = make_mesh({"pp": pp})
    b, s = 8, 16
    params, kv, tokens, positions, btab, slots, ctx = _setup(b, s)

    ref_logits, ref_kv = llama.forward(
        params, CFG, tokens, positions, kv, btab, slots, ctx
    )

    staged = stage_params(params, pp)
    skv = stage_cache(kv, pp)
    got_logits, got_kv = pipeline_forward(
        staged, CFG, tokens, positions, skv, btab, slots, ctx, mesh,
        num_microbatches=microbatches,
    )
    np.testing.assert_allclose(
        np.asarray(got_logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
    )
    for got, ref in zip(unstage_cache(got_kv), ref_kv):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4
        )


def test_pp_decode_matches_plain_forward():
    pp = 2
    mesh = make_mesh({"pp": pp})
    b, s = 4, 1
    bs = 8
    params, kv, _, _, btab, _, _ = _setup(b, 1, bs=bs)
    ctx_prev = 5
    positions = jnp.full((b, 1), ctx_prev, jnp.int32)
    tokens = jnp.asarray(np.arange(b).reshape(b, 1) + 3, jnp.int32)
    slots = (btab[:, ctx_prev // bs] * bs + ctx_prev % bs)[:, None]
    ctx = jnp.full((b,), ctx_prev + 1, jnp.int32)
    # pre-populate the cache so decode attends over history
    k0 = jax.random.normal(jax.random.PRNGKey(1), kv[0].shape, jnp.float32)
    v0 = jax.random.normal(jax.random.PRNGKey(2), kv[1].shape, jnp.float32)
    kv = (k0, v0)

    ref_logits, ref_kv = llama.forward(
        params, CFG, tokens, positions, kv, btab, slots, ctx
    )
    got_logits, got_kv = pipeline_forward(
        stage_params(params, pp), CFG, tokens, positions, stage_cache(kv, pp),
        btab, slots, ctx, mesh,
    )
    np.testing.assert_allclose(
        np.asarray(got_logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
    )
    for got, ref in zip(unstage_cache(got_kv), ref_kv):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4
        )


def test_pp_rejects_bad_shapes():
    mesh = make_mesh({"pp": 4})
    params = llama.init_params(CFG, jax.random.PRNGKey(0), jnp.float32)
    with pytest.raises(ValueError, match="not divisible"):
        stage_params(params, 3)


def test_pp_tp_matches_plain_forward():
    """pp x tp: layers staged over pp AND heads/columns Megatron-sharded
    over tp inside each stage (psum after wo / w_down) — numerically the
    plain forward."""
    pp, tp = 2, 2
    mesh = make_mesh({"pp": pp, "tp": tp})
    b, s = 4, 8
    params, kv, tokens, positions, btab, slots, ctx = _setup(b, s)

    ref_logits, ref_kv = llama.forward(
        params, CFG, tokens, positions, kv, btab, slots, ctx
    )

    staged = stage_params(params, pp)
    skv = stage_cache(kv, pp)
    got_logits, got_kv = pipeline_forward(
        staged, CFG, tokens, positions, skv, btab, slots, ctx, mesh,
    )
    np.testing.assert_allclose(
        np.asarray(got_logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
    )
    for got, ref in zip(unstage_cache(got_kv), ref_kv):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4
        )


# ---------- serving-engine integration (EngineConfig.pp_size) ----------


def test_model_runner_pp_matches_single_stage():
    """ModelRunner with pp_size=2 (and pp x tp) must produce the same
    step outputs as the plain single-device runner — params are staged
    and the cache stage-sharded inside the runner."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.model_runner import ModelRunner

    params = llama.init_params(CFG, jax.random.PRNGKey(0), jnp.float32)

    def run_steps(econfig):
        runner = ModelRunner(econfig, params=params)
        b, s, bs = 4, 8, 8
        rng = np.random.default_rng(1)
        tokens = rng.integers(0, CFG.vocab_size, (b, s)).astype(np.int32)
        positions = np.tile(np.arange(s, dtype=np.int32), (b, 1))
        w = econfig.blocks_per_seq
        btab = np.zeros((b, w), np.int32)
        for i in range(b):
            btab[i, : s // bs] = np.arange(i * (s // bs), (i + 1) * (s // bs))
        slots = np.take_along_axis(
            btab, positions // bs, axis=1
        ) * bs + positions % bs
        ctx = np.full(b, s, np.int32)
        last = np.full(b, s - 1, np.int32)
        out1, *_ = runner.step(
            tokens, positions, btab, slots, ctx, last,
            np.zeros(b, np.float32), np.zeros(b, np.int32),
            np.ones(b, np.float32), jax.random.PRNGKey(0),
        )
        # one decode step on top
        dec = np.asarray(out1).reshape(b, 1).astype(np.int32)
        dslots = (btab[:, s // bs] * bs + s % bs).reshape(b, 1)
        for i in range(b):
            btab[i, s // bs] = b * (s // bs) + i
            dslots[i, 0] = btab[i, s // bs] * bs
        out2, *_ = runner.step(
            dec, np.full((b, 1), s, np.int32), btab, dslots,
            np.full(b, s + 1, np.int32), np.zeros(b, np.int32),
            np.zeros(b, np.float32), np.zeros(b, np.int32),
            np.ones(b, np.float32), jax.random.PRNGKey(1),
        )
        return np.asarray(out1), np.asarray(out2)

    def cfg_for(pp, tp, dp=1):
        return EngineConfig(
            model=CFG, max_batch_size=4, max_model_len=64, kv_block_size=8,
            num_kv_blocks=64, dtype="float32", pp_size=pp, tp_size=tp,
            dp_size=dp, prefill_buckets=[16], allow_random_weights=True,
        )

    ref1, ref2 = run_steps(cfg_for(1, 1))
    pp1, pp2 = run_steps(cfg_for(2, 1))
    np.testing.assert_array_equal(pp1, ref1)
    np.testing.assert_array_equal(pp2, ref2)
    pt1, pt2 = run_steps(cfg_for(2, 2))
    np.testing.assert_array_equal(pt1, ref1)
    np.testing.assert_array_equal(pt2, ref2)
    # pp x dp: batch shards over the auto dp axis through the pipeline
    pd1, pd2 = run_steps(cfg_for(2, 2, dp=2))
    np.testing.assert_array_equal(pd1, ref1)
    np.testing.assert_array_equal(pd2, ref2)


def test_pp_engine_serves_request_end_to_end():
    """A request served through JaxServingEngine with pp_size=2 streams
    the same greedy tokens as the single-stage engine."""
    import asyncio

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.serving import JaxServingEngine
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    params = llama.init_params(CFG, jax.random.PRNGKey(0), jnp.float32)
    mdc = ModelDeploymentCard(display_name="t", slug="t", model_path=None)

    async def serve(pp, multi_step=1):
        econfig = EngineConfig(
            model=CFG, max_batch_size=4, max_model_len=64, kv_block_size=8,
            num_kv_blocks=64, dtype="float32", pp_size=pp,
            prefill_buckets=[16], allow_random_weights=True,
            multi_step_decode=multi_step,
        )
        engine = await JaxServingEngine.create(
            mdc, engine_config=econfig, params=params, warmup=False
        )
        req = PreprocessedRequest(
            token_ids=[1, 17, 43, 99, 7, 3],
            stop_conditions=StopConditions(max_tokens=8, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
        )
        got = []
        async for out in engine.generate(Context(req)):
            got.extend(out["token_ids"])
        await engine.close()
        return got

    ref = asyncio.run(serve(1))
    got = asyncio.run(serve(2))
    assert got == ref and len(got) == 8
    # the fused decode burst composes with the staged pp trunk: the scan
    # body traces pipeline_forward per step, stream unchanged
    burst = asyncio.run(serve(2, multi_step=4))
    assert burst == ref


def test_pp_rejects_unsupported_configs():
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.model_runner import ModelRunner

    # MLA stages over pp (replicated dense prefix + staged MoE trunk) —
    # but manual tp inside a stage has no latent head axis to shard
    mla = ModelConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=4,
        num_heads=4, num_kv_heads=4, head_dim=16, kv_lora_rank=16,
        qk_rope_head_dim=8, qk_nope_head_dim=12, v_head_dim=12,
    )
    with pytest.raises(NotImplementedError, match="not tp"):
        ModelRunner(EngineConfig(
            model=mla, max_batch_size=2, max_model_len=32,
            kv_block_size=8, num_kv_blocks=16, dtype="float32", pp_size=2,
            tp_size=2, allow_random_weights=True,
        ))
    with pytest.raises(ValueError):
        ModelRunner(EngineConfig(
            model=ModelConfig(
                vocab_size=128, hidden_size=32, intermediate_size=64,
                num_layers=3, num_heads=4, num_kv_heads=2, head_dim=8,
            ),
            max_batch_size=2, max_model_len=32, kv_block_size=8,
            num_kv_blocks=16, dtype="float32", pp_size=2,
            allow_random_weights=True,
        ))


def test_pp_dp_shards_batch_and_matches():
    """pp x dp x tp: dp is a GSPMD (auto) axis — batch arrays arrive
    dp-sharded and the pipelined program must produce the same logits
    and cache as the plain forward."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dynamo_tpu.engine.model_runner import build_mesh

    mesh = build_mesh(2, 2, pp=2)  # pp2 x dp2 x tp2 = 8 devices
    b, s = 4, 8
    params, kv, tokens, positions, btab, slots, ctx = _setup(b, s)

    ref_logits, ref_kv = llama.forward(
        params, CFG, tokens, positions, kv, btab, slots, ctx
    )

    staged = stage_params(params, 2)
    skv = stage_cache(kv, 2)
    # shard the batch over dp as the engine's jit in_shardings do
    dp1 = NamedSharding(mesh, P("dp"))
    dp2 = NamedSharding(mesh, P("dp", None))
    tokens, positions, btab, slots = (
        jax.device_put(x, dp2) for x in (tokens, positions, btab, slots)
    )
    ctx = jax.device_put(ctx, dp1)
    got_logits, got_kv = pipeline_forward(
        staged, CFG, tokens, positions, skv, btab, slots, ctx, mesh,
    )
    np.testing.assert_allclose(
        np.asarray(got_logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(unstage_cache(got_kv)[0]), np.asarray(ref_kv[0]),
        rtol=1e-5, atol=1e-5,
    )


def test_pp_ep_stages_mixtral_moe():
    """pp x ep x tp: the MoE trunk stages over pp with experts on the
    auto ep axis — parity vs mixtral.forward."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dynamo_tpu.engine.model_runner import build_mesh
    from dynamo_tpu.models import mixtral

    cfg = ModelConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=4,
        num_heads=4, num_kv_heads=2, head_dim=8, attention_impl="xla",
        num_experts=4, num_experts_per_tok=2,
    )
    mesh = build_mesh(1, 2, ep=2, pp=2)  # pp2 x ep2 x tp2
    b, s, bs, blocks = 4, 8, 8, 32
    params = mixtral.init_params(cfg, jax.random.PRNGKey(1), jnp.float32)
    kv = mixtral.init_kv_cache(cfg, blocks, bs, jnp.float32)
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (b, s)), jnp.int32)
    positions = jnp.tile(jnp.arange(s, dtype=jnp.int32), (b, 1))
    w = 4
    btab = jnp.asarray((np.arange(b * w).reshape(b, w)) % blocks, jnp.int32)
    slots = (
        jnp.take_along_axis(btab, positions // bs, axis=1) * bs + positions % bs
    ).astype(jnp.int32)
    ctx = jnp.full((b,), s, jnp.int32)

    ref_logits, ref_kv = mixtral.forward(
        params, cfg, tokens, positions, kv, btab, slots, ctx
    )

    staged = stage_params(params, 2)
    skv = stage_cache(kv, 2)
    got_logits, got_kv = pipeline_forward(
        staged, cfg, tokens, positions, skv, btab, slots, ctx, mesh,
        arch=mixtral,
    )
    np.testing.assert_allclose(
        np.asarray(got_logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(unstage_cache(got_kv)[0]), np.asarray(ref_kv[0]),
        rtol=1e-5, atol=1e-5,
    )


def test_model_runner_pp_ep_moe_matches_single_stage():
    """Mixtral through the engine with pp_size=2 x ep_size=2: same
    sampled tokens as the unstaged single-device runner."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.model_runner import ModelRunner
    from dynamo_tpu.models import mixtral

    mcfg = ModelConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=4,
        num_heads=4, num_kv_heads=2, head_dim=8, attention_impl="xla",
        num_experts=4, num_experts_per_tok=2,
    )
    params = mixtral.init_params(mcfg, jax.random.PRNGKey(2), jnp.float32)

    def run_steps(econfig):
        runner = ModelRunner(econfig, params=params)
        b, s, bs = 4, 8, 8
        rng = np.random.default_rng(3)
        tokens = rng.integers(0, mcfg.vocab_size, (b, s)).astype(np.int32)
        positions = np.tile(np.arange(s, dtype=np.int32), (b, 1))
        w = econfig.blocks_per_seq
        btab = np.zeros((b, w), np.int32)
        for i in range(b):
            btab[i, : s // bs] = np.arange(i * (s // bs), (i + 1) * (s // bs))
        slots = np.take_along_axis(
            btab, positions // bs, axis=1
        ) * bs + positions % bs
        ctx = np.full(b, s, np.int32)
        last = np.full(b, s - 1, np.int32)
        out1, *_ = runner.step(
            tokens, positions, btab, slots, ctx, last,
            np.zeros(b, np.float32), np.zeros(b, np.int32),
            np.ones(b, np.float32), jax.random.PRNGKey(4),
        )
        return np.asarray(out1)

    def cfg_for(pp, ep, tp=1):
        return EngineConfig(
            model=mcfg, max_batch_size=4, max_model_len=64, kv_block_size=8,
            num_kv_blocks=64, dtype="float32", pp_size=pp, ep_size=ep,
            tp_size=tp, prefill_buckets=[16], allow_random_weights=True,
        )

    ref = run_steps(cfg_for(1, 1))
    got = run_steps(cfg_for(2, 2))
    np.testing.assert_array_equal(got, ref)
    got_tp = run_steps(cfg_for(2, 2, tp=2))
    np.testing.assert_array_equal(got_tp, ref)

    # int8 expert stacks through the staged pp x ep trunk: the quantized
    # program's argmax may legitimately differ from fp32, so compare
    # against the UNSTAGED int8 engine instead
    import dataclasses

    q_mcfg = dataclasses.replace(mcfg, quantization="int8")
    q_ref = run_steps(dataclasses.replace(cfg_for(1, 1), model=q_mcfg))
    q_got = run_steps(dataclasses.replace(cfg_for(2, 2, tp=2), model=q_mcfg))
    np.testing.assert_array_equal(q_got, q_ref)


def test_pp_stages_gemma2_sandwich_trunk():
    """Gemma-2 stages over pp x tp via the family hooks (scaled embed,
    sandwich norms, softcap, GLOBAL-index window alternation) — parity
    vs gemma2.forward. num_layers/pp is ODD so a stage-local layer
    index would flip the window parity on stage 1."""
    from dynamo_tpu.engine.model_runner import build_mesh
    from dynamo_tpu.models import gemma2

    cfg = ModelConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_layers=6,  # pp2 -> 3 layers/stage (odd: parity test bites)
        num_heads=4, num_kv_heads=2, head_dim=8, attention_impl="xla",
        model_family="gemma2", sliding_window=4, attn_logit_softcap=50.0,
        final_logit_softcap=30.0, query_pre_attn_scalar=8,
    )
    mesh = build_mesh(1, 2, pp=2)
    b, s, bs, blocks = 4, 16, 8, 32
    params = gemma2.init_params(cfg, jax.random.PRNGKey(3), jnp.float32)
    kv = gemma2.init_kv_cache(cfg, blocks, bs, jnp.float32)
    rng = np.random.default_rng(5)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (b, s)), jnp.int32)
    positions = jnp.tile(jnp.arange(s, dtype=jnp.int32), (b, 1))
    w = 4
    btab = jnp.asarray((np.arange(b * w).reshape(b, w)) % blocks, jnp.int32)
    slots = (
        jnp.take_along_axis(btab, positions // bs, axis=1) * bs + positions % bs
    ).astype(jnp.int32)
    ctx = jnp.full((b,), s, jnp.int32)

    ref_logits, ref_kv = gemma2.forward(
        params, cfg, tokens, positions, kv, btab, slots, ctx
    )
    got_logits, got_kv = pipeline_forward(
        stage_params(params, 2), cfg, tokens, positions,
        stage_cache(kv, 2), btab, slots, ctx, mesh, arch=gemma2,
    )
    np.testing.assert_allclose(
        np.asarray(got_logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(unstage_cache(got_kv)[0]), np.asarray(ref_kv[0]),
        rtol=1e-5, atol=1e-5,
    )


def test_model_runner_pp_gemma2_matches_single_stage(tmp_path):
    """Gemma-2 through the engine with pp_size=2 x tp_size=2: same greedy
    step outputs as the unstaged single-device runner."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.model_runner import ModelRunner
    from dynamo_tpu.models import gemma2

    mcfg = ModelConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=6,
        num_heads=4, num_kv_heads=2, head_dim=8, attention_impl="xla",
        model_family="gemma2", sliding_window=4, attn_logit_softcap=50.0,
        final_logit_softcap=30.0, query_pre_attn_scalar=8,
    )
    params = gemma2.init_params(mcfg, jax.random.PRNGKey(6), jnp.float32)

    def run_steps(econfig):
        runner = ModelRunner(econfig, params=params)
        b, s, bs = 4, 8, 8
        rng = np.random.default_rng(7)
        tokens = rng.integers(0, mcfg.vocab_size, (b, s)).astype(np.int32)
        positions = np.tile(np.arange(s, dtype=np.int32), (b, 1))
        w = econfig.blocks_per_seq
        btab = np.zeros((b, w), np.int32)
        for i in range(b):
            btab[i, : s // bs] = np.arange(i * (s // bs), (i + 1) * (s // bs))
        slots = np.take_along_axis(
            btab, positions // bs, axis=1
        ) * bs + positions % bs
        out1, *_ = runner.step(
            tokens, positions, btab, slots, np.full(b, s, np.int32),
            np.full(b, s - 1, np.int32), np.zeros(b, np.float32),
            np.zeros(b, np.int32), np.ones(b, np.float32),
            jax.random.PRNGKey(8),
        )
        return np.asarray(out1)

    def cfg_for(pp, tp):
        return EngineConfig(
            model=mcfg, max_batch_size=4, max_model_len=64, kv_block_size=8,
            num_kv_blocks=64, dtype="float32", pp_size=pp, tp_size=tp,
            prefill_buckets=[16], allow_random_weights=True,
        )

    ref = run_steps(cfg_for(1, 1))
    got = run_steps(cfg_for(2, 2))
    np.testing.assert_array_equal(got, ref)


def test_pp_stages_mla_trunk():
    """DeepSeek MLA over pp (VERDICT r4 item 7): the staged latent-cache
    trunk matches deepseek.forward exactly — dense (num_experts=0) and
    homogeneous-MoE (first_k_dense_replace=0) variants, and pp x dp."""
    from dynamo_tpu.models import deepseek
    from dynamo_tpu.parallel.mesh import make_mesh

    def parity(mcfg, mesh_axes, b=4, s=8):
        mesh = make_mesh(mesh_axes)
        params = deepseek.init_params(mcfg, jax.random.PRNGKey(5), jnp.float32)
        kv = deepseek.init_kv_cache(mcfg, 32, 8, jnp.float32)
        rng = np.random.default_rng(6)
        tokens = jnp.asarray(
            rng.integers(0, mcfg.vocab_size, (b, s)), jnp.int32)
        positions = jnp.tile(jnp.arange(s, dtype=jnp.int32), (b, 1))
        w, bs = 4, 8
        btab = jnp.asarray((np.arange(b * w).reshape(b, w)) % 32, jnp.int32)
        slots = (jnp.take_along_axis(btab, positions // bs, axis=1) * bs
                 + positions % bs).astype(jnp.int32)
        ctx = jnp.full((b,), s, jnp.int32)

        ref_logits, ref_kv = deepseek.forward(
            params, mcfg, tokens, positions, kv, btab, slots, ctx)

        pp = mesh.shape["pp"]
        n_pre = mcfg.first_k_dense_replace if mcfg.num_experts else 0
        staged = stage_params(params, pp)
        staged_kv = stage_cache(tuple(kv), pp, prefix_layers=n_pre)
        got_logits, got_kv = pipeline_forward(
            staged, mcfg, tokens, positions, staged_kv, btab, slots, ctx,
            mesh, arch=deepseek,
        )
        np.testing.assert_allclose(
            np.asarray(got_logits), np.asarray(ref_logits),
            rtol=2e-4, atol=2e-4)
        for got_c, ref_c in zip(unstage_cache(got_kv), ref_kv):
            np.testing.assert_allclose(
                np.asarray(got_c), np.asarray(ref_c), rtol=2e-4, atol=2e-4)

    dense_mla = ModelConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=4,
        num_heads=4, num_kv_heads=4, head_dim=16, attention_impl="xla",
        kv_lora_rank=16, qk_rope_head_dim=8, qk_nope_head_dim=12,
        v_head_dim=12,
    )
    parity(dense_mla, {"pp": 2})
    parity(dense_mla, {"pp": 2, "dp": 2})  # latent writes gather over dp

    moe_mla = ModelConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=4,
        num_heads=4, num_kv_heads=4, head_dim=16, attention_impl="xla",
        kv_lora_rank=16, qk_rope_head_dim=8, qk_nope_head_dim=12,
        v_head_dim=12, num_experts=4, num_experts_per_tok=2,
        moe_intermediate_size=32, n_shared_experts=1,
        first_k_dense_replace=0,
    )
    parity(moe_mla, {"pp": 2, "ep": 1})
    # pp x ep with SHARED experts: the replicated shared contribution is
    # 1/ep-scaled so the joint (ep) psum restores it exactly once
    parity(moe_mla, {"pp": 2, "ep": 2})

    # the REAL V2/V3 trunk layout: dense prefix + MoE trunk. The prefix
    # cannot stack into the stage scan, so it runs REPLICATED (params,
    # cache, compute) at injection while the MoE trunk stages — exact
    # parity including both cache groups.
    import dataclasses as _dc

    mixed_mla = _dc.replace(moe_mla, num_layers=6, first_k_dense_replace=2)
    parity(mixed_mla, {"pp": 2, "ep": 1})
    parity(mixed_mla, {"pp": 2, "ep": 1, "dp": 2})  # prefix writes gather over dp


def test_model_runner_pp_mla_matches_single_stage():
    """MLA through the engine with pp_size=2 (+yarn rope scaling): same
    sampled tokens as the unstaged runner; unsupported compositions
    (tp>1, dense prefix) reject loudly."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.model_runner import ModelRunner
    from dynamo_tpu.models import deepseek

    mcfg = ModelConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=4,
        num_heads=4, num_kv_heads=4, head_dim=16, attention_impl="xla",
        kv_lora_rank=16, qk_rope_head_dim=8, qk_nope_head_dim=12,
        v_head_dim=12, q_lora_rank=24,
        rope_scaling={"rope_type": "yarn", "factor": 2.0,
                      "original_max_position_embeddings": 32,
                      "mscale": 1.0, "mscale_all_dim": 1.0},
    )
    params = deepseek.init_params(mcfg, jax.random.PRNGKey(8), jnp.float32)

    def run_steps(econfig):
        runner = ModelRunner(econfig, params=params)
        b, s, bs = 4, 8, 8
        rng = np.random.default_rng(9)
        tokens = rng.integers(0, mcfg.vocab_size, (b, s)).astype(np.int32)
        positions = np.tile(np.arange(s, dtype=np.int32), (b, 1))
        w = econfig.blocks_per_seq
        btab = np.zeros((b, w), np.int32)
        for i in range(b):
            btab[i, : s // bs] = np.arange(i * (s // bs), (i + 1) * (s // bs))
        slots = np.take_along_axis(
            btab, positions // bs, axis=1
        ) * bs + positions % bs
        ctx = np.full(b, s, np.int32)
        last = np.full(b, s - 1, np.int32)
        out1, *_ = runner.step(
            tokens, positions, btab, slots, ctx, last,
            np.zeros(b, np.float32), np.zeros(b, np.int32),
            np.ones(b, np.float32), jax.random.PRNGKey(10),
        )
        return np.asarray(out1)

    def cfg_for(pp, tp=1, model=None):
        return EngineConfig(
            model=model or mcfg, max_batch_size=4, max_model_len=64,
            kv_block_size=8, num_kv_blocks=64, dtype="float32",
            pp_size=pp, tp_size=tp, prefill_buckets=[16],
            allow_random_weights=True,
        )

    ref = run_steps(cfg_for(1))
    got = run_steps(cfg_for(2))
    np.testing.assert_array_equal(got, ref)

    # guard: manual tp rejects loudly (no latent head axis to shard)
    with pytest.raises(NotImplementedError, match="not tp"):
        ModelRunner(cfg_for(2, tp=2), params=params)

    # the real V2/V3 layout (dense prefix + MoE trunk) serves through
    # the engine: replicated prefix + staged trunk, same sampled tokens
    import dataclasses

    mixed = dataclasses.replace(
        mcfg, num_layers=6, num_experts=4, num_experts_per_tok=2,
        moe_intermediate_size=32, n_shared_experts=1,
        first_k_dense_replace=2,
    )
    mixed_params = deepseek.init_params(mixed, jax.random.PRNGKey(1),
                                        jnp.float32)

    def run_mixed(pp):
        runner = ModelRunner(cfg_for(pp, model=mixed), params=mixed_params)
        b, s, bs = 4, 8, 8
        rng = np.random.default_rng(11)
        tokens = rng.integers(0, mixed.vocab_size, (b, s)).astype(np.int32)
        positions = np.tile(np.arange(s, dtype=np.int32), (b, 1))
        w = runner.config.blocks_per_seq
        btab = np.zeros((b, w), np.int32)
        for i in range(b):
            btab[i, : s // bs] = np.arange(i * (s // bs), (i + 1) * (s // bs))
        slots = np.take_along_axis(
            btab, positions // bs, axis=1
        ) * bs + positions % bs
        out1, *_ = runner.step(
            tokens, positions, btab, slots, np.full(b, s, np.int32),
            np.full(b, s - 1, np.int32), np.zeros(b, np.float32),
            np.zeros(b, np.int32), np.ones(b, np.float32),
            jax.random.PRNGKey(12),
        )
        return np.asarray(out1)

    np.testing.assert_array_equal(run_mixed(2), run_mixed(1))

    # V3-shaped layer arithmetic: TOTAL layers need not divide by pp —
    # only the staged trunk (61 = 3 dense + 58 staged in the real
    # checkpoint; here 7 = 3 + 4). And the wire-layout block ops
    # round-trip through the mixed {"pre","stg"} cache.
    odd = dataclasses.replace(mixed, num_layers=7, first_k_dense_replace=3)
    odd_params = deepseek.init_params(odd, jax.random.PRNGKey(13),
                                      jnp.float32)
    runner = ModelRunner(cfg_for(2, model=odd), params=odd_params)
    rng = np.random.default_rng(14)
    blocks_k = rng.standard_normal(
        (7, 3, 8, 1, odd.kv_lora_rank)).astype(np.float32)
    blocks_v = rng.standard_normal(
        (7, 3, 8, 1, odd.qk_rope_head_dim)).astype(np.float32)
    runner.scatter_blocks([2, 5, 9], blocks_k, blocks_v)
    k_got, v_got = runner.gather_blocks([2, 5, 9])
    np.testing.assert_allclose(np.asarray(k_got), blocks_k, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(v_got), blocks_v, rtol=1e-6)
