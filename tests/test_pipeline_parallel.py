"""Pipeline parallelism vs. the plain forward (virtual CPU pp mesh).

The collective GPipe schedule (parallel/pipeline.py) must be numerically
identical to llama.forward — same logits, same KV cache contents — for
prefill and decode, with M == P and M > P microbatches."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.config import ModelConfig
from dynamo_tpu.models import llama
from dynamo_tpu.parallel.mesh import make_mesh
from dynamo_tpu.parallel.pipeline import (
    pipeline_forward,
    stage_cache,
    stage_params,
    unstage_cache,
)

CFG = ModelConfig(
    vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=4,
    num_heads=4, num_kv_heads=2, head_dim=8, attention_impl="xla",
)


def _setup(b, s, bs=8, blocks=32):
    params = llama.init_params(CFG, jax.random.PRNGKey(0), jnp.float32)
    kv = llama.init_kv_cache(CFG, blocks, bs, jnp.float32)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, CFG.vocab_size, (b, s)), jnp.int32)
    positions = jnp.tile(jnp.arange(s, dtype=jnp.int32), (b, 1))
    w = 4
    btab = jnp.asarray(
        (np.arange(b * w).reshape(b, w)) % blocks, jnp.int32
    )
    slots = (
        jnp.take_along_axis(btab, positions // bs, axis=1) * bs + positions % bs
    ).astype(jnp.int32)
    ctx = jnp.full((b,), s, jnp.int32)
    return params, kv, tokens, positions, btab, slots, ctx


@pytest.mark.parametrize("microbatches", [None, 8])
def test_pp_prefill_matches_plain_forward(microbatches):
    pp = 4
    mesh = make_mesh({"pp": pp})
    b, s = 8, 16
    params, kv, tokens, positions, btab, slots, ctx = _setup(b, s)

    ref_logits, ref_kv = llama.forward(
        params, CFG, tokens, positions, kv, btab, slots, ctx
    )

    staged = stage_params(params, pp)
    skv = stage_cache(kv, pp)
    got_logits, got_kv = pipeline_forward(
        staged, CFG, tokens, positions, skv, btab, slots, ctx, mesh,
        num_microbatches=microbatches,
    )
    np.testing.assert_allclose(
        np.asarray(got_logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
    )
    for got, ref in zip(unstage_cache(got_kv), ref_kv):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4
        )


def test_pp_decode_matches_plain_forward():
    pp = 2
    mesh = make_mesh({"pp": pp})
    b, s = 4, 1
    bs = 8
    params, kv, _, _, btab, _, _ = _setup(b, 1, bs=bs)
    ctx_prev = 5
    positions = jnp.full((b, 1), ctx_prev, jnp.int32)
    tokens = jnp.asarray(np.arange(b).reshape(b, 1) + 3, jnp.int32)
    slots = (btab[:, ctx_prev // bs] * bs + ctx_prev % bs)[:, None]
    ctx = jnp.full((b,), ctx_prev + 1, jnp.int32)
    # pre-populate the cache so decode attends over history
    k0 = jax.random.normal(jax.random.PRNGKey(1), kv[0].shape, jnp.float32)
    v0 = jax.random.normal(jax.random.PRNGKey(2), kv[1].shape, jnp.float32)
    kv = (k0, v0)

    ref_logits, ref_kv = llama.forward(
        params, CFG, tokens, positions, kv, btab, slots, ctx
    )
    got_logits, got_kv = pipeline_forward(
        stage_params(params, pp), CFG, tokens, positions, stage_cache(kv, pp),
        btab, slots, ctx, mesh,
    )
    np.testing.assert_allclose(
        np.asarray(got_logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
    )
    for got, ref in zip(unstage_cache(got_kv), ref_kv):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4
        )


def test_pp_rejects_bad_shapes():
    mesh = make_mesh({"pp": 4})
    params = llama.init_params(CFG, jax.random.PRNGKey(0), jnp.float32)
    with pytest.raises(ValueError, match="not divisible"):
        stage_params(params, 3)
