"""N-gram (prompt-lookup) speculative decoding.

The verify step must be invisible in outputs: a greedy request streams
the identical tokens with speculation on or off — acceptance only
changes how many device dispatches the stream costs. Reference analog:
the ngram speculative decoding of the engines the reference delegates
to (vLLM `speculative_model: [ngram]`).
"""

import asyncio
import json
import os

import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.scheduler import ngram_propose
from dynamo_tpu.engine.serving import JaxServingEngine
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import Context

from fixtures import make_model_dir


def test_ngram_propose_finds_latest_match():
    #        0  1  2  3  4  5  6  7  8
    hist = [5, 6, 7, 1, 2, 5, 6, 9, 5, 6]
    # tail (5, 6) matched latest at start 5 → continuation [9, 5, 6]
    assert ngram_propose(hist, 2, 3) == [9, 5, 6]
    assert ngram_propose(hist, 2, 1) == [9]


def test_ngram_propose_no_match_or_short_history():
    assert ngram_propose([1, 2, 3, 4], 2, 3) == []      # (3,4) unseen
    assert ngram_propose([1, 2], 3, 3) == []            # too short
    assert ngram_propose([7, 7, 7, 7], 2, 8) == [7, 7]  # runs off the end


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    d = make_model_dir(tmp_path_factory.mktemp("specmodel"), name="tiny-spec")
    cfg = LlamaConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    LlamaForCausalLM(cfg).save_pretrained(d, safe_serialization=True)
    with open(os.path.join(d, "config.json")) as f:
        c = json.load(f)
    c["eos_token_id"] = 2
    c["bos_token_id"] = 1
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump(c, f)
    return d


def _config(model_dir, spec, **kw):
    cfg = ModelConfig.from_model_dir(model_dir)
    return EngineConfig(
        model=cfg, max_batch_size=4, max_model_len=128, kv_block_size=8,
        num_kv_blocks=96, dtype="float32", spec_ngram_tokens=spec,
        spec_ngram_match=2, **kw,
    )


async def _collect(engine, token_ids, sampling, max_tokens=24):
    req = PreprocessedRequest(
        token_ids=list(token_ids),
        stop_conditions=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        sampling_options=sampling,
    )
    toks = []
    async for out in engine.generate(Context(req)):
        toks.extend(out["token_ids"])
    return toks


def _runs(model_dir, spec):
    async def go():
        mdc = ModelDeploymentCard.from_local_path(model_dir)
        engine = await JaxServingEngine.create(
            mdc, engine_config=_config(model_dir, spec), warmup=False)
        results = []
        # a looping prompt (proposals will fire) and a plain one
        results.append(await _collect(
            engine, [1, 9, 8, 9, 8, 9, 8], SamplingOptions(temperature=0.0)))
        results.append(await _collect(
            engine, [1, 17, 43, 99, 7], SamplingOptions(temperature=0.0)))
        # a sampled request: not spec-eligible, must still stream right
        results.append(await _collect(
            engine, [1, 5, 9, 13], SamplingOptions(temperature=0.8, seed=7)))
        # concurrent greedy pair
        results.extend(await asyncio.gather(
            _collect(engine, [1, 42, 42, 42, 42], SamplingOptions(temperature=0.0)),
            _collect(engine, [1, 7, 100, 7, 100, 7], SamplingOptions(temperature=0.0)),
        ))
        metrics = engine.metrics()
        await engine.close()
        return results, metrics

    return asyncio.get_event_loop_policy().new_event_loop().run_until_complete(go())


def test_spec_streams_bit_equal_and_accepts(model_dir):
    base, base_m = _runs(model_dir, 0)
    spec, spec_m = _runs(model_dir, 4)
    assert spec == base
    assert "spec_proposed_tokens" not in base_m
    assert spec_m["spec_proposed_tokens"] > 0  # proposals actually fired


@pytest.mark.asyncio
async def test_spec_saves_dispatches_on_repetitive_output(model_dir):
    # a model generating a short cycle is the ideal case: acceptance
    # should make dispatches << generated tokens once a cycle emerges
    mdc = ModelDeploymentCard.from_local_path(model_dir)
    engine = await JaxServingEngine.create(
        mdc, engine_config=_config(model_dir, 4), warmup=False)
    toks = await _collect(
        engine, [1, 9, 8, 9, 8, 9, 8], SamplingOptions(temperature=0.0),
        max_tokens=32)
    m = engine.metrics()
    steps = engine.scheduler.steps
    await engine.close()
    assert len(toks) == 32
    if m["spec_accepted_tokens"] > 0:
        assert steps < 32 + 2  # prefill + fewer decode dispatches


@pytest.mark.asyncio
async def test_spec_with_eos_stop(model_dir):
    # eos handling mid-accepted-run must match the sequential engine
    mdc = ModelDeploymentCard.from_local_path(model_dir)

    async def run(spec, stop_ids):
        engine = await JaxServingEngine.create(
            mdc, engine_config=_config(model_dir, spec), warmup=False)
        req = PreprocessedRequest(
            token_ids=[1, 9, 8, 9, 8],
            stop_conditions=StopConditions(
                max_tokens=24, ignore_eos=True,
                stop_token_ids_hidden=stop_ids),
            sampling_options=SamplingOptions(temperature=0.0),
        )
        toks, finish = [], None
        async for out in engine.generate(Context(req)):
            toks.extend(out["token_ids"])
            if out.get("finish_reason"):
                finish = out["finish_reason"]
        await engine.close()
        return toks, finish

    full, _ = await run(0, None)
    stop_tok = full[3]
    want = await run(0, [stop_tok])
    got = await run(4, [stop_tok])
    assert got == want
