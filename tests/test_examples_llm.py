"""The canonical examples/llm SDK graph, served in-process with echo
engines — mirrors the reference's GPU-free example test strategy."""

import asyncio
import json
import urllib.request

import pytest

from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.transports.memory import MemoryHub
from dynamo_tpu.sdk import DynamoClient, ServiceConfig, graph_services
from dynamo_tpu.sdk.serving import serve_graph_inprocess, stop_graph

from fixtures import make_model_dir


@pytest.fixture
def model_dir(tmp_path):
    return make_model_dir(tmp_path)


@pytest.fixture(autouse=True)
def fresh_links():
    """Link state is process-global; each test composes its own graph."""
    from examples.llm import components as C

    for svc in (C.Frontend, C.Processor, C.Router, C.Worker, C.PrefillWorker):
        svc.unlink_all()
    yield
    for svc in (C.Frontend, C.Processor, C.Router, C.Worker, C.PrefillWorker):
        svc.unlink_all()


def _config(model_dir, extra=None):
    data = {
        "Common": {"model-path": model_dir, "model-name": "tiny"},
        "Frontend": {"http-port": 0, "http-host": "127.0.0.1"},
        "Processor": {"router-mode": "round_robin",
                      "common-configs": ["model-path", "model-name"]},
        "Worker": {"engine": "echo_core",
                   "common-configs": ["model-path", "model-name"]},
        "Router": {"block-size": 4},
    }
    if extra:
        for k, v in extra.items():
            data.setdefault(k, {}).update(v)
    return ServiceConfig(data)


def test_graphs_compose():
    """The flagship chain reaches all five services; the agg chain must NOT
    pull in Router/PrefillWorker (graph modules link at import time, one
    graph per process — tests compose explicitly instead)."""
    from examples.llm import components as C

    C.Frontend.link(C.Processor).link(C.Router).link(C.Worker).link(C.PrefillWorker)
    names = {s.name for s in graph_services(C.Frontend)}
    assert names == {"Frontend", "Processor", "Router", "Worker", "PrefillWorker"}

    for svc in (C.Frontend, C.Processor, C.Router, C.Worker, C.PrefillWorker):
        svc.unlink_all()
    C.Frontend.link(C.Processor).link(C.Worker)
    assert {s.name for s in graph_services(C.Frontend)} == {
        "Frontend", "Processor", "Worker"
    }


async def _fetch_sse(url, body):
    """POST + parse SSE in a thread (urllib is sync)."""
    def go():
        req = urllib.request.Request(
            url, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        chunks = []
        with urllib.request.urlopen(req, timeout=30) as resp:
            for line in resp:
                line = line.decode().strip()
                if line.startswith("data: ") and line != "data: [DONE]":
                    chunks.append(json.loads(line[len("data: "):]))
        return chunks
    return await asyncio.get_running_loop().run_in_executor(None, go)


async def test_agg_graph_end_to_end(model_dir):
    """HTTP SSE -> Frontend -> Processor -> Worker (echo) -> stream back."""
    from examples.llm.components import Frontend, Processor, Worker

    Frontend.link(Processor).link(Worker)
    drt = DistributedRuntime.in_process(MemoryHub())
    drt2, handles, objs = await serve_graph_inprocess(
        Frontend, drt, config=_config(model_dir)
    )
    try:
        # give the watcher a beat to pick up the Processor's registration
        await asyncio.sleep(0.3)
        port = objs["Frontend"].http.port
        chunks = await _fetch_sse(
            f"http://127.0.0.1:{port}/v1/chat/completions",
            {
                "model": "tiny",
                "messages": [{"role": "user", "content": "hello world"}],
                "stream": True,
                "max_tokens": 8,
            },
        )
        assert chunks, "no chunks streamed"
        text = "".join(
            (c.get("choices", [{}])[0].get("delta") or {}).get("content") or ""
            for c in chunks
        )
        assert text  # echo engine detokenizes the prompt back
        finish = [c for c in chunks
                  if c.get("choices", [{}])[0].get("finish_reason")]
        assert finish, "no finish_reason chunk"
    finally:
        await stop_graph(drt2, handles)


async def test_agg_router_graph_kv_routing(model_dir):
    """router-mode kv: Processor asks the Router service for a worker."""
    from examples.llm.components import Frontend, Processor, Router, Worker

    Frontend.link(Processor).link(Router).link(Worker)
    drt = DistributedRuntime.in_process(MemoryHub())
    cfg = _config(model_dir, extra={"Processor": {"router-mode": "kv"}})
    drt2, handles, _objs = await serve_graph_inprocess(Frontend, drt, config=cfg)
    try:
        from examples.llm import components as C

        client = DynamoClient(C.Processor, drt)
        await client.start()
        await client.wait_ready(timeout=10)
        chunks = [
            c async for c in client.chat({
                "model": "tiny",
                "messages": [{"role": "user", "content": "route me please"}],
                "stream": True,
                "max_tokens": 4,
            })
        ]
        assert chunks
        # the Router service itself must answer scheduling queries
        router_client = DynamoClient(C.Router, drt)
        await router_client.start()
        await router_client.wait_ready(timeout=10)
        decisions = [d async for d in router_client.generate(
            {"token_ids": list(range(16))}
        )]
        assert decisions and "worker_id" in decisions[0]
    finally:
        await stop_graph(drt2, handles)
