"""Deployment plane: manifest rendering, reconcile loop, api-store CRUD.

Reference analog: the operator controller tests
(deploy/dynamo/operator/internal/controller/*_test.go) — here the
reconcile logic is pure-Python and tested against an in-memory cluster.
"""

import pytest

from dynamo_tpu.deploy import InMemoryKube, Reconciler, render_manifests
from dynamo_tpu.deploy.api_store import ApiStoreService, DeploymentStore


def _cr(name="g1", services=None, **spec):
    return {
        "apiVersion": "dynamo.tpu/v1alpha1",
        "kind": "DynamoTpuGraphDeployment",
        "metadata": {"name": name, "namespace": "serving", "uid": "u-1"},
        "spec": {"image": "dynamo-tpu:test", "namespace": "public",
                 "services": services or {}, **spec},
    }


def test_render_defaults_include_dynstore_and_frontend():
    manifests = render_manifests(_cr())
    kinds = {(m["kind"], m["metadata"]["name"]) for m in manifests}
    assert ("Deployment", "g1-dynstore") in kinds
    assert ("Deployment", "g1-frontend") in kinds
    assert ("Service", "g1-dynstore") in kinds
    assert ("Service", "g1-frontend") in kinds
    for m in manifests:
        assert m["metadata"]["ownerReferences"][0]["name"] == "g1"


def test_render_worker_gets_tpu_resources_and_wiring():
    cr = _cr(services={
        "decode": {
            "role": "decode", "replicas": 2, "tpus": 4, "tpuTopology": "2x2",
            "modelPath": "/models/llama", "extraArgs": ["--tensor-parallel-size", "4"],
        },
        "prefill": {"role": "prefill", "replicas": 4, "tpus": 1,
                    "modelPath": "/models/llama"},
    }, modelName="llama")
    by_name = {m["metadata"]["name"]: m for m in render_manifests(cr)}

    decode = by_name["g1-decode"]
    assert decode["spec"]["replicas"] == 2
    container = decode["spec"]["template"]["spec"]["containers"][0]
    assert container["resources"]["limits"]["google.com/tpu"] == "4"
    sel = decode["spec"]["template"]["spec"]["nodeSelector"]
    assert sel["cloud.google.com/gke-tpu-topology"] == "2x2"
    cmd = container["command"]
    assert "in=dyn://public.backend.generate" in cmd
    assert "--remote-prefill" in cmd
    assert "--store-host" in cmd and "g1-dynstore" in cmd
    assert "--model-name" in cmd and "llama" in cmd
    assert "--tensor-parallel-size" in cmd

    prefill = by_name["g1-prefill"]
    assert prefill["spec"]["replicas"] == 4
    assert "in=prefill" in prefill["spec"]["template"]["spec"]["containers"][0]["command"]


def test_render_rejects_unknown_role():
    with pytest.raises(ValueError, match="unknown service role"):
        render_manifests(_cr(services={"x": {"role": "nonsense"}}))


def test_reconcile_applies_updates_and_prunes():
    kube = InMemoryKube()
    rec = Reconciler(kube)

    cr = _cr(services={"worker": {"role": "worker", "replicas": 1,
                                  "modelPath": "/m"}})
    changes = rec.reconcile(cr)
    assert len(changes["applied"]) == len(render_manifests(cr))
    assert not changes["deleted"]
    assert "Deployment/serving/g1-worker" in kube.objects

    # idempotent: nothing re-applied
    changes = rec.reconcile(cr)
    assert changes == {"applied": [], "deleted": []}

    # scale up → only the changed child re-applies
    cr["spec"]["services"]["worker"]["replicas"] = 3
    changes = rec.reconcile(cr)
    assert changes["applied"] == ["Deployment/serving/g1-worker"]
    assert kube.objects["Deployment/serving/g1-worker"]["spec"]["replicas"] == 3

    # remove the service → its Deployment is pruned
    del cr["spec"]["services"]["worker"]
    changes = rec.reconcile(cr)
    assert "Deployment/serving/g1-worker" in changes["deleted"]
    assert "Deployment/serving/g1-worker" not in kube.objects

    # finalize removes everything managed
    removed = rec.finalize(cr)
    assert removed
    assert not kube.list_managed("serving", "g1")


@pytest.mark.asyncio
async def test_api_store_crud_over_http(aiohttp_client=None):
    import aiohttp

    service = ApiStoreService(DeploymentStore(":memory:"), "127.0.0.1", 0)
    await service.start()
    base = f"http://127.0.0.1:{service.port}"
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(f"{base}/api/v1/deployments",
                              json={"name": "g1", "spec": {"replicas": 2}}) as r:
                assert r.status == 201
            async with s.post(f"{base}/api/v1/deployments",
                              json={"name": "g1", "spec": {}}) as r:
                assert r.status == 409
            async with s.get(f"{base}/api/v1/deployments/g1") as r:
                assert (await r.json())["spec"] == {"replicas": 2}
            async with s.put(f"{base}/api/v1/deployments/g1",
                             json={"spec": {"replicas": 5}}) as r:
                assert (await r.json())["spec"] == {"replicas": 5}
            async with s.get(f"{base}/api/v1/deployments") as r:
                assert len((await r.json())["deployments"]) == 1
            async with s.delete(f"{base}/api/v1/deployments/g1") as r:
                assert (await r.json())["deleted"] is True
            async with s.get(f"{base}/api/v1/deployments/g1") as r:
                assert r.status == 404
    finally:
        await service.stop()


def test_reconcile_repairs_external_deletion():
    kube = InMemoryKube()
    rec = Reconciler(kube)
    cr = _cr(services={"worker": {"role": "worker"}})
    rec.reconcile(cr)
    # someone kubectl-deletes a child out from under the operator
    kube.delete("Deployment", "serving", "g1-worker")
    changes = rec.reconcile(cr)
    assert "Deployment/serving/g1-worker" in changes["applied"]
    assert "Deployment/serving/g1-worker" in kube.objects


@pytest.mark.asyncio
async def test_api_store_update_accepts_both_envelopes():
    import aiohttp

    service = ApiStoreService(DeploymentStore(":memory:"), "127.0.0.1", 0)
    await service.start()
    base = f"http://127.0.0.1:{service.port}"
    try:
        async with aiohttp.ClientSession() as s:
            await s.post(f"{base}/api/v1/deployments",
                         json={"name": "g1", "spec": {"a": 1}})
            # envelope form (same shape POST takes)
            async with s.put(f"{base}/api/v1/deployments/g1",
                             json={"name": "g1", "spec": {"a": 2}}) as r:
                assert (await r.json())["spec"] == {"a": 2}
            # a spec whose document contains a top-level "spec" key is
            # preserved verbatim (no unwrap guessing)
            async with s.put(f"{base}/api/v1/deployments/g1",
                             json={"spec": {"spec": {"replicas": 2}}}) as r:
                assert (await r.json())["spec"] == {"spec": {"replicas": 2}}
            # bare (non-envelope) and non-object bodies rejected
            async with s.put(f"{base}/api/v1/deployments/g1", json=[1, 2]) as r:
                assert r.status == 400
            async with s.put(f"{base}/api/v1/deployments/g1",
                             json={"a": 1}) as r:
                assert r.status == 400
    finally:
        await service.stop()


# ---------- CR status + api-store → operator wiring (round 3) ----------


def _cr3(name="g1", generation=3, services=None):
    return {
        "apiVersion": "dynamo.tpu/v1alpha1",
        "kind": "DynamoTpuGraphDeployment",
        "metadata": {"name": name, "namespace": "default",
                     "generation": generation},
        "spec": {"services": services or {"worker": {"role": "worker"}}},
    }


def test_reconcile_writes_cr_status():
    """After reconcile the CR status carries the observed generation,
    child counts, and a Reconciled=True condition (reference analog:
    dynamodeployment_controller.go status handling)."""
    kube = InMemoryKube()
    rec = Reconciler(kube)
    rec.reconcile(_cr3(generation=7))
    status = kube.statuses[("default", "g1")]
    assert status["observedGeneration"] == 7
    assert status["children"] == {"Deployment": 3, "Service": 2}
    (cond,) = status["conditions"]
    assert (cond["type"], cond["status"]) == ("Reconciled", "True")
    assert cond["reason"] == "ReconcileSucceeded"

    # second pass: in sync, still True
    rec.reconcile(_cr3(generation=7))
    assert kube.statuses[("default", "g1")]["conditions"][0]["message"] == "in sync"


def test_reconcile_error_writes_false_condition():
    kube = InMemoryKube()
    rec = Reconciler(kube)
    bad = _cr3(services={"worker": {"role": "no-such-role"}})
    with pytest.raises(ValueError):
        rec.reconcile(bad)
    (cond,) = kube.statuses[("default", "g1")]["conditions"]
    assert (cond["status"], cond["reason"]) == ("False", "ReconcileError")
    assert "no-such-role" in cond["message"]


async def test_store_to_operator_end_to_end():
    """llmctl-deploy path: POST a graph spec to the api-store, source CRs
    from the store, reconcile into InMemoryKube, status lands back in the
    record; DELETE → finalize prunes the children (reference analog:
    api-store create_dynamo_deployment → k8s objects,
    ai_dynamo_store/api/deployments.py:30)."""
    import asyncio

    from dynamo_tpu.deploy.operator import control_loop  # noqa: F401
    from dynamo_tpu.deploy.store_source import ApiStoreClient, record_to_cr

    service = ApiStoreService(DeploymentStore(":memory:"), "127.0.0.1", 0)
    await service.start()
    try:
        client = ApiStoreClient(f"http://127.0.0.1:{service.port}")
        loop = asyncio.get_running_loop()

        # llmctl deploy create (sync client off the event loop thread)
        spec = {"services": {"worker": {"role": "worker", "tpus": 4}},
                "modelName": "tiny"}
        await loop.run_in_executor(None, lambda: client.create("graph1", spec))

        kube = InMemoryKube()
        rec = Reconciler(kube, status_writer=client.write_status)
        crs = await loop.run_in_executor(None, client.get_crs)
        assert len(crs) == 1 and crs[0]["metadata"]["name"] == "graph1"
        for cr in crs:
            await loop.run_in_executor(None, rec.reconcile, cr)

        # children exist (worker + default dynstore/frontend + services)
        kinds = sorted(k.split("/")[0] for k in kube.objects)
        assert kinds.count("Deployment") == 3 and kinds.count("Service") == 2

        # status round-tripped into the store record
        rec1 = await loop.run_in_executor(None, client.get, "graph1")
        cond = rec1["status"]["conditions"][0]
        assert (cond["type"], cond["status"]) == ("Reconciled", "True")

        # llmctl deploy delete → finalize prunes every child
        await loop.run_in_executor(None, client.delete, "graph1")
        crs2 = await loop.run_in_executor(None, client.get_crs)
        assert crs2 == []
        removed = rec.finalize(record_to_cr(
            {"name": "graph1", "spec": spec, "updated": 1}
        ))
        assert len(removed) == 5 and kube.objects == {}
    finally:
        await service.stop()


async def test_store_source_unreachable_returns_none():
    """A dead store must yield None (skip cycle), never [] (finalize all)."""
    from dynamo_tpu.deploy.store_source import ApiStoreClient

    client = ApiStoreClient("http://127.0.0.1:1", timeout=0.5)
    import asyncio
    loop = asyncio.get_running_loop()
    assert await loop.run_in_executor(None, client.get_crs) is None


def test_render_planner_role():
    """The planner control-plane pod renders like any other role and
    observes the graph's own backend endpoint."""
    cr = _cr(services={"planner": {"role": "planner"}})
    by_name = {m["metadata"]["name"]: m for m in render_manifests(cr)}
    cmd = by_name["g1-planner"]["spec"]["template"]["spec"]["containers"][0]["command"]
    assert "in=planner" in cmd
    assert "--worker-endpoint" in cmd
    assert "dyn://public.backend.generate" in cmd
