"""Disaggregated prefill/decode: block transfer, conditional routing,
remote-prefill end-to-end equivalence with local generation."""

import asyncio

import numpy as np
import pytest

import jax.numpy as jnp

from dynamo_tpu.disagg import (
    DisaggRouter,
    KvTransferClient,
    KvTransferServer,
    PrefillWorker,
    RemotePrefillCoordinator,
)
from dynamo_tpu.disagg.protocols import PrefillQueue, RemotePrefillRequest
from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.model_runner import ModelRunner
from dynamo_tpu.engine.scheduler import EngineRequest, Scheduler
from dynamo_tpu.models.loader import load_llama_params
from dynamo_tpu.protocols.common import (
    FinishReason,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.transports.memory import MemoryHub

from test_jax_engine import hf_model_dir, hf_logits, TINY  # noqa: F401


def _make_runner(hf_model_dir, **overrides):
    cfg = ModelConfig.from_model_dir(hf_model_dir)
    econfig = EngineConfig(
        model=cfg, max_batch_size=4, max_model_len=128, kv_block_size=8,
        num_kv_blocks=64, dtype="float32", **overrides,
    )
    params = load_llama_params(hf_model_dir, cfg, jnp.float32)
    return ModelRunner(econfig, params=params), econfig


def _greedy_request(request_id, prompt, max_tokens=8):
    req = PreprocessedRequest(
        token_ids=list(prompt),
        sampling_options=SamplingOptions(temperature=0.0),
        stop_conditions=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    )
    return EngineRequest(
        request_id=request_id,
        prompt=list(prompt),
        req=req,
        ctx=Context(req).context,
        out_queue=asyncio.Queue(),
    )


async def _collect(er):
    tokens = []
    while True:
        out = await asyncio.wait_for(er.out_queue.get(), timeout=60)
        if out is None:
            return tokens
        tokens.extend(out.token_ids)


# ---------------------------------------------------------------- block ops


def test_gather_scatter_roundtrip(hf_model_dir):
    runner, econfig = _make_runner(hf_model_dir)
    cfg = econfig.model
    bs = econfig.kv_block_size
    ids = [3, 7, 11, 12, 40]
    shape = (cfg.num_layers, len(ids), bs, cfg.num_kv_heads, cfg.head_dim)
    k = np.random.default_rng(0).normal(size=shape).astype(np.float32)
    v = np.random.default_rng(1).normal(size=shape).astype(np.float32)
    runner.scatter_blocks(ids, k, v)
    k2, v2 = runner.gather_blocks(ids)
    np.testing.assert_allclose(k2, k, rtol=1e-6)
    np.testing.assert_allclose(v2, v, rtol=1e-6)
    # untouched blocks remain zero
    kz, _ = runner.gather_blocks([0])
    assert np.all(kz == 0)


async def test_transfer_server_roundtrip(hf_model_dir):
    """Blocks pushed over real TCP land in the destination runner's cache."""
    runner_a, econfig = _make_runner(hf_model_dir)
    runner_b, _ = _make_runner(hf_model_dir)
    cfg = econfig.model
    bs = econfig.kv_block_size

    commits = []
    server = KvTransferServer(
        scatter=lambda rid, ids, k, v: runner_b.scatter_blocks(ids, k, v),
        on_commit=lambda rid, tok, lp, top=None, spans=None:
            commits.append((rid, tok, lp)),
    )
    await server.start()
    try:
        src_ids = [2, 5, 9]
        dst_ids = [10, 20, 30]
        shape = (cfg.num_layers, len(src_ids), bs, cfg.num_kv_heads, cfg.head_dim)
        k = np.random.default_rng(2).normal(size=shape).astype(np.float32)
        v = np.random.default_rng(3).normal(size=shape).astype(np.float32)
        runner_a.scatter_blocks(src_ids, k, v)

        kk, vv = runner_a.gather_blocks(src_ids)
        client = await KvTransferClient("127.0.0.1", server.port).connect()
        await client.send_blocks("r1", dst_ids, kk, vv, chunk_blocks=2)
        await client.send_commit("r1", 42, 0.5)
        await client.close()

        assert commits == [("r1", 42, 0.5)]
        k2, v2 = runner_b.gather_blocks(dst_ids)
        np.testing.assert_allclose(k2, k, rtol=1e-6)
        np.testing.assert_allclose(v2, v, rtol=1e-6)
    finally:
        await server.close()


async def test_transfer_drops_unauthorized_frames(hf_model_dir):
    runner, econfig = _make_runner(hf_model_dir)
    cfg = econfig.model
    bs = econfig.kv_block_size
    server = KvTransferServer(
        scatter=lambda rid, ids, k, v: runner.scatter_blocks(ids, k, v),
        on_commit=lambda *a: None,
        authorize=lambda rid, ids: False,  # e.g. request was cancelled
    )
    await server.start()
    try:
        shape = (cfg.num_layers, 1, bs, cfg.num_kv_heads, cfg.head_dim)
        k = np.ones(shape, np.float32)
        client = await KvTransferClient("127.0.0.1", server.port).connect()
        await client.send_blocks("ghost", [4], k, k)
        await client.send_commit("ghost", 1, None)
        await client.close()
        kz, _ = runner.gather_blocks([4])
        assert np.all(kz == 0)  # frame was dropped, cache untouched
    finally:
        await server.close()


# ---------------------------------------------------------------- router


def test_disagg_router_decision():
    r = DisaggRouter(max_local_prefill_length=100, max_prefill_queue_size=2)
    assert not r.prefill_remote(100, 0, 0)        # at threshold → local
    assert r.prefill_remote(101, 0, 0)            # above → remote
    assert not r.prefill_remote(300, 250, 0)      # prefix hit absorbs it
    assert not r.prefill_remote(500, 0, 2)        # queue full → local
    assert r.prefill_remote(500, 0, 1)


async def test_disagg_router_dynamic_config():
    hub = MemoryHub()
    drt = DistributedRuntime.in_process(hub)
    r = DisaggRouter(max_local_prefill_length=100, model_name="m")
    await r.start(drt.discovery, drt.runtime)
    assert r.prefill_remote(200, 0, 0)
    await DisaggRouter.publish_config(drt.discovery, "public", "m",
                                      max_local_prefill_length=1000,
                                      max_prefill_queue_size=5)
    await asyncio.sleep(0.05)
    assert not r.prefill_remote(200, 0, 0)  # threshold raised live
    assert r.max_prefill_queue_size == 5
    await r.stop()
    await drt.close()


async def test_prefill_queue_ack_and_redelivery():
    hub = MemoryHub()
    drt = DistributedRuntime.in_process(hub)
    q = PrefillQueue(drt.messaging, "ns", visibility=0.2)
    rpr = RemotePrefillRequest("r1", "e1", [1, 2, 3], [0], 0)
    await q.push(rpr)
    got, ack = await q.pop(timeout=1)
    assert got.request_id == "r1" and got.token_ids == [1, 2, 3]
    # no ack → redelivered after the visibility window
    await asyncio.sleep(0.3)
    got2, ack2 = await q.pop(timeout=1)
    assert got2.request_id == "r1"
    ack2()
    await asyncio.sleep(0.3)
    assert await q.depth() == 0
    await drt.close()


# ---------------------------------------------------------------- e2e


async def _decode_engine_with_disagg(hf_model_dir, hub, engine_overrides=None,
                                     **router_kw):
    runner, econfig = _make_runner(hf_model_dir, **(engine_overrides or {}))
    drt = DistributedRuntime.in_process(hub)
    timeout = router_kw.pop("timeout", 60.0)
    router = DisaggRouter(**router_kw)
    coord = RemotePrefillCoordinator(
        drt, runner, router=router, depth_refresh_s=0.05,
        prefill_timeout_s=timeout,
    )
    await coord.start()
    sched = Scheduler(runner, econfig, disagg=coord)
    sched.start()
    return sched, coord, drt, econfig


async def test_remote_prefill_with_spec_decode_matches_local(hf_model_dir):
    """Ngram speculative decoding on a disagg decode worker: the stream
    after a REMOTE prefill (seq installed from transferred KV) must equal
    pure local generation — proposals draw on the installed history."""
    prompt = [1, 9, 8, 9, 8, 9, 8, 9, 8, 21, 40, 2]  # repetitive → proposals

    runner_l, econfig = _make_runner(hf_model_dir)
    sched_l = Scheduler(runner_l, econfig)
    sched_l.start()
    er = _greedy_request("base-spec", prompt, max_tokens=12)
    sched_l.add_request(er)
    baseline = await _collect(er)
    await sched_l.stop()

    hub = MemoryHub()
    sched, coord, drt_d, _ = await _decode_engine_with_disagg(
        hf_model_dir, hub, max_local_prefill_length=0,
        max_prefill_queue_size=100,
        engine_overrides={"spec_ngram_tokens": 4, "spec_ngram_match": 2},
    )
    runner_p, pconfig = _make_runner(hf_model_dir)
    drt_p = DistributedRuntime.in_process(hub)
    worker = PrefillWorker(drt_p, runner_p, pconfig)
    worker_task = asyncio.create_task(worker.run())
    try:
        er1 = _greedy_request("r1-spec", prompt, max_tokens=12)
        sched.add_request(er1)
        out1 = await _collect(er1)
        assert out1 == baseline
        assert coord.remote_completed == 1
    finally:
        worker_task.cancel()
        await worker.close()
        await sched.stop()
        await drt_p.close()
        await drt_d.close()


async def test_remote_prefill_matches_local(hf_model_dir):
    """Greedy decode after remote prefill == pure local generation."""
    prompt = [1, 17, 43, 99, 7, 3, 250, 12, 5, 77, 8, 21]

    # baseline: local-only engine
    runner_l, econfig = _make_runner(hf_model_dir)
    sched_l = Scheduler(runner_l, econfig)
    sched_l.start()
    er = _greedy_request("base", prompt)
    sched_l.add_request(er)
    baseline = await _collect(er)
    await sched_l.stop()
    assert len(baseline) == 8

    # disagg: decode engine + separate prefill worker, threshold 0 → all remote
    hub = MemoryHub()
    sched, coord, drt_d, _ = await _decode_engine_with_disagg(
        hf_model_dir, hub, max_local_prefill_length=0, max_prefill_queue_size=100,
    )
    runner_p, pconfig = _make_runner(hf_model_dir)
    drt_p = DistributedRuntime.in_process(hub)
    worker = PrefillWorker(drt_p, runner_p, pconfig)
    worker_task = asyncio.create_task(worker.run())
    try:
        er1 = _greedy_request("r1", prompt)
        sched.add_request(er1)
        out1 = await _collect(er1)
        assert out1 == baseline

        # second identical prompt: decode-side prefix hit → suffix-only transfer
        er2 = _greedy_request("r2", prompt)
        sched.add_request(er2)
        out2 = await _collect(er2)
        assert out2 == baseline

        assert coord.remote_completed == 2
        assert worker.prefills == 2
        # second prefill skipped the cached prefix on both sides
        assert worker.prefill_tokens < 2 * len(prompt)
    finally:
        worker_task.cancel()
        await worker.close()
        await sched.stop()
        await drt_p.close()
        await drt_d.close()


async def test_remote_prefill_streamed_chunks_match_local(hf_model_dir):
    """TCP plane, MULTI-CHUNK prompt: the worker's chunked prefill streams
    per-chunk frames while later chunks compute, and the decode stream is
    still byte-identical to pure local generation. Also pins the bounded-
    buffer contract: never more than 2 chunk-sized host frames live."""
    prompt = [1 + (i * 37) % 200 for i in range(28)]  # 28 tokens, 4 blocks

    runner_l, econfig = _make_runner(hf_model_dir)
    sched_l = Scheduler(runner_l, econfig)
    sched_l.start()
    er = _greedy_request("base-stream", prompt)
    sched_l.add_request(er)
    baseline = await _collect(er)
    await sched_l.stop()

    hub = MemoryHub()
    sched, coord, drt_d, _ = await _decode_engine_with_disagg(
        hf_model_dir, hub, max_local_prefill_length=0,
        max_prefill_queue_size=100,
    )
    # worker chunks at 8 tokens/step (1 block per chunk) → 4 chunks,
    # streamed as multiple frames
    runner_p, pconfig = _make_runner(
        hf_model_dir,
        prefill_buckets=[8, 16, 32, 64, 128],
        max_prefill_tokens_per_step=8,
    )
    drt_p = DistributedRuntime.in_process(hub)
    worker = PrefillWorker(drt_p, runner_p, pconfig)
    worker_task = asyncio.create_task(worker.run())
    try:
        er1 = _greedy_request("r-stream", prompt)
        sched.add_request(er1)
        out1 = await _collect(er1)
        assert out1 == baseline
        assert coord.remote_completed == 1
        assert worker.transfer_frames >= 4  # actually streamed, not one shot
        assert worker.max_live_host_frames <= 2
        # worker-side prefix-hit accounting: cold cache → ratio 0, but the
        # totals registered (and render through the registry gauge)
        assert worker.prefix_total_tokens == len(prompt)
        assert worker.prefix_hit_tokens == 0
    finally:
        worker_task.cancel()
        await worker.close()
        await sched.stop()
        await drt_p.close()
        await drt_d.close()


class _LoopbackIci:
    """In-process collective plane: send/recv pair over a thread-safe
    queue (send runs in the worker's executor, recv on the server's
    daemon thread), preserving the seq-in-payload pairing contract."""

    receiver_rank = 0

    def __init__(self, buckets=(2,)):
        import queue

        self.buckets = tuple(buckets)
        self.q = queue.Queue()
        self.sends = 0

    def send(self, k, v, seq=0):
        self.sends += 1
        self.q.put((np.asarray(k), np.asarray(v), int(seq)))

    def recv(self, nblocks):
        k, v, seq = self.q.get(timeout=30)
        return k[:, :nblocks], v[:, :nblocks], seq


async def test_remote_prefill_streamed_ici_matches_local(hf_model_dir):
    """ICI plane, multi-chunk prompt: the pipelined gather→header→
    collective loop (one collective in flight, headers strictly after the
    previous collective resolves) delivers a byte-identical stream."""
    prompt = [1 + (i * 53) % 199 for i in range(28)]

    runner_l, econfig = _make_runner(hf_model_dir)
    sched_l = Scheduler(runner_l, econfig)
    sched_l.start()
    er = _greedy_request("base-ici-stream", prompt)
    sched_l.add_request(er)
    baseline = await _collect(er)
    await sched_l.stop()

    hub = MemoryHub()
    sched, coord, drt_d, _ = await _decode_engine_with_disagg(
        hf_model_dir, hub, max_local_prefill_length=0,
        max_prefill_queue_size=100,
    )
    ici = _LoopbackIci(buckets=(2,))  # ≤2 blocks per collective frame
    coord._server.ici_recv = ici.recv
    coord._server.ici_rank = 0
    runner_p, pconfig = _make_runner(
        hf_model_dir,
        prefill_buckets=[8, 16, 32, 64, 128],
        max_prefill_tokens_per_step=8,
    )
    drt_p = DistributedRuntime.in_process(hub)
    worker = PrefillWorker(drt_p, runner_p, pconfig, ici=ici)
    worker._ici_usable = lambda client: worker.ici is not None
    worker_task = asyncio.create_task(worker.run())
    try:
        er1 = _greedy_request("r-ici-stream", prompt)
        sched.add_request(er1)
        out1 = await _collect(er1)
        assert out1 == baseline
        assert coord.remote_completed == 1
        assert ici.sends >= 2          # payload rode the collective plane
        assert worker.ici is ici       # plane healthy throughout
    finally:
        worker_task.cancel()
        await worker.close()
        await sched.stop()
        await drt_p.close()
        await drt_d.close()


async def test_mid_stream_sender_failure_nacks_commit_and_falls_back(
        hf_model_dir):
    """Sender dies BETWEEN two streamed KV frames: the receiver poisons
    the request's commit, a later (redelivered) commit is nacked, the
    request id is revoked on fallback, and the stream completes via
    local prefill — byte-identical to baseline. Extends
    test_remote_prefill_timeout_falls_back_local to the partial-stream
    hazard that only exists now that frames ship before compute ends."""
    prompt = [1, 17, 43, 99, 7, 3, 250, 12, 5, 77, 8, 21, 9, 14, 100, 61]

    runner_l, econfig = _make_runner(hf_model_dir)
    sched_l = Scheduler(runner_l, econfig)
    sched_l.start()
    er = _greedy_request("base-midfail", prompt)
    sched_l.add_request(er)
    baseline = await _collect(er)
    await sched_l.stop()

    hub = MemoryHub()
    sched, coord, drt, _ = await _decode_engine_with_disagg(
        hf_model_dir, hub, max_local_prefill_length=0,
        max_prefill_queue_size=100, timeout=3.0,
    )
    drt_p = DistributedRuntime.in_process(hub)
    q = PrefillQueue(drt_p.messaging, "public")
    cfg = econfig.model
    bs = econfig.kv_block_size
    try:
        er1 = _greedy_request("r-midfail", prompt)
        sched.add_request(er1)
        popped = await q.pop(timeout=10)
        assert popped is not None
        rpr, ack = popped
        ack()  # we play the (sole) prefill worker by hand
        shape = (cfg.num_layers, 1, bs, cfg.num_kv_heads, cfg.head_dim)
        k = np.ones(shape, np.float32)

        # attempt 1: one frame on the wire, then the connection dies
        c1 = await KvTransferClient("127.0.0.1", coord._server.port).connect()
        await c1.send_blocks(rpr.request_id, rpr.block_ids[:1], k, k)
        await c1.close()          # killed between frames — no commit
        await asyncio.sleep(0.1)  # let the server observe the EOF

        # attempt 2 (a redelivery would do this): full stream + commit —
        # the poisoned request id must be NACKED, not committed
        c2 = await KvTransferClient("127.0.0.1", coord._server.port).connect()
        for i in range(len(rpr.block_ids)):
            await c2.send_blocks(rpr.request_id, rpr.block_ids[i : i + 1], k, k)
        committed = await c2.send_commit(rpr.request_id, 42, None)
        assert committed is False

        # the decode side never resumes on the nacked commit: the bounded
        # timeout falls back to LOCAL prefill and the stream matches
        out = await asyncio.wait_for(_collect(er1), timeout=60)
        assert out == baseline
        assert coord.remote_completed == 0

        # the request id was revoked at fallback: late frames are dropped
        # and a late commit is nacked again, not resumed-on
        await c2.send_blocks(rpr.request_id, rpr.block_ids[:1], k, k)
        assert await c2.send_commit(rpr.request_id, 42, None) is False
        await c2.close()
    finally:
        await sched.stop()
        await drt_p.close()
        await drt.close()


async def test_remote_prefill_timeout_falls_back_local(hf_model_dir):
    """No prefill worker alive → decode worker recovers by prefilling locally."""
    prompt = [1, 17, 43, 99, 7, 3, 250, 12, 5, 77, 8, 21]

    runner_l, econfig = _make_runner(hf_model_dir)
    sched_l = Scheduler(runner_l, econfig)
    sched_l.start()
    er = _greedy_request("base", prompt)
    sched_l.add_request(er)
    baseline = await _collect(er)
    await sched_l.stop()

    hub = MemoryHub()
    sched, coord, drt, _ = await _decode_engine_with_disagg(
        hf_model_dir, hub, max_local_prefill_length=0, max_prefill_queue_size=100,
        timeout=0.4,
    )
    coord.prefill_timeout_s = 0.4
    try:
        er1 = _greedy_request("r1", prompt)
        sched.add_request(er1)
        out = await _collect(er1)
        assert out == baseline
        assert coord.remote_submitted == 1
        assert coord.remote_completed == 0
    finally:
        await sched.stop()
        await drt.close()


# ------------------------------------------------- ici failure recovery


class _ExplodingIci:
    """Sender-side plane stub whose collective fails mid-entry (the peer
    died inside the ppermute): IciSendError(entered=True)."""

    buckets = (16,)

    def __init__(self):
        self.sends = 0

    def send(self, k, v, seq=0):
        from dynamo_tpu.disagg.ici_transfer import IciSendError

        self.sends += 1
        raise IciSendError(RuntimeError("peer died mid-collective"), True)


class _PreEntryFailIci:
    """Sender-side stub failing BEFORE the collective was dispatched
    (device_put/staging error): entered=False → balance, keep plane."""

    buckets = (16,)

    def __init__(self, fail_times=1):
        self.fail_times = fail_times
        self.sends = 0
        self.balanced = 0

    def send(self, k, v, seq=0):
        from dynamo_tpu.disagg.ici_transfer import IciSendError

        self.sends += 1
        if self.sends <= self.fail_times:
            raise IciSendError(RuntimeError("staging failed"), False)
        # "succeed": nothing to move in-process — the receiver-side stub
        # below supplies the payload path; this models a healthy entry

    def send_balancing_entry(self, nblocks):
        self.balanced += 1


async def test_ici_entered_failure_abandons_plane_and_request_completes(
        hf_model_dir):
    """The VERDICT r4 item-8 recovery story, end to end in-process:
    collective dies mid-entry (entered=True) → sender abandons the plane
    → queue redelivery retries over TCP → the receiver (which dropped
    the orphaned first attempt) nacks that commit → the decode side's
    bounded timeout falls back to LOCAL prefill → the request completes
    with the exact baseline stream. Per-request failure, never
    per-process (reference bar: docs/disagg_serving.md:102-110)."""
    prompt = [1, 17, 43, 99, 7, 3, 250, 12, 5, 77, 8, 21]

    runner_l, econfig_l = _make_runner(hf_model_dir)
    sched_l = Scheduler(runner_l, econfig_l)
    sched_l.start()
    er = _greedy_request("base", prompt)
    sched_l.add_request(er)
    baseline = await _collect(er)
    await sched_l.stop()

    import time as _time

    class _RecvDropIci:
        """Receiver-side stub: the orphaned entry 'returns' a poison
        payload (what a balancing entry or unwind leaves behind)."""

        receiver_rank = 0

        def recv(self, nblocks):
            _time.sleep(0.05)
            shp = (econfig_l.model.num_layers, nblocks, 8,
                   econfig_l.model.num_kv_heads,
                   econfig_l.model.head_dim)
            z = np.zeros(shp, np.float32)
            return z, z, -1  # seq never matches a header → dropped

    hub = MemoryHub()
    sched, coord, drt_d, _ = await _decode_engine_with_disagg(
        hf_model_dir, hub, max_local_prefill_length=0,
        max_prefill_queue_size=100, timeout=8.0,
    )
    coord._server.ici_recv = _RecvDropIci().recv
    coord._server.ici_rank = 0
    runner_p, pconfig = _make_runner(hf_model_dir)
    drt_p = DistributedRuntime.in_process(hub)
    worker = PrefillWorker(drt_p, runner_p, pconfig, ici=_ExplodingIci())
    worker.queue.visibility = 0.5  # fast redelivery for the test
    worker._ici_usable = lambda client: worker.ici is not None
    worker_task = asyncio.create_task(worker.run())
    try:
        er1 = _greedy_request("r-ici-die", prompt)
        sched.add_request(er1)
        out1 = await asyncio.wait_for(_collect(er1), timeout=90)
        assert out1 == baseline
        assert worker.ici is None  # plane abandoned after entered=True
    finally:
        worker_task.cancel()
        await worker.close()
        await sched.stop()
        await drt_p.close()
        await drt_d.close()


async def test_ici_pre_entry_failure_balances_and_keeps_plane(hf_model_dir):
    """entered=False through the REAL prefill worker: the first attempt
    fails pre-entry, the worker pairs the orphaned receiver entry with a
    poison balancing entry and KEEPS the plane; the redelivered attempt
    rides ici again (payload dropped by the receiver stub, commit
    nacked) and the decode side's bounded timeout completes the stream
    locally — identical to baseline."""
    prompt = [1, 17, 43, 99, 7, 3, 250, 12]

    runner_l, econfig_l = _make_runner(hf_model_dir)
    sched_l = Scheduler(runner_l, econfig_l)
    sched_l.start()
    er = _greedy_request("base2", prompt)
    sched_l.add_request(er)
    baseline = await _collect(er)
    await sched_l.stop()

    import time as _time

    class _RecvDropIci:
        receiver_rank = 0

        def recv(self, nblocks):
            _time.sleep(0.05)
            shp = (econfig_l.model.num_layers, nblocks, 8,
                   econfig_l.model.num_kv_heads, econfig_l.model.head_dim)
            z = np.zeros(shp, np.float32)
            return z, z, -1  # seq never matches a header -> dropped

    hub = MemoryHub()
    sched, coord, drt_d, _ = await _decode_engine_with_disagg(
        hf_model_dir, hub, max_local_prefill_length=0,
        max_prefill_queue_size=100, timeout=8.0,
    )
    coord._server.ici_recv = _RecvDropIci().recv
    coord._server.ici_rank = 0
    runner_p, pconfig = _make_runner(hf_model_dir)
    drt_p = DistributedRuntime.in_process(hub)
    ici = _PreEntryFailIci(fail_times=1)
    worker = PrefillWorker(drt_p, runner_p, pconfig, ici=ici)
    worker.queue.visibility = 0.5
    worker._ici_usable = lambda client: worker.ici is not None
    worker_task = asyncio.create_task(worker.run())
    try:
        er1 = _greedy_request("r-ici-balance", prompt)
        sched.add_request(er1)
        out1 = await asyncio.wait_for(_collect(er1), timeout=90)
        assert out1 == baseline
        assert ici.balanced == 1      # orphan paired with poison
        assert worker.ici is ici      # plane KEPT after entered=False
        assert ici.sends >= 2         # redelivery rode ici again
    finally:
        worker_task.cancel()
        await worker.close()
        await sched.stop()
        await drt_p.close()
        await drt_d.close()
