"""Multi-model multi-tenant fleet (registry/): units + e2e.

The acceptance bar (ISSUE 12): one frontend routes ``model=`` across
per-model worker pools sharing one endpoint (streams byte-identical to
single-model runs), an idle model drains to zero and cold-starts back
on first request within the deadline, and a tenant exceeding its token
bucket gets 429 + Retry-After while a second tenant's concurrent
requests are untouched.
"""

import asyncio
import json

import aiohttp
import pytest

from dynamo_tpu.http.service import (
    HttpService,
    ModelManager,
    ModelWatcher,
    register_model,
)
from dynamo_tpu.kv_router.indexer import OverlapScores
from dynamo_tpu.kv_router.protocols import ForwardPassMetrics
from dynamo_tpu.kv_router.scheduler import AllWorkersBusy, KvScheduler
from dynamo_tpu.planner.admission import AdmissionRejected
from dynamo_tpu.registry import (
    ColdStartTimeout,
    KubePoolBackend,
    ModelCard,
    ModelRegistry,
    PoolConfig,
    PoolDemand,
    PoolManager,
    PoolPolicy,
    PoolPolicyConfig,
    RegistryAdmin,
    TenantQuota,
    TenantQuotas,
)
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.transports.memory import MemoryHub


class Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# --------------------------------------------------------------------------
# cards + registry view
# --------------------------------------------------------------------------


def test_model_card_wire_roundtrip_and_visibility():
    card = ModelCard(
        name="m8b", endpoint="dyn://public.backend.generate",
        model_type="both", family="llama", context_length=8192,
        aliases=["m8b-fast", "default"], tenants=["acme", "globex"],
        model_path="/models/m8b",
    )
    again = ModelCard.from_wire(json.loads(json.dumps(card.to_wire())))
    assert again == card
    assert card.visible_to("acme") and card.visible_to("globex")
    assert not card.visible_to("rivals") and not card.visible_to(None)
    public = ModelCard(name="pub", endpoint="dyn://a.b.c")
    assert public.visible_to(None) and public.visible_to("anyone")
    admin_only = ModelCard(name="hidden", endpoint="dyn://a.b.c",
                           tenants=[])
    assert not admin_only.visible_to("acme")
    with pytest.raises(ValueError):
        ModelCard(name="x", model_type="bogus")


def test_registry_resolves_aliases_and_tenant_visibility():
    reg = ModelRegistry()
    reg.put(ModelCard(name="m8b", endpoint="dyn://a.b.c",
                      aliases=["fast"]))
    reg.put(ModelCard(name="acme-ft", endpoint="dyn://a.b.c",
                      tenants=["acme"]))
    assert reg.resolve("m8b") == "m8b"
    assert reg.resolve("fast") == "m8b"          # alias → canonical
    assert reg.resolve("nope") is None
    # tenant scoping: invisible answers exactly like unknown
    assert reg.resolve("acme-ft", "acme") == "acme-ft"
    assert reg.resolve("acme-ft", "rivals") is None
    assert reg.resolve("acme-ft", None) is None
    assert reg.visible("acme") == ["acme-ft", "m8b"]
    assert reg.visible("rivals") == ["m8b"]
    # alias release on removal
    reg.remove("m8b")
    assert reg.resolve("fast") is None
    # alias collision: first owner keeps it
    reg.put(ModelCard(name="a", endpoint="dyn://a.b.c", aliases=["x"]))
    reg.put(ModelCard(name="b", endpoint="dyn://a.b.c", aliases=["x"]))
    assert reg.resolve("x") == "a"


def test_registry_listeners_fire_and_survive_failures():
    reg = ModelRegistry()
    seen = []
    reg.add_listener(lambda n, c: (_ for _ in ()).throw(RuntimeError()))
    reg.add_listener(lambda n, c: seen.append((n, c is not None)))
    reg.put(ModelCard(name="m", endpoint="dyn://a.b.c"))
    reg.remove("m")
    assert seen == [("m", True), ("m", False)]


# --------------------------------------------------------------------------
# tenant token buckets
# --------------------------------------------------------------------------


def test_tenant_parse_contract_garbage_degrades_to_default():
    q = TenantQuotas()
    assert q.resolve(None) == "default"
    assert q.resolve("") == "default"
    assert q.resolve("acme") == "acme"
    assert q.resolve("  acme  ") == "acme"
    # garbage: counted fallback, never an error
    for bad in ("sp ace", "a" * 65, "…", "-leading", 'x"y'):
        assert q.resolve(bad) == "default"
    text = q.registry.render()
    assert "dynamo_registry_tenant_fallbacks_total 5" in text


def test_request_bucket_depletes_and_refills():
    clock = Clock()
    q = TenantQuotas(default=TenantQuota(requests_per_s=2.0, burst_s=1.0),
                     clock=clock)
    q.admit("acme")
    q.admit("acme")  # burst capacity = 2
    with pytest.raises(AdmissionRejected) as e:
        q.admit("acme")
    assert e.value.outcome == "quota"
    assert int(e.value.retry_after_header) >= 1
    # isolation: a different tenant has its own bucket
    q.admit("globex")
    # refill: half a second buys one request back
    clock.advance(0.5)
    q.admit("acme")
    with pytest.raises(AdmissionRejected):
        q.admit("acme")
    text = q.registry.render()
    assert 'dynamo_registry_tenant_sheds_total{bucket="requests",tenant="acme"} 2' in text
    assert 'outcome="quota"' in text


def test_token_bucket_overdraft_delays_next_admission():
    clock = Clock()
    q = TenantQuotas(default=TenantQuota(tokens_per_s=10.0, burst_s=1.0),
                     clock=clock)
    q.admit("acme")
    # the stream actually used 25 tokens: 10 capacity - 25 = -15
    q.charge_tokens("acme", 25)
    with pytest.raises(AdmissionRejected) as e:
        q.admit("acme")
    assert e.value.outcome == "quota"
    # refill must pay the overdraft back past zero: 15/10 = 1.5s + 1 token
    clock.advance(1.0)
    with pytest.raises(AdmissionRejected):
        q.admit("acme")
    clock.advance(0.7)
    q.admit("acme")
    assert 'dynamo_registry_tenant_tokens_total{tenant="acme"} 25' \
        in q.registry.render()


def test_tenant_table_is_bounded_with_idle_eviction():
    clock = Clock()
    q = TenantQuotas(default=TenantQuota(requests_per_s=1.0), clock=clock,
                     max_tracked=3)
    for i in range(3):
        q.admit(f"t{i}")
        clock.advance(1.0)
    q.admit("t-new")  # evicts the longest-idle (t0)
    assert len(q._tenants) == 3 and "t0" not in q._tenants


def test_quota_outcome_rides_a_shared_admissions_counter():
    from dynamo_tpu.telemetry.registry import MetricsRegistry

    shared = MetricsRegistry()
    q = TenantQuotas(default=TenantQuota(requests_per_s=1.0, burst_s=1.0))
    q.bind_admissions(shared)
    q.admit("acme")
    with pytest.raises(AdmissionRejected):
        q.admit("acme")
    text = shared.render()
    assert 'outcome="quota",tenant="acme"' in text \
        or 'tenant="acme",outcome="quota"' in text
    # the quota family must NOT also render on the quotas' own registry
    assert "dynamo_planner_admissions_total" not in q.registry.render()


# --------------------------------------------------------------------------
# pool policy + manager
# --------------------------------------------------------------------------


def test_pool_policy_scale_to_zero_with_cooldown():
    clock = Clock()
    policy = PoolPolicy(PoolPolicyConfig(idle_to_zero_s=60.0,
                                         cooldown_s=30.0), clock=clock)
    demand = {"m": PoolDemand(workers=2, idle_s=120.0)}
    acts = policy.decide(demand)
    assert [(a.model, a.kind) for a in acts] == [("m", "scale_to_zero")]
    # pacing: the same decision inside the cooldown is withheld
    assert policy.decide(demand) == []
    clock.advance(31.0)
    assert len(policy.decide(demand)) == 1
    # a busy pool never drains
    assert policy.decide({"m": PoolDemand(workers=2, idle_s=5.0)}) == []
    # an empty pool has nothing to drain
    assert policy.decide({"m": PoolDemand(workers=0, idle_s=999.0)}) == []


def test_pool_policy_cold_start_beats_idle_and_cooldown():
    clock = Clock()
    policy = PoolPolicy(PoolPolicyConfig(idle_to_zero_s=60.0), clock=clock)
    acts = policy.decide(
        {"m": PoolDemand(workers=0, idle_s=999.0, cold_pending=True)})
    assert [(a.model, a.kind) for a in acts] == [("m", "cold_start")]


async def test_pool_manager_cold_start_shares_one_spawn_and_completes():
    reg = ModelRegistry()
    reg.put(ModelCard(name="m", endpoint="dyn://a.b.c"))
    size = {"m": 0}
    spawns = []

    async def spawner(card):
        spawns.append(card.name)
        await asyncio.sleep(0.05)
        size["m"] = 1

    pm = PoolManager(reg, lambda m: size[m], spawner=spawner,
                     config=PoolConfig(cold_start_deadline_s=5.0,
                                       poll_s=0.01))
    # concurrent cold requests share ONE spawn
    await asyncio.gather(*(pm.await_capacity("m") for _ in range(4)))
    assert spawns == ["m"]
    text = pm.registry.render()
    assert ('dynamo_registry_cold_starts_total{model="m",'
            'outcome="started"} 1') in text
    assert 'outcome="completed"} 4' in text
    await pm.stop()


async def test_pool_manager_cold_start_timeout_and_no_spawner():
    reg = ModelRegistry()
    reg.put(ModelCard(name="m", endpoint="dyn://a.b.c"))

    async def dead_spawner(card):
        pass  # nothing ever joins

    pm = PoolManager(reg, lambda m: 0, spawner=dead_spawner,
                     config=PoolConfig(cold_start_deadline_s=0.1,
                                       poll_s=0.01, retry_after_s=7.0))
    with pytest.raises(ColdStartTimeout) as e:
        await pm.await_capacity("m")
    assert e.value.retry_after_s == 7.0
    # no spawner at all: same bounded wait, counted distinctly
    pm2 = PoolManager(reg, lambda m: 0,
                      config=PoolConfig(cold_start_deadline_s=0.05,
                                        poll_s=0.01))
    with pytest.raises(ColdStartTimeout):
        await pm2.await_capacity("m")
    assert 'outcome="no_spawner"} 1' in pm2.registry.render()
    await pm.stop()
    await pm2.stop()


async def test_pool_manager_step_drains_idle_pool():
    clock = Clock()
    reg = ModelRegistry()
    reg.put(ModelCard(name="idle-m", endpoint="dyn://a.b.c"))
    reg.put(ModelCard(name="busy-m", endpoint="dyn://a.b.c"))
    size = {"idle-m": 2, "busy-m": 2}
    drained = []

    async def drainer(model):
        drained.append(model)
        size[model] = 0

    pm = PoolManager(
        reg, lambda m: size[m], drainer=drainer, clock=clock,
        policy=PoolPolicy(PoolPolicyConfig(idle_to_zero_s=60.0),
                          clock=clock),
    )
    pm.note_request("busy-m")
    clock.advance(120.0)
    pm.note_request("busy-m")  # stays warm
    applied = await pm.step()
    assert drained == ["idle-m"]
    assert [(a.model, a.kind) for a in applied] == [("idle-m",
                                                     "scale_to_zero")]
    assert 'dynamo_registry_scale_to_zero_total{model="idle-m"} 1' \
        in pm.registry.render()
    await pm.stop()


async def test_kube_pool_backend_patches_replicas_0_and_1():
    from dynamo_tpu.deploy import InMemoryKube, Reconciler

    kube = InMemoryKube()
    cr = {
        "apiVersion": "dynamo.example.com/v1alpha1",
        "kind": "DynamoDeployment",
        "metadata": {"name": "fleet", "namespace": "serving"},
        "spec": {"image": "dynamo-tpu:test", "namespace": "public",
                 "services": {}},
    }
    backend = KubePoolBackend(Reconciler(kube), cr)
    await backend.spawn(ModelCard(name="m8b", endpoint="dyn://a.b.c"))
    dep = kube.objects["Deployment/serving/fleet-pool-m8b"]
    assert dep["spec"]["replicas"] == 1
    await backend.drain("m8b")
    dep = kube.objects["Deployment/serving/fleet-pool-m8b"]
    assert dep["spec"]["replicas"] == 0


def test_recovery_respawn_with_card_passes_the_card_through():
    """respawn-with-a-different-card: the one new recovery capability
    the pool plane needs — the controller routes the card into the
    respawner keyword."""
    from dynamo_tpu.recovery import RecoveryConfig, RecoveryController

    got = []

    async def respawner(card=None):
        got.append(card)

    controller = RecoveryController(
        engine_id="e", respawner=respawner,
        config=RecoveryConfig(respawn_backoff_s=0.01),
    )
    card = ModelCard(name="swap-in", endpoint="dyn://a.b.c")

    async def go():
        assert await controller.respawn_with_card(card) is True
        # a plain respawn afterwards carries no card
        await controller._respawn("plain")

    asyncio.run(go())
    assert got == [card, None]


# --------------------------------------------------------------------------
# per-model pool partition in the KV scheduler
# --------------------------------------------------------------------------


def test_kv_scheduler_pool_filter_selects_within_the_model_pool():
    ks = KvScheduler(block_size=16)
    # w-b is far less loaded AND holds the prefix — but serves model b
    ks.update_metrics("w-a", ForwardPassMetrics(
        request_active_slots=3, request_total_slots=4,
        kv_active_blocks=50, kv_total_blocks=64))
    ks.update_metrics("w-b", ForwardPassMetrics(
        request_total_slots=4, kv_total_blocks=64))
    overlap = OverlapScores(scores={"w-b": 4})
    for _ in range(8):
        d = ks.schedule(64, overlap, pool={"w-a"})
        assert d.worker_id == "w-a"
        # the pull hint must not point across pools either: w-b's
        # "overlap" is another model's KV
        assert d.best_prefix_worker is None
    with pytest.raises(AllWorkersBusy):
        ks.schedule(64, OverlapScores(), pool=set())
    # no pool = the old whole-endpoint behavior
    assert ks.schedule(64, overlap).worker_id in ("w-a", "w-b")


# --------------------------------------------------------------------------
# HTTP edge: 404 body, /v1/models enrichment, tenant isolation
# --------------------------------------------------------------------------


async def test_unknown_model_404_body_shape():
    service = HttpService(ModelManager(), host="127.0.0.1", port=0)
    await service.start()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"http://127.0.0.1:{service.port}/v1/chat/completions",
                json={"model": "ghost",
                      "messages": [{"role": "user", "content": "hi"}]},
            ) as r:
                assert r.status == 404
                body = await r.json()
    finally:
        await service.stop()
    err = body["error"]
    assert err["code"] == "model_not_found"
    assert err["type"] == "invalid_request_error"
    assert err["param"] == "model"
    assert "'ghost'" in err["message"]


class _FixedEngine:
    """Deterministic OpenAI-level engine: fixed ids, tagged content —
    byte-identical bodies across runs by construction."""

    def __init__(self, tag):
        self.tag = tag

    def generate(self, ctx):
        async def gen():
            req = ctx.payload
            text = req.messages[-1].text_content() if hasattr(
                req, "messages") else ""
            base = {"id": f"cmpl-{self.tag}", "object":
                    "chat.completion.chunk", "created": 0,
                    "model": getattr(req, "model", "?")}
            yield {**base, "choices": [{"index": 0, "delta":
                   {"role": "assistant"}, "finish_reason": None}]}
            yield {**base, "choices": [{"index": 0, "delta":
                   {"content": f"{self.tag}:{text}"},
                   "finish_reason": None}]}
            yield {**base, "choices": [{"index": 0, "delta": {},
                   "finish_reason": "stop"}]}

        return gen()


async def test_v1_models_enrichment_and_tenant_filter():
    manager = ModelManager()
    manager.add_chat_model("m8b", _FixedEngine("a"))
    manager.set_card(ModelCard(
        name="m8b", endpoint="dyn://a.b.c", family="llama",
        context_length=8192, aliases=["fast"], owned_by="fleet-team"))
    manager.add_chat_model("acme-ft", _FixedEngine("b"))
    manager.set_card(ModelCard(
        name="acme-ft", endpoint="dyn://a.b.c", tenants=["acme"]))
    quotas = TenantQuotas()  # quota-less but tenant-aware
    service = HttpService(manager, host="127.0.0.1", port=0,
                          quotas=quotas)
    await service.start()
    try:
        async with aiohttp.ClientSession() as s:
            url = f"http://127.0.0.1:{service.port}/v1/models"
            async with s.get(url) as r:
                body = await r.json()
            rows = {m["id"]: m for m in body["data"]}
            # anonymous callers see only public models, enriched
            assert set(rows) == {"m8b"}
            assert rows["m8b"]["family"] == "llama"
            assert rows["m8b"]["max_model_len"] == 8192
            assert rows["m8b"]["aliases"] == ["fast"]
            assert rows["m8b"]["owned_by"] == "fleet-team"
            async with s.get(url, headers={"X-Tenant": "acme"}) as r:
                body = await r.json()
            assert {m["id"] for m in body["data"]} == {"m8b", "acme-ft"}
            # the scoped model 404s for the wrong tenant — and by alias
            async with s.post(
                f"http://127.0.0.1:{service.port}/v1/chat/completions",
                json={"model": "acme-ft",
                      "messages": [{"role": "user", "content": "x"}]},
                headers={"X-Tenant": "rivals"},
            ) as r:
                assert r.status == 404
                assert (await r.json())["error"]["code"] == "model_not_found"
            # the right tenant gets through
            async with s.post(
                f"http://127.0.0.1:{service.port}/v1/chat/completions",
                json={"model": "acme-ft",
                      "messages": [{"role": "user", "content": "x"}]},
                headers={"X-Tenant": "acme"},
            ) as r:
                assert r.status == 200
            # alias routing: "fast" resolves to m8b and serves
            async with s.post(
                f"http://127.0.0.1:{service.port}/v1/chat/completions",
                json={"model": "fast",
                      "messages": [{"role": "user", "content": "y"}]},
            ) as r:
                assert r.status == 200
                body = await r.json()
            assert body["choices"][0]["message"]["content"] == "a:y"
    finally:
        await service.stop()


async def test_tenant_spike_sheds_spiker_only_e2e():
    """The acceptance e2e: tenant A blows through its bucket → 429 +
    Retry-After; tenant B's concurrent requests all succeed; garbage
    X-Tenant degrades to default with a counter, never a 500."""
    manager = ModelManager()
    manager.add_chat_model("m", _FixedEngine("m"))
    quotas = TenantQuotas(
        default=TenantQuota(requests_per_s=1000.0),
        overrides={"spiky": TenantQuota(requests_per_s=1.0, burst_s=3.0)},
    )
    service = HttpService(manager, host="127.0.0.1", port=0,
                          quotas=quotas)
    await service.start()
    try:
        async with aiohttp.ClientSession() as s:
            url = f"http://127.0.0.1:{service.port}/v1/chat/completions"

            async def one(tenant):
                async with s.post(
                    url,
                    json={"model": "m", "messages":
                          [{"role": "user", "content": "hi"}]},
                    headers={"X-Tenant": tenant},
                ) as r:
                    return r.status, r.headers.get("Retry-After"), \
                        await r.json()

            results = await asyncio.gather(
                *(one("spiky") for _ in range(8)),
                *(one("calm") for _ in range(8)),
            )
            spiky, calm = results[:8], results[8:]
        # the spiker: 3 admitted (burst), the rest shed with Retry-After
        ok = [r for r in spiky if r[0] == 200]
        shed = [r for r in spiky if r[0] == 429]
        assert len(ok) == 3 and len(shed) == 5
        for status, retry_after, body in shed:
            assert retry_after is not None and int(retry_after) >= 1
            assert body["error"]["type"] == "overloaded"
        # the calm tenant is untouched
        assert all(r[0] == 200 for r in calm)
        text = service.metrics.render()
        assert 'dynamo_registry_tenant_sheds_total{bucket="requests",tenant="spiky"} 5' in text
        assert 'outcome="quota",tenant="spiky"' in text \
            or 'tenant="spiky",outcome="quota"' in text

        # garbage header: default tenant, 200, counted
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"http://127.0.0.1:{service.port}/v1/chat/completions",
                json={"model": "m",
                      "messages": [{"role": "user", "content": "hi"}]},
                headers={"X-Tenant": "not a tenant !!"},
            ) as r:
                assert r.status == 200
        assert ("dynamo_registry_tenant_fallbacks_total 1"
                in service.metrics.render())
    finally:
        await service.stop()


# --------------------------------------------------------------------------
# two-model two-pool e2e over one shared endpoint
# --------------------------------------------------------------------------


def _pool_handler(tag):
    async def handler(payload, ctx):
        from dynamo_tpu.protocols.openai import ChatCompletionRequest
        from dynamo_tpu.runtime.engine import Context

        req = ChatCompletionRequest.model_validate(payload)
        async for chunk in _FixedEngine(tag).generate(Context(req)):
            yield chunk

    return handler


async def _sse_body(port, model, content="route me"):
    async with aiohttp.ClientSession() as s:
        async with s.post(
            f"http://127.0.0.1:{port}/v1/chat/completions",
            json={"model": model, "stream": True,
                  "messages": [{"role": "user", "content": content}]},
        ) as r:
            assert r.status == 200, await r.text()
            return await r.read()


async def _two_pool_frontend(hub, models):
    """Frontend + watcher over ``hub`` with cards for ``models``
    (name → endpoint path)."""
    front_drt = DistributedRuntime.in_process(hub)
    manager = ModelManager()
    watcher = ModelWatcher(front_drt, manager, namespace="public")
    await watcher.start()
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    return front_drt, manager, watcher, service


async def test_two_model_pools_share_one_endpoint_byte_identical():
    hub = MemoryHub()
    path = "dyn://prod.pool.generate"

    async def worker(model_tag):
        drt = DistributedRuntime.in_process(hub)
        ep = drt.namespace("prod").component("pool").endpoint("generate")
        serving = await ep.serve(_pool_handler(model_tag),
                                 metadata={"model": model_tag})
        await register_model(
            drt, "public", model_tag, path, model_type="both",
            card=ModelCard(name=model_tag, endpoint=path,
                           model_type="both"),
        )
        return drt, serving

    # single-model baseline: only m-a serving
    drt_a, serving_a = await worker("m-a")
    _, manager, watcher, service = await _two_pool_frontend(hub, None)
    await asyncio.sleep(0.05)
    baseline_a = await _sse_body(service.port, "m-a")
    await service.stop()
    await watcher.stop()

    # full fleet: both pools behind the SAME component endpoint
    drt_b, serving_b = await worker("m-b")
    _, manager, watcher, service = await _two_pool_frontend(hub, None)
    await asyncio.sleep(0.05)
    try:
        assert manager.model_names() == ["m-a", "m-b"]
        assert watcher.pool_size("m-a") == 1
        assert watcher.pool_size("m-b") == 1
        body_a = await _sse_body(service.port, "m-a")
        body_b = await _sse_body(service.port, "m-b")
        # model= routed into the right pool, and the stream is byte-
        # identical to the single-model run
        assert body_a == baseline_a
        assert b"m-a:route me" in body_a and b"m-b:" not in body_a
        assert b"m-b:route me" in body_b
        # repeat under interleaving: never a cross-pool pick
        for _ in range(5):
            assert (await _sse_body(service.port, "m-a")) == baseline_a

        # rebind without restart: worker A leaves → pool empties → 503
        # (card still registered), a fresh worker joins → routes again
        await serving_a.stop()
        await asyncio.sleep(0.05)
        assert watcher.pool_size("m-a") == 0
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"http://127.0.0.1:{service.port}/v1/chat/completions",
                json={"model": "m-a",
                      "messages": [{"role": "user", "content": "x"}]},
            ) as r:
                assert r.status == 503
                assert r.headers.get("Retry-After") is not None
        ep_a2 = drt_a.namespace("prod").component("pool").endpoint(
            "generate")
        serving_a2 = await ep_a2.serve(_pool_handler("m-a"),
                                       metadata={"model": "m-a"})
        await asyncio.sleep(0.05)
        assert (await _sse_body(service.port, "m-a")) == baseline_a
        await serving_a2.stop()
    finally:
        await service.stop()
        await watcher.stop()
        await serving_b.stop()


async def test_admin_add_remove_rebinds_routes():
    hub = MemoryHub()
    path = "dyn://prod.pool.generate"
    worker_drt = DistributedRuntime.in_process(hub)
    ep = worker_drt.namespace("prod").component("pool").endpoint("generate")
    serving = await ep.serve(_pool_handler("dyn-m"),
                             metadata={"model": "dyn-m"})

    front_drt, manager, watcher, service = await _two_pool_frontend(
        hub, None)
    service.registry_admin = RegistryAdmin(front_drt, "public")
    try:
        url = f"http://127.0.0.1:{service.port}"
        async with aiohttp.ClientSession() as s:
            # not registered yet: proper 404 body
            async with s.post(
                f"{url}/v1/chat/completions",
                json={"model": "dyn-m",
                      "messages": [{"role": "user", "content": "x"}]},
            ) as r:
                assert r.status == 404
            # dynamic add through the admin API (dynamoctl's wire)
            async with s.post(f"{url}/admin/models", json={
                "name": "dyn-m", "endpoint": path, "model_type": "both",
                "family": "llama", "aliases": ["dyn-alias"],
            }) as r:
                assert r.status == 200, await r.text()
            await asyncio.sleep(0.05)
            body = await _sse_body(service.port, "dyn-m", "added live")
            assert b"dyn-m:added live" in body
            # the alias resolves too
            assert b"dyn-m:added live" in await _sse_body(
                service.port, "dyn-alias", "added live")
            # admin view lists the card
            async with s.get(f"{url}/admin/models") as r:
                cards = (await r.json())["models"]
            assert [c["name"] for c in cards] == ["dyn-m"]
            # malformed endpoint rejects at the door
            async with s.post(f"{url}/admin/models", json={
                "name": "bad", "endpoint": "not-an-endpoint"
            }) as r:
                assert r.status == 400
            # dynamic remove unbinds the route
            async with s.delete(f"{url}/admin/models/dyn-m") as r:
                assert r.status == 200
            await asyncio.sleep(0.05)
            async with s.post(
                f"{url}/v1/chat/completions",
                json={"model": "dyn-m",
                      "messages": [{"role": "user", "content": "x"}]},
            ) as r:
                assert r.status == 404
    finally:
        await service.stop()
        await watcher.stop()
        await serving.stop()


# --------------------------------------------------------------------------
# scale-to-zero → cold-start respawn e2e
# --------------------------------------------------------------------------


async def test_scale_to_zero_and_cold_start_respawn_e2e():
    """The elasticity e2e: an idle model's pool drains to zero, the
    next request cold-starts a worker with that model's card, and the
    queued request completes within the deadline."""
    hub = MemoryHub()
    path = "dyn://prod.pool.generate"
    worker_drt = DistributedRuntime.in_process(hub)
    ep = worker_drt.namespace("prod").component("pool").endpoint("generate")
    state = {"serving": None, "spawned": 0}

    async def spawn_worker(card):
        state["spawned"] += 1
        state["serving"] = await ep.serve(
            _pool_handler(card.name), metadata={"model": card.name})

    async def drain_pool(model):
        if state["serving"] is not None:
            await state["serving"].stop()
            state["serving"] = None

    front_drt, manager, watcher, service = await _two_pool_frontend(
        hub, None)
    # durable (admin) card: scale-to-zero needs the registration to
    # outlive the workers
    admin = RegistryAdmin(front_drt, "public")
    await admin.add(ModelCard(name="elastic-m", endpoint=path,
                              model_type="both"))
    await asyncio.sleep(0.05)

    clock = Clock()
    pools = PoolManager(
        manager.registry, watcher.pool_size,
        spawner=spawn_worker, drainer=drain_pool, clock=clock,
        config=PoolConfig(cold_start_deadline_s=5.0, poll_s=0.01),
        policy=PoolPolicy(PoolPolicyConfig(idle_to_zero_s=60.0),
                          clock=clock),
    )
    service.attach_pools(pools)
    try:
        # first request finds the pool cold → cold start #1
        body = await _sse_body(service.port, "elastic-m", "wake up")
        assert b"elastic-m:wake up" in body
        assert state["spawned"] == 1
        assert watcher.pool_size("elastic-m") == 1

        # idle long enough → the policy drains the pool to zero
        clock.advance(120.0)
        applied = await pools.step()
        assert [(a.model, a.kind) for a in applied] == [
            ("elastic-m", "scale_to_zero")]
        await asyncio.sleep(0.05)
        assert watcher.pool_size("elastic-m") == 0

        # next request cold-starts again and completes in-deadline —
        # the full scale-to-zero → respawn → serve cycle
        body = await _sse_body(service.port, "elastic-m", "wake again")
        assert b"elastic-m:wake again" in body
        assert state["spawned"] == 2
        text = service.metrics.render()
        assert 'dynamo_registry_scale_to_zero_total{model="elastic-m"} 1' \
            in text
        assert ('dynamo_registry_cold_starts_total{model="elastic-m",'
                'outcome="completed"} 2') in text
        # /admin/pools reflects the live pool
        async with aiohttp.ClientSession() as s:
            async with s.get(
                f"http://127.0.0.1:{service.port}/admin/pools") as r:
                rows = (await r.json())["pools"]
        row = next(p for p in rows if p["model"] == "elastic-m")
        assert row["workers"] == 1 and row["requests_total"] == 2
    finally:
        await pools.stop()
        await service.stop()
        await watcher.stop()
        if state["serving"] is not None:
            await state["serving"].stop()


# --------------------------------------------------------------------------
# fleet hub: MODEL column
# --------------------------------------------------------------------------


async def test_hub_fleet_workers_shows_model_column():
    from dynamo_tpu.telemetry.hub import FleetHub
    from dynamo_tpu.telemetry.registry import MetricsRegistry

    reg = MetricsRegistry()
    reg.gauge("dynamo_registry_model_info", "model served").set(
        1.0, model="m8b")
    hub = FleetHub()
    hub.add_local("w1", "decode_engine", reg)
    await hub.scrape_once()
    rows = hub.fleet_workers()["workers"]
    assert rows[0]["model"] == "m8b"
    # dynamotop renders it
    import importlib
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    dynamotop = importlib.import_module("dynamotop")
    text = "\n".join(dynamotop.render_workers(rows))
    assert "MODEL" in text and "m8b" in text
    await hub.stop()


# --------------------------------------------------------------------------
# dynamoctl: the llmctl analogue over the admin API
# --------------------------------------------------------------------------


async def test_dynamoctl_drives_the_admin_api(capsys):
    import importlib
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    dynamoctl = importlib.import_module("dynamoctl")

    hub = MemoryHub()
    path = "dyn://prod.pool.generate"
    worker_drt = DistributedRuntime.in_process(hub)
    ep = worker_drt.namespace("prod").component("pool").endpoint("generate")
    serving = await ep.serve(_pool_handler("ctl-m"),
                             metadata={"model": "ctl-m"})
    front_drt, manager, watcher, service = await _two_pool_frontend(
        hub, None)
    service.registry_admin = RegistryAdmin(front_drt, "public")
    pools = PoolManager(manager.registry, watcher.pool_size)
    service.attach_pools(pools)
    base = ["--frontend", f"http://127.0.0.1:{service.port}"]

    def run(*argv):
        # urllib is sync — keep it off this loop
        return dynamoctl.main([*base, *argv])

    try:
        assert await asyncio.to_thread(
            run, "models", "add", "ctl-m", path,
            "--family", "llama", "--alias", "ctl-alias") == 0
        await asyncio.sleep(0.05)
        assert await asyncio.to_thread(run, "models", "list") == 0
        out = capsys.readouterr().out
        assert "ctl-m" in out and "ctl-alias" in out
        assert await asyncio.to_thread(run, "models", "catalog") == 0
        assert "family=llama" in capsys.readouterr().out
        # a request so the pool shows demand, then the pools view
        await _sse_body(service.port, "ctl-m", "via ctl")
        assert await asyncio.to_thread(run, "pools") == 0
        out = capsys.readouterr().out
        assert "ctl-m" in out and "workers=1" in out
        assert await asyncio.to_thread(run, "models", "remove",
                                       "ctl-m") == 0
        await asyncio.sleep(0.05)
        assert "ctl-m" not in manager.model_names()
        # malformed endpoint: server-side 400 → exit 1
        assert await asyncio.to_thread(
            run, "models", "add", "bad", "not-an-endpoint") == 1
    finally:
        await pools.stop()
        await service.stop()
        await watcher.stop()
        await serving.stop()


# --------------------------------------------------------------------------
# review-hardening regressions
# --------------------------------------------------------------------------


async def test_wrong_endpoint_kind_is_404_not_retryable_503():
    """A chat-only card must 404 on /v1/completions (the model does not
    exist for that API) — not a forever-retry 503."""
    manager = ModelManager()
    manager.add_chat_model("chat-only", _FixedEngine("c"))
    manager.set_card(ModelCard(name="chat-only", endpoint="dyn://a.b.c",
                               model_type="chat"))
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"http://127.0.0.1:{service.port}/v1/completions",
                json={"model": "chat-only", "prompt": "x"},
            ) as r:
                assert r.status == 404
                assert (await r.json())["error"]["code"] == "model_not_found"
            async with s.post(
                f"http://127.0.0.1:{service.port}/v1/chat/completions",
                json={"model": "chat-only",
                      "messages": [{"role": "user", "content": "x"}]},
            ) as r:
                assert r.status == 200
    finally:
        await service.stop()


async def test_tenant_visibility_works_without_quotas():
    """Tenant IDENTITY must parse on a quota-less frontend: a scoped
    model serves its tenant and hides from others even when no
    --tenant-* enforcement is configured."""
    manager = ModelManager()
    manager.add_chat_model("acme-ft", _FixedEngine("a"))
    manager.set_card(ModelCard(name="acme-ft", endpoint="dyn://a.b.c",
                               tenants=["acme"]))
    service = HttpService(manager, host="127.0.0.1", port=0)  # no quotas
    await service.start()
    try:
        async with aiohttp.ClientSession() as s:
            url = f"http://127.0.0.1:{service.port}"
            body = {"model": "acme-ft",
                    "messages": [{"role": "user", "content": "x"}]}
            async with s.post(f"{url}/v1/chat/completions", json=body,
                              headers={"X-Tenant": "acme"}) as r:
                assert r.status == 200
            async with s.post(f"{url}/v1/chat/completions",
                              json=body) as r:
                assert r.status == 404
            async with s.get(f"{url}/v1/models",
                             headers={"X-Tenant": "acme"}) as r:
                assert [m["id"] for m in (await r.json())["data"]] \
                    == ["acme-ft"]
            async with s.get(f"{url}/v1/models") as r:
                assert (await r.json())["data"] == []
    finally:
        await service.stop()


def test_pool_filter_does_not_inflate_draining_skips():
    """Structural pool exclusions are not drain events: multi-pool
    scheduling must leave the draining-skip counter untouched."""
    ks = KvScheduler(block_size=16)
    ks.update_metrics("w-a", ForwardPassMetrics(request_total_slots=4,
                                                kv_total_blocks=64))
    ks.update_metrics("w-b", ForwardPassMetrics(request_total_slots=4,
                                                kv_total_blocks=64))
    for _ in range(5):
        ks.schedule(64, OverlapScores(), pool={"w-a"})
    assert ks.draining_skips == 0
    # a REAL drain inside the pool still counts
    ks.update_metrics("w-c", ForwardPassMetrics(
        request_total_slots=4, kv_total_blocks=64, draining=True))
    ks.schedule(64, OverlapScores(), pool={"w-a", "w-c"})
    assert ks.draining_skips == 1


async def test_health_lists_scoped_models_and_admin_rejects_bad_body():
    """/health is the operator surface — visibility-blind; a non-object
    admin body is a 400, never a 500."""
    manager = ModelManager()
    manager.add_chat_model("acme-ft", _FixedEngine("a"))
    manager.set_card(ModelCard(name="acme-ft", endpoint="dyn://a.b.c",
                               tenants=["acme"]))
    service = HttpService(manager, host="127.0.0.1", port=0)
    front_drt = DistributedRuntime.in_process(MemoryHub())
    service.registry_admin = RegistryAdmin(front_drt, "public")
    await service.start()
    try:
        async with aiohttp.ClientSession() as s:
            url = f"http://127.0.0.1:{service.port}"
            async with s.get(f"{url}/health") as r:
                assert (await r.json())["models"] == ["acme-ft"]
            for bad in ([], "x", 7):
                async with s.post(f"{url}/admin/models", json=bad) as r:
                    assert r.status == 400, await r.text()
    finally:
        await service.stop()


async def test_cold_start_retries_a_failed_spawn_within_the_deadline():
    """One crashing spawn attempt must not burn every waiter's budget:
    the wait re-kicks (paced) and completes on the retry."""
    reg = ModelRegistry()
    reg.put(ModelCard(name="m", endpoint="dyn://a.b.c"))
    size = {"m": 0}
    attempts = []

    async def flaky_spawner(card):
        attempts.append(1)
        if len(attempts) == 1:
            raise RuntimeError("transient spawn failure")
        size["m"] = 1

    pm = PoolManager(reg, lambda m: size[m], spawner=flaky_spawner,
                     config=PoolConfig(cold_start_deadline_s=5.0,
                                       poll_s=0.01, retry_kick_s=0.05))
    await pm.await_capacity("m")
    assert len(attempts) == 2
    assert 'outcome="completed"} 1' in pm.registry.render()
    await pm.stop()


def test_note_request_ignores_cardless_models():
    """Card-less engines are not pool citizens — scale-to-zero must
    never synthesize pool services for them."""
    reg = ModelRegistry()
    pm = PoolManager(reg, lambda m: 0)
    pm.note_request("local-only")
    assert pm.snapshot() == []


def test_min_workers_floor_disables_scale_to_zero():
    """The only drain the policy emits is to-zero, so a nonzero floor
    must mean 'never drain' — not 'drain past the floor anyway'."""
    clock = Clock()
    policy = PoolPolicy(PoolPolicyConfig(idle_to_zero_s=60.0,
                                         min_workers=1), clock=clock)
    assert policy.decide({"m": PoolDemand(workers=2, idle_s=999.0)}) == []


async def test_alias_requests_reach_a_metadata_partitioned_pool():
    """An alias must canonicalize at the edge: downstream pool
    partitioning (worker metadata, processor routing) keys on the
    canonical name, which the alias string can never match."""
    hub = MemoryHub()
    path = "dyn://prod.pool.generate"
    worker_drt = DistributedRuntime.in_process(hub)
    ep = worker_drt.namespace("prod").component("pool").endpoint("generate")
    seen_models = []

    async def handler(payload, ctx):
        seen_models.append(payload.get("model"))
        async for chunk in _pool_handler("al-m")(payload, ctx):
            yield chunk

    serving = await ep.serve(handler, metadata={"model": "al-m"})
    front_drt, manager, watcher, service = await _two_pool_frontend(
        hub, None)
    service.registry_admin = RegistryAdmin(front_drt, "public")
    await service.registry_admin.add(ModelCard(
        name="al-m", endpoint=path, model_type="both",
        aliases=["al-alias"]))
    await asyncio.sleep(0.05)
    try:
        body = await _sse_body(service.port, "al-alias", "via alias")
        assert b"al-m:via alias" in body
        # the worker received the CANONICAL name, not the alias
        assert seen_models == ["al-m"]
    finally:
        await service.stop()
        await watcher.stop()
        await serving.stop()
