"""KV router: radix indexer, scheduler cost, routed end-to-end, recorder."""

import asyncio

import pytest

from dynamo_tpu.kv_router.indexer import KvIndexer, ShardedKvIndexer
from dynamo_tpu.kv_router.metrics_aggregator import KvMetricsAggregator
from dynamo_tpu.kv_router.protocols import (
    ForwardPassMetrics,
    KvCacheRemoved,
    KvCacheStored,
    RouterEvent,
)
from dynamo_tpu.kv_router.publisher import KvEventPublisher, KvMetricsPublisher
from dynamo_tpu.kv_router.recorder import KvRecorder, replay_events
from dynamo_tpu.kv_router.router import KvRouter
from dynamo_tpu.kv_router.scheduler import AllWorkersBusy, KvScheduler
from dynamo_tpu.llm.processor import KvRoutedClient
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions
from dynamo_tpu.runtime.client import Client
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.transports.memory import MemoryHub
from dynamo_tpu.tokens import compute_block_hashes


def stored(worker, hashes, parent=None):
    return RouterEvent(worker_id=worker, stored=KvCacheStored(hashes, parent))


def removed(worker, hashes):
    return RouterEvent(worker_id=worker, removed=KvCacheRemoved(hashes))


def test_radix_tree_match_and_remove():
    idx = KvIndexer(block_size=4)
    tokens = list(range(16))  # 4 blocks
    h = compute_block_hashes(tokens, 4)

    idx.apply_event(stored("w1", h))
    idx.apply_event(stored("w2", h[:2]))

    scores = idx.find_matches(h)
    assert scores.scores == {"w1": 4, "w2": 2}
    assert scores.frequencies == [2, 2, 1, 1]

    # divergent suffix only matches the shared prefix
    other = compute_block_hashes(list(range(8)) + [99] * 8, 4)
    scores2 = idx.find_matches(other)
    assert scores2.scores == {"w1": 2, "w2": 2}

    # removal of a middle block cuts the chain for that worker
    idx.apply_event(removed("w1", [h[1]]))
    scores3 = idx.find_matches(h)
    assert scores3.scores["w1"] == 1  # only block 0 still consecutive
    assert scores3.scores["w2"] == 2

    idx.remove_worker("w2")
    scores4 = idx.find_matches(h)
    assert "w2" not in scores4.scores


def test_radix_tree_orphan_parent():
    """Stored events whose parent is unknown still index standalone."""
    idx = KvIndexer(block_size=4)
    idx.apply_event(stored("w1", [111, 222], parent=999))  # 999 never stored
    # chain rooted at root: matching [111, 222] directly works
    scores = idx.find_matches([111, 222])
    assert scores.scores == {"w1": 2}


def test_sharded_indexer_merges():
    idx = ShardedKvIndexer(num_shards=3, block_size=4)
    tokens = list(range(12))
    h = compute_block_hashes(tokens, 4)
    for w in ("a", "b", "c", "d"):
        idx.apply_event(stored(w, h[:2] if w == "d" else h))
    scores = idx.find_matches(h)
    assert scores.scores["a"] == 3 and scores.scores["d"] == 2
    idx.remove_worker("a")
    assert "a" not in idx.find_matches(h).scores


def test_scheduler_cost_function():
    sched = KvScheduler(block_size=4)
    sched.update_metrics("idle", ForwardPassMetrics(
        request_active_slots=0, request_total_slots=8,
        kv_active_blocks=0, kv_total_blocks=100,
    ))
    sched.update_metrics("busy", ForwardPassMetrics(
        request_active_slots=8, request_total_slots=8,
        kv_active_blocks=90, kv_total_blocks=100,
    ))
    from dynamo_tpu.kv_router.indexer import OverlapScores

    # no overlap → idle worker wins on load
    d = sched.schedule(16, OverlapScores())
    assert d.worker_id == "idle"

    # busy worker with full prefix overlap beats idle (2*1.0 - 0.9 - 1.0 > 0)
    d2 = sched.schedule(16, OverlapScores(scores={"busy": 4}))
    assert d2.worker_id == "busy"
    assert d2.prefix_hit_tokens == 16

    # predicted-state: repeated no-overlap requests spread over the idle one
    # but bump its predicted load each time
    before = sched.workers["idle"].predicted_active
    sched.schedule(16, OverlapScores())
    assert sched.workers["idle"].predicted_active == before + 1


def test_scheduler_all_busy():
    sched = KvScheduler(block_size=4, require_free_slot=True)
    sched.update_metrics("w", ForwardPassMetrics(
        request_active_slots=8, request_total_slots=8, kv_total_blocks=10,
    ))
    from dynamo_tpu.kv_router.indexer import OverlapScores

    with pytest.raises(AllWorkersBusy):
        sched.schedule(4, OverlapScores())


@pytest.mark.asyncio
async def test_kv_router_end_to_end_over_hub(tmp_path):
    """Two token-level workers publish KV events + metrics; the router
    sends a request with a matching prefix to the right worker."""
    hub = MemoryHub()
    w1_drt = DistributedRuntime.in_process(hub)
    w2_drt = DistributedRuntime.in_process(hub)
    r_drt = DistributedRuntime.in_process(hub)

    served = {"w-one": 0, "w-two": 0}

    def make_worker(drt, instance_id):
        ep = drt.namespace("prod").component("backend").endpoint("generate")

        async def handler(payload, ctx):
            served[instance_id] += 1
            req = PreprocessedRequest.from_wire(payload)
            yield {"token_ids": [req.token_ids[0]], "finish_reason": "length"}

        metrics = ForwardPassMetrics(
            request_active_slots=0, request_total_slots=4,
            kv_active_blocks=10, kv_total_blocks=100,
        )
        return ep, handler, metrics

    ep1, h1, m1 = make_worker(w1_drt, "w-one")
    pub1 = KvEventPublisher(ep1.component, "w-one")
    pub1.start()
    s1 = await ep1.serve(
        h1, instance_id="w-one",
        stats_handler=KvMetricsPublisher(m1.to_wire).stats_handler,
    )
    ep2, h2, m2 = make_worker(w2_drt, "w-two")
    pub2 = KvEventPublisher(ep2.component, "w-two")
    pub2.start()
    s2 = await ep2.serve(
        h2, instance_id="w-two",
        stats_handler=KvMetricsPublisher(m2.to_wire).stats_handler,
    )

    # router side
    r_ep = r_drt.namespace("prod").component("backend").endpoint("generate")
    client = Client(r_ep)
    router = await KvRouter(r_ep.component, client, block_size=4, poll_interval=0.02).start()
    await client.wait_for_instances(2)

    # w-two advertises the prefix of our request
    prompt = list(range(100, 116))
    hashes = compute_block_hashes(prompt, 4)
    pub2.publish_stored(hashes, None)
    await asyncio.sleep(0.05)  # event + metrics propagation
    assert router.indexer.find_matches(hashes).scores == {"w-two": 4}

    routed = KvRoutedClient(client, router)
    req = PreprocessedRequest(token_ids=prompt, stop_conditions=StopConditions(max_tokens=1))
    outs = [o async for o in routed.generate(Context(req))]
    assert outs and served["w-two"] == 1 and served["w-one"] == 0

    # worker death → index purged via aggregator on_remove
    await s2.stop()
    hub.expire_lease((await w2_drt.discovery.primary_lease()).id)
    await asyncio.sleep(0.1)
    assert "w-two" not in router.indexer.find_matches(hashes).scores

    await router.stop()
    await s1.stop()
    for d in (w1_drt, w2_drt, r_drt):
        await d.close()


@pytest.mark.asyncio
async def test_recorder_and_replay(tmp_path):
    hub = MemoryHub()
    drt = DistributedRuntime.in_process(hub)
    comp = drt.namespace("p").component("c")
    path = str(tmp_path / "events.jsonl")

    rec = await KvRecorder(comp, path).start()
    pub = KvEventPublisher(comp, "w9")
    pub.start()
    tokens = list(range(8))
    h = compute_block_hashes(tokens, 4)
    pub.publish_stored(h, None)
    pub.publish_removed([h[1]])
    await asyncio.sleep(0.05)
    await rec.stop()
    assert rec.count == 2

    idx = KvIndexer(block_size=4)
    n = replay_events(path, idx)
    assert n == 2
    assert idx.find_matches(h).scores == {"w9": 1}
    await drt.close()


# ---------- staleness-aware cost function ----------


def test_scheduler_skips_stale_workers():
    """A worker whose scrape stopped keeps its last (usually flattering)
    snapshot forever; with a staleness bound the cost function stops
    trusting it and routes to fresh workers even at worse load."""
    from dynamo_tpu.kv_router.indexer import OverlapScores

    t = {"now": 0.0}
    sched = KvScheduler(block_size=4, staleness_bound_s=2.0,
                        clock=lambda: t["now"])
    # the stale worker LOOKS idle; the fresh one looks loaded
    sched.update_metrics("wedged", ForwardPassMetrics(
        request_active_slots=0, request_total_slots=8,
        kv_active_blocks=0, kv_total_blocks=100,
    ))
    sched.update_metrics("alive", ForwardPassMetrics(
        request_active_slots=6, request_total_slots=8,
        kv_active_blocks=50, kv_total_blocks=100,
    ))
    # both fresh: the idle-looking one wins on load
    assert sched.schedule(16, OverlapScores()).worker_id == "wedged"

    # only "alive" keeps scraping; "wedged" ages past the bound
    t["now"] = 5.0
    sched.update_metrics("alive", ForwardPassMetrics(
        request_active_slots=6, request_total_slots=8,
        kv_active_blocks=50, kv_total_blocks=100,
    ))
    d = sched.schedule(16, OverlapScores())
    assert d.worker_id == "alive"
    assert sched.stale_skips == 1


def test_scheduler_all_stale_falls_back_to_routing():
    """Every snapshot stale (scrape loop hiccup) → route on old data
    rather than refusing every request."""
    from dynamo_tpu.kv_router.indexer import OverlapScores

    t = {"now": 0.0}
    sched = KvScheduler(block_size=4, staleness_bound_s=1.0,
                        clock=lambda: t["now"])
    sched.update_metrics("w1", ForwardPassMetrics(
        request_active_slots=0, request_total_slots=8, kv_total_blocks=10,
    ))
    t["now"] = 60.0
    d = sched.schedule(4, OverlapScores())
    assert d.worker_id == "w1"
    assert sched.stale_skips == 0  # fallback is not a skip


def test_scheduler_without_bound_trusts_forever():
    from dynamo_tpu.kv_router.indexer import OverlapScores

    t = {"now": 0.0}
    sched = KvScheduler(block_size=4, clock=lambda: t["now"])
    sched.update_metrics("w1", ForwardPassMetrics(
        request_active_slots=0, request_total_slots=8, kv_total_blocks=10,
    ))
    t["now"] = 1e6
    assert sched.schedule(4, OverlapScores()).worker_id == "w1"
    assert sched.stale_skips == 0
