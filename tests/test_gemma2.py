"""Gemma-2 family: logit parity vs HF transformers, sliding-window
semantics, and end-to-end serving."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.models import gemma2, resolve
from dynamo_tpu.models.loader import load_checkpoint_params

from fixtures import make_model_dir

TINY = dict(
    vocab_size=512,
    hidden_size=64,
    intermediate_size=128,
    num_hidden_layers=4,       # two sliding + two full layers
    num_attention_heads=4,
    num_key_value_heads=2,
    head_dim=16,
    max_position_embeddings=256,
    rms_norm_eps=1e-6,
    rope_theta=10000.0,
    query_pre_attn_scalar=16,
    sliding_window=4,          # small enough to bite inside the test prompt
    attn_logit_softcapping=50.0,
    final_logit_softcapping=30.0,
)

PROMPT = [2, 17, 43, 99, 7, 3, 250, 12, 5, 77, 140, 9]


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    import torch
    from transformers import Gemma2Config, Gemma2ForCausalLM

    d = make_model_dir(tmp_path_factory.mktemp("g2model"), name="tiny-gemma2")
    cfg = Gemma2Config(**TINY)
    torch.manual_seed(0)
    Gemma2ForCausalLM(cfg).save_pretrained(d, safe_serialization=True)
    with open(os.path.join(d, "config.json")) as f:
        c = json.load(f)
    c["eos_token_id"] = 1
    c["bos_token_id"] = 2
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump(c, f)
    return d


@pytest.fixture(scope="module")
def hf_out(model_dir):
    import torch
    from transformers import Gemma2ForCausalLM

    model = Gemma2ForCausalLM.from_pretrained(
        model_dir, torch_dtype=torch.float32, attn_implementation="eager"
    )
    model.eval()
    with torch.no_grad():
        logits = model(torch.tensor([PROMPT])).logits[0].numpy()
        gen = model.generate(
            torch.tensor([PROMPT]), max_new_tokens=10, do_sample=False,
        )[0][len(PROMPT):].tolist()
    return logits, gen


def test_resolve_picks_gemma2(model_dir):
    cfg = ModelConfig.from_model_dir(model_dir)
    assert cfg.model_family == "gemma2"
    assert cfg.sliding_window == 4 and cfg.attn_logit_softcap == 50.0
    assert resolve(cfg) is gemma2


def test_gemma2_prefill_logits_match_hf(model_dir, hf_out):
    """Full-sequence prefill logits vs HF fp32 — softcaps, sandwich
    norms, and the even-layer sliding window all in play (the prompt is
    3x the window)."""
    hf_logits, _ = hf_out
    cfg = ModelConfig.from_model_dir(model_dir)
    cfg.attention_impl = "xla"
    params = load_checkpoint_params(model_dir, cfg, gemma2, jnp.float32)
    s = len(PROMPT)
    k, v = gemma2.init_kv_cache(cfg, 16, 8, jnp.float32)
    tokens = jnp.asarray([PROMPT], jnp.int32)
    positions = jnp.arange(s, dtype=jnp.int32)[None]
    bt = jnp.arange(4, dtype=jnp.int32)[None]
    slots = positions
    logits, _ = gemma2.forward(
        params, cfg, tokens, positions, (k, v), bt, slots,
        jnp.asarray([s], jnp.int32),
    )
    np.testing.assert_allclose(
        np.asarray(logits[0]), hf_logits, rtol=2e-4, atol=2e-4
    )


@pytest.mark.asyncio
async def test_gemma2_engine_greedy_matches_hf_generate(model_dir, hf_out):
    from dynamo_tpu.engine.serving import JaxServingEngine
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    _, hf_gen = hf_out
    mdc = ModelDeploymentCard.from_local_path(model_dir)
    mcfg = ModelConfig.from_model_dir(model_dir)
    mcfg.attention_impl = "xla"
    econfig = EngineConfig(
        model=mcfg, max_batch_size=2, max_model_len=64, kv_block_size=8,
        num_kv_blocks=32, dtype="float32",
    )
    engine = await JaxServingEngine.create(
        mdc, engine_config=econfig, warmup=False)
    req = PreprocessedRequest(
        token_ids=PROMPT,
        stop_conditions=StopConditions(max_tokens=10, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0),
    )
    toks = []
    async for out in engine.generate(Context(req)):
        toks.extend(out["token_ids"])
    await engine.close()
    assert toks == hf_gen


@pytest.mark.asyncio
async def test_gemma2_multi_step_burst_bit_equal(model_dir):
    """The fused decode burst composes with gemma2's distinct logit tail
    (softcap inside logits_from_hidden): streams identical at K=1/K=4."""
    from dynamo_tpu.engine.serving import JaxServingEngine
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    mdc = ModelDeploymentCard.from_local_path(model_dir)

    async def serve(k):
        mcfg = ModelConfig.from_model_dir(model_dir)
        mcfg.attention_impl = "xla"
        engine = await JaxServingEngine.create(
            mdc, engine_config=EngineConfig(
                model=mcfg, max_batch_size=2, max_model_len=64,
                kv_block_size=8, num_kv_blocks=32, dtype="float32",
                multi_step_decode=k,
            ), warmup=False)
        req = PreprocessedRequest(
            token_ids=PROMPT,
            stop_conditions=StopConditions(max_tokens=12, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.8, seed=3),
        )
        toks = []
        async for out in engine.generate(Context(req)):
            toks.extend(out["token_ids"])
        await engine.close()
        return toks

    assert await serve(1) == await serve(4)


def test_sliding_window_actually_masks(model_dir):
    """With the window forced tiny, positions beyond it must stop
    influencing the next-token logits on sliding layers: perturbing an
    early token changes full-attention output but a one-layer
    sliding-only model's decode distribution stays put."""
    cfg = ModelConfig.from_model_dir(model_dir)
    cfg.attention_impl = "xla"
    params = load_checkpoint_params(model_dir, cfg, gemma2, jnp.float32)

    def last_logits(prompt, sliding):
        c = ModelConfig.from_model_dir(model_dir)
        c.attention_impl = "xla"
        c.sliding_window = sliding
        k, v = gemma2.init_kv_cache(c, 16, 8, jnp.float32)
        s = len(prompt)
        logits, _ = gemma2.forward(
            params, c, jnp.asarray([prompt], jnp.int32),
            jnp.arange(s, dtype=jnp.int32)[None], (k, v),
            jnp.arange(4, dtype=jnp.int32)[None],
            jnp.arange(s, dtype=jnp.int32)[None],
            jnp.asarray([s], jnp.int32),
        )
        return np.asarray(logits[0, -1])

    base = PROMPT
    perturbed = [base[0], 499] + base[2:]  # flip a token far outside win=2
    # full attention: the early token matters
    assert not np.allclose(last_logits(base, 0), last_logits(perturbed, 0))
    # full layers still see the early token, so the 4-layer model reacts
    # regardless — but a model with ONLY layer 0 (sliding, window 2) must
    # find it invisible from the last position
    sl_params = {
        "embed": params["embed"],
        "final_norm": params["final_norm"],
        "layers": jax.tree.map(lambda x: x[0:1], params["layers"]),
    }
    c2 = ModelConfig.from_model_dir(model_dir)
    c2.attention_impl = "xla"
    c2.num_layers = 1
    c2.sliding_window = 2

    def only_sliding(prompt):
        s = len(prompt)
        kk, vv = gemma2.init_kv_cache(c2, 16, 8, jnp.float32)
        logits, _ = gemma2.forward(
            sl_params, c2, jnp.asarray([prompt], jnp.int32),
            jnp.arange(s, dtype=jnp.int32)[None], (kk, vv),
            jnp.arange(4, dtype=jnp.int32)[None],
            jnp.arange(s, dtype=jnp.int32)[None],
            jnp.asarray([s], jnp.int32),
        )
        return np.asarray(logits[0, -1])

    np.testing.assert_allclose(
        only_sliding(base), only_sliding(perturbed), rtol=1e-5, atol=1e-5
    )

def test_gemma2_pallas_kernels_match_xla(model_dir, monkeypatch):
    """The windowed+softcapped Pallas kernels serve Gemma-2's full forward
    (traced per-layer window inside the scan) — parity vs the XLA path for
    prefill AND a decode step. DYN_PALLAS_INTERPRET drives the kernels in
    interpret mode through the jitted model forward on CPU."""
    monkeypatch.setenv("DYN_PALLAS_INTERPRET", "1")
    cfg_x = ModelConfig.from_model_dir(model_dir)
    cfg_x.attention_impl = "xla"
    cfg_p = ModelConfig.from_model_dir(model_dir)
    cfg_p.attention_impl = "pallas"
    params = load_checkpoint_params(model_dir, cfg_x, gemma2, jnp.float32)

    s = len(PROMPT)
    tokens = jnp.asarray([PROMPT], jnp.int32)
    positions = jnp.arange(s, dtype=jnp.int32)[None]
    bt = jnp.arange(4, dtype=jnp.int32)[None]
    ctx = jnp.asarray([s], jnp.int32)

    outs = {}
    for name, cfg in (("xla", cfg_x), ("pallas", cfg_p)):
        k, v = gemma2.init_kv_cache(cfg, 16, 8, jnp.float32)
        logits, (k, v) = gemma2.forward(
            params, cfg, tokens, positions, (k, v), bt, positions, ctx
        )
        # one decode step on the warm cache
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)[:, None]
        dpos = jnp.asarray([[s]], jnp.int32)
        dslot = jnp.asarray([[s]], jnp.int32)
        dlogits, _ = gemma2.forward(
            params, cfg, nxt, dpos, (k, v), bt, dslot,
            jnp.asarray([s + 1], jnp.int32),
        )
        outs[name] = (np.asarray(logits), np.asarray(dlogits))

    np.testing.assert_allclose(
        outs["pallas"][0], outs["xla"][0], rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        outs["pallas"][1], outs["xla"][1], rtol=2e-4, atol=2e-4
    )
