"""llmctl registry management + the namespace metrics aggregator."""

import asyncio

import pytest

from dynamo_tpu.cli.llmctl import KINDS, build_parser, run as llmctl_run
from dynamo_tpu.cli.metrics import MetricsAggregator
from dynamo_tpu.http.service import list_models
from dynamo_tpu.kv_router.protocols import KV_HIT_RATE_EVENT, ForwardPassMetrics
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.transports.memory import MemoryHub


def _drt():
    return DistributedRuntime.in_process(MemoryHub())


class TestLlmctl:
    def _args(self, *argv):
        return build_parser().parse_args(["--store-port", "1", *argv])

    async def test_add_list_remove(self, capsys):
        drt = _drt()
        try:
            rc = await llmctl_run(
                self._args("http", "add", "chat-models", "m8b",
                           "dyn://public.backend.generate"), drt)
            assert rc == 0
            rc = await llmctl_run(
                self._args("http", "add", "completion-models", "c1",
                           "dyn://public.backend.generate"), drt)
            assert rc == 0

            models = await list_models(drt, "public")
            by_name = {m["name"]: m for m in models}
            assert by_name["m8b"]["model_type"] == "chat"
            assert by_name["c1"]["model_type"] == "completions"

            rc = await llmctl_run(self._args("http", "list"), drt)
            assert rc == 0
            out = capsys.readouterr().out
            assert "m8b" in out and "dyn://public.backend.generate" in out

            rc = await llmctl_run(
                self._args("http", "remove", "chat-models", "m8b"), drt)
            assert rc == 0
            models = await list_models(drt, "public")
            assert [m["name"] for m in models] == ["c1"]
        finally:
            await drt.close()

    async def test_add_rejects_bad_endpoint(self):
        drt = _drt()
        try:
            rc = await llmctl_run(
                self._args("http", "add", "models", "x", "http://nope"), drt)
            assert rc == 2
            # structurally short dyn:// paths must fail too (the frontend's
            # watcher parses strictly)
            rc = await llmctl_run(
                self._args("http", "add", "models", "x", "dyn://ns.comp"), drt)
            assert rc == 2
            assert await list_models(drt, "public") == []
        finally:
            await drt.close()

    def test_kind_mapping(self):
        assert KINDS == {
            "chat-models": "chat",
            "completion-models": "completions",
            "models": "both",
        }


async def test_metrics_aggregator_scrape_and_events():
    drt = _drt()
    try:
        # a worker endpoint with a ForwardPassMetrics stats handler
        fpm = ForwardPassMetrics(
            request_active_slots=3, request_total_slots=8,
            kv_active_blocks=100, kv_total_blocks=256,
            gpu_cache_usage_perc=0.39,
        )

        async def handler(payload, ctx):
            yield {"ok": True}

        comp = drt.namespace("public").component("backend")
        serving = await comp.endpoint("generate").serve(
            handler, stats_handler=fpm.to_wire
        )

        agg = MetricsAggregator(drt, "dyn://public.backend.generate")
        await agg.start()
        try:
            # scrape pass picks up the worker's stats
            for _ in range(20):
                if await agg.collect_once() > 0:
                    break
                await asyncio.sleep(0.05)
            text = agg.render()
            assert "dynamo_worker_request_active_slots" in text
            assert "3.0" in text
            assert "dynamo_worker_kv_total_blocks" in text

            # kv-hit-rate events land in counters
            await drt.namespace("public").publish_event(
                KV_HIT_RATE_EVENT,
                {"worker_id": "w1", "isl_blocks": 10, "overlap_blocks": 7},
            )
            await asyncio.sleep(0.1)
            text = agg.render()
            assert 'dynamo_kv_hit_rate_events_total{worker="w1"} 1.0' in text
            assert 'dynamo_kv_hit_overlap_blocks_total{worker="w1"} 7.0' in text

            # dead instances stop exporting: after the worker goes away,
            # its gauge series are pruned on the next scrape
            await serving.stop()
            await asyncio.sleep(0.05)
            assert await agg.collect_once() == 0
            assert "request_active_slots{instance=" not in agg.render()
        finally:
            agg.stop()
            await serving.stop()
    finally:
        await drt.close()
