"""The REST KubeClient against a real-shaped fake API server.

VERDICT r3 weak #6: the operator had only ever reconciled through a
kubectl shell-out or the InMemoryKube logic double — API-server
behaviors (server-side apply upsert, labelSelector lists, the status
subresource ignoring spec edits, watch streams, 404/409 codes) were
untested. The fake here implements those behaviors at the HTTP layer,
and the REAL Reconciler + watch_loop drive the REAL KubeApiClient
against it.
"""

import asyncio
import contextlib
import json
import threading

import pytest
from aiohttp import web

from dynamo_tpu.deploy.kube_api import KubeApiClient, KubeApiError
from dynamo_tpu.deploy.operator import (
    GROUP,
    PLURAL,
    Reconciler,
    VERSION,
)

CR_BASE = f"/apis/{GROUP}/{VERSION}"


class FakeKubeApiServer:
    """Enough of the Kubernetes REST surface, with real semantics:
    SSA patch upserts (and bumps resourceVersion), list honors
    labelSelector, /status merge-patch IGNORES non-status fields,
    DELETE of a missing object is 404, watch streams JSON lines."""

    def __init__(self):
        self.objects = {}  # (plural, ns, name) → object dict
        self.crs = {}      # (ns, name) → CR dict
        self.rv = 0
        self.watch_queues = []
        self.requests = []  # (method, path, query) log for assertions
        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self.handle)
        self.app = app
        self.port = None
        self._runner = None

    async def start(self):
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        self.port = self._runner.addresses[0][1]

    async def stop(self):
        await self._runner.cleanup()

    def put_cr(self, name, spec, namespace="default", generation=1):
        self.rv += 1
        cr = {
            "apiVersion": f"{GROUP}/{VERSION}",
            "kind": "DynamoTpuGraphDeployment",
            "metadata": {"name": name, "namespace": namespace,
                         "generation": generation,
                         "resourceVersion": str(self.rv), "uid": f"uid-{name}"},
            "spec": spec,
        }
        self.crs[(namespace, name)] = cr
        self._emit({"type": "MODIFIED" if generation > 1 else "ADDED",
                    "object": cr})
        return cr

    def delete_cr(self, name, namespace="default"):
        cr = self.crs.pop((namespace, name), None)
        if cr:
            self._emit({"type": "DELETED", "object": cr})

    def _emit(self, event):
        for q in self.watch_queues:
            q.put_nowait(event)

    async def handle(self, request: web.Request):
        path = "/" + request.match_info["tail"]
        self.requests.append((request.method, path, dict(request.query)))
        self.auth_headers = getattr(self, "auth_headers", [])
        self.auth_headers.append(request.headers.get("Authorization"))
        parts = [p for p in path.split("/") if p]

        # ---- CR endpoints ----
        if path.startswith(CR_BASE):
            return await self._handle_cr(request, path, parts)

        # ---- coordination.k8s.io Leases (real CAS semantics) ----
        if path.startswith("/apis/coordination.k8s.io/v1"):
            return await self._handle_lease(request, parts)

        # ---- children: /apis/apps/v1/... or /api/v1/... ----
        ns_i = parts.index("namespaces")
        ns, plural = parts[ns_i + 1], parts[ns_i + 2]
        name = parts[ns_i + 3] if len(parts) > ns_i + 3 else None
        key = (plural, ns, name)

        if request.method == "PATCH":
            if request.content_type != "application/apply-patch+yaml":
                return web.json_response(
                    {"reason": "UnsupportedMediaType"}, status=415)
            if request.query.get("force") != "true":
                # a competing fieldManager owns these objects; real SSA
                # controllers must force — surface the conflict
                return web.json_response({"reason": "Conflict"}, status=409)
            body = json.loads(await request.text())
            self.rv += 1
            body.setdefault("metadata", {})["resourceVersion"] = str(self.rv)
            self.objects[key] = body
            return web.json_response(body)

        if request.method == "DELETE":
            if key not in self.objects:
                return web.json_response({"reason": "NotFound"}, status=404)
            del self.objects[key]
            return web.json_response({"status": "Success"})

        if request.method == "GET" and name is None:
            sel = request.query.get("labelSelector", "")
            wanted = dict(
                part.split("=", 1) for part in sel.split(",") if "=" in part
            )
            items = []
            for (pl, ons, _n), obj in self.objects.items():
                if pl != plural or ons != ns:
                    continue
                labels = (obj.get("metadata") or {}).get("labels") or {}
                if all(labels.get(k) == v for k, v in wanted.items()):
                    # real list responses strip per-item kind/apiVersion
                    slim = {k: v for k, v in obj.items()
                            if k not in ("kind", "apiVersion")}
                    items.append(slim)
            return web.json_response({"items": items})

        return web.json_response({"reason": "NotFound"}, status=404)

    async def _handle_lease(self, request, parts):
        self.leases = getattr(self, "leases", {})  # (ns, name) → (obj, rv)
        ns = parts[parts.index("namespaces") + 1]
        name = parts[-1] if parts[-1] != "leases" else None
        if request.method == "GET":
            entry = self.leases.get((ns, name))
            if entry is None:
                return web.json_response({"reason": "NotFound"}, status=404)
            return web.json_response(entry[0])
        if request.method == "POST":
            body = json.loads(await request.text())
            key = (ns, body["metadata"]["name"])
            if key in self.leases:
                return web.json_response(
                    {"reason": "AlreadyExists"}, status=409)
            self.rv += 1
            body["metadata"]["resourceVersion"] = str(self.rv)
            self.leases[key] = (body, str(self.rv))
            return web.json_response(body, status=201)
        if request.method == "PUT":
            body = json.loads(await request.text())
            entry = self.leases.get((ns, name))
            if entry is None:
                return web.json_response({"reason": "NotFound"}, status=404)
            if body["metadata"].get("resourceVersion") != entry[1]:
                return web.json_response({"reason": "Conflict"}, status=409)
            self.rv += 1
            body["metadata"]["resourceVersion"] = str(self.rv)
            self.leases[(ns, name)] = (body, str(self.rv))
            return web.json_response(body)
        return web.json_response({"reason": "MethodNotAllowed"}, status=405)

    async def _handle_cr(self, request, path, parts):
        if path.endswith("/status") and request.method == "PATCH":
            ns, name = parts[-4], parts[-2]
            cr = self.crs.get((ns, name))
            if cr is None:
                return web.json_response({"reason": "NotFound"}, status=404)
            if request.content_type != "application/merge-patch+json":
                return web.json_response(
                    {"reason": "UnsupportedMediaType"}, status=415)
            body = json.loads(await request.text())
            # the subresource contract: ONLY status is applied; spec/
            # metadata edits smuggled into the body are ignored
            cr["status"] = body.get("status", cr.get("status"))
            self.rv += 1
            cr["metadata"]["resourceVersion"] = str(self.rv)
            return web.json_response(cr)

        if request.method == "GET" and parts[-1] == PLURAL:
            if request.query.get("watch") == "1":
                resp = web.StreamResponse()
                await resp.prepare(request)
                q: asyncio.Queue = asyncio.Queue()
                self.watch_queues.append(q)
                try:
                    while True:
                        event = await q.get()
                        if event is None:
                            break
                        await resp.write(
                            (json.dumps(event) + "\n").encode())
                finally:
                    self.watch_queues.remove(q)
                return resp
            items = []
            for cr in self.crs.values():
                slim = {k: v for k, v in cr.items()
                        if k not in ("kind", "apiVersion")}
                items.append(slim)
            return web.json_response({"items": items})

        return web.json_response({"reason": "NotFound"}, status=404)


@contextlib.asynccontextmanager
async def fake_server():
    # the harness has no async-fixture support (conftest runs coroutine
    # TESTS in a fresh loop); the server must live inside that same loop
    server = FakeKubeApiServer()
    await server.start()
    try:
        yield server
    finally:
        await server.stop()


def client_for(server) -> KubeApiClient:
    return KubeApiClient(f"http://127.0.0.1:{server.port}")


async def _in_thread(fn, *args):
    return await asyncio.get_running_loop().run_in_executor(
        None, fn, *args)


async def test_reconcile_e2e_over_rest():
    """The real Reconciler drives the real REST client: children are
    server-side applied, orphans pruned via labelSelector lists, and
    the status subresource carries the condition + artifact version."""
    async with fake_server() as fake:
        client = client_for(fake)
        cr = fake.put_cr("g1", {
            "services": {"worker": {"role": "worker", "tpus": 4}},
            "modelName": "tiny",
            "artifact": {"name": "agg", "version": "abc123def456"},
        })
        rec = Reconciler(client)

        await _in_thread(rec.reconcile, cr)
        deployments = [k for k in fake.objects if k[0] == "deployments"]
        services = [k for k in fake.objects if k[0] == "services"]
        assert len(deployments) == 3 and len(services) == 2
        status = fake.crs[("default", "g1")]["status"]
        assert status["conditions"][0]["status"] == "True"
        assert status["artifactVersion"] == "abc123def456"

        # shrink the spec → the orphan is pruned over REST
        cr2 = fake.put_cr("g1", {"services": {}}, generation=2)
        await _in_thread(rec.reconcile, cr2)
        deployments = [k for k in fake.objects if k[0] == "deployments"]
        assert len(deployments) == 2  # dynstore + frontend defaults remain
        assert not any(n == "g1-worker" for (_p, _ns, n) in fake.objects)


async def test_apply_is_server_side_apply_with_force():
    async with fake_server() as fake:
        client = client_for(fake)
        manifest = {
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": "d1", "namespace": "default",
                         "labels": {"a": "b"}},
            "spec": {"replicas": 1},
        }
        await _in_thread(client.apply, manifest)
        await _in_thread(client.apply, manifest)  # idempotent upsert
        method, path, query = fake.requests[-1]
        assert method == "PATCH" and path.endswith("/deployments/d1")
        assert query["fieldManager"] == "dynamo-tpu-operator"
        assert query["force"] == "true"
        assert ("deployments", "default", "d1") in fake.objects


async def test_delete_ignores_not_found_but_raises_other_errors():
    async with fake_server() as fake:
        client = client_for(fake)
        await _in_thread(client.delete, "Deployment", "default", "ghost")
        with pytest.raises(KubeApiError):
            # unknown child kind → client-side KeyError is wrapped? no:
            # an unroutable namespace-less path gives a server 404 for
            # SERVICES only when absent; use status-subresource on a missing
            # CR as the non-ignorable error instead
            await _in_thread(
                client.update_status,
                {"metadata": {"name": "ghost", "namespace": "default"}},
                {"x": 1},
            )


async def test_status_subresource_ignores_spec_edits():
    async with fake_server() as fake:
        client = client_for(fake)
        fake.put_cr("g2", {"services": {}})
        # a buggy writer smuggling spec into the status patch must not
        # mutate the spec (the subresource contract)
        await _in_thread(
            client.update_status,
            {"metadata": {"name": "g2", "namespace": "default"},
             "spec": {"services": {"evil": {}}}},
            {"conditions": [{"type": "Reconciled", "status": "True"}]},
        )
        cr = fake.crs[("default", "g2")]
        assert cr["spec"] == {"services": {}}
        assert cr["status"]["conditions"][0]["status"] == "True"


async def test_get_crs_restores_kind_and_none_on_dead_api():
    async with fake_server() as fake:
        client = client_for(fake)
        fake.put_cr("g3", {"services": {}})
        crs = await _in_thread(client.get_crs)
        assert crs[0]["kind"] == "DynamoTpuGraphDeployment"
        assert crs[0]["apiVersion"] == f"{GROUP}/{VERSION}"
        dead = KubeApiClient("http://127.0.0.1:1", timeout=0.3)
        assert await _in_thread(dead.get_crs) is None


async def test_lease_cas_over_rest_single_winner():
    """KubeApiLeases: create-only POST and resourceVersion'd PUT give
    real CAS — two electors racing produce exactly one leader, and a
    stale-version renewal is an authoritative loss, not an error."""
    from dynamo_tpu.deploy.kube_api import KubeApiLeases
    from dynamo_tpu.deploy.leader import LeaderElector

    async with fake_server() as fake:
        client = client_for(fake)
        leases = KubeApiLeases(client)

        def cas_round():
            electors = [
                LeaderElector(leases, f"e{i}", namespace="default")
                for i in range(4)
            ]
            return [e.try_acquire_or_renew() for e in electors]

        wins = await _in_thread(cas_round)
        assert sum(wins) == 1

        # stale-version write: read, let someone else write, then CAS
        spec, version = await _in_thread(
            lambda: leases.read("default", "dynamo-tpu-operator"))
        assert spec is not None
        ok = await _in_thread(
            lambda: leases.write(
                "default", "dynamo-tpu-operator",
                {**spec, "holderIdentity": "usurper"}, version))
        assert ok  # first CAS with the fresh version wins
        stale = await _in_thread(
            lambda: leases.write(
                "default", "dynamo-tpu-operator",
                {**spec, "holderIdentity": "stale"}, version))
        assert stale is False  # lost race → False, never an exception


async def test_token_file_is_reread_per_request(tmp_path):
    """Bound serviceaccount tokens rotate on disk (~1h); caching the
    startup token would 401 forever after expiry."""
    async with fake_server() as fake:
        tok = tmp_path / "token"
        tok.write_text("tok-1")
        client = KubeApiClient(
            f"http://127.0.0.1:{fake.port}", token_file=str(tok)
        )
        await _in_thread(client.get_crs)
        tok.write_text("tok-2")  # kubelet rotated the projected token
        await _in_thread(client.get_crs)
        assert fake.auth_headers[-2:] == ["Bearer tok-1", "Bearer tok-2"]


def test_from_in_cluster_off_cluster_is_a_clear_error(monkeypatch):
    monkeypatch.delenv("KUBERNETES_SERVICE_HOST", raising=False)
    with pytest.raises(RuntimeError, match="kube-api-url"):
        KubeApiClient.from_in_cluster()


async def test_watch_loop_over_rest_stream():
    """deploy/watch.py watch_loop consuming the client's open_watch:
    an ADDED event reconciles; a DELETED event finalizes."""
    async with fake_server() as fake:
        from dynamo_tpu.deploy.watch import watch_loop

        client = client_for(fake)
        rec = Reconciler(client)
        stop = threading.Event()

        loop_thread = threading.Thread(
            target=watch_loop,
            args=(rec, client.get_crs, client.open_watch, stop),
            kwargs={"reconnect_backoff_s": 0.1},
            daemon=True,
        )
        loop_thread.start()
        try:
            # wait for the stream to actually register (a fixed sleep
            # races the relist on a loaded host; a missed ADDED event
            # could not be recovered inside the poll window below)
            for _ in range(200):
                if fake.watch_queues:
                    break
                await asyncio.sleep(0.05)
            else:
                raise AssertionError("watch stream never connected")
            fake.put_cr("w1", {"services": {"worker": {"role": "worker"}}})
            for _ in range(100):
                if ("deployments", "default", "w1-worker") in fake.objects:
                    break
                await asyncio.sleep(0.05)
            else:
                raise AssertionError("watch event did not reconcile w1")
            # the status patch lands AFTER the deployment create — poll for
            # it too, or a loaded host hits the gap (KeyError: 'status')
            for _ in range(100):
                if fake.crs.get(("default", "w1"), {}).get(
                        "status", {}).get("conditions"):
                    break
                await asyncio.sleep(0.05)
            else:
                raise AssertionError("status conditions never patched")

            fake.delete_cr("w1")
            for _ in range(100):
                if not any(ns == "default" and n and n.startswith("w1-")
                           for (_p, ns, n) in fake.objects):
                    break
                await asyncio.sleep(0.05)
            else:
                raise AssertionError("DELETED event did not finalize w1")
        finally:
            stop.set()
            for q in list(fake.watch_queues):
                q.put_nowait(None)  # unblock the stream
            await asyncio.sleep(0.05)
