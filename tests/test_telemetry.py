"""Telemetry correctness: exposition text validity, the unified registry,
and per-request trace spans (scheduler-stamped stages end to end)."""

import asyncio
import json
import math

import pytest

from dynamo_tpu.telemetry.exposition import (
    histogram_series,
    parse_exposition,
)
from dynamo_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    escape_label_value,
)
from dynamo_tpu.telemetry.tracing import TraceRecorder, span_breakdown

# ---------------------------------------------------------------- exposition


def test_escaped_labels_round_trip():
    """Backslashes, quotes, and newlines in label values (model names,
    error strings) must survive render → parse unchanged."""
    nasty = 'models\\v1"prod"\nllama'
    c = Counter("dynamo_test_requests_total", "help")
    c.inc(3, model=nasty, status="ok")
    families = parse_exposition("\n".join(c.render()) + "\n")
    fam = families["dynamo_test_requests_total"]
    assert fam.type == "counter"
    (sample,) = fam.samples
    assert sample.labels["model"] == nasty
    assert sample.labels["status"] == "ok"
    assert sample.value == 3.0


def test_escape_label_value_idempotent_inputs():
    assert escape_label_value("plain") == "plain"
    assert escape_label_value('a"b') == 'a\\"b'
    assert escape_label_value("a\\b") == "a\\\\b"
    assert escape_label_value("a\nb") == "a\\nb"


def test_help_text_escaped():
    c = Counter("dynamo_test_total", "line one\nline two")
    text = "\n".join(c.render())
    # a raw newline in HELP would truncate the comment mid-line and leave
    # an unparseable "line two" sample line
    assert "# HELP dynamo_test_total line one\\nline two" in text


def test_histogram_buckets_monotone_and_inf_equals_count():
    h = Histogram("dynamo_test_duration_seconds", "help",
                  buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0, 0.05):  # includes one beyond the ladder
        h.observe(v, model="m")
    families = parse_exposition("\n".join(h.render()) + "\n")
    series = histogram_series(families["dynamo_test_duration_seconds"])
    (entry,) = series.values()
    bounds = [b for b, _ in entry["buckets"]]
    counts = [c for _, c in entry["buckets"]]
    assert bounds == [0.01, 0.1, 1.0, math.inf]
    assert counts == sorted(counts), "cumulative bucket counts must be monotone"
    assert counts[-1] == entry["count"] == 5
    assert entry["sum"] == pytest.approx(5.605)


def test_counter_monotonic_across_scrapes():
    c = Counter("dynamo_test_events_total", "help")

    def scrape():
        fams = parse_exposition("\n".join(c.render()) + "\n")
        return {
            tuple(sorted(s.labels.items())): s.value
            for s in fams["dynamo_test_events_total"].samples
        }

    c.inc(model="a")
    c.inc(2, model="b")
    first = scrape()
    c.inc(model="a")
    second = scrape()
    for key, value in first.items():
        assert second[key] >= value, "counters must never decrease"
    assert second[(("model", "a"),)] == 2.0


def test_gauge_set_and_dec():
    g = Gauge("dynamo_test_inflight_requests", "help")
    g.set(5, model="m")
    g.dec(2, model="m")
    fams = parse_exposition("\n".join(g.render()) + "\n")
    assert fams["dynamo_test_inflight_requests"].type == "gauge"
    assert fams["dynamo_test_inflight_requests"].samples[0].value == 3.0


# ---------------------------------------------------------------- registry


def test_registry_attach_merges_expositions():
    """Engine-side instruments attached to the frontend registry render
    in ONE scrape (the tentpole: one /metrics for every layer)."""
    frontend = MetricsRegistry()
    frontend.counter("dynamo_http_test_requests_total", "h").inc(model="m")
    engine = MetricsRegistry()
    engine.histogram("dynamo_scheduler_test_duration_seconds", "h").observe(0.1)
    engine.callback_gauge("dynamo_kv_test_active_blocks", "h", lambda: 7)
    frontend.attach(engine)
    frontend.attach(engine)  # idempotent

    families = parse_exposition(frontend.render())
    assert "dynamo_http_test_requests_total" in families
    assert "dynamo_scheduler_test_duration_seconds" in families
    assert families["dynamo_kv_test_active_blocks"].samples[0].value == 7.0
    assert "dynamo_scheduler_test_duration_seconds" in frontend.names()


def test_registry_get_or_create_and_type_conflict():
    reg = MetricsRegistry()
    a = reg.counter("dynamo_test_things_total", "h")
    assert reg.counter("dynamo_test_things_total", "h") is a
    with pytest.raises(ValueError):
        reg.gauge("dynamo_test_things_total", "h")


def test_callback_gauge_labeled_and_crash_safe():
    reg = MetricsRegistry()
    reg.callback_gauge(
        "dynamo_test_worker_load_requests", "h",
        lambda: [({"instance": "w1"}, 3), ({"instance": "w2"}, 5)],
    )
    reg.callback_gauge("dynamo_test_broken_requests", "h",
                       lambda: 1 / 0)
    families = parse_exposition(reg.render())
    samples = {s.labels["instance"]: s.value
               for s in families["dynamo_test_worker_load_requests"].samples}
    assert samples == {"w1": 3.0, "w2": 5.0}
    # the broken callback renders nothing — /metrics stays up
    assert "dynamo_test_broken_requests" not in families


# ---------------------------------------------------------------- tracing


def test_span_breakdown_offsets_and_durations():
    # marks are stamped at phase COMPLETION: each gap is attributed to
    # the mark that closes it, so prefill compute lands under "prefill"
    stages = [("http", 10.0), ("prefill", 10.5), ("completion", 11.0)]
    spans = span_breakdown(stages, end=11.25)
    assert [s["name"] for s in spans] == ["prefill", "completion", "egress"]
    assert [s["offset_s"] for s in spans] == [0.0, 0.5, 1.0]
    assert [s["duration_s"] for s in spans] == [0.5, 0.5, 0.25]


def test_trace_recorder_bounded_queue_drops_and_counts(tmp_path):
    """A hung JSONL filesystem must not grow memory without bound: once
    the writer queue is full, traces are dropped and counted."""
    import threading

    rec = TraceRecorder(jsonl_path=str(tmp_path / "t.jsonl"),
                        jsonl_queue_size=1)
    # stand in a finished thread for the writer so nothing drains the
    # queue — the shape of a sink wedged mid-write
    blocked = threading.Thread(target=lambda: None)
    blocked.start()
    blocked.join()
    rec._writer = blocked
    for i in range(3):
        rec.record(f"req-{i}", "m", "success", [("http", 1.0)], end=2.0)
    assert rec.dropped == 2 and rec._queue.qsize() == 1
    rec.close(timeout=0.1)  # must return promptly, not hang


def test_trace_recorder_ring_and_jsonl(tmp_path):
    path = tmp_path / "traces.jsonl"
    rec = TraceRecorder(capacity=2, jsonl_path=str(path))
    for i in range(3):
        rec.record(f"req-{i}", "m", "success",
                   [("http", 1.0), ("completion", 2.0)], end=2.5)
    assert len(rec) == 2
    assert rec.get("req-0") is None, "oldest trace evicted at capacity"
    assert rec.get("req-2")["total_s"] == pytest.approx(1.5)
    rec.close()  # sink IO runs on a writer thread; close() drains it
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert [t["request_id"] for t in lines] == ["req-0", "req-1", "req-2"]
    assert lines[0]["spans"][0]["name"] == "completion"


# ------------------------------------------------------- scheduler end-to-end


def _tiny_scheduler():
    from dynamo_tpu.engine.config import EngineConfig, ModelConfig
    from dynamo_tpu.engine.model_runner import ModelRunner
    from dynamo_tpu.engine.scheduler import Scheduler

    cfg = ModelConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=8,
    )
    econfig = EngineConfig(
        model=cfg, max_batch_size=2, max_model_len=32, kv_block_size=8,
        num_kv_blocks=16, dtype="float32", prefill_buckets=[16],
        allow_random_weights=True,
    )
    return Scheduler(ModelRunner(econfig), econfig)


def _request(request_id, prompt, max_tokens=4):
    from dynamo_tpu.engine.scheduler import EngineRequest
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    req = PreprocessedRequest(
        token_ids=list(prompt),
        sampling_options=SamplingOptions(temperature=0.0),
        stop_conditions=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    )
    return EngineRequest(
        request_id=request_id, prompt=list(prompt), req=req,
        ctx=Context(req).context, out_queue=asyncio.Queue(),
    )


async def _drain(er):
    tokens = []
    while True:
        out = await asyncio.wait_for(er.out_queue.get(), timeout=60)
        if out is None:
            return tokens
        tokens.extend(out.token_ids)


@pytest.mark.asyncio
async def test_scheduler_instruments_and_request_spans():
    """One scrape of the scheduler's registry covers step/phase/ITL
    histograms and KV gauges, and a served request's context carries the
    admission → prefill → first_token → completion span marks."""
    sched = _tiny_scheduler()
    sched.start()
    try:
        ers = [_request("t-0", [1, 5, 9, 13]), _request("t-1", [1, 42, 7])]
        for er in ers:
            sched.add_request(er)
        for er in ers:
            assert len(await _drain(er)) == 4

        families = parse_exposition(sched.registry.render())
        step = histogram_series(
            families["dynamo_scheduler_step_duration_seconds"])
        (entry,) = step.values()
        assert entry["count"] >= 1
        counts = [c for _, c in entry["buckets"]]
        assert counts == sorted(counts)
        assert counts[-1] == entry["count"]

        phases = {
            key_val
            for key in histogram_series(
                families["dynamo_scheduler_phase_duration_seconds"])
            for name, key_val in key if name == "phase"
        }
        assert {"admission", "prefill", "decode", "host_sync"} <= phases

        # 2 requests × 4 tokens → 3 inter-token gaps each
        itl = histogram_series(
            families["dynamo_scheduler_inter_token_latency_seconds"])
        assert list(itl.values())[0]["count"] == 6

        assert families["dynamo_kv_total_blocks"].samples[0].value == 16
        assert families["dynamo_scheduler_total_slots"].samples[0].value == 2
        assert families["dynamo_scheduler_active_slots"].samples[0].value == 0

        for er in ers:
            names = [name for name, _ in er.ctx.stages]
            required = ["queued", "admission", "prefill",
                        "first_token", "completion"]
            positions = [names.index(n) for n in required]
            assert positions == sorted(positions), (
                f"stages out of order: {names}")
    finally:
        await sched.stop()


# ------------------------------------------------------- HTTP service surface


@pytest.mark.asyncio
async def test_http_trace_ids_and_debug_requests_endpoint():
    """X-Request-Id is honored end to end: echoed on the response and
    queryable as a span breakdown at GET /debug/requests/{id}."""
    import aiohttp

    from dynamo_tpu.http.service import HttpService, ModelManager
    from dynamo_tpu.llm.engines.echo import EchoEngineFull

    manager = ModelManager()
    manager.add_chat_model("echo", EchoEngineFull())
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    try:
        base = f"http://127.0.0.1:{service.port}"
        body = {"model": "echo",
                "messages": [{"role": "user", "content": "hi there"}]}
        async with aiohttp.ClientSession() as session:
            async with session.post(
                f"{base}/v1/chat/completions", json=body,
                headers={"X-Request-Id": "trace-me-123"},
            ) as resp:
                assert resp.status == 200
                assert resp.headers["X-Request-Id"] == "trace-me-123"
                await resp.json()

            async with session.get(
                f"{base}/debug/requests/trace-me-123") as resp:
                assert resp.status == 200
                trace = await resp.json()
            assert trace["status"] == "success"
            assert trace["model"] == "echo"
            span_names = [s["name"] for s in trace["spans"]]
            # the echo engine stamps only the ingress "http" mark, so the
            # whole request is the trailing egress span (end-attribution)
            assert span_names[-1] == "egress"
            assert trace["total_s"] >= 0

            async with session.get(f"{base}/debug/requests/nope") as resp:
                assert resp.status == 404

            # the scrape the trace rode alongside is itself valid text
            async with session.get(f"{base}/metrics") as resp:
                families = parse_exposition(await resp.text())
        dur = histogram_series(
            families["dynamo_http_service_request_duration_seconds"])
        entry = dur[(("model", "echo"),)]
        counts = [c for _, c in entry["buckets"]]
        assert counts == sorted(counts)
        assert counts[-1] == entry["count"] >= 1
    finally:
        await service.stop()


@pytest.mark.asyncio
async def test_metrics_sidecar_server():
    """dyn:// roles (router processor, token-level worker) expose their
    registry on the --metrics-port sidecar listener."""
    import aiohttp

    from dynamo_tpu.telemetry.server import MetricsServer

    reg = MetricsRegistry()
    reg.counter("dynamo_kv_router_decisions_total", "h").inc(worker="w1")
    server = await MetricsServer(reg, host="127.0.0.1", port=0).start()
    try:
        async with aiohttp.ClientSession() as session:
            async with session.get(
                f"http://127.0.0.1:{server.port}/metrics") as resp:
                assert resp.status == 200
                families = parse_exposition(await resp.text())
        fam = families["dynamo_kv_router_decisions_total"]
        assert fam.samples[0].labels == {"worker": "w1"}
    finally:
        await server.stop()
