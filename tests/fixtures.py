"""Shared test fixtures: a tiny trained tokenizer + fake HF model dir."""

import json
import os

from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers

CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "hello world this is a test of the tokenizer",
    "paged attention on tpu with jax and pallas kernels",
    "distributed serving with disaggregated prefill and decode",
    "USER: what is the capital of france? ASSISTANT: paris STOP",
    "a b c d e f g h i j k l m n o p q r s t u v w x y z",
    "0 1 2 3 4 5 6 7 8 9 émojis ünïcode ✓ 中文 tokens",
    # JSON structural characters: guided-JSON decoding needs the
    # tokenizer to be able to EXPRESS the grammar (braces, quotes,
    # colons, commas, brackets, minus, dot, backslash)
    '{"name": "value", "n": [1, 2.5, -3], "ok": true, "x": null}',
]

CHAT_TEMPLATE = (
    "{{ bos_token }}"
    "{% for message in messages %}"
    "<|{{ message.role }}|>{{ message.content }}</s>"
    "{% endfor %}"
    "{% if add_generation_prompt %}<|assistant|>{% endif %}"
)


def build_tiny_tokenizer() -> Tokenizer:
    tok = Tokenizer(models.BPE(unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=512,
        special_tokens=["<unk>", "<s>", "</s>", "<|user|>", "<|assistant|>", "<|system|>"],
    )
    tok.train_from_iterator(CORPUS, trainer)
    return tok


def make_model_dir(tmp_path, name="tiny-llama", context_length=256,
                   config_overrides=None) -> str:
    """Write a fake HF snapshot dir: tokenizer.json + config.json + tokenizer_config.json.

    ``config_overrides`` merges extra/replacement keys into config.json
    (e.g. real model dims for a flagship-shape serving benchmark).
    """
    model_dir = os.path.join(str(tmp_path), name)
    os.makedirs(model_dir, exist_ok=True)
    tok = build_tiny_tokenizer()
    tok.save(os.path.join(model_dir, "tokenizer.json"))
    eos_id = tok.token_to_id("</s>")
    bos_id = tok.token_to_id("<s>")
    config = {
        "model_type": "llama",
        "eos_token_id": eos_id,
        "bos_token_id": bos_id,
        "max_position_embeddings": context_length,
        "vocab_size": tok.get_vocab_size(),
    }
    config.update(config_overrides or {})
    with open(os.path.join(model_dir, "config.json"), "w") as f:
        json.dump(config, f)
    with open(os.path.join(model_dir, "tokenizer_config.json"), "w") as f:
        json.dump(
            {
                "chat_template": CHAT_TEMPLATE,
                "bos_token": "<s>",
                "eos_token": "</s>",
            },
            f,
        )
    return model_dir
