"""Sequence-parallel long-context prefill (EngineConfig.sp_size,
docs/long_context.md).

The acceptance contract: a prompt routed through the mesh-sharded SP
chunk ladder produces a decode stream byte-identical to the dense
single-device ladder (same checkpoint, same seeds), the first decode
burst dispatches BEFORE the final chunk's outputs are host-synced (the
early decode handoff), and a request cancelled mid-SP-prefill leaks
zero blocks.
"""

import asyncio
import json
import os

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.serving import JaxServingEngine
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.telemetry.flight import FlightRecorder

from fixtures import make_model_dir

TINY = dict(
    vocab_size=512,
    hidden_size=64,
    intermediate_size=128,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    max_position_embeddings=512,
    rms_norm_eps=1e-5,
    rope_theta=10000.0,
)


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    d = make_model_dir(tmp_path_factory.mktemp("spmodel"), name="tiny-sp")
    cfg = LlamaConfig(**TINY, tie_word_embeddings=False)
    torch.manual_seed(0)
    LlamaForCausalLM(cfg).save_pretrained(d, safe_serialization=True)
    with open(os.path.join(d, "config.json")) as f:
        c = json.load(f)
    c["eos_token_id"] = 2
    c["bos_token_id"] = 1
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump(c, f)
    return d


def _config(model_dir, sp=1, **kw):
    cfg = ModelConfig.from_model_dir(model_dir)
    kw.setdefault("max_prefill_tokens_per_step", 32)
    if sp > 1:
        kw.setdefault("long_prefill_threshold_tokens", 48)
        # sp bucket = largest bucket <= sp * budget = 256 → one chunk
        # would swallow the whole prompt; cap the budget so the ladder
        # genuinely chunks (bucket 128, prompt ~200 → 2+ chunks)
        kw["max_prefill_tokens_per_step"] = 16
    kw.setdefault("max_model_len", 384)
    kw.setdefault("num_kv_blocks", 160)
    return EngineConfig(
        model=cfg, max_batch_size=4, kv_block_size=8,
        dtype="float32", sp_size=sp, **kw,
    )


async def _collect(engine, token_ids, sampling, max_tokens=16,
                   ignore_eos=True):
    req = PreprocessedRequest(
        token_ids=list(token_ids),
        stop_conditions=StopConditions(
            max_tokens=max_tokens, ignore_eos=ignore_eos,
        ),
        sampling_options=sampling,
    )
    toks, finish = [], None
    async for out in engine.generate(Context(req)):
        toks.extend(out["token_ids"])
        if out.get("finish_reason"):
            finish = out["finish_reason"]
    return toks, finish


def _prompt(n, seed=3):
    return [1] + [
        int(t) for t in
        np.random.default_rng(seed).integers(3, 500, n - 1)
    ]


async def _make_engine(model_dir, sp, flight=None, **kw):
    mdc = ModelDeploymentCard.from_local_path(model_dir)
    cfg = _config(model_dir, sp=sp, **kw)
    engine = await JaxServingEngine.create(
        mdc, engine_config=cfg, warmup=False,
    )
    if flight is not None:
        engine.scheduler.flight = flight
    return engine


def test_sp_stream_matches_dense_ladder(model_dir):
    """The CPU-mesh differential: SP chunked prefill ≡ dense chunked
    prefill (greedy AND seeded sampling), with the decode stream
    byte-identical and zero leaked blocks on both engines."""

    async def go(sp):
        engine = await _make_engine(model_dir, sp)
        long_p = _prompt(200)
        results = [
            await _collect(engine, long_p, SamplingOptions(temperature=0.0)),
            await _collect(engine, long_p,
                           SamplingOptions(temperature=0.8, seed=11)),
            # short prompt: stays on the dense ladder on BOTH engines
            await _collect(engine, _prompt(20),
                           SamplingOptions(temperature=0.0)),
        ]
        chunks = sum(engine.scheduler._sp_chunks_c.values.values())
        used = engine.scheduler.allocator.used
        await engine.close()
        return results, chunks, used

    dense, d_chunks, d_used = asyncio.run(go(1))
    spres, s_chunks, s_used = asyncio.run(go(8))
    assert dense == spres
    assert d_chunks == 0          # no SP program on the dense engine
    assert s_chunks >= 2          # the long prompt genuinely chunked
    assert d_used == 0 and s_used == 0
    # the streams are real generations, not empty
    assert len(spres[0][0]) == 16


def test_sp_pallas_kernel_route_stream_matches_xla(model_dir, monkeypatch):
    """The whole-engine kernel-campaign differential: an SP engine
    serving on the Pallas route (interpret mode on CPU — the paged
    prefix-walk kernel inside sp_chunk_attention AND the fused sampling
    epilogue, which fused_epilogue=auto engages with it) must emit the
    same decode stream as the XLA-route engine, greedy and seeded."""
    monkeypatch.setenv("DYN_PALLAS_INTERPRET", "1")
    from dynamo_tpu.llm.model_card import ModelDeploymentCard as MDC
    from dynamo_tpu.ops import attention as attn

    def routed(route):
        return sum(
            v for k, v in attn.ATTENTION_ROUTE_COUNTER.values.items()
            if dict(k).get("route") == route
        )

    async def go(impl):
        mdc = MDC.from_local_path(model_dir)
        cfg = _config(model_dir, sp=8)
        cfg.model.attention_impl = impl
        engine = await JaxServingEngine.create(
            mdc, engine_config=cfg, warmup=False,
        )
        long_p = _prompt(200)
        res = [
            await _collect(engine, long_p,
                           SamplingOptions(temperature=0.0), max_tokens=8),
            await _collect(engine, long_p,
                           SamplingOptions(temperature=0.8, seed=11),
                           max_tokens=8),
        ]
        chunks = sum(engine.scheduler._sp_chunks_c.values.values())
        fused = engine.scheduler.runner._fused_epilogue_enabled()
        used = engine.scheduler.allocator.used
        await engine.close()
        return res, chunks, fused, used

    base_kernel = routed("sp_ring_kernel")
    xla_res, x_chunks, x_fused, x_used = asyncio.run(go("xla"))
    assert not x_fused  # auto keeps the XLA tail with the XLA kernels
    assert routed("sp_ring_kernel") == base_kernel
    pal_res, p_chunks, p_fused, p_used = asyncio.run(go("pallas"))
    assert p_fused     # ...and fuses the tail on the Pallas route
    assert routed("sp_ring_kernel") > base_kernel
    assert xla_res == pal_res
    assert x_chunks >= 2 and p_chunks >= 2
    assert x_used == 0 and p_used == 0
    assert len(pal_res[0][0]) == 8


def test_sp_early_handoff_overlaps_final_drain(model_dir):
    """The early decode handoff: the first decode burst dispatches off
    the DEVICE-resident first token, before the final SP chunk's
    outputs are host-synced — pinned two ways: the runner receives a
    non-numpy (device) tokens0, and the flight ring shows sp_handoff
    recorded before sp_drain."""
    flight = FlightRecorder(capacity=256)

    async def go():
        engine = await _make_engine(model_dir, 8, flight=flight)
        runner = engine.runner
        seen = {}
        orig = runner.decode_burst

        def spy(tokens0, *a, **kw):
            seen.setdefault("tokens0_type", type(tokens0))
            return orig(tokens0, *a, **kw)

        runner.decode_burst = spy
        toks, _ = await _collect(
            engine, _prompt(200), SamplingOptions(temperature=0.0))
        await engine.close()
        return toks, seen

    toks, seen = asyncio.run(go())
    assert len(toks) == 16
    # tokens0 arrived as a device array — the first token was never
    # synced to the host before the burst dispatched
    assert seen["tokens0_type"] is not np.ndarray
    kinds = [e["kind"] for e in flight.snapshot()]
    assert "scheduler.sp_handoff" in kinds
    assert "scheduler.sp_drain" in kinds
    assert kinds.index("scheduler.sp_handoff") < kinds.index(
        "scheduler.sp_drain")
    # the ladder really ran multiple chunks before the handoff
    assert kinds.count("scheduler.sp_chunk") >= 2


def test_sp_cancel_mid_prefill_leaks_nothing(model_dir):
    """Conn-drop / cancellation mid-SP-prefill: the ladder drops the
    request, every block frees, and the engine keeps serving."""

    async def go():
        engine = await _make_engine(model_dir, 8)
        req = PreprocessedRequest(
            token_ids=_prompt(200),
            stop_conditions=StopConditions(max_tokens=16, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
        )
        ctx = Context(req)
        agen = engine.generate(ctx)
        task = asyncio.ensure_future(agen.__anext__())
        # let admission + the first chunk happen, then drop the client
        for _ in range(50):
            await asyncio.sleep(0.01)
            if engine.scheduler.sp_active is not None:
                break
        ctx.context.stop_generating()
        try:
            await asyncio.wait_for(task, timeout=30)
        except (StopAsyncIteration, asyncio.TimeoutError):
            pass
        await agen.aclose()
        # the scheduler reaps the cancel on its next passes
        for _ in range(100):
            await asyncio.sleep(0.01)
            if (engine.scheduler.allocator.used == 0
                    and engine.scheduler.sp_active is None):
                break
        used = engine.scheduler.allocator.used
        sp_active = engine.scheduler.sp_active
        # the engine still serves new work afterwards
        toks, _ = await _collect(
            engine, _prompt(60, seed=9), SamplingOptions(temperature=0.0))
        await engine.close()
        return used, sp_active, toks

    used, sp_active, toks = asyncio.run(go())
    assert used == 0
    assert sp_active is None
    assert len(toks) == 16


def test_sp_metrics_and_warmup(model_dir):
    """The prefill_sp program warms up front (no late compile on the
    first long prompt) and the catalog instruments move."""

    async def go():
        mdc = ModelDeploymentCard.from_local_path(model_dir)
        engine = await JaxServingEngine.create(
            mdc, engine_config=_config(model_dir, sp=8), warmup=True,
        )
        tracker = engine.runner.compiles
        assert any(p == "prefill_sp" for (p, _k) in tracker._seen)
        await _collect(engine, _prompt(200), SamplingOptions(temperature=0.0))
        text = engine.scheduler.registry.render()
        await engine.close()
        return text

    text = asyncio.run(go())
    assert "dynamo_engine_prefill_sp_chunks_total" in text
    assert "dynamo_engine_prefill_sp_axis_depth 8.0" in text
    assert "dynamo_engine_prefill_sp_exposed_seconds" in text
    # tokens counter moved by at least the long prompt's suffix
    for line in text.splitlines():
        if line.startswith("dynamo_engine_prefill_sp_tokens_total"):
            assert float(line.split()[-1]) >= 199
            break
    else:
        raise AssertionError("sp tokens counter missing")


@pytest.mark.slow
def test_sp_long_context_e2e(model_dir):
    """Genuinely long prompt (multiple hundreds of tokens, many chunks)
    — the slow-marked long-context e2e."""

    async def go(sp):
        engine = await _make_engine(
            model_dir, sp, max_model_len=448, num_kv_blocks=256)
        toks, fin = await _collect(
            engine, _prompt(400), SamplingOptions(temperature=0.0),
            max_tokens=24)
        await engine.close()
        return toks, fin

    assert asyncio.run(go(8)) == asyncio.run(go(1))


def test_embeddings_ride_the_prefill_path(model_dir):
    """/v1/embeddings engine half: the batched cacheless prefill trunk
    produces deterministic, batch-invariant, L2-normalized vectors with
    correct usage counts — and touches no KV blocks."""
    from dynamo_tpu.llm.embeddings import Embedder, EmbeddingError
    from dynamo_tpu.llm.tokenizer import HFTokenizer

    async def go():
        engine = await _make_engine(model_dir, 1)
        tok = HFTokenizer.from_model_path(model_dir)
        emb = Embedder(tok, engine,
                       max_model_len=engine.config.max_model_len,
                       vocab_size=engine.config.model.vocab_size)
        v1, n1 = await emb.embed("hello world")
        v2, n2 = await emb.embed(["hello world", "something else entirely"])
        used = engine.scheduler.allocator.used
        # invalid token ids reject at the door
        try:
            await emb.embed([[10_000_000]])
            bad = False
        except EmbeddingError:
            bad = True
        await engine.close()
        return v1, n1, v2, n2, used, bad

    v1, n1, v2, n2, used, bad = asyncio.run(go())
    assert used == 0            # no KV blocks were ever allocated
    assert bad
    assert n1 >= 1 and n2 > n1
    # batch row 0 == the single-input vector (same program family)
    np.testing.assert_allclose(v2[0], v1[0], rtol=1e-5, atol=1e-5)
    # unit norm, and distinct inputs embed distinctly
    assert abs(np.linalg.norm(v1[0]) - 1.0) < 1e-5
    assert not np.allclose(v2[0], v2[1])


def test_sp_backlog_honors_the_prefill_batch_cap(model_dir):
    """SP-routed admissions pre-allocate their whole prompt's blocks, so
    the sp backlog is bounded by max_prefill_batch — oversize backlogs
    wait block-free in `waiting`, exactly like the dense path."""

    async def go():
        engine = await _make_engine(model_dir, 8, max_prefill_batch=2)
        sched = engine.scheduler
        tasks = []
        for i in range(4):
            req = PreprocessedRequest(
                token_ids=_prompt(180, seed=20 + i),
                stop_conditions=StopConditions(max_tokens=4,
                                               ignore_eos=True),
                sampling_options=SamplingOptions(temperature=0.0),
            )

            async def consume(r=req):
                toks = []
                async for out in engine.generate(Context(r)):
                    toks.extend(out["token_ids"])
                return toks

            tasks.append(asyncio.ensure_future(consume()))
        max_backlog = 0
        while not all(t.done() for t in tasks):
            backlog = len(sched.sp_queue) + (
                1 if sched.sp_active is not None else 0)
            max_backlog = max(max_backlog, backlog)
            await asyncio.sleep(0.005)
        results = [await t for t in tasks]
        used = sched.allocator.used
        await engine.close()
        return max_backlog, results, used

    max_backlog, results, used = asyncio.run(go())
    assert max_backlog <= 2          # the cap held under a 4-prompt burst
    assert all(len(r) == 4 for r in results)  # everyone still completed
    assert used == 0
