"""Mixtral-style MoE: dispatch correctness, capacity semantics, EP sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.models import mixtral, resolve
from dynamo_tpu.models.mixtral import expert_capacity, moe_mlp

MOE_CFG = dict(
    vocab_size=256, hidden_size=32, intermediate_size=64, num_layers=2,
    num_heads=4, num_kv_heads=2, head_dim=8, num_experts=4,
    num_experts_per_tok=2,
)


def _weights(key, d, i, e, dtype=jnp.float32):
    ks = jax.random.split(key, 4)
    s = d ** -0.5
    return (
        jax.random.normal(ks[0], (d, e), dtype) * s,        # router
        jax.random.normal(ks[1], (e, d, i), dtype) * s,     # gate
        jax.random.normal(ks[2], (e, d, i), dtype) * s,     # up
        jax.random.normal(ks[3], (e, i, d), dtype) * (i ** -0.5),  # down
    )


def naive_moe(x, router_w, w_gate, w_up, w_down, top_k):
    """Per-token loop oracle (no capacity limit)."""
    probs = jax.nn.softmax(x @ router_w, axis=-1)
    vals, idx = jax.lax.top_k(probs, top_k)
    vals = vals / vals.sum(axis=-1, keepdims=True)
    out = np.zeros_like(np.asarray(x))
    for t in range(x.shape[0]):
        for j in range(top_k):
            e = int(idx[t, j])
            xe = np.asarray(x[t])
            h = np.asarray(jax.nn.silu(xe @ w_gate[e])) * np.asarray(xe @ w_up[e])
            out[t] += float(vals[t, j]) * (h @ np.asarray(w_down[e]))
    return out


def test_moe_mlp_matches_naive_with_ample_capacity():
    t, d, i, e, k = 24, 16, 32, 4, 2
    x = jax.random.normal(jax.random.PRNGKey(0), (t, d), jnp.float32)
    rw, wg, wu, wd = _weights(jax.random.PRNGKey(1), d, i, e)
    got = moe_mlp(x, rw, wg, wu, wd, top_k=k, capacity=t)  # nothing drops
    want = naive_moe(x, rw, wg, wu, wd, k)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-4)


def test_moe_capacity_drops_overflow_tokens():
    """With capacity 1, at most one token per expert contributes; dropped
    (token, expert) pairs contribute exactly zero."""
    t, d, i, e, k = 8, 16, 32, 2, 1
    x = jnp.tile(jax.random.normal(jax.random.PRNGKey(2), (1, d)), (t, 1))
    rw, wg, wu, wd = _weights(jax.random.PRNGKey(3), d, i, e)
    got = np.asarray(moe_mlp(x, rw, wg, wu, wd, top_k=k, capacity=1))
    # identical tokens all route to the same expert; only the first fits
    assert np.any(got[0] != 0)
    np.testing.assert_allclose(got[1:], 0.0, atol=1e-6)


def test_pad_tokens_do_not_steal_capacity():
    """Bucket-pad tokens must not displace real tokens from expert slots."""
    t, d, i, e, k = 8, 16, 32, 2, 1
    real = jax.random.normal(jax.random.PRNGKey(4), (4, d), jnp.float32)
    rw, wg, wu, wd = _weights(jax.random.PRNGKey(5), d, i, e)
    # pads (copies of real rows, guaranteed same routing) come FIRST — with
    # no masking they would win the token-major slot race
    x = jnp.concatenate([real, real], axis=0)
    valid = jnp.asarray([0.0] * 4 + [1.0] * 4)
    got = np.asarray(moe_mlp(x, rw, wg, wu, wd, top_k=k, capacity=4, valid=valid))
    np.testing.assert_allclose(got[:4], 0.0, atol=1e-6)  # pads contribute 0
    want = np.asarray(moe_mlp(real, rw, wg, wu, wd, top_k=k, capacity=4))
    np.testing.assert_allclose(got[4:], want, rtol=1e-5, atol=1e-5)


def test_routing_semantics_variants():
    """DeepSeek knobs: no-topk-norm, routed scaling, sigmoid scoring."""
    t, d, i, e, k = 12, 16, 32, 4, 2
    x = jax.random.normal(jax.random.PRNGKey(6), (t, d), jnp.float32)
    rw, wg, wu, wd = _weights(jax.random.PRNGKey(7), d, i, e)
    base = np.asarray(moe_mlp(x, rw, wg, wu, wd, top_k=k, capacity=t))
    # routed_scaling multiplies the whole routed output
    scaled = np.asarray(moe_mlp(x, rw, wg, wu, wd, top_k=k, capacity=t,
                                routed_scaling=16.0))
    np.testing.assert_allclose(scaled, base * 16.0, rtol=1e-4)
    # norm_topk=False uses raw softmax probabilities (sum < 1) as gates
    unnorm = np.asarray(moe_mlp(x, rw, wg, wu, wd, top_k=k, capacity=t,
                                norm_topk=False))
    assert np.all(np.abs(unnorm) <= np.abs(base) + 1e-5)
    assert not np.allclose(unnorm, base)
    # sigmoid scoring is a different distribution but still finite/valid
    sig = np.asarray(moe_mlp(x, rw, wg, wu, wd, top_k=k, capacity=t,
                             scoring="sigmoid", norm_topk=True))
    assert np.all(np.isfinite(sig))
    with pytest.raises(ValueError, match="scoring"):
        moe_mlp(x, rw, wg, wu, wd, top_k=k, capacity=t, scoring="banana")


def test_group_limited_routing_restricts_selection():
    """n_group/topk_group (DeepSeek V2/V3): every selected expert must
    come from the topk_group best-scoring groups — a token whose two
    best experts straddle groups routes differently than unrestricted."""
    t, d, i, e, k = 12, 16, 32, 8, 2
    x = jax.random.normal(jax.random.PRNGKey(8), (t, d), jnp.float32)
    rw, wg, wu, wd = _weights(jax.random.PRNGKey(9), d, i, e)

    def routed_experts(scoring="softmax", **kw):
        logits = (x @ rw).astype(jnp.float32)
        probs = (jax.nn.sigmoid(logits) if scoring == "sigmoid"
                 else jax.nn.softmax(logits, axis=-1))
        bias = kw.get("router_bias")
        select = probs if bias is None else probs + bias[None, :]
        n_group, topk_group = kw.get("n_group", 1), kw.get("topk_group", 1)
        if n_group > 1:
            gsize = e // n_group
            g = np.asarray(select).reshape(t, n_group, gsize)
            if bias is not None:
                gscore = np.sort(g, axis=-1)[..., -2:].sum(-1)
            else:
                gscore = g.max(-1)
            keep = np.argsort(-gscore, axis=-1)[:, :topk_group]
            mask = np.zeros((t, n_group))
            np.put_along_axis(mask, keep, 1.0, axis=1)
            select = np.asarray(select) * np.repeat(mask, gsize, axis=1)
        return np.argsort(-np.asarray(select), axis=-1)[:, :k]

    # V2 group_limited_greedy: group score = group max
    got = np.asarray(moe_mlp(x, rw, wg, wu, wd, top_k=k, capacity=t,
                             n_group=4, topk_group=1))
    want_idx = routed_experts(n_group=4, topk_group=1)
    # oracle recompute through naive loop restricted to want_idx
    probs = np.asarray(jax.nn.softmax((x @ rw).astype(jnp.float32), axis=-1))
    out = np.zeros((t, d), np.float32)
    for ti in range(t):
        vals = probs[ti, want_idx[ti]]
        vals = vals / vals.sum()
        for j, ei in enumerate(want_idx[ti]):
            xe = np.asarray(x[ti])
            h = np.asarray(jax.nn.silu(xe @ wg[ei])) * np.asarray(xe @ wu[ei])
            out[ti] += vals[j] * (h @ np.asarray(wd[ei]))
    np.testing.assert_allclose(got, out, rtol=1e-4, atol=1e-4)
    # and the restriction actually bit: routing differs from unrestricted
    unrestricted = np.asarray(moe_mlp(x, rw, wg, wu, wd, top_k=k, capacity=t))
    assert not np.allclose(got, unrestricted)

    # V3 noaux_tc: biased selection (top-2-sum group score), unbiased
    # combine weights — verify against the oracle's bias branch, not
    # just finiteness
    bias = jax.random.normal(jax.random.PRNGKey(10), (e,)) * 0.5
    got3 = np.asarray(moe_mlp(
        x, rw, wg, wu, wd, top_k=k, capacity=t, scoring="sigmoid",
        norm_topk=False, router_bias=bias, n_group=4, topk_group=2))
    idx3 = routed_experts(scoring="sigmoid", router_bias=np.asarray(bias),
                          n_group=4, topk_group=2)
    sig = np.asarray(jax.nn.sigmoid((x @ rw).astype(jnp.float32)))
    out3 = np.zeros((t, d), np.float32)
    for ti in range(t):
        for ei in idx3[ti]:  # combine weights = UNbiased sigmoid scores
            xe = np.asarray(x[ti])
            h = np.asarray(jax.nn.silu(xe @ wg[ei])) * np.asarray(xe @ wu[ei])
            out3[ti] += sig[ti, ei] * (h @ np.asarray(wd[ei]))
    np.testing.assert_allclose(got3, out3, rtol=1e-4, atol=1e-4)


def test_group_limited_config_validation():
    # n_group must divide the expert count
    with pytest.raises(ValueError, match="does not divide"):
        ModelConfig.from_hf_config(
            {"n_routed_experts": 6, "n_group": 4, "topk_group": 2})
    # permitted groups must hold >= top_k experts
    with pytest.raises(ValueError, match="fewer experts"):
        ModelConfig.from_hf_config(
            {"n_routed_experts": 8, "n_group": 8, "topk_group": 1,
             "num_experts_per_tok": 2})
    # V2-Lite: topk_method=greedy disables the restriction
    cfg = ModelConfig.from_hf_config(
        {"n_routed_experts": 8, "n_group": 4, "topk_group": 2,
         "topk_method": "greedy"})
    assert cfg.n_group == 1 and cfg.topk_group == 1
    # a real V3-shaped config parses
    cfg = ModelConfig.from_hf_config(
        {"n_routed_experts": 8, "n_group": 4, "topk_group": 2,
         "num_experts_per_tok": 2})
    assert cfg.n_group == 4 and cfg.topk_group == 2


def test_expert_capacity_sizing():
    assert expert_capacity(64, 8, 2, capacity_factor=1.0) == 16
    assert expert_capacity(1, 8, 2, capacity_factor=1.0) == 1  # never 0


def test_registry_resolves_moe():
    assert resolve(ModelConfig(**MOE_CFG)) is mixtral
    assert resolve(ModelConfig()).__name__.endswith("llama")


def test_mixtral_forward_prefill_decode_consistency():
    """Greedy decode after prefill must equal teacher-forced prefill logits."""
    cfg = ModelConfig(**MOE_CFG)
    params = mixtral.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    k_cache, v_cache = mixtral.init_kv_cache(cfg, 16, 4, jnp.float32)

    s = 8
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, s), 0, 256)
    pos = jnp.arange(s)[None, :]
    btab = jnp.arange(4)[None, :]
    slot = pos
    # full prefill: logits for every position
    logits_all, (k1, v1) = mixtral.forward(
        params, cfg, tokens, pos, (k_cache, v_cache), btab, slot,
        jnp.asarray([s]),
    )
    # incremental: prefill s-1 then decode token s-1
    logits_pre, (k2, v2) = mixtral.forward(
        params, cfg, tokens[:, : s - 1], pos[:, : s - 1], (k_cache, v_cache),
        btab, slot[:, : s - 1], jnp.asarray([s - 1]),
    )
    logits_dec, _ = mixtral.forward(
        params, cfg, tokens[:, s - 1 :], pos[:, s - 1 :], (k2, v2),
        btab, slot[:, s - 1 :], jnp.asarray([s]),
    )
    np.testing.assert_allclose(
        np.asarray(logits_all[0, -1]), np.asarray(logits_dec[0, -1]),
        rtol=2e-3, atol=2e-3,
    )


@pytest.mark.parametrize("dp,ep,tp", [(1, 2, 2), (2, 2, 2)])
def test_model_runner_moe_ep_sharding(dp, ep, tp):
    """Full engine step with experts sharded over ep on the virtual mesh."""
    from dynamo_tpu.engine.model_runner import ModelRunner, build_mesh

    mcfg = ModelConfig(**MOE_CFG)
    cfg = EngineConfig(
        model=mcfg, max_batch_size=2 * dp, max_model_len=64, kv_block_size=8,
        num_kv_blocks=32, dtype="float32", dp_size=dp, ep_size=ep, tp_size=tp,
        prefill_buckets=[64],
    )
    runner = ModelRunner(cfg, mesh=build_mesh(dp, tp, jax.devices()[: dp * ep * tp], ep=ep))
    b, w, bs = cfg.max_batch_size, cfg.blocks_per_seq, cfg.kv_block_size
    s = 8
    tokens = np.random.RandomState(0).randint(0, 256, (b, s)).astype(np.int32)
    positions = np.tile(np.arange(s, dtype=np.int32), (b, 1))
    btab = np.zeros((b, w), np.int32)
    for i in range(b):
        btab[i, 0] = i
    slot_map = btab[:, :1] * bs + positions
    next_tokens, *_ = runner.step(
        tokens, positions, btab, slot_map, np.full(b, s, np.int32),
        np.full(b, s - 1, np.int32), np.zeros(b, np.float32),
        np.zeros(b, np.int32), np.ones(b, np.float32), jax.random.PRNGKey(0),
    )
    assert np.asarray(next_tokens).shape == (b,)
