"""Mixtral-style MoE: dispatch correctness, capacity semantics, EP sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.models import mixtral, resolve
from dynamo_tpu.models.mixtral import expert_capacity, moe_mlp

MOE_CFG = dict(
    vocab_size=256, hidden_size=32, intermediate_size=64, num_layers=2,
    num_heads=4, num_kv_heads=2, head_dim=8, num_experts=4,
    num_experts_per_tok=2,
)


def _weights(key, d, i, e, dtype=jnp.float32):
    ks = jax.random.split(key, 4)
    s = d ** -0.5
    return (
        jax.random.normal(ks[0], (d, e), dtype) * s,        # router
        jax.random.normal(ks[1], (e, d, i), dtype) * s,     # gate
        jax.random.normal(ks[2], (e, d, i), dtype) * s,     # up
        jax.random.normal(ks[3], (e, i, d), dtype) * (i ** -0.5),  # down
    )


def naive_moe(x, router_w, w_gate, w_up, w_down, top_k):
    """Per-token loop oracle (no capacity limit)."""
    probs = jax.nn.softmax(x @ router_w, axis=-1)
    vals, idx = jax.lax.top_k(probs, top_k)
    vals = vals / vals.sum(axis=-1, keepdims=True)
    out = np.zeros_like(np.asarray(x))
    for t in range(x.shape[0]):
        for j in range(top_k):
            e = int(idx[t, j])
            xe = np.asarray(x[t])
            h = np.asarray(jax.nn.silu(xe @ w_gate[e])) * np.asarray(xe @ w_up[e])
            out[t] += float(vals[t, j]) * (h @ np.asarray(w_down[e]))
    return out


def test_moe_mlp_matches_naive_with_ample_capacity():
    t, d, i, e, k = 24, 16, 32, 4, 2
    x = jax.random.normal(jax.random.PRNGKey(0), (t, d), jnp.float32)
    rw, wg, wu, wd = _weights(jax.random.PRNGKey(1), d, i, e)
    got = moe_mlp(x, rw, wg, wu, wd, top_k=k, capacity=t)  # nothing drops
    want = naive_moe(x, rw, wg, wu, wd, k)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-4)


def test_moe_capacity_drops_overflow_tokens():
    """With capacity 1, at most one token per expert contributes; dropped
    (token, expert) pairs contribute exactly zero."""
    t, d, i, e, k = 8, 16, 32, 2, 1
    x = jnp.tile(jax.random.normal(jax.random.PRNGKey(2), (1, d)), (t, 1))
    rw, wg, wu, wd = _weights(jax.random.PRNGKey(3), d, i, e)
    got = np.asarray(moe_mlp(x, rw, wg, wu, wd, top_k=k, capacity=1))
    # identical tokens all route to the same expert; only the first fits
    assert np.any(got[0] != 0)
    np.testing.assert_allclose(got[1:], 0.0, atol=1e-6)


def test_pad_tokens_do_not_steal_capacity():
    """Bucket-pad tokens must not displace real tokens from expert slots."""
    t, d, i, e, k = 8, 16, 32, 2, 1
    real = jax.random.normal(jax.random.PRNGKey(4), (4, d), jnp.float32)
    rw, wg, wu, wd = _weights(jax.random.PRNGKey(5), d, i, e)
    # pads (copies of real rows, guaranteed same routing) come FIRST — with
    # no masking they would win the token-major slot race
    x = jnp.concatenate([real, real], axis=0)
    valid = jnp.asarray([0.0] * 4 + [1.0] * 4)
    got = np.asarray(moe_mlp(x, rw, wg, wu, wd, top_k=k, capacity=4, valid=valid))
    np.testing.assert_allclose(got[:4], 0.0, atol=1e-6)  # pads contribute 0
    want = np.asarray(moe_mlp(real, rw, wg, wu, wd, top_k=k, capacity=4))
    np.testing.assert_allclose(got[4:], want, rtol=1e-5, atol=1e-5)


def test_routing_semantics_variants():
    """DeepSeek knobs: no-topk-norm, routed scaling, sigmoid scoring."""
    t, d, i, e, k = 12, 16, 32, 4, 2
    x = jax.random.normal(jax.random.PRNGKey(6), (t, d), jnp.float32)
    rw, wg, wu, wd = _weights(jax.random.PRNGKey(7), d, i, e)
    base = np.asarray(moe_mlp(x, rw, wg, wu, wd, top_k=k, capacity=t))
    # routed_scaling multiplies the whole routed output
    scaled = np.asarray(moe_mlp(x, rw, wg, wu, wd, top_k=k, capacity=t,
                                routed_scaling=16.0))
    np.testing.assert_allclose(scaled, base * 16.0, rtol=1e-4)
    # norm_topk=False uses raw softmax probabilities (sum < 1) as gates
    unnorm = np.asarray(moe_mlp(x, rw, wg, wu, wd, top_k=k, capacity=t,
                                norm_topk=False))
    assert np.all(np.abs(unnorm) <= np.abs(base) + 1e-5)
    assert not np.allclose(unnorm, base)
    # sigmoid scoring is a different distribution but still finite/valid
    sig = np.asarray(moe_mlp(x, rw, wg, wu, wd, top_k=k, capacity=t,
                             scoring="sigmoid", norm_topk=True))
    assert np.all(np.isfinite(sig))
    with pytest.raises(ValueError, match="scoring"):
        moe_mlp(x, rw, wg, wu, wd, top_k=k, capacity=t, scoring="banana")


def test_group_limited_routing_rejected():
    with pytest.raises(NotImplementedError, match="n_group"):
        ModelConfig.from_hf_config({"n_group": 4, "topk_group": 2})


def test_expert_capacity_sizing():
    assert expert_capacity(64, 8, 2, capacity_factor=1.0) == 16
    assert expert_capacity(1, 8, 2, capacity_factor=1.0) == 1  # never 0


def test_registry_resolves_moe():
    assert resolve(ModelConfig(**MOE_CFG)) is mixtral
    assert resolve(ModelConfig()).__name__.endswith("llama")


def test_mixtral_forward_prefill_decode_consistency():
    """Greedy decode after prefill must equal teacher-forced prefill logits."""
    cfg = ModelConfig(**MOE_CFG)
    params = mixtral.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    k_cache, v_cache = mixtral.init_kv_cache(cfg, 16, 4, jnp.float32)

    s = 8
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, s), 0, 256)
    pos = jnp.arange(s)[None, :]
    btab = jnp.arange(4)[None, :]
    slot = pos
    # full prefill: logits for every position
    logits_all, (k1, v1) = mixtral.forward(
        params, cfg, tokens, pos, (k_cache, v_cache), btab, slot,
        jnp.asarray([s]),
    )
    # incremental: prefill s-1 then decode token s-1
    logits_pre, (k2, v2) = mixtral.forward(
        params, cfg, tokens[:, : s - 1], pos[:, : s - 1], (k_cache, v_cache),
        btab, slot[:, : s - 1], jnp.asarray([s - 1]),
    )
    logits_dec, _ = mixtral.forward(
        params, cfg, tokens[:, s - 1 :], pos[:, s - 1 :], (k2, v2),
        btab, slot[:, s - 1 :], jnp.asarray([s]),
    )
    np.testing.assert_allclose(
        np.asarray(logits_all[0, -1]), np.asarray(logits_dec[0, -1]),
        rtol=2e-3, atol=2e-3,
    )


@pytest.mark.parametrize("dp,ep,tp", [(1, 2, 2), (2, 2, 2)])
def test_model_runner_moe_ep_sharding(dp, ep, tp):
    """Full engine step with experts sharded over ep on the virtual mesh."""
    from dynamo_tpu.engine.model_runner import ModelRunner, build_mesh

    mcfg = ModelConfig(**MOE_CFG)
    cfg = EngineConfig(
        model=mcfg, max_batch_size=2 * dp, max_model_len=64, kv_block_size=8,
        num_kv_blocks=32, dtype="float32", dp_size=dp, ep_size=ep, tp_size=tp,
        prefill_buckets=[64],
    )
    runner = ModelRunner(cfg, mesh=build_mesh(dp, tp, jax.devices()[: dp * ep * tp], ep=ep))
    b, w, bs = cfg.max_batch_size, cfg.blocks_per_seq, cfg.kv_block_size
    s = 8
    tokens = np.random.RandomState(0).randint(0, 256, (b, s)).astype(np.int32)
    positions = np.tile(np.arange(s, dtype=np.int32), (b, 1))
    btab = np.zeros((b, w), np.int32)
    for i in range(b):
        btab[i, 0] = i
    slot_map = btab[:, :1] * bs + positions
    next_tokens, *_ = runner.step(
        tokens, positions, btab, slot_map, np.full(b, s, np.int32),
        np.full(b, s - 1, np.int32), np.zeros(b, np.float32),
        np.zeros(b, np.int32), np.ones(b, np.float32), jax.random.PRNGKey(0),
    )
    assert np.asarray(next_tokens).shape == (b,)
