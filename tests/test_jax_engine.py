"""JAX engine correctness: logits vs HF transformers, continuous batching,
prefix caching, allocator semantics."""

import asyncio
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.engine.block_allocator import BlockAllocator, KvEventSink
from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.model_runner import ModelRunner, build_mesh
from dynamo_tpu.engine.scheduler import EngineRequest, Scheduler
from dynamo_tpu.engine.serving import JaxServingEngine
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.models import llama
from dynamo_tpu.models.loader import load_llama_params
from dynamo_tpu.protocols.common import (
    FinishReason,
    OutputOptions,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import Context

from fixtures import make_model_dir

TINY = dict(
    vocab_size=512,
    hidden_size=64,
    intermediate_size=128,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    max_position_embeddings=256,
    rms_norm_eps=1e-5,
    rope_theta=10000.0,
)


@pytest.fixture(scope="module")
def hf_model_dir(tmp_path_factory):
    """Tiny HF Llama checkpoint + our tokenizer files in one dir."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    d = make_model_dir(tmp_path_factory.mktemp("hfmodel"), name="tiny-hf")
    cfg = LlamaConfig(**TINY, tie_word_embeddings=False)
    torch.manual_seed(0)
    model = LlamaForCausalLM(cfg)
    model.save_pretrained(d, safe_serialization=True)
    # save_pretrained rewrites config.json; re-add tokenizer metadata fields
    with open(os.path.join(d, "config.json")) as f:
        c = json.load(f)
    c["eos_token_id"] = 2
    c["bos_token_id"] = 1
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump(c, f)
    return d


@pytest.fixture(scope="module")
def hf_logits(hf_model_dir):
    """Reference logits + greedy continuation from transformers (fp32 CPU)."""
    import torch
    from transformers import LlamaForCausalLM

    model = LlamaForCausalLM.from_pretrained(hf_model_dir, torch_dtype=torch.float32)
    model.eval()
    prompt = [1, 17, 43, 99, 7, 3, 250, 12, 5, 77]
    with torch.no_grad():
        out = model(torch.tensor([prompt]))
        logits = out.logits[0].numpy()
        gen = model.generate(
            torch.tensor([prompt]), max_new_tokens=12, do_sample=False,
            eos_token_id=None, pad_token_id=0,
        )[0].tolist()
    return prompt, logits, gen[len(prompt):]


def _make_runner(hf_model_dir, **overrides):
    cfg = ModelConfig.from_model_dir(hf_model_dir)
    econfig = EngineConfig(
        model=cfg, max_batch_size=4, max_model_len=128, kv_block_size=8,
        num_kv_blocks=64, dtype="float32", **overrides,
    )
    params = load_llama_params(hf_model_dir, cfg, jnp.float32)
    return ModelRunner(econfig, params=params), econfig


def test_prefill_logits_match_hf(hf_model_dir, hf_logits):
    prompt, ref_logits, _ = hf_logits
    runner, econfig = _make_runner(hf_model_dir)
    cfg = econfig.model
    s = len(prompt)
    bs = econfig.kv_block_size
    n_blocks = -(-s // bs)
    tokens = np.asarray([prompt], np.int32)
    positions = np.arange(s, dtype=np.int32)[None, :]
    block_tables = np.zeros((1, econfig.blocks_per_seq), np.int32)
    block_tables[0, :n_blocks] = np.arange(1, n_blocks + 1)
    slot_map = (block_tables[0, positions // bs] * bs + positions % bs).astype(np.int32)
    logits, _cache = llama.forward(
        runner.params, cfg,
        jnp.asarray(tokens), jnp.asarray(positions), runner.kv_cache,
        jnp.asarray(block_tables), jnp.asarray(slot_map),
        jnp.asarray([s], np.int32),
    )
    got = np.asarray(logits[0], np.float32)
    np.testing.assert_allclose(got, ref_logits, rtol=2e-3, atol=2e-3)


@pytest.mark.asyncio
async def test_greedy_decode_matches_hf(hf_model_dir, hf_logits):
    prompt, _, ref_continuation = hf_logits
    mdc = ModelDeploymentCard.from_local_path(hf_model_dir)
    cfg = ModelConfig.from_model_dir(hf_model_dir)
    econfig = EngineConfig(
        model=cfg, max_batch_size=4, max_model_len=128, kv_block_size=8,
        num_kv_blocks=64, dtype="float32",
    )
    engine = await JaxServingEngine.create(
        mdc, engine_config=econfig, warmup=False
    )
    req = PreprocessedRequest(
        token_ids=prompt,
        stop_conditions=StopConditions(max_tokens=12, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0),
    )
    got = []
    async for out in engine.generate(Context(req)):
        got.extend(out["token_ids"])
    assert got == ref_continuation
    await engine.close()


@pytest.mark.asyncio
async def test_concurrent_requests_match_sequential(hf_model_dir):
    """Continuous batching must not change greedy outputs."""
    mdc = ModelDeploymentCard.from_local_path(hf_model_dir)
    cfg = ModelConfig.from_model_dir(hf_model_dir)
    econfig = EngineConfig(
        model=cfg, max_batch_size=4, max_model_len=128, kv_block_size=8,
        num_kv_blocks=96, dtype="float32", enable_prefix_caching=False,
    )
    engine = await JaxServingEngine.create(mdc, engine_config=econfig, warmup=False)

    prompts = [
        [1, 5, 9, 13],
        [1, 100, 200, 300, 400, 17],
        [1, 42],
        [1, 7, 7, 7, 7, 7, 7, 7, 7],
    ]

    async def run_one(p):
        req = PreprocessedRequest(
            token_ids=p,
            stop_conditions=StopConditions(max_tokens=8, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
        )
        toks = []
        async for out in engine.generate(Context(req)):
            toks.extend(out["token_ids"])
        return toks

    sequential = []
    for p in prompts:
        sequential.append(await run_one(p))
    concurrent = await asyncio.gather(*(run_one(p) for p in prompts))
    assert concurrent == sequential
    await engine.close()


@pytest.mark.asyncio
async def test_prefix_cache_hit_and_consistency(hf_model_dir):
    mdc = ModelDeploymentCard.from_local_path(hf_model_dir)
    cfg = ModelConfig.from_model_dir(hf_model_dir)
    econfig = EngineConfig(
        model=cfg, max_batch_size=4, max_model_len=128, kv_block_size=8,
        num_kv_blocks=96, dtype="float32", enable_prefix_caching=True,
    )
    engine = await JaxServingEngine.create(mdc, engine_config=econfig, warmup=False)
    prompt = [1] + list(range(50, 50 + 23))  # 24 tokens = 3 full blocks

    async def run():
        req = PreprocessedRequest(
            token_ids=prompt,
            stop_conditions=StopConditions(max_tokens=6, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
        )
        toks = []
        async for out in engine.generate(Context(req)):
            toks.extend(out["token_ids"])
        return toks

    first = await run()
    m1 = engine.metrics()
    assert m1["gpu_prefix_cache_hit_rate"] == 0.0
    second = await run()
    m2 = engine.metrics()
    assert second == first  # cache hit must not change outputs
    assert m2["gpu_prefix_cache_hit_rate"] > 0.0
    await engine.close()


@pytest.mark.asyncio
async def test_eos_and_hidden_stop(hf_model_dir):
    mdc = ModelDeploymentCard.from_local_path(hf_model_dir)
    cfg = ModelConfig.from_model_dir(hf_model_dir)
    econfig = EngineConfig(
        model=cfg, max_batch_size=2, max_model_len=128, kv_block_size=8,
        num_kv_blocks=64, dtype="float32",
    )
    engine = await JaxServingEngine.create(mdc, engine_config=econfig, warmup=False)

    # find what greedy generates first, then declare it a hidden stop id
    req = PreprocessedRequest(
        token_ids=[1, 5, 9], stop_conditions=StopConditions(max_tokens=3, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0),
    )
    first_toks = []
    async for out in engine.generate(Context(req)):
        first_toks.extend(out["token_ids"])

    req2 = PreprocessedRequest(
        token_ids=[1, 5, 9],
        stop_conditions=StopConditions(
            max_tokens=10, stop_token_ids_hidden=[first_toks[0]], ignore_eos=True
        ),
        sampling_options=SamplingOptions(temperature=0.0),
    )
    outs = []
    async for out in engine.generate(Context(req2)):
        outs.append(out)
    assert outs[-1]["finish_reason"] == "stop"
    assert len(outs) == 1  # stopped on the very first token
    await engine.close()


# ---------- allocator unit tests ----------


def test_allocator_prefix_match_and_eviction():
    events = {"stored": [], "removed": []}
    sink = KvEventSink(
        on_stored=lambda h, p: events["stored"].append((h, p)),
        on_removed=lambda h: events["removed"].append(h),
    )
    alloc = BlockAllocator(num_blocks=4, block_size=4, events=sink)

    prompt = list(range(8))  # 2 full blocks
    blocks, cached = alloc.allocate_prompt(prompt)
    assert cached == 0 and len(blocks) == 2
    from dynamo_tpu.tokens import compute_block_hashes

    hashes = compute_block_hashes(prompt, 4)
    alloc.register_complete(blocks[0], hashes[0], None)
    alloc.register_complete(blocks[1], hashes[1], hashes[0])
    assert len(events["stored"]) == 2

    # same prompt again → both blocks matched (minus recompute-last rule)
    blocks2, cached2 = alloc.allocate_prompt(prompt)
    assert cached2 == 4  # one block reused; last block recomputed by design
    assert blocks2[0] == blocks[0]

    alloc.free_blocks(blocks)
    alloc.free_blocks(blocks2)
    # all blocks reusable now; exhaust memory to force eviction
    a = alloc.allocate_prompt(list(range(100, 116)))[0]  # 4 blocks → evicts
    assert len(a) == 4
    assert events["removed"]  # eviction announced


def test_allocator_oom():
    alloc = BlockAllocator(num_blocks=2, block_size=4, enable_prefix_caching=False)
    alloc.allocate_prompt(list(range(8)))
    with pytest.raises(MemoryError):
        alloc.allocate_prompt(list(range(8)))


# ---------- TP sharding on virtual devices ----------


def test_tp_sharded_runner_matches_single_device(hf_model_dir, hf_logits):
    prompt, ref_logits, _ = hf_logits
    cfg = ModelConfig.from_model_dir(hf_model_dir)
    econfig = EngineConfig(
        model=cfg, max_batch_size=2, max_model_len=64, kv_block_size=8,
        num_kv_blocks=32, dtype="float32", tp_size=2,
    )
    params = load_llama_params(hf_model_dir, cfg, jnp.float32)
    runner = ModelRunner(econfig, params=params, mesh=build_mesh(1, 2))

    s = len(prompt)
    bs = econfig.kv_block_size
    tokens = np.asarray([prompt], np.int32)
    positions = np.arange(s, dtype=np.int32)[None, :]
    btab = np.zeros((1, econfig.blocks_per_seq), np.int32)
    btab[0, : -(-s // bs)] = np.arange(-(-s // bs))
    slot_map = (btab[0, positions // bs] * bs + positions % bs).astype(np.int32)
    next_tokens, *_ = runner.step(
        tokens, positions, btab, slot_map,
        np.asarray([s], np.int32), np.asarray([s - 1], np.int32),
        np.zeros(1, np.float32), np.zeros(1, np.int32), np.ones(1, np.float32),
        jax.random.PRNGKey(0),
    )
    # greedy next token must match the HF argmax at the last position
    assert int(np.asarray(next_tokens)[0]) == int(ref_logits[-1].argmax())


# ---------- round-2 scheduler features ----------


@pytest.mark.asyncio
async def test_preemption_resumes_stream(hf_model_dir):
    """KV OOM mid-decode must preempt and then CONTINUE the stream
    (VERDICT r1 weak #4: the old code re-prefilled only the prompt and
    re-emitted a fresh stream — duplicated/divergent output).

    Continuity properties (recompute-preemption can differ in the last
    float bits, so post-resume tokens may legitimately diverge on a
    near-tie greedy argmax — same caveat as vLLM recompute preemption):
    - every stream emits EXACTLY max_tokens tokens (a restart would emit
      pre-preemption tokens twice),
    - tokens emitted before the preemption point match the uninterrupted
      run bit-for-bit."""
    mdc = ModelDeploymentCard.from_local_path(hf_model_dir)
    cfg = ModelConfig.from_model_dir(hf_model_dir)

    async def run_with(num_blocks, prompts, max_tokens=24):
        econfig = EngineConfig(
            model=cfg, max_batch_size=4, max_model_len=128, kv_block_size=8,
            num_kv_blocks=num_blocks, dtype="float32",
            enable_prefix_caching=False,
        )
        engine = await JaxServingEngine.create(
            mdc, engine_config=econfig, warmup=False
        )
        sched = engine.scheduler
        first_preempt = {}  # prompt-key -> generated count at first preempt
        orig_preempt = sched._preempt

        def recording_preempt(er):
            first_preempt.setdefault(er.prompt[1], er.generated)
            orig_preempt(er)

        sched._preempt = recording_preempt

        async def one(p):
            req = PreprocessedRequest(
                token_ids=p,
                stop_conditions=StopConditions(
                    max_tokens=max_tokens, ignore_eos=True
                ),
                sampling_options=SamplingOptions(temperature=0.0),
            )
            toks = []
            async for out in engine.generate(Context(req)):
                toks.extend(out["token_ids"])
            return toks

        outs = await asyncio.gather(*(one(p) for p in prompts))
        await engine.close()
        return outs, first_preempt

    prompts = [
        [1] + list(range(40, 56)),   # 17 tokens
        [1] + list(range(80, 96)),
        [1] + list(range(120, 136)),
    ]
    # plenty of memory: no preemption — the ground truth
    want, none_preempted = await run_with(64, prompts)
    assert not none_preempted
    # tight memory: (17 + 24) tokens/seq = 6 blocks/seq * 3 seqs = 18 blocks
    # needed at the end; 13 blocks forces preemption churn
    got, preempted = await run_with(13, prompts)
    assert preempted, "test is vacuous: no preemption happened"
    for p, w, g in zip(prompts, want, got):
        assert len(g) == len(w) == 24  # no restarted/duplicated emission
        cut = preempted.get(p[1], len(w))
        assert g[:cut] == w[:cut]


@pytest.mark.asyncio
async def test_preemption_under_speculative_decode(hf_model_dir):
    """KV OOM during the speculative path (which reserves K+1 positions
    ahead) must preempt and resume with the same continuity guarantees
    as plain decode — and the resumed stream still totals max_tokens."""
    mdc = ModelDeploymentCard.from_local_path(hf_model_dir)
    cfg = ModelConfig.from_model_dir(hf_model_dir)

    async def run_with(num_blocks, prompts, max_tokens=20):
        econfig = EngineConfig(
            model=cfg, max_batch_size=4, max_model_len=128, kv_block_size=8,
            num_kv_blocks=num_blocks, dtype="float32",
            enable_prefix_caching=False,
            spec_ngram_tokens=4, spec_ngram_match=2,
        )
        engine = await JaxServingEngine.create(
            mdc, engine_config=econfig, warmup=False
        )
        sched = engine.scheduler
        preempted = []
        orig = sched._preempt

        def rec(er):
            preempted.append(er.request_id)
            orig(er)

        sched._preempt = rec

        async def one(p):
            req = PreprocessedRequest(
                token_ids=p,
                stop_conditions=StopConditions(
                    max_tokens=max_tokens, ignore_eos=True
                ),
                sampling_options=SamplingOptions(temperature=0.0),
            )
            toks = []
            async for out in engine.generate(Context(req)):
                toks.extend(out["token_ids"])
            return toks

        outs = await asyncio.gather(*(one(p) for p in prompts))
        m = engine.metrics()
        await engine.close()
        return outs, preempted, m

    # repetitive prompts so ngram proposals fire
    prompts = [
        [1] + [9, 8] * 8,
        [1] + [5, 6] * 8,
        [1] + [3, 4] * 8,
    ]
    want, none_preempted, _ = await run_with(64, prompts)
    assert not none_preempted
    got, preempted, metrics = await run_with(10, prompts)
    assert preempted, "test is vacuous: no preemption happened"
    for w, g in zip(want, got):
        assert len(g) == len(w) == 20  # no restarted/duplicated emission


@pytest.mark.asyncio
async def test_chunked_prefill_bounds_decode_stall(hf_model_dir):
    """With max_prefill_tokens_per_step set, a long prompt prefills in
    chunks interleaved with decode steps, and outputs stay identical."""
    mdc = ModelDeploymentCard.from_local_path(hf_model_dir)
    cfg = ModelConfig.from_model_dir(hf_model_dir)

    async def run_with(chunk_budget):
        econfig = EngineConfig(
            model=cfg, max_batch_size=4, max_model_len=256, kv_block_size=8,
            num_kv_blocks=96, dtype="float32", enable_prefix_caching=False,
            max_prefill_tokens_per_step=chunk_budget,
            prefill_buckets=[16, 32, 64, 128, 256],
        )
        engine = await JaxServingEngine.create(
            mdc, engine_config=econfig, warmup=False
        )
        sched = engine.scheduler

        async def one(p, max_tokens):
            req = PreprocessedRequest(
                token_ids=p,
                stop_conditions=StopConditions(
                    max_tokens=max_tokens, ignore_eos=True
                ),
                sampling_options=SamplingOptions(temperature=0.0),
            )
            toks = []
            async for out in engine.generate(Context(req)):
                toks.extend(out["token_ids"])
            return toks

        # a short request decoding while a 100-token prompt prefills
        short_task = asyncio.create_task(one([1, 5, 9], 20))
        await asyncio.sleep(0.05)
        long_task = asyncio.create_task(one([1] + list(range(100, 199)), 4))
        outs = await asyncio.gather(short_task, long_task)
        steps = sched.steps
        await engine.close()
        return outs, steps

    want, _ = await run_with(8192)   # one-shot prefill (old behavior)
    got, steps = await run_with(16)  # 100-token prompt → ≥7 chunks
    assert got == want
    assert steps > 10  # chunked run takes many more scheduler steps


@pytest.mark.asyncio
async def test_prefill_budget_shrinks_batch_instead_of_overrunning(hf_model_dir):
    """When a full prefill batch exceeds max_prefill_tokens_per_step even
    at the smallest bucket, the scheduler admits fewer rows that step
    (ADVICE r3): computed positions = padded rows x padded bucket must
    stay within budget, and outputs must be unchanged."""
    mdc = ModelDeploymentCard.from_local_path(hf_model_dir)
    cfg = ModelConfig.from_model_dir(hf_model_dir)

    async def run_with(budget):
        econfig = EngineConfig(
            model=cfg, max_batch_size=4, max_model_len=128, kv_block_size=8,
            num_kv_blocks=96, dtype="float32", enable_prefix_caching=False,
            max_prefill_tokens_per_step=budget,
            prefill_buckets=[16, 32, 64, 128],
        )
        engine = await JaxServingEngine.create(
            mdc, engine_config=econfig, warmup=False
        )
        sched = engine.scheduler
        overruns = []
        orig_step = sched.runner.step

        def spy(tokens, *a, **kw):
            rows, bucket = tokens.shape
            if bucket > 1 and rows * bucket > budget:  # prefill-shaped call
                overruns.append((rows, bucket))
            return orig_step(tokens, *a, **kw)

        sched.runner.step = spy

        async def one(p):
            req = PreprocessedRequest(
                token_ids=p,
                stop_conditions=StopConditions(max_tokens=6, ignore_eos=True),
                sampling_options=SamplingOptions(temperature=0.0),
            )
            toks = []
            async for out in engine.generate(Context(req)):
                toks.extend(out["token_ids"])
            return toks

        prompts = [[1] + list(range(2 + 40 * i, 41 + 40 * i)) for i in range(4)]
        outs = await asyncio.gather(*(one(p) for p in prompts))
        await engine.close()
        return outs, overruns

    want, _ = await run_with(8192)
    got, overruns = await run_with(32)  # 4 rows x smallest bucket = 64 > 32
    assert got == want
    assert not overruns, f"prefill steps exceeded the budget: {overruns}"


@pytest.mark.asyncio
async def test_sampling_penalties_and_seed_isolation(hf_model_dir):
    """Penalties/min_p are honored; per-request seeds are reproducible and
    isolated from batchmates (VERDICT r1 next-round #5)."""
    mdc = ModelDeploymentCard.from_local_path(hf_model_dir)
    cfg = ModelConfig.from_model_dir(hf_model_dir)
    econfig = EngineConfig(
        model=cfg, max_batch_size=4, max_model_len=128, kv_block_size=8,
        num_kv_blocks=96, dtype="float32", enable_prefix_caching=False,
    )
    engine = await JaxServingEngine.create(mdc, engine_config=econfig, warmup=False)

    async def one(p, max_tokens=12, **so):
        req = PreprocessedRequest(
            token_ids=p,
            stop_conditions=StopConditions(max_tokens=max_tokens, ignore_eos=True),
            sampling_options=SamplingOptions(**so),
        )
        toks = []
        async for out in engine.generate(Context(req)):
            toks.extend(out["token_ids"])
        return toks

    # 1. a huge repetition penalty must change the greedy continuation:
    #    this prompt's unpenalized greedy run emits 425 repeatedly
    rep_prompt = [1] + list(range(80, 96))
    base = await one(rep_prompt, max_tokens=24, temperature=0.0)
    assert len(base) != len(set(base)), "premise: greedy repeats here"
    pen = await one(rep_prompt, max_tokens=24, temperature=0.0,
                    repetition_penalty=50.0)
    assert base != pen
    # the penalized run must never emit a token twice (50x penalty is an
    # effective ban on this tiny vocab's logit range)
    assert len(pen) == len(set(pen))
    # presence penalty: a large one likewise bans repeats of generated tokens
    pres = await one(rep_prompt, max_tokens=24, temperature=0.0,
                     presence_penalty=100.0)
    assert len(pres) == len(set(pres))

    # 2. seeded sampling is reproducible...
    a = await one([1, 5, 9], temperature=1.0, seed=1234)
    b = await one([1, 5, 9], temperature=1.0, seed=1234)
    assert a == b
    # ...isolated from concurrent batchmates with other seeds...
    c, _d = await asyncio.gather(
        one([1, 5, 9], temperature=1.0, seed=1234),
        one([1, 42, 3], temperature=1.0, seed=77),
    )
    assert c == a
    # ...and different seeds give different streams
    e = await one([1, 5, 9], temperature=1.0, seed=4321)
    assert e != a

    # 3. min_p=1.0 keeps only the argmax → equals greedy
    g = await one([1, 5, 9], temperature=0.0)
    m = await one([1, 5, 9], temperature=1.0, min_p=1.0, seed=5)
    assert m == g

    # 4. n > 1 fans out into independent seeded choices at the engine:
    # deltas come back tagged with their choice index, greedy choices
    # are identical to the single-choice stream, and the fold covers
    # every choice (ISSUE 13: n>1 rows are ordinary chain members)
    req = PreprocessedRequest(
        token_ids=[1, 5, 9],
        stop_conditions=StopConditions(max_tokens=6, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0, n=2),
    )
    per_choice = {0: [], 1: []}
    async for out in engine.generate(Context(req)):
        per_choice[out["choice"]].extend(out.get("token_ids", []))
    single = await one([1, 5, 9], max_tokens=6, temperature=0.0)
    assert per_choice[0] == per_choice[1] == single
    # n beyond the OpenAI cap still rejects loudly
    from dynamo_tpu.runtime.engine import EngineError
    with pytest.raises(EngineError):
        await one([1, 5, 9], n=21)
    await engine.close()


@pytest.mark.asyncio
async def test_logit_bias_forces_and_bans_tokens(hf_model_dir):
    """OpenAI logit_bias: +100 forces a token under greedy; -100 bans the
    greedy choice (the engine applies per-slot bias rows in the sampler)."""
    mdc = ModelDeploymentCard.from_local_path(hf_model_dir)
    cfg = ModelConfig.from_model_dir(hf_model_dir)
    econfig = EngineConfig(
        model=cfg, max_batch_size=2, max_model_len=64, kv_block_size=8,
        num_kv_blocks=32, dtype="float32",
    )
    engine = await JaxServingEngine.create(mdc, engine_config=econfig, warmup=False)
    prompt = [1, 17, 43, 99, 7]

    async def gen(bias):
        req = PreprocessedRequest(
            token_ids=prompt,
            stop_conditions=StopConditions(max_tokens=3, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0, logit_bias=bias),
        )
        toks = []
        async for out in engine.generate(Context(req)):
            toks.extend(out["token_ids"])
        return toks

    baseline = await gen(None)
    forced = await gen({123: 100.0})
    banned = await gen({baseline[0]: -100.0})
    await engine.close()
    assert forced == [123, 123, 123]
    assert banned[0] != baseline[0]


@pytest.mark.asyncio
async def test_top_logprobs_stream(hf_model_dir):
    """top_logprobs alternatives ride each token's logprobs entry and the
    chosen (greedy) token leads its own top list."""
    mdc = ModelDeploymentCard.from_local_path(hf_model_dir)
    cfg = ModelConfig.from_model_dir(hf_model_dir)
    econfig = EngineConfig(
        model=cfg, max_batch_size=2, max_model_len=64, kv_block_size=8,
        num_kv_blocks=32, dtype="float32",
    )
    engine = await JaxServingEngine.create(mdc, engine_config=econfig, warmup=False)
    req = PreprocessedRequest(
        token_ids=[1, 17, 43, 99, 7],
        stop_conditions=StopConditions(max_tokens=4, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0),
        output_options=OutputOptions(logprobs=3),
    )
    entries = []
    async for out in engine.generate(Context(req)):
        for lp in out.get("logprobs") or []:
            entries.append(lp)
    await engine.close()
    assert len(entries) == 4
    for lp in entries:
        top = lp["top"]
        assert len(top) == 3
        ids = list(top)
        # greedy: the sampled token is the most likely → first in top
        assert int(ids[0]) == lp["token_id"]
        vals = [top[i] for i in ids]
        assert vals == sorted(vals, reverse=True)
        assert abs(vals[0] - lp["logprob"]) < 1e-5


def test_warmup_falls_back_to_xla_when_pallas_cannot_compile(hf_model_dir):
    """attention_impl auto + a Pallas path that cannot compile on this
    backend → warmup flips the engine to XLA instead of leaving a bomb
    for the first request (pallas_call is uncompilable on CPU without
    interpret mode, which makes this a REAL failure-path test)."""
    cfg = ModelConfig.from_model_dir(hf_model_dir)
    cfg.attention_impl = "auto"
    econfig = EngineConfig(
        model=cfg, max_batch_size=2, max_model_len=64, kv_block_size=8,
        num_kv_blocks=32, dtype="float32", prefill_buckets=[16],
    )
    params = load_llama_params(hf_model_dir, cfg, jnp.float32)
    runner = ModelRunner(econfig, params=params)
    from dynamo_tpu.ops import attention as attn_mod

    orig = attn_mod.resolve_attention_impl
    try:
        # force 'auto' to resolve to pallas as it would on TPU
        attn_mod.resolve_attention_impl = (
            lambda impl: "pallas" if impl == "auto" else orig(impl)
        )
        runner._build_step()
        runner.warmup()
    finally:
        attn_mod.resolve_attention_impl = orig
    assert cfg.attention_impl == "xla"
    # and the engine actually serves afterwards
    out, *_ = runner.step(
        np.zeros((2, 1), np.int32), np.zeros((2, 1), np.int32),
        np.zeros((2, 8), np.int32), np.full((2, 1), -1, np.int32),
        np.ones(2, np.int32), np.zeros(2, np.int32),
        np.zeros(2, np.float32), np.zeros(2, np.int32),
        np.ones(2, np.float32), jax.random.PRNGKey(0),
    )
    assert np.asarray(out).shape == (2,)


@pytest.mark.asyncio
async def test_prompt_logprobs_honored(hf_model_dir, hf_logits):
    """OutputOptions.prompt_logprobs (reference common.rs:320-341) must be
    HONORED: one entry per prompt token (first None), matching the
    model's actual next-token log-softmax, independent of chunking and
    of a warm prefix cache."""
    prompt, ref_logits, _ = hf_logits
    mdc = ModelDeploymentCard.from_local_path(hf_model_dir)
    cfg = ModelConfig.from_model_dir(hf_model_dir)
    econfig = EngineConfig(
        model=cfg, max_batch_size=2, max_model_len=128, kv_block_size=8,
        num_kv_blocks=64, dtype="float32", prefill_buckets=[4, 16],
        max_prefill_tokens_per_step=4,  # force multi-chunk prefill
    )
    engine = await JaxServingEngine.create(
        mdc, engine_config=econfig, warmup=False
    )

    async def one():
        req = PreprocessedRequest(
            token_ids=prompt,
            stop_conditions=StopConditions(max_tokens=2, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
            output_options=OutputOptions(prompt_logprobs=0),
        )
        outs = []
        async for out in engine.generate(Context(req)):
            outs.append(out)
        return outs

    outs = await one()
    plps = outs[0]["prompt_logprobs"]
    assert plps is not None and len(plps) == len(prompt)
    assert plps[0] is None
    # expected: log_softmax of the HF reference logits at each next token
    ref = np.asarray(ref_logits, np.float64)
    ref_lse = np.log(np.sum(np.exp(ref - ref.max(-1, keepdims=True)), -1))
    for i in range(1, len(prompt)):
        want = ref[i - 1, prompt[i]] - ref[i - 1].max() - ref_lse[i - 1]
        assert abs(plps[i] - want) < 5e-3, (i, plps[i], want)
    # later outputs don't repeat them
    assert all(o.get("prompt_logprobs") is None for o in outs[1:])

    # a warm prefix cache must not swallow positions: run the SAME prompt
    # again (its blocks are now cached) — full-length result, same values
    outs2 = await one()
    plps2 = outs2[0]["prompt_logprobs"]
    assert len(plps2) == len(prompt)
    np.testing.assert_allclose(
        [x for x in plps2[1:]], [x for x in plps[1:]], rtol=1e-5, atol=1e-6
    )
    await engine.close()


@pytest.mark.asyncio
async def test_prompt_logprobs_absent_by_default(hf_model_dir):
    mdc = ModelDeploymentCard.from_local_path(hf_model_dir)
    cfg = ModelConfig.from_model_dir(hf_model_dir)
    econfig = EngineConfig(
        model=cfg, max_batch_size=2, max_model_len=64, kv_block_size=8,
        num_kv_blocks=32, dtype="float32", prefill_buckets=[16],
    )
    engine = await JaxServingEngine.create(
        mdc, engine_config=econfig, warmup=False
    )
    req = PreprocessedRequest(
        token_ids=[1, 5, 9],
        stop_conditions=StopConditions(max_tokens=2, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0),
    )
    async for out in engine.generate(Context(req)):
        assert out.get("prompt_logprobs") is None
    await engine.close()


@pytest.mark.asyncio
async def test_prompt_scoring_max_tokens_zero(hf_model_dir):
    """The OpenAI prompt-scoring idiom (echo + logprobs + max_tokens=0)
    must run the prefill for its logits and return prompt_logprobs with
    NO generated token — not short-circuit to an empty response."""
    mdc = ModelDeploymentCard.from_local_path(hf_model_dir)
    cfg = ModelConfig.from_model_dir(hf_model_dir)
    econfig = EngineConfig(
        model=cfg, max_batch_size=2, max_model_len=64, kv_block_size=8,
        num_kv_blocks=32, dtype="float32", prefill_buckets=[16],
    )
    engine = await JaxServingEngine.create(
        mdc, engine_config=econfig, warmup=False
    )
    prompt = [1, 17, 43, 99, 7]
    req = PreprocessedRequest(
        token_ids=prompt,
        stop_conditions=StopConditions(max_tokens=0),
        sampling_options=SamplingOptions(temperature=0.0),
        output_options=OutputOptions(prompt_logprobs=0),
    )
    outs = [o async for o in engine.generate(Context(req))]
    assert outs[0].get("prompt_logprobs") is not None
    assert len(outs[0]["prompt_logprobs"]) == len(prompt)
    assert all(not o.get("token_ids") for o in outs)
    assert outs[-1]["finish_reason"] == "length"

    # plain max_tokens=0 (no prompt_logprobs) still short-circuits
    req2 = PreprocessedRequest(
        token_ids=prompt,
        stop_conditions=StopConditions(max_tokens=0),
        sampling_options=SamplingOptions(temperature=0.0),
    )
    outs2 = [o async for o in engine.generate(Context(req2))]
    assert outs2 == [{"token_ids": [], "finish_reason": "length"}]
    await engine.close()


def test_extra_engine_args_override_model_and_engine_fields(hf_model_dir):
    """--extra-engine-args JSON passthrough (reference: dynamo-run
    flags.rs:175): ModelConfig keys hit the model config, EngineConfig
    keys the engine config; a max_model_len override re-derives the
    prefill-bucket ladder; unknown keys fail loudly."""
    from dynamo_tpu.engine.serving import engine_config_from_mdc

    mdc = ModelDeploymentCard.from_local_path(hf_model_dir)
    cfg = engine_config_from_mdc(
        mdc, extra={"attention_impl": "pallas", "num_kv_blocks": 77}
    )
    assert cfg.model.attention_impl == "pallas"
    assert cfg.num_kv_blocks == 77

    # max_model_len override must re-derive buckets past the old top
    small = engine_config_from_mdc(mdc)
    bigger = engine_config_from_mdc(
        mdc, extra={"max_model_len": 4 * small.max_model_len}
    )
    assert bigger.max_model_len == 4 * small.max_model_len
    assert bigger.prefill_buckets[-1] >= bigger.max_model_len \
        or bigger.prefill_buckets[-1] > small.prefill_buckets[-1]
    assert bigger.bucket_for(small.prefill_buckets[-1] + 1)

    with pytest.raises(ValueError, match="no ModelConfig or EngineConfig"):
        engine_config_from_mdc(mdc, extra={"not_a_field": 1})
