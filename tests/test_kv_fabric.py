"""Cluster KV fabric: content-addressed cold tier, cross-worker prefix
pull, chaos fallbacks, router cold scoring, and recovery peer ranking.

The differential contract everywhere: a pulled/rehydrated prefix must
produce a BYTE-IDENTICAL stream to a full local recompute, and every
failure path (dead peer, mid-stream drop, stall past the deadline,
corrupt spill file) must fall back to local recompute with zero leaked
blocks on both sides.
"""

import asyncio
import os
import struct

import numpy as np
import pytest

from dynamo_tpu.engine.block_allocator import KvEventSink
from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.model_runner import ModelRunner
from dynamo_tpu.engine.scheduler import Scheduler
from dynamo_tpu.kv import KvColdTier, KvHostTier
from dynamo_tpu.kv_router.indexer import KvIndexer
from dynamo_tpu.kv_router.protocols import (
    ForwardPassMetrics,
    KvCacheRemoved,
    KvCacheStored,
    RouterEvent,
)
from dynamo_tpu.kv_router.scheduler import KvScheduler
from dynamo_tpu.models.loader import load_llama_params
from dynamo_tpu.telemetry.flight import FlightRecorder
from dynamo_tpu.tokens import compute_block_hashes
from dynamo_tpu.utils import faults

import jax.numpy as jnp

from test_disagg import _collect, _greedy_request
from test_jax_engine import hf_model_dir, hf_logits, TINY  # noqa: F401


# ---------------------------------------------------------------- cold tier


def _blk(seed, shape=(2, 1, 4, 2, 3)):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


def test_cold_tier_roundtrip_is_content_addressed(tmp_path):
    """Worker A writes, worker B (a fresh instance over the same dir —
    the respawn/shared-mount case) rehydrates by sequence hash."""
    a = KvColdTier(str(tmp_path), capacity_blocks=8)
    k1, v1 = _blk(0), _blk(1)
    k2, v2 = _blk(2), _blk(3)
    a.put(101, k1, v1, parent_hash=None)
    a.put(202, k2, v2, parent_hash=101)
    assert a.has(101) and a.has(202)
    assert a.match_extension([101, 202, 999], 0) == [101, 202]

    primed = []
    b = KvColdTier(str(tmp_path), capacity_blocks=8,
                   on_stored=lambda hs, parent: primed.extend(hs))
    assert not b.has(101)  # fresh index until primed
    assert b.refresh() == 2
    assert b.has(101) and b.has(202)
    # the respawn-warm priming ADVERTISES the inventory (tier="cold"
    # events) so routers/peers can score the rehydratable prefixes
    assert sorted(primed) == [101, 202]
    gk, gv = b.get(101)
    np.testing.assert_array_equal(gk, k1)
    np.testing.assert_array_equal(gv, v1)
    gk2, _ = b.get(202)
    np.testing.assert_array_equal(gk2, k2)


def test_cold_tier_corrupt_and_truncated_are_misses(tmp_path):
    """A failed verification is a MISS, never an install: corrupt files
    are quarantined and counted."""
    tier = KvColdTier(str(tmp_path), capacity_blocks=8)
    tier.put(111, _blk(0), _blk(1))
    tier.put(222, _blk(2), _blk(3))
    tier.put(333, _blk(4), _blk(5))

    # flip a payload byte → checksum mismatch
    p1 = os.path.join(str(tmp_path), f"{111:016x}.kvb")
    raw = bytearray(open(p1, "rb").read())
    raw[-3] ^= 0xFF
    open(p1, "wb").write(bytes(raw))
    assert tier.get(111) is None
    assert not os.path.exists(p1)  # quarantined
    assert not tier.has(111)

    # truncate mid-payload
    p2 = os.path.join(str(tmp_path), f"{222:016x}.kvb")
    raw = open(p2, "rb").read()
    open(p2, "wb").write(raw[: len(raw) // 2])
    assert tier.get(222) is None
    assert not os.path.exists(p2)

    # a renamed (mis-addressed) file must not serve under the new hash
    p3 = os.path.join(str(tmp_path), f"{333:016x}.kvb")
    p4 = os.path.join(str(tmp_path), f"{444:016x}.kvb")
    os.rename(p3, p4)
    fresh = KvColdTier(str(tmp_path), capacity_blocks=8)
    fresh.refresh()
    assert fresh.get(444) is None  # header hash mismatch → corrupt miss


def test_cold_tier_capacity_evicts_oldest(tmp_path):
    tier = KvColdTier(str(tmp_path), capacity_blocks=2)
    for i, h in enumerate([11, 22, 33]):
        tier.put(h, _blk(i), _blk(i + 10))
        # distinct mtimes on coarse-granularity filesystems
        os.utime(os.path.join(str(tmp_path), f"{h:016x}.kvb"),
                 (1000 + i, 1000 + i))
        tier._enforce_capacity()
    assert not tier.has(11) and tier.has(22) and tier.has(33)
    assert not os.path.exists(
        os.path.join(str(tmp_path), f"{11:016x}.kvb"))


def test_host_tier_spills_to_cold_on_eviction(tmp_path):
    """The host tier's capacity eviction is the cold tier's spill
    source — and the spill announces cold ownership via the event
    hooks."""
    stored_cold = []
    cold = KvColdTier(str(tmp_path), capacity_blocks=8,
                      on_stored=lambda hs, parent: stored_cold.extend(hs))
    data = {}

    def gather(ids):
        k = np.stack([data[i] for i in ids])[None]
        return k, k.copy()

    tier = KvHostTier(gather, lambda ids, k, v: None, capacity_blocks=1,
                      on_evict=cold.offer)
    for bid, h in [(0, 100), (1, 101)]:
        data[bid] = np.full(4, bid, np.float32)
        tier.offload(h, bid)
    tier.drain()  # capacity 1 → hash 100 evicted → spilled to cold
    assert not tier.has(100) and tier.has(101)
    assert cold.has(100)
    assert stored_cold == [100]
    gk, _ = cold.get(100)
    np.testing.assert_array_equal(gk, np.full(4, 0, np.float32)[None][None])


async def test_cold_event_hooks_marshal_onto_the_loop(tmp_path):
    """The ownership hooks feed loop-bound machinery (the KV event
    publisher's asyncio queue), but spill writes run on the executor —
    the hook must come back on the event loop thread, not fire from
    the worker thread."""
    import threading

    loop_thread = threading.current_thread()
    seen = []

    def on_stored(hashes, parent):
        seen.append((threading.current_thread() is loop_thread,
                     list(hashes)))

    cold = KvColdTier(str(tmp_path), capacity_blocks=8,
                      on_stored=on_stored)
    cold.offer(7, _blk(0), _blk(1))
    await cold.close()  # the write itself has landed...
    for _ in range(50):  # ...now let call_soon_threadsafe deliver
        if seen:
            break
        await asyncio.sleep(0.01)
    assert seen == [(True, [7])]
    assert cold.has(7)


# ------------------------------------------------------------ router scoring


def _stored(worker, hashes, parent=None, tier="hbm"):
    return RouterEvent(worker_id=worker,
                       stored=KvCacheStored(hashes, parent), tier=tier)


def test_indexer_scores_cold_ownership_separately():
    idx = KvIndexer(block_size=4)
    chain = [1, 2, 3, 4]
    idx.apply_event(_stored("w1", chain[:2]))             # warm 2
    idx.apply_event(_stored("w1", chain[2:], 2, "cold"))  # +2 cold
    idx.apply_event(_stored("w2", chain, tier="cold"))    # 4 cold only
    out = idx.find_matches(chain)
    assert out.scores == {"w1": 2}
    assert out.cold_scores == {"w1": 2, "w2": 4}
    # cold removal shrinks the run
    idx.apply_event(RouterEvent(worker_id="w2",
                                removed=KvCacheRemoved([2]), tier="cold"))
    out = idx.find_matches(chain)
    assert out.cold_scores["w2"] == 1
    idx.remove_worker("w1")
    out = idx.find_matches(chain)
    assert "w1" not in out.scores and "w1" not in out.cold_scores


def test_kv_scheduler_discounts_cold_hits_and_reports_pull_hint():
    sched = KvScheduler(block_size=4, cold_discount=0.5)
    m = ForwardPassMetrics(request_active_slots=0, request_total_slots=8,
                           kv_active_blocks=0, kv_total_blocks=100)
    sched.update_metrics("warm", m)
    sched.update_metrics("cold", m)
    from dynamo_tpu.kv_router.indexer import OverlapScores

    # equal coverage: 4 warm blocks beat 4 cold blocks
    overlap = OverlapScores(scores={"warm": 4},
                            cold_scores={"cold": 4})
    d = sched.schedule(16, overlap)
    assert d.worker_id == "warm"
    assert d.best_prefix_worker == "warm"

    # an 8-block cold owner out-scores a 2-block warm one at 0.5 discount
    sched2 = KvScheduler(block_size=4, cold_discount=0.5)
    sched2.update_metrics("warm", m)
    sched2.update_metrics("cold", m)
    overlap = OverlapScores(scores={"warm": 2},
                            cold_scores={"cold": 8})
    d = sched2.schedule(40, overlap)
    assert d.worker_id == "cold"
    assert d.cold_blocks == 8
    assert d.best_prefix_worker == "cold"
    assert d.best_prefix_blocks == 8


def test_recovery_peer_ranking_prefers_prefix_owner():
    """The PR 8 carry-over: migration targets rank by the fabric's
    ownership view instead of discovery order — joined through the
    descriptor's ``worker_id`` (KV-event id namespace), NOT the
    migration plane's engine_id, which is a different uuid."""
    from dynamo_tpu.kv import KvFabric
    from dynamo_tpu.recovery.controller import RecoveryController

    fab = KvFabric(runner=None, allocator=None, engine_id="self-w",
                   block_size=4)
    prompt = list(range(1, 13))
    chain = compute_block_hashes(prompt, 4)
    fab.apply_event(_stored("w-b", chain))  # KV events key by worker id
    peers = [
        {"engine_id": "eng-a", "worker_id": "w-a", "host": "h", "port": 1},
        {"engine_id": "eng-b", "worker_id": "w-b", "host": "h", "port": 2},
        {"engine_id": "self", "host": "h", "port": 3},
    ]
    ctl = RecoveryController(engine_id="self", peers=lambda: peers,
                             peer_ranker=fab.rank_peers)

    er = type("_Er", (), {"prompt": prompt})()
    ranked = ctl._candidate_peers(er)
    assert [p["engine_id"] for p in ranked] == ["eng-b", "eng-a"]
    # without a request, discovery order is preserved (self still excluded)
    assert [p["engine_id"] for p in ctl._candidate_peers()] == [
        "eng-a", "eng-b"]


# ------------------------------------------------------------------ e2e rigs


def _fabric_config(hf_model_dir, **overrides):
    cfg = ModelConfig.from_model_dir(hf_model_dir)
    kw = dict(
        max_batch_size=4, max_model_len=128, kv_block_size=8,
        num_kv_blocks=64, dtype="float32", prefix_pull=True,
        prefix_pull_min_blocks=2, prefix_pull_timeout_s=10.0,
    )
    kw.update(overrides)
    return cfg, EngineConfig(model=cfg, **kw)


def _engine(hf_model_dir, events=None, **overrides):
    cfg, econfig = _fabric_config(hf_model_dir, **overrides)
    params = load_llama_params(hf_model_dir, cfg, jnp.float32)
    runner = ModelRunner(econfig, params=params)
    # private flight ring per engine: the rigs run several engines in
    # one process, and the assertions below must not see each other's
    # (or earlier tests') events through the process-global recorder
    sched = Scheduler(runner, econfig, events=events,
                      flight=FlightRecorder())
    return sched


def _events(sched, kind):
    """This engine's flight events of one ``kind``, with the recorded
    keyword payload flattened out of the nested ``data`` dict."""
    return [{**e.get("data", {}), **e}
            for e in sched.flight.snapshot() if e.get("kind") == kind]


SHARED_PREFIX = [1, 17, 43, 99, 7, 3, 250, 12, 5, 77, 8, 21,
                 33, 44, 55, 66, 9, 2, 120, 14, 71, 88, 19, 4]  # 3 blocks


async def _run_one(sched, prompt, rid, max_tokens=6):
    er = _greedy_request(rid, prompt, max_tokens=max_tokens)
    sched.add_request(er)
    return await _collect(er)


def _assert_no_leaks(sched):
    assert not sched.allocator.pinned, "leaked pins"
    assert not sched.allocator.refcount, "leaked block refs"


def _wire_a_to_b(sched_b, worker_id="worker-a"):
    """KV event sink for engine A that feeds B's fabric ownership view
    (the same RouterEvent stream the router would relay)."""
    def on_stored(hashes, parent):
        sched_b.fabric.apply_event(_stored(worker_id, list(hashes), parent))

    def on_removed(hashes):
        sched_b.fabric.apply_event(RouterEvent(
            worker_id=worker_id, removed=KvCacheRemoved(list(hashes))))

    return KvEventSink(on_stored=on_stored, on_removed=on_removed)


async def _two_engine_rig(hf_model_dir):
    """B's fabric sees A's KV events and pulls from A's serve half."""
    sched_b = _engine(hf_model_dir)
    sched_a = _engine(hf_model_dir, events=_wire_a_to_b(sched_b))
    server_a = await sched_a.fabric.serve()
    sched_b.fabric.peers = (
        lambda: {"worker-a": {"host": "127.0.0.1", "port": server_a.port}}
    )
    sched_a.start()
    sched_b.start()
    return sched_a, sched_b


async def test_prefix_pull_from_peer_byte_identical(hf_model_dir):
    """The headline differential: worker A computed a shared prefix;
    worker B pulls it instead of recomputing, streams byte-identically,
    and prefills only the un-matched tail."""
    prompt_a = SHARED_PREFIX + [30, 31, 32, 33, 34, 35]
    prompt_b = SHARED_PREFIX + [40, 41, 42, 43, 44, 45]

    # recompute baseline for prompt_b on a fresh engine
    sched_base = _engine(hf_model_dir)
    sched_base.start()
    baseline = await _run_one(sched_base, prompt_b, "base")
    await sched_base.stop()

    sched_a, sched_b = await _two_engine_rig(hf_model_dir)
    try:
        await _run_one(sched_a, prompt_a, "warm")  # A now owns the prefix

        # spy B's prefill work: positions actually computed per step
        real_step = sched_b.runner.step
        prefill_positions = []

        def spy_step(tokens, positions, btab, slot_map, *a, **kw):
            if tokens.shape[1] > 1:  # prefill-shaped (decode is S=1)
                prefill_positions.append(int((slot_map >= 0).sum()))
            return real_step(tokens, positions, btab, slot_map, *a, **kw)

        sched_b.runner.step = spy_step
        out = await _run_one(sched_b, prompt_b, "pulled")
        assert out == baseline, "pulled prefix diverged from recompute"

        # the pull committed: 3 shared blocks = 24 tokens never recomputed
        assert sched_b.prefix_hit_tokens == 24
        assert sched_b.prefix_total_tokens == len(prompt_b)
        # B's prefill covered ONLY the 6-token tail
        assert sum(prefill_positions) == len(prompt_b) - 24
        pulls = _events(sched_b, "scheduler.pull_commit")
        assert pulls and pulls[-1]["blocks"] == 3
        assert pulls[-1]["source"] == "peer"
        _assert_no_leaks(sched_b)
    finally:
        await sched_a.stop()
        await sched_b.stop()
    _assert_no_leaks(sched_a)


async def test_prefix_pull_conn_drop_falls_back_byte_identical(hf_model_dir):
    """Chaos: the serving side dies mid-pull → local recompute, byte-
    identical, zero leaked blocks on BOTH sides."""
    prompt_a = SHARED_PREFIX + [30, 31, 32, 33, 34, 35]
    prompt_b = SHARED_PREFIX + [40, 41, 42, 43, 44, 45]
    sched_base = _engine(hf_model_dir)
    sched_base.start()
    baseline = await _run_one(sched_base, prompt_b, "base")
    await sched_base.stop()

    sched_a, sched_b = await _two_engine_rig(hf_model_dir)
    try:
        await _run_one(sched_a, prompt_a, "warm")
        faults.arm("transfer_conn_drop", "once")
        out = await _run_one(sched_b, prompt_b, "dropped")
        assert out == baseline
        falls = _events(sched_b, "kv_fabric.local_fallback")
        assert falls, "expected a local fallback after the drop"
        _assert_no_leaks(sched_b)
    finally:
        faults.reset()
        await sched_a.stop()
        await sched_b.stop()
    _assert_no_leaks(sched_a)


async def test_prefix_pull_stall_times_out_and_falls_back(hf_model_dir):
    """Chaos: a stalled pull must never hold the request — the deadline
    cancels it and the stream still matches the recompute baseline."""
    prompt_a = SHARED_PREFIX + [30, 31, 32, 33, 34, 35]
    prompt_b = SHARED_PREFIX + [40, 41, 42, 43, 44, 45]
    sched_base = _engine(hf_model_dir)
    sched_base.start()
    baseline = await _run_one(sched_base, prompt_b, "base")
    await sched_base.stop()

    sched_b = _engine(hf_model_dir, prefix_pull_timeout_s=0.5)
    sched_a = _engine(hf_model_dir, events=_wire_a_to_b(sched_b))
    server_a = await sched_a.fabric.serve()
    sched_b.fabric.peers = (
        lambda: {"worker-a": {"host": "127.0.0.1", "port": server_a.port}}
    )
    sched_a.start()
    sched_b.start()
    try:
        await _run_one(sched_a, prompt_a, "warm")
        faults.arm("prefix_pull_stall", "once")
        out = await _run_one(sched_b, prompt_b, "stalled")
        assert out == baseline
        falls = _events(sched_b, "kv_fabric.local_fallback")
        assert falls and falls[-1]["reason"] == "timeout"
        _assert_no_leaks(sched_b)
    finally:
        faults.reset()
        await sched_a.stop()
        await sched_b.stop()
    _assert_no_leaks(sched_a)


async def test_cold_tier_rehydrates_after_respawn(hf_model_dir, tmp_path):
    """The respawn-warm acceptance path: spill a prefix through host-
    tier eviction, kill the engine, and a fresh engine over the same
    cold directory rehydrates instead of fully recomputing."""
    cold_dir = str(tmp_path / "cold")
    prompt = SHARED_PREFIX + [30, 31, 32, 33, 34, 35]
    # 34 fresh tokens against 6 HBM blocks: allocating the evictor must
    # evict the first prompt's cached blocks → host tier (capacity 1)
    # → overflow spills the prefix to the cold tier
    evictor = [2] + list(range(90, 123))

    def mk(**kw):
        return _engine(
            hf_model_dir, num_kv_blocks=6, max_model_len=64,
            host_kv_blocks=1, cold_tier_dir=cold_dir, cold_tier_blocks=32,
            prefix_pull_min_blocks=1, **kw,
        )

    sched1 = mk()
    sched1.start()
    baseline = await _run_one(sched1, prompt, "first")
    # a second prompt evicts the first one's HBM blocks → host tier
    # (capacity 1) → overflow spills to the cold tier
    await _run_one(sched1, evictor, "evictor")
    await sched1.stop()  # drains spill writes (fabric.close → cold.close)
    assert len(os.listdir(cold_dir)) >= 2

    # "respawn": a fresh engine over the same directory, nothing in HBM
    # or host RAM
    sched2 = mk()
    assert sched2.fabric.cold.refresh() >= 2  # the cli wiring's priming
    sched2.start()
    try:
        out = await _run_one(sched2, prompt, "rehydrated")
        assert out == baseline
        pulls = _events(sched2, "scheduler.pull_commit")
        assert pulls and pulls[-1]["source"] == "cold"
        assert sched2.prefix_hit_tokens == pulls[-1]["blocks"] * 8
        _assert_no_leaks(sched2)
    finally:
        await sched2.stop()


async def test_corrupt_cold_block_is_a_miss_never_installed(
        hf_model_dir, tmp_path):
    """A corrupted spill file mid-run: the pull commits only the verified
    prefix and the stream still matches the recompute baseline."""
    cold_dir = str(tmp_path / "cold")
    prompt = SHARED_PREFIX + [30, 31, 32, 33, 34, 35]
    evictor = [2] + list(range(90, 123))  # see the rehydrate rig above

    def mk():
        return _engine(
            hf_model_dir, num_kv_blocks=6, max_model_len=64,
            host_kv_blocks=1, cold_tier_dir=cold_dir, cold_tier_blocks=32,
            prefix_pull_min_blocks=1,
        )

    sched1 = mk()
    sched1.start()
    baseline = await _run_one(sched1, prompt, "first")
    await _run_one(sched1, evictor, "evictor")
    await sched1.stop()

    # corrupt the LAST spilled prefix block's payload
    chain = compute_block_hashes(prompt, 8)
    spilled = [h for h in chain
               if os.path.exists(os.path.join(cold_dir, f"{h:016x}.kvb"))]
    assert len(spilled) >= 2
    victim = os.path.join(cold_dir, f"{spilled[-1]:016x}.kvb")
    raw = bytearray(open(victim, "rb").read())
    raw[-1] ^= 0xFF
    open(victim, "wb").write(bytes(raw))

    sched2 = mk()
    sched2.fabric.cold.refresh()
    sched2.start()
    try:
        out = await _run_one(sched2, prompt, "partial")
        assert out == baseline
        pulls = _events(sched2, "scheduler.pull_commit")
        # only the verified run installed; the corrupt block recomputed
        assert pulls and pulls[-1]["blocks"] == len(spilled) - 1
        _assert_no_leaks(sched2)
    finally:
        await sched2.stop()


# ------------------------------------------------- pool-scoped discovery


def test_pool_scope_peers_filters_by_model_metadata():
    """Two model pools share one component: the peer filter keeps the
    same-pool peer, drops the other pool's, and treats a missing record
    or missing metadata as a wildcard (single-pool deployments)."""
    import msgpack

    from dynamo_tpu.cli.run import _pool_scope_peers

    def rec(wid, model=None):
        info = {"instance_id": wid, "subject": "s", "worker_id": wid}
        if model is not None:
            info["model"] = model
        return msgpack.packb(info, use_bin_type=True)

    eps = {
        "ns/components/backend/endpoints/generate:w-a2": rec("w-a2", "modelA"),
        "ns/components/backend/endpoints/generate:w-b1": rec("w-b1", "modelB"),
        "ns/components/backend/endpoints/generate:w-any": rec("w-any"),
        "ns/components/backend/endpoints/generate:w-junk": b"\x00not-msgpack",
    }
    peers = {w: {"engine_id": w, "host": "h", "port": 1}
             for w in ("w-a2", "w-b1", "w-any", "w-junk", "w-norec")}

    scoped, live = _pool_scope_peers(peers, eps, "modelA")
    # same pool + wildcards survive; the other pool is invisible
    assert set(scoped) == {"w-a2", "w-any", "w-junk", "w-norec"}
    # liveness stays pool-agnostic: every registered id counts
    assert live == {"w-a2", "w-b1", "w-any", "w-junk"}

    # no model (pre-pool deployments): the filter is a no-op
    unscoped, _ = _pool_scope_peers(peers, eps, "")
    assert set(unscoped) == set(peers)


async def test_fabric_peer_refresh_is_pool_scoped():
    """End-to-end through _setup_kv_fabric against an in-process
    discovery plane: two pools registered on ONE shared component; this
    worker's peer cache must only ever hold its own pool (plus
    wildcards), while dead-id pruning still spans the component."""
    import types

    import msgpack

    from dynamo_tpu.cli.run import _setup_kv_fabric
    from dynamo_tpu.kv.fabric import fabric_key
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.transports.memory import MemoryHub

    class _StubServer:
        port = 7

    class _StubFabric:
        # just the surface _setup_kv_fabric wires; the filter under
        # test runs against the real discovery records
        peer_pull = True
        cold = None

        def __init__(self, engine_id):
            self.engine_id = engine_id
            self.indexer = types.SimpleNamespace(worker_ids=[])
            self.removed = []
            self.held = []
            self.peers = lambda: {}

        async def serve(self, host=""):
            return _StubServer()

        def remove_worker(self, wid):
            self.removed.append(wid)
            if wid in self.indexer.worker_ids:
                self.indexer.worker_ids.remove(wid)

        def hold_task(self, task):
            self.held.append(task)

        def apply_event(self, ev):
            pass

    drt = DistributedRuntime.in_process(MemoryHub())
    endpoint = drt.namespace("ns").component("backend").endpoint("generate")
    lease = await drt.discovery.primary_lease()

    async def register(wid, model):
        await drt.discovery.kv_create(
            endpoint.etcd_key(wid),
            msgpack.packb({"instance_id": wid, "subject": "s",
                           "worker_id": wid, "model": model},
                          use_bin_type=True),
            lease_id=lease.id)
        await drt.discovery.kv_put(
            fabric_key("ns", "backend", wid),
            msgpack.packb({"host": "h", "port": 1, "engine_id": wid},
                          use_bin_type=True),
            lease_id=lease.id)

    await register("w-a1", "modelA")      # self
    await register("w-a2", "modelA")      # same pool → visible peer
    await register("w-b1", "modelB")      # other pool → filtered
    fab = _StubFabric("w-a1")
    # a dead incarnation's hash runs linger in the ownership view
    fab.indexer.worker_ids.extend(["w-dead", "w-b1"])
    core = types.SimpleNamespace(
        scheduler=types.SimpleNamespace(fabric=fab))
    flags = types.SimpleNamespace(namespace="ns", advertise_host="127.0.0.1")

    out = await _setup_kv_fabric(
        flags, core, drt=drt, component="backend", endpoint=endpoint,
        instance_id="w-a1", model="modelA")
    try:
        assert out is fab
        assert set(fab.peers()) == {"w-a2"}          # not self, not modelB
        assert "w-dead" in fab.removed               # lease-based prune
        assert "w-b1" not in fab.removed             # alive, just scoped out
    finally:
        for task in fab.held:
            task.cancel()
        await asyncio.gather(*fab.held, return_exceptions=True)
