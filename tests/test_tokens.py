"""Tests for token block hashing (dynamo_tpu/tokens.py)."""

from dynamo_tpu.tokens import (
    TokenSequence,
    chain_hash,
    compute_block_hash,
    compute_block_hashes,
)


def test_block_hash_content_addressed():
    assert compute_block_hash([1, 2, 3]) == compute_block_hash([1, 2, 3])
    assert compute_block_hash([1, 2, 3]) != compute_block_hash([1, 2, 4])
    # seed changes the hash
    assert compute_block_hash([1, 2, 3], seed=1) != compute_block_hash([1, 2, 3], seed=2)


def test_sequence_hash_is_position_dependent():
    # same block content at different prefix positions → different sequence hash
    hashes = compute_block_hashes([5, 5, 5, 5, 5, 5, 5, 5], block_size=4)
    assert len(hashes) == 2
    assert hashes[0] != hashes[1]
    # but the chained construction is deterministic
    bh = compute_block_hash([5, 5, 5, 5])
    assert hashes[0] == bh
    assert hashes[1] == chain_hash(hashes[0], bh)


def test_compute_block_hashes_ignores_partial_tail():
    full = compute_block_hashes(list(range(8)), block_size=4)
    ragged = compute_block_hashes(list(range(10)), block_size=4)
    assert full == ragged


def test_shared_prefix_shares_hashes():
    a = compute_block_hashes(list(range(16)) + [99] * 4, block_size=4)
    b = compute_block_hashes(list(range(16)) + [42] * 4, block_size=4)
    assert a[:4] == b[:4]
    assert a[4] != b[4]


def test_token_sequence_incremental_matches_batch():
    ids = list(range(37))
    seq = TokenSequence(block_size=4)
    for t in ids:
        seq.push(t)
    assert seq.token_ids == ids
    assert len(seq) == 37
    assert len(seq.blocks) == 9
    assert len(seq.tail) == 1
    assert seq.sequence_hashes() == compute_block_hashes(ids, block_size=4)


def test_token_sequence_extend_returns_completed():
    seq = TokenSequence(block_size=4)
    assert seq.extend([1, 2, 3]) == []
    done = seq.extend([4, 5])
    assert len(done) == 1
    assert done[0].tokens == (1, 2, 3, 4)
    assert done[0].position == 0
    assert done[0].parent_sequence_hash is None


def test_token_sequence_init_with_tokens():
    seq = TokenSequence(list(range(10)), block_size=4)
    assert len(seq.blocks) == 2
    assert seq.blocks[1].parent_sequence_hash == seq.blocks[0].sequence_hash


def test_spm_tokenizer_model_loading(tmp_path):
    """tokenizer.model-only snapshots load via the SPM protobuf path."""
    from transformers.convert_slow_tokenizer import import_protobuf

    from dynamo_tpu.llm.tokenizer import HFTokenizer

    model_pb2 = import_protobuf()
    proto = model_pb2.ModelProto()
    pieces = [
        ("<unk>", 0.0, 2), ("<s>", 0.0, 3), ("</s>", 0.0, 3),
        ("▁hello", -1.0, 1), ("▁world", -1.0, 1),
        ("▁", -2.0, 1), ("h", -5.0, 1), ("e", -5.0, 1),
        ("l", -5.0, 1), ("o", -5.0, 1), ("w", -5.0, 1), ("r", -5.0, 1),
        ("d", -5.0, 1),
    ]
    for piece, score, tp in pieces:
        p = proto.pieces.add()
        p.piece, p.score, p.type = piece, score, tp
    proto.trainer_spec.unk_id = 0
    path = tmp_path / "tokenizer.model"
    path.write_bytes(proto.SerializeToString())

    tok = HFTokenizer.from_pretrained_dir(str(tmp_path))
    ids = tok.encode("hello world")
    assert tok.decode(ids) == "hello world"
    assert tok.id_to_token(ids[0]) == "▁hello"


def test_spm_bpe_model_type(tmp_path):
    """SPM BPE protos (model_type=2, original Llama exports) reconstruct
    merge order from vocab ranks."""
    from transformers.convert_slow_tokenizer import import_protobuf

    from dynamo_tpu.llm.tokenizer import HFTokenizer

    model_pb2 = import_protobuf()
    proto = model_pb2.ModelProto()
    # ranks encode merge priority: he < ll < llo < hello
    pieces = [
        ("<unk>", 0.0, 2), ("<s>", 0.0, 3),
        ("▁", -1.0, 1),
        ("h", -2.0, 1), ("e", -2.0, 1), ("l", -2.0, 1), ("o", -2.0, 1),
        ("he", -3.0, 1), ("ll", -3.5, 1), ("llo", -4.0, 1),
        ("hello", -5.0, 1),
    ]
    for piece, score, tp in pieces:
        p = proto.pieces.add()
        p.piece, p.score, p.type = piece, score, tp
    proto.trainer_spec.unk_id = 0
    proto.trainer_spec.model_type = 2  # BPE
    (tmp_path / "tokenizer.model").write_bytes(proto.SerializeToString())

    tok = HFTokenizer.from_pretrained_dir(str(tmp_path))
    ids = tok.encode("hello")
    names = [tok.id_to_token(i) for i in ids]
    assert names == ["▁", "hello"], names
    assert tok.decode(ids) == "hello"
