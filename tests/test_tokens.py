"""Tests for token block hashing (dynamo_tpu/tokens.py)."""

from dynamo_tpu.tokens import (
    TokenSequence,
    chain_hash,
    compute_block_hash,
    compute_block_hashes,
)


def test_block_hash_content_addressed():
    assert compute_block_hash([1, 2, 3]) == compute_block_hash([1, 2, 3])
    assert compute_block_hash([1, 2, 3]) != compute_block_hash([1, 2, 4])
    # seed changes the hash
    assert compute_block_hash([1, 2, 3], seed=1) != compute_block_hash([1, 2, 3], seed=2)


def test_sequence_hash_is_position_dependent():
    # same block content at different prefix positions → different sequence hash
    hashes = compute_block_hashes([5, 5, 5, 5, 5, 5, 5, 5], block_size=4)
    assert len(hashes) == 2
    assert hashes[0] != hashes[1]
    # but the chained construction is deterministic
    bh = compute_block_hash([5, 5, 5, 5])
    assert hashes[0] == bh
    assert hashes[1] == chain_hash(hashes[0], bh)


def test_compute_block_hashes_ignores_partial_tail():
    full = compute_block_hashes(list(range(8)), block_size=4)
    ragged = compute_block_hashes(list(range(10)), block_size=4)
    assert full == ragged


def test_shared_prefix_shares_hashes():
    a = compute_block_hashes(list(range(16)) + [99] * 4, block_size=4)
    b = compute_block_hashes(list(range(16)) + [42] * 4, block_size=4)
    assert a[:4] == b[:4]
    assert a[4] != b[4]


def test_token_sequence_incremental_matches_batch():
    ids = list(range(37))
    seq = TokenSequence(block_size=4)
    for t in ids:
        seq.push(t)
    assert seq.token_ids == ids
    assert len(seq) == 37
    assert len(seq.blocks) == 9
    assert len(seq.tail) == 1
    assert seq.sequence_hashes() == compute_block_hashes(ids, block_size=4)


def test_token_sequence_extend_returns_completed():
    seq = TokenSequence(block_size=4)
    assert seq.extend([1, 2, 3]) == []
    done = seq.extend([4, 5])
    assert len(done) == 1
    assert done[0].tokens == (1, 2, 3, 4)
    assert done[0].position == 0
    assert done[0].parent_sequence_hash is None


def test_token_sequence_init_with_tokens():
    seq = TokenSequence(list(range(10)), block_size=4)
    assert len(seq.blocks) == 2
    assert seq.blocks[1].parent_sequence_hash == seq.blocks[0].sequence_hash
