"""HTTP frontend tests: real aiohttp server + aiohttp client, SSE + metrics."""

import asyncio
import json

import aiohttp
import pytest

from dynamo_tpu.http.service import (
    HttpService,
    ModelManager,
    ModelWatcher,
    register_model,
    unregister_model,
)
from dynamo_tpu.llm.backend import Backend
from dynamo_tpu.llm.engines.echo import EchoEngineCore, EchoEngineFull
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
from dynamo_tpu.llm.tokenizer import HFTokenizer
from dynamo_tpu.protocols import sse
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.pipeline import build_pipeline
from dynamo_tpu.runtime.transports.memory import MemoryHub

from fixtures import make_model_dir


async def start_echo_service():
    manager = ModelManager()
    manager.add_chat_model("echo", EchoEngineFull())
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    return service


@pytest.mark.asyncio
async def test_models_and_health():
    service = await start_echo_service()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{service.port}/v1/models") as r:
                body = await r.json()
                assert r.status == 200
                assert body["data"][0]["id"] == "echo"
            async with s.get(f"http://127.0.0.1:{service.port}/health") as r:
                assert (await r.json())["status"] == "ok"
    finally:
        await service.stop()


@pytest.mark.asyncio
async def test_chat_streaming_sse():
    service = await start_echo_service()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"http://127.0.0.1:{service.port}/v1/chat/completions",
                json={
                    "model": "echo",
                    "messages": [{"role": "user", "content": "one two three"}],
                    "stream": True,
                },
            ) as r:
                assert r.status == 200
                assert r.headers["Content-Type"].startswith("text/event-stream")
                raw = await r.read()
        payloads = list(sse.parse_stream(raw))
        text = "".join(
            c["choices"][0].get("delta", {}).get("content") or ""
            for c in payloads if c.get("choices")
        )
        assert text.strip() == "one two three"
        assert raw.decode().strip().endswith("data: [DONE]")
    finally:
        await service.stop()


@pytest.mark.asyncio
async def test_chat_non_streaming_aggregates():
    service = await start_echo_service()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"http://127.0.0.1:{service.port}/v1/chat/completions",
                json={
                    "model": "echo",
                    "messages": [{"role": "user", "content": "hello there"}],
                },
            ) as r:
                assert r.status == 200
                body = await r.json()
        assert body["object"] == "chat.completion"
        assert body["choices"][0]["message"]["content"].strip() == "hello there"
        assert body["choices"][0]["finish_reason"] == "stop"
    finally:
        await service.stop()


@pytest.mark.asyncio
async def test_unknown_model_404_and_bad_body_400():
    service = await start_echo_service()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"http://127.0.0.1:{service.port}/v1/chat/completions",
                json={"model": "nope", "messages": [{"role": "user", "content": "x"}]},
            ) as r:
                assert r.status == 404
            async with s.post(
                f"http://127.0.0.1:{service.port}/v1/chat/completions",
                data=b"not json",
            ) as r:
                assert r.status == 400
            async with s.post(
                f"http://127.0.0.1:{service.port}/v1/chat/completions",
                json={"model": "echo"},  # missing messages
            ) as r:
                assert r.status == 400
    finally:
        await service.stop()


@pytest.mark.asyncio
async def test_beam_search_fields_rejected_400():
    """use_beam_search/length_penalty are engine pass-throughs in the
    reference (lib/llm/src/protocols/common.rs:248-316) that no engine here
    honors — they must be rejected loudly, not silently ignored."""
    service = await start_echo_service()
    try:
        async with aiohttp.ClientSession() as s:
            for field, value in (("use_beam_search", True), ("length_penalty", 0.8)):
                async with s.post(
                    f"http://127.0.0.1:{service.port}/v1/chat/completions",
                    json={
                        "model": "echo",
                        "messages": [{"role": "user", "content": "x"}],
                        field: value,
                    },
                ) as r:
                    assert r.status == 400
                    body = await r.json()
                    assert field in body["error"]["message"]
                async with s.post(
                    f"http://127.0.0.1:{service.port}/v1/completions",
                    json={"model": "echo", "prompt": "x", field: value},
                ) as r:
                    assert r.status == 400
            # no-op values (vLLM-client serialized defaults) are allowed:
            # null, use_beam_search=false, length_penalty=1.0
            async with s.post(
                f"http://127.0.0.1:{service.port}/v1/chat/completions",
                json={
                    "model": "echo",
                    "messages": [{"role": "user", "content": "x"}],
                    "use_beam_search": False,
                    "length_penalty": 1.0,
                },
            ) as r:
                assert r.status == 200
            async with s.post(
                f"http://127.0.0.1:{service.port}/v1/chat/completions",
                json={
                    "model": "echo",
                    "messages": [{"role": "user", "content": "x"}],
                    "use_beam_search": None,
                },
            ) as r:
                assert r.status == 200
    finally:
        await service.stop()


@pytest.mark.asyncio
async def test_streaming_validation_error_is_http_400(tmp_path):
    """Oversized prompt with stream=true must get a 400, not a 200-SSE-error."""
    model_dir = make_model_dir(tmp_path)
    mdc = ModelDeploymentCard.from_local_path(model_dir, "tiny")
    tok = HFTokenizer.from_pretrained_dir(model_dir)
    engine = build_pipeline([OpenAIPreprocessor(mdc, tok), Backend(tok)], EchoEngineCore())
    manager = ModelManager()
    manager.add_chat_model("tiny", engine)
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"http://127.0.0.1:{service.port}/v1/chat/completions",
                json={
                    "model": "tiny",
                    "messages": [{"role": "user", "content": "word " * 600}],
                    "stream": True,
                },
            ) as r:
                assert r.status == 400
                body = await r.json()
                assert "exceeds context" in body["error"]["message"]
    finally:
        await service.stop()


@pytest.mark.asyncio
async def test_metrics_exposed():
    service = await start_echo_service()
    try:
        async with aiohttp.ClientSession() as s:
            await s.post(
                f"http://127.0.0.1:{service.port}/v1/chat/completions",
                json={"model": "echo", "messages": [{"role": "user", "content": "x"}]},
            )
            async with s.get(f"http://127.0.0.1:{service.port}/metrics") as r:
                text = await r.text()
        assert 'dynamo_http_service_requests_total{model="echo",status="success"} 1' in text
        assert "dynamo_http_service_request_duration_seconds_bucket" in text
        assert "dynamo_http_service_time_to_first_token_seconds" in text
    finally:
        await service.stop()


@pytest.mark.asyncio
async def test_full_pipeline_over_http(tmp_path):
    """Tokenizing pipeline (preprocessor→backend→echo_core) behind HTTP."""
    model_dir = make_model_dir(tmp_path)
    mdc = ModelDeploymentCard.from_local_path(model_dir, "tiny")
    tok = HFTokenizer.from_pretrained_dir(model_dir)
    engine = build_pipeline([OpenAIPreprocessor(mdc, tok), Backend(tok)], EchoEngineCore())
    manager = ModelManager()
    manager.add_chat_model("tiny", engine)
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"http://127.0.0.1:{service.port}/v1/chat/completions",
                json={
                    "model": "tiny",
                    "messages": [{"role": "user", "content": "the quick brown fox"}],
                    "max_tokens": 64,
                },
            ) as r:
                body = await r.json()
        assert "the quick brown fox" in body["choices"][0]["message"]["content"]
    finally:
        await service.stop()


@pytest.mark.asyncio
async def test_model_watcher_hot_add_remove():
    """Worker registers a model in discovery → frontend hot-adds it."""
    hub = MemoryHub()
    worker_drt = DistributedRuntime.in_process(hub)
    front_drt = DistributedRuntime.in_process(hub)

    # worker serving OpenAI-level requests
    ep = worker_drt.namespace("prod").component("worker").endpoint("generate")

    async def handler(payload, ctx):
        from dynamo_tpu.runtime.engine import Context

        async for chunk in EchoEngineFull().generate(Context(payload, ctx)):
            yield chunk

    serving = await ep.serve(handler)

    manager = ModelManager()
    watcher = ModelWatcher(front_drt, manager, namespace="public")
    await watcher.start()
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    try:
        await register_model(
            worker_drt, "public", "remote-echo", "dyn://prod.worker.generate"
        )
        await asyncio.sleep(0.05)
        assert "remote-echo" in manager.model_names()

        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"http://127.0.0.1:{service.port}/v1/chat/completions",
                json={
                    "model": "remote-echo",
                    "messages": [{"role": "user", "content": "routed hello"}],
                },
            ) as r:
                assert r.status == 200
                body = await r.json()
        assert body["choices"][0]["message"]["content"].strip() == "routed hello"

        await unregister_model(worker_drt, "public", "remote-echo")
        await asyncio.sleep(0.05)
        assert "remote-echo" not in manager.model_names()
    finally:
        await service.stop()
        await watcher.stop()
        await serving.stop()


@pytest.mark.asyncio
async def test_profile_endpoint_captures_trace(tmp_path):
    """--profile-dir exposes /debug/profile; a capture writes a trace dir
    (jax profiler works on CPU, so this runs the real capture path)."""
    import os

    manager = ModelManager()
    manager.add_chat_model("echo", EchoEngineFull())
    service = HttpService(
        manager, host="127.0.0.1", port=0, profile_dir=str(tmp_path)
    )
    await service.start()
    try:
        async with aiohttp.ClientSession() as s:
            url = f"http://127.0.0.1:{service.port}/debug/profile?seconds=0.2"
            async with s.get(url) as r:
                body = await r.json()
                assert r.status == 200
                assert body["trace_dir"].startswith(str(tmp_path))
            # the capture produced profiler artifacts on disk
            files = [
                os.path.join(dp, f)
                for dp, _dn, fn in os.walk(body["trace_dir"]) for f in fn
            ]
            assert files, "no trace files written"
            async with s.get(
                f"http://127.0.0.1:{service.port}/debug/profile?seconds=abc"
            ) as r:
                assert r.status == 400
            async with s.get(
                f"http://127.0.0.1:{service.port}/debug/profile?seconds=nan"
            ) as r:
                assert r.status == 400  # NaN survives min/max clamps
    finally:
        await service.stop()


@pytest.mark.asyncio
async def test_profile_endpoint_absent_without_dir():
    service = await start_echo_service()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(
                f"http://127.0.0.1:{service.port}/debug/profile"
            ) as r:
                assert r.status == 404
    finally:
        await service.stop()


@pytest.mark.asyncio
async def test_loadgen_sweep_against_echo_service():
    """The benchmark load generator (examples/llm/benchmarks/loadgen.py —
    the reference's genai-perf sweep analog) runs a 2-level sweep against
    the echo engine and reports sane stats (GPU/TPU-free, same pattern
    as the reference's CI: fake engines behind the real frontend)."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "loadgen",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
            "examples", "llm", "benchmarks", "loadgen.py"),
    )
    loadgen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(loadgen)

    service = await start_echo_service()
    try:
        prompt = loadgen.build_prompt(8, None)
        levels = await loadgen.sweep(
            f"http://127.0.0.1:{service.port}", "echo", prompt,
            osl=8, requests=6, levels=[1, 3],
        )
    finally:
        await service.stop()
    assert [lv["concurrency"] for lv in levels] == [1, 3]
    for lv in levels:
        assert lv["ok"] == 6 and lv["errors"] == 0
        assert lv["req_per_s"] > 0
        assert lv["ttft_p50_ms"] >= 0 and lv["ttft_p95_ms"] >= lv["ttft_p50_ms"]


def test_metrics_callback_gauges_render():
    """Engine metrics registered as callback gauges appear on /metrics
    renders, pulled fresh each time; a failing callback renders nothing
    rather than taking the endpoint down."""
    from dynamo_tpu.http.metrics import ServiceMetrics

    m = ServiceMetrics("dynamo")
    state = {"kv_active_blocks": 3, "gpu_prefix_cache_hit_rate": 0.5,
             "spec_accepted_tokens": 7, "label": "not-a-number",
             "flag": True}
    m.register_callback_gauges("dynamo_engine", lambda: state)
    text = m.render()
    assert "dynamo_engine_kv_active_blocks 3.0" in text
    assert "dynamo_engine_spec_accepted_tokens 7.0" in text
    assert "label" not in text and "flag" not in text  # numbers only
    state["kv_active_blocks"] = 9  # pulled fresh at every render
    assert "dynamo_engine_kv_active_blocks 9.0" in m.render()

    m2 = ServiceMetrics("dynamo")
    m2.register_callback_gauges("dynamo_engine", lambda: 1 / 0)
    assert m2.render()  # endpoint survives a broken engine callback


# --------------------------------------------------------------------------
# /v1/embeddings — the prefill-only workload (llm/embeddings.py)
# --------------------------------------------------------------------------


@pytest.mark.asyncio
async def test_embeddings_endpoint_openai_shape():
    from dynamo_tpu.llm.embeddings import EchoEmbedder

    manager = ModelManager()
    engine = EchoEngineFull()
    engine.embedder = EchoEmbedder(dim=8)
    manager.add_chat_model("echo", engine)
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    try:
        async with aiohttp.ClientSession() as s:
            url = f"http://127.0.0.1:{service.port}/v1/embeddings"
            # single string
            async with s.post(url, json={"model": "echo",
                                         "input": "hello world"}) as r:
                body = await r.json()
                assert r.status == 200
                assert body["object"] == "list"
                assert body["model"] == "echo"
                row = body["data"][0]
                assert row["object"] == "embedding" and row["index"] == 0
                assert len(row["embedding"]) == 8
                assert body["usage"]["prompt_tokens"] == 2
                assert body["usage"]["total_tokens"] == 2
                first = row["embedding"]
            # batch of strings: per-row indexes, deterministic vectors
            async with s.post(url, json={
                "model": "echo", "input": ["hello world", "other"],
            }) as r:
                body = await r.json()
                assert [d["index"] for d in body["data"]] == [0, 1]
                assert body["data"][0]["embedding"] == first
                assert body["data"][1]["embedding"] != first
            # token-id input shapes
            async with s.post(url, json={"model": "echo",
                                         "input": [1, 2, 3]}) as r:
                body = await r.json()
                assert r.status == 200
                assert body["usage"]["prompt_tokens"] == 3
            async with s.post(url, json={
                "model": "echo", "input": [[1, 2], [3, 4, 5]],
            }) as r:
                body = await r.json()
                assert len(body["data"]) == 2
                assert body["usage"]["prompt_tokens"] == 5
            # base64 encoding round-trips to the float rows
            async with s.post(url, json={
                "model": "echo", "input": "hello world",
                "encoding_format": "base64",
            }) as r:
                import base64

                import numpy as np

                body = await r.json()
                dec = np.frombuffer(
                    base64.b64decode(body["data"][0]["embedding"]),
                    np.float32,
                )
                assert np.allclose(dec, np.asarray(first, np.float32))
            # error shapes
            async with s.post(url, json={"model": "echo"}) as r:
                assert r.status == 400
            async with s.post(url, json={"model": "echo",
                                         "input": {"bad": 1}}) as r:
                assert r.status == 400
            async with s.post(url, json={"model": "nope",
                                         "input": "x"}) as r:
                assert r.status == 404
                assert (await r.json())["error"]["code"] == "model_not_found"
    finally:
        await service.stop()


@pytest.mark.asyncio
async def test_embeddings_501_without_embedder():
    service = await start_echo_service()  # plain engine, no embedder
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"http://127.0.0.1:{service.port}/v1/embeddings",
                json={"model": "echo", "input": "x"},
            ) as r:
                assert r.status == 501
                assert "prefill" in (await r.json())["error"]["message"]
    finally:
        await service.stop()
