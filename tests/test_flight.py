"""Flight recorder + XLA compile observability (telemetry/flight.py),
the flightdump pretty-printer, and the profiler capture-dir fix.

The compile-storm acceptance test drives a REAL ModelRunner on CPU: two
request shapes missing the warmed bucket set after serving start must
produce exactly two ``late`` compile events — the recompile-storm
signal docs/perf_tuning.md warns about but nothing previously detected.
"""

import json
import os
import sys

import numpy as np
import pytest

from dynamo_tpu.telemetry.flight import (
    CompileTracker,
    FlightRecorder,
    flight_recorder,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------------------------------------------------------
# FlightRecorder: the ring itself
# --------------------------------------------------------------------------


def test_ring_is_bounded_and_counts_drops():
    fr = FlightRecorder(capacity=16)
    for i in range(50):
        fr.record("test.event", request_id=f"r{i}", i=i)
    assert len(fr) == 16
    assert fr.dropped == 34
    assert fr.appended == 50
    events = fr.snapshot()
    # newest survive (the moments before a crash are the valuable ones)
    assert [e["data"]["i"] for e in events] == list(range(34, 50))
    # chronological + monotonic stamps
    assert all(a["t"] <= b["t"] for a, b in zip(events, events[1:]))
    assert all(a["seq"] < b["seq"] for a, b in zip(events, events[1:]))


def test_snapshot_filters_by_request_and_trace_id():
    fr = FlightRecorder(capacity=64)
    fr.record("a", request_id="req-1")
    fr.record("b", request_id="req-2", trace_id="trace-x")
    fr.record("c")  # no id at all
    assert [e["kind"] for e in fr.snapshot(request_id="req-1")] == ["a"]
    # trace ids match too (the operator usually has the X-Request-Id)
    assert [e["kind"] for e in fr.snapshot(request_id="trace-x")] == ["b"]
    assert len(fr.snapshot()) == 3
    assert fr.snapshot(n=1)[-1]["kind"] == "c"


def test_global_recorder_is_a_singleton():
    assert flight_recorder() is flight_recorder()


def test_capacity_env_override(monkeypatch):
    monkeypatch.setenv("DYN_FLIGHT_EVENTS", "128")
    assert FlightRecorder().capacity == 128
    monkeypatch.setenv("DYN_FLIGHT_EVENTS", "not-a-number")
    assert FlightRecorder().capacity == 4096  # default, not a crash


# --------------------------------------------------------------------------
# CompileTracker: first-dispatch-per-key detection + phase classification
# --------------------------------------------------------------------------


def test_compile_tracker_counts_first_dispatch_per_key_only():
    fr = FlightRecorder(capacity=64)
    tracker = CompileTracker(flight=fr)
    with tracker.track("prefill", "b2_s64") as first:
        assert first
    with tracker.track("prefill", "b2_s64") as first:
        assert not first
    with tracker.track("prefill", "b2_s128") as first:
        assert first
    assert [r["key"] for r in tracker.records] == ["b2_s64", "b2_s128"]
    assert all(r["phase"] == "startup" for r in tracker.records)
    assert tracker.late_compiles == 0

    tracker.mark_serving_started()
    with tracker.track("decode", "b2_s1"):
        pass
    assert tracker.records[-1]["phase"] == "late"
    assert tracker.late_compiles == 1
    # compile events land in the flight ring with their phase
    kinds = [e for e in fr.snapshot() if e["kind"] == "xla.compile"]
    assert len(kinds) == 3
    assert kinds[-1]["data"]["phase"] == "late"
    # and in the exposition, labelled program+phase
    text = tracker.registry.render()
    assert ('dynamo_engine_xla_compiles_total'
            '{phase="late",program="decode"} 1.0') in text
    assert ('dynamo_engine_xla_compiles_total'
            '{phase="startup",program="prefill"} 2.0') in text
    assert "dynamo_engine_xla_compile_duration_seconds_bucket" in text


def test_compile_tracker_reset_seen_recounts():
    tracker = CompileTracker(flight=FlightRecorder())
    with tracker.track("decode", "k"):
        pass
    tracker.reset_seen()
    with tracker.track("decode", "k") as first:
        assert first  # rebuilt programs compile again and must count
    assert len(tracker.records) == 2


def test_attention_route_counter_rides_the_dispatch_hook():
    """record_route() must attribute routes to the program whose tracked
    dispatch is on the stack (ops.attention.route_program installed as
    CompileTracker.dispatch_cm) — and, routes being TRACE-time facts,
    count once per compiled specialization, not once per step."""
    from dynamo_tpu.ops import attention as attn

    def count(program, route):
        key = (("program", program), ("route", route))
        return attn.ATTENTION_ROUTE_COUNTER.values.get(key, 0.0)

    tracker = CompileTracker(flight=FlightRecorder())
    tracker.dispatch_cm = attn.route_program

    base = count("decode", "sp_ring_kernel")
    for _ in range(3):  # repeat dispatches: only the first one traces
        with tracker.track("decode", "b2_s1") as first:
            if first:  # the dispatch seams record inside the trace
                attn.record_route("sp_ring_kernel")
    assert count("decode", "sp_ring_kernel") == base + 1
    # the tracked dispatches themselves stay startup-phase compiles
    assert all(r["phase"] == "startup" for r in tracker.records)
    # the hook restores its previous label on exit
    base_u = count("unknown", "xla")
    attn.record_route("xla")
    assert count("unknown", "xla") == base_u + 1

    # engine wiring: every runner installs the hook and registers the
    # singleton into its compile registry (the engine scrape), once
    runner, _ = _tiny_runner()
    assert runner.compiles.dispatch_cm is attn.route_program
    assert (attn.ATTENTION_ROUTE_COUNTER.name
            in runner.compiles.registry.names())
    runner2, _ = _tiny_runner()  # re-registration must not duplicate
    assert runner2.compiles.registry.names().count(
        attn.ATTENTION_ROUTE_COUNTER.name) <= 1


# --------------------------------------------------------------------------
# the compile-storm acceptance test: real runner, real compiles
# --------------------------------------------------------------------------


def _tiny_runner():
    from dynamo_tpu.engine.config import EngineConfig, ModelConfig
    from dynamo_tpu.engine.model_runner import ModelRunner

    cfg = EngineConfig(
        model=ModelConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            num_layers=1, num_heads=2, num_kv_heads=1,
        ),
        max_batch_size=2, max_model_len=128, kv_block_size=8,
        num_kv_blocks=32, dtype="float32",
    )
    return ModelRunner(cfg), cfg


def _dispatch(runner, b, s, w):
    import jax

    z2 = np.zeros((b, s), np.int32)
    runner.step(
        z2, z2, np.zeros((b, w), np.int32), np.full((b, s), -1, np.int32),
        np.ones(b, np.int32), np.zeros(b, np.int32),
        np.zeros(b, np.float32), np.zeros(b, np.int32),
        np.ones(b, np.float32), jax.random.PRNGKey(0),
    )


def test_compile_storm_two_unseen_buckets_after_serving_start():
    runner, cfg = _tiny_runner()
    fr = FlightRecorder(capacity=256)
    runner.compiles.flight = fr
    b = cfg.max_batch_size
    w = cfg.blocks_per_seq

    # "warmup": one prefill bucket compiled before serving starts
    _dispatch(runner, b, 64, w)
    assert [r["phase"] for r in runner.compiles.records] == ["startup"]

    runner.compiles.mark_serving_started()

    # the storm: two request shapes that missed the warmed ladder
    _dispatch(runner, b, 128, w)   # unseen prefill bucket
    _dispatch(runner, b, 1, w)     # unseen decode shape
    # …and a repeat of an already-compiled shape, which must NOT count
    _dispatch(runner, b, 64, w)

    late = [r for r in runner.compiles.records if r["phase"] == "late"]
    assert len(late) == 2, late
    assert {r["program"] for r in late} == {"prefill", "decode"}
    assert all(r["duration_s"] > 0 for r in late)
    ring_late = [
        e for e in fr.snapshot()
        if e["kind"] == "xla.compile" and e["data"]["phase"] == "late"
    ]
    assert len(ring_late) == 2
    text = runner.compiles.registry.render()
    assert ('dynamo_engine_xla_compiles_total'
            '{phase="late",program="prefill"} 1.0') in text
    assert ('dynamo_engine_xla_compiles_total'
            '{phase="late",program="decode"} 1.0') in text


def test_scheduler_attaches_compile_registry_and_marks_serving():
    """The engine scrape must carry the runner's compile series, and
    Scheduler.start() must flip the late-compile phase."""
    import asyncio

    from dynamo_tpu.engine.config import EngineConfig, ModelConfig
    from dynamo_tpu.engine.scheduler import Scheduler

    class RunnerStub:
        compiles = CompileTracker(flight=FlightRecorder())

        def gather_blocks_device(self, ids):  # host-tier hook, unused
            raise NotImplementedError

    cfg = EngineConfig(
        model=ModelConfig(vocab_size=64), max_batch_size=2,
        max_model_len=64, kv_block_size=8, num_kv_blocks=16,
    )
    sched = Scheduler(RunnerStub(), cfg, flight=FlightRecorder())
    assert "dynamo_engine_xla_compiles_total" in sched.registry.names()

    async def go():
        sched.start()
        assert RunnerStub.compiles.serving
        await sched.stop()

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(go())
    finally:
        loop.close()


# --------------------------------------------------------------------------
# satellite: profiler capture dirs can no longer collide
# --------------------------------------------------------------------------


def test_trace_dir_names_unique_within_one_second():
    from dynamo_tpu.utils.profiling import trace_dir_name

    # the old strftime-only name collided for any two captures in the
    # same second and exist_ok=True silently merged them
    names = {trace_dir_name() for _ in range(100)}
    assert len(names) == 100
    assert all(n.startswith("trace-") for n in names)
    assert all(f"-{os.getpid()}-" in n for n in names)


def test_capture_trace_rejects_collision(tmp_path, monkeypatch):
    """capture_trace must CREATE its directory (exist_ok=False): a name
    collision fails loudly instead of merging two captures."""
    from dynamo_tpu.utils import profiling

    monkeypatch.setattr(profiling, "trace_dir_name", lambda: "trace-fixed")
    made = profiling.capture_trace(str(tmp_path), 0.0)
    assert os.path.isdir(made)
    with pytest.raises(FileExistsError):
        profiling.capture_trace(str(tmp_path), 0.0)


# --------------------------------------------------------------------------
# satellite: scripts/flightdump.py renders artifacts readably
# --------------------------------------------------------------------------


def _sample_artifact():
    from dynamo_tpu.telemetry.watchdog import build_flight_artifact

    fr = FlightRecorder(capacity=32)
    fr.record("scheduler.admission", request_id="req-a", slot=0)
    fr.record("scheduler.burst_dispatch", rows=1, requests=["req-a"])
    fr.record("watchdog.trip", reason="decode_stall")
    return build_flight_artifact(reason="unit_test", flight=fr)


def test_flightdump_renders_event_table_and_stacks(tmp_path, capsys):
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    try:
        import flightdump
    finally:
        sys.path.pop(0)

    path = os.path.join(str(tmp_path), "artifact.json")
    with open(path, "w") as f:
        json.dump(_sample_artifact(), f, default=str)

    assert flightdump.main(["flightdump", path]) == 0
    out = capsys.readouterr().out
    assert "scheduler.admission" in out
    assert "req-a" in out
    assert "decode_stall" in out
    assert "--- thread" in out  # stack section
    assert "reason=unit_test" in out

    # per-request filtering: only req-a's events survive
    assert flightdump.main(
        ["flightdump", path, "--request", "req-a", "--no-stacks"]
    ) == 0
    out = capsys.readouterr().out
    assert "scheduler.admission" in out
    assert "watchdog.trip" not in out
    assert "--- thread" not in out

    # unreadable artifact is a clean exit-2, not a stack trace
    assert flightdump.main(
        ["flightdump", os.path.join(str(tmp_path), "missing.json")]
    ) == 2
