"""Decode-specialized Pallas paged attention vs. the XLA reference path.

Runs in interpret mode on CPU (manual-DMA semantics are emulated by the
Pallas interpreter). Reference analog: correctness strategy mirrors
tests/test_pallas_attention.py — check against ops/attention.py's
gather/softmax path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.ops.attention import (
    attention,
    paged_attention,
    scatter_kv_stacked,
)
from dynamo_tpu.ops.pallas_decode import paged_decode_attention


def make_stacked_case(rng, layers, b, h, kvh, d, bs, w, dtype=jnp.float32):
    n_blocks = b * w + 3
    q = jnp.asarray(rng.standard_normal((b, 1, h, d)), dtype)
    k_cache = jnp.asarray(
        rng.standard_normal((layers, n_blocks, bs, kvh, d)), dtype
    )
    v_cache = jnp.asarray(
        rng.standard_normal((layers, n_blocks, bs, kvh, d)), dtype
    )
    perm = rng.permutation(n_blocks)[: b * w]
    block_tables = jnp.asarray(perm.reshape(b, w), jnp.int32)
    return q, k_cache, v_cache, block_tables


@pytest.mark.parametrize("ppc", [8, 2, 1])  # 2/1 force the multi-chunk
@pytest.mark.parametrize("ctx", [[1, 17, 64, 128], [38, 6, 1, 90]])
def test_decode_matches_xla_reference(ctx, ppc):
    """ppc < live pages exercises the double-buffered prefetch loop
    (slot alternation + wait ordering), not just the single-chunk case."""
    rng = np.random.default_rng(0)
    layers, b, h, kvh, d, bs, w = 3, 4, 8, 4, 64, 16, 8
    q, k_cache, v_cache, bt = make_stacked_case(rng, layers, b, h, kvh, d, bs, w)
    ctx = jnp.asarray(ctx, jnp.int32)
    positions = (ctx - 1)[:, None]

    for li in range(layers):
        ref = paged_attention(
            q, k_cache[li], v_cache[li], bt, positions, ctx
        )
        out = paged_decode_attention(
            q, k_cache, v_cache, bt, ctx,
            layer_idx=jnp.int32(li), pages_per_chunk=ppc, interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5,
            err_msg=f"layer {li}",
        )


def test_decode_gqa_bf16_small_chunk():
    """Odd GQA group + bf16 + pages_per_chunk > live pages."""
    rng = np.random.default_rng(1)
    layers, b, h, kvh, d, bs, w = 2, 2, 8, 2, 32, 8, 4
    q, k_cache, v_cache, bt = make_stacked_case(
        rng, layers, b, h, kvh, d, bs, w, jnp.bfloat16
    )
    ctx = jnp.asarray([9, 23], jnp.int32)
    positions = (ctx - 1)[:, None]
    ref = paged_attention(q, k_cache[1], v_cache[1], bt, positions, ctx)
    out = paged_decode_attention(
        q, k_cache, v_cache, bt, ctx,
        layer_idx=jnp.int32(1), pages_per_chunk=8, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=5e-2, atol=5e-2,
    )


def test_attention_dispatch_decode_stacked():
    """attention() routes S=1 + stacked cache through the decode kernel."""
    rng = np.random.default_rng(2)
    layers, b, h, kvh, d, bs, w = 2, 4, 8, 4, 64, 16, 8
    q, k_cache, v_cache, bt = make_stacked_case(rng, layers, b, h, kvh, d, bs, w)
    ctx = jnp.asarray([40, 3, 77, 128], jnp.int32)
    positions = (ctx - 1)[:, None]
    ref = paged_attention(q, k_cache[0], v_cache[0], bt, positions, ctx)
    out = attention(
        q, k_cache, v_cache, bt, positions, ctx,
        impl="pallas", interpret=True, layer_idx=jnp.int32(0),
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_attention_dispatch_decode_on_mesh():
    """Decode kernel under a dp x tp shard_map mesh."""
    from dynamo_tpu.engine.model_runner import build_mesh

    rng = np.random.default_rng(3)
    layers, b, h, kvh, d, bs, w = 2, 4, 8, 4, 64, 16, 4
    q, k_cache, v_cache, bt = make_stacked_case(rng, layers, b, h, kvh, d, bs, w)
    ctx = jnp.asarray([12, 30, 64, 5], jnp.int32)
    positions = (ctx - 1)[:, None]

    mesh = build_mesh(2, 4)
    ref = paged_attention(q, k_cache[1], v_cache[1], bt, positions, ctx)
    out = attention(
        q, k_cache, v_cache, bt, positions, ctx,
        impl="pallas", mesh=mesh, interpret=True, layer_idx=jnp.int32(1),
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_scatter_kv_stacked_matches_per_layer():
    """Stacked scatter == slice + scatter_kv + splice, incl. -1 drops."""
    from dynamo_tpu.ops.attention import scatter_kv

    rng = np.random.default_rng(4)
    layers, n, bs, kvh, dk = 3, 6, 8, 2, 16
    b, s = 2, 4
    k_all = jnp.asarray(rng.standard_normal((layers, n, bs, kvh, dk)), jnp.float32)
    v_all = jnp.asarray(rng.standard_normal((layers, n, bs, kvh, dk)), jnp.float32)
    new_k = jnp.asarray(rng.standard_normal((b, s, kvh, dk)), jnp.float32)
    new_v = jnp.asarray(rng.standard_normal((b, s, kvh, dk)), jnp.float32)
    slots = jnp.asarray([[0, 5, 17, -1], [30, 31, -1, 2]], jnp.int32)

    for li in range(layers):
        k2, v2 = scatter_kv_stacked(k_all, v_all, new_k, new_v, slots, jnp.int32(li))
        ref_k, ref_v = scatter_kv(k_all[li], v_all[li], new_k, new_v, slots)
        np.testing.assert_array_equal(np.asarray(k2[li]), np.asarray(ref_k))
        np.testing.assert_array_equal(np.asarray(v2[li]), np.asarray(ref_v))
        # other layers untouched
        for lj in range(layers):
            if lj != li:
                np.testing.assert_array_equal(
                    np.asarray(k2[lj]), np.asarray(k_all[lj])
                )


def test_prefill_kernel_stacked_layer_idx():
    """paged_flash_attention with a stacked cache + runtime layer index."""
    from dynamo_tpu.ops.pallas_attention import paged_flash_attention

    rng = np.random.default_rng(5)
    layers, b, s, h, kvh, d, bs = 2, 2, 32, 8, 4, 64, 16
    w = 4
    n_blocks = b * w + 1
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k_cache = jnp.asarray(rng.standard_normal((layers, n_blocks, bs, kvh, d)), jnp.float32)
    v_cache = jnp.asarray(rng.standard_normal((layers, n_blocks, bs, kvh, d)), jnp.float32)
    bt = jnp.asarray(rng.permutation(n_blocks)[: b * w].reshape(b, w), jnp.int32)
    base = np.zeros(b, np.int32)
    ctx = jnp.full((b,), s, jnp.int32)
    positions = jnp.asarray(base)[:, None] + jnp.arange(s)[None, :]

    for li in range(layers):
        ref = paged_attention(q, k_cache[li], v_cache[li], bt, positions, ctx)
        out = paged_flash_attention(
            q, k_cache, v_cache, bt, jnp.asarray(base), ctx,
            layer_idx=jnp.int32(li), interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )


def test_mla_decode_matches_xla_reference():
    """MLA decode kernel vs models/deepseek.mla_paged_attention (interpret)."""
    from dynamo_tpu.models.deepseek import mla_paged_attention
    from dynamo_tpu.ops.pallas_decode import mla_paged_decode_attention

    rng = np.random.default_rng(7)
    layers, b, h, r, rd, bs, w = 2, 4, 8, 32, 16, 8, 8
    n_blocks = b * w + 2
    q_lat = jnp.asarray(rng.standard_normal((b, 1, h, r)), jnp.float32)
    q_rope = jnp.asarray(rng.standard_normal((b, 1, h, rd)), jnp.float32)
    c_cache = jnp.asarray(
        rng.standard_normal((layers, n_blocks, bs, 1, r)), jnp.float32
    )
    kr_cache = jnp.asarray(
        rng.standard_normal((layers, n_blocks, bs, 1, rd)), jnp.float32
    )
    bt = jnp.asarray(
        rng.permutation(n_blocks)[: b * w].reshape(b, w), jnp.int32
    )
    ctx = jnp.asarray([1, 13, 40, 64], jnp.int32)
    positions = (ctx - 1)[:, None]
    scale = 0.25

    for li in range(layers):
        ref = mla_paged_attention(
            q_lat, q_rope, c_cache[li], kr_cache[li], bt, positions, ctx, scale
        )
        out = mla_paged_decode_attention(
            q_lat, q_rope, c_cache, kr_cache, bt, ctx,
            layer_idx=jnp.int32(li), scale=scale, pages_per_chunk=2,
            interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5,
            err_msg=f"layer {li}",
        )


def test_mla_attention_dispatch_and_mesh():
    """deepseek.mla_attention routes decode to the kernel, incl. tp mesh."""
    from dynamo_tpu.engine.model_runner import build_mesh
    from dynamo_tpu.models.deepseek import mla_attention, mla_paged_attention

    rng = np.random.default_rng(8)
    layers, b, h, r, rd, bs, w = 2, 4, 8, 32, 16, 8, 4
    n_blocks = b * w + 1
    q_lat = jnp.asarray(rng.standard_normal((b, 1, h, r)), jnp.float32)
    q_rope = jnp.asarray(rng.standard_normal((b, 1, h, rd)), jnp.float32)
    c_cache = jnp.asarray(
        rng.standard_normal((layers, n_blocks, bs, 1, r)), jnp.float32
    )
    kr_cache = jnp.asarray(
        rng.standard_normal((layers, n_blocks, bs, 1, rd)), jnp.float32
    )
    bt = jnp.asarray(rng.permutation(n_blocks)[: b * w].reshape(b, w), jnp.int32)
    ctx = jnp.asarray([5, 17, 30, 9], jnp.int32)
    positions = (ctx - 1)[:, None]

    ref = mla_paged_attention(
        q_lat, q_rope, c_cache[1], kr_cache[1], bt, positions, ctx, 0.5
    )
    out = mla_attention(
        q_lat, q_rope, c_cache, kr_cache, jnp.int32(1), bt, positions, ctx,
        0.5, impl="pallas", interpret=True,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    mesh = build_mesh(2, 4)
    out = mla_attention(
        q_lat, q_rope, c_cache, kr_cache, jnp.int32(1), bt, positions, ctx,
        0.5, impl="pallas", mesh=mesh, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

@pytest.mark.parametrize("window", [1, 7, 24, 64, 1000])
def test_decode_windowed_matches_xla_reference(window):
    """Sliding-window decode (Mistral/Gemma-2 even layers): the kernel
    starts its page walk at the window's first live chunk, so parity with
    the XLA mask is the proof the skipped chunks were truly dead."""
    rng = np.random.default_rng(9)
    layers, b, h, kvh, d, bs, w = 2, 4, 8, 4, 64, 16, 8
    q, k_cache, v_cache, bt = make_stacked_case(rng, layers, b, h, kvh, d, bs, w)
    ctx = jnp.asarray([1, 17, 64, 128], jnp.int32)
    positions = (ctx - 1)[:, None]

    ref = paged_attention(
        q, k_cache[1], v_cache[1], bt, positions, ctx, sliding_window=window
    )
    out = paged_decode_attention(
        q, k_cache, v_cache, bt, ctx,
        layer_idx=jnp.int32(1), pages_per_chunk=2, interpret=True,
        window=jnp.asarray(window, jnp.int32),
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_decode_softcap_matches_xla_reference():
    """Gemma-2 logit softcapping, with and without a window on top."""
    rng = np.random.default_rng(10)
    layers, b, h, kvh, d, bs, w = 2, 4, 8, 4, 64, 16, 8
    q, k_cache, v_cache, bt = make_stacked_case(rng, layers, b, h, kvh, d, bs, w)
    ctx = jnp.asarray([5, 33, 90, 128], jnp.int32)
    positions = (ctx - 1)[:, None]

    ref = paged_attention(
        q, k_cache[0], v_cache[0], bt, positions, ctx, softcap=30.0
    )
    out = paged_decode_attention(
        q, k_cache, v_cache, bt, ctx,
        layer_idx=jnp.int32(0), interpret=True, softcap=30.0,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )

    ref = paged_attention(
        q, k_cache[1], v_cache[1], bt, positions, ctx, softcap=30.0,
        sliding_window=20,
    )
    out = paged_decode_attention(
        q, k_cache, v_cache, bt, ctx,
        layer_idx=jnp.int32(1), interpret=True, softcap=30.0,
        window=jnp.asarray(20, jnp.int32), pages_per_chunk=1,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_decode_traced_window_per_layer():
    """Gemma-2 alternates windowed/full layers inside one jitted scan: the
    window must work as a TRACED per-layer scalar without retracing."""
    rng = np.random.default_rng(11)
    layers, b, h, kvh, d, bs, w = 2, 2, 8, 4, 64, 16, 8
    q, k_cache, v_cache, bt = make_stacked_case(rng, layers, b, h, kvh, d, bs, w)
    ctx = jnp.asarray([47, 111], jnp.int32)
    positions = (ctx - 1)[:, None]

    @jax.jit
    def both_layers(q, k_cache, v_cache, bt, ctx):
        def one(li):
            win = jnp.where(li % 2 == 0, jnp.int32(24), jnp.int32(2**30))
            return paged_decode_attention(
                q, k_cache, v_cache, bt, ctx, layer_idx=li,
                interpret=True, window=win,
            )
        return one(jnp.int32(0)), one(jnp.int32(1))

    out0, out1 = both_layers(q, k_cache, v_cache, bt, ctx)
    ref0 = paged_attention(
        q, k_cache[0], v_cache[0], bt, positions, ctx, sliding_window=24
    )
    ref1 = paged_attention(q, k_cache[1], v_cache[1], bt, positions, ctx)
    np.testing.assert_allclose(np.asarray(out0), np.asarray(ref0), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(ref1), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("window", [1, 30, 100, 4096])
def test_prefill_windowed_softcap_matches_xla_reference(window):
    """Flash-prefill kernel with window + softcap (Gemma-2 prefill): the
    kv_map's lower page clamp must not skip any live page."""
    from dynamo_tpu.ops.pallas_attention import paged_flash_attention

    rng = np.random.default_rng(12)
    layers, b, s, h, kvh, d, bs = 2, 2, 64, 8, 4, 64, 16
    w = 8
    n_blocks = b * w + 1
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k_cache = jnp.asarray(
        rng.standard_normal((layers, n_blocks, bs, kvh, d)), jnp.float32
    )
    v_cache = jnp.asarray(
        rng.standard_normal((layers, n_blocks, bs, kvh, d)), jnp.float32
    )
    bt = jnp.asarray(rng.permutation(n_blocks)[: b * w].reshape(b, w), jnp.int32)
    # chunked-prefill shape: rows continue at different bases past cached ctx
    base = jnp.asarray([0, 48], jnp.int32)
    ctx = jnp.asarray([s, 48 + s], jnp.int32)
    positions = base[:, None] + jnp.arange(s)[None, :]

    ref = paged_attention(
        q, k_cache[1], v_cache[1], bt, positions, ctx,
        softcap=30.0, sliding_window=window,
    )
    out = paged_flash_attention(
        q, k_cache, v_cache, bt, base, ctx,
        layer_idx=jnp.int32(1), interpret=True, softcap=30.0,
        window=jnp.asarray(window, jnp.int32), q_chunk=32,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_attention_dispatch_windowed_softcap_rides_pallas():
    """attention() no longer forces XLA for softcap/sliding_window — the
    kernels implement both; parity at the dispatch level, decode+prefill."""
    rng = np.random.default_rng(13)
    layers, b, h, kvh, d, bs, w = 2, 4, 8, 4, 64, 16, 8
    q, k_cache, v_cache, bt = make_stacked_case(rng, layers, b, h, kvh, d, bs, w)
    ctx = jnp.asarray([9, 33, 77, 128], jnp.int32)
    positions = (ctx - 1)[:, None]

    ref = attention(
        q, k_cache, v_cache, bt, positions, ctx, impl="xla",
        layer_idx=jnp.int32(0), softcap=25.0, sliding_window=18,
    )
    out = attention(
        q, k_cache, v_cache, bt, positions, ctx, impl="pallas",
        interpret=True, layer_idx=jnp.int32(0), softcap=25.0,
        sliding_window=18,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    # prefill dispatch (S > 1, affine positions)
    s = 32
    qp = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    basep = jnp.zeros((b,), jnp.int32)
    posp = basep[:, None] + jnp.arange(s)[None, :]
    ctxp = jnp.full((b,), s, jnp.int32)
    ref = attention(
        qp, k_cache, v_cache, bt, posp, ctxp, impl="xla",
        layer_idx=jnp.int32(1), softcap=25.0, sliding_window=12,
    )
    out = attention(
        qp, k_cache, v_cache, bt, posp, ctxp, impl="pallas",
        interpret=True, layer_idx=jnp.int32(1), softcap=25.0,
        sliding_window=12,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_decode_sinks_matches_xla_reference():
    """GPT-OSS attention sinks: both kernels fold exp(sink - m) into the
    finalize denominator; parity vs the XLA sink column, with and
    without a window on top."""
    rng = np.random.default_rng(14)
    layers, b, h, kvh, d, bs, w = 2, 4, 8, 4, 64, 16, 8
    q, k_cache, v_cache, bt = make_stacked_case(rng, layers, b, h, kvh, d, bs, w)
    ctx = jnp.asarray([1, 17, 64, 128], jnp.int32)
    positions = (ctx - 1)[:, None]
    sinks = jnp.asarray(rng.standard_normal(h), jnp.float32)

    ref = paged_attention(
        q, k_cache[1], v_cache[1], bt, positions, ctx, sinks=sinks
    )
    out = paged_decode_attention(
        q, k_cache, v_cache, bt, ctx,
        layer_idx=jnp.int32(1), pages_per_chunk=2, interpret=True,
        sinks=sinks,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    ref = paged_attention(
        q, k_cache[0], v_cache[0], bt, positions, ctx, sinks=sinks,
        sliding_window=20,
    )
    out = paged_decode_attention(
        q, k_cache, v_cache, bt, ctx,
        layer_idx=jnp.int32(0), interpret=True, sinks=sinks,
        window=jnp.asarray(20, jnp.int32),
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_prefill_sinks_matches_xla_reference():
    from dynamo_tpu.ops.pallas_attention import paged_flash_attention

    rng = np.random.default_rng(15)
    layers, b, s, h, kvh, d, bs = 2, 2, 64, 8, 4, 64, 16
    w = 8
    n_blocks = b * w + 1
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k_cache = jnp.asarray(
        rng.standard_normal((layers, n_blocks, bs, kvh, d)), jnp.float32
    )
    v_cache = jnp.asarray(
        rng.standard_normal((layers, n_blocks, bs, kvh, d)), jnp.float32
    )
    bt = jnp.asarray(rng.permutation(n_blocks)[: b * w].reshape(b, w), jnp.int32)
    base = jnp.asarray([0, 48], jnp.int32)
    ctx = jnp.asarray([s, 48 + s], jnp.int32)
    positions = base[:, None] + jnp.arange(s)[None, :]
    sinks = jnp.asarray(rng.standard_normal(h), jnp.float32)

    ref = paged_attention(
        q, k_cache[0], v_cache[0], bt, positions, ctx, sinks=sinks,
        sliding_window=30,
    )
    out = paged_flash_attention(
        q, k_cache, v_cache, bt, base, ctx,
        layer_idx=jnp.int32(0), interpret=True, q_chunk=32,
        window=jnp.asarray(30, jnp.int32), sinks=sinks,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_attention_dispatch_sinks_rides_pallas_incl_mesh():
    """attention() routes sinks to the kernels (no more XLA forcing),
    incl. under the dp x tp shard_map where sinks shard with the heads."""
    from dynamo_tpu.engine.model_runner import build_mesh

    rng = np.random.default_rng(16)
    layers, b, h, kvh, d, bs, w = 2, 4, 8, 4, 64, 16, 4
    q, k_cache, v_cache, bt = make_stacked_case(rng, layers, b, h, kvh, d, bs, w)
    ctx = jnp.asarray([12, 30, 64, 5], jnp.int32)
    positions = (ctx - 1)[:, None]
    sinks = jnp.asarray(rng.standard_normal(h), jnp.float32)

    ref = attention(
        q, k_cache, v_cache, bt, positions, ctx, impl="xla",
        layer_idx=jnp.int32(1), sinks=sinks,
    )
    out = attention(
        q, k_cache, v_cache, bt, positions, ctx, impl="pallas",
        interpret=True, layer_idx=jnp.int32(1), sinks=sinks,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    mesh = build_mesh(2, 4)
    out = attention(
        q, k_cache, v_cache, bt, positions, ctx, impl="pallas",
        mesh=mesh, interpret=True, layer_idx=jnp.int32(1), sinks=sinks,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


# --------------------------------------------------------------------------
# the fused S-token verify kernel (speculative propose-verify rounds)
# --------------------------------------------------------------------------


@pytest.mark.parametrize("ppc", [8, 2, 1])
def test_verify_matches_xla_reference(ppc):
    """paged_verify_attention vs the gather/softmax reference: S query
    tokens at affine positions (ctx - S + s), one page walk per row —
    chunked prefetch exercised at ppc < live pages."""
    from dynamo_tpu.ops.pallas_decode import paged_verify_attention

    rng = np.random.default_rng(7)
    layers, b, h, kvh, d, bs, w, s = 2, 3, 8, 4, 64, 16, 8, 5
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    _, k_cache, v_cache, bt = make_stacked_case(
        rng, layers, b, h, kvh, d, bs, w
    )
    ctx = jnp.asarray([s + 1, 37, 101], jnp.int32)  # incl. the S tail
    positions = (ctx - s)[:, None] + jnp.arange(s)[None, :]

    for li in range(layers):
        ref = paged_attention(
            q, k_cache[li], v_cache[li], bt, positions, ctx
        )
        out = paged_verify_attention(
            q, k_cache, v_cache, bt, ctx - s, ctx,
            layer_idx=jnp.int32(li), pages_per_chunk=ppc, interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5,
            err_msg=f"layer {li}",
        )


def test_verify_windowed_matches_xla_reference():
    """Sliding window on the verify tail: each query's own lower bound
    applies (key > q_pos - window)."""
    from dynamo_tpu.ops.pallas_decode import paged_verify_attention

    rng = np.random.default_rng(8)
    layers, b, h, kvh, d, bs, w, s = 2, 2, 4, 2, 32, 8, 8, 4
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    _, k_cache, v_cache, bt = make_stacked_case(
        rng, layers, b, h, kvh, d, bs, w
    )
    ctx = jnp.asarray([29, 53], jnp.int32)
    positions = (ctx - s)[:, None] + jnp.arange(s)[None, :]
    ref = paged_attention(
        q, k_cache[0], v_cache[0], bt, positions, ctx,
        sliding_window=16,
    )
    out = paged_verify_attention(
        q, k_cache, v_cache, bt, ctx - s, ctx,
        layer_idx=jnp.int32(0), interpret=True,
        window=jnp.asarray(16, jnp.int32),
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5,
    )


def test_attention_dispatch_small_s_rides_verify_kernel():
    """attention() routes 1 < S <= VERIFY_MAX_S through the verify
    kernel (affine verify layout) and matches the XLA reference."""
    rng = np.random.default_rng(9)
    layers, b, h, kvh, d, bs, w, s = 2, 2, 8, 4, 64, 16, 8, 3
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    _, k_cache, v_cache, bt = make_stacked_case(
        rng, layers, b, h, kvh, d, bs, w
    )
    ctx = jnp.asarray([s, 64], jnp.int32)
    positions = (ctx - s)[:, None] + jnp.arange(s)[None, :]
    ref = attention(
        q, k_cache, v_cache, bt, positions, ctx,
        impl="xla", layer_idx=jnp.int32(1),
    )
    out = attention(
        q, k_cache, v_cache, bt, positions, ctx,
        impl="pallas", interpret=True, layer_idx=jnp.int32(1),
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5,
    )


def test_verify_padded_chunk_valid_rows_match_flash_contract():
    """A right-padded small chunk (ctx < base + S — the shape a custom
    sub-32 prefill bucket would produce): valid rows must match the XLA
    reference exactly; pad rows are garbage the caller discards (the
    flash kernel's contract)."""
    from dynamo_tpu.ops.pallas_decode import paged_verify_attention

    rng = np.random.default_rng(11)
    layers, b, h, kvh, d, bs, w, s = 2, 2, 4, 2, 32, 8, 8, 6
    valid = 4  # last 2 query rows are padding
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    _, k_cache, v_cache, bt = make_stacked_case(
        rng, layers, b, h, kvh, d, bs, w
    )
    base = jnp.asarray([10, 3], jnp.int32)
    ctx = base + valid
    positions = base[:, None] + jnp.arange(s)[None, :]
    ref = paged_attention(
        q, k_cache[0], v_cache[0], bt, positions, ctx
    )
    out = paged_verify_attention(
        q, k_cache, v_cache, bt, base, ctx,
        layer_idx=jnp.int32(0), interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(out)[:, :valid], np.asarray(ref)[:, :valid],
        rtol=2e-5, atol=2e-5,
    )
