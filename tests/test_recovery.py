"""Self-healing serving (recovery/): policy ladder units + chaos e2e.

The acceptance bar (ISSUE 8): an injected mid-burst wedge yields a
watchdog trip followed by automated drain, live migration of in-flight
requests to a healthy peer with a byte-identical continued stream, and
a respawned engine re-registered in discovery — no leaked blocks or
slots on either side, and the KV router never routes to the draining
worker. Faults come from utils/faults.py (DYN_FAULT sites), engines are
the deterministic FakeRunner (token = f(prev, pos), so any scheduling —
including a cross-engine resume — must reproduce the same stream).
"""

import asyncio
import os
import uuid

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.scheduler import EngineRequest, Scheduler
from dynamo_tpu.kv_router.indexer import OverlapScores
from dynamo_tpu.kv_router.protocols import ForwardPassMetrics
from dynamo_tpu.kv_router.scheduler import AllWorkersBusy, KvScheduler
from dynamo_tpu.planner.admission import (
    AdmissionConfig,
    AdmissionController,
    AdmissionRejected,
)
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.recovery import (
    MigrationServer,
    MigrationSink,
    MigrationState,
    RecoveryConfig,
    RecoveryController,
    migration_class,
)
from dynamo_tpu.transfer.framing import pack_frame, read_header
from dynamo_tpu.runtime.engine import AsyncEngineContext
from dynamo_tpu.telemetry.flight import FlightRecorder
from dynamo_tpu.telemetry.watchdog import StallWatchdog
from dynamo_tpu.tokens import TokenSequence
from dynamo_tpu.utils import faults

from test_decode_pipeline import FakeRunner


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


class MigRunner(FakeRunner):
    """FakeRunner + the block-op surface the migration plane uses.

    KV payloads are zeros (the fake's token rule depends only on the
    carry, never on cache contents) — block *accounting* stays real, so
    the leak assertions are meaningful; ``sync_delay`` slows decode
    syncs so a test can reliably drain mid-stream."""

    def __init__(self, config, sync_delay=0.0):
        super().__init__(config)
        self.sync_delay = sync_delay
        self.scattered = []

    def gather_blocks(self, block_ids):
        bs = self.config.kv_block_size
        shape = (1, len(block_ids), bs, 1, 4)
        return (np.zeros(shape, np.float16), np.zeros(shape, np.float16))

    def scatter_blocks(self, block_ids, k, v):
        self.scattered.append(list(block_ids))

    def decode_burst(self, *args, **kw):
        out = super().decode_burst(*args, **kw)
        if not self.sync_delay:
            return out

        delay = self.sync_delay

        class _Slow:
            def __init__(self, arr):
                self._arr = np.asarray(arr)

            def __array__(self, dtype=None):
                import time

                time.sleep(delay)
                a = self._arr
                return a.astype(dtype) if dtype is not None else a

            def __getitem__(self, item):
                return _Slow(self._arr[item])

        return tuple(_Slow(a) for a in out)


def _config(**kw):
    kw.setdefault("num_kv_blocks", 64)
    kw.setdefault("max_model_len", 256)
    kw.setdefault("multi_step_decode", 4)
    return EngineConfig(
        model=ModelConfig(vocab_size=512, hidden_size=32,
                          intermediate_size=64, num_layers=1, num_heads=2,
                          num_kv_heads=1),
        max_batch_size=4, kv_block_size=8, dtype="float32",
        enable_prefix_caching=False, **kw,
    )


def _request(prompt, max_tokens, sampling=None):
    req = PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=max_tokens,
                                       ignore_eos=True),
        sampling_options=sampling or SamplingOptions(temperature=0.0),
        eos_token_ids=[],
    )
    return EngineRequest(
        request_id=uuid.uuid4().hex, prompt=list(prompt), req=req,
        ctx=AsyncEngineContext(), out_queue=asyncio.Queue(),
    )


async def _collect(er, limit=None):
    toks, finish = [], None
    while True:
        out = await asyncio.wait_for(er.out_queue.get(), timeout=60)
        if out is None:
            return toks, finish
        toks.extend(out.token_ids)
        if out.finish_reason is not None:
            finish = out.finish_reason
        if limit is not None and len(toks) >= limit:
            return toks, finish


def _baseline(prompt, max_tokens):
    """The unperturbed stream: one healthy scheduler, start to finish."""
    config = _config()

    async def go():
        sched = Scheduler(MigRunner(config), config,
                          flight=FlightRecorder())
        sched.start()
        er = _request(prompt, max_tokens)
        sched.add_request(er)
        try:
            return await _collect(er)
        finally:
            await sched.stop()

    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(go())
    finally:
        loop.close()


# --------------------------------------------------------------------------
# unit: migrate-vs-fail decision per request class
# --------------------------------------------------------------------------


def _decode_state(er, n_tokens=6):
    """Put a request into plain decode state (committed KV, pending)."""
    toks = list(er.prompt) + list(range(100, 100 + n_tokens))
    er.seq = TokenSequence(toks, block_size=8)
    er.context_len = len(toks)
    er.pending_token = 7
    er.generated = n_tokens + 1
    return er


def test_migration_class_policy():
    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    try:
        # plain decode-state → hot
        assert migration_class(_decode_state(_request([1, 2, 3], 20))) == "hot"
        # still waiting (no KV yet) → cold
        assert migration_class(_request([1, 2, 3], 20)) == "cold"
        # mid-prefill (KV covers a prefix only) → cold
        er = _request(list(range(1, 30)), 20)
        er.seq = TokenSequence(er.prompt, block_size=8)
        er.context_len = 8
        assert migration_class(er) == "cold"
        # guided_choice rebuilds its trie on the peer → cold
        er = _decode_state(_request([1, 2, 3], 20, SamplingOptions(
            temperature=0.0, guided_choice_token_ids=[[5, 6]])))
        assert migration_class(er) == "cold"
        # guided_json's grammar cursor cannot serialize → fail
        er = _decode_state(_request([1, 2, 3], 20, SamplingOptions(
            temperature=0.0, guided_json={"type": "json_object"})))
        assert migration_class(er) == "fail"
        # prompt logprobs not yet emitted → cold (peer recomputes)
        er = _decode_state(_request([1, 2, 3], 20))
        er.want_prompt_lps = True
        assert migration_class(er) == "cold"
    finally:
        asyncio.set_event_loop(None)
        loop.close()


# --------------------------------------------------------------------------
# unit: respawn ladder (backoff + consecutive-failure budget)
# --------------------------------------------------------------------------


async def test_respawn_backoff_doubles_and_budget_gives_up(monkeypatch):
    delays = []
    real_sleep = asyncio.sleep

    async def fake_sleep(d):
        delays.append(d)
        await real_sleep(0)

    monkeypatch.setattr(asyncio, "sleep", fake_sleep)
    calls = []

    async def bad_respawner():
        calls.append(1)
        raise RuntimeError("spawn failed")

    c = RecoveryController(
        respawner=bad_respawner,
        config=RecoveryConfig(respawn_backoff_s=0.01, max_respawns=3),
    )
    assert await c._respawn("test") is False
    assert len(calls) == 3
    assert delays == [0.01, 0.02, 0.04]
    assert c.consecutive_respawn_failures == 3


async def test_respawn_success_resets_budget():
    registered = []

    async def good_respawner():
        return None

    async def register():
        registered.append(1)

    c = RecoveryController(
        respawner=good_respawner, register=register,
        config=RecoveryConfig(respawn_backoff_s=0.01, max_respawns=3),
    )
    c.consecutive_respawn_failures = 2  # prior failures, budget not blown
    assert await c._respawn("test") is True
    assert c.consecutive_respawn_failures == 0
    assert registered == [1]


# --------------------------------------------------------------------------
# unit: drain gates, router exclusion, admission drain
# --------------------------------------------------------------------------


async def test_set_draining_gates_admission_until_cleared():
    config = _config()
    sched = Scheduler(MigRunner(config), config, flight=FlightRecorder())
    sched.set_draining(True)
    sched.start()
    er = _request([1, 2, 3], 4)
    sched.add_request(er)
    await asyncio.sleep(0.1)
    assert er in sched.waiting and er.slot < 0, \
        "draining scheduler admitted a request"
    assert sched.metrics()["draining"] is True
    assert sched.watchdog_probe()["stopping"] is True
    sched.set_draining(False)
    toks, finish = await _collect(er)
    assert len(toks) == 4
    await sched.stop()


def test_router_never_picks_draining_worker():
    ks = KvScheduler(block_size=8)
    ks.update_metrics("sick", ForwardPassMetrics(
        request_total_slots=4, kv_total_blocks=64, draining=True))
    ks.update_metrics("ok", ForwardPassMetrics(
        request_total_slots=4, kv_total_blocks=64))
    for _ in range(20):
        assert ks.schedule(32, OverlapScores()).worker_id == "ok"
    assert ks.draining_skips == 20
    ks.update_metrics("ok", ForwardPassMetrics(
        request_total_slots=4, kv_total_blocks=64, draining=True))
    with pytest.raises(AllWorkersBusy):
        ks.schedule(32, OverlapScores())


async def test_admission_draining_rejects_and_flushes_queued():
    ac = AdmissionController(AdmissionConfig(
        limit=1, queue_depth=4, queue_timeout_s=30.0))
    await ac.acquire(1)
    queued = asyncio.ensure_future(ac.acquire(2))
    await asyncio.sleep(0.01)
    ac.set_draining(True)
    with pytest.raises(AdmissionRejected) as ei:
        await queued
    assert ei.value.outcome == "draining"
    with pytest.raises(AdmissionRejected) as ei:
        await ac.acquire(2)
    assert ei.value.outcome == "draining"
    ac.set_draining(False)
    ac.release()
    await ac.acquire(2)  # admits again after the drain clears


# --------------------------------------------------------------------------
# POST /admin/drain
# --------------------------------------------------------------------------


async def test_admin_drain_endpoint():
    import aiohttp

    from dynamo_tpu.http.service import HttpService, ModelManager

    service = HttpService(ModelManager(), host="127.0.0.1", port=0)
    await service.start()
    calls = {}

    async def drainer(mode, respawn):
        calls.update(mode=mode, respawn=respawn)
        return {"migrated": 2, "failed": 0}

    try:
        async with aiohttp.ClientSession() as s:
            url = f"http://127.0.0.1:{service.port}/admin/drain"
            async with s.post(url) as r:
                assert r.status == 501  # no controller attached
            service.drainer = drainer
            async with s.post(url + "?mode=migrate&respawn=1") as r:
                assert r.status == 200
                assert (await r.json())["migrated"] == 2
            async with s.post(url + "?mode=bogus") as r:
                assert r.status == 400
    finally:
        await service.stop()
    assert calls == {"mode": "migrate", "respawn": True}


# --------------------------------------------------------------------------
# migration plane: partial-stream poison on the receiver
# --------------------------------------------------------------------------


async def test_receiver_poisons_partial_migration():
    config = _config()
    dst = Scheduler(MigRunner(config), config, flight=FlightRecorder())
    dst.start()
    server = await MigrationServer(
        MigrationSink(dst, dst.runner)).start()
    try:
        state = MigrationState(
            request_id="m1", trace_id="t1",
            req=_request([1, 2, 3], 8).req.to_wire(),
            committed_tokens=[1, 2, 3, 9], resume_tokens=[],
            pending_token=7, generated=2, base_key=[1, 2],
            prompt_lps_emitted=False, kv_block_size=config.kv_block_size,
        )
        reader, writer = await asyncio.open_connection(
            server.host, server.port)
        pack_frame(writer, {"type": "mig_begin", "state": state.to_wire(),
                       "nblocks": 2})
        await writer.drain()
        ack = await read_header(reader, "migration")
        assert ack["ok"]
        assert dst.allocator.used == 2  # reservation held
        writer.close()  # sender dies before commit
        for _ in range(50):
            if dst.allocator.used == 0:
                break
            await asyncio.sleep(0.02)
        assert dst.allocator.used == 0, "poisoned reservation leaked blocks"
        assert all(s is None for s in dst.slots), "nothing may be installed"
    finally:
        await server.close()
        await dst.stop()


# --------------------------------------------------------------------------
# live migration e2e: healthy drain (rolling update), hot KV transfer
# --------------------------------------------------------------------------


def _drive_migration(wedge: bool, max_tokens=48, conn_drop=False):
    """Run a request on a source engine, disturb it mid-stream (admin
    drain, or a DYN_FAULT wedge + watchdog trip), and return everything
    the assertions need."""
    config = _config()
    prompt = [1, 17, 43]
    out = {}

    async def go():
        src_runner = MigRunner(config, sync_delay=0.02)
        dst_runner = MigRunner(config)
        src = Scheduler(src_runner, config, flight=FlightRecorder())
        dst = Scheduler(dst_runner, config, flight=FlightRecorder())
        src.start()
        dst.start()
        server = await MigrationServer(
            MigrationSink(dst, dst_runner)).start()
        peers = [{"host": server.host, "port": server.port,
                  "engine_id": "dst"}]
        if conn_drop:
            # first attempt's connection is dropped by the fault — the
            # controller must fail over to the next peer (same receiver)
            peers = peers + peers
        wd = None
        if wedge:
            wd = StallWatchdog(
                probe=src.watchdog_probe, requests=src.request_table,
                flight=src.flight, interval_s=0.02, stall_s=0.15,
            ).start()
        respawned = []
        hooks = []

        async def respawner():
            respawned.append(1)
            return None

        async def register():
            hooks.append("register")

        async def deregister():
            hooks.append("deregister")

        controller = RecoveryController(
            engine_id="src", scheduler=src, runner=src_runner,
            watchdog=wd, peers=lambda: peers, respawner=respawner,
            register=register, deregister=deregister,
            config=RecoveryConfig(drain_grace_s=0.05,
                                  respawn_backoff_s=0.01),
            flight=src.flight,
        ).attach()

        er = _request(prompt, max_tokens)
        src.add_request(er)
        toks, finish = await _collect(er, limit=6)  # stream is live
        assert finish is None, "request finished before the disturbance"
        if wedge:
            # next decode sync wedges in its executor thread; detection
            # and recovery must be fully automatic from here
            faults.arm("decode_burst_hang", "once")
        else:
            if conn_drop:
                faults.arm("transfer_conn_drop", "once")
            summary = await controller.drain(hard=False, reason="admin")
            out["summary"] = summary
        rest, finish = await _collect(er)
        out["toks"], out["finish"] = toks + rest, finish
        if wedge:
            out["trips"] = [t["reason"] for t in wd.trips]
            # the automatic ladder records its summary when it completes
            for _ in range(100):
                if controller.recoveries:
                    break
                await asyncio.sleep(0.02)
            out["summary"] = controller.recoveries[0]
            out["respawned"] = bool(respawned)
        out["hooks"] = hooks
        out["stages"] = [s for s, _ in er.ctx.stages]
        # cluster-stitched trace material: the peer's span export rode
        # the mig_end frame back into the source context
        out["remote"] = list(er.ctx.remote_spans)
        out["src_used"] = src.allocator.used
        out["src_metrics"] = src.metrics()
        out["dst_steps"] = dst.steps
        out["dst_scattered"] = list(dst_runner.scattered)
        out["migrations"] = controller.registry.render()
        faults.release()
        if wd is not None:
            await wd.stop()
        await controller.close()
        await server.close()
        await dst.stop()
        await src.stop()
        out["dst_used"] = dst.allocator.used

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(go())
    finally:
        loop.close()
    out["want"] = _baseline(prompt, max_tokens)
    return out


def test_admin_drain_migrates_hot_stream_byte_identical():
    out = _drive_migration(wedge=False)
    assert out["summary"]["migrated"] == 1
    assert out["summary"]["failed"] == 0
    # byte-identical continuation across the engine hop
    assert (out["toks"], out["finish"]) == out["want"]
    # hot: the KV actually crossed the wire into the peer's cache
    assert out["dst_scattered"], "no KV was scattered on the peer"
    assert out["dst_steps"] > 0, "the peer never decoded"
    assert 'mode="hot",outcome="committed"' in out["migrations"] \
        or 'outcome="committed",mode="hot"' in out["migrations"]
    # zero leaks on either side, and the hop is traceable from BOTH
    # ends: the source stamps migration.relay at commit, the peer's
    # migration.resume (and its decode tail) ships back on mig_end
    assert out["src_used"] == 0
    assert out["dst_used"] == 0
    assert "migration.relay" in out["stages"]
    peer_sets = [rs for rs in out["remote"]
                 if rs["source"] == "migration_peer"]
    assert peer_sets, "peer span export never arrived on mig_end"
    peer_names = [n for n, _ in peer_sets[0]["spans"]]
    assert "migration.resume" in peer_names
    assert "completion" in peer_names
    assert "deregister" in out["hooks"]


def test_migration_conn_drop_fails_over_to_next_peer():
    out = _drive_migration(wedge=False, conn_drop=True)
    assert out["summary"]["migrated"] == 1
    assert (out["toks"], out["finish"]) == out["want"]
    assert out["src_used"] == 0 and out["dst_used"] == 0


# --------------------------------------------------------------------------
# the chaos e2e: wedge → trip → drain → migrate → respawn
# --------------------------------------------------------------------------


def test_wedge_trips_drain_migrate_respawn():
    out = _drive_migration(wedge=True)
    # detection: exactly one decode_stall for one wedge
    assert out["trips"] == ["decode_stall"]
    # recovery: automated drain migrated the in-flight request (cold —
    # a wedged device cannot be gathered from) and respawned
    assert out["summary"]["reason"] == "decode_stall"
    assert out["summary"]["migrated"] == 1
    assert out["summary"]["failed"] == 0
    assert out["summary"]["respawned"] is True
    assert out["respawned"]
    assert out["hooks"] == ["deregister", "register"]
    # the continued stream is byte-identical to an unwedged run
    assert (out["toks"], out["finish"]) == out["want"]
    # zero leaked blocks on the source, none on the target either
    assert out["src_used"] == 0
    assert out["dst_used"] == 0
    # the draining snapshot excludes the sick worker from routing
    sick = ForwardPassMetrics.from_wire(out["src_metrics"])
    assert sick.draining is True
    ks = KvScheduler(block_size=8)
    ks.update_metrics("src", sick)
    ks.update_metrics("dst", ForwardPassMetrics(
        request_total_slots=4, kv_total_blocks=64))
    for _ in range(10):
        assert ks.schedule(16, OverlapScores()).worker_id == "dst"
    # the hop shows up in the request's stitched trace from both ends:
    # relay on the source, resume (cold re-prefill + decode) on the peer
    assert "migration.relay" in out["stages"]
    peer_sets = [rs for rs in out["remote"]
                 if rs["source"] == "migration_peer"]
    assert peer_sets, "peer span export never arrived on mig_end"
    assert "migration.resume" in [n for n, _ in peer_sets[0]["spans"]]


# --------------------------------------------------------------------------
# supervised-child satellite: restart telemetry + down listeners
# --------------------------------------------------------------------------


@pytest.mark.asyncio
async def test_child_exit_fault_respawns_with_restart_metric(tmp_path):
    from test_subprocess_engine import ECHO_ENGINE, child_env, write_engine

    from dynamo_tpu.llm.engines.subprocess_host import SubprocessEngine
    from dynamo_tpu.runtime.engine import Context, EngineError

    env = child_env()
    env["DYN_FAULT"] = "child_exit:once"
    eng = await SubprocessEngine.load(
        write_engine(tmp_path, ECHO_ENGINE), child_env=env,
        restart_backoff_s=0.05,
    )
    downs = []
    eng.add_down_listener(downs.append)
    try:
        # first request: the child exits hard before serving it
        with pytest.raises(EngineError):
            async for _ in eng.generate(Context({"token_ids": [1]})):
                pass
        # disarm: DYN_FAULT is re-parsed by every fresh child, so the
        # "once" would otherwise fire again in the respawned process
        eng.child_env.pop("DYN_FAULT", None)
        # next request respawns and serves
        toks = [
            t
            for c in [c async for c in eng.generate(
                Context({"token_ids": [3, 1]}))]
            for t in c.get("token_ids", [])
        ]
        assert toks == [3, 1]
        assert eng.spawn_count == 2
        assert downs, "down listener never fired"
        text = eng.host_registry.render()
        assert "dynamo_engine_restarts_total" in text
        assert 'dynamo_engine_restarts_total{reason="exit"} 1.0' in text \
            or 'dynamo_engine_restarts_total{reason="disconnect"} 1.0' in text
    finally:
        await eng.close()


# --------------------------------------------------------------------------
# draining rejections are retryable (engine facade + HTTP mapping)
# --------------------------------------------------------------------------


async def test_draining_engine_rejects_with_retryable_error():
    from dynamo_tpu.engine.serving import JaxServingEngine
    from dynamo_tpu.runtime.engine import Context, EngineDrainingError

    config = _config()
    sched = Scheduler(MigRunner(config), config, flight=FlightRecorder())
    engine = JaxServingEngine(sched.runner, sched, config)
    sched.set_draining(True)
    with pytest.raises(EngineDrainingError):
        async for _ in engine.generate(Context(_request([1, 2, 3], 4).req)):
            pass


async def test_http_maps_draining_to_503_with_retry_after():
    import aiohttp

    from dynamo_tpu.http.service import HttpService, ModelManager
    from dynamo_tpu.runtime.engine import EngineDrainingError

    class DrainingEngine:
        def generate(self, ctx):
            async def gen():
                raise EngineDrainingError("engine is draining")
                yield  # pragma: no cover

            return gen()

    manager = ModelManager()
    manager.add_chat_model("m", DrainingEngine())
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"http://127.0.0.1:{service.port}/v1/chat/completions",
                json={"model": "m",
                      "messages": [{"role": "user", "content": "hi"}]},
            ) as r:
                assert r.status == 503
                assert r.headers.get("Retry-After") == "1"
                body = await r.json()
                assert body["error"]["type"] == "service_unavailable"
    finally:
        await service.stop()


async def test_receiver_nacks_oversized_migration():
    """A sequence the target cannot hold (beyond its max_model_len /
    block-table width) must nack at reserve — before any state mutates
    on the healthy peer — not blow up inside install."""
    from dynamo_tpu.recovery import MigrationRejected

    config = _config(max_model_len=64)
    dst = Scheduler(MigRunner(config), config, flight=FlightRecorder())
    sink = MigrationSink(dst, dst.runner)
    # hot: 100 committed tokens >= the target's 64-token horizon
    state = MigrationState(
        request_id="big", trace_id="t",
        req=_request(list(range(1, 10)), 8).req.to_wire(),
        committed_tokens=list(range(1, 101)), resume_tokens=[],
        pending_token=7, generated=91, base_key=[1, 2],
        prompt_lps_emitted=False, kv_block_size=config.kv_block_size,
    )
    with pytest.raises(MigrationRejected):
        sink.reserve(state, 13)
    # cold: prompt + resume past the horizon nacks too
    state2 = MigrationState(
        request_id="big2", trace_id="t",
        req=_request(list(range(1, 60)), 8).req.to_wire(),
        committed_tokens=[], resume_tokens=list(range(1, 10)),
        pending_token=-1, generated=9, base_key=[1, 2],
        prompt_lps_emitted=False, kv_block_size=config.kv_block_size,
    )
    with pytest.raises(MigrationRejected):
        sink.reserve(state2, 0)
    # geometry mismatch on the block table width
    state3 = MigrationState(
        request_id="wide", trace_id="t",
        req=_request([1, 2, 3], 8).req.to_wire(),
        committed_tokens=[1, 2, 3, 4], resume_tokens=[],
        pending_token=7, generated=2, base_key=[1, 2],
        prompt_lps_emitted=False, kv_block_size=config.kv_block_size,
    )
    with pytest.raises(MigrationRejected):
        sink.reserve(state3, config.blocks_per_seq + 1)
    assert dst.allocator.used == 0
    assert all(s is None for s in dst.slots)


# --------------------------------------------------------------------------
# stream re-bind: the source relay exits at the handoff (ISSUE 12
# satellite; the PR 8 carry-over)
# --------------------------------------------------------------------------


async def test_stream_rebind_lets_source_relay_exit():
    """A follow_migrated_stream consumer sees the `migrated` control
    frame, attaches directly to the peer, the peer's pump hands off
    (mig_handoff → the source's relay ends while the peer is STILL
    generating), and the continued stream is byte-identical."""
    from dynamo_tpu.recovery.migration import follow_migrated_stream
    from dynamo_tpu.telemetry.flight import flight_recorder

    config = _config()
    prompt = [1, 17, 43]
    max_tokens = 48
    src_runner = MigRunner(config, sync_delay=0.02)
    # the peer decodes slowly too, so the attach handshake (and the
    # handoff) reliably lands mid-stream, not after it ended
    dst_runner = MigRunner(config, sync_delay=0.02)
    src = Scheduler(src_runner, config, flight=FlightRecorder())
    dst = Scheduler(dst_runner, config, flight=FlightRecorder())
    src.start()
    dst.start()
    server = await MigrationServer(MigrationSink(dst, dst_runner)).start()
    controller = RecoveryController(
        engine_id="src", scheduler=src, runner=src_runner,
        peers=lambda: [{"host": server.host, "port": server.port,
                        "engine_id": "dst"}],
        config=RecoveryConfig(drain_grace_s=0.05),
        flight=src.flight,
    )
    er = _request(prompt, max_tokens)
    src.add_request(er)

    async def queue_stream():
        while True:
            out = await er.out_queue.get()
            if out is None:
                return
            yield out

    toks = []
    finish = None

    async def consume():
        nonlocal finish
        stream = follow_migrated_stream(queue_stream(), ctx=er.ctx)
        async for out in stream:
            assert out.migrated is None, "control frame leaked"
            toks.extend(out.token_ids)
            if out.finish_reason is not None:
                finish = out.finish_reason

    async def watch_relay():
        # how many tokens the CLIENT had when the source's relay duty
        # ended — the handoff must land mid-stream, not at its end
        while not controller._relays:
            await asyncio.sleep(0.002)
        relay = next(iter(controller._relays))
        await asyncio.wait({relay})
        return len(toks)

    loop = asyncio.get_running_loop()
    task = loop.create_task(consume())
    watcher = loop.create_task(watch_relay())
    while len(toks) < 6:  # the stream is live on the source
        await asyncio.sleep(0.01)
    summary = await controller.drain(hard=False, reason="admin")
    assert summary["migrated"] == 1 and summary["failed"] == 0
    relay_done_at_token = await asyncio.wait_for(watcher, timeout=60)
    await asyncio.wait_for(task, timeout=60)

    # _baseline drives its own event loop — run it in a thread
    want = await asyncio.to_thread(_baseline, prompt, max_tokens)
    assert (toks, finish) == want
    # the handoff actually happened: the source's relay duty ended
    # while the peer was still generating (the source could exit here)
    kinds = [e["kind"] for e in flight_recorder().snapshot()]
    assert "recovery.migrate_handoff" in kinds
    assert relay_done_at_token is not None
    assert relay_done_at_token < len(want[0]), (
        "relay only ended at stream end — no handoff happened")
    # the peer's span export arrived over the ATTACHED connection
    peer_sets = [rs for rs in er.ctx.remote_spans
                 if rs["source"] == "migration_peer"]
    assert peer_sets and "migration.resume" in [
        n for n, _ in peer_sets[0]["spans"]]
    # zero leaks on either side
    assert src.allocator.used == 0
    await controller.close()
    await server.close()
    await dst.stop()
    await src.stop()
    assert dst.allocator.used == 0


async def test_rebind_attach_failure_falls_back_to_relay():
    """If the consumer cannot reach the peer (e.g. a NATed client), the
    relay keeps carrying the stream to its end — byte-identical, no
    error surfaced."""
    from dynamo_tpu.protocols.common import EngineOutput, FinishReason
    from dynamo_tpu.recovery.migration import follow_migrated_stream

    async def fake_stream():
        # a source stream whose migrated frame points at a dead port,
        # then relays the full stream itself (what the source does
        # when nobody attaches)
        yield EngineOutput(token_ids=[1])
        yield EngineOutput(migrated={"host": "127.0.0.1", "port": 9,
                                     "resume_id": "x"})
        yield EngineOutput(token_ids=[2])
        yield EngineOutput(token_ids=[3],
                           finish_reason=FinishReason.LENGTH)

    toks, finish = [], None
    async for out in follow_migrated_stream(fake_stream()):
        toks.extend(out.token_ids)
        finish = out.finish_reason or finish
    assert toks == [1, 2, 3]
    assert finish == FinishReason.LENGTH
