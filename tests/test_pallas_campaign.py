"""Interpret-mode differentials for the kernel campaign.

Three kernels, each checked against the engine's pre-existing XLA
formulation (the same strategy as tests/test_pallas_decode.py):

- the sequence-parallel ring-prefill's paged prefix walk
  (ops/pallas_sp.py via parallel/sequence.sp_chunk_attention) vs the
  XLA gather route, plus a jaxpr audit that the kernel route never
  materializes the gathered [1, W·bs, KVH, D] prefix;
- the verify kernel's softcap / sinks / fp8-KV specializations
  (ops/pallas_decode.paged_verify_attention) vs the gather/softmax
  reference;
- the fused sampling epilogue (ops/pallas_epilogue.py) vs the dense
  ladder in engine/sampling.py — BIT-identical, not allclose: the
  kernel replicates the ladder's exact op sequence so the Pallas and
  XLA engines emit the same tokens from the same seeds.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine import sampling as S
from dynamo_tpu.ops.attention import paged_attention
from dynamo_tpu.ops.pallas_decode import paged_verify_attention
from dynamo_tpu.ops.pallas_epilogue import fused_sampling_epilogue
from dynamo_tpu.parallel.mesh import make_mesh
from dynamo_tpu.parallel.sequence import sp_chunk_attention


# --------------------------------------------------------------------------
# SP ring-prefill: paged prefix-walk kernel vs the XLA gather route
# --------------------------------------------------------------------------

_SP_DIMS = dict(b=1, s=16, h=4, kvh=2, d=16, L=2, N=8, bs=8, W=8)


def _sp_case(seed=0):
    rng = np.random.default_rng(seed)
    c = _SP_DIMS
    q = jnp.asarray(rng.normal(size=(c["b"], c["s"], c["h"], c["d"])),
                    jnp.float32)
    k = jnp.asarray(rng.normal(size=(c["b"], c["s"], c["kvh"], c["d"])),
                    jnp.float32)
    v = jnp.asarray(rng.normal(size=(c["b"], c["s"], c["kvh"], c["d"])),
                    jnp.float32)
    kc = jnp.asarray(
        rng.normal(size=(c["L"], c["N"], c["bs"], c["kvh"], c["d"])),
        jnp.float32)
    vc = jnp.asarray(
        rng.normal(size=(c["L"], c["N"], c["bs"], c["kvh"], c["d"])),
        jnp.float32)
    btab = jnp.asarray(rng.permutation(c["N"])[: c["W"]], jnp.int32)[None, :]
    return q, k, v, kc, vc, btab


@pytest.mark.parametrize(
    "chunk_start,context_len",
    [
        (24, 37),   # multi-page committed prefix ending mid-page
        (0, 13),    # first chunk: empty prefix, ring pass only
        (19, 35),   # prefix boundary mid-page (partial last page DMA)
    ],
)
def test_sp_kernel_matches_gather_route(chunk_start, context_len):
    """The kernel route (ring partials over fresh K/V + the paged
    prefix walk, exp-weighted merge) must match the gather route's one
    joint softmax row-for-row."""
    q, k, v, kc, vc, btab = _sp_case()
    mesh = make_mesh({"sp": 4})
    ref = sp_chunk_attention(
        q, k, v, kc, vc, btab, chunk_start, context_len, 1, mesh,
        impl="xla",
    )
    out = sp_chunk_attention(
        q, k, v, kc, vc, btab, chunk_start, context_len, 1, mesh,
        impl="pallas", interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5,
    )


def _iter_eqns(jaxpr):
    for eqn in jaxpr.eqns:
        yield eqn
        for val in eqn.params.values():
            items = val if isinstance(val, (list, tuple)) else [val]
            for item in items:
                inner = getattr(item, "jaxpr", item)
                if hasattr(inner, "eqns"):
                    yield from _iter_eqns(inner)


def _materializes_prefix(fn, *args):
    """Does any intermediate in fn's jaxpr carry the full gathered
    prefix — a [*, W·bs, ...] array (every cache slot widthwise)?"""
    full = _SP_DIMS["W"] * _SP_DIMS["bs"]
    jaxpr = jax.make_jaxpr(fn)(*args)
    for eqn in _iter_eqns(jaxpr.jaxpr):
        for var in eqn.outvars:
            shape = getattr(getattr(var, "aval", None), "shape", ())
            if len(shape) >= 4 and full in shape:
                return True
    return False


def test_sp_kernel_route_never_materializes_the_prefix():
    """The point of the page-walk kernel: the committed prefix streams
    page-by-page through the DMA scratch and NEVER exists as a
    [1, W·bs, KVH, D] array. The gather route is the positive control —
    its jaxpr must show the materialized prefix this audit looks for."""
    q, k, v, kc, vc, btab = _sp_case()
    mesh = make_mesh({"sp": 4})

    def route(impl):
        return lambda *a: sp_chunk_attention(
            *a, 24, 37, 1, mesh, impl=impl, interpret=(impl == "pallas"),
        )

    assert _materializes_prefix(route("xla"), q, k, v, kc, vc, btab)
    assert not _materializes_prefix(route("pallas"), q, k, v, kc, vc, btab)


# --------------------------------------------------------------------------
# verify kernel specializations: softcap / sinks / fp8 KV
# --------------------------------------------------------------------------


def _verify_case(seed, layers=2, b=2, h=4, kvh=2, d=32, bs=8, w=8, s=4):
    rng = np.random.default_rng(seed)
    n_blocks = b * w + 3
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k_cache = jnp.asarray(
        rng.standard_normal((layers, n_blocks, bs, kvh, d)), jnp.float32)
    v_cache = jnp.asarray(
        rng.standard_normal((layers, n_blocks, bs, kvh, d)), jnp.float32)
    bt = jnp.asarray(
        rng.permutation(n_blocks)[: b * w].reshape(b, w), jnp.int32)
    ctx = jnp.asarray([29, 53], jnp.int32)
    positions = (ctx - s)[:, None] + jnp.arange(s)[None, :]
    return q, k_cache, v_cache, bt, ctx, positions, s


def test_verify_softcap_matches_xla_reference():
    """Gemma-2-class verify: logit soft-capping is a static Mosaic
    specialization of the verify kernel, checked against the gather
    reference's cap·tanh(logits/cap)."""
    q, kc, vc, bt, ctx, positions, s = _verify_case(21)
    ref = paged_attention(q, kc[1], vc[1], bt, positions, ctx, softcap=30.0)
    out = paged_verify_attention(
        q, kc, vc, bt, ctx - s, ctx,
        layer_idx=jnp.int32(1), interpret=True, softcap=30.0,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5,
    )


def test_verify_sinks_matches_xla_reference():
    """GPT-OSS-class verify: per-head sink logits join each query's
    softmax denominator (no value contribution), alongside the runtime
    sliding window the family alternates."""
    rng = np.random.default_rng(22)
    q, kc, vc, bt, ctx, positions, s = _verify_case(22)
    sinks = jnp.asarray(rng.standard_normal(q.shape[2]), jnp.float32)
    ref = paged_attention(
        q, kc[0], vc[0], bt, positions, ctx,
        sliding_window=16, sinks=sinks,
    )
    out = paged_verify_attention(
        q, kc, vc, bt, ctx - s, ctx,
        layer_idx=jnp.int32(0), interpret=True,
        window=jnp.asarray(16, jnp.int32), sinks=sinks,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5,
    )


@pytest.mark.parametrize("variant", ["plain", "softcap", "sinks"])
def test_verify_fp8_kv_matches_xla_reference(variant):
    """fp8 KV serving x verify: the cache stores e4m3 and the kernel
    upcasts after the DMA — compared against the gather reference over
    the SAME stored values (upcast at the gather), so the check is
    exact, not a quantization-error bound."""
    rng = np.random.default_rng(23)
    q, kc, vc, bt, ctx, positions, s = _verify_case(23)
    kf8 = kc.astype(jnp.float8_e4m3fn)
    vf8 = vc.astype(jnp.float8_e4m3fn)
    k32 = kf8.astype(jnp.float32)
    v32 = vf8.astype(jnp.float32)
    ref_kw, kern_kw = {}, {}
    if variant == "softcap":
        ref_kw["softcap"] = kern_kw["softcap"] = 30.0
    elif variant == "sinks":
        sinks = jnp.asarray(rng.standard_normal(q.shape[2]), jnp.float32)
        ref_kw = dict(sliding_window=16, sinks=sinks)
        kern_kw = dict(window=jnp.asarray(16, jnp.int32), sinks=sinks)
    ref = paged_attention(q, k32[1], v32[1], bt, positions, ctx, **ref_kw)
    out = paged_verify_attention(
        q, kf8, vf8, bt, ctx - s, ctx,
        layer_idx=jnp.int32(1), interpret=True, **kern_kw,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5,
    )


# --------------------------------------------------------------------------
# fused sampling epilogue: bit-identical to the dense ladder
# --------------------------------------------------------------------------

_B, _V, _NS = 6, 64, 8
_MAX_LEN = 512


def _epilogue_case():
    rng = np.random.default_rng(1)
    last_logits = jnp.asarray(rng.normal(size=(_B, _V)) * 4, jnp.float32)
    counts = jnp.asarray(rng.integers(0, 3, size=(_NS, _V)), jnp.int32)
    seen = jnp.asarray(rng.integers(0, 2, size=(_NS, _V)), jnp.bool_)
    bias = jnp.asarray(rng.normal(size=(_NS, _V)) * 0.5, jnp.float32)
    # one row per regime: greedy, top-k, top-p, min-p + penalties,
    # top-k + repetition, greedy again
    params = S.SamplingParams(
        temperature=jnp.asarray([0.0, 0.7, 1.0, 1.3, 0.9, 0.0], jnp.float32),
        top_k=jnp.asarray([0, 5, 0, 0, 3, 0], jnp.int32),
        top_p=jnp.asarray([1.0, 1.0, 0.9, 1.0, 0.8, 1.0], jnp.float32),
        min_p=jnp.asarray([0.0, 0.0, 0.0, 0.2, 0.05, 0.0], jnp.float32),
        presence_penalty=jnp.asarray(
            [0.0, 0.5, 0.0, 1.1, 0.0, 0.0], jnp.float32),
        frequency_penalty=jnp.asarray(
            [0.0, 0.0, 0.3, 0.2, 0.0, 0.0], jnp.float32),
        repetition_penalty=jnp.asarray(
            [1.0, 1.2, 1.0, 1.05, 1.3, 1.0], jnp.float32),
        keys=jnp.asarray(rng.integers(0, 2**32, size=(_B, 2)), jnp.uint32),
        counters=jnp.asarray(rng.integers(0, 100, size=(_B,)), jnp.int32),
    )
    scalars = (
        params.temperature, params.top_k, params.top_p, params.min_p,
        params.presence_penalty, params.frequency_penalty,
        params.repetition_penalty,
    )
    # the engine precomputes the gumbel field outside the kernel —
    # argmax(gumbel + logits) IS jax.random.categorical's sampler, so
    # sharing row keys keeps the token stream identical to the ladder
    row_keys = S._row_keys(params)
    gum = jax.vmap(
        lambda kk: jax.random.gumbel(kk, (_V,), jnp.float32))(row_keys)
    return rng, last_logits, counts, seen, bias, params, scalars, gum


def _epilogue_reference(case, slots, commit, extra=None, finish=None):
    _, last_logits, counts, seen, bias, params, _, _ = case
    row_bias = bias[slots]
    if extra is not None:
        row_bias = row_bias + extra
    nt = S.sample(last_logits, params, counts[slots], seen[slots], row_bias)
    logp = jax.nn.log_softmax((last_logits + row_bias).astype(jnp.float32))
    lps = logp[jnp.arange(_B), nt]
    cnt_out = counts.at[slots, nt].add(commit.astype(jnp.int32))
    if finish is None:
        return nt, lps, cnt_out
    gen, pos, min_new, max_new, stop_ids, ring, sh, sl = finish
    gen_n = gen + commit.astype(jnp.int32)
    hard = S.device_finish_mask(
        nt, gen_n, pos, stop_ids, min_new, max_new, _MAX_LEN)
    ring_n = S.ring_push(ring, nt, commit)
    cand = S.stop_candidate_mask(ring_n, gen_n, min_new, sh, sl)
    return nt, lps, cnt_out, hard, cand, ring_n


def _assert_bit_identical(got, ref):
    assert len(got) == len(ref)
    for i, (g, r) in enumerate(zip(got, ref)):
        np.testing.assert_array_equal(
            np.asarray(g), np.asarray(r), err_msg=f"output {i}")


def test_epilogue_bit_identical_plain_and_guided():
    """Mixed sampling regimes in one batch, aliased in-kernel count
    commit; then the guided-decoding extra-bias operand on top."""
    case = _epilogue_case()
    rng, last_logits, counts, seen, bias, _, scalars, gum = case
    slots = jnp.asarray([3, 0, 5, 1, 7, 2], jnp.int32)  # unique
    commit = jnp.asarray([1, 1, 0, 1, 1, 0], jnp.bool_)

    got = fused_sampling_epilogue(
        last_logits, gum, scalars, counts, seen, bias, slots, commit,
        max_model_len=_MAX_LEN, interpret=True,
    )
    _assert_bit_identical(got, _epilogue_reference(case, slots, commit))

    extra = jnp.where(
        jnp.asarray(rng.integers(0, 4, size=(_B, _V))) == 0, -1e9, 0.0,
    ).astype(jnp.float32)
    got = fused_sampling_epilogue(
        last_logits, gum, scalars, counts, seen, bias, slots, commit,
        extra_bias=extra, max_model_len=_MAX_LEN, interpret=True,
    )
    _assert_bit_identical(
        got, _epilogue_reference(case, slots, commit, extra=extra))


def test_epilogue_bit_identical_finish_fusion():
    """The chained-burst tail: device_finish_mask, the suffix-ring push
    and the rolling-hash stop-sequence candidate mask all fused behind
    sampling — against the unfused engine/sampling.py ops."""
    case = _epilogue_case()
    rng, last_logits, counts, seen, bias, _, scalars, gum = case
    slots = jnp.asarray([3, 0, 5, 1, 7, 2], jnp.int32)
    commit = jnp.asarray([1, 1, 0, 1, 1, 0], jnp.bool_)

    gen = jnp.asarray(rng.integers(0, 40, size=(_B,)), jnp.int32)
    pos = jnp.asarray(rng.integers(0, 500, size=(_B,)), jnp.int32)
    min_new = jnp.asarray([0, 0, 5, 0, 60, 0], jnp.int32)
    max_new = jnp.asarray([39, 100, 100, 2, 100, 100], jnp.int32)
    stop_ids = jnp.full((_B, S.STOP_ID_WIDTH), -1, jnp.int32)
    stop_ids = stop_ids.at[:, 0].set(7)  # token 7 is an eos everywhere
    ring = jnp.asarray(
        np.stack([
            S.ring_init(rng.integers(0, _V, size=20).tolist())
            for _ in range(_B)
        ]),
        jnp.int32,
    )
    # per-row watched suffixes whose hash prefix matches the live ring
    # tail, so a sampled continuation CAN complete them
    sh = np.zeros((_B, S.STOP_SEQ_WIDTH), np.uint32)
    sl = np.zeros((_B, S.STOP_SEQ_WIDTH), np.int32)
    for r in range(_B):
        sh[r, 0] = S.stop_seq_hash([int(ring[r, -1]), 11])
        sl[r, 0] = 2
        sh[r, 1] = S.stop_seq_hash([int(t) for t in ring[r, -3:]])
        sl[r, 1] = 3
    fin = (gen, pos, min_new, max_new, stop_ids, ring,
           jnp.asarray(sh), jnp.asarray(sl))

    got = fused_sampling_epilogue(
        last_logits, gum, scalars, counts, seen, bias, slots, commit,
        finish=fin, max_model_len=_MAX_LEN, interpret=True,
    )
    _assert_bit_identical(
        got, _epilogue_reference(case, slots, commit, finish=fin))


def test_epilogue_bit_identical_duplicate_slots():
    """The batched-prefill step's pad rows share slot 0 — the aliased
    in-kernel commit would double-count them, so that path runs
    alias_counts=False (the commit scatters outside the kernel) and
    must still be bit-identical."""
    case = _epilogue_case()
    _, last_logits, counts, seen, bias, _, scalars, gum = case
    slots = jnp.asarray([0, 2, 0, 0, 4, 0], jnp.int32)
    commit = jnp.asarray([1, 1, 0, 0, 1, 0], jnp.bool_)
    got = fused_sampling_epilogue(
        last_logits, gum, scalars, counts, seen, bias, slots, commit,
        alias_counts=False, max_model_len=_MAX_LEN, interpret=True,
    )
    _assert_bit_identical(got, _epilogue_reference(case, slots, commit))
