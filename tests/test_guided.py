"""Guided decoding (vLLM-style ``guided_choice``).

The preprocessor tokenizes each choice; the engine walks a token trie
and rewrites the sampler's bias row per step, so the completion is
exactly one of the choices under greedy OR sampled decoding. Reference
analog: the guided decoding of the engines the reference delegates to
(vLLM guided_choice; the reference proxies OpenAI-level JSON through)."""

import asyncio

import numpy as np
import pytest

import jax

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.serving import JaxServingEngine
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.models import llama
from dynamo_tpu.protocols.common import (
    PreprocessedRequest, SamplingOptions, StopConditions,
)
from dynamo_tpu.runtime.engine import Context

CFG = ModelConfig(
    vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=2,
    num_heads=4, num_kv_heads=2, head_dim=8, attention_impl="xla",
)

CHOICES = [[5, 9, 7], [5, 2], [40, 41, 42, 43]]


async def _generate(engine, *, temperature=0.0, seed=None, choices=CHOICES,
                    max_tokens=8, logit_bias=None):
    req = PreprocessedRequest(
        token_ids=[1, 17, 43, 99],
        stop_conditions=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        sampling_options=SamplingOptions(
            temperature=temperature, seed=seed, logit_bias=logit_bias,
            guided_choice_token_ids=choices,
        ),
    )
    toks, finish = [], None
    async for out in engine.generate(Context(req)):
        toks.extend(out["token_ids"])
        if out.get("finish_reason"):
            finish = out["finish_reason"]
    return toks, finish


async def _engine(**cfg_kw):
    econfig = EngineConfig(
        model=CFG, max_batch_size=2, max_model_len=64, kv_block_size=8,
        num_kv_blocks=32, dtype="float32", prefill_buckets=[16],
        allow_random_weights=True, **cfg_kw,
    )
    mdc = ModelDeploymentCard(display_name="t", slug="t")
    params = llama.init_params(CFG, jax.random.PRNGKey(0), jax.numpy.float32)
    return await JaxServingEngine.create(
        mdc, engine_config=econfig, params=params, warmup=False)


def test_guided_choice_greedy_and_sampled():
    async def run():
        engine = await _engine()
        greedy, finish = await _generate(engine)
        assert greedy in CHOICES and finish == "stop"
        # sampled runs stay inside the choice set too (mask, not luck:
        # 124 of 128 vocab ids are banned at the root)
        seen = set()
        for seed in range(4):
            toks, fin = await _generate(engine, temperature=1.5, seed=seed)
            assert toks in CHOICES and fin == "stop"
            seen.add(tuple(toks))
        await engine.close()
        return greedy, seen

    greedy, seen = asyncio.run(run())
    assert greedy  # non-empty


def test_guided_prefix_choice_resolves_to_longer_or_stops():
    """[5] is a strict prefix of [5, 9, 7]: after emitting 5 the engine
    allows {9} ∪ eos; with ignore_eos + no eos in vocab path the longer
    choice wins deterministically under greedy."""
    async def run():
        engine = await _engine()
        toks, fin = await _generate(
            engine, choices=[[5], [5, 9, 7]], max_tokens=8)
        await engine.close()
        return toks, fin

    toks, fin = asyncio.run(run())
    assert toks in ([5], [5, 9, 7]) and fin == "stop"


def test_guided_respects_max_tokens():
    async def run():
        engine = await _engine()
        toks, fin = await _generate(
            engine, choices=[[40, 41, 42, 43]], max_tokens=2)
        await engine.close()
        return toks, fin

    toks, fin = asyncio.run(run())
    assert toks == [40, 41] and fin == "length"


def test_guided_excluded_from_speculation_paths():
    """A guided row must not ride ngram speculation or the fused burst
    (its mask changes per step) — and the output stays constrained."""
    async def run():
        engine = await _engine(spec_ngram_tokens=4, multi_step_decode=4)
        toks, fin = await _generate(engine)
        await engine.close()
        return toks, fin

    toks, fin = asyncio.run(run())
    assert toks in CHOICES and fin == "stop"
