"""Guided decoding (vLLM-style ``guided_choice``).

The preprocessor tokenizes each choice; the engine walks a token trie
and rewrites the sampler's bias row per step, so the completion is
exactly one of the choices under greedy OR sampled decoding. Reference
analog: the guided decoding of the engines the reference delegates to
(vLLM guided_choice; the reference proxies OpenAI-level JSON through)."""

import asyncio
import os

import numpy as np
import pytest

import jax

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.serving import JaxServingEngine
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.models import llama
from dynamo_tpu.protocols.common import (
    PreprocessedRequest, SamplingOptions, StopConditions,
)
from dynamo_tpu.runtime.engine import Context

CFG = ModelConfig(
    vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=2,
    num_heads=4, num_kv_heads=2, head_dim=8, attention_impl="xla",
)

CHOICES = [[5, 9, 7], [5, 2], [40, 41, 42, 43]]


async def _generate(engine, *, temperature=0.0, seed=None, choices=CHOICES,
                    max_tokens=8, logit_bias=None):
    req = PreprocessedRequest(
        token_ids=[1, 17, 43, 99],
        stop_conditions=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        sampling_options=SamplingOptions(
            temperature=temperature, seed=seed, logit_bias=logit_bias,
            guided_choice_token_ids=choices,
        ),
    )
    toks, finish = [], None
    async for out in engine.generate(Context(req)):
        toks.extend(out["token_ids"])
        if out.get("finish_reason"):
            finish = out["finish_reason"]
    return toks, finish


async def _engine(**cfg_kw):
    econfig = EngineConfig(
        model=CFG, max_batch_size=2, max_model_len=64, kv_block_size=8,
        num_kv_blocks=32, dtype="float32", prefill_buckets=[16],
        allow_random_weights=True, **cfg_kw,
    )
    mdc = ModelDeploymentCard(display_name="t", slug="t")
    params = llama.init_params(CFG, jax.random.PRNGKey(0), jax.numpy.float32)
    return await JaxServingEngine.create(
        mdc, engine_config=econfig, params=params, warmup=False)


def test_guided_choice_greedy_and_sampled():
    async def run():
        engine = await _engine()
        greedy, finish = await _generate(engine)
        assert greedy in CHOICES and finish == "stop"
        # sampled runs stay inside the choice set too (mask, not luck:
        # 124 of 128 vocab ids are banned at the root)
        seen = set()
        for seed in range(4):
            toks, fin = await _generate(engine, temperature=1.5, seed=seed)
            assert toks in CHOICES and fin == "stop"
            seen.add(tuple(toks))
        await engine.close()
        return greedy, seen

    greedy, seen = asyncio.run(run())
    assert greedy  # non-empty


def test_guided_prefix_choice_resolves_to_longer_or_stops():
    """[5] is a strict prefix of [5, 9, 7]: after emitting 5 the engine
    allows {9} ∪ eos; with ignore_eos + no eos in vocab path the longer
    choice wins deterministically under greedy."""
    async def run():
        engine = await _engine()
        toks, fin = await _generate(
            engine, choices=[[5], [5, 9, 7]], max_tokens=8)
        await engine.close()
        return toks, fin

    toks, fin = asyncio.run(run())
    assert toks in ([5], [5, 9, 7]) and fin == "stop"


def test_guided_respects_max_tokens():
    async def run():
        engine = await _engine()
        toks, fin = await _generate(
            engine, choices=[[40, 41, 42, 43]], max_tokens=2)
        await engine.close()
        return toks, fin

    toks, fin = asyncio.run(run())
    assert toks == [40, 41] and fin == "length"


def test_guided_excluded_from_speculation_paths():
    """A guided row must not ride ngram speculation or the fused burst
    (its mask changes per step) — and the output stays constrained."""
    async def run():
        engine = await _engine(spec_ngram_tokens=4, multi_step_decode=4)
        toks, fin = await _generate(engine)
        await engine.close()
        return toks, fin

    toks, fin = asyncio.run(run())
    assert toks in CHOICES and fin == "stop"


# ---------------------------------------------------------------------------
# guided JSON (response_format / vLLM guided_json — VERDICT r4 item 6)
# ---------------------------------------------------------------------------

import json as _json
import random as _random

from dynamo_tpu.engine.guided import (
    JsonConstraint,
    JsonGrammar,
    build_piece_table,
    compile_schema,
)

# a deliberately adversarial piece table: structural chars, multi-char
# fusions, numbers, escapes, literals, and junk that must get masked out
PIECES = [None] * 128
for i, s in enumerate([
    '{', '}', '[', ']', '"', ':', ',', ' ', '\n', '-',
    '0', '1', '7', '25', '3.5', '0.25', 'e5', 'E-2', '.5',
    'a', 'b', 'ab', 'name', 'x', 'y z', 'true', 'false', 'null',
    '{"', '"}', '":', '": ', '", "', '"a"', '\\', '\\n', '\\u00e9',
    'tr', 'ue', 'nu', 'll', '[]', '{}', '[1', ',2]', 'word up',
    '!', '@#', '<tag>', "'", '\t', '\x01',
]):
    PIECES[i + 2] = s  # 0/1 reserved (None → banned like specials)


def _decode(toks):
    return "".join(PIECES[t] for t in toks)


def _random_walk(grammar, seed, max_steps=300):
    """Random token walk over the masked vocab; returns (text, done)."""
    rng = _random.Random(seed)
    c = JsonConstraint(grammar)
    toks = []
    for _ in range(max_steps):
        ids, at_end = c.allowed()
        assert ids or at_end, "dead state with no way out"
        if not ids:
            return _decode(toks), True  # only eos remains
        t = rng.choice(ids)
        toks.append(t)
        v = c.advance(t)
        assert v != "derail", (PIECES[t], _decode(toks))
        if v == "done":
            return _decode(toks), True
        if at_end and rng.random() < 0.25:
            return _decode(toks), True  # simulate eos at a legal end
    return _decode(toks), False


def test_json_object_random_walks_always_parse():
    g = JsonGrammar(PIECES)
    finished = 0
    for seed in range(40):
        text, done = _random_walk(g, seed)
        if done:
            finished += 1
            obj = _json.loads(text)  # every finished walk parses
            assert isinstance(obj, dict)  # json_object ⇒ top-level object
    assert finished >= 20  # the machine actually terminates walks


def test_json_schema_random_walks_validate():
    schema = {
        "type": "object",
        "properties": {
            "name": {"type": "string"},
            "a": {"type": "integer"},
            "ab": {"enum": ["x", "ab", 7]},
            "b": {"type": "array", "items": {"type": "number"}},
        },
        "required": ["name"],
    }
    g = JsonGrammar(PIECES, schema)
    finished = 0
    for seed in range(40):
        text, done = _random_walk(g, seed)
        if not done:
            continue
        finished += 1
        obj = _json.loads(text)
        assert set(obj) <= {"name", "a", "ab", "b"}
        assert "name" in obj and isinstance(obj["name"], str)
        if "a" in obj:
            assert isinstance(obj["a"], int) and not isinstance(obj["a"], bool)
        if "ab" in obj:
            assert obj["ab"] in ("x", "ab", 7)
        if "b" in obj:
            assert isinstance(obj["b"], list)
            assert all(isinstance(v, (int, float)) for v in obj["b"])
    assert finished >= 15


def test_json_schema_unsupported_keywords_rejected():
    for bad in (
        {"type": "string", "pattern": "a+"},
        {"type": "number", "minimum": 3},
        {"type": "array", "items": {}, "minItems": 1},
        {"oneOf": [{"type": "string"}]},
        {"type": ["string", "number"]},
        # 'required' without 'properties' cannot be enforced
        {"type": "object", "required": ["id"]},
        # property names needing JSON escaping are not walkable
        {"type": "object", "properties": {'a"b': {"type": "string"}}},
        {"type": "object", "properties": {"a\nb": {"type": "string"}}},
    ):
        with pytest.raises(ValueError):
            compile_schema(bad)
    # annotations pass
    compile_schema({"type": "object", "title": "T", "description": "d",
                    "properties": {"a": {"type": "string", "default": "q"}}})


def test_json_engine_end_to_end_parses():
    """Through the real engine: random weights + the piece-table mask ⇒
    whatever greedy emits, the finished completion parses as JSON."""
    async def run():
        engine = await _engine()
        # inject the synthetic piece table (no tokenizer in this fixture)
        engine._pieces = PIECES + [None] * (CFG.vocab_size - len(PIECES))
        engine._model_path = "<injected>"
        req = PreprocessedRequest(
            token_ids=[1, 17, 43, 99],
            stop_conditions=StopConditions(max_tokens=48, ignore_eos=True),
            sampling_options=SamplingOptions(
                temperature=0.0,
                guided_json={"type": "json_object"},
            ),
        )
        toks, finish = [], None
        async for out in engine.generate(Context(req)):
            toks.extend(out["token_ids"])
            if out.get("finish_reason"):
                finish = out["finish_reason"]
        await engine.close()
        return toks, finish

    toks, finish = asyncio.run(run())
    text = _decode(toks)
    if finish == "stop":
        assert isinstance(_json.loads(text), dict)
    else:  # budget hit mid-object: still a valid JSON *prefix*
        g = JsonGrammar(PIECES)
        assert g.run_piece(g.initial(), text) is not None


def test_json_engine_sampled_conformance():
    """Sampled decoding (several seeds) stays inside the grammar."""
    async def run():
        engine = await _engine()
        engine._pieces = PIECES + [None] * (CFG.vocab_size - len(PIECES))
        engine._model_path = "<injected>"
        outs = []
        for seed in range(3):
            req = PreprocessedRequest(
                token_ids=[1, 17, 43, 99],
                stop_conditions=StopConditions(max_tokens=40, ignore_eos=True),
                sampling_options=SamplingOptions(
                    temperature=1.2, seed=seed,
                    guided_json={"type": "json_object"},
                ),
            )
            toks, finish = [], None
            async for out in engine.generate(Context(req)):
                toks.extend(out["token_ids"])
                if out.get("finish_reason"):
                    finish = out["finish_reason"]
            outs.append((toks, finish))
        await engine.close()
        return outs

    outs = asyncio.run(run())
    g = JsonGrammar(PIECES)
    for toks, finish in outs:
        text = _decode(toks)
        if finish == "stop":
            assert isinstance(_json.loads(text), dict), text
        else:
            assert g.run_piece(g.initial(), text) is not None, text


def test_piece_table_from_real_tokenizer(tmp_path):
    """build_piece_table models mid-sequence rendering: decoding token
    by token through the table must equal decoding the whole sequence."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from fixtures import build_tiny_tokenizer

    from dynamo_tpu.llm.tokenizer import HFTokenizer

    tok = HFTokenizer(build_tiny_tokenizer())
    pieces = build_piece_table(tok, tok.vocab_size)
    ids = tok.encode("hello world this is a test", add_special_tokens=False)
    assert "".join(pieces[i] for i in ids) == tok.decode(ids)
