"""Two-process multi-host bring-up over localhost (CPU backend).

Each process plays one "node": rank 0 hosts the JAX coordinator
(the leader, reference MultiNodeConfig leader_addr semantics,
lib/llm/src/engines.rs:39-57), both join via
parallel.mesh.initialize_multihost, and together they run ONE jitted
sharded step over a global 4-device dp x tp mesh — the GPU-free
equivalent of the reference's Ray leader/follower vLLM bring-up
(lib/engines/vllm0_7/src/ray.rs:66-230).
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.environ["REPO_ROOT"])
from dynamo_tpu.parallel.mesh import MultiHostConfig, initialize_multihost, make_mesh

rank = int(sys.argv[1])
leader = sys.argv[2]
initialize_multihost(MultiHostConfig(
    leader_addr=leader, num_nodes=2, node_rank=rank,
))

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

devices = jax.devices()
assert len(devices) == 4, f"global device count {len(devices)}"
assert jax.process_count() == 2

mesh = make_mesh({"dp": 2, "tp": 2}, devices)
x_spec = NamedSharding(mesh, P("dp", None))
w_spec = NamedSharding(mesh, P(None, "tp"))

# one sharded "layer step": batch over dp, features over tp
xg = np.arange(4 * 8, dtype=np.float32).reshape(4, 8) / 100.0
wg = np.linspace(-1, 1, 64, dtype=np.float32).reshape(8, 8)
x = jax.make_array_from_process_local_data(x_spec, xg[rank * 2 : rank * 2 + 2])
w = jax.device_put(wg, w_spec)

y = jax.jit(lambda x, w: jnp.tanh(x @ w), out_shardings=x_spec)(x, w)
# this process's devices all sit in one dp row -> every addressable shard
# holds the same 2 global rows (replicated over local tp)
want = np.tanh(xg[rank * 2 : rank * 2 + 2] @ wg)
for s in y.addressable_shards:
    np.testing.assert_allclose(np.asarray(s.data), want, rtol=1e-4, atol=1e-6)
print(f"RANK{rank}_OK", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_sharded_step(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    leader = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)  # drop the TPU site hook; this is a CPU test
    env["JAX_PLATFORMS"] = "cpu"
    env["REPO_ROOT"] = repo
    # each process contributes 2 virtual CPU devices -> 4 global
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=2"
    ).strip()

    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(rank), leader],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env, text=True,
        )
        for rank in (0, 1)
    ]
    outs = []
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
    assert "RANK0_OK" in outs[0]
    assert "RANK1_OK" in outs[1]


_MODEL_WORKER = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.environ["REPO_ROOT"])
from dynamo_tpu.parallel.mesh import MultiHostConfig, initialize_multihost

rank = int(sys.argv[1])
leader = sys.argv[2]
initialize_multihost(MultiHostConfig(
    leader_addr=leader, num_nodes=2, node_rank=rank,
))

import numpy as np
from jax.experimental import multihost_utils

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.model_runner import ModelRunner

assert jax.process_count() == 2 and len(jax.devices()) == 4

mcfg = ModelConfig(
    vocab_size=512, hidden_size=64, intermediate_size=128,
    num_layers=2, num_heads=4, num_kv_heads=2, head_dim=8,
    attention_impl="xla",
)
cfg = EngineConfig(
    model=mcfg, max_batch_size=4, max_model_len=64, kv_block_size=8,
    num_kv_blocks=64, dtype="float32", dp_size=2, tp_size=2,
    prefill_buckets=[16], allow_random_weights=True,
)
# params derive deterministically from the config seed on every process;
# the runner shards them over the GLOBAL 2-process x 2-device mesh
runner = ModelRunner(cfg)
assert runner.mesh.devices.size == 4

b, s, bs, w = 4, 16, cfg.kv_block_size, cfg.blocks_per_seq
rng = np.random.default_rng(0)
tokens = rng.integers(0, 512, (b, s)).astype(np.int32)
positions = np.tile(np.arange(s, dtype=np.int32), (b, 1))
btab = np.zeros((b, w), np.int32)
for i in range(b):
    btab[i, : s // bs] = np.arange(i * (s // bs), (i + 1) * (s // bs))
slots = np.take_along_axis(btab, positions // bs, axis=1) * bs + positions % bs
ctx = np.full(b, s, np.int32)

out1, *_ = runner.step(
    tokens, positions, btab, slots, ctx, np.full(b, s - 1, np.int32),
    np.zeros(b, np.float32), np.zeros(b, np.int32), np.ones(b, np.float32),
    jax.random.PRNGKey(0),
)
t1 = multihost_utils.process_allgather(out1, tiled=True)
t1 = np.asarray(t1).reshape(-1)[:b]

dec = t1.reshape(b, 1).astype(np.int32)
dslots = np.zeros((b, 1), np.int32)
for i in range(b):
    btab[i, s // bs] = b * (s // bs) + i
    dslots[i, 0] = btab[i, s // bs] * bs
out2, *_ = runner.step(
    dec, np.full((b, 1), s, np.int32), btab, dslots,
    np.full(b, s + 1, np.int32), np.zeros(b, np.int32),
    np.zeros(b, np.float32), np.zeros(b, np.int32), np.ones(b, np.float32),
    jax.random.PRNGKey(1),
)
t2 = multihost_utils.process_allgather(out2, tiled=True)
t2 = np.asarray(t2).reshape(-1)[:b]
print(f"RANK{rank}_TOKENS {' '.join(map(str, t1))} | {' '.join(map(str, t2))}",
      flush=True)
print(f"RANK{rank}_OK", flush=True)
"""


def _expected_tokens():
    """The same prefill+decode on a single-process runner — the multihost
    step must be numerically the same model."""
    import numpy as np

    import jax
    from dynamo_tpu.engine.config import EngineConfig, ModelConfig
    from dynamo_tpu.engine.model_runner import ModelRunner

    mcfg = ModelConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=8,
        attention_impl="xla",
    )
    cfg = EngineConfig(
        model=mcfg, max_batch_size=4, max_model_len=64, kv_block_size=8,
        num_kv_blocks=64, dtype="float32",
        prefill_buckets=[16], allow_random_weights=True,
    )
    runner = ModelRunner(cfg)
    b, s, bs, w = 4, 16, cfg.kv_block_size, cfg.blocks_per_seq
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 512, (b, s)).astype(np.int32)
    positions = np.tile(np.arange(s, dtype=np.int32), (b, 1))
    btab = np.zeros((b, w), np.int32)
    for i in range(b):
        btab[i, : s // bs] = np.arange(i * (s // bs), (i + 1) * (s // bs))
    slots = np.take_along_axis(btab, positions // bs, axis=1) * bs + positions % bs
    ctx = np.full(b, s, np.int32)
    out1, *_ = runner.step(
        tokens, positions, btab, slots, ctx, np.full(b, s - 1, np.int32),
        np.zeros(b, np.float32), np.zeros(b, np.int32), np.ones(b, np.float32),
        jax.random.PRNGKey(0),
    )
    t1 = np.asarray(out1)
    dec = t1.reshape(b, 1).astype(np.int32)
    dslots = np.zeros((b, 1), np.int32)
    for i in range(b):
        btab[i, s // bs] = b * (s // bs) + i
        dslots[i, 0] = btab[i, s // bs] * bs
    out2, *_ = runner.step(
        dec, np.full((b, 1), s, np.int32), btab, dslots,
        np.full(b, s + 1, np.int32), np.zeros(b, np.int32),
        np.zeros(b, np.float32), np.zeros(b, np.int32), np.ones(b, np.float32),
        jax.random.PRNGKey(1),
    )
    t2 = np.asarray(out2)
    return list(map(int, t1)), list(map(int, t2))


@pytest.mark.slow
def test_two_process_model_runner_step():
    """A real ModelRunner serving step (bucketed prefill + batched decode)
    over a 2-process x 2-device-each dp x tp mesh — the serving math, not
    a toy matmul. Greedy tokens must match the single-process runner
    bit-for-bit (same params, same inputs)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    want1, want2 = _expected_tokens()
    leader = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["REPO_ROOT"] = repo
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _MODEL_WORKER, str(rank), leader],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env, text=True,
        )
        for rank in (0, 1)
    ]
    outs = []
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
    expected = (f"TOKENS {' '.join(map(str, want1))} | "
                f"{' '.join(map(str, want2))}")
    for rank, out in enumerate(outs):
        assert f"RANK{rank}_OK" in out
        assert expected in out, f"rank {rank} tokens diverged:\n{out}"
