"""Two-process multi-host bring-up over localhost (CPU backend).

Each process plays one "node": rank 0 hosts the JAX coordinator
(the leader, reference MultiNodeConfig leader_addr semantics,
lib/llm/src/engines.rs:39-57), both join via
parallel.mesh.initialize_multihost, and together they run ONE jitted
sharded step over a global 4-device dp x tp mesh — the GPU-free
equivalent of the reference's Ray leader/follower vLLM bring-up
(lib/engines/vllm0_7/src/ray.rs:66-230).
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.environ["REPO_ROOT"])
from dynamo_tpu.parallel.mesh import MultiHostConfig, initialize_multihost, make_mesh

rank = int(sys.argv[1])
leader = sys.argv[2]
initialize_multihost(MultiHostConfig(
    leader_addr=leader, num_nodes=2, node_rank=rank,
))

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

devices = jax.devices()
assert len(devices) == 4, f"global device count {len(devices)}"
assert jax.process_count() == 2

mesh = make_mesh({"dp": 2, "tp": 2}, devices)
x_spec = NamedSharding(mesh, P("dp", None))
w_spec = NamedSharding(mesh, P(None, "tp"))

# one sharded "layer step": batch over dp, features over tp
xg = np.arange(4 * 8, dtype=np.float32).reshape(4, 8) / 100.0
wg = np.linspace(-1, 1, 64, dtype=np.float32).reshape(8, 8)
x = jax.make_array_from_process_local_data(x_spec, xg[rank * 2 : rank * 2 + 2])
w = jax.device_put(wg, w_spec)

y = jax.jit(lambda x, w: jnp.tanh(x @ w), out_shardings=x_spec)(x, w)
# this process's devices all sit in one dp row -> every addressable shard
# holds the same 2 global rows (replicated over local tp)
want = np.tanh(xg[rank * 2 : rank * 2 + 2] @ wg)
for s in y.addressable_shards:
    np.testing.assert_allclose(np.asarray(s.data), want, rtol=1e-4, atol=1e-6)
print(f"RANK{rank}_OK", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_sharded_step(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    leader = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)  # drop the TPU site hook; this is a CPU test
    env["JAX_PLATFORMS"] = "cpu"
    env["REPO_ROOT"] = repo
    # each process contributes 2 virtual CPU devices -> 4 global
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=2"
    ).strip()

    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(rank), leader],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env, text=True,
        )
        for rank in (0, 1)
    ]
    outs = []
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
    assert "RANK0_OK" in outs[0]
    assert "RANK1_OK" in outs[1]
