"""Out-of-process engine hosting: crash containment, heartbeat, respawn.

VERDICT r3 item 3 — the analog of the reference's supervised engine
subprocesses (reference: lib/engines/sglang/src/worker.rs:307-445). The
acceptance bar: kill -9 the engine mid-stream → the request fails
cleanly (error prologue when nothing streamed yet), the worker stays up,
and the next request serves off a respawned child.
"""

import asyncio
import os
import signal

import pytest

from dynamo_tpu.llm.engines.subprocess_host import (
    EngineStreamDied,
    SubprocessEngine,
)
from dynamo_tpu.runtime.engine import AsyncEngineContext, Context, EngineError
from dynamo_tpu.runtime.network import _pump

# the engine child must not import the TPU site hook (dead-relay hangs);
# scrub the env exactly like every other multi-process test
def child_env():
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    return env


ECHO_ENGINE = """
import asyncio

async def generate(request):
    for t in request.get("token_ids", []):
        yield {"token_ids": [t]}
    yield {"token_ids": [], "finish_reason": "stop"}
"""

SLOW_ENGINE = """
import asyncio

async def generate(request):
    yield {"token_ids": [1]}
    await asyncio.sleep(600)
    yield {"token_ids": [2]}
"""

STALL_BEFORE_FIRST = """
import asyncio

async def generate(request):
    await asyncio.sleep(600)
    yield {"token_ids": [1]}
"""

WEDGED_ENGINE = """
import time

async def generate(request):
    yield {"token_ids": [1]}
    time.sleep(600)   # blocks the child's event loop: pings go unanswered
    yield {"token_ids": [2]}
"""

RAISING_INIT = """
async def initialize(engine_args):
    raise RuntimeError("bad credentials")

async def generate(request):
    yield {}
"""

USER_ERROR_ENGINE = """
async def generate(request):
    yield {"token_ids": [7]}
    raise ValueError("model exploded")
"""


def write_engine(tmp_path, src, name="eng.py"):
    p = tmp_path / name
    p.write_text(src)
    return str(p)


@pytest.mark.asyncio
async def test_subprocess_engine_streams_and_closes(tmp_path):
    eng = await SubprocessEngine.load(
        write_engine(tmp_path, ECHO_ENGINE), child_env=child_env()
    )
    try:
        chunks = [c async for c in eng.generate(Context(
            {"token_ids": [3, 1, 4]}
        ))]
        toks = [t for c in chunks for t in c.get("token_ids", [])]
        assert toks == [3, 1, 4]
        assert chunks[-1]["finish_reason"] == "stop"
        # concurrent streams multiplex over the one socket
        outs = await asyncio.gather(*[
            _collect(eng, {"token_ids": [i, i + 1]}) for i in range(4)
        ])
        assert outs == [[i, i + 1] for i in range(4)]
        assert eng.spawn_count == 1
    finally:
        await eng.close()


async def _collect(eng, payload):
    return [
        t
        for c in [c async for c in eng.generate(Context(payload))]
        for t in c.get("token_ids", [])
    ]


@pytest.mark.asyncio
async def test_kill9_midstream_fails_cleanly_and_respawns(tmp_path):
    eng = await SubprocessEngine.load(
        write_engine(tmp_path, SLOW_ENGINE), child_env=child_env(),
        restart_backoff_s=0.05,
    )
    try:
        stream = eng.generate(Context({"token_ids": []})).__aiter__()
        first = await asyncio.wait_for(stream.__anext__(), timeout=30)
        assert first == {"token_ids": [1]}

        os.kill(eng._proc.pid, signal.SIGKILL)
        with pytest.raises(EngineStreamDied):
            await asyncio.wait_for(stream.__anext__(), timeout=30)

        # the worker survives: the next request respawns the child and
        # serves (swap the file to the echo engine so the respawned child
        # — which re-reads it — finishes its stream)
        write_engine(tmp_path, ECHO_ENGINE)
        chunks = [c async for c in eng.generate(Context({"token_ids": [9]}))]
        assert chunks[0] == {"token_ids": [9]}
        assert chunks[-1]["finish_reason"] == "stop"
        assert eng.spawn_count == 2
    finally:
        await eng.close()


@pytest.mark.asyncio
async def test_kill9_before_first_output_maps_to_error_prologue(tmp_path):
    """Through the real network plane: a request whose engine dies before
    any output must produce {t: prologue, ok: False}, not a hang or an
    empty stream."""
    eng = await SubprocessEngine.load(
        write_engine(tmp_path, STALL_BEFORE_FIRST), child_env=child_env(),
        restart_backoff_s=0.05,
    )
    sent = []

    async def send(frame):
        sent.append(frame)

    async def stream_fn(ctx):
        async for c in eng.generate(Context({"token_ids": []}, ctx)):
            yield c

    try:
        ctx = AsyncEngineContext("req-1")
        pump = asyncio.create_task(_pump(stream_fn, ctx, send))
        await asyncio.sleep(1.0)  # request is in flight, nothing streamed
        os.kill(eng._proc.pid, signal.SIGKILL)
        await asyncio.wait_for(pump, timeout=30)
        assert sent, "no frames reached the requester"
        assert sent[0]["t"] == "prologue"
        assert sent[0]["ok"] is False
        assert "engine" in sent[0]["error"]
    finally:
        await eng.close()


@pytest.mark.asyncio
async def test_wedged_child_detected_by_heartbeat_and_killed(tmp_path):
    """A child whose event loop is blocked (the compile-hang failure mode)
    never exits on its own — only the missed-pong path can catch it."""
    eng = await SubprocessEngine.load(
        write_engine(tmp_path, WEDGED_ENGINE), child_env=child_env(),
        heartbeat_interval_s=0.2, heartbeat_misses=2, restart_backoff_s=0.05,
    )
    try:
        stream = eng.generate(Context({"token_ids": []})).__aiter__()
        first = await asyncio.wait_for(stream.__anext__(), timeout=30)
        assert first == {"token_ids": [1]}
        pid = eng._proc.pid
        with pytest.raises(EngineStreamDied) as ei:
            await asyncio.wait_for(stream.__anext__(), timeout=30)
        assert "heartbeat" in str(ei.value)
        # the wedged process was actually killed, not leaked
        for _ in range(50):
            try:
                os.kill(pid, 0)
                await asyncio.sleep(0.1)
            except ProcessLookupError:
                break
        else:
            pytest.fail(f"wedged child {pid} still alive")
    finally:
        await eng.close()


@pytest.mark.asyncio
async def test_user_error_is_engine_error_not_restart(tmp_path):
    eng = await SubprocessEngine.load(
        write_engine(tmp_path, USER_ERROR_ENGINE), child_env=child_env(),
    )
    try:
        chunks = []
        with pytest.raises(EngineError, match="model exploded"):
            async for c in eng.generate(Context({"token_ids": []})):
                chunks.append(c)
        assert chunks == [{"token_ids": [7]}]
        # a user exception is NOT a process failure: the same child serves
        # the next request (which, for this engine file, errors the same way)
        assert eng.spawn_count == 1
        chunks2 = []
        with pytest.raises(EngineError, match="model exploded"):
            async for c in eng.generate(Context({"token_ids": []})):
                chunks2.append(c)
        assert chunks2 == [{"token_ids": [7]}]
        assert eng.spawn_count == 1
    finally:
        await eng.close()


@pytest.mark.asyncio
async def test_init_error_reported_not_retried(tmp_path):
    with pytest.raises(EngineError, match="bad credentials"):
        await SubprocessEngine.load(
            write_engine(tmp_path, RAISING_INIT), child_env=child_env(),
        )


@pytest.mark.asyncio
async def test_cli_isolate_engine_flag_wires_subprocess_host(tmp_path):
    import argparse

    from dynamo_tpu.cli.run import _load_python_engine
    from dynamo_tpu.llm.engines.python_file import PythonFileEngine

    path = write_engine(tmp_path, ECHO_ENGINE)
    flags = argparse.Namespace(isolate_engine=False, extra_engine_args=None)
    eng = await _load_python_engine(path, flags)
    assert isinstance(eng, PythonFileEngine)

    flags.isolate_engine = True
    # the CLI path inherits os.environ in the child; scrub for CI the same
    # way production scrubs nothing (the hook is healthy there)
    import unittest.mock

    with unittest.mock.patch.dict(os.environ, child_env(), clear=True):
        eng = await _load_python_engine(path, flags)
    try:
        assert isinstance(eng, SubprocessEngine)
        assert await _collect(eng, {"token_ids": [5]}) == [5]
    finally:
        await eng.close()


@pytest.mark.asyncio
async def test_http_service_survives_engine_kill(tmp_path):
    """The full worker surface: an OpenAI-level subprocess engine behind
    the HTTP frontend; kill -9 the engine child between requests → the
    frontend process stays up and the next request serves."""
    import aiohttp

    from dynamo_tpu.http.service import HttpService, ModelManager

    OPENAI_ECHO = """
import time, uuid

async def generate(request):
    text = request["messages"][-1]["content"]
    yield {
        "id": "chatcmpl-" + uuid.uuid4().hex,
        "object": "chat.completion.chunk",
        "created": int(time.time()),
        "model": request.get("model", "sub"),
        "choices": [{"index": 0, "delta": {"role": "assistant",
                                           "content": text},
                     "finish_reason": "stop"}],
    }
"""
    path = write_engine(tmp_path, OPENAI_ECHO, "openai_echo.py")
    eng = await SubprocessEngine.load(
        path, child_env=child_env(), restart_backoff_s=0.05,
    )
    manager = ModelManager()
    manager.add_chat_model("sub", eng)
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    try:
        async def ask(text):
            async with aiohttp.ClientSession() as s:
                async with s.post(
                    f"http://127.0.0.1:{service.port}/v1/chat/completions",
                    json={"model": "sub",
                          "messages": [{"role": "user", "content": text}]},
                ) as r:
                    return r.status, await r.json()

        status, body = await ask("hello")
        assert status == 200
        assert body["choices"][0]["message"]["content"] == "hello"

        os.kill(eng._proc.pid, signal.SIGKILL)
        # wait for the supervisor to notice (read-loop EOF) so the next
        # request deterministically takes the respawn path
        for _ in range(100):
            if eng._proc is None:
                break
            await asyncio.sleep(0.05)
        # the frontend survives; the next request respawns the engine
        status, body = await ask("again")
        assert status == 200
        assert body["choices"][0]["message"]["content"] == "again"
        assert eng.spawn_count == 2
    finally:
        await service.stop()
        await eng.close()


@pytest.mark.asyncio
async def test_stop_cancels_child_stream(tmp_path):
    eng = await SubprocessEngine.load(
        write_engine(tmp_path, SLOW_ENGINE), child_env=child_env(),
    )
    try:
        ctx = AsyncEngineContext("req-s")
        stream = eng.generate(Context({"token_ids": []}, ctx)).__aiter__()
        first = await asyncio.wait_for(stream.__anext__(), timeout=30)
        assert first == {"token_ids": [1]}
        ctx.stop_generating()
        # the child cancels the generator task and ends the stream
        with pytest.raises(StopAsyncIteration):
            while True:
                await asyncio.wait_for(stream.__anext__(), timeout=30)
        # engine still healthy for the next request (first chunk only —
        # this engine file then sleeps by design)
        ctx2 = AsyncEngineContext("req-s2")
        stream2 = eng.generate(Context({"token_ids": []}, ctx2)).__aiter__()
        assert await asyncio.wait_for(stream2.__anext__(), timeout=30) == \
            {"token_ids": [1]}
        ctx2.stop_generating()
        assert eng.spawn_count == 1
    finally:
        await eng.close()


# ---------------------------------------------------------------------------
# @jax: the native engine hosted out-of-process (VERDICT r4 item 5 — the
# actual compile-hang hazard runs as a supervised child; reference analog
# lib/engines/sglang/src/worker.rs:307-445)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def jax_model_dir(tmp_path_factory):
    import json

    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    from fixtures import make_model_dir

    d = make_model_dir(tmp_path_factory.mktemp("subproc_jax"), name="tiny-hf")
    cfg = LlamaConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    LlamaForCausalLM(cfg).save_pretrained(d, safe_serialization=True)
    with open(os.path.join(d, "config.json")) as f:
        c = json.load(f)
    c["eos_token_id"] = 2
    c["bos_token_id"] = 1
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump(c, f)
    return str(d)


def _jax_flags(model_dir):
    return {
        "model_path": model_dir, "model_name": "tiny-hf",
        "kv_block_size": 8, "max_batch_size": 2, "max_model_len": 64,
        "extra_engine_args": None, "isolate_engine": False,
    }


def _greedy_req(n=4):
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    return PreprocessedRequest(
        token_ids=[3, 7, 11],
        stop_conditions=StopConditions(max_tokens=n),
        sampling_options=SamplingOptions(temperature=0.0),
    ).to_wire()


@pytest.mark.asyncio
async def test_jax_engine_hosted_in_subprocess(jax_model_dir):
    """@jax child: serve → SIGSTOP (a wedged Mosaic compile freezes the
    child's loop exactly like this) → heartbeat kill → respawn → serve."""
    from dynamo_tpu.engine.block_allocator import KvEventSink

    kv_events = []
    sink = KvEventSink(
        on_stored=lambda h, p: kv_events.append(("stored", list(h), p)),
        on_removed=lambda h: kv_events.append(("removed", list(h))),
    )
    eng = await SubprocessEngine.load(
        "@jax", {"flags": _jax_flags(jax_model_dir)},
        child_env=child_env(), init_timeout_s=300.0,
        heartbeat_interval_s=0.3, heartbeat_misses=3,
        restart_backoff_s=0.05, events=sink,
    )
    try:
        toks = await asyncio.wait_for(_collect(eng, _greedy_req()), 60)
        assert len(toks) == 4
        assert eng.spawn_count == 1

        # inject the wedge: freeze the child process (its event loop —
        # and with it every pong — stops, like a hung in-process compile)
        pid = eng._proc.pid
        os.kill(pid, signal.SIGSTOP)
        with pytest.raises((EngineError, EngineStreamDied)) as ei:
            await asyncio.wait_for(_collect(eng, _greedy_req()), 60)
        assert "heartbeat" in str(ei.value)
        # SIGKILL still lands on a SIGSTOPped pid; reaped by the host
        for _ in range(100):
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                break
            await asyncio.sleep(0.1)
        else:
            os.kill(pid, signal.SIGCONT)
            pytest.fail(f"wedged jax child {pid} still alive")

        # serving resumes on a respawned child, greedy stream identical
        toks2 = await asyncio.wait_for(_collect(eng, _greedy_req()), 120)
        assert toks2 == toks
        assert eng.spawn_count == 2
    finally:
        await eng.close()


@pytest.mark.asyncio
async def test_jax_subprocess_forwards_kv_events_and_metrics(jax_model_dir):
    """The child's allocator events replay into the worker-side sink
    (KV-aware routing keeps working out-of-process) and engine metrics
    ride the heartbeat pongs."""
    from dynamo_tpu.engine.block_allocator import KvEventSink

    kv_events = []
    sink = KvEventSink(
        on_stored=lambda h, p: kv_events.append(("stored", list(h), p)),
        on_removed=lambda h: kv_events.append(("removed", list(h))),
    )
    eng = await SubprocessEngine.load(
        "@jax", {"flags": _jax_flags(jax_model_dir)},
        child_env=child_env(), init_timeout_s=300.0,
        heartbeat_interval_s=0.2, events=sink,
    )
    try:
        # a full-block prompt (block size 8) gets its prefix registered
        from dynamo_tpu.protocols.common import (
            PreprocessedRequest,
            SamplingOptions,
            StopConditions,
        )

        req = PreprocessedRequest(
            token_ids=list(range(3, 3 + 16)),
            stop_conditions=StopConditions(max_tokens=2),
            sampling_options=SamplingOptions(temperature=0.0),
        ).to_wire()
        toks = await asyncio.wait_for(_collect(eng, req), 60)
        assert len(toks) == 2
        for _ in range(100):  # events ride the async pump; wait briefly
            if any(e[0] == "stored" for e in kv_events):
                break
            await asyncio.sleep(0.05)
        assert any(e[0] == "stored" for e in kv_events)
        # metrics piggyback on pongs
        for _ in range(100):
            if eng.metrics():
                break
            await asyncio.sleep(0.05)
        assert isinstance(eng.metrics(), dict) and eng.metrics()
    finally:
        await eng.close()


@pytest.mark.asyncio
async def test_child_death_purges_advertised_kv_hashes():
    """A dead child takes its allocator with it: every block hash it
    advertised as stored must replay as removed into the worker-side
    sink, or KV-aware routing would chase prefix hits that cannot
    occur (code-review r5 finding)."""
    from dynamo_tpu.engine.block_allocator import KvEventSink

    events = []
    sink = KvEventSink(
        on_stored=lambda h, p: events.append(("stored", list(h))),
        on_removed=lambda h: events.append(("removed", list(h))),
    )
    eng = SubprocessEngine("@unused", events=sink)
    eng._on_kv_frame({"t": "kv", "ev": "stored", "hashes": [11, 12],
                      "parent": None})
    eng._on_kv_frame({"t": "kv", "ev": "stored", "hashes": [13],
                      "parent": 12})
    eng._on_kv_frame({"t": "kv", "ev": "removed", "hashes": [12]})
    assert eng._kv_live_hashes == {11, 13}
    await eng._on_child_down("test kill")
    assert ("removed", [11, 13]) in events
    assert eng._kv_live_hashes == set()
