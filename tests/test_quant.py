"""Weight-only int8 serving (ModelConfig.quantization).

Reference analog: the quantized checkpoints the reference's engines
serve as their canonical workload (examples/llm/benchmarks/perf.sh
FP8-dynamic model); here quantization is a serving-time transform.
"""

import asyncio

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.model_runner import ModelRunner, build_mesh
from dynamo_tpu.models import llama
from dynamo_tpu.models.quant import (
    QuantizedWeight, dense, quantize_int8, quantize_params,
)

TINY = dict(
    vocab_size=256, hidden_size=64, intermediate_size=128,
    num_layers=2, num_heads=8, num_kv_heads=4, head_dim=8,
)


def test_quantize_roundtrip_error_bounded():
    w = jax.random.normal(jax.random.PRNGKey(0), (3, 48, 32)) * 0.2
    qw = quantize_int8(w)
    assert qw.q.dtype == jnp.int8 and qw.scale.shape == (3, 32)
    deq = qw.q.astype(jnp.float32) * qw.scale[:, None, :]
    # symmetric rounding: error per element <= scale/2
    err = jnp.abs(deq - w)
    assert bool(jnp.all(err <= qw.scale[:, None, :] * 0.5 + 1e-7))


def test_dense_matches_explicit_dequant():
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 48), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(2), (48, 32), jnp.float32)
    qw = quantize_int8(w)
    got = dense(x, qw)
    want = (x @ qw.q.astype(jnp.float32)) * qw.scale
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)
    # plain arrays pass through untouched
    np.testing.assert_allclose(
        np.asarray(dense(x, w)), np.asarray(x @ w), rtol=1e-6)


def test_quantize_params_targets_matmul_weights_only():
    cfg = ModelConfig(**TINY)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    qp = quantize_params(params)
    assert isinstance(qp["layers"]["wq"], QuantizedWeight)
    assert isinstance(qp["layers"]["w_down"], QuantizedWeight)
    assert isinstance(qp["lm_head"], QuantizedWeight)
    assert not isinstance(qp["embed"], QuantizedWeight)
    assert not isinstance(qp["layers"]["ln1"], QuantizedWeight)
    # the weight stream halves (int8 vs f32 here: 4x on the quantized set)
    orig = sum(x.size * x.dtype.itemsize
               for x in jax.tree.leaves(params["layers"]))
    quant = sum(x.size * x.dtype.itemsize
                for x in jax.tree.leaves(qp["layers"]))
    assert quant < orig / 2


def test_mirror_specs_shards_scales():
    from jax.sharding import PartitionSpec as P

    from dynamo_tpu.models.quant import mirror_specs

    qw = quantize_int8(jnp.ones((2, 8, 16)))
    specs = mirror_specs(
        {"wq": qw, "ln1": jnp.ones(4)},
        {"wq": P(None, None, "tp"), "ln1": P()},
    )
    assert tuple(specs["wq"].q) == (None, None, "tp")
    assert tuple(specs["wq"].scale) == (None, "tp")  # in axis dropped
    # 2D lm_head-style weight: scale shards with the out (vocab) axis
    qw2 = quantize_int8(jnp.ones((8, 16)))
    s2 = mirror_specs({"lm_head": qw2}, {"lm_head": P(None, "tp")})
    assert tuple(s2["lm_head"].scale) == ("tp",)


def _logits(cfg, params, prompt, arch=llama):
    """One prefill over a fresh tiny cache, raw logits out."""
    cache = arch.init_kv_cache(cfg, 16, 8, jnp.float32)
    s = len(prompt)
    tokens = jnp.asarray([prompt], jnp.int32)
    positions = jnp.arange(s, dtype=jnp.int32)[None]
    bt = jnp.arange(4, dtype=jnp.int32)[None]
    slots = positions
    logits, _ = arch.forward(
        params, cfg, tokens, positions, cache, bt, slots,
        jnp.asarray([s], jnp.int32),
    )
    return np.asarray(logits[0, -1], np.float64)


def test_quantized_logits_track_full_precision():
    cfg = ModelConfig(**TINY, attention_impl="xla")
    params = llama.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    prompt = [1, 17, 43, 99, 7, 3, 250, 12]
    full = _logits(cfg, params, prompt)
    quant = _logits(cfg, quantize_params(params), prompt)
    cos = np.dot(full, quant) / (np.linalg.norm(full) * np.linalg.norm(quant))
    assert cos > 0.99, f"quantized logits diverged (cos={cos:.4f})"


def test_quantized_runner_serves_on_tp_mesh():
    # sharded execution: q and scale follow the Megatron specs through
    # the mirrored spec tree (8 virtual CPU devices from conftest)
    cfg = EngineConfig(
        model=ModelConfig(**TINY, attention_impl="xla", quantization="int8"),
        max_batch_size=2, max_model_len=64, kv_block_size=8,
        num_kv_blocks=32, dtype="float32", tp_size=2, prefill_buckets=[16],
    )
    runner = ModelRunner(cfg, mesh=build_mesh(1, 2, jax.devices()[:2]))
    b, s = 2, 8
    tokens = np.random.default_rng(0).integers(0, 256, (b, s)).astype(np.int32)
    positions = np.tile(np.arange(s, dtype=np.int32), (b, 1))
    btab = np.zeros((b, cfg.blocks_per_seq), np.int32)
    btab[0, 0], btab[1, 0] = 0, 1
    slots = btab[:, :1] * 8 + positions
    nt, *_ = runner.step(
        tokens, positions, btab, slots, np.full(b, s, np.int32),
        np.full(b, s - 1, np.int32), np.zeros(b, np.float32),
        np.zeros(b, np.int32), np.ones(b, np.float32),
        jax.random.PRNGKey(0),
    )
    assert np.asarray(nt).shape == (b,)


@pytest.mark.asyncio
async def test_quantized_engine_serves_deterministically(tmp_path):
    import json as _json
    import os as _os

    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    from dynamo_tpu.engine.serving import JaxServingEngine
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context
    from fixtures import make_model_dir

    d = make_model_dir(tmp_path, name="tiny-q")
    hf = LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=8, num_key_value_heads=4,
        max_position_embeddings=128, rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    LlamaForCausalLM(hf).save_pretrained(d, safe_serialization=True)
    c = _json.load(open(_os.path.join(d, "config.json")))
    c["eos_token_id"] = 2
    _json.dump(c, open(_os.path.join(d, "config.json"), "w"))

    mdc = ModelDeploymentCard.from_local_path(d)
    mcfg = ModelConfig.from_model_dir(d)
    mcfg.quantization = "int8"
    # composed with the fused burst AND ngram speculation: the quantized
    # head feeds both the scan body and the verify's greedy argmax
    econfig = EngineConfig(
        model=mcfg, max_batch_size=2, max_model_len=64, kv_block_size=8,
        num_kv_blocks=32, dtype="float32", multi_step_decode=4,
        spec_ngram_tokens=4, spec_ngram_match=2,
    )
    engine = await JaxServingEngine.create(
        mdc, engine_config=econfig, warmup=False)

    async def run():
        req = PreprocessedRequest(
            token_ids=[1, 17, 43, 99],
            stop_conditions=StopConditions(max_tokens=8, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
        )
        toks = []
        async for out in engine.generate(Context(req)):
            toks.extend(out["token_ids"])
        return toks

    first = await run()
    second = await run()
    await engine.close()
    assert len(first) == 8 and first == second


def test_quantization_rejects_unknown_scheme():
    cfg = EngineConfig(
        model=ModelConfig(**TINY, quantization="fp4"),
        max_batch_size=2, max_model_len=64, kv_block_size=8,
        num_kv_blocks=16, dtype="float32",
    )
    with pytest.raises(ValueError, match="fp4"):
        ModelRunner(cfg)


MOE_CFG = dict(
    vocab_size=256, hidden_size=32, intermediate_size=64, num_layers=2,
    num_heads=4, num_kv_heads=2, head_dim=8, num_experts=4,
    num_experts_per_tok=2,
)
MLA_CFG = dict(
    vocab_size=256, hidden_size=64, intermediate_size=96, num_layers=2,
    num_heads=4, num_kv_heads=4, head_dim=16,
    kv_lora_rank=16, qk_rope_head_dim=8, qk_nope_head_dim=12, v_head_dim=12,
)


def test_quantized_moe_logits_track_full_precision():
    """VERDICT r3 item 6: int8 composes with routed experts — the expert
    einsums dispatch through quant.expert_einsum."""
    from dynamo_tpu.models import mixtral

    cfg = ModelConfig(**MOE_CFG, attention_impl="xla")
    params = mixtral.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    prompt = [1, 17, 43, 99, 7, 3, 250, 12]
    full = _logits(cfg, params, prompt, arch=mixtral)
    qp = quantize_params(params)
    assert isinstance(qp["layers"]["w_gate"], QuantizedWeight)  # [L,E,D,I]
    assert not isinstance(qp["layers"]["router"], QuantizedWeight)
    quant = _logits(cfg, qp, prompt, arch=mixtral)
    cos = np.dot(full, quant) / (np.linalg.norm(full) * np.linalg.norm(quant))
    assert cos > 0.99, f"quantized MoE logits diverged (cos={cos:.4f})"


def test_quantized_mla_logits_track_full_precision():
    """int8 composes with MLA: the low-rank projections serve quantized;
    w_kr / absorbed w_uk / w_uv stay full precision."""
    from dynamo_tpu.models import deepseek

    cfg = ModelConfig(
        **{**MLA_CFG, "q_lora_rank": 24}, attention_impl="xla"
    )
    params = deepseek.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    prompt = [1, 17, 43, 99, 7, 3, 250, 12]
    full = _logits(cfg, params, prompt, arch=deepseek)
    qp = quantize_params(params)
    layers = qp["dense_layers"] if "dense_layers" in qp else qp["layers"]
    assert isinstance(layers["w_dkv"], QuantizedWeight)
    assert isinstance(layers["w_uq"], QuantizedWeight)
    assert not isinstance(layers["w_kr"], QuantizedWeight)
    assert not isinstance(layers["w_uk"], QuantizedWeight)
    quant = _logits(cfg, qp, prompt, arch=deepseek)
    cos = np.dot(full, quant) / (np.linalg.norm(full) * np.linalg.norm(quant))
    assert cos > 0.99, f"quantized MLA logits diverged (cos={cos:.4f})"


def test_quantized_gemma2_logits_track_full_precision():
    """Gemma-2's own forward (sandwich norms, GeGLU, softcaps) also
    serves int8 — every family's matmuls route through quant.dense."""
    from dynamo_tpu.models import gemma2

    cfg = ModelConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=8, num_kv_heads=4, head_dim=8,
        attention_impl="xla", attn_logit_softcap=50.0,
        final_logit_softcap=30.0, sliding_window=8,
        query_pre_attn_scalar=8, tie_word_embeddings=True,
    )
    params = gemma2.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    prompt = [1, 17, 43, 99, 7, 3, 250, 12]
    full = _logits(cfg, params, prompt, arch=gemma2)
    quant = _logits(cfg, quantize_params(params), prompt, arch=gemma2)
    cos = np.dot(full, quant) / (np.linalg.norm(full) * np.linalg.norm(quant))
    assert cos > 0.99, f"quantized gemma2 logits diverged (cos={cos:.4f})"


def test_quantized_moe_runner_serves_on_ep_mesh():
    """int8 expert stacks shard over ep×tp through the mirrored specs."""
    cfg = EngineConfig(
        model=ModelConfig(**MOE_CFG, attention_impl="xla",
                          quantization="int8"),
        max_batch_size=2, max_model_len=64, kv_block_size=8,
        num_kv_blocks=32, dtype="float32", ep_size=2, tp_size=2,
        prefill_buckets=[16],
    )
    runner = ModelRunner(
        cfg, mesh=build_mesh(1, 2, ep=2, devices=jax.devices()[:4])
    )
    b, s = 2, 8
    tokens = np.random.default_rng(0).integers(0, 256, (b, s)).astype(np.int32)
    positions = np.tile(np.arange(s, dtype=np.int32), (b, 1))
    btab = np.zeros((b, cfg.blocks_per_seq), np.int32)
    btab[0, 0], btab[1, 0] = 0, 1
    slots = btab[:, :1] * 8 + positions
    nt, *_ = runner.step(
        tokens, positions, btab, slots, np.full(b, s, np.int32),
        np.full(b, s - 1, np.int32), np.zeros(b, np.float32),
        np.zeros(b, np.int32), np.ones(b, np.float32),
        jax.random.PRNGKey(0),
    )
    assert np.asarray(nt).shape == (b,)


@pytest.mark.asyncio
async def test_quantized_pp_engine_serves(tmp_path):
    """int8 × pp: staged QuantizedWeight leaves ([P, L/P, ...]) serve
    through the collective GPipe engine path, composed with the K-burst."""
    import json as _json
    import os as _os

    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    from dynamo_tpu.engine.serving import JaxServingEngine
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context
    from fixtures import make_model_dir

    d = make_model_dir(tmp_path, name="tiny-qpp")
    hf = LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=8, num_key_value_heads=4,
        max_position_embeddings=128, rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    LlamaForCausalLM(hf).save_pretrained(d, safe_serialization=True)
    c = _json.load(open(_os.path.join(d, "config.json")))
    c["eos_token_id"] = 2
    _json.dump(c, open(_os.path.join(d, "config.json"), "w"))

    mdc = ModelDeploymentCard.from_local_path(d)

    async def run(quantization):
        mcfg = ModelConfig.from_model_dir(d)
        mcfg.attention_impl = "xla"
        mcfg.quantization = quantization
        econfig = EngineConfig(
            model=mcfg, max_batch_size=2, max_model_len=64, kv_block_size=8,
            num_kv_blocks=32, dtype="float32", pp_size=2,
            multi_step_decode=2,
        )
        engine = await JaxServingEngine.create(
            mdc, engine_config=econfig, warmup=False)
        req = PreprocessedRequest(
            token_ids=[1, 17, 43, 99],
            stop_conditions=StopConditions(max_tokens=8, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
        )
        toks = []
        async for out in engine.generate(Context(req)):
            toks.extend(out["token_ids"])
        await engine.close()
        return toks

    full = await run(None)
    quant = await run("int8")
    assert len(quant) == 8
    # greedy decode over a tiny random model: int8 should track the
    # full-precision trajectory for at least the first tokens
    assert quant[0] == full[0]
