"""dynlint: fixture-verified rule behavior + the tier-1 enforcement gate.

Each rule gets at least one true-positive and one true-negative fixture
(the acceptance contract for the analyzer), plus suppression, baseline,
and CLI exit-code coverage. The enforcement test at the bottom (marker:
``dynlint``) is the CI gate: the whole package must lint clean modulo
the committed baseline — a new violation in a PR fails tier-1 here.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from dynamo_tpu.analysis import (  # noqa: E402
    all_rules,
    diff_against_baseline,
    get_rules,
    lint_paths,
    lint_source,
    load_baseline,
    write_baseline,
)

PACKAGE_ROOT = os.path.join(REPO_ROOT, "dynamo_tpu")
BASELINE = os.path.join(REPO_ROOT, "scripts", "dynlint_baseline.json")


def findings(src, rule):
    return lint_source(textwrap.dedent(src), get_rules([rule]))


def rule_names(src, rule):
    return [f.rule for f in findings(src, rule)]


# --------------------------------------------------------------------------
# async-blocking
# --------------------------------------------------------------------------


def test_async_blocking_flags_sleep_in_async_def():
    out = findings(
        """
        import time
        async def work():
            time.sleep(1)
        """,
        "async-blocking",
    )
    assert len(out) == 1 and "time.sleep" in out[0].message
    assert out[0].line == 4


def test_async_blocking_resolves_from_imports_and_aliases():
    assert rule_names(
        """
        from time import sleep
        async def work():
            sleep(1)
        """,
        "async-blocking",
    ) == ["async-blocking"]
    assert rule_names(
        """
        import subprocess as sp
        async def work():
            sp.run(["ls"])
        """,
        "async-blocking",
    ) == ["async-blocking"]


def test_async_blocking_flags_open_and_requests():
    src = """
    import requests
    async def fetch(path):
        f = open(path)
        return requests.get("http://x")
    """
    assert len(findings(src, "async-blocking")) == 2


def test_async_blocking_ignores_locals_named_like_modules():
    # a mapping of in-flight requests is a natural name in this codebase;
    # attribute chains only resolve when the root is actually imported
    assert not findings(
        """
        async def lookup(requests, rid):
            return requests.get(rid)
        async def resolve(socket):
            return socket.getaddrinfo()
        """,
        "async-blocking",
    )


def test_async_blocking_ignores_sync_defs_and_async_sleep():
    assert not findings(
        """
        import time, asyncio
        def sync_work():
            time.sleep(1)
        async def ok():
            await asyncio.sleep(1)
        """,
        "async-blocking",
    )


def test_async_blocking_skips_nested_sync_def():
    # the nested def runs wherever it's called (typically an executor);
    # flagging it here would force suppressions on the executor idiom
    assert not findings(
        """
        import time
        async def work(loop):
            def blocking():
                time.sleep(1)
            await loop.run_in_executor(None, blocking)
        """,
        "async-blocking",
    )


# --------------------------------------------------------------------------
# task-leak
# --------------------------------------------------------------------------


def test_task_leak_flags_discarded_handle():
    out = findings(
        """
        import asyncio
        async def go(coro):
            asyncio.create_task(coro)
        """,
        "task-leak",
    )
    assert len(out) == 1 and "discarded" in out[0].message


def test_task_leak_flags_discarded_ensure_future_and_loop_spawn():
    src = """
    import asyncio
    async def go(loop, coro):
        asyncio.ensure_future(coro)
        loop.create_task(coro)
    """
    assert len(findings(src, "task-leak")) == 2


def test_task_leak_ignores_kept_handles():
    assert not findings(
        """
        import asyncio
        async def go(self, coro, tasks):
            t = asyncio.create_task(coro)
            self._task = asyncio.create_task(coro)
            tasks["x"] = asyncio.create_task(coro)
            await asyncio.create_task(coro)
            return t
        """,
        "task-leak",
    )


def test_task_leak_ignores_task_groups():
    assert not findings(
        """
        import asyncio
        async def go(coro):
            async with asyncio.TaskGroup() as tg:
                tg.create_task(coro)
        """,
        "task-leak",
    )


# --------------------------------------------------------------------------
# lock-across-await
# --------------------------------------------------------------------------


def test_lock_flags_threading_lock_in_async_def():
    out = findings(
        """
        import threading
        async def work():
            lock = threading.Lock()
        """,
        "lock-across-await",
    )
    assert len(out) == 1 and "threading.Lock" in out[0].message


def test_lock_flags_lock_held_across_await():
    out = findings(
        """
        async def work(self, thing):
            with self._lock:
                await thing()
        """,
        "lock-across-await",
    )
    assert len(out) == 1 and "across an await" in out[0].message


def test_lock_ignores_asyncio_lock_and_sync_contexts():
    assert not findings(
        """
        import asyncio, threading
        def sync_work():
            lock = threading.Lock()
            with lock:
                pass
        async def ok(self):
            self._lock = asyncio.Lock()
            async with self._lock:
                await asyncio.sleep(0)
        """,
        "lock-across-await",
    )


def test_lock_ignores_non_lock_context_managers_with_await():
    assert not findings(
        """
        async def work(self, session):
            with self.tracer.span("x"):
                await session.send()
        """,
        "lock-across-await",
    )


# --------------------------------------------------------------------------
# jit-impure
# --------------------------------------------------------------------------


def test_jit_impure_flags_print_and_self_mutation_in_decorated_fn():
    src = """
    import jax
    class M:
        @jax.jit
        def step(self, x):
            print("tracing", x)
            self.calls = self.calls + 1
            return x
    """
    msgs = [f.message for f in findings(src, "jit-impure")]
    assert len(msgs) == 2
    assert any("print()" in m for m in msgs)
    assert any("mutates self.calls" in m for m in msgs)


def test_jit_impure_flags_host_sync_in_jit_call_form():
    # the call form jax.jit(fn) is how model_runner builds every step
    src = """
    import jax
    import numpy as np
    def step(x):
        return np.asarray(x).item()
    compiled = jax.jit(step, donate_argnums=(0,))
    """
    msgs = [f.message for f in findings(src, "jit-impure")]
    assert any("numpy.asarray" in m for m in msgs)
    assert any(".item()" in m for m in msgs)


def test_jit_impure_flags_global_mutation_and_partial_decorator():
    src = """
    import functools, jax
    COUNT = 0
    @functools.partial(jax.jit, static_argnums=(1,))
    def step(x, n):
        global COUNT
        COUNT = COUNT + 1
        return x
    """
    out = findings(src, "jit-impure")
    assert len(out) == 1 and "global 'COUNT'" in out[0].message


def test_jit_impure_ignores_untraced_code_and_debug_print():
    assert not findings(
        """
        import jax
        import numpy as np
        def plain(x):
            print(x)          # not traced: fine
            return np.asarray(x).item()
        @jax.jit
        def traced(x):
            jax.debug.print("x={}", x)   # the traced print: fine
            return x * 2
        """,
        "jit-impure",
    )


# --------------------------------------------------------------------------
# silent-except
# --------------------------------------------------------------------------


def test_silent_except_flags_swallowed_broad_handlers():
    src = """
    def f():
        try:
            work()
        except Exception:
            pass
    def g():
        try:
            work()
        except:
            return None
    """
    assert len(findings(src, "silent-except")) == 2


def test_silent_except_ignores_logged_raised_and_narrow():
    assert not findings(
        """
        import logging
        logger = logging.getLogger(__name__)
        def f():
            try:
                work()
            except Exception:
                logger.exception("work failed")
        def g():
            try:
                work()
            except Exception as e:
                raise RuntimeError("ctx") from e
        def h():
            try:
                work()
            except ConnectionResetError:
                pass   # narrow: presumed deliberate
        """,
        "silent-except",
    )


def test_silent_except_treats_future_set_exception_as_observed():
    # disagg/transfer.py's daemon-thread bridge: the error propagates
    # through the Future, which is observation, not swallowing
    assert not findings(
        """
        def work(fut, fn):
            try:
                fut.set_result(fn())
            except BaseException as e:
                fut.set_exception(e)
        """,
        "silent-except",
    )


# --------------------------------------------------------------------------
# metric-name
# --------------------------------------------------------------------------


def test_metric_name_flags_off_convention_registration():
    src = """
    def register(reg):
        reg.counter("dynamo_scheduler_preemptions", "help")
        reg.histogram("dynamo_kv_usage_ratio", "help")
    """
    out = findings(src, "metric-name")
    # the counter name breaks two clauses (unit suffix + _total), the
    # ratio histogram one — each clause is its own finding
    assert len(out) == 3
    assert any("_total" in f.message for f in out)
    assert any("base unit" in f.message for f in out)


def test_metric_name_unit_suffix_requires_segment_boundary():
    # "subtotal"/"kilobytes" merely END in a unit string; the unit must
    # be the whole last segment
    src = """
    def register(reg):
        reg.gauge("dynamo_scheduler_subtotal", "help")
        reg.histogram("dynamo_transfer_kilobytes", "help")
    """
    out = findings(src, "metric-name")
    assert len(out) >= 2
    assert {f.line for f in out} == {3, 4}


def test_metric_name_accepts_conforming_registration():
    assert not findings(
        """
        def register(reg):
            reg.counter("dynamo_scheduler_preemptions_total", "help")
            reg.histogram("dynamo_scheduler_step_duration_seconds", "help")
            reg.gauge("dynamo_kv_block_usage_ratio", "help")
        """,
        "metric-name",
    )


# --------------------------------------------------------------------------
# suppressions
# --------------------------------------------------------------------------


def test_suppression_on_same_line_and_line_above():
    src = """
    import time
    async def work():
        time.sleep(1)  # dynlint: allow(async-blocking) - test latency injection
        # dynlint: allow(async-blocking) - second form
        time.sleep(2)
        time.sleep(3)
    """
    out = findings(src, "async-blocking")
    assert len(out) == 1 and out[0].line == 7


def test_suppression_is_per_rule():
    # an allow() for a DIFFERENT rule must not mask this one
    src = """
    import time
    async def work():
        time.sleep(1)  # dynlint: allow(silent-except) - wrong rule
    """
    assert len(findings(src, "async-blocking")) == 1


def test_trailing_suppression_does_not_bleed_to_next_line():
    # an allow on a line of CODE covers that line only; a later edit
    # adding the same violation right below must still be flagged
    src = """
    import time
    async def work():
        time.sleep(1)  # dynlint: allow(async-blocking) - justified here
        time.sleep(2)
    """
    out = findings(src, "async-blocking")
    assert len(out) == 1 and out[0].line == 5


def test_suppression_allows_multiple_rules_and_all():
    src = """
    import time
    async def work():
        time.sleep(1)  # dynlint: allow(async-blocking, task-leak) - multi
        time.sleep(2)  # dynlint: allow(all) - blanket
    """
    assert not findings(src, "async-blocking")


# --------------------------------------------------------------------------
# baseline mechanics
# --------------------------------------------------------------------------


def test_baseline_roundtrip_and_new_violation_detection(tmp_path):
    src_v1 = textwrap.dedent(
        """
        import time
        async def a():
            time.sleep(1)
        """
    )
    rules = get_rules(["async-blocking"])
    first = lint_source(src_v1, rules, rel="pkg/mod.py")
    path = str(tmp_path / "baseline.json")
    write_baseline(path, first)
    baseline = load_baseline(path)

    # same findings (even at shifted lines) -> clean
    shifted = lint_source("# moved\n# down\n" + src_v1, rules, rel="pkg/mod.py")
    diff = diff_against_baseline(shifted, baseline)
    assert not diff.new and len(diff.known) == 1

    # one MORE identical violation -> exactly the excess is new
    src_v2 = src_v1 + "    time.sleep(1)\n"
    diff = diff_against_baseline(
        lint_source(src_v2, rules, rel="pkg/mod.py"), baseline
    )
    assert len(diff.new) == 1 and len(diff.known) == 1

    # violation fixed -> stale entry reported, nothing fails
    diff = diff_against_baseline([], baseline)
    assert not diff.new and diff.stale


def test_baseline_partial_fix_is_stale_not_free_allowance():
    """Fixing one of N identical debt items must surface as stale, or
    the freed count would silently absorb a future new violation."""
    rules = get_rules(["async-blocking"])
    two = lint_source(
        textwrap.dedent(
            """
            import time
            async def a():
                time.sleep(1)
                time.sleep(1)
            """
        ),
        rules, rel="pkg/mod.py",
    )
    baseline = {two[0].key(): 2}
    one = lint_source(
        textwrap.dedent(
            """
            import time
            async def a():
                time.sleep(1)
            """
        ),
        rules, rel="pkg/mod.py",
    )
    diff = diff_against_baseline(one, baseline)
    assert not diff.new and len(diff.known) == 1
    assert diff.stale == [two[0].key()]


# --------------------------------------------------------------------------
# CLI contract
# --------------------------------------------------------------------------


def _write_pkg(tmp_path, body):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(textwrap.dedent(body))
    return str(pkg)


def test_cli_exit_codes_and_update_baseline(tmp_path):
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    try:
        import dynlint
    finally:
        sys.path.pop(0)
    pkg = _write_pkg(
        tmp_path,
        """
        import time
        async def a():
            time.sleep(1)
        """,
    )
    baseline = str(tmp_path / "b.json")
    # dirty, no baseline -> 1
    assert dynlint.main(["dynlint", pkg, "--baseline", baseline]) == 1
    # record the debt -> 0 afterwards
    assert dynlint.main(
        ["dynlint", pkg, "--baseline", baseline, "--update-baseline"]) == 0
    assert dynlint.main(["dynlint", pkg, "--baseline", baseline]) == 0
    # --no-baseline still reports it
    assert dynlint.main(
        ["dynlint", pkg, "--baseline", baseline, "--no-baseline"]) == 1
    # unknown rule -> usage error
    assert dynlint.main(["dynlint", pkg, "--rules", "nope"]) == 2
    entries = json.load(open(baseline))["entries"]
    assert len(entries) == 1 and "async-blocking" in next(iter(entries))


def test_cli_refuses_scoped_update_of_shared_baseline(tmp_path):
    """--update-baseline with --rules or a narrowed path would rewrite
    the SHARED baseline from partial findings, deleting out-of-scope
    entries — the CLI must refuse (exit 2) before writing."""
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    try:
        import dynlint
    finally:
        sys.path.pop(0)
    before = open(BASELINE).read()
    assert dynlint.main(
        ["dynlint", "--rules", "silent-except", "--update-baseline"]) == 2
    assert dynlint.main(
        ["dynlint", os.path.join(PACKAGE_ROOT, "engine"),
         "--update-baseline"]) == 2
    assert open(BASELINE).read() == before, "shared baseline was rewritten"
    # a scoped update pointed at a PRIVATE baseline file is fine
    private = str(tmp_path / "scoped.json")
    assert dynlint.main(
        ["dynlint", os.path.join(PACKAGE_ROOT, "engine"),
         "--baseline", private, "--update-baseline"]) == 0
    assert os.path.exists(private)


def test_check_metric_names_script_contract_unchanged():
    """The shim keeps the historical CLI: exit 0 + conformance summary."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts",
                                      "check_metric_names.py")],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "conform" in proc.stdout


# --------------------------------------------------------------------------
# enforcement: the package itself is clean modulo the committed baseline
# --------------------------------------------------------------------------


@pytest.mark.dynlint
def test_dynamo_tpu_lints_clean_modulo_baseline():
    findings_all = lint_paths([PACKAGE_ROOT], all_rules())
    diff = diff_against_baseline(findings_all, load_baseline(BASELINE))
    assert not diff.new, "new dynlint violations:\n" + "\n".join(
        f.render() for f in diff.new
    )
    assert not diff.stale, (
        "stale baseline entries (fixed debt — run "
        "'python scripts/dynlint.py --update-baseline' to prune):\n"
        + "\n".join(diff.stale)
    )


def test_kernel_campaign_ops_modules_are_jit_impure_clean():
    """The kernel-campaign modules — the SP paged prefix walk, the
    fused sampling epilogue, and the decode kernels they share helpers
    with — must carry ZERO jit-impure findings, with no baseline
    allowance: host-effect Python inside these traced bodies would fire
    once per Mosaic specialization compile and skew every differential."""
    mods = [
        os.path.join(PACKAGE_ROOT, "ops", "pallas_sp.py"),
        os.path.join(PACKAGE_ROOT, "ops", "pallas_epilogue.py"),
        os.path.join(PACKAGE_ROOT, "ops", "pallas_decode.py"),
    ]
    found = lint_paths(mods, get_rules(["jit-impure"]))
    assert not found, "\n".join(f.render() for f in found)


def test_overlapping_paths_do_not_double_count():
    """dynlint dynamo_tpu dynamo_tpu/engine must not lint guided.py twice
    — duplicate counts would trip the baseline ratchet with phantoms."""
    engine = os.path.join(PACKAGE_ROOT, "engine")
    once = lint_paths([engine], get_rules(["silent-except"]))
    twice = lint_paths([engine, os.path.join(engine, "guided.py")],
                       get_rules(["silent-except"]))
    assert [f.key() for f in once] == [f.key() for f in twice]


def test_lint_paths_raises_on_missing_path():
    """A typo'd scope must never read as a clean scan."""
    with pytest.raises(FileNotFoundError):
        lint_paths([os.path.join(REPO_ROOT, "no_such_dir")], all_rules())
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    try:
        import dynlint
    finally:
        sys.path.pop(0)
    assert dynlint.main(["dynlint", "no_such_dir"]) == 2


def test_scoped_paths_produce_baseline_stable_keys():
    """dynlint <repo>, <file> and <subdir> must key findings identically
    to the package-wide scan, or the baseline only works for full runs."""
    guided = os.path.join(PACKAGE_ROOT, "engine", "guided.py")
    for scope in (guided, os.path.join(PACKAGE_ROOT, "engine"), REPO_ROOT):
        found = lint_paths([scope], get_rules(["silent-except"]))
        files = {f.file for f in found}
        assert "dynamo_tpu/engine/guided.py" in files, (scope, files)
        diff = diff_against_baseline(found, load_baseline(BASELINE))
        assert not [f for f in diff.new
                    if f.file == "dynamo_tpu/engine/guided.py"]


# --------------------------------------------------------------------------
# dispatch-ahead decode pipeline: the hot loop's purity contract
# --------------------------------------------------------------------------


@pytest.mark.dynlint
def test_decode_pipeline_modules_pass_jit_impure_and_async_blocking():
    """The pipelined decode path lives or dies on two properties dynlint
    polices: no host syncs inside the traced burst program (jit-impure)
    and no blocking calls on the scheduler's event loop (async-blocking
    — the executor-side token sync must be the only host sync in the
    loop). Pin them with ZERO findings, not baseline-covered ones."""
    modules = [
        os.path.join(PACKAGE_ROOT, "engine", "scheduler.py"),
        os.path.join(PACKAGE_ROOT, "engine", "model_runner.py"),
        os.path.join(PACKAGE_ROOT, "engine", "block_allocator.py"),
    ]
    found = lint_paths(modules, get_rules(["jit-impure", "async-blocking"]))
    assert found == [], "pipeline hot path regressed:\n" + "\n".join(
        f.render() for f in found
    )


def test_scheduler_token_sync_is_the_only_loop_host_sync():
    """Structural pin for the pipeline's purity claim: inside
    engine/scheduler.py's async functions, every ``np.asarray`` host
    sync happens inside a nested (executor-bound) ``def``, never
    directly on the event loop."""
    import ast

    path = os.path.join(PACKAGE_ROOT, "engine", "scheduler.py")
    with open(path) as f:
        tree = ast.parse(f.read())

    def direct_calls(fn):
        """Call nodes in fn's body, excluding nested function bodies
        (those run wherever they're called — here, the executor)."""
        out = []
        stack = [n for n in ast.iter_child_nodes(fn)]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                continue
            if isinstance(node, ast.Call):
                out.append(node)
            stack.extend(ast.iter_child_nodes(node))
        return out

    offenders = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.AsyncFunctionDef):
            continue
        for call in direct_calls(node):
            f_ = call.func
            if (isinstance(f_, ast.Attribute) and f_.attr == "asarray"
                    and isinstance(f_.value, ast.Name)
                    and f_.value.id == "np"):
                offenders.append((node.name, call.lineno))
    assert not offenders, (
        "np.asarray on the scheduler event loop (host sync must ride "
        f"run_in_executor): {offenders}"
    )


def test_jit_impure_flags_host_sync_in_burst_shaped_program():
    """TP fixture shaped like the burst program: an np.asarray of the
    carry inside the traced function is exactly the per-dispatch stall
    the pipeline exists to remove — jit-impure must catch it."""
    out = findings(
        """
        import jax
        import numpy as np

        def build(step):
            def burst(carry, tokens0):
                toks = step(carry, tokens0)
                host = np.asarray(toks)   # host sync under trace
                return toks, host
            return jax.jit(burst)
        """,
        "jit-impure",
    )
    assert [f.rule for f in out] == ["jit-impure"]
    assert "numpy.asarray" in out[0].message


def test_async_blocking_flags_sync_sleep_in_pipelined_loop_shape():
    """TP fixture shaped like a naive dispatch-ahead loop that waits for
    the device with a blocking sleep on the event loop."""
    out = findings(
        """
        import time
        async def decode_pipelined(runner, bursts):
            for burst in bursts:
                runner.dispatch(burst)
                time.sleep(0.001)  # "wait for the device"
        """,
        "async-blocking",
    )
    assert [f.rule for f in out] == ["async-blocking"]


def test_async_blocking_flags_drain_callback_waiting_on_loop():
    """TP fixture shaped like a careless chained-decode drain: the
    callback reconciling a queued burst waits out the device with a
    blocking sleep ON the scheduler loop instead of syncing through the
    executor — exactly the hop the persistent loop's async row drain
    must ride (scheduler._apply_burst's run_in_executor)."""
    out = findings(
        """
        import time
        async def drain_chain(chain, apply_tokens):
            while chain:
                burst = chain.popleft()
                while not burst.toks.is_ready():
                    time.sleep(0.0005)  # "wait for the burst"
                apply_tokens(burst)
        """,
        "async-blocking",
    )
    assert [f.rule for f in out] == ["async-blocking"]


# --------------------------------------------------------------------------
# streamed remote prefill: the transfer pipeline's purity contract
# --------------------------------------------------------------------------


@pytest.mark.dynlint
def test_disagg_stream_modules_pass_jit_impure_and_async_blocking():
    """The streamed remote-prefill pipeline has the same two load-bearing
    properties as the decode pipeline: no host syncs under trace and no
    blocking work on the worker's event loop — the device gather is
    dispatch-only on the loop (it must serialize with the step's donated
    cache buffers) while every host sync (device→host frame copy, byte
    packing) and frame write rides the executor-bound pump. Pin the whole
    disagg vertical clean, ZERO findings (not baseline-covered ones)."""
    modules = [
        os.path.join(PACKAGE_ROOT, "disagg", "prefill_worker.py"),
        os.path.join(PACKAGE_ROOT, "disagg", "transfer.py"),
        os.path.join(PACKAGE_ROOT, "disagg", "ici_transfer.py"),
        os.path.join(PACKAGE_ROOT, "disagg", "coordinator.py"),
    ]
    found = lint_paths(modules, get_rules(["jit-impure", "async-blocking"]))
    assert found == [], "streamed transfer hot path regressed:\n" + "\n".join(
        f.render() for f in found
    )


def test_async_blocking_flags_sync_wait_in_streaming_pump_shape():
    """TP fixture shaped like a naive frame pump that waits out the wire
    with a blocking sleep on the loop — exactly what the executor-bound
    pump discipline forbids."""
    out = findings(
        """
        import time
        async def frame_pump(frames, sock):
            for k, v in frames:
                sock.write(k.tobytes())
                time.sleep(0.01)  # "let the bytes drain"
        """,
        "async-blocking",
    )
    assert [f.rule for f in out] == ["async-blocking"]


# --------------------------------------------------------------------------
# flight recorder + stall watchdog: the always-on observability contract
# --------------------------------------------------------------------------


@pytest.mark.dynlint
def test_flight_watchdog_modules_pass_async_blocking_and_task_leak():
    """The flight ring runs on EVERY hot path and the watchdog watches
    the loop it runs on, so their own discipline is load-bearing: the
    ring append must never touch the event loop (no blocking IO in async
    code — artifact writes ride run_in_executor) and the watchdog task
    must be held and cancelled on stop (a leaked watchdog would sample a
    dead engine forever). Pin both modules ZERO-finding, not
    baseline-covered."""
    modules = [
        os.path.join(PACKAGE_ROOT, "telemetry", "flight.py"),
        os.path.join(PACKAGE_ROOT, "telemetry", "watchdog.py"),
    ]
    found = lint_paths(modules, get_rules(["async-blocking", "task-leak"]))
    assert found == [], "flight/watchdog discipline regressed:\n" + "\n".join(
        f.render() for f in found
    )


def test_task_leak_flags_watchdog_shaped_discarded_task():
    """TP fixture shaped like a careless watchdog: the sampling task's
    handle is dropped, so stop() can never cancel it and it samples a
    dead engine forever."""
    out = findings(
        """
        import asyncio

        class Watchdog:
            def start(self):
                asyncio.get_running_loop().create_task(self._run())

            async def _run(self):
                while True:
                    await asyncio.sleep(1.0)
        """,
        "task-leak",
    )
    assert [f.rule for f in out] == ["task-leak"]


def test_async_blocking_flags_artifact_write_on_loop_shape():
    """TP fixture shaped like a naive trip handler that writes the
    flight artifact directly on the event loop — exactly the stall the
    watchdog exists to detect, committed by the watchdog itself."""
    out = findings(
        """
        import json

        async def on_trip(artifact, path):
            with open(path, "w") as f:
                json.dump(artifact, f)
        """,
        "async-blocking",
    )
    assert [f.rule for f in out] == ["async-blocking"]
    assert "open" in out[0].message


def test_jit_impure_flags_host_sync_in_gather_shaped_program():
    """TP fixture shaped like the frame gather: an np.asarray inside the
    traced gather is a per-frame device→host stall — the transfer would
    serialize against compute instead of overlapping it."""
    out = findings(
        """
        import jax
        import numpy as np

        def build(cache):
            def gather(ids):
                blocks = cache[:, ids]
                return np.asarray(blocks)   # host sync under trace
            return jax.jit(gather)
        """,
        "jit-impure",
    )
    assert [f.rule for f in out] == ["jit-impure"]
    assert "numpy.asarray" in out[0].message


@pytest.mark.dynlint
def test_enforcement_scan_is_not_vacuous():
    """The walk must actually see the tree: recorded debt is present and
    the analyzer parses every module (no parse-error findings)."""
    findings_all = lint_paths([PACKAGE_ROOT], all_rules())
    assert not [f for f in findings_all if f.rule == "parse-error"]
    # the committed baseline's debt is real, live findings
    diff = diff_against_baseline(findings_all, load_baseline(BASELINE))
    assert len(diff.known) >= 1


# --------------------------------------------------------------------------
# closed-loop SLA planner: the control loop's own discipline
# --------------------------------------------------------------------------


@pytest.mark.dynlint
def test_planner_modules_pass_async_blocking_and_task_leak():
    """The planner loop is exactly the shape these rules police: a
    periodic asyncio task that calls out to cluster clients (kubectl
    subprocess, api-store REST — both MUST ride an executor) and that
    stop() must be able to cancel (a leaked planner keeps scaling a
    deployment nobody is watching). Pin the whole subsystem ZERO-finding,
    not baseline-covered."""
    modules = [
        os.path.join(PACKAGE_ROOT, "planner", "planner.py"),
        os.path.join(PACKAGE_ROOT, "planner", "policy.py"),
        os.path.join(PACKAGE_ROOT, "planner", "signals.py"),
        os.path.join(PACKAGE_ROOT, "planner", "admission.py"),
        os.path.join(PACKAGE_ROOT, "planner", "actuation.py"),
    ]
    found = lint_paths(modules, get_rules(["async-blocking", "task-leak"]))
    assert found == [], "planner loop discipline regressed:\n" + "\n".join(
        f.render() for f in found
    )


def test_async_blocking_flags_kubectl_on_loop_shape():
    """TP fixture shaped like a careless KubeActuator: the reconcile
    (a kubectl subprocess under the hood) runs directly on the planner's
    event loop, stalling every admission decision behind the API server."""
    out = findings(
        """
        import subprocess

        async def apply_scale(manifest):
            subprocess.run(["kubectl", "apply", "-f", "-"], input=manifest)
        """,
        "async-blocking",
    )
    assert [f.rule for f in out] == ["async-blocking"]


def test_task_leak_flags_planner_shaped_discarded_loop():
    """TP fixture shaped like a careless planner: the observe→decide→
    actuate task handle is dropped, so stop() can never cancel it and it
    keeps patching replicas after shutdown."""
    out = findings(
        """
        import asyncio

        class Planner:
            def start(self):
                asyncio.create_task(self._loop())

            async def _loop(self):
                while True:
                    await asyncio.sleep(2.0)
        """,
        "task-leak",
    )
    assert [f.rule for f in out] == ["task-leak"]


# --------------------------------------------------------------------------
# self-healing recovery: the drain/migrate/respawn stack's discipline
# --------------------------------------------------------------------------


@pytest.mark.dynlint
def test_recovery_modules_pass_async_blocking_and_task_leak():
    """The recovery ladder runs precisely when the engine is ailing —
    a controller that blocks the event loop (a sleep-based respawn
    backoff, an inline KV gather) would wedge the very loop the watchdog
    is trying to save, and a dropped relay task would strand a migrated
    client stream. Pin the subsystem (and the fault-injection helper the
    chaos paths call from hot loops) ZERO-finding, not baseline-covered."""
    modules = [
        os.path.join(PACKAGE_ROOT, "recovery", "controller.py"),
        os.path.join(PACKAGE_ROOT, "recovery", "migration.py"),
        os.path.join(PACKAGE_ROOT, "utils", "faults.py"),
    ]
    found = lint_paths(modules, get_rules(["async-blocking", "task-leak"]))
    assert found == [], "recovery discipline regressed:\n" + "\n".join(
        f.render() for f in found
    )


def test_async_blocking_flags_respawn_loop_sleeping_on_loop():
    """TP fixture shaped like a careless respawn ladder: the exponential
    backoff runs time.sleep on the event loop, so every admission
    decision, watchdog sample, and relay frame stalls behind it."""
    out = findings(
        """
        import time

        async def respawn_with_backoff(spawn):
            delay = 1.0
            for _ in range(3):
                try:
                    return await spawn()
                except Exception:
                    time.sleep(delay)
                    delay *= 2
        """,
        "async-blocking",
    )
    assert [f.rule for f in out] == ["async-blocking"]


def test_task_leak_flags_migration_relay_shaped_discarded_task():
    """TP fixture shaped like a careless migrator: the relay task that
    forwards the peer's resumed stream is dropped on the floor — close()
    can never cancel it and its exception is silently lost along with
    the client's stream tail."""
    out = findings(
        """
        import asyncio

        class Migrator:
            def ship(self, er):
                asyncio.create_task(self._relay(er))

            async def _relay(self, er):
                while True:
                    await asyncio.sleep(0.1)
        """,
        "task-leak",
    )
    assert [f.rule for f in out] == ["task-leak"]


# --------------------------------------------------------------------------
# request X-ray: the cross-process trace/SLO/device-time modules
# --------------------------------------------------------------------------


@pytest.mark.dynlint
def test_xray_telemetry_modules_pass_async_blocking_and_task_leak():
    """The X-ray modules sit on every request's exit path (trace record,
    SLO verdict) and on the scheduler's reconciliation seams (device
    time), so their own discipline is load-bearing: span folding and SLO
    accounting are pure arithmetic that must never block the event loop,
    and nothing here may spawn an unheld task. Pin the whole vertical
    ZERO-finding, not baseline-covered."""
    modules = [
        os.path.join(PACKAGE_ROOT, "telemetry", "tracing.py"),
        os.path.join(PACKAGE_ROOT, "telemetry", "stitch.py"),
        os.path.join(PACKAGE_ROOT, "telemetry", "device_time.py"),
        os.path.join(PACKAGE_ROOT, "telemetry", "slo.py"),
    ]
    found = lint_paths(modules, get_rules(["async-blocking", "task-leak"]))
    assert found == [], "x-ray telemetry discipline regressed:\n" + "\n".join(
        f.render() for f in found
    )


def test_async_blocking_flags_span_export_write_on_loop_shape():
    """TP fixture shaped like a careless span exporter: serializing the
    stitched trace to disk directly on the event loop — exactly the
    stall the trace JSONL sink's writer thread (and the flight
    artifact's run_in_executor write) exist to avoid."""
    out = findings(
        """
        import json

        async def export_stitched_trace(trace, path):
            with open(path, "w") as f:
                json.dump(trace, f)
        """,
        "async-blocking",
    )
    assert [f.rule for f in out] == ["async-blocking"]
    assert "open" in out[0].message


# --------------------------------------------------------------------------
# fleet hub + incident recorder: the modules that run WHILE things break
# --------------------------------------------------------------------------


@pytest.mark.dynlint
def test_fleet_observability_modules_pass_async_blocking_and_task_leak():
    """The hub's scrape loop shares the frontend's event loop and the
    incident recorder runs at the exact moment the process is already
    ailing — a bundle write or profiler capture on the loop would extend
    the very stall it is documenting, and a dropped capture/scrape task
    would silently lose the evidence. Pin all three modules ZERO-finding,
    not baseline-covered."""
    modules = [
        os.path.join(PACKAGE_ROOT, "telemetry", "hub.py"),
        os.path.join(PACKAGE_ROOT, "telemetry", "history.py"),
        os.path.join(PACKAGE_ROOT, "telemetry", "incidents.py"),
    ]
    found = lint_paths(modules, get_rules(["async-blocking", "task-leak"]))
    assert found == [], "fleet observability discipline regressed:\n" + \
        "\n".join(f.render() for f in found)


def test_async_blocking_flags_bundle_write_on_loop_shape():
    """TP fixture shaped like a careless incident capture: serializing
    the bundle to disk directly on the event loop, right when the
    watchdog just reported that loop as the problem."""
    out = findings(
        """
        import json

        async def capture_bundle(manifest, artifact, path):
            with open(path, "w") as f:
                json.dump({"manifest": manifest, "flight": artifact}, f)
        """,
        "async-blocking",
    )
    assert [f.rule for f in out] == ["async-blocking"]
    assert "open" in out[0].message


def test_async_blocking_flags_profiler_capture_sleeping_on_loop():
    """TP fixture shaped like a careless incident profile window: the
    jax.profiler capture holds the trace open with time.sleep ON the
    loop — utils/profiling.capture_trace is executor-only for a reason."""
    out = findings(
        """
        import time

        async def profile_window(trace, seconds):
            with trace:
                time.sleep(seconds)
        """,
        "async-blocking",
    )
    assert [f.rule for f in out] == ["async-blocking"]


def test_task_leak_flags_discarded_capture_task_shape():
    """TP fixture shaped like a careless trigger: the capture task is
    dropped on the floor — stop() can never await it and a failing
    capture's exception (the evidence loss!) is silently swallowed."""
    out = findings(
        """
        import asyncio

        class Recorder:
            def trigger(self, reason):
                asyncio.get_running_loop().create_task(self._capture(reason))

            async def _capture(self, reason):
                await asyncio.sleep(1.0)
        """,
        "task-leak",
    )
    assert [f.rule for f in out] == ["task-leak"]


# --------------------------------------------------------------------------
# cluster KV fabric: spill I/O must ride the executor
# --------------------------------------------------------------------------


@pytest.mark.dynlint
def test_kv_fabric_modules_pass_async_blocking_and_task_leak():
    """The fabric's pull pump shares the scheduler's event loop and the
    cold tier's spill writes fire from the host tier's drain (also
    loop-side): a blocking file read/write or a dropped spill future
    there stalls decode for every request. Pin both modules with ZERO
    findings (not baseline-covered ones) on the two rules that police
    exactly that — all disk I/O rides the executor with its future
    held (kv/cold_tier.py offer/close discipline)."""
    modules = [
        os.path.join(PACKAGE_ROOT, "kv", "fabric.py"),
        os.path.join(PACKAGE_ROOT, "kv", "cold_tier.py"),
    ]
    found = lint_paths(modules, get_rules(["async-blocking", "task-leak"]))
    assert found == [], "KV fabric hot path regressed:\n" + "\n".join(
        f.render() for f in found
    )


def test_async_blocking_flags_cold_spill_write_on_loop():
    """TP fixture shaped like the tempting-but-wrong cold-tier spill:
    writing the block file synchronously inside the async eviction hook
    blocks the scheduler loop for a disk round-trip per evicted block."""
    out = findings(
        """
        import os

        async def on_evict(path, sequence_hash, payload):
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(payload)
            os.replace(tmp, path)
        """,
        "async-blocking",
    )
    assert [f.rule for f in out] == ["async-blocking"]
    assert "open" in out[0].message


# --------------------------------------------------------------------------
# multi-model registry plane: watch/pool loops share the serving loop
# --------------------------------------------------------------------------


@pytest.mark.dynlint
def test_registry_modules_pass_async_blocking_and_task_leak():
    """The registry's pool-policy loop, cold-start tasks, and quota
    buckets all run ON the frontend's serving loop (single-loop
    discipline like the admission controller): a blocking call stalls
    every request, and a dropped cold-start or policy-loop task is a
    spawn nobody can cancel or observe failing. Pin the whole package
    with ZERO findings (not baseline-covered ones) on the two rules
    that police exactly that."""
    modules = [
        os.path.join(PACKAGE_ROOT, "registry", "cards.py"),
        os.path.join(PACKAGE_ROOT, "registry", "registry.py"),
        os.path.join(PACKAGE_ROOT, "registry", "pools.py"),
        os.path.join(PACKAGE_ROOT, "registry", "policy.py"),
        os.path.join(PACKAGE_ROOT, "registry", "tenants.py"),
    ]
    found = lint_paths(modules, get_rules(["async-blocking", "task-leak"]))
    assert found == [], "registry plane regressed:\n" + "\n".join(
        f.render() for f in found
    )


def test_task_leak_flags_discarded_registry_watch_task():
    """TP fixture shaped like the tempting-but-wrong registry watcher:
    spawning the watch loop without holding the task means a worker
    churn event after GC silently stops rebinding routes — models keep
    serving stale pools and nobody sees the exception."""
    out = findings(
        """
        import asyncio

        class RegistryWatcher:
            async def start(self, watcher):
                asyncio.create_task(self._watch_loop(watcher))

            async def _watch_loop(self, watcher):
                async for ev in watcher:
                    self.apply(ev)
        """,
        "task-leak",
    )
    assert [f.rule for f in out] == ["task-leak"]


# --------------------------------------------------------------------------
# unrestricted persistent decode (ISSUE 13): the in-carry spec/guided/
# stop-string machinery's purity contract
# --------------------------------------------------------------------------


@pytest.mark.dynlint
def test_unrestricted_chain_modules_pass_jit_impure_and_async_blocking():
    """The reworked sampling module (suffix ring + stop hashes inside
    the traced burst) and the guided device-table builder must stay
    clean on the two rules that police the chain's purity: no host
    syncs under trace (jit-impure) and no blocking work on the event
    loop (async-blocking — the table compile's per-state vocab sweep
    rides an executor; scheduler._guided_chain_reason). Pin ZERO
    findings, not baseline-covered ones."""
    modules = [
        os.path.join(PACKAGE_ROOT, "engine", "sampling.py"),
        os.path.join(PACKAGE_ROOT, "engine", "guided.py"),
    ]
    found = lint_paths(modules, get_rules(["jit-impure", "async-blocking"]))
    assert found == [], "unrestricted-chain module regressed:\n" + "\n".join(
        f.render() for f in found
    )


def test_async_blocking_flags_grammar_table_compile_on_loop_shape():
    """TP fixture shaped like a careless guided-chain admission: the
    grammar's device-table compile busy-polls (and reads the piece
    table) ON the scheduler loop instead of riding an executor — the
    per-state vocab sweep is seconds of CPU for a real tokenizer, which
    would starve every live stream's drain."""
    out = findings(
        """
        import time
        async def admit_guided(sched, er, compile_table):
            table = compile_table(er.guided)   # O(states x vocab) sweep
            while table is None:
                time.sleep(0.01)               # "wait for the compile"
                table = compile_table(er.guided)
            sched.install_table(er, table)
        """,
        "async-blocking",
    )
    assert [f.rule for f in out] == ["async-blocking"]


@pytest.mark.dynlint
def test_sp_prefill_modules_pass_jit_impure_and_async_blocking():
    """The sequence-parallel prefill seam (docs/long_context.md): the
    SP chunk ladder dispatches on the scheduler loop and must stay
    dispatch-only (no host syncs outside the executor), and the
    parallel attention modules trace under jit (no impurity). Pin the
    whole vertical ZERO-finding, not baseline-covered."""
    modules = [
        os.path.join(PACKAGE_ROOT, "parallel", "sequence.py"),
        os.path.join(PACKAGE_ROOT, "parallel", "ring_attention.py"),
        os.path.join(PACKAGE_ROOT, "ops", "compat.py"),
        os.path.join(PACKAGE_ROOT, "llm", "embeddings.py"),
        os.path.join(PACKAGE_ROOT, "engine", "scheduler.py"),
    ]
    found = lint_paths(
        modules, get_rules(["jit-impure", "async-blocking"]))
    assert found == [], "sp prefill seam regressed:\n" + "\n".join(
        f.render() for f in found
    )


# --------------------------------------------------------------------------
# fleet simulator: virtual-time discipline under sim/
# --------------------------------------------------------------------------


@pytest.mark.dynlint
def test_sim_modules_pass_async_blocking_and_task_leak():
    """The simulator's 1000x claim rests on the virtual loop never
    blocking for real: one time.sleep or sync file read inside a sim
    coroutine burns WALL time per virtual tick (the speedup gate in
    scripts/fleetsim.py would quietly decay to 1x), and a dropped
    worker/chaos/scrape task would outlive the run and corrupt the
    next scenario's determinism. Pin the whole package ZERO-finding,
    not baseline-covered."""
    sim = os.path.join(PACKAGE_ROOT, "sim")
    modules = [os.path.join(sim, name)
               for name in sorted(os.listdir(sim))
               if name.endswith(".py")]
    assert len(modules) >= 7  # the scan must actually see the package
    found = lint_paths(modules, get_rules(["async-blocking", "task-leak"]))
    assert found == [], "sim virtual-time discipline regressed:\n" + \
        "\n".join(f.render() for f in found)


def test_async_blocking_flags_sim_loop_sleeping_for_real():
    """TP fixture shaped like the tempting-but-wrong sim pacing: the
    arrival dispatcher waits out inter-arrival gaps with time.sleep —
    real seconds on the virtual loop, exactly the bug that turns a
    1000x replay back into real time."""
    out = findings(
        """
        import time

        async def dispatch_arrivals(requests, serve):
            last = 0.0
            for req in requests:
                time.sleep(req.arrival_s - last)   # real seconds!
                last = req.arrival_s
                serve(req)
        """,
        "async-blocking",
    )
    assert [f.rule for f in out] == ["async-blocking"]


def test_task_leak_flags_sim_serve_shaped_discarded_task():
    """TP fixture shaped like a careless request dispatcher: per-request
    serve tasks spawned without holding the handle can never be awaited
    at teardown, so a late completion leaks into the NEXT scenario's
    virtual clock and breaks byte-identical replay."""
    out = findings(
        """
        import asyncio

        class Fleet:
            def dispatch(self, req):
                asyncio.create_task(self._serve(req))

            async def _serve(self, req):
                await asyncio.sleep(1.0)
        """,
        "task-leak",
    )
    assert [f.rule for f in out] == ["task-leak"]


# --------------------------------------------------------------------------
# wallclock-in-sim: the simulator's virtual-time contract as a rule
# --------------------------------------------------------------------------

SIM_REL = "dynamo_tpu/sim/fixture_mod.py"


def sim_findings(src, rel=SIM_REL):
    return lint_source(textwrap.dedent(src), get_rules(["wallclock-in-sim"]),
                       rel=rel)


def test_wallclock_in_sim_flags_time_reads_and_sleep():
    out = sim_findings(
        """
        import time
        def sample():
            return time.time()
        def tick():
            time.sleep(0.1)
        """,
    )
    assert [f.line for f in out] == [4, 6]
    assert "time.time" in out[0].message and "time.sleep" in out[1].message


def test_wallclock_in_sim_resolves_aliases_and_datetime():
    out = sim_findings(
        """
        from time import monotonic as mono
        import datetime
        def sample():
            return mono(), datetime.datetime.now()
        """,
    )
    assert len(out) == 2
    assert {"time.monotonic", "datetime.datetime.now"} <= {
        m for f in out for m in [f.message.split("()")[0]]
    }


def test_wallclock_in_sim_flags_loop_time():
    out = sim_findings(
        """
        def drive(loop):
            return loop.time()
        """,
    )
    assert len(out) == 1 and "loop.time()" in out[0].message


def test_wallclock_in_sim_scoped_to_sim_package_only():
    """The identical source outside dynamo_tpu/sim/ is legitimate."""
    src = """
        import time
        def sample():
            return time.time()
    """
    assert sim_findings(src, rel="dynamo_tpu/telemetry/hub.py") == []
    assert sim_findings(src, rel="dynamo_tpu/sim_tools/x.py") == []
    assert len(sim_findings(src)) == 1


def test_wallclock_in_sim_does_not_flag_virtual_clock_idiom():
    """clock() calls routed through the scenario's VirtualClock — the
    sanctioned spelling — stay clean, as do mere mentions in strings."""
    assert sim_findings(
        """
        def sample(clock):
            return clock.now()  # "time.time" in a comment is fine
        """,
    ) == []


def test_wallclock_in_sim_suppression():
    out = sim_findings(
        """
        import time
        def seed_entropy():
            # dynlint: allow(wallclock-in-sim) - one-shot seed material, never consulted mid-run
            return time.time_ns()
        """,
    )
    assert out == []


@pytest.mark.dynlint
def test_sim_package_has_zero_wallclock_findings():
    """The rule that replaced test_fleetsim's regex scan must hold the
    same line: ZERO findings under sim/, not baseline-covered ones."""
    sim = os.path.join(PACKAGE_ROOT, "sim")
    assert lint_paths([sim], get_rules(["wallclock-in-sim"])) == []


# --------------------------------------------------------------------------
# dynrace: thread-domain inference
# --------------------------------------------------------------------------

from dynamo_tpu.analysis import SourceModule, infer_domains  # noqa: E402


def domains_of(src, rel="dynamo_tpu/fixture_mod.py"):
    mod = SourceModule(rel, textwrap.dedent(src))
    return infer_domains([mod])


def test_domains_async_def_is_loop():
    doms = domains_of(
        """
        async def pump():
            pass
        def untouched():
            pass
        """,
    )
    assert doms["dynamo_tpu/fixture_mod.py:pump"] == {"loop"}
    assert doms["dynamo_tpu/fixture_mod.py:untouched"] == set()


def test_domains_executor_lambda_and_thread_target():
    doms = domains_of(
        """
        import asyncio
        import threading

        class C:
            async def offload(self):
                loop = asyncio.get_running_loop()
                await loop.run_in_executor(None, lambda: self.render())
            def render(self):
                pass
            def start(self):
                threading.Thread(target=self._drain, daemon=True).start()
            def _drain(self):
                pass
        """,
    )
    assert doms["dynamo_tpu/fixture_mod.py:C.offload.<lambda>"] == {"executor"}
    # the lambda's body calls render() -> executor propagates through
    assert doms["dynamo_tpu/fixture_mod.py:C.render"] == {"executor"}
    assert doms["dynamo_tpu/fixture_mod.py:C._drain"] == {"thread"}


def test_domains_fixpoint_through_two_hop_call_chain():
    doms = domains_of(
        """
        import threading

        class C:
            async def on_loop(self):
                self._mid()
            def _mid(self):
                self._leaf()
            def _leaf(self):
                pass
            def start(self):
                threading.Thread(target=self._mid).start()
        """,
    )
    # loop (via async caller) and thread (via Thread target) both reach
    # _leaf two hops down
    assert doms["dynamo_tpu/fixture_mod.py:C._mid"] == {"loop", "thread"}
    assert doms["dynamo_tpu/fixture_mod.py:C._leaf"] == {"loop", "thread"}


def test_domains_annotation_overrides_propagation():
    doms = domains_of(
        """
        class C:
            async def on_loop(self):
                self._helper()
            # dynrace: domain(executor)
            def _helper(self):
                pass
            # dynrace: domain(any)
            def _anywhere(self):
                pass
        """,
    )
    # pinned: the loop caller must NOT add its domain
    assert doms["dynamo_tpu/fixture_mod.py:C._helper"] == {"executor"}
    assert doms["dynamo_tpu/fixture_mod.py:C._anywhere"] == set()


def test_domains_call_soon_threadsafe_and_partial_unwrap():
    doms = domains_of(
        """
        import functools

        class C:
            # dynrace: domain(thread)
            def from_thread(self, loop):
                loop.call_soon_threadsafe(self._apply)
                loop.call_later(1.0, functools.partial(self._tick, 3))
            def _apply(self):
                pass
            def _tick(self, n):
                pass
        """,
    )
    assert doms["dynamo_tpu/fixture_mod.py:C._apply"] == {"loop"}
    assert doms["dynamo_tpu/fixture_mod.py:C._tick"] == {"loop"}


def test_domains_nested_def_inherits_enclosing_domain():
    doms = domains_of(
        """
        async def handler():
            def fmt(x):
                return x
            return fmt(1)
        """,
    )
    assert doms["dynamo_tpu/fixture_mod.py:handler.fmt"] == {"loop"}


# --------------------------------------------------------------------------
# dynrace: cross-domain-race findings and sanctioned idioms
# --------------------------------------------------------------------------


def race_findings(src):
    return findings(src, "cross-domain-race")


def test_race_flags_executor_render_iterating_loop_mutated_dict():
    """The PR 10 class verbatim: the /fleet render runs in the executor
    and iterates a registry the scrape loop mutates in place."""
    out = race_findings(
        """
        import asyncio

        class Hub:
            def __init__(self):
                self._workers = {}
            async def scrape_once(self, name, w):
                self._workers[name] = w
            async def handle_fleet(self):
                loop = asyncio.get_running_loop()
                return await loop.run_in_executor(None, self.render)
            def render(self):
                return [w.name for w in self._workers.values()]
        """,
    )
    assert len(out) == 1
    assert out[0].line == 13
    assert "_workers" in out[0].message and "executor" in out[0].message


def test_race_sanctions_list_snapshot_read():
    """Same shape, but the render materializes list(...) first — the
    repo's sanctioned GIL-atomic snapshot idiom must stay clean."""
    assert race_findings(
        """
        import asyncio

        class Hub:
            def __init__(self):
                self._workers = {}
            async def scrape_once(self, name, w):
                self._workers[name] = w
            async def handle_fleet(self):
                loop = asyncio.get_running_loop()
                return await loop.run_in_executor(None, self.render)
            def render(self):
                return [w.name for w in list(self._workers.values())]
        """,
    ) == []


def test_race_flags_write_write_across_domains():
    out = race_findings(
        """
        import threading

        class C:
            def __init__(self):
                self.cur = None
            async def on_loop(self):
                self.cur = object()
            # dynrace: domain(thread)
            def off_loop(self):
                self.cur = None
        """,
    )
    assert len(out) == 2 and {f.line for f in out} == {8, 11}


def test_race_sanctions_lock_held_on_both_sides():
    assert race_findings(
        """
        import threading

        class C:
            def __init__(self):
                self.vals = []
                self._lock = threading.Lock()
            # dynrace: domain(thread)
            def writer(self):
                with self._lock:
                    self.vals.append(1)
            async def reader(self):
                with self._lock:
                    return [v for v in self.vals]
        """,
    ) == []


def test_race_flags_lock_held_on_one_side_only():
    out = race_findings(
        """
        import threading

        class C:
            def __init__(self):
                self.vals = []
                self._lock = threading.Lock()
            # dynrace: domain(thread)
            def writer(self):
                with self._lock:
                    self.vals.append(1)
            async def reader(self):
                return [v for v in self.vals]
        """,
    )
    assert len(out) == 1 and out[0].line == 13


def test_race_sanctions_queue_handoff():
    assert race_findings(
        """
        import queue

        class C:
            def __init__(self):
                self.q = queue.Queue(maxsize=64)
            # dynrace: domain(thread)
            def producer(self):
                self.q.put(1)
            async def consumer(self):
                return self.q.get_nowait()
        """,
    ) == []


def test_race_sanctions_call_soon_threadsafe_marshal():
    """Thread-side code marshals the mutation onto the loop — the
    callback is inferred loop-domain, so all writes live in one domain."""
    assert race_findings(
        """
        class C:
            def __init__(self, loop):
                self.loop = loop
                self.hooks = []
            # dynrace: domain(thread)
            def from_thread(self):
                self.loop.call_soon_threadsafe(self._apply)
            def _apply(self):
                self.hooks.append(1)
            async def on_loop(self):
                self.hooks.append(2)
        """,
    ) == []


def test_race_sanctions_init_only_assignment_then_reads():
    assert race_findings(
        """
        class C:
            def __init__(self, cfg):
                self.cfg = cfg
            async def on_loop(self):
                return self.cfg
            # dynrace: domain(executor)
            def render(self):
                return self.cfg
        """,
    ) == []


def test_race_sanctions_rebind_publish_with_cross_domain_reads():
    """Loop-side rebinding to a FRESH object is an atomic pointer
    publish; off-loop readers see the old or new dict, never a torn
    one — the snapshot-publish idiom must not be flagged."""
    assert race_findings(
        """
        class C:
            def __init__(self):
                self.snap = {}
            async def refresh(self):
                self.snap = {"a": 1}
            # dynrace: domain(executor)
            def render(self):
                return dict(self.snap)
        """,
    ) == []


def test_race_flags_live_deque_iteration_across_domains():
    """The device_time class: reconciliation appends to a rolling deque
    on the loop while a render callback iterates it off-loop — deques
    raise RuntimeError when mutated mid-iteration."""
    out = race_findings(
        """
        import collections

        class Tracker:
            def __init__(self):
                self._window = collections.deque(maxlen=4096)
            async def observe(self, s):
                self._window.append(s)
            # dynrace: domain(executor)
            def _samples(self):
                return [s for s in self._window]
        """,
    )
    assert len(out) == 1 and out[0].line == 11
    # ...and the list() spelling of the same read is the sanctioned fix
    assert race_findings(
        """
        import collections

        class Tracker:
            def __init__(self):
                self._window = collections.deque(maxlen=4096)
            async def observe(self, s):
                self._window.append(s)
            # dynrace: domain(executor)
            def _samples(self):
                return [s for s in list(self._window)]
        """,
    ) == []


def test_race_flags_rmw_counter_in_two_domains():
    out = race_findings(
        """
        class C:
            def __init__(self):
                self.n = 0
            async def on_loop(self):
                self.n += 1
            # dynrace: domain(executor)
            def off(self):
                self.n += 1
        """,
    )
    assert len(out) == 2


def test_race_unknown_domain_produces_no_findings():
    """A function the graph never reaches has no inferred domain — the
    pass is conservative and must stay silent rather than guess."""
    assert race_findings(
        """
        class C:
            def __init__(self):
                self.vals = []
            def somewhere(self):
                self.vals.append(1)
            async def reader(self):
                for v in self.vals:
                    pass
        """,
    ) == []


def test_race_suppression_and_key_stability():
    src = """
        import asyncio

        class Hub:
            def __init__(self):
                self._workers = {}
            async def scrape_once(self, name, w):
                self._workers[name] = w
            async def handle_fleet(self):
                loop = asyncio.get_running_loop()
                return await loop.run_in_executor(None, self.render)
            def render(self):
                # dynlint: allow(cross-domain-race) - fixture: documented benign
                return [w.name for w in self._workers.values()]
    """
    assert race_findings(src) == []
    # finding keys are line-free for the baseline ratchet
    noisy = race_findings(src.replace(
        "# dynlint: allow(cross-domain-race) - fixture: documented benign",
        "pass"))
    assert noisy and ":cross-domain-race: " in noisy[0].key()
    assert str(noisy[0].line) not in noisy[0].key().split(":")[0]


def test_race_cross_module_domain_propagation_via_relative_import():
    """Domains must propagate through a call edge that crosses a module
    boundary via a relative import (core's alias map skips those —
    domains.py enriches it), and Thread(target=<imported name>) must
    seed the function defined in the OTHER module."""
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        pkg = os.path.join(td, "pkg")
        os.makedirs(pkg)
        open(os.path.join(pkg, "__init__.py"), "w").close()
        with open(os.path.join(pkg, "helpers.py"), "w") as f:
            f.write(textwrap.dedent(
                """
                def compute():
                    return 1
                """))
        with open(os.path.join(pkg, "owner.py"), "w") as f:
            f.write(textwrap.dedent(
                """
                import threading
                from .helpers import compute

                async def on_loop():
                    return compute()

                def start():
                    threading.Thread(target=compute).start()
                """))
        mods = []
        for name in ("helpers.py", "owner.py"):
            with open(os.path.join(pkg, name)) as f:
                mods.append(SourceModule(f"pkg/{name}", f.read()))
        doms = infer_domains(mods)
        # loop via the async caller in owner.py, thread via the Thread
        # target — both reached compute() across the module boundary
        assert doms["pkg/helpers.py:compute"] == {"loop", "thread"}


# --------------------------------------------------------------------------
# unified transfer plane (dynamo_tpu/transfer/)
# --------------------------------------------------------------------------


@pytest.mark.dynlint
def test_transfer_plane_modules_pass_three_rule_screen():
    """Every KV byte in the system rides this package (disagg push,
    fabric pull, hot migration), so its discipline failures multiply:
    a blocking encode on the loop stalls all three planes at once, a
    dropped pump task strands a half-sent stream, and a cross-domain
    write on the shared poison/pipe state corrupts commit semantics
    under the executor offloads the framing itself performs. Pin the
    whole package ZERO-finding — not baseline-covered — on all three
    rules."""
    modules = [
        os.path.join(PACKAGE_ROOT, "transfer", "__init__.py"),
        os.path.join(PACKAGE_ROOT, "transfer", "framing.py"),
        os.path.join(PACKAGE_ROOT, "transfer", "plane.py"),
        os.path.join(PACKAGE_ROOT, "transfer", "tcp.py"),
        os.path.join(PACKAGE_ROOT, "transfer", "ici.py"),
    ]
    found = lint_paths(
        modules,
        get_rules(["async-blocking", "task-leak", "cross-domain-race"]),
    )
    assert found == [], "transfer-plane discipline regressed:\n" + \
        "\n".join(f.render() for f in found)


def test_async_blocking_flags_pack_on_loop_shape():
    """TP fixture shaped like a careless transfer backend: the frame
    encode spills through a blocking file write on the event loop —
    every other channel's pipelining stalls behind one sender's disk.
    (The real backends push encode_blocks through run_in_executor and
    only pack the small header inline.)"""
    out = findings(
        """
        import numpy as np

        async def send_frame(writer, k, v, spool_path):
            kb = np.ascontiguousarray(k).tobytes()
            with open(spool_path, "wb") as fh:
                fh.write(kb)
            writer.write(kb)
            await writer.drain()
        """,
        "async-blocking",
    )
    assert [f.rule for f in out] == ["async-blocking"]


# --------------------------------------------------------------------------
# dynrace: enforcement pins for the triaged serving-plane modules
# --------------------------------------------------------------------------


@pytest.mark.dynlint
def test_serving_plane_modules_pass_cross_domain_race():
    """The triage held the tree at zero un-suppressed findings; pin the
    hot modules individually so a regression names the file. These are
    the regression tests for this PR's fixes:

    - kv_router/metrics_aggregator.py: per-worker gauge callbacks and
      the staleness gauge iterated live dicts the poll loop mutates —
      now list() snapshots;
    - telemetry/device_time.py: _samples() iterated the live rolling
      deque the reconciliation seams append to — now a list() snapshot;
    - engine/scheduler.py: slot-occupancy gauges counted over the live
      slot table — now list() snapshots;
    - kv_router/recorder.py: FIFO single-worker executor serializes all
      _fh ops — suppressed inline with justification;
    - telemetry/hub.py: the PR 10 hardening (snapshot reads in the
      executor-side /fleet renders) proved clean under the detector.
    """
    modules = [
        os.path.join(PACKAGE_ROOT, "kv_router", "metrics_aggregator.py"),
        os.path.join(PACKAGE_ROOT, "kv_router", "recorder.py"),
        os.path.join(PACKAGE_ROOT, "telemetry", "device_time.py"),
        os.path.join(PACKAGE_ROOT, "telemetry", "hub.py"),
        os.path.join(PACKAGE_ROOT, "telemetry", "history.py"),
        os.path.join(PACKAGE_ROOT, "telemetry", "tracing.py"),
        os.path.join(PACKAGE_ROOT, "engine", "scheduler.py"),
        os.path.join(PACKAGE_ROOT, "kv", "cold_tier.py"),
    ]
    found = lint_paths(modules, get_rules(["cross-domain-race"]))
    assert found == [], "\n".join(f.render() for f in found)


@pytest.mark.dynlint
def test_whole_package_cross_domain_race_is_triaged():
    """Tree-wide: every cross-domain-race finding is fixed, suppressed
    inline with justification, or recorded in the baseline — zero
    un-triaged findings (the tentpole's acceptance bar)."""
    found = lint_paths([PACKAGE_ROOT], get_rules(["cross-domain-race"]))
    diff = diff_against_baseline(found, load_baseline(BASELINE))
    assert not diff.new, "\n".join(f.render() for f in diff.new)


# --------------------------------------------------------------------------
# CLI: --changed mode
# --------------------------------------------------------------------------


def _git(cwd, *args):
    subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
        cwd=cwd, check=True, capture_output=True,
    )


def test_cli_changed_scopes_reporting_to_differing_files(tmp_path,
                                                         monkeypatch):
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    try:
        import dynlint
    finally:
        sys.path.pop(0)

    repo = tmp_path / "repo"
    pkg = repo / "pkg"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "clean.py").write_text("x = 1\n")
    dirty = textwrap.dedent(
        """
        import time
        async def a():
            time.sleep(1)
        """)
    (pkg / "dirty.py").write_text(dirty)
    _git(repo, "init", "-q")
    _git(repo, "add", ".")
    _git(repo, "commit", "-qm", "seed")

    monkeypatch.setattr(dynlint, "REPO_ROOT", str(repo))
    baseline = str(tmp_path / "b.json")

    # nothing changed vs HEAD -> clean exit, pre-existing debt unreported
    assert dynlint.main(
        ["dynlint", str(pkg), "--baseline", baseline, "--changed"]) == 0

    # touch the dirty file -> its finding is reported again
    (pkg / "dirty.py").write_text(dirty + "y = 2\n")
    assert dynlint.main(
        ["dynlint", str(pkg), "--baseline", baseline, "--changed"]) == 1
    # ...but only the clean file changing stays clean
    _git(repo, "add", ".")
    _git(repo, "commit", "-qm", "touch dirty")
    (pkg / "clean.py").write_text("x = 3\n")
    assert dynlint.main(
        ["dynlint", str(pkg), "--baseline", baseline, "--changed"]) == 0
    # an untracked .py file is linted too
    (pkg / "fresh.py").write_text(dirty)
    assert dynlint.main(
        ["dynlint", str(pkg), "--baseline", baseline, "--changed"]) == 1
    # explicit ref form
    assert dynlint.main(
        ["dynlint", str(pkg), "--baseline", baseline,
         "--changed=HEAD"]) == 1


def test_cli_changed_filters_baseline_to_changed_files(tmp_path,
                                                       monkeypatch):
    """Debt recorded for UNCHANGED files must neither satisfy nor be
    reported stale by a --changed run."""
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    try:
        import dynlint
    finally:
        sys.path.pop(0)

    repo = tmp_path / "repo"
    pkg = repo / "pkg"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    dirty = textwrap.dedent(
        """
        import time
        async def a():
            time.sleep(1)
        """)
    (pkg / "debt.py").write_text(dirty)
    (pkg / "other.py").write_text("x = 1\n")
    _git(repo, "init", "-q")
    _git(repo, "add", ".")
    _git(repo, "commit", "-qm", "seed")
    monkeypatch.setattr(dynlint, "REPO_ROOT", str(repo))

    baseline = str(tmp_path / "b.json")
    assert dynlint.main(
        ["dynlint", str(pkg), "--baseline", baseline,
         "--update-baseline"]) == 0
    # only other.py changes: debt.py's baseline entry is out of scope,
    # must not be flagged stale (exit 0)
    (pkg / "other.py").write_text("x = 2\n")
    assert dynlint.main(
        ["dynlint", str(pkg), "--baseline", baseline, "--changed"]) == 0


def test_cli_changed_bad_ref_is_usage_error():
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    try:
        import dynlint
    finally:
        sys.path.pop(0)
    assert dynlint.main(
        ["dynlint", "--changed=definitely-not-a-ref"]) == 2


def test_cli_list_rules_and_github_format_cover_new_rules(capsys):
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    try:
        import dynlint
    finally:
        sys.path.pop(0)
    assert dynlint.main(["dynlint", "--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "cross-domain-race" in out and "wallclock-in-sim" in out
    # ::error rendering carries the rule name for CI annotations
    from dynamo_tpu.analysis import Finding
    gh = Finding("cross-domain-race", "dynamo_tpu/x.py", 3, "msg")
    assert gh.render_github().startswith(
        "::error file=dynamo_tpu/x.py,line=3,title=dynlint/cross-domain-race")


def test_project_rule_context_not_shrunk_by_changed_scope(tmp_path):
    """only_files restricts REPORTING, not parsing: a cross-module race
    must be reported on a changed file even when the other half of the
    race lives in an unchanged module."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "writer.py").write_text(textwrap.dedent(
        """
        class W:
            def __init__(self):
                self.vals = []
            async def on_loop(self):
                self.vals.append(1)
            # dynrace: domain(executor)
            def render(self):
                return [v for v in self.vals]
        """))
    (pkg / "other.py").write_text("x = 1\n")
    rules = get_rules(["cross-domain-race"])
    scoped = lint_paths([str(pkg)], rules, only_files={"pkg/writer.py"})
    assert [f.file for f in scoped] == ["pkg/writer.py"]
    # scoping to the OTHER file hides the finding without losing it
    assert lint_paths([str(pkg)], rules, only_files={"pkg/other.py"}) == []
